"""Delta-main compaction (PR 16) — the background worker that turns the
storage engine from append-only-plus-bulk into a true delta-main system
(ref: TiFlash delta-tree — OLTP writes land row-major in a delta layer
and a compactor folds them into the columnar main; arXiv 2112.13099 on
specializing resident layout to the workload).

Every txn write lands row-major in MemKV (the delta). The compactor,
one per durable primary store, periodically:

  1. selects tables whose mutable delta (w-CF entries) exceeds a
     threshold, using MemKV.count_range per table prefix (two bisects —
     no value touching),
  2. folds each such table's rows PLUS every MVCC version at/below the
     gcworker safepoint into fresh sorted ColumnarRun / IntIndexRun /
     byte-Run segments (MVCCStore.fold_plan decides; the decode reuses
     the scan path's row→chunk machinery and br/ingest's builders), and
  3. publishes under the SAME atomic discipline bulk ingest uses: one
     WAL record (the 'Z' compaction frame), one data-version bump, one
     cache invalidation barrier, a crashpoint before the journal append,
     standby-shippable.

Merges keep the per-table run count bounded: when a key-space plane
(record plane, or the index planes jointly) accumulates more than
max_runs runs, the OLDEST contiguous commit-ts prefix of structurally
identical runs folds into one (size-tiered/leveled: small young runs
repeatedly merge into a larger old one). Only a contiguous-by-ts prefix
is safe to merge — the merged run takes the newest source's commit_ts,
so a skipped-over middle run would suddenly lose to resurrected older
versions.

MVCC GC is wired THROUGH this subsystem: gcworker.tick delegates table
spans to Compactor.gc_pass (delete-versions-via-compaction — versions
die by folding, the newest visible value surviving as a segment row)
and mvcc.gc sweeps only what the fold doesn't own (meta keys, stores
without a compactor).

Scheduling: a compaction is a low-priority internal job. It defers
whenever the admission scheduler has foreground waiters, pauses
entirely while the memory arbiter's OOM degrade is active, and never
instantiates the resource controller on a store that hasn't built one.

Concurrency: folds race against live commits by design — the fold plan
is recomputed under the kv lock at publish time and compared to the
plan the artifact was built from (MVCCStore.apply_compaction's
expect_plans); any slip aborts the round with nothing journaled
(CompactionRaced) and the next tick retries. The compactor's own _lock
(rank compact.worker) guards only its stats dict and is never held
across a kv/wal acquisition.
"""

from __future__ import annotations

import struct
import threading
import weakref

import numpy as np

from ..codec import tablecodec
from ..utils import metrics as M
from ..utils.failpoint import inject as _fp
from .mvcc import CompactionRaced, _dk


def _prefix_next(prefix: bytes) -> bytes:
    from ..planner.ranger import prefix_next

    return prefix_next(prefix)


def _decode_be_handles(sl: np.ndarray, n: int) -> np.ndarray:
    """(n, 8) big-endian sign-flipped key bytes → int64 (vectorized)."""
    enc = np.ascontiguousarray(sl).view(">u8").reshape(n)
    return (enc.astype(np.uint64) ^ np.uint64(1 << 63)).view(np.int64)


class Compactor:
    """Background delta-main compactor for ONE durable primary store.

    Holds only a weakref to the store: the worker thread must never pin
    a store that tests (or a failover) have dropped — the loop exits
    when the ref dies. Inert by default in short-lived processes: the
    fold timestamp is the gc safepoint (now - tidb_gc_life_time, 10min
    default), so young versions never fold until an operator shortens
    the gc life or the gcworker delegates a pass.
    """

    DEFAULT_INTERVAL_S = 1.0
    DEFAULT_THRESHOLD = 2048
    DEFAULT_MAX_RUNS = 8
    FAN_IN = 4  # size-tiered merge width: oldest <=4 ts-groups fold into one

    def __init__(self, storage):
        self._storage = weakref.ref(storage)
        # rank "compact.worker": guards stats/last_error ONLY; never held
        # across any kv/wal/scheduler acquisition
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats: dict[int, dict] = {}  # table_id → counters (under _lock)
        self.rounds = 0
        self.last_error = ""
        # (sp, delta) memo per table: skip re-walking a span whose state
        # can't have changed since the last no-op attempt
        self._attempted: dict[int, tuple[int, int]] = {}

    # --- config (read from store.global_vars each tick: SET GLOBAL is
    # the control plane, no push plumbing needed) --------------------------

    def enabled(self, store) -> bool:
        return store.global_vars.get("tidb_compact_enable", "ON") == "ON"

    def _threshold(self, store) -> int:
        try:
            return int(store.global_vars.get(
                "tidb_compact_delta_threshold", self.DEFAULT_THRESHOLD))
        except ValueError:
            return self.DEFAULT_THRESHOLD

    def _max_runs(self, store) -> int:
        try:
            return max(2, int(store.global_vars.get(
                "tidb_compact_max_runs", self.DEFAULT_MAX_RUNS)))
        except ValueError:
            return self.DEFAULT_MAX_RUNS

    def _interval_s(self, store) -> float:
        from .gcworker import parse_go_duration_ms

        ms = parse_go_duration_ms(
            str(store.global_vars.get("tidb_compact_interval", "")))
        return ms / 1000.0 if ms else self.DEFAULT_INTERVAL_S

    # --- worker lifecycle --------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tidb-compactor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None

    def wake(self) -> None:
        self._wake.set()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            store = self._storage()
            if store is None:
                return
            interval = self._interval_s(store)
            store = None  # don't pin the store across the wait
            self._wake.wait(interval)
            self._wake.clear()
            if self._stopped.is_set():
                return
            store = self._storage()
            if store is None:
                return
            try:
                self.tick(store)
            except Exception as e:  # the worker must never die silently
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"
            store = None

    # --- one round ---------------------------------------------------------

    def tick(self, store=None, force_sp: int | None = None) -> dict:
        """One compaction round: threshold-select tables, fold each,
        then bound run counts via merges. Synchronous — tests and the
        gcworker call it directly; the background thread is just a
        clock."""
        store = self._storage() if store is None else store
        out = {"folded": 0, "removed": 0, "merged": 0}
        if store is None or store.standby or not self.enabled(store):
            return out
        if store.mem.degraded:
            # OOM degrade pauses internal jobs first: a compaction's
            # decode/build allocations are exactly what the arbiter is
            # trying to claw back
            M.COMPACT_ROUNDS.inc(outcome="paused")
            return out
        rc = getattr(store, "_sched", None)
        if rc is not None and rc.scheduler.queue_depth() > 0:
            # strictly-background admission: foreground statements are
            # queued — never compete with them for a slot
            M.COMPACT_ROUNDS.inc(outcome="deferred")
            return out
        sp = store.gc_worker.compute_safe_point() if force_sp is None else force_sp
        if sp <= 0:
            return out
        threshold = self._threshold(store)
        for tid, _prefix, delta in self._candidates(store):
            if delta < threshold:
                continue
            if self._attempted.get(tid) == (sp, delta):
                continue  # nothing changed since the last no-op attempt
            res = self.compact_table(store, tid, sp)
            if res is None:
                self._attempted[tid] = (sp, delta)
            else:
                self._attempted.pop(tid, None)
                out["folded"] += res["rows"]
                out["removed"] += res["removed"]
        for tid in self._tables_with_runs(store):
            out["merged"] += self.maybe_merge(store, tid)
        with self._lock:
            self.rounds += 1
        return out

    def gc_pass(self, store, sp: int) -> int:
        """The gcworker's delete-versions-via-compaction path: fold EVERY
        table span's at-or-below-safepoint versions (no delta threshold —
        GC must reclaim), returning mutable versions removed. Tables the
        fold skips (raced, ingest window open) are left for mvcc.gc's
        per-key sweep right after."""
        removed = 0
        for tid, _prefix, _delta in self._candidates(store):
            res = self.compact_table(store, tid, sp)
            if res is not None:
                removed += res["removed"]
        return removed

    # --- selection ---------------------------------------------------------

    def _candidates(self, store):
        """(table_id, 9-byte prefix, delta count) per table present in
        the mutable write CF — leapfrogs prefix to prefix via bisect, so
        cost is O(tables · log n), not O(versions)."""
        kv = store.mvcc.kv
        out = []
        k = kv.first_key_at_or_after(b"w")
        while k is not None and k[:1] == b"w" and len(k) >= 10:
            prefix = k[1:10]
            end = b"w" + _prefix_next(prefix)
            if prefix[:1] == b"t":
                delta = kv.count_range(b"w" + prefix, end)
                out.append((tablecodec._dint(prefix[1:9]), prefix, delta))
            k = kv.first_key_at_or_after(end)
        return out

    def _tables_with_runs(self, store):
        tids = set()
        with store.mvcc.kv.lock:
            for r in store.mvcc.runs:
                tid = getattr(r, "table_id", None)
                if tid is None and r.n:
                    k = r.key_at(0)
                    if k[:1] == b"t" and len(k) >= 9:
                        tid = tablecodec._dint(k[1:9])
                if tid is not None:
                    tids.add(tid)
        return sorted(tids)

    # --- fold --------------------------------------------------------------

    def compact_table(self, store, tid: int, sp: int) -> dict | None:
        """Fold one table's mutable delta at/below sp into segments.
        Returns stats, or None when there was nothing to fold or the
        round must retry (raced, ingest window open, value vanished)."""
        if store.table_ingesting(tid):
            return None  # the ingest window owns this table right now
        from ..utils.tracing import StatementTrace

        # folding re-stamps survivor versions at the fold ts, so ANY
        # snapshot between the original commit_ts and the fold ts would
        # stop seeing them — the same contract GC enforces. tick() passes
        # the gcworker safepoint (already clamped); force-folds (tests,
        # crashpoints, bench) get the clamp here so a live txn's current
        # reads never lose a row to a concurrent fold.
        ma = store.min_active_start_ts()
        if ma is not None:
            sp = min(sp, ma - 1)
        if sp <= 0:
            return None
        mvcc = store.mvcc
        tprefix = tablecodec.table_prefix(tid)
        start, end = tprefix, _prefix_next(tprefix)
        trace = StatementTrace(sql=f"COMPACT TABLE {tid}", recording=True)
        with trace.span("compact.plan", table=tid):
            with mvcc.kv.lock:
                plan = mvcc.fold_plan(start, end, sp)
                doom, _kills, puts = plan
                if not doom:
                    return None  # nothing at/below the safepoint
                vals = {}
                for uk, sts, _cts in puts:
                    v = mvcc.kv.get(_dk(uk, sts))
                    if v is None:  # concurrent per-key gc got there first
                        return None
                    vals[uk] = v
        with trace.span("compact.build", rows=len(puts)):
            new_runs = self._build_runs(store, tid, tprefix, puts, vals, sp)
        # crashpoint: artifacts built and sorted, NOTHING journaled or
        # published — recovery must see the compaction as absent (and the
        # pre-fold row-major state still fully intact)
        _fp("compact/after-artifact-before-publish")
        from .wal import iter_compact_chunks

        # the Z record streams to the journal as one frame group — never
        # materialized whole; the counting wrapper keeps the byte metric
        # without a second pass (zero when the publish raced: the
        # generator is only consumed after the race checks pass)
        jbytes = 0

        def record_chunks():
            nonlocal jbytes
            for c in iter_compact_chunks(tid, sp, [(start, end)], [], new_runs):
                jbytes += len(c)
                yield c

        # a txn that began at/below the fold ts while we built artifacts
        # could read the span mid-snapshot — abort the round like any
        # other race (the plan compare below only witnesses WRITES)
        ma = store.min_active_start_ts()
        if ma is not None and ma <= sp:
            M.COMPACT_ROUNDS.inc(outcome="raced")
            return None
        with trace.span("compact.publish", runs=len(new_runs)):
            try:
                removed = mvcc.apply_compaction(
                    tid, sp, [(start, end)], [], new_runs,
                    record_chunks=record_chunks(), expect_plans=[plan])
            except CompactionRaced:
                M.COMPACT_ROUNDS.inc(outcome="raced")
                return None
            publish_barrier(store, tid)
        M.COMPACT_ROUNDS.inc(outcome="fold")
        M.COMPACT_ROWS.inc(len(puts))
        M.COMPACT_VERSIONS.inc(removed)
        M.COMPACT_BYTES.inc(jbytes)
        self._bump(tid, rows_folded=len(puts), versions_reclaimed=removed,
                   folds=1)
        trace.finish()
        store.trace_ring.push(trace)
        return {"rows": len(puts), "removed": removed, "runs": len(new_runs)}

    def _bump(self, tid: int, **deltas) -> None:
        with self._lock:
            st = self.stats.setdefault(tid, {
                "folds": 0, "merges": 0, "rows_folded": 0,
                "versions_reclaimed": 0,
            })
            for k, v in deltas.items():
                st[k] = st.get(k, 0) + v

    def _table_info(self, store, tid: int):
        from ..catalog.meta import Meta

        txn = store.begin()
        try:
            return Meta(txn).table(tid)
        except Exception:
            return None
        finally:
            txn.rollback()

    def _build_runs(self, store, tid, tprefix, puts, vals, sp) -> list:
        """Folded (key, value) pairs → segments: columnar record plane
        and int-index planes where the shapes allow, byte runs for
        everything else (string/NULL/uint index keys, schema-less
        tables). Input arrives in ascending key order (fold_plan walks
        the sorted CF), which every builder below relies on."""
        from ..br.ingest import runs_from_kvs

        rec_prefix = tablecodec.record_prefix(tid)
        idx_marker = tprefix + b"_i"
        rec_keys: list[bytes] = []
        rec_vals: list[bytes] = []
        by_iid: dict[int, list[tuple[bytes, bytes]]] = {}
        other: list[tuple[bytes, bytes]] = []
        for uk, _sts, _cts in puts:
            v = vals[uk]
            if len(uk) == 19 and uk.startswith(rec_prefix):
                rec_keys.append(uk)
                rec_vals.append(v)
            elif len(uk) >= 19 and uk.startswith(idx_marker):
                by_iid.setdefault(tablecodec._dint(uk[11:19]), []).append((uk, v))
            else:
                other.append((uk, v))

        info = self._table_info(store, tid)
        runs: list = []
        if rec_keys:
            crun = None
            if info is not None:
                try:
                    crun = self._build_record_run(info, rec_keys, rec_vals, sp)
                except Exception:
                    crun = None  # odd row payloads: keep them row-encoded
            if crun is not None:
                runs.append(crun)
            else:
                other.extend(zip(rec_keys, rec_vals))
        for iid, pairs in sorted(by_iid.items()):
            ix = None
            if info is not None:
                ix = next((x for x in info.indexes if x.id == iid), None)
            irun = self._build_int_index_run(tid, ix, pairs, sp) if ix else None
            if irun is not None:
                runs.append(irun)
            else:
                other.extend(pairs)
        if other:
            other.sort()
            runs.extend(runs_from_kvs(other, sp))
        return runs

    def _build_record_run(self, info, keys, vals, sp):
        """Row-encoded record pairs → one ColumnarRun, through the SAME
        row→chunk decode the scan path serves from — so a fold changes
        the resident layout, never the values a read decodes."""
        from ..br.ingest import kind_of
        from ..copr.tilecache import decode_rows_to_batch
        from ..mysqltypes.datum import K_DEC, K_FLOAT, K_STR, K_UINT
        from .segment import ColSpec, ColumnarRun, canonical_str_array

        batch = decode_rows_to_batch(info, list(zip(keys, vals)), 0)
        specs = []
        for c, data, valid in zip(info.columns, batch.data, batch.valid):
            if getattr(c, "hidden", False) and c.name == "_tidb_rowid":
                continue  # the run's handle plane carries it
            k = kind_of(c.ft)
            if k == K_STR:
                if data.dtype.kind != "S":
                    data = np.array(
                        [x if (valid[i] and x is not None) else ""
                         for i, x in enumerate(data)], dtype=object)
                    data = canonical_str_array(data)
            elif k == K_FLOAT:
                data = np.ascontiguousarray(data, dtype=np.float64)
            elif k == K_UINT:
                data = np.ascontiguousarray(data, dtype=np.uint64)
            else:
                data = np.asarray(data).astype(np.int64, copy=False)
            v = None if bool(valid.all()) else np.ascontiguousarray(valid, dtype=bool)
            scale = max(c.ft.decimal, 0) if k == K_DEC else 0
            specs.append(ColSpec(c.id, k, scale, data, v))
        # keys ascend, and sign-flipped BE preserves int64 order — presorted
        return ColumnarRun.build(info.id, batch.handles, specs, sp, presorted=True)

    def _build_int_index_run(self, tid, ix, pairs, sp):
        """Index pairs → IntIndexRun when every key is the pure int form
        (0x03-flagged complete groups, the txn path's value shape) —
        anything else (NULLs, strings, unsigned 0x04 flags) returns None
        and stays a byte run. Verification is exact: a pair the plane
        could not reproduce bit-identically never enters it."""
        from .segment import IntIndexRun

        plen = 19  # index_prefix: t + tid + _i + iid
        k_count = len(ix.col_offsets)
        klen = plen + 9 * k_count + (0 if ix.unique else 8)
        n = len(pairs)
        if n == 0 or any(len(k) != klen for k, _ in pairs):
            return None
        km = np.frombuffer(b"".join(k for k, _ in pairs), np.uint8).reshape(n, klen)
        cols = []
        for g in range(k_count):
            off = plen + 9 * g
            if not bool((km[:, off] == 0x03).all()):
                return None  # NULL / uint / non-int flag byte
            cols.append(_decode_be_handles(km[:, off + 1 : off + 9], n))
        if ix.unique:
            try:
                handles = np.fromiter((int(v) for _, v in pairs), np.int64, n)
            except (ValueError, TypeError):
                return None
            for (_, v), h in zip(pairs, handles):
                if v != str(int(h)).encode():
                    return None  # value form the plane can't synthesize
        else:
            if any(v != b"" for _, v in pairs):
                return None
            handles = _decode_be_handles(km[:, plen + 9 * k_count :], n)
        return IntIndexRun.build(tid, ix.id, cols, handles, bool(ix.unique), sp)

    # --- merge -------------------------------------------------------------

    def maybe_merge(self, store, tid: int) -> int:
        """Bound the table's run count: while any key-space plane holds
        more than max_runs runs, fold the oldest contiguous prefix of
        structurally identical ts-groups into one run (size-tiered).
        Returns runs retired."""
        mvcc = store.mvcc
        max_runs = self._max_runs(store)
        tprefix = tablecodec.table_prefix(tid)
        retired_total = 0
        for _ in range(8):  # a few levels per tick, never unbounded
            with mvcc.kv.lock:
                cand = self._merge_candidate(mvcc, tid, tprefix, max_runs)
                if cand is None:
                    break
                skey, take = cand
                merged, retire = self._merge_build(tid, skey, take)
            if merged is None:
                break
            _fp("compact/after-artifact-before-publish")
            from .wal import iter_compact_chunks

            jbytes = 0

            def record_chunks(merged=merged, retire=retire):
                nonlocal jbytes
                for c in iter_compact_chunks(
                        tid, merged.commit_ts, [], retire, [merged]):
                    jbytes += len(c)
                    yield c

            try:
                mvcc.apply_compaction(
                    tid, merged.commit_ts, [], retire, [merged],
                    record_chunks=record_chunks(), expect_plans=None)
            except CompactionRaced:  # pragma: no cover - no spans, no race
                break
            publish_barrier(store, tid)
            n_retired = sum(len(rs) for _cts, rs in take)
            retired_total += n_retired
            M.COMPACT_ROUNDS.inc(outcome="merge")
            M.COMPACT_BYTES.inc(jbytes)
            self._bump(tid, merges=1)
        return retired_total

    def _merge_candidate(self, mvcc, tid, tprefix, max_runs):
        """Pick (structural key, [(cts, [runs])]) to merge, or None.
        Caller holds kv.lock. Planes: the record key-space (ColumnarRuns
        + 19-byte record-shaped byte runs) and the index key-space (all
        IntIndexRuns + other byte runs) — runs only shadow within their
        plane, and ONLY an oldest-first contiguous ts-prefix may collapse
        into one commit_ts without reordering history."""
        from .segment import ColumnarRun, IntIndexRun, Run

        planes: dict[str, list] = {"rec": [], "idx": []}
        for r in mvcc.runs:
            if isinstance(r, ColumnarRun):
                if r.table_id == tid:
                    planes["rec"].append((r.commit_ts, ("C", 0), r))
            elif isinstance(r, IntIndexRun):
                if r.table_id == tid:
                    planes["idx"].append((r.commit_ts, ("N", r.index_id), r))
            elif type(r) is Run and r.n and r.key_at(0).startswith(tprefix):
                rec_shaped = r.w == 19 and r.key_at(0)[9:11] == b"_r"
                planes["rec" if rec_shaped else "idx"].append(
                    (r.commit_ts, ("R", r.w), r))
        for items in planes.values():
            if len(items) <= max_runs:
                continue
            items.sort(key=lambda t: t[0])  # stable: equal ts keep list order
            groups: list[tuple[int, set, list]] = []
            for cts, skey, r in items:
                if groups and groups[-1][0] == cts:
                    groups[-1][1].add(skey)
                    groups[-1][2].append(r)
                else:
                    groups.append((cts, {skey}, [r]))
            first = groups[0][1]
            if len(first) != 1:
                continue  # mixed oldest group: nothing safely mergeable
            skey = next(iter(first))
            take = []
            for cts, skeys, rs in groups:
                if skeys != {skey}:
                    break  # structural barrier: stay a contiguous prefix
                take.append((cts, rs))
                if len(take) >= self.FAN_IN:
                    break
            if len(take) >= 2:
                return skey, take
        return None

    def _merge_build(self, tid, skey, take):
        """Build the merged run from ts-ascending source groups. Returns
        (run | None, retire identities). Keep-newest dedup: concatenation
        order is history order, stable sorts preserve it, and the LAST
        occurrence of a key wins."""
        kind, aux = skey
        srcs = [r for _cts, rs in take for r in rs]
        cts_out = take[-1][0]
        if kind == "C":
            merged = self._merge_columnar(tid, srcs, cts_out)
            retire = [(0, 0, cts) for cts, _rs in take]
        elif kind == "N":
            merged = self._merge_intindex(tid, aux, srcs, cts_out)
            retire = [(1, aux, cts) for cts, _rs in take]
        else:
            merged = self._merge_byte(srcs, cts_out)
            retire = [(2, aux, cts) for cts, _rs in take]
        return merged, retire

    def _merge_columnar(self, tid, runs, cts_out):
        from .segment import ColSpec, ColumnarRun

        sig = [(c.cid, c.kind, c.scale) for c in runs[0].cols]
        for r in runs[1:]:
            if [(c.cid, c.kind, c.scale) for c in r.cols] != sig:
                return None  # schema drifted between ingests: don't merge
        hs, datas, valids = [], [[] for _ in sig], [[] for _ in sig]
        has_valid = [False] * len(sig)
        for r in runs:
            keep = np.nonzero(r.alive)[0] if r.alive is not None else None
            h = r.handles_arr if keep is None else r.handles_arr[keep]
            hs.append(h)
            for ci, c in enumerate(r.cols):
                d = c.data if keep is None else c.data[keep]
                datas[ci].append(d)
                if c.valid is not None:
                    has_valid[ci] = True
                    valids[ci].append(c.valid if keep is None else c.valid[keep])
                else:
                    valids[ci].append(np.ones(len(h), dtype=bool))
        handles = np.concatenate(hs)
        n = len(handles)
        if n == 0:
            return None
        order = np.argsort(handles, kind="stable")
        sh = handles[order]
        last = np.ones(n, dtype=bool)
        if n > 1:
            last[:-1] = sh[:-1] != sh[1:]
        sel = order[last]
        specs = []
        for ci, (cid, ckind, scale) in enumerate(sig):
            data = np.concatenate(datas[ci])[sel]
            v = None
            if has_valid[ci]:
                v = np.concatenate(valids[ci])[sel]
                if bool(v.all()):
                    v = None
            specs.append(ColSpec(cid, ckind, scale, data, v))
        return ColumnarRun.build(tid, sh[last], specs, cts_out, presorted=True)

    def _merge_intindex(self, tid, iid, runs, cts_out):
        from .segment import IntIndexRun

        k_count = len(runs[0].key_cols)
        unique = bool(runs[0].unique)
        for r in runs[1:]:
            if len(r.key_cols) != k_count or bool(r.unique) != unique:
                return None
        cols = [[] for _ in range(k_count)]
        hs = []
        for r in runs:
            keep = np.nonzero(r.alive)[0] if r.alive is not None else None
            hs.append(r.handles_arr if keep is None else r.handles_arr[keep])
            for ci, c in enumerate(r.key_cols):
                cols[ci].append(c if keep is None else c[keep])
        handles = np.concatenate(hs)
        n = len(handles)
        if n == 0:
            return None
        ccols = [np.concatenate(c) for c in cols]
        levels = ccols + ([] if unique else [handles])
        order = np.lexsort(tuple(levels[::-1]))  # stable, primary first
        same = np.zeros(n - 1, dtype=bool) if n > 1 else np.zeros(0, dtype=bool)
        if n > 1:
            same[:] = True
            for lv in levels:
                s = lv[order]
                same &= s[1:] == s[:-1]
        last = np.ones(n, dtype=bool)
        last[:-1] = ~same
        sel = order[last]
        return IntIndexRun(tid, iid, [c[sel] for c in ccols], handles[sel],
                           unique, cts_out)

    def _merge_byte(self, runs, cts_out):
        from ..br.ingest import runs_from_kvs

        pairs: dict[bytes, bytes] = {}
        for r in runs:  # history order: later assignment = newer wins
            for i in range(r.n):
                if r.alive is None or r.alive[i]:
                    pairs[r.key_at(i)] = r.value(i)
        if not pairs:
            return None
        out = runs_from_kvs(sorted(pairs.items()), cts_out)
        return out[0] if len(out) == 1 else None


def publish_barrier(store, table_id: int) -> None:
    """The publish tail shared with bulk ingest (br/ingest owns it; this
    shim only dodges the storage→br import at module load): semi-sync
    durability wait, then ONE data-version bump — which invalidates every
    session's version-checked tile/build-side cache entries."""
    from ..br.ingest import publish_barrier as _pb

    _pb(store, table_id)


def compaction_rows(session) -> list:
    """information_schema.COMPACTION memtable rows (catalog/memtables)."""
    store = session.store
    comp = store.compactor
    if comp is None:
        return []
    with store.mvcc.kv.lock:
        run_counts: dict[int, int] = {}
        for r in store.mvcc.runs:
            tid = getattr(r, "table_id", None)
            if tid is None and r.n:
                k = r.key_at(0)
                if k[:1] == b"t" and len(k) >= 9:
                    tid = tablecodec._dint(k[1:9])
            if tid is not None:
                run_counts[tid] = run_counts.get(tid, 0) + 1
    deltas = {tid: delta for tid, _p, delta in comp._candidates(store)}
    with comp._lock:
        stats = {tid: dict(st) for tid, st in comp.stats.items()}
    rows = []
    for tid in sorted(set(stats) | set(run_counts) | set(deltas)):
        st = stats.get(tid, {})
        rows.append((tid, st.get("folds", 0), st.get("merges", 0),
                     st.get("rows_folded", 0), st.get("versions_reclaimed", 0),
                     run_counts.get(tid, 0), deltas.get(tid, 0)))
    return rows

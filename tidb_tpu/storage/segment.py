"""Immutable sorted ingest segments — the LSM-run / TiFlash-columnar-replica
analog (ref: br/pkg/lightning local backend builds SSTs and ingests them
without touching the write path; unistore sits on badger's LSM runs).

A `Run` is one bulk-ingested, single-commit-ts sorted segment:
  - fixed-width user keys as a (n, w) uint8 matrix (memcomparable order)
  - values as ONE buffer + (starts, lens) — no per-row bytes objects
  - a whole-run commit_ts: every entry became visible atomically, so MVCC
    visibility is a single comparison per run, not per key

Point/range lookups binary-search the key matrix directly (no per-key
Python objects are ever materialized on the ingest or scan hot paths).
Scans return `SegmentView`s (run slice + optional dropped rows) so the
columnar decode layer (copr/tilecache.py) can gather straight from the
run's buffers.
"""

from __future__ import annotations

import numpy as np


def sort_key_matrix(key_mat: np.ndarray) -> np.ndarray:
    """Row order that sorts fixed-width byte-string rows lexicographically.
    Views rows as big-endian u64 words (zero-padded) and lexsorts."""
    n, w = key_mat.shape
    pad = (-w) % 8
    if pad:
        m = np.zeros((n, w + pad), dtype=np.uint8)
        m[:, :w] = key_mat
    else:
        m = np.ascontiguousarray(key_mat)
    words = m.view(">u8").reshape(n, (w + pad) // 8)
    return np.lexsort(tuple(words[:, c] for c in range(words.shape[1] - 1, -1, -1)))


class Run:
    """One immutable sorted segment (all keys same width, one commit_ts)."""

    __slots__ = ("key_mat", "vbuf", "starts", "lens", "commit_ts", "alive", "n", "w", "_keybuf")

    def __init__(self, key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray, commit_ts: int):
        self.key_mat = key_mat
        self.vbuf = vbuf  # bytes or 1-D uint8 array
        self.starts = starts
        self.lens = lens
        self.commit_ts = commit_ts
        self.alive: np.ndarray | None = None  # None = all alive
        self.n, self.w = key_mat.shape
        self._keybuf: bytes | None = None  # lazy contiguous key bytes

    @staticmethod
    def build(key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray,
              commit_ts: int, presorted: bool = False) -> "Run":
        key_mat = np.ascontiguousarray(key_mat, dtype=np.uint8)
        if not presorted and key_mat.shape[0] > 1:
            order = sort_key_matrix(key_mat)
            if not np.array_equal(order, np.arange(len(order))):
                key_mat = np.ascontiguousarray(key_mat[order])
                starts = np.asarray(starts)[order]
                lens = np.asarray(lens)[order]
        return Run(key_mat, vbuf, np.asarray(starts, np.int64), np.asarray(lens, np.int64), commit_ts)

    # --- key access -------------------------------------------------------

    def key_at(self, i: int) -> bytes:
        if self._keybuf is None:
            self._keybuf = self.key_mat.tobytes()
        return self._keybuf[i * self.w : (i + 1) * self.w]

    def _bisect(self, key: bytes) -> int:
        """Leftmost row index with key_at(row) >= key (bytes comparison —
        a shorter probe key sorts before any key it prefixes, matching
        python bytes ordering used by MemKV)."""
        lo, hi = 0, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if self.key_at(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    # --- point ops --------------------------------------------------------

    def find(self, key: bytes) -> int:
        """Row index of key, or -1."""
        if len(key) != self.w:
            return -1
        i = self._bisect(key)
        if i < self.n and self.key_at(i) == key and (self.alive is None or self.alive[i]):
            return i
        return -1

    def value(self, i: int) -> bytes:
        s = int(self.starts[i])
        v = self.vbuf[s : s + int(self.lens[i])]
        return v.tobytes() if isinstance(v, np.ndarray) else v

    def value_buffer(self) -> np.ndarray:
        """The whole value plane as a u8 array (decode fast path)."""
        if isinstance(self.vbuf, np.ndarray):
            return self.vbuf
        return np.frombuffer(self.vbuf, dtype=np.uint8)

    def range(self, start: bytes, end: bytes | None) -> tuple[int, int]:
        i = self._bisect(start)
        j = self._bisect(end) if end is not None else self.n
        return i, j

    def kill_range(self, start: bytes, end: bytes | None) -> int:
        """Tombstone all rows in [start, end) (unsafe_destroy_range)."""
        i, j = self.range(start, end)
        if i >= j:
            return 0
        if self.alive is None:
            self.alive = np.ones(self.n, dtype=bool)
        killed = int(self.alive[i:j].sum())
        self.alive[i:j] = False
        return killed


class SegmentView:
    """A scan's view of one run slice, minus dropped (shadowed) rows."""

    __slots__ = ("run", "i", "j", "drop")

    def __init__(self, run: Run, i: int, j: int, drop: set[int] | None = None):
        self.run = run
        self.i = i
        self.j = j
        self.drop = drop  # absolute row indices within run

    def keep_idx(self) -> np.ndarray:
        """Absolute row indices surviving drop + alive mask, in key order."""
        idx = np.arange(self.i, self.j, dtype=np.int64)
        if self.run.alive is not None:
            idx = idx[self.run.alive[self.i : self.j]]
        if self.drop:
            idx = idx[~np.isin(idx, np.fromiter(self.drop, np.int64, len(self.drop)))]
        return idx

    @property
    def n_rows(self) -> int:
        return len(self.keep_idx())

    def min_key(self) -> bytes:
        return self.run.key_at(self.i)

    def max_key(self) -> bytes:
        return self.run.key_at(self.j - 1)

    def pairs(self) -> list[tuple[bytes, bytes]]:
        """Materialize (key, value) pairs — the legacy-scan compat path."""
        r = self.run
        return [(r.key_at(int(i)), r.value(int(i))) for i in self.keep_idx()]

"""Immutable sorted ingest segments — the LSM-run / TiFlash-columnar-replica
analog (ref: br/pkg/lightning local backend builds SSTs and ingests them
without touching the write path; unistore sits on badger's LSM runs).

A `Run` is one bulk-ingested, single-commit-ts sorted segment:
  - fixed-width user keys as a (n, w) uint8 matrix (memcomparable order)
  - values as ONE buffer + (starts, lens) — no per-row bytes objects
  - a whole-run commit_ts: every entry became visible atomically, so MVCC
    visibility is a single comparison per run, not per key

Point/range lookups binary-search the key matrix directly (no per-key
Python objects are ever materialized on the ingest or scan hot paths).
Scans return `SegmentView`s (run slice + optional dropped rows) so the
columnar decode layer (copr/tilecache.py) can gather straight from the
run's buffers.

PR 15 adds two specialized subclasses the bulk-ingest path builds so the
row-major byte planes are never materialized at load time (the columnar
form IS the ingest wire format — arXiv:2506.10092):

  `ColumnarRun`  record-plane segment holding the COLUMN arrays plus the
                 int64 handles; record keys, the v2 row-byte plane and
                 per-row values synthesize lazily on first demand (scans
                 read the columns directly via copr/tilecache).
  `IntIndexRun`  all-int secondary-index segment holding the sorted key
                 columns + handles; the (n, w) key byte matrix, which
                 only index-path scans need, builds lazily.

Both honor the full Run surface (find/range/value/pairs/kill_range), so
every existing consumer — MVCC merge, snapshots, WAL replay, region
splits — keeps working; they just stop paying for bytes nobody asked for.
"""

from __future__ import annotations

import numpy as np


def sort_key_matrix(key_mat: np.ndarray) -> np.ndarray:
    """Row order that sorts fixed-width byte-string rows lexicographically.
    Views rows as big-endian u64 words (zero-padded) and lexsorts."""
    n, w = key_mat.shape
    pad = (-w) % 8
    if pad:
        m = np.zeros((n, w + pad), dtype=np.uint8)
        m[:, :w] = key_mat
    else:
        m = np.ascontiguousarray(key_mat)
    words = m.view(">u8").reshape(n, (w + pad) // 8)
    return np.lexsort(tuple(words[:, c] for c in range(words.shape[1] - 1, -1, -1)))


class Run:
    """One immutable sorted segment (all keys same width, one commit_ts)."""

    __slots__ = ("key_mat", "vbuf", "starts", "lens", "commit_ts", "alive", "n", "w", "_keybuf")

    def __init__(self, key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray, commit_ts: int):
        self.key_mat = key_mat
        self.vbuf = vbuf  # bytes or 1-D uint8 array
        self.starts = starts
        self.lens = lens
        self.commit_ts = commit_ts
        self.alive: np.ndarray | None = None  # None = all alive
        self.n, self.w = key_mat.shape
        self._keybuf: bytes | None = None  # lazy contiguous key bytes

    @staticmethod
    def build(key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray,
              commit_ts: int, presorted: bool = False) -> "Run":
        key_mat = np.ascontiguousarray(key_mat, dtype=np.uint8)
        if not presorted and key_mat.shape[0] > 1:
            order = sort_key_matrix(key_mat)
            if not np.array_equal(order, np.arange(len(order))):
                key_mat = np.ascontiguousarray(key_mat[order])
                starts = np.asarray(starts)[order]
                lens = np.asarray(lens)[order]
        return Run(key_mat, vbuf, np.asarray(starts, np.int64), np.asarray(lens, np.int64), commit_ts)

    # --- key access -------------------------------------------------------

    def key_at(self, i: int) -> bytes:
        if self._keybuf is None:
            self._keybuf = self.key_mat.tobytes()
        return self._keybuf[i * self.w : (i + 1) * self.w]

    def _bisect(self, key: bytes) -> int:
        """Leftmost row index with key_at(row) >= key (bytes comparison —
        a shorter probe key sorts before any key it prefixes, matching
        python bytes ordering used by MemKV)."""
        lo, hi = 0, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if self.key_at(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    # --- point ops --------------------------------------------------------

    def find(self, key: bytes) -> int:
        """Row index of key, or -1."""
        if len(key) != self.w:
            return -1
        i = self._bisect(key)
        if i < self.n and self.key_at(i) == key and (self.alive is None or self.alive[i]):
            return i
        return -1

    def value(self, i: int) -> bytes:
        s = int(self.starts[i])
        v = self.vbuf[s : s + int(self.lens[i])]
        return v.tobytes() if isinstance(v, np.ndarray) else v

    def value_buffer(self) -> np.ndarray:
        """The whole value plane as a u8 array (decode fast path)."""
        if isinstance(self.vbuf, np.ndarray):
            return self.vbuf
        return np.frombuffer(self.vbuf, dtype=np.uint8)

    def range(self, start: bytes, end: bytes | None) -> tuple[int, int]:
        i = self._bisect(start)
        j = self._bisect(end) if end is not None else self.n
        return i, j

    def kill_range(self, start: bytes, end: bytes | None) -> int:
        """Tombstone all rows in [start, end) (unsafe_destroy_range)."""
        i, j = self.range(start, end)
        if i >= j:
            return 0
        if self.alive is None:
            self.alive = np.ones(self.n, dtype=bool)
        killed = int(self.alive[i:j].sum())
        self.alive[i:j] = False
        return killed


    def to_wal_record(self) -> bytes:
        """Self-describing WAL/snapshot payload (alive-compacted)."""
        from .wal import rec_run

        if self.alive is not None:
            keep = self.alive
            return rec_run(self.key_mat[keep], self.value_buffer(),
                           self.starts[keep], self.lens[keep], self.commit_ts)
        return rec_run(self.key_mat, self.vbuf, self.starts, self.lens, self.commit_ts)


def canonical_str_array(arr: np.ndarray) -> np.ndarray:
    """Object/unicode string column → 'S' bytes array (utf8 per element
    on non-ascii). ColSpec string lanes stay in their INPUT form (object
    arrays of str are the scan-side chunk form already — converting 16M
    of them at load time was the single biggest remaining cost); this is
    the one conversion point for consumers that genuinely need bytes
    (the WAL ingest record, the lazy v2 row plane)."""
    a = np.asarray(arr)
    if a.dtype.kind == "S":
        return a
    try:
        return a.astype("S")
    except UnicodeEncodeError:
        return np.array(
            [v.encode("utf8") if isinstance(v, str) else (v or b"") for v in a],
            dtype="S",
        )


class ColSpec:
    """One column's payload inside a ColumnarRun: canonical numpy arrays
    (int64 for int/time/duration and scaled decimals, uint64 for
    unsigned, float64 for doubles, an 'S<w>' — or still-object str —
    array for strings) plus the v2-row metadata needed to synthesize row
    bytes bit-compatibly."""

    __slots__ = ("cid", "kind", "scale", "data", "valid")

    def __init__(self, cid: int, kind: int, scale: int, data: np.ndarray,
                 valid: np.ndarray | None = None):
        self.cid = cid
        self.kind = kind
        self.scale = scale
        self.data = data
        self.valid = valid  # None = all valid

    def take(self, order: np.ndarray) -> "ColSpec":
        return ColSpec(self.cid, self.kind, self.scale, self.data[order],
                       None if self.valid is None else self.valid[order])


def _decode_be_handle(b: bytes) -> int:
    """8 sign-flipped big-endian bytes → signed int64 handle — the ONE
    memcomparable-int codec (codec/tablecodec), not a local copy."""
    from ..codec.tablecodec import _dint

    return _dint(b)


def _encode_be_handle(h: int) -> bytes:
    from ..codec.tablecodec import _cint

    return _cint(h)


class ColumnarRun(Run):
    """Record-plane segment in columnar form — what the bulk-ingest path
    builds. Keys are `record_prefix(table_id) + BE(handle)` by
    construction, so point/range probes binary-search the int64 handle
    array (no key matrix); the (n, 19) key matrix and the row-major v2
    value plane materialize lazily, only for consumers that genuinely
    need bytes (legacy pair scans, per-row point gets)."""

    # no __slots__: lazily-materialized planes live in the instance dict

    def __init__(self, table_id: int, handles: np.ndarray, cols: list[ColSpec],
                 commit_ts: int):
        from ..codec import tablecodec

        self.table_id = table_id
        self.handles_arr = np.ascontiguousarray(handles, dtype=np.int64)
        self.cols = cols
        self.commit_ts = commit_ts
        self.alive = None
        self.n = len(self.handles_arr)
        self.w = 19
        self._prefix = tablecodec.record_prefix(table_id)
        self._keybuf = None
        self._key_mat = None
        self._rows = None  # (vbuf u8 array, starts, lens) once materialized

    @staticmethod
    def build(table_id: int, handles: np.ndarray, cols: list[ColSpec],
              commit_ts: int, presorted: bool = False) -> "ColumnarRun":
        handles = np.asarray(handles, dtype=np.int64)
        if not presorted and len(handles) > 1 and not (np.diff(handles) > 0).all():
            order = np.argsort(handles, kind="stable")
            handles = handles[order]
            cols = [c.take(order) for c in cols]
        return ColumnarRun(table_id, handles, cols, commit_ts)

    # --- lazy planes -------------------------------------------------------

    @property
    def key_mat(self) -> np.ndarray:
        if self._key_mat is None:
            from ..codec import rowfast

            self._key_mat = rowfast.record_key_matrix(self.table_id, self.handles_arr)
        return self._key_mat

    def _ensure_rows(self):
        if self._rows is None:
            from ..codec import rowfast

            buf, offs = rowfast.encode_rows_v2(
                [c.cid for c in self.cols],
                [c.kind for c in self.cols],
                [c.scale for c in self.cols],
                [c.data for c in self.cols],
                [c.valid for c in self.cols],
            )
            self._rows = (buf, offs[:-1].copy(), np.diff(offs))
        return self._rows

    @property
    def vbuf(self):
        return self._ensure_rows()[0]

    @property
    def starts(self) -> np.ndarray:
        return self._ensure_rows()[1]

    @property
    def lens(self) -> np.ndarray:
        return self._ensure_rows()[2]

    # --- key access without the matrix -------------------------------------

    def key_at(self, i: int) -> bytes:
        return self._prefix + _encode_be_handle(int(self.handles_arr[i]))

    def _bisect(self, key: bytes) -> int:
        p = self._prefix
        head = key[:11]
        if head != p:
            return 0 if head < p else self.n
        s = key[11:]
        if len(s) <= 8:
            # zero-padding preserves >= semantics: a key equal to the
            # padded probe is longer than (hence >) the raw probe, and
            # any key with the probe as a byte-prefix compares >= it
            probe, side = s + b"\x00" * (8 - len(s)), "left"
        else:
            probe, side = s[:8], "right"  # longer probe: equal-handle keys sort below it
        return int(np.searchsorted(self.handles_arr, _decode_be_handle(probe), side=side))

    def find(self, key: bytes) -> int:
        if len(key) != 19 or key[:11] != self._prefix:
            return -1
        h = _decode_be_handle(key[11:])
        i = int(np.searchsorted(self.handles_arr, h))
        if i < self.n and int(self.handles_arr[i]) == h and (self.alive is None or self.alive[i]):
            return i
        return -1

    def value(self, i: int) -> bytes:
        """Synthesize row i's v2 bytes on demand (point-get path); the
        full plane, once materialized, serves slices directly. A burst
        of per-row calls (a legacy pair scan walking the run) amortizes
        by materializing the whole plane after a small threshold instead
        of paying a full single-row encode per row."""
        if self._rows is not None:
            return super().value(i)
        self._value_calls = getattr(self, "_value_calls", 0) + 1
        if self._value_calls > 64:
            self._ensure_rows()
            return super().value(i)
        from ..codec import rowfast

        buf, offs = rowfast.encode_rows_v2(
            [c.cid for c in self.cols],
            [c.kind for c in self.cols],
            [c.scale for c in self.cols],
            [c.data[i : i + 1] for c in self.cols],
            [None if c.valid is None else c.valid[i : i + 1] for c in self.cols],
        )
        return buf.tobytes()

    def value_buffer(self) -> np.ndarray:
        return self._ensure_rows()[0]

    def to_wal_record(self) -> bytes:
        from .wal import rec_crun

        if self.alive is not None:
            keep = np.nonzero(self.alive)[0]
            compact = ColumnarRun(self.table_id, self.handles_arr[keep],
                                  [c.take(keep) for c in self.cols], self.commit_ts)
            return rec_crun(compact)
        return rec_crun(self)


class IntIndexRun(Run):
    """All-int secondary-index segment: `index_prefix + (0x03 + BE(col))*k
    [+ BE(handle)]` keys held as sorted int64 columns. Well-formed probes
    (whole 9-byte groups, the planner's index ranges and DML's exact
    index keys) binary-search the int columns; irregular probes (e.g. a
    chaos region split at a non-key byte boundary) fall back to the
    lazily-built key matrix. Unique-index values (the decimal-string
    handle) also build lazily."""

    def __init__(self, table_id: int, index_id: int, key_cols: list[np.ndarray],
                 handles: np.ndarray, unique: bool, commit_ts: int):
        from ..codec import tablecodec

        self.table_id = table_id
        self.index_id = index_id
        self.key_cols = [np.ascontiguousarray(c, dtype=np.int64) for c in key_cols]
        self.handles_arr = np.ascontiguousarray(handles, dtype=np.int64)
        self.unique = unique
        self.commit_ts = commit_ts
        self.alive = None
        self.n = len(self.handles_arr)
        self._prefix = tablecodec.index_prefix(table_id, index_id)
        self.w = len(self._prefix) + 9 * len(self.key_cols) + (0 if unique else 8)
        self._keybuf = None
        self._key_mat = None
        self._rows = None

    @staticmethod
    def build(table_id: int, index_id: int, key_cols: list[np.ndarray],
              handles: np.ndarray, unique: bool, commit_ts: int) -> "IntIndexRun":
        cols, handles = sort_int_key_cols(
            [np.asarray(c, dtype=np.int64) for c in key_cols],
            np.asarray(handles, dtype=np.int64),
        )
        return IntIndexRun(table_id, index_id, cols, handles, unique, commit_ts)

    @property
    def key_mat(self) -> np.ndarray:
        if self._key_mat is None:
            from ..codec import rowfast

            self._key_mat = rowfast.int_index_key_matrix(
                self.table_id, self.index_id, self.key_cols,
                None if self.unique else self.handles_arr,
            )
        return self._key_mat

    def _ensure_rows(self):
        if self._rows is None:
            if self.unique:
                from ..codec import rowfast

                vbuf, starts, lens = rowfast.handle_value_buffer(self.handles_arr)
                self._rows = (np.frombuffer(vbuf, dtype=np.uint8), starts, lens)
            else:
                z = np.zeros(self.n, dtype=np.int64)
                self._rows = (np.empty(0, dtype=np.uint8), z, z.copy())
        return self._rows

    @property
    def vbuf(self):
        return self._ensure_rows()[0]

    @property
    def starts(self) -> np.ndarray:
        return self._ensure_rows()[1]

    @property
    def lens(self) -> np.ndarray:
        return self._ensure_rows()[2]

    def value(self, i: int) -> bytes:
        return str(int(self.handles_arr[i])).encode() if self.unique else b""

    def key_at(self, i: int) -> bytes:
        parts = [self._prefix]
        for c in self.key_cols:
            parts.append(b"\x03" + _encode_be_handle(int(c[i])))
        if not self.unique:
            parts.append(_encode_be_handle(int(self.handles_arr[i])))
        return b"".join(parts)

    def _levels(self) -> list[np.ndarray]:
        return self.key_cols + ([] if self.unique else [self.handles_arr])

    def _parse_probe(self, key: bytes):
        """Decompose a probe into complete int levels → (values, side) or
        None when the probe doesn't follow the key structure."""
        plen = len(self._prefix)
        head = key[:plen]
        if head != self._prefix:
            return ("before",) if head < self._prefix else ("after",)
        rest = key[plen:]
        vals = []
        for li in range(len(self.key_cols)):
            if not rest:
                break
            if len(rest) < 9 or rest[0] != 0x03:
                return None  # partial/odd group: matrix fallback
            vals.append((li, _decode_be_handle(rest[1:9])))
            rest = rest[9:]
        else:
            if rest and not self.unique:
                if len(rest) < 8:
                    return None
                vals.append((len(self.key_cols), _decode_be_handle(rest[:8])))
                rest = rest[8:]
        if rest == b"":
            return (vals, "left")
        if not any(rest):
            # trailing zeros: a key that merely EXTENDS the parsed groups
            # still compares >= the probe ('left'), but a key consisting
            # of EXACTLY the parsed groups is a byte-prefix of the probe
            # and sorts BELOW it — the successor-key idiom key+b'\\x00'
            # must land AFTER the equal key ('right')
            full = len(vals) == len(self._levels())
            return (vals, "right" if full else "left")
        return None

    def _bisect(self, key: bytes) -> int:
        parsed = self._parse_probe(key)
        if parsed is None:
            return super()._bisect(key)  # byte compare over synthesized keys
        if parsed == ("before",):
            return 0
        if parsed == ("after",):
            return self.n
        vals, side = parsed
        levels = self._levels()
        lo, hi = 0, self.n
        for li, v in vals:
            arr = levels[li]
            lo2 = lo + int(np.searchsorted(arr[lo:hi], v, side="left"))
            hi = lo + int(np.searchsorted(arr[lo:hi], v, side="right"))
            lo = lo2
            if lo >= hi:
                return lo
        return hi if side == "right" else lo

    def find(self, key: bytes) -> int:
        if len(key) != self.w:
            return -1
        i = self._bisect(key)
        if i < self.n and self.key_at(i) == key and (self.alive is None or self.alive[i]):
            return i
        return -1

    def to_wal_record(self) -> bytes:
        from .wal import rec_irun

        if self.alive is not None:
            keep = np.nonzero(self.alive)[0]
            compact = IntIndexRun(self.table_id, self.index_id,
                                  [c[keep] for c in self.key_cols],
                                  self.handles_arr[keep], self.unique, self.commit_ts)
            return rec_irun(compact)
        return rec_irun(self)


def sort_int_key_cols(cols: list[np.ndarray], handles: np.ndarray
                      ) -> tuple[list[np.ndarray], np.ndarray]:
    """Order (cols..., handle) tuples ascending — the memcomparable key
    order of sign-flipped big-endian int keys.

    Single-col fast paths exploit frame-of-reference + common-stride
    reduction (packed dates are all multiples of 86400e6 — the PR 7
    'pack' codec trick applied to sorting):

      * codes fit int16 → stable radix ARGSORT over the narrow codes
        (numpy's radix kicks in at ≤16-bit keys; handle order within
        equal codes rides on stability, so handles never join the key),
        the sorted column rebuilds from bincount+repeat, and arange
        handles (the auto-alloc case) come back as `order + first` —
        no 128MB gathers at all;
      * codes + handle bits fit one int64 → pack and np.sort (radix,
        no permutation array);
      * else → stable lexsort."""
    n = len(handles)
    if n <= 1:
        return cols, handles
    if len(cols) == 1:
        fast = _sort_single_col(cols[0], handles)
        if fast is not None:
            return fast
    order = np.lexsort((handles, *cols[::-1]))
    return [c[order] for c in cols], handles[order]


def _sort_single_col(col: np.ndarray, handles: np.ndarray):
    n = len(handles)
    c_lo, c_hi = int(col.min()), int(col.max())
    h_lo, h_hi = int(handles.min()), int(handles.max())
    if c_hi - c_lo >= 1 << 62 or h_hi - h_lo >= 1 << 62:
        return None  # checked BEFORE subtracting: int64 span overflow
    g = int(np.gcd.reduce(col[:4096] - c_lo))
    if g > 1:
        q, r = np.divmod(col - c_lo, g)
        if r.any():  # sample stride doesn't hold globally
            g, q = 1, col - c_lo
    else:
        g, q = 1, col - c_lo
    span = (c_hi - c_lo) // g
    if span < (1 << 15) and (n <= 1 or bool((np.diff(handles) >= 0).all())):
        # ASCENDING handles only (the bulk path always passes the sorted
        # record plane's handles): stability then makes within-code input
        # order equal handle order, so handles never need to join the key
        order = np.argsort(q.astype(np.int16), kind="stable")
        counts = np.bincount(q, minlength=span + 1)
        c_s = np.repeat(np.arange(span + 1, dtype=np.int64) * g + c_lo, counts)
        if h_lo + n - 1 == h_hi and bool((np.diff(handles) == 1).all()):
            h_s = order + h_lo  # arange handles: the permutation IS the answer
        else:
            h_s = handles[order]
        return [c_s], h_s
    bits_h = max(1, (h_hi - h_lo).bit_length())
    if span.bit_length() + bits_h > 62:
        return None
    pk = np.sort((q << bits_h) | (handles - h_lo), kind="stable")
    c_s = (pk >> bits_h) * g + c_lo
    h_s = (pk & ((1 << bits_h) - 1)) + h_lo
    return [c_s], h_s


class SegmentView:
    """A scan's view of one run slice, minus dropped (shadowed) rows."""

    __slots__ = ("run", "i", "j", "drop")

    def __init__(self, run: Run, i: int, j: int, drop: set[int] | None = None):
        self.run = run
        self.i = i
        self.j = j
        self.drop = drop  # absolute row indices within run

    def keep_idx(self) -> np.ndarray:
        """Absolute row indices surviving drop + alive mask, in key order."""
        idx = np.arange(self.i, self.j, dtype=np.int64)
        if self.run.alive is not None:
            idx = idx[self.run.alive[self.i : self.j]]
        if self.drop:
            idx = idx[~np.isin(idx, np.fromiter(self.drop, np.int64, len(self.drop)))]
        return idx

    @property
    def n_rows(self) -> int:
        return len(self.keep_idx())

    def min_key(self) -> bytes:
        return self.run.key_at(self.i)

    def max_key(self) -> bytes:
        return self.run.key_at(self.j - 1)

    def pairs(self) -> list[tuple[bytes, bytes]]:
        """Materialize (key, value) pairs — the legacy-scan compat path."""
        r = self.run
        return [(r.key_at(int(i)), r.value(int(i))) for i in self.keep_idx()]

"""In-memory ordered KV engine (the badger-LSM stand-in; ref: unistore's
lockstore MemStore — a skiplist. Here: sorted key array + dict, which gives
O(log n) point ops and cache-friendly range scans; the C++ engine can slot
in behind the same interface later).
"""

from __future__ import annotations

import bisect
from threading import RLock


class MemKV:
    """Sorted byte-key → byte-value store with range scans.

    Thread-safe via a coarse RLock (matches the single-writer pattern of
    the in-process store; scans snapshot the key array slice).
    """

    def __init__(self):
        self._keys: list[bytes] = []
        self._map: dict[bytes, bytes] = {}
        self.lock = RLock()
        self.journal = None  # durable-mode WAL hook (storage/wal.py)

    def __len__(self):
        return len(self._keys)

    def get(self, key: bytes) -> bytes | None:
        return self._map.get(key)

    # Mutations journal FIRST, then touch the in-memory state: a poisoned
    # WAL (storage/wal.py IO-failure degrade) raises out of the append, and
    # journal-first means that raise leaves memory exactly at the state the
    # durable log describes — reads keep serving a consistent store.

    def put(self, key: bytes, value: bytes) -> None:
        with self.lock:
            if self.journal is not None:
                from .wal import rec_put

                self.journal.append(rec_put(key, value))
            if key not in self._map:
                bisect.insort(self._keys, key)
            self._map[key] = value

    def delete(self, key: bytes) -> None:
        with self.lock:
            if key in self._map:
                if self.journal is not None:
                    from .wal import rec_delete

                    self.journal.append(rec_delete(key))
                del self._map[key]
                i = bisect.bisect_left(self._keys, key)
                if i < len(self._keys) and self._keys[i] == key:
                    self._keys.pop(i)

    def write_batch(self, puts: list[tuple[bytes, bytes]], deletes: list[bytes] = ()) -> None:
        with self.lock:
            for k, v in puts:
                if self.journal is not None:
                    from .wal import rec_put

                    self.journal.append(rec_put(k, v))
                if k not in self._map:
                    bisect.insort(self._keys, k)
                self._map[k] = v
            for k in deletes:
                self.delete(k)

    def scan(self, start: bytes, end: bytes | None = None, limit: int | None = None):
        """Yield (key, value) for start <= key < end in order."""
        with self.lock:
            i = bisect.bisect_left(self._keys, start)
            keys = self._keys[i : i + limit if limit is not None else None]
            if end is not None:
                j = bisect.bisect_left(keys, end)
                keys = keys[:j]
            snapshot = [(k, self._map[k]) for k in keys]
        return snapshot

    def iter_from(self, start: bytes):
        """Iterator over (key, value) from start; snapshots lazily in
        chunks. Chunks grow 8 → 64 → ... → 1024: most callers are MVCC
        point lookups that consume one or two entries (a fixed 1024-row
        snapshot per point get was the single largest allocation on the
        warmed statement hot path), while range scans amortize to the
        full chunk within three batches."""
        cur = start
        limit = 8
        while True:
            batch = self.scan(cur, None, limit)
            if not batch:
                return
            yield from batch
            cur = batch[-1][0] + b"\x00"
            limit = min(limit * 8, 1024)

    def bulk_load(self, pairs: list[tuple[bytes, bytes]]) -> None:
        """Bulk ingest (the Lightning local-backend analog): sorts only the
        NEW keys and merges with the existing sorted key array — O(m log m
        + n + m), and a pure append when the batch lands past the tail."""
        import heapq

        with self.lock:
            if self.journal is not None:
                from .wal import rec_put

                for k, v in pairs:
                    self.journal.append(rec_put(k, v))
            fresh = [k for k, _ in pairs if k not in self._map]
            self._map.update(pairs)
            if not fresh:
                return
            fresh = sorted(set(fresh))
            if not self._keys or fresh[0] > self._keys[-1]:
                self._keys.extend(fresh)
            else:
                self._keys = list(heapq.merge(self._keys, fresh))

    def count_range(self, start: bytes, end: bytes) -> int:
        """Number of keys in [start, end) — two bisects, no snapshot.
        The compactor's delta estimator: cheap enough to poll per table
        per tick without touching values."""
        with self.lock:
            i = bisect.bisect_left(self._keys, start)
            j = bisect.bisect_left(self._keys, end)
            return j - i

    def first_key_at_or_after(self, start: bytes) -> bytes | None:
        """Smallest key >= start, or None. Lets a caller enumerate the
        distinct table prefixes in a CF by leapfrogging (bisect per
        prefix) instead of walking every version entry."""
        with self.lock:
            i = bisect.bisect_left(self._keys, start)
            return self._keys[i] if i < len(self._keys) else None

    def delete_range(self, start: bytes, end: bytes) -> int:
        with self.lock:
            i = bisect.bisect_left(self._keys, start)
            j = bisect.bisect_left(self._keys, end)
            doomed = self._keys[i:j]
            if doomed and self.journal is not None:
                from .wal import rec_delete_range

                self.journal.append(rec_delete_range(start, end))
            for k in doomed:
                del self._map[k]
            del self._keys[i:j]
            return len(doomed)

"""Timestamp oracle — the PD TSO stand-in (ref: unistore/pd.go fake PD).

Timestamps are (physical_ms << 18) | logical, like TiDB's TSO, so they
embed wall time yet stay strictly monotonic under bursts.
"""

from __future__ import annotations

import time
from threading import Lock


class TSO:
    LOGICAL_BITS = 18

    def __init__(self):
        self._lock = Lock()
        self._last = 0

    def next(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000) << self.LOGICAL_BITS
            ts = max(phys, self._last + 1)
            self._last = ts
            return ts

    def current(self) -> int:
        """A read-only timestamp (for stale reads / GC watermarks)."""
        with self._lock:
            return self._last

    @staticmethod
    def physical_ms(ts: int) -> int:
        return ts >> TSO.LOGICAL_BITS

"""Timestamp oracle — the PD TSO stand-in (ref: unistore/pd.go fake PD).

Timestamps are (physical_ms << 18) | logical, like TiDB's TSO, so they
embed wall time yet stay strictly monotonic under bursts.
"""

from __future__ import annotations

import time
from threading import Lock


class TSO:
    LOGICAL_BITS = 18

    def __init__(self):
        self._lock = Lock()
        self._last = 0

    def next(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000) << self.LOGICAL_BITS
            ts = max(phys, self._last + 1)
            self._last = ts
            return ts

    def current(self) -> int:
        """A read-only timestamp (for stale reads / GC watermarks)."""
        with self._lock:
            return self._last

    def advance_to(self, ts: int) -> None:
        """Never allocate at or below `ts` again. A real PD persists its
        high water; this stand-in re-learns it at recovery/promotion from
        the durable state instead. Without the seed, a store reopened in
        the SAME millisecond as its predecessor's last commit hands out
        read timestamps below that commit_ts — the freshest committed
        write is invisible until the wall clock ticks over."""
        with self._lock:
            if ts > self._last:
                self._last = ts

    @staticmethod
    def physical_ms(ts: int) -> int:
        return ts >> TSO.LOGICAL_BITS

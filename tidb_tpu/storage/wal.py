"""ctypes binding + journal layer over the native WAL engine
(native/wal.cpp). The durability spec is the reference's storage-node
model (unistore over badger's value-log, production TiKV over RocksDB
WAL): every mutation appends a framed record, commits group-flush +
fsync, recovery replays the intact prefix, and snapshots checkpoint the
full state so the log can reset.

Record payloads (framing/CRC live in C++; payloads are ours):
  b'P' u32 klen key value          put
  b'D' u32 klen key                delete
  b'X' u32 slen start u32 elen end delete_range
  b'R' run: u32 w, u64 n, u64 commit_ts, key_mat, starts, lens, vbuf
  b'G' / b'g' chunk / b'F'         frame group: ONE logical record
       streamed as bounded chunks (see GroupAssembler)

Group commit (PR 13): `sync_group` batches concurrent committers'
fsyncs — every committer appends its records, then ONE leader runs the
fsync for the whole group while followers wait on the flushed sequence
number. A failed group sync withholds EVERY ack in the group (leader and
followers all raise `StorageIOError`) and poisons the log exactly like a
per-commit fsync failure would. `tidb_wal_group_commit=OFF` routes
`Storage.wal_sync` back to plain `sync()` — bit-identical per-commit
behavior — as the live incident fallback.

Failure discipline (the durability fault domain, PR 10):

  * IO failure — ONE failed append or fsync poisons the `Wal` (the
    fsyncgate rule: after a failed fsync the kernel may have dropped the
    dirty pages, so re-trying and acking would be lying). Every later
    write raises `StorageIOError`; the owning Storage flips read-only.
    The commit IN FLIGHT at the failure is indeterminate — the error at
    the durability point means UNKNOWN outcome (the standard contract for
    an error after the commit point), never a false ack; every commit
    AFTER it fails before touching anything.
  * Corruption — recovery distinguishes a TORN TAIL (a crash cut the
    last frames; nothing with a valid CRC follows) from MID-LOG
    CORRUPTION (a bad frame with valid CRC frames after it — bit rot
    inside committed history). The first is truncated and tolerated;
    the second raises `WalCorruptionError` unless the operator opted
    into `drop-corrupt` (see Storage._open_durable / the
    `tidb_wal_recovery_mode` sysvar).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..errors import StorageIOError
from ..utils import metrics as M
from ..utils.failpoint import inject as _fp

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "wal.cpp")
_LIB: ctypes.CDLL | None = None
_LIB_LOCK = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    """Build (once, mtime-cached) and load the native library."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.join(os.path.dirname(src), "libtpuwal.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", so, src],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p]
        lib.wal_append.restype = ctypes.c_longlong
        lib.wal_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_flush.restype = ctypes.c_int
        lib.wal_flush.argtypes = [ctypes.c_void_p]
        lib.wal_fd.restype = ctypes.c_int
        lib.wal_fd.argtypes = [ctypes.c_void_p]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        lib.wal_abort.argtypes = [ctypes.c_void_p]
        lib.wal_replay_open.restype = ctypes.c_void_p
        lib.wal_replay_open.argtypes = [ctypes.c_char_p]
        lib.wal_replay_next.restype = ctypes.c_int
        lib.wal_replay_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.wal_replay_valid_bytes.restype = ctypes.c_uint64
        lib.wal_replay_valid_bytes.argtypes = [ctypes.c_void_p]
        lib.wal_replay_close.argtypes = [ctypes.c_void_p]
        lib.snap_write.restype = ctypes.c_int
        lib.snap_write.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.snap_read.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.snap_read.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.snap_probe.restype = ctypes.c_int
        lib.snap_probe.argtypes = [ctypes.c_char_p]
        lib.snap_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _LIB = lib
        return lib


class Wal:
    """One open write-ahead log.

    `on_io_error(op)` is the degrade hook the owning Storage installs:
    called exactly once, on the failure that poisons the log, BEFORE the
    `StorageIOError` is raised to the writer."""

    def __init__(self, path: str, on_io_error=None):
        self.lib = _load_lib()
        self.path = path
        self._h = self.lib.wal_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open WAL at {path}")
        self._lock = threading.Lock()
        self.poisoned = False
        self.on_io_error = on_io_error
        # --- group commit (PR 13) -----------------------------------------
        # `_appended_seq` counts records accepted (guarded by `_lock`, like
        # the append itself); `_flushed_seq` is the highest count known
        # durably fsynced (guarded by `_gc_cond`). A committer's records
        # are all <= the seq it reads AFTER its last append, so waiting
        # for `_flushed_seq >= that` waits for exactly its durability.
        self._gc_cond = threading.Condition()
        self._appended_seq = 0
        self._flushed_seq = 0
        self._sync_leader = False  # a group fsync is in flight
        # targets of committers currently waiting for durability: a
        # fsync that covers a target satisfies that committer, and the
        # covering leader counts exactly those for the group-size metric
        # (entrants arriving mid-fsync with later targets stay queued
        # for the NEXT group instead of being silently absorbed)
        self._group_targets: list[int] = []
        # --- log shipping (PR 14) -----------------------------------------
        # `tap(wal, seq, payload)` observes every accepted append (called
        # under `_lock`, in append order — it must only enqueue, never
        # block); `on_durable(wal, covered_seq)` fires when `_flushed_seq`
        # advances (under `_gc_cond`) so a shipper can wake without
        # polling. Installed by storage/ship.WalShipper via Storage.
        self.tap = None
        self.on_durable = None

    def _io_failed(self, op: str, cause) -> None:
        """First failure poisons the log; callers see a typed error."""
        first = not self.poisoned
        self.poisoned = True
        if first:
            M.WAL_IO_ERRORS.inc(op=op)
            cb = self.on_io_error
            if cb is not None:
                cb(op)
        err = StorageIOError(
            f"WAL {op} failed on {self.path!r} ({cause}); the log is "
            f"poisoned and the store is read-only — no commit will ack "
            f"until the store is reopened on healthy media"
        )
        if isinstance(cause, BaseException):
            raise err from cause
        raise err

    def append(self, payload: bytes) -> None:
        with self._lock:
            self._append_locked(payload)
        # durability-gap crashpoint: record buffered, nothing fsynced yet
        _fp("wal/after-append-before-sync")

    def _append_locked(self, payload: bytes) -> None:
        if self.poisoned:
            self._io_failed("append", "log already poisoned")
        if self._h is None:
            raise StorageIOError(f"WAL {self.path!r} is closed")
        try:
            _fp("wal/io-error-append")
        except OSError as e:
            self._io_failed("append", e)
        if self.lib.wal_append(self._h, payload, len(payload)) < 0:
            self._io_failed("append", "native append error")
        self._appended_seq += 1
        if self.tap is not None:
            self.tap(self, self._appended_seq, payload)

    def append_group(self, chunks) -> int:
        """Append ONE logical record streamed as a bounded frame group:
        a bare b'G' frame, one b'g'-prefixed frame per chunk, a bare
        b'F' frame — all under the append lock, so no other committer's
        frames interleave. The logical record is the chunk concatenation;
        it is never materialized here, which is the point — a 16M-row
        ingest journals at per-chunk memory instead of holding its whole
        WAL image resident. Returns the logical record's byte length.
        Recovery (and a shipped standby) joins the group back into the
        monolithic record; an unterminated trailing group is truncated
        wholesale at its b'G' frame — atomic replay, same contract as
        the single-frame form."""
        total = 0
        with self._lock:
            self._append_locked(b"G")
            for chunk in _iter_bounded(chunks):
                total += len(chunk)
                self._append_locked(b"g" + chunk)
            self._append_locked(b"F")
        _fp("wal/after-append-before-sync")
        return total

    def sync(self) -> int:
        """Flush + fsync everything appended so far. Returns the record
        sequence the fsync covered (appends hold the same lock, so the
        count read after a successful fsync IS the durable high-water).
        Publishes the covered sequence to the group-commit state, so a
        per-commit sync (OFF mode, checkpoint) releases any concurrent
        group waiters it covered and the next group leader doesn't
        re-fsync already-durable records."""
        _fp("wal/before-sync")
        with self._lock:
            if self.poisoned:
                self._io_failed("sync", "log already poisoned")
            if self._h is None:
                covered = self._appended_seq  # closed: close() flushed + fsynced
            else:
                try:
                    _fp("wal/io-error-sync")
                except OSError as e:
                    self._io_failed("sync", e)
                if self.lib.wal_sync(self._h) != 0:
                    self._io_failed("sync", "native fsync error")
                covered = self._appended_seq
        with self._gc_cond:
            if covered > self._flushed_seq:
                self._flushed_seq = covered
            # waiters this fsync satisfied leave the queue uncounted —
            # the size histogram is leader-observed groups only
            self._group_targets = [t for t in self._group_targets if t > covered]
            if self.on_durable is not None:
                self.on_durable(self, covered)
            self._gc_cond.notify_all()
        return covered

    def sync_group(self, session=None, deadline=None) -> None:
        """Group-commit durability point: wait until everything this
        committer appended is fsynced, batching concurrent committers
        into one fsync.

        One leader at a time runs the real `sync()`; everyone else waits
        on `_flushed_seq`. The wait polls the shared interrupt gate, so a
        KILL or statement deadline releases a follower cleanly — its ack
        is withheld (the commit is indeterminate: the leader's fsync may
        still land it), never falsified. A failed group sync poisons the
        log; the leader raises from `sync()` and every follower observes
        `poisoned` and raises too — no ack in the group survives."""
        with self._lock:
            target = self._appended_seq
        with self._gc_cond:
            if self._flushed_seq >= target:
                M.WAL_GROUP_COMMIT.inc(outcome="follower")
                return  # an earlier leader already covered our records
            self._group_targets.append(target)
            while True:
                if self.poisoned:
                    self._io_failed("sync", "group sync failed; ack withheld")
                if self._flushed_seq >= target:
                    M.WAL_GROUP_COMMIT.inc(outcome="follower")
                    return
                if not self._sync_leader:
                    self._sync_leader = True
                    break  # this committer leads; all paths below are leader-only
                self._gc_cond.wait(0.05)
                if session is not None or deadline is not None:
                    from ..sched.scheduler import raise_if_interrupted

                    raise_if_interrupted(session, deadline)
        # --- leader: flush under the append lock, fsync OUTSIDE it — the
        # whole point of the group: committers keep appending (and piling
        # into the next group) while this group's fsync runs
        covered = -1
        try:
            try:
                # EIO/crash injection mid-group-sync: records appended
                # (possibly flushed), fsync not yet run — no committer in
                # the group may ack past this point on failure
                _fp("wal/group-sync-fail")
            except OSError as e:
                self._io_failed("sync", e)
            _fp("wal/before-sync")
            fd = -1
            with self._lock:
                if self.poisoned:
                    self._io_failed("sync", "log already poisoned")
                if self._h is not None:
                    try:
                        _fp("wal/io-error-sync")
                    except OSError as e:
                        self._io_failed("sync", e)
                    if self.lib.wal_flush(self._h) != 0:
                        self._io_failed("sync", "native flush error")
                    # dup so a concurrent close() can't invalidate the fd
                    # between releasing the lock and the fsync below
                    fd = os.dup(self.lib.wal_fd(self._h))
                high = self._appended_seq
            if fd >= 0:
                try:
                    os.fsync(fd)
                except OSError as e:
                    self._io_failed("sync", e)
                finally:
                    os.close(fd)
            covered = high
        finally:
            with self._gc_cond:
                self._sync_leader = False
                if covered >= 0:
                    self._flushed_seq = max(self._flushed_seq, covered)
                    if self.on_durable is not None:
                        self.on_durable(self, covered)
                    # the group = exactly the registered committers this
                    # fsync covered (leader included); later targets stay
                    # queued for the next leader
                    n = sum(1 for t in self._group_targets if t <= covered)
                    self._group_targets = [t for t in self._group_targets if t > covered]
                    M.WAL_GROUP_COMMIT.inc(outcome="leader")
                    if n:
                        M.WAL_GROUP_SIZE.observe(n)
                else:
                    # failed group sync: the log is poisoned, the whole
                    # queue will observe `poisoned` and raise — the
                    # group's acks are withheld, its targets moot
                    self._group_targets.clear()
                    M.WAL_GROUP_COMMIT.inc(outcome="error")
                self._gc_cond.notify_all()

    def durable_seq(self) -> int:
        """Highest record sequence KNOWN durable on this log. A cleanly
        closed log (checkpoint rotation flushed + fsynced everything) is
        durable through its whole append count; a poisoned log is durable
        only through the last successful fsync — frames past that must
        never ship to a standby (they may be gone with the page cache).
        A superseded log (spare-dir rotation snapshotted its in-memory
        effects) is fully durable THROUGH THE SNAPSHOT, which the
        rotation records by setting `_superseded`."""
        if getattr(self, "_superseded", False):
            with self._lock:
                return self._appended_seq
        with self._lock:
            closed = self._h is None
            appended = self._appended_seq
            poisoned = self.poisoned
        if closed and not poisoned:
            return appended
        with self._gc_cond:
            return self._flushed_seq

    def close(self) -> None:
        with self._lock:
            if self._h:
                if self.poisoned:
                    # NOTHING may be written after poisoning: drop the
                    # buffered (necessarily unacked) records like a crash
                    # would, instead of flushing them past the failure
                    self.lib.wal_abort(self._h)
                else:
                    self.lib.wal_close(self._h)
                self._h = None

    @staticmethod
    def replay(path: str):
        """Yield intact record payloads (stops at the first bad frame)."""
        recs, _ = Wal.replay_records(path)
        yield from recs

    @staticmethod
    def replay_records(path: str) -> tuple[list[bytes], int]:
        """→ (intact-prefix record payloads, intact byte prefix length).
        The caller must truncate the file to the prefix before appending,
        or post-recovery commits land beyond the torn bytes and are lost
        on the next replay. Corruption-agnostic: use `scan_log` to learn
        whether valid frames FOLLOW the first bad one."""
        lib = _load_lib()
        h = lib.wal_replay_open(path.encode())
        if not h:
            # distinguish "no log" from "log unreadable": truncating an
            # intact-but-unreadable log would destroy committed data
            if os.path.exists(path) and os.path.getsize(path) > 0:
                raise OSError(f"WAL {path!r} exists but could not be read")
            return [], 0
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_uint64()
            recs = []
            while lib.wal_replay_next(h, ctypes.byref(out), ctypes.byref(n)):
                recs.append(ctypes.string_at(out, n.value))
            return recs, int(lib.wal_replay_valid_bytes(h))
        finally:
            lib.wal_replay_close(h)

    @staticmethod
    def scan_log(path: str) -> "WalScan":
        """Full recovery scan: the intact prefix PLUS a look past the
        first bad frame, so recovery can tell a torn tail from mid-log
        corruption (see WalScan)."""
        recs, valid = Wal.replay_records(path)
        size = os.path.getsize(path) if os.path.exists(path) else 0
        salvage: list[bytes] = []
        gap = 0
        if valid < size:
            with open(path, "rb") as f:
                f.seek(valid)
                tail = f.read()
            salvage, gap = _scan_salvage(tail)
        return WalScan(recs, valid, size, salvage, gap)


@dataclass
class WalScan:
    """Result of Wal.scan_log.

    `records` is the intact prefix. When the file has a bad frame
    (`corrupt`), `salvage` holds the valid-CRC frames found AFTER it —
    non-empty salvage means MID-LOG corruption (committed history exists
    beyond the bad bytes; silently truncating would drop it), empty
    salvage means a plain torn tail. `salvage_gap` is the byte distance
    from the intact prefix to the first salvaged frame (the corrupt
    region recovery would discard under drop-corrupt)."""

    records: list = field(default_factory=list)
    valid_prefix: int = 0
    file_size: int = 0
    salvage: list = field(default_factory=list)
    salvage_gap: int = 0

    @property
    def corrupt(self) -> bool:
        return self.valid_prefix < self.file_size

    @property
    def mid_log(self) -> bool:
        return bool(self.salvage)


# resync scan window after a corrupt frame whose length header is ALSO
# gone: probing every byte offset is O(window * frame) worst case, so it
# is bounded — real logs resync at the first true frame boundary anyway
_SALVAGE_SCAN_CAP = 4 << 20
# CRC-work budget for the offset-probing fallback: pathological tails
# (e.g. long runs whose bytes keep decoding as in-range frame lengths)
# would otherwise cost O(window²) in checksums
_SALVAGE_CRC_BUDGET = 32 << 20


def _scan_salvage(tail: bytes) -> tuple[list[bytes], int]:
    """Hunt for a valid frame chain after the first bad frame.

    A chain only qualifies when it runs to EOF or ends in ONE incomplete
    trailing frame (bit rot leaves the rest of the file as intact frames;
    a crash may additionally tear the very last one). A torn tail's
    garbage bytes can contain pseudo-frames whose CRC happens to check
    out, but such a chain ends mid-garbage and is rejected — this errs
    toward classifying as torn (auto-recoverable) while never letting a
    real committed suffix be silently truncated. Zero-length frames also
    disqualify a chain: no real record is empty, but a zero-filled torn
    region chains as (len=0, crc=0) frames forever. Known limits: TWO
    separate corrupt regions read as a torn tail at the second one, and
    the offset-probing fallback (length header destroyed too) stops at a
    bounded CRC budget, classifying as torn past it."""
    n = len(tail)
    budget = [_SALVAGE_CRC_BUDGET]

    def chain(off: int) -> tuple[list[bytes], bool]:
        out: list[bytes] = []
        while off + 8 <= n:
            ln, crc = struct.unpack_from("<II", tail, off)
            if ln == 0:
                return out, False  # no real record is empty: garbage
            if off + 8 + ln > n:
                return out, True  # incomplete trailing frame: torn end
            budget[0] -= ln
            if zlib.crc32(tail[off + 8 : off + 8 + ln]) != crc:
                return out, False  # mid-data garbage: chain disqualified
            out.append(tail[off + 8 : off + 8 + ln])
            off += 8 + ln
        return out, True  # EOF (or < 8 trailing header bytes)

    # bit rot in a payload keeps the framing intact: the bad frame's
    # length header still points at the next frame
    if n >= 8:
        ln, _ = struct.unpack_from("<II", tail, 0)
        if ln and 8 + ln < n:
            got, clean_end = chain(8 + ln)
            if got and clean_end:
                return got, 8 + ln
    # length header corrupted too: resync by probing offsets (bounded)
    for off in range(1, max(0, min(n, _SALVAGE_SCAN_CAP) - 8)):
        if budget[0] <= 0:
            break
        got, clean_end = chain(off)
        if got and clean_end:
            return got, off
    return [], 0


def fsync_dir(path: str) -> None:
    """fsync a directory so renames/unlinks inside it are durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def snap_write(path: str, payload: bytes) -> None:
    if _load_lib().snap_write(path.encode(), payload, len(payload)) != 0:
        raise OSError(f"snapshot write failed: {path}")


def snap_read(path: str) -> bytes | None:
    lib = _load_lib()
    n = ctypes.c_uint64()
    buf = lib.snap_read(path.encode(), ctypes.byref(n))
    if not buf:
        return None
    try:
        return ctypes.string_at(buf, n.value)
    finally:
        lib.snap_free(buf)


def snap_probe(path: str) -> int:
    """Classify a snapshot file: -1 absent, 0 intact, 1 corrupt (present
    but short / bad magic / bad CRC). `snap_read` returns None for both
    absent and corrupt; recovery must refuse on corrupt instead of
    booting an empty store over the wrong epoch's log."""
    return int(_load_lib().snap_probe(path.encode()))


# --------------------------------------------------------- record payloads


def rec_put(key: bytes, value: bytes) -> bytes:
    return b"P" + struct.pack("<I", len(key)) + key + value


def rec_delete(key: bytes) -> bytes:
    return b"D" + struct.pack("<I", len(key)) + key


def rec_delete_range(start: bytes, end: bytes) -> bytes:
    return b"X" + struct.pack("<I", len(start)) + start + struct.pack("<I", len(end)) + end


def rec_kill_runs(start: bytes, end: bytes) -> bytes:
    return b"K" + struct.pack("<I", len(start)) + start + struct.pack("<I", len(end)) + end


def rec_run(key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray, commit_ts: int) -> bytes:
    n, w = key_mat.shape
    vb = bytes(vbuf) if not isinstance(vbuf, bytes) else vbuf
    return (
        b"R"
        + struct.pack("<IQQ", w, n, commit_ts)
        + np.ascontiguousarray(key_mat, dtype=np.uint8).tobytes()
        + np.ascontiguousarray(starts, dtype=np.int64).tobytes()
        + np.ascontiguousarray(lens, dtype=np.int64).tobytes()
        + struct.pack("<Q", len(vb))
        + vb
    )


def rec_crun(run) -> bytes:
    """Columnar record-run payload (storage/segment.ColumnarRun): the
    handles + column arrays ship as-is — no row-major value plane is ever
    materialized for the log, so the WAL write costs what the data weighs
    (the 'compressed tile form doubles as the ingest wire format' idea,
    arXiv:2506.10092)."""
    parts = [
        b"C",
        struct.pack("<QQq I", run.n, run.commit_ts, run.table_id, len(run.cols)),
        np.ascontiguousarray(run.handles_arr, dtype="<i8").tobytes(),
    ]
    for c in run.cols:
        data = c.data
        if data.dtype.kind in "OU":  # still-object str lanes canonicalize here
            from .segment import canonical_str_array

            data = canonical_str_array(data)
        data = np.ascontiguousarray(data)
        if data.dtype.kind == "S":
            if data.dtype.itemsize == 0:  # all-empty strings: keep width >= 1
                data = data.astype("S1")
            width = data.dtype.itemsize
            payload = data.tobytes()
        else:
            width = 0
            payload = data.astype(data.dtype.newbyteorder("<"), copy=False).tobytes()
        has_valid = 0 if c.valid is None else 1
        parts.append(struct.pack("<iBBBI", c.cid, c.kind, c.scale, has_valid, width))
        parts.append(payload)
        if has_valid:
            parts.append(np.ascontiguousarray(c.valid, dtype=np.uint8).tobytes())
    return b"".join(parts)


def rec_irun(run) -> bytes:
    """Int-index-run payload (storage/segment.IntIndexRun): sorted key
    columns + handles; the key byte matrix rebuilds lazily on demand."""
    parts = [
        b"N",
        struct.pack("<QQqqBB", run.n, run.commit_ts, run.table_id,
                    run.index_id, 1 if run.unique else 0, len(run.key_cols)),
    ]
    for c in run.key_cols:
        parts.append(np.ascontiguousarray(c, dtype="<i8").tobytes())
    parts.append(np.ascontiguousarray(run.handles_arr, dtype="<i8").tobytes())
    return b"".join(parts)


def rec_ingest(runs) -> bytes:
    """ONE logical bulk-ingest record (PR 15): every run of the ingest —
    record plane plus all index planes — nested in a single WAL frame,
    so recovery (and a shipped standby) replays the ingest atomically:
    the frame's CRC either admits the whole ingest or none of it."""
    subs = [r.to_wal_record() for r in runs]
    parts = [b"I", struct.pack("<I", len(subs))]
    for s in subs:
        parts.append(struct.pack("<Q", len(s)))
        parts.append(s)
    return b"".join(parts)


def rec_compact(table_id: int, fold_ts: int, spans, retire, runs) -> bytes:
    """ONE logical delta-main compaction (PR 16): the new segments, the
    mutable spans whose versions <= fold_ts they replace, and the retired
    source runs of a merge — a single WAL frame, so recovery (and a
    shipped standby) applies the whole fold-and-swap atomically or not at
    all. The frame does NOT carry per-key deletions: the fold decision is
    a pure function of (store state, span, fold_ts), recomputed at apply
    time (MVCCStore.apply_compaction) — replay walks the same state the
    live publish saw, so it reaches the same decision.

    retire entries are (kind, aux, commit_ts) identity tuples:
    kind 0 = ColumnarRun (aux unused), 1 = IntIndexRun (aux = index_id),
    2 = byte Run (aux = key width; scoped to table_id's key prefix)."""
    parts = [b"Z", struct.pack("<qQ", table_id, fold_ts),
             struct.pack("<I", len(spans))]
    for s, e in spans:
        parts.append(struct.pack("<I", len(s)))
        parts.append(s)
        parts.append(struct.pack("<I", len(e)))
        parts.append(e)
    parts.append(struct.pack("<I", len(retire)))
    for kind, aux, cts in retire:
        parts.append(struct.pack("<BqQ", kind, aux, cts))
    subs = [r.to_wal_record() for r in runs]
    parts.append(struct.pack("<I", len(subs)))
    for s in subs:
        parts.append(struct.pack("<Q", len(s)))
        parts.append(s)
    return b"".join(parts)


# ------------------------------------------------------------ frame groups
#
# A frame group streams ONE logical record to the log as bounded pieces:
#   b'G'            group begin (bare)
#   b'g' <chunk>    one chunk of the logical record
#   b'F'            group end (bare)
# The logical record is the concatenation of the chunks — byte-identical
# to the monolithic form, so `apply_record` never sees group tags. The
# writer holds the append lock across the whole group (Wal.append_group),
# so a group is always contiguous in the log and a torn group can only be
# the log's final frames.

GROUP_CHUNK_BYTES = 1 << 20


def _iter_bounded(chunks):
    """Re-chunk byte pieces to <= GROUP_CHUNK_BYTES each. Oversized
    pieces are split; small ones pass through un-coalesced (bounding
    resident memory is the goal, minimizing frame count is not)."""
    for piece in chunks:
        if len(piece) <= GROUP_CHUNK_BYTES:
            if piece:
                yield piece
        else:
            for off in range(0, len(piece), GROUP_CHUNK_BYTES):
                yield piece[off : off + GROUP_CHUNK_BYTES]


def iter_ingest_chunks(runs):
    """Stream the bulk-ingest record as chunks whose concatenation is
    byte-identical to `rec_ingest(runs)` — at most one run's WAL record
    is resident at a time instead of the whole ingest image."""
    yield b"I" + struct.pack("<I", len(runs))
    for r in runs:
        s = r.to_wal_record()
        yield struct.pack("<Q", len(s))
        yield s


def iter_compact_chunks(table_id: int, fold_ts: int, spans, retire, runs):
    """Stream the delta-main compaction record as chunks whose
    concatenation is byte-identical to `rec_compact(...)`."""
    parts = [b"Z", struct.pack("<qQ", table_id, fold_ts),
             struct.pack("<I", len(spans))]
    for s, e in spans:
        parts.append(struct.pack("<I", len(s)))
        parts.append(s)
        parts.append(struct.pack("<I", len(e)))
        parts.append(e)
    parts.append(struct.pack("<I", len(retire)))
    for kind, aux, cts in retire:
        parts.append(struct.pack("<BqQ", kind, aux, cts))
    parts.append(struct.pack("<I", len(runs)))
    yield b"".join(parts)
    for r in runs:
        s = r.to_wal_record()
        yield struct.pack("<Q", len(s))
        yield s


class GroupAssembler:
    """Join frame-group chunks back into logical records.

    `feed(payload)` returns the complete logical records the frame
    finished: a non-group frame passes straight through, group frames
    buffer until the closing b'F' joins them. Malformed sequences (a
    group tag outside a group, a non-chunk frame inside one) raise
    ValueError — the writer holds the append lock across a group, so
    they are unreachable from an honest log."""

    def __init__(self):
        self._chunks: list[bytes] | None = None

    @property
    def open(self) -> bool:
        return self._chunks is not None

    def feed(self, payload: bytes) -> list[bytes]:
        tag = payload[:1]
        if self._chunks is None:
            if tag == b"G":
                _need(len(payload) == 1, "G frame not bare")
                self._chunks = []
                return []
            _need(tag not in (b"g", b"F"), f"group frame {tag!r} outside a group")
            return [payload]
        if tag == b"g":
            self._chunks.append(payload[1:])
            return []
        if tag == b"F":
            _need(len(payload) == 1, "F frame not bare")
            rec = b"".join(self._chunks)
            self._chunks = None
            _need(len(rec) >= 1, "empty frame group")
            return [rec]
        raise ValueError(f"malformed WAL record: frame {tag!r} inside an open group")


def _apply_crun(payload: bytes):
    """Parse a 'C' payload → ColumnarRun (validating every length)."""
    from .segment import ColSpec, ColumnarRun

    _need(len(payload) >= 29, "C header short")
    n, commit_ts, table_id, ncols = struct.unpack_from("<QQq I", payload, 1)
    pos = 29
    _need(len(payload) >= pos + 8 * n, "C handles truncated")
    handles = np.frombuffer(payload, "<i8", n, pos).copy()
    pos += 8 * n
    cols = []
    for _ in range(ncols):
        _need(len(payload) >= pos + 11, "C column header short")
        # width is u32: a single TEXT value past 64KiB must not overflow
        # the lane-width field
        cid, kind, scale, has_valid, width = struct.unpack_from("<iBBBI", payload, pos)
        pos += 11
        from ..mysqltypes.datum import K_FLOAT, K_UINT

        if width:
            nb = width * n
            _need(len(payload) >= pos + nb, "C string column truncated")
            data = np.frombuffer(payload, f"S{width}", n, pos).copy()
        else:
            nb = 8 * n
            _need(len(payload) >= pos + nb, "C fixed column truncated")
            dt = "<f8" if kind == K_FLOAT else ("<u8" if kind == K_UINT else "<i8")
            data = np.frombuffer(payload, dt, n, pos).copy()
        pos += nb
        valid = None
        if has_valid:
            _need(len(payload) >= pos + n, "C valid mask truncated")
            valid = np.frombuffer(payload, np.uint8, n, pos).astype(bool)
            pos += n
        cols.append(ColSpec(cid, kind, scale, data, valid))
    _need(pos == len(payload), "C trailing bytes")
    return ColumnarRun(table_id, handles, cols, commit_ts)


def _apply_irun(payload: bytes):
    from .segment import IntIndexRun

    _need(len(payload) >= 35, "N header short")
    n, commit_ts, table_id, index_id, unique, k = struct.unpack_from("<QQqqBB", payload, 1)
    pos = 35
    _need(len(payload) == pos + 8 * n * (k + 1), "N arrays length mismatch")
    cols = []
    for _ in range(k):
        cols.append(np.frombuffer(payload, "<i8", n, pos).copy())
        pos += 8 * n
    handles = np.frombuffer(payload, "<i8", n, pos).copy()
    return IntIndexRun(table_id, index_id, cols, handles, bool(unique), commit_ts)


def _need(ok: bool, what: str) -> None:
    if not ok:
        raise ValueError(f"malformed WAL record: {what}")


def apply_record(payload: bytes, kv, mvcc) -> None:
    """Replay one journal record into the in-memory store.

    Every length field is validated BEFORE it is used to slice: a
    truncated or mutated payload must raise ValueError, never half-apply
    a short key/value (Python slices truncate silently) or hand
    np.frombuffer an out-of-range view. CRC framing makes malformed
    payloads unreachable in normal recovery; this is the defense for the
    drop-corrupt salvage path and for writer bugs."""
    _need(len(payload) >= 1, "empty payload")
    tag = payload[:1]
    if tag == b"P":
        _need(len(payload) >= 5, "P header short")
        (klen,) = struct.unpack_from("<I", payload, 1)
        _need(len(payload) >= 5 + klen, "P key truncated")
        key = payload[5 : 5 + klen]
        kv.put(key, payload[5 + klen :])
    elif tag == b"D":
        _need(len(payload) >= 5, "D header short")
        (klen,) = struct.unpack_from("<I", payload, 1)
        _need(len(payload) == 5 + klen, "D length mismatch")
        kv.delete(payload[5 : 5 + klen])
    elif tag in (b"X", b"K"):
        _need(len(payload) >= 5, "range header short")
        (slen,) = struct.unpack_from("<I", payload, 1)
        _need(len(payload) >= 9 + slen, "range start truncated")
        start = payload[5 : 5 + slen]
        (elen,) = struct.unpack_from("<I", payload, 5 + slen)
        _need(len(payload) == 9 + slen + elen, "range length mismatch")
        end = payload[9 + slen : 9 + slen + elen]
        if tag == b"X":
            kv.delete_range(start, end)
        else:
            mvcc.kill_runs_range(start, end)
    elif tag in (b"R", b"C", b"N"):
        mvcc.ingest_runs([_parse_run_record(payload)])
    elif tag == b"I":
        # ONE logical bulk ingest: parse EVERY nested run first (any
        # malformed sub-record refuses the whole frame — never a
        # half-applied ingest), then publish them as one atomic unit
        _need(len(payload) >= 5, "I header short")
        (nsub,) = struct.unpack_from("<I", payload, 1)
        pos = 5
        runs = []
        for _ in range(nsub):
            _need(len(payload) >= pos + 8, "I sub-record header short")
            (slen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            _need(len(payload) >= pos + slen, "I sub-record truncated")
            runs.append(_parse_run_record(payload[pos : pos + slen]))
            pos += slen
        _need(pos == len(payload), "I trailing bytes")
        mvcc.ingest_runs(runs)
    elif tag == b"Z":
        # ONE logical compaction: parse EVERYTHING first (spans, retire
        # identities, every nested run — any malformed piece refuses the
        # whole frame), then fold-and-swap as one atomic unit
        _need(len(payload) >= 21, "Z header short")
        table_id, fold_ts = struct.unpack_from("<qQ", payload, 1)
        pos = 17
        (nspans,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        spans = []
        for _ in range(nspans):
            _need(len(payload) >= pos + 4, "Z span header short")
            (slen,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            _need(len(payload) >= pos + slen + 4, "Z span start truncated")
            s = payload[pos : pos + slen]
            pos += slen
            (elen,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            _need(len(payload) >= pos + elen, "Z span end truncated")
            spans.append((s, payload[pos : pos + elen]))
            pos += elen
        _need(len(payload) >= pos + 4, "Z retire header short")
        (nret,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        _need(len(payload) >= pos + 17 * nret, "Z retire truncated")
        retire = []
        for _ in range(nret):
            kind, aux, cts = struct.unpack_from("<BqQ", payload, pos)
            pos += 17
            retire.append((kind, aux, cts))
        _need(len(payload) >= pos + 4, "Z runs header short")
        (nruns,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        runs = []
        for _ in range(nruns):
            _need(len(payload) >= pos + 8, "Z sub-record header short")
            (slen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            _need(len(payload) >= pos + slen, "Z sub-record truncated")
            runs.append(_parse_run_record(payload[pos : pos + slen]))
            pos += slen
        _need(pos == len(payload), "Z trailing bytes")
        mvcc.apply_compaction(table_id, fold_ts, spans, retire, runs)
    else:
        raise ValueError(f"unknown WAL record tag {tag!r}")


def _parse_run_record(payload: bytes):
    """One run-shaped record payload → a Run/ColumnarRun/IntIndexRun
    (validated, NOT yet published)."""
    from .segment import Run

    _need(len(payload) >= 1, "empty run record")
    tag = payload[:1]
    if tag == b"C":
        return _apply_crun(payload)
    if tag == b"N":
        return _apply_irun(payload)
    _need(tag == b"R", f"unexpected run record tag {tag!r}")
    _need(len(payload) >= 21, "R header short")
    w, n, commit_ts = struct.unpack_from("<IQQ", payload, 1)
    pos = 21
    _need(len(payload) >= pos + n * w + 16 * n + 8, "R arrays truncated")
    key_mat = np.frombuffer(payload, np.uint8, n * w, pos).reshape(int(n), w).copy()
    pos += n * w
    starts = np.frombuffer(payload, np.int64, n, pos).copy()
    pos += 8 * n
    lens = np.frombuffer(payload, np.int64, n, pos).copy()
    pos += 8 * n
    (vlen,) = struct.unpack_from("<Q", payload, pos)
    _need(len(payload) == pos + 8 + vlen, "R value buffer length mismatch")
    vbuf = payload[pos + 8 : pos + 8 + vlen]
    if n:
        _need(
            bool(
                (starts >= 0).all() and (lens >= 0).all()
                and (starts <= vlen).all() and (lens <= vlen).all()
                and (starts + lens <= vlen).all()
            ),
            "R value slices out of range",
        )
    return Run(key_mat, vbuf, starts, lens, commit_ts)

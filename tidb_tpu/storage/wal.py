"""ctypes binding + journal layer over the native WAL engine
(native/wal.cpp). The durability spec is the reference's storage-node
model (unistore over badger's value-log, production TiKV over RocksDB
WAL): every mutation appends a framed record, commits group-flush +
fsync, recovery replays the intact prefix, and snapshots checkpoint the
full state so the log can reset.

Record payloads (framing/CRC live in C++; payloads are ours):
  b'P' u32 klen key value          put
  b'D' u32 klen key                delete
  b'X' u32 slen start u32 elen end delete_range
  b'R' run: u32 w, u64 n, u64 commit_ts, key_mat, starts, lens, vbuf
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

import numpy as np
from ..utils.failpoint import inject as _fp

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "wal.cpp")
_LIB: ctypes.CDLL | None = None
_LIB_LOCK = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    """Build (once, mtime-cached) and load the native library."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.join(os.path.dirname(src), "libtpuwal.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", so, src],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p]
        lib.wal_append.restype = ctypes.c_longlong
        lib.wal_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        lib.wal_replay_open.restype = ctypes.c_void_p
        lib.wal_replay_open.argtypes = [ctypes.c_char_p]
        lib.wal_replay_next.restype = ctypes.c_int
        lib.wal_replay_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.wal_replay_valid_bytes.restype = ctypes.c_uint64
        lib.wal_replay_valid_bytes.argtypes = [ctypes.c_void_p]
        lib.wal_replay_close.argtypes = [ctypes.c_void_p]
        lib.snap_write.restype = ctypes.c_int
        lib.snap_write.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.snap_read.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.snap_read.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.snap_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _LIB = lib
        return lib


class Wal:
    """One open write-ahead log."""

    def __init__(self, path: str):
        self.lib = _load_lib()
        self.path = path
        self._h = self.lib.wal_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open WAL at {path}")
        self._lock = threading.Lock()

    def append(self, payload: bytes) -> None:
        with self._lock:
            if self.lib.wal_append(self._h, payload, len(payload)) < 0:
                raise OSError("WAL append failed")

    def sync(self) -> None:
        _fp("wal/before-sync")
        with self._lock:
            if self.lib.wal_sync(self._h) != 0:
                raise OSError("WAL fsync failed")

    def close(self) -> None:
        with self._lock:
            if self._h:
                self.lib.wal_close(self._h)
                self._h = None

    @staticmethod
    def replay(path: str):
        """Yield intact record payloads (stops at a torn tail)."""
        recs, _ = Wal.replay_records(path)
        yield from recs

    @staticmethod
    def replay_records(path: str) -> tuple[list[bytes], int]:
        """→ (intact record payloads, intact byte prefix length). The
        caller must truncate the file to the prefix before appending, or
        post-recovery commits land beyond the torn bytes and are lost on
        the next replay."""
        lib = _load_lib()
        h = lib.wal_replay_open(path.encode())
        if not h:
            # distinguish "no log" from "log unreadable": truncating an
            # intact-but-unreadable log would destroy committed data
            if os.path.exists(path) and os.path.getsize(path) > 0:
                raise OSError(f"WAL {path!r} exists but could not be read")
            return [], 0
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_uint64()
            recs = []
            while lib.wal_replay_next(h, ctypes.byref(out), ctypes.byref(n)):
                recs.append(ctypes.string_at(out, n.value))
            return recs, int(lib.wal_replay_valid_bytes(h))
        finally:
            lib.wal_replay_close(h)


def fsync_dir(path: str) -> None:
    """fsync a directory so renames/unlinks inside it are durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def snap_write(path: str, payload: bytes) -> None:
    if _load_lib().snap_write(path.encode(), payload, len(payload)) != 0:
        raise OSError(f"snapshot write failed: {path}")


def snap_read(path: str) -> bytes | None:
    lib = _load_lib()
    n = ctypes.c_uint64()
    buf = lib.snap_read(path.encode(), ctypes.byref(n))
    if not buf:
        return None
    try:
        return ctypes.string_at(buf, n.value)
    finally:
        lib.snap_free(buf)


# --------------------------------------------------------- record payloads


def rec_put(key: bytes, value: bytes) -> bytes:
    return b"P" + struct.pack("<I", len(key)) + key + value


def rec_delete(key: bytes) -> bytes:
    return b"D" + struct.pack("<I", len(key)) + key


def rec_delete_range(start: bytes, end: bytes) -> bytes:
    return b"X" + struct.pack("<I", len(start)) + start + struct.pack("<I", len(end)) + end


def rec_kill_runs(start: bytes, end: bytes) -> bytes:
    return b"K" + struct.pack("<I", len(start)) + start + struct.pack("<I", len(end)) + end


def rec_run(key_mat: np.ndarray, vbuf, starts: np.ndarray, lens: np.ndarray, commit_ts: int) -> bytes:
    n, w = key_mat.shape
    vb = bytes(vbuf) if not isinstance(vbuf, bytes) else vbuf
    return (
        b"R"
        + struct.pack("<IQQ", w, n, commit_ts)
        + np.ascontiguousarray(key_mat, dtype=np.uint8).tobytes()
        + np.ascontiguousarray(starts, dtype=np.int64).tobytes()
        + np.ascontiguousarray(lens, dtype=np.int64).tobytes()
        + struct.pack("<Q", len(vb))
        + vb
    )


def apply_record(payload: bytes, kv, mvcc) -> None:
    """Replay one journal record into the in-memory store."""
    tag = payload[:1]
    if tag == b"P":
        (klen,) = struct.unpack_from("<I", payload, 1)
        key = payload[5 : 5 + klen]
        kv.put(key, payload[5 + klen :])
    elif tag == b"D":
        (klen,) = struct.unpack_from("<I", payload, 1)
        kv.delete(payload[5 : 5 + klen])
    elif tag in (b"X", b"K"):
        (slen,) = struct.unpack_from("<I", payload, 1)
        start = payload[5 : 5 + slen]
        (elen,) = struct.unpack_from("<I", payload, 5 + slen)
        end = payload[9 + slen : 9 + slen + elen]
        if tag == b"X":
            kv.delete_range(start, end)
        else:
            mvcc.kill_runs_range(start, end)
    elif tag == b"R":
        w, n, commit_ts = struct.unpack_from("<IQQ", payload, 1)
        pos = 1 + 20
        key_mat = np.frombuffer(payload, np.uint8, n * w, pos).reshape(int(n), w).copy()
        pos += n * w
        starts = np.frombuffer(payload, np.int64, n, pos).copy()
        pos += 8 * n
        lens = np.frombuffer(payload, np.int64, n, pos).copy()
        pos += 8 * n
        (vlen,) = struct.unpack_from("<Q", payload, pos)
        vbuf = payload[pos + 8 : pos + 8 + vlen]
        mvcc.ingest_run(key_mat, vbuf, starts, lens, commit_ts, presorted=True)
    else:
        raise ValueError(f"unknown WAL record tag {tag!r}")

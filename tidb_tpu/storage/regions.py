"""Region map — key-space sharding metadata (ref: unistore/cluster.go,
mock_region.go; PD's region tree).

Regions partition the key space [start, end). The cop client splits key
ranges along region boundaries into tasks (copr/coprocessor.go:151 analog);
on the TPU side each region's rows become a shard of the device mesh.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from threading import RLock


@dataclass
class Region:
    id: int
    start: bytes  # inclusive; b"" = -inf
    end: bytes  # exclusive; b"" = +inf
    leader_store: int = 1
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (self.end == b"" or key < self.end)


class RegionMap:
    def __init__(self):
        self._lock = RLock()
        self._next_id = 2
        self.regions: list[Region] = [Region(1, b"", b"")]

    def _starts(self):
        return [r.start for r in self.regions]

    def locate(self, key: bytes) -> Region:
        with self._lock:
            i = bisect.bisect_right(self._starts(), key) - 1
            return self.regions[max(i, 0)]

    def split(self, split_key: bytes) -> Region | None:
        """Split the region containing split_key at that key."""
        with self._lock:
            i = bisect.bisect_right(self._starts(), split_key) - 1
            r = self.regions[max(i, 0)]
            if r.start == split_key or (r.end != b"" and split_key >= r.end):
                return None
            new = Region(self._next_id, split_key, r.end, r.leader_store, r.epoch + 1)
            self._next_id += 1
            r.end = split_key
            r.epoch += 1
            self.regions.insert(i + 1, new)
            return new

    def split_many(self, keys: list[bytes]) -> int:
        n = 0
        for k in sorted(set(keys)):
            if self.split(k) is not None:
                n += 1
        return n

    def regions_in_range(self, start: bytes, end: bytes | None) -> list[Region]:
        """All regions overlapping [start, end)."""
        with self._lock:
            out = []
            for r in self.regions:
                if end is not None and end != b"" and r.start >= end:
                    break
                if r.end != b"" and r.end <= start:
                    continue
                out.append(r)
            return out

    def split_ranges(self, start: bytes, end: bytes) -> list[tuple["Region", bytes, bytes]]:
        """Clip [start, end) against region boundaries → per-region subranges
        (the buildCopTasks region alignment, copr/coprocessor.go:151)."""
        out = []
        for r in self.regions_in_range(start, end):
            s = max(start, r.start)
            e = end if r.end == b"" else (min(end, r.end) if end != b"" else r.end)
            if e == b"" or s < e:
                out.append((r, s, e))
        return out

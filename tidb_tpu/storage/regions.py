"""Region map — key-space sharding metadata (ref: unistore/cluster.go,
mock_region.go; PD's region tree).

Regions partition the key space [start, end). The cop client splits key
ranges along region boundaries into tasks (copr/coprocessor.go:151 analog);
on the TPU side each region's rows become a shard of the device mesh.
"""

from __future__ import annotations

import bisect
import random
from dataclasses import dataclass, field
from threading import RLock


def _mid_key(start: bytes, end: bytes) -> bytes | None:
    """Lexicographic midpoint of [start, end) — an arbitrary but valid
    split key (region boundaries may land anywhere inside an encoded key).
    Open-ended bounds extend with a 0x80 probe byte; None when the range
    is too narrow to split."""
    if end == b"":
        return start + b"\x80"
    width = max(len(start), len(end)) + 1
    a = int.from_bytes(start.ljust(width, b"\x00"), "big")
    b = int.from_bytes(end.ljust(width, b"\x00"), "big")
    mid = (a + b) // 2
    if mid <= a:
        return None
    key = mid.to_bytes(width, "big").rstrip(b"\x00")
    return key if start < key and (end == b"" or key < end) else None


@dataclass
class Region:
    id: int
    start: bytes  # inclusive; b"" = -inf
    end: bytes  # exclusive; b"" = +inf
    leader_store: int = 1
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (self.end == b"" or key < self.end)


class RegionMap:
    def __init__(self):
        self._lock = RLock()
        self._next_id = 2
        self.regions: list[Region] = [Region(1, b"", b"")]

    def _starts(self):
        return [r.start for r in self.regions]

    def locate(self, key: bytes) -> Region:
        with self._lock:
            i = bisect.bisect_right(self._starts(), key) - 1
            return self.regions[max(i, 0)]

    def split(self, split_key: bytes) -> Region | None:
        """Split the region containing split_key at that key."""
        with self._lock:
            i = bisect.bisect_right(self._starts(), split_key) - 1
            r = self.regions[max(i, 0)]
            if r.start == split_key or (r.end != b"" and split_key >= r.end):
                return None
            new = Region(self._next_id, split_key, r.end, r.leader_store, r.epoch + 1)
            self._next_id += 1
            r.end = split_key
            r.epoch += 1
            self.regions.insert(i + 1, new)
            return new

    def split_many(self, keys: list[bytes]) -> int:
        n = 0
        for k in sorted(set(keys)):
            if self.split(k) is not None:
                n += 1
        return n

    def regions_in_range(self, start: bytes, end: bytes | None) -> list[Region]:
        """All regions overlapping [start, end)."""
        with self._lock:
            out = []
            for r in self.regions:
                if end is not None and end != b"" and r.start >= end:
                    break
                if r.end != b"" and r.end <= start:
                    continue
                out.append(r)
            return out

    def transfer_leader(self, region_id: int | None = None, to_store: int | None = None,
                        stores: int = 3, rng: random.Random | None = None) -> Region | None:
        """Move a region's leadership to another store (PD's
        transfer-leader operator). Leadership moves do NOT bump the epoch
        — an in-flight cop task built against the old leader sees a
        NotLeader-shaped mismatch and must chase the new leader, not
        re-split (the distinction the typed retry taxonomy exists for)."""
        with self._lock:
            if region_id is None:
                r = (rng or random).choice(self.regions)
            else:
                r = next((x for x in self.regions if x.id == region_id), None)
                if r is None:
                    return None
            r.leader_store = to_store if to_store is not None else (r.leader_store % stores) + 1
            return r

    def chaos_step(self, rng: random.Random | None = None) -> str:
        """One random act of region chaos — a mid-query split at a byte
        midpoint or a leader transfer — the failpoint-armed helper behind
        tests/test_chaos.py (arm it on `cop/before-task` with
        ("prob", p, lambda: store.regions.chaos_step()))."""
        rng = rng or random
        with self._lock:
            if rng.random() < 0.5:
                self.transfer_leader(rng=rng if isinstance(rng, random.Random) else None)
                return "transfer"
            r = self.regions[rng.randrange(len(self.regions))]
            key = _mid_key(r.start, r.end)
            if key is not None and self.split(key) is not None:
                return "split"
            return "none"

    def split_ranges(self, start: bytes, end: bytes) -> list[tuple["Region", bytes, bytes]]:
        """Clip [start, end) against region boundaries → per-region subranges
        (the buildCopTasks region alignment, copr/coprocessor.go:151)."""
        out = []
        for r in self.regions_in_range(start, end):
            s = max(start, r.start)
            e = end if r.end == b"" else (min(end, r.end) if end != b"" else r.end)
            if e == b"" or s < e:
                out.append((r, s, e))
        return out

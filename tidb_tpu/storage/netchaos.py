"""Network fault injection for the replica fleet (PR 19) — the chaos
half of partition hardening.

A `NetChaos` manager wraps each fleet socket endpoint (`_SocketSender`
→ `StandbyServer`, including the status RPC that shares the port) in a
frame-aware TCP proxy. The sender attaches to the proxy's address, so
every byte of the ship wire — frames, acks, HELLO, heartbeats, status
round trips — can be dropped, delayed, duplicated, black-holed or cut
per link without touching either endpoint. That is exactly the fault
surface TiDB's reference deployment delegates to Raft leases and store
heartbeats, and that log-replica designs (Taurus, arXiv:2506.20010)
treat as the primary constraint on the quorum ack path.

Every decision routes through the existing failpoint machinery: a rule
is an armed failpoint named `netchaos/<link>/<kind>`, so chaos runs are
seedable (`FP.seed`), composable with every other armed site, and
`tools/crashpoint.py` can hang a ("crash",) action on a chaos site —
the proxy fires non-decision actions it finds armed there (that is how
"partition + kill" composes into one round).

Rule kinds, per link:

  * `drop-conn`   — per-c2s-frame decision: cut the connection (flaky
                    wire; the sender answers with reconnect-resync)
  * `refuse`      — while armed, new connections are accepted and
                    immediately closed (the flapper's down phase —
                    distinct from black-hole: the sender SEES the
                    failure instantly)
  * `blackhole-c2s` / `blackhole-s2c` — while armed, that direction is
                    read and DISCARDED. The TCP connection stays open
                    and writable: the far side observes silence, not an
                    error — the failure class the 30s socket timeout
                    used to hide, and what link heartbeats now break
                    typed (`reason=timeout`) in ~hundreds of ms
  * `delay-c2s` / `delay-s2c` — hold the direction's next unit for
                    `spec` seconds: a float, or `(fixed, jitter)` with
                    the jittered part drawn from the seeded chaos RNG
  * `dup-frame`   — per-ship-frame decision: forward the frame twice
                    (the seq-based idempotent receive must apply once)
  * `drop-frame`  — per-ship-frame decision: swallow the frame (the
                    standby sees a seq gap / short ack and the sender
                    resyncs)

Named partition groups build on the rules: `partition(group, links,
direction=...)` black-holes every listed link, `direction="c2s"` /
`"s2c"` makes the partition ASYMMETRIC (frames arrive but acks vanish,
or vice versa — the split-brain battery's favorite), `heal(group)`
lifts it. `flap(link, up_s, down_s)` cycles a link through
refuse+disconnect phases on its own thread.

Lock order (tools/analyze/lock_order.toml): `netchaos.mgr` (56) >
`netchaos` (61) — both are leaves by design: every failpoint (65)
arm/decide and every socket op on a snapshotted conn list happens with
the lock RELEASED, and nothing is ever acquired under either.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

from ..utils.failpoint import FP, Failpoints

log = logging.getLogger(__name__)

_FRAME_HDR = struct.Struct("<BII")  # tag, len, crc32 (the ship wire shape)
# ship frames eligible for dup/drop rules: data frames only — duplicating
# a SYNC would elicit a second ack and desync the request/response rhythm
_DATA_TAGS = (0x46, 0x66)  # _TAG_FRAME 'F', _TAG_FRAME_SEQ 'f'


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("chaos peer closed")
        buf += got
    return buf


def _hard_close(sock: socket.socket) -> None:
    """shutdown() BEFORE close(): a plain close() while the peer pump
    thread is blocked in recv() on the same socket keeps the file alive
    (the blocked syscall holds its reference), so the FIN never goes out
    and the far endpoint hangs until its IO deadline instead of seeing
    the teardown. shutdown() sends the FIN immediately and wakes the
    blocked recv with EOF."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosEndpoint:
    """One frame-aware TCP proxy in front of one StandbyServer port.

    client (c2s) direction is parsed at ship-frame granularity so the
    frame-level rules (dup/drop/delay per frame) can fire; the server
    (s2c) direction — acks, HELLO and status replies — pumps opaque
    chunks (duplicating an ack would desync the sender) and supports
    the direction rules only (black-hole, delay)."""

    def __init__(self, name: str, upstream_host: str, upstream_port: int,
                 fp: Failpoints = FP, host: str = "127.0.0.1"):
        self.name = name
        self.upstream = (upstream_host, upstream_port)
        self._fpreg = fp
        self._lock = threading.Lock()  # "netchaos" (rank 61): conn registry
        self._conns: list[socket.socket] = []
        self._closing = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self.host, self.port = self._sock.getsockname()[:2]
        self._sock.listen(8)
        self._thread = threading.Thread(
            target=self._accept_loop, name=f"netchaos:{name}", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- rules

    def _site(self, kind: str) -> str:
        return f"netchaos/{self.name}/{kind}"

    def _armed(self, kind: str) -> bool:
        return self._fpreg.armed(self._site(kind))

    def _decide(self, kind: str):
        """Resolve one rule hit. Decision rules (True / ("prob", p) /
        ("nth", n)) return True when they fire; a composed NON-decision
        action armed at the site (("crash",), an exception, a callable)
        fires right here — the chaos site doubles as a failpoint site,
        which is what lets the crash harness kill the process exactly
        at a chaos event."""
        act = self._fpreg.decide(self._site(kind))
        if act is None or act is True:
            return act
        if isinstance(act, (int, float)) and not isinstance(act, bool):
            return act  # a delay spec
        if isinstance(act, tuple) and act and act[0] not in ("crash", "sleep"):
            return act  # (fixed, jitter) delay spec
        Failpoints._fire(act)
        return True

    def _delay(self, kind: str) -> None:
        spec = self._decide(kind)
        if not spec or spec is True:
            return
        if isinstance(spec, tuple):
            fixed, jitter = float(spec[0]), float(spec[1])
        else:
            fixed, jitter = float(spec), 0.0
        import time

        time.sleep(fixed + jitter * self._fpreg.rand())

    # ------------------------------------------------------------- pumps

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._armed("refuse") or self._closing:
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._lock:
                self._conns += [conn, up]
            threading.Thread(target=self._pump_c2s, args=(conn, up),
                             name=f"netchaos-c2s:{self.name}",
                             daemon=True).start()
            threading.Thread(target=self._pump_s2c, args=(conn, up),
                             name=f"netchaos-s2c:{self.name}",
                             daemon=True).start()

    def _drop_pair(self, conn: socket.socket, up: socket.socket) -> None:
        with self._lock:
            for s in (conn, up):
                if s in self._conns:
                    self._conns.remove(s)
        for s in (conn, up):
            _hard_close(s)

    def _pump_c2s(self, conn: socket.socket, up: socket.socket) -> None:
        """Client→server at ship-frame granularity: header + payload are
        read as a unit so per-frame rules can drop/dup/delay exactly one
        frame without corrupting the stream for the next."""
        try:
            while not self._closing:
                hdr = _recv_exact(conn, _FRAME_HDR.size)
                tag, ln, _crc = _FRAME_HDR.unpack(hdr)
                frame = hdr + (_recv_exact(conn, ln) if ln else b"")
                if self._decide("drop-conn"):
                    break
                self._delay("delay-c2s")
                if self._armed("blackhole-c2s"):
                    continue  # read and discarded: silence, not an error
                if tag in _DATA_TAGS and self._decide("drop-frame"):
                    continue
                up.sendall(frame)
                if tag in _DATA_TAGS and self._decide("dup-frame"):
                    up.sendall(frame)
        except (ConnectionError, OSError):
            pass
        finally:
            self._drop_pair(conn, up)

    def _pump_s2c(self, conn: socket.socket, up: socket.socket) -> None:
        try:
            while not self._closing:
                data = up.recv(65536)
                if not data:
                    break
                self._delay("delay-s2c")
                if self._armed("blackhole-s2c"):
                    continue
                conn.sendall(data)
        except (ConnectionError, OSError):
            pass
        finally:
            self._drop_pair(conn, up)

    # -------------------------------------------------------------- ops

    def kill_connections(self) -> None:
        """Cut every live connection through this proxy right now (the
        flapper's disconnect edge; the listener stays up)."""
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            _hard_close(s)

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        self.kill_connections()


class NetChaos:
    """Fleet-level chaos: named proxies, per-link rules, named partition
    groups (incl. asymmetric one-way partitions) and flappers. One
    instance per test/harness; `close()` disarms every rule it armed."""

    _KINDS = ("drop-conn", "refuse", "blackhole-c2s", "blackhole-s2c",
              "delay-c2s", "delay-s2c", "dup-frame", "drop-frame")

    def __init__(self, fp: Failpoints = FP):
        self._fpreg = fp
        self._mu = threading.Lock()  # "netchaos.mgr" (rank 56)
        self._proxies: dict[str, ChaosEndpoint] = {}
        self._groups: dict[str, tuple[tuple[str, ...], str]] = {}
        self._flappers: dict[str, tuple[threading.Thread, threading.Event]] = {}

    # ------------------------------------------------------------ wiring

    def wrap(self, name: str, host: str, port: int) -> tuple[str, int]:
        """Put a chaos proxy in front of `host:port` and return the
        address to attach the ship link (and status RPC) to. With no
        rules armed the proxy is a transparent relay."""
        ep = ChaosEndpoint(name, host, port, fp=self._fpreg)
        with self._mu:
            if name in self._proxies:
                ep.close()
                raise ValueError(f"chaos link {name!r} already wrapped")
            self._proxies[name] = ep
        return ep.host, ep.port

    def endpoint(self, name: str) -> ChaosEndpoint:
        with self._mu:
            return self._proxies[name]

    # ------------------------------------------------------------- rules

    def rule(self, name: str, kind: str, action=True) -> None:
        """Arm one rule: `action` is any failpoint action shape — True
        (always fire), ("prob", p), ("nth", n), a float/(fixed, jitter)
        delay spec for delay-* kinds, or a composed ("crash",)."""
        if kind not in self._KINDS:
            raise ValueError(f"unknown chaos rule kind {kind!r}")
        self._fpreg.enable(f"netchaos/{name}/{kind}", action)

    def clear(self, name: str, kind: str | None = None) -> None:
        for k in (self._KINDS if kind is None else (kind,)):
            self._fpreg.disable(f"netchaos/{name}/{k}")

    # -------------------------------------------------------- partitions

    def partition(self, group: str, links: list[str],
                  direction: str = "both") -> None:
        """Named partition: black-hole the listed links. `direction`
        picks the asymmetric variants — "c2s" (frames/heartbeats never
        arrive; the far side still answers whoever reaches it), "s2c"
        (frames ARE delivered and applied but acks vanish: the primary
        sees a dead link while the standby keeps catching up — the
        nastiest split-brain precursor), or "both"."""
        if direction not in ("both", "c2s", "s2c"):
            raise ValueError(f"bad partition direction {direction!r}")
        kinds = {"both": ("blackhole-c2s", "blackhole-s2c"),
                 "c2s": ("blackhole-c2s",), "s2c": ("blackhole-s2c",)}[direction]
        with self._mu:
            self._groups[group] = (tuple(links), direction)
        for l in links:
            for k in kinds:
                self._fpreg.enable(f"netchaos/{l}/{k}", True)

    def heal(self, group: str) -> None:
        """Lift a named partition (black-holed bytes were consumed, not
        buffered — the link resumes from silence, which is exactly what
        heartbeat-resync must cope with)."""
        with self._mu:
            links, _direction = self._groups.pop(group, ((), "both"))
        for l in links:
            self._fpreg.disable(f"netchaos/{l}/blackhole-c2s")
            self._fpreg.disable(f"netchaos/{l}/blackhole-s2c")

    # ---------------------------------------------------------- flapping

    def flap(self, name: str, up_s: float, down_s: float) -> None:
        """Cycle one link: up for `up_s`, then refuse + cut connections
        for `down_s`, repeat until `unflap`/`close`. A flap period below
        the reconnect budget exercises reconnect-resync without breaking
        the link; one above the heartbeat deadline breaks it typed."""
        stop = threading.Event()

        def run() -> None:
            ep = self.endpoint(name)
            while not stop.wait(up_s):
                self._fpreg.enable(f"netchaos/{name}/refuse", True)
                ep.kill_connections()
                if stop.wait(down_s):
                    break
                self._fpreg.disable(f"netchaos/{name}/refuse")
            self._fpreg.disable(f"netchaos/{name}/refuse")

        t = threading.Thread(target=run, name=f"netchaos-flap:{name}",
                             daemon=True)
        with self._mu:
            if name in self._flappers:
                raise ValueError(f"link {name!r} is already flapping")
            self._flappers[name] = (t, stop)
        t.start()

    def unflap(self, name: str) -> None:
        with self._mu:
            t, stop = self._flappers.pop(name, (None, None))
        if t is not None:
            stop.set()
            t.join(timeout=5.0)

    # ------------------------------------------------------------- close

    def kill_connections(self, name: str) -> None:
        self.endpoint(name).kill_connections()

    def close(self) -> None:
        with self._mu:
            flappers = list(self._flappers)
            proxies = list(self._proxies.items())
            groups = list(self._groups)
        for n in flappers:
            self.unflap(n)
        for g in groups:
            self.heal(g)
        for name, ep in proxies:
            self.clear(name)
            ep.close()
        with self._mu:
            self._proxies.clear()

"""MVCC garbage collection orchestrator
(ref: store/gcworker/gc_worker.go:63 — leader-elected worker; :397
safepoint = now - gc_life_time; :616 runGCJob resolve-locks + delete
ranges + version compaction).

Single-process: leadership collapses to the worker instance on Storage.
The physical version compaction itself lives in MVCCStore.gc; this layer
owns the safepoint policy and bookkeeping.
"""

from __future__ import annotations

import time

from .tso import TSO


class GCWorker:
    def __init__(self, storage, life_ms: int = 10 * 60 * 1000):
        self.storage = storage
        self.life_ms = life_ms  # tidb_gc_life_time analog
        self.last_safe_point = 0
        self.runs = 0
        self.removed_total = 0

    def compute_safe_point(self, now_ms: int | None = None) -> int:
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        return max(0, now_ms - self.life_ms) << TSO.LOGICAL_BITS

    def tick(self, now_ms: int | None = None) -> int:
        """One GC round; returns versions removed. Skips when the
        safepoint hasn't advanced (gc_worker leaderTick behavior)."""
        sp = self.compute_safe_point(now_ms)
        if sp <= self.last_safe_point:
            return 0
        self.last_safe_point = sp
        self.runs += 1
        removed = self.storage.mvcc.gc(sp)
        self.removed_total += removed
        return removed

"""MVCC garbage collection orchestrator
(ref: store/gcworker/gc_worker.go:63 — leader-elected worker; :397
safepoint = now - gc_life_time; :616 runGCJob resolve-locks + delete
ranges + version compaction).

Single-process: leadership collapses to the worker instance on Storage.
The physical version compaction itself lives in MVCCStore.gc; this layer
owns the safepoint policy and bookkeeping.
"""

from __future__ import annotations

import time

from .tso import TSO


def parse_go_duration_ms(s: str) -> int | None:
    """'10m0s' / '1h30m' / '90s' → milliseconds (the tidb_gc_* format,
    ref: gc_worker.go parseDuration)."""
    import re

    s = s.strip().lower()
    if not s:
        return None
    ms = 0.0
    pos = 0
    for m in re.finditer(r"(\d+(?:\.\d+)?)(ms|h|m|s)", s):
        if m.start() != pos:
            return None
        v = float(m.group(1))
        ms += v * {"h": 3_600_000, "m": 60_000, "s": 1_000, "ms": 1}[m.group(2)]
        pos = m.end()
    return int(ms) if pos == len(s) and pos else None


class GCWorker:
    def __init__(self, storage, life_ms: int = 10 * 60 * 1000):
        self.storage = storage
        self.life_ms = life_ms  # tidb_gc_life_time analog
        self.interval_ms = 10 * 60 * 1000  # tidb_gc_run_interval
        self.enabled = True  # tidb_gc_enable
        self.last_safe_point = 0
        self.runs = 0
        self.removed_total = 0

    def compute_safe_point(self, now_ms: int | None = None) -> int:
        """now - gc_life_time, clamped below the oldest active transaction
        so its snapshot stays readable (ref: gc_worker.go:397
        calcSafePointByMinStartTS)."""
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        sp = max(0, now_ms - self.life_ms) << TSO.LOGICAL_BITS
        min_start = self.storage.min_active_start_ts()
        if min_start is not None:
            sp = min(sp, min_start - 1)
        return max(0, sp)

    def _resolve_orphan_locks(self, sp: int, now_ms: int) -> int:
        """Clear pre-safepoint locks via their primaries before compaction
        (ref: gc_worker.go:616 runGCJob -> resolveLocks). Live txns never
        hold locks below sp — sp is clamped under min active start-ts —
        so everything found here belongs to dead transactions."""
        from .mvcc import Lock as LockRec

        mvcc = self.storage.mvcc
        stale = []
        with mvcc.kv.lock:
            for k, v in mvcc.kv.iter_from(b"l"):
                if not k.startswith(b"l"):
                    break
                lock = LockRec.decode(v)
                if lock.start_ts <= sp:
                    stale.append((k[1:], lock))
        resolved = 0
        for key, lock in stale:
            if mvcc.resolve_lock(key, lock, now_ms):
                resolved += 1
        return resolved

    def tick(self, now_ms: int | None = None) -> int:
        """One GC round; returns versions removed. Skips when the
        safepoint hasn't advanced (gc_worker leaderTick behavior)."""
        if not self.enabled:
            return 0  # SET GLOBAL tidb_gc_enable = OFF
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        sp = self.compute_safe_point(now_ms)
        if sp <= self.last_safe_point:
            return 0
        self.last_safe_point = sp
        self.runs += 1
        self._resolve_orphan_locks(sp, now_ms)
        removed = 0
        comp = self.storage.compactor
        if comp is not None:
            # delete-versions-via-compaction (PR 16): table spans reclaim
            # by folding into columnar segments — the newest visible value
            # survives as a segment row instead of a row-major rewrite
            removed += comp.gc_pass(self.storage, sp)
        # sweep what the fold doesn't own: meta keys, tables the fold
        # skipped (raced / ingest window open), stores with no compactor
        removed += self.storage.mvcc.gc(sp)
        self.removed_total += removed
        return removed

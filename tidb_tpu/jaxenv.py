"""JAX environment setup — imported by every device-facing module.

Device aggregation of scaled-int decimals and packed datetimes requires
64-bit lanes; XLA:TPU lowers s64 via 32-bit pairs, which is acceptable for
the reduction tails (the hot loops are f32/i32). Centralizing the config
here keeps `import tidb_tpu` (and the pure-host modules: mysqltypes, codec,
chunk, parser, planner) jax-free.
"""

import jax

jax.config.update("jax_enable_x64", True)

from jax import numpy as jnp  # noqa: E402  (re-export for device modules)

__all__ = ["jax", "jnp"]

"""JAX environment setup — imported by every device-facing module.

Device aggregation of scaled-int decimals and packed datetimes requires
64-bit lanes; XLA:TPU lowers s64 via 32-bit pairs, which is acceptable for
the reduction tails (the hot loops are f32/i32). Centralizing the config
here keeps `import tidb_tpu` (and the pure-host modules: mysqltypes, codec,
chunk, parser, planner) jax-free.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: cop/MPP programs are keyed by DAG
# digest in-process, but across processes (server restart, bench runs,
# the driver) recompiling identical programs costs seconds each on the
# TPU. The on-disk cache makes warmup a read (ref: the jit-cache story
# of copr/coprocessor_cache.go, taken one level down the stack).
_cache_dir = os.environ.get(
    "TIDB_TPU_XLA_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "tidb_tpu_xla")
)
try:
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
    pass

from jax import numpy as jnp  # noqa: E402  (re-export for device modules)

__all__ = ["jax", "jnp"]


# --------------------------------------------------------------------------
# single-buffer device→host result packing
#
# Over a remote device link (the axon tunnel) EVERY array fetched from the
# device costs a full round-trip (~100ms measured), so multi-output
# programs ship one int64 matrix instead. Row 0 carries per-row dtype tags
# IN-BAND: jit keeps one executable per input-dtype signature, and any
# out-of-band metadata recorded at trace time goes stale when signatures
# alternate over the same compiled-program cache entry.
# --------------------------------------------------------------------------

_KIND_I64, _KIND_F64, _KIND_BOOL, _KIND_U64 = 0, 1, 2, 3


def pack_rows(outs):
    """[array (L,)] (int/float/bool/uint64) → one int64 matrix (n+1, L)
    whose row 0 holds the dtype tags. All arrays must share length L ≥
    len(outs)."""
    import numpy as _np

    rows, kinds = [], []
    for o in outs:
        if o.dtype == jnp.float32:
            o = o.astype(jnp.float64)
        if o.dtype == jnp.float64:
            kinds.append(_KIND_F64)
            rows.append(jax.lax.bitcast_convert_type(o, jnp.int64))
        elif o.dtype == jnp.uint64:
            kinds.append(_KIND_U64)
            rows.append(jax.lax.bitcast_convert_type(o, jnp.int64))
        elif o.dtype == jnp.bool_:
            kinds.append(_KIND_BOOL)
            rows.append(o.astype(jnp.int64))
        else:
            kinds.append(_KIND_I64)
            rows.append(o.astype(jnp.int64))
    L = rows[0].shape[0]
    need = len(kinds) + 1
    if L < need:  # tiny result rows (top-k): widen so the tags fit
        rows = [jnp.concatenate([r, jnp.zeros((need - L,), jnp.int64)]) for r in rows]
        L = need
    tag = _np.zeros(L, dtype=_np.int64)
    tag[: len(kinds)] = kinds
    tag[-1] = len(kinds)  # row count, so unpack needs no side channel
    return jnp.stack([jnp.asarray(tag)] + rows)


def unpack_rows(packed):
    """Inverse of pack_rows over the fetched numpy matrix."""
    import numpy as _np

    tag = packed[0]
    n = int(tag[-1])
    out = []
    for i in range(n):
        row = packed[1 + i]
        k = int(tag[i])
        if k == _KIND_F64:
            out.append(row.view(_np.float64))
        elif k == _KIND_U64:
            out.append(row.view(_np.uint64))
        elif k == _KIND_BOOL:
            out.append(row != 0)
        else:
            out.append(row)
    return out


def pack_flat(outs):
    """Variable-length single-buffer packing: [header | seg0 | seg1 | ...]
    as one int64 vector. Bool lanes ship bit-packed (64 rows/word) — for
    full-row results the valid lane would otherwise double the transfer.
    Header: [n, kind0, len0, kind1, len1, ...] (static length)."""
    import numpy as _np

    header = [len(outs)]
    segs = []
    for o in outs:
        if o.dtype == jnp.float32:
            o = o.astype(jnp.float64)
        if o.dtype == jnp.float64:
            kind = _KIND_F64
            seg = jax.lax.bitcast_convert_type(o, jnp.int64)
        elif o.dtype == jnp.uint64:
            kind = _KIND_U64
            seg = jax.lax.bitcast_convert_type(o, jnp.int64)
        elif o.dtype == jnp.bool_:
            kind = _KIND_BOOL
            L = o.shape[0]
            W = -(-L // 64)
            padded = jnp.concatenate([o, jnp.zeros((W * 64 - L,), bool)])
            bits = padded.reshape(W, 64).astype(jnp.uint64) << jnp.arange(64, dtype=jnp.uint64)[None, :]
            seg = jax.lax.bitcast_convert_type(jnp.sum(bits, axis=1, dtype=jnp.uint64), jnp.int64)
            header += [kind, int(L)]
            segs.append(seg)
            continue
        else:
            kind = _KIND_I64
            seg = o.astype(jnp.int64)
        header += [kind, int(seg.shape[0])]
        segs.append(seg)

    return jnp.concatenate([jnp.asarray(_np.asarray(header, dtype=_np.int64))] + segs)


def unpack_flat(flat):
    """Inverse of pack_flat over the fetched numpy vector."""
    import numpy as _np

    n = int(flat[0])
    pos = 1 + 2 * n
    out = []
    for i in range(n):
        kind = int(flat[1 + 2 * i])
        L = int(flat[2 + 2 * i])
        if kind == _KIND_BOOL:
            W = -(-L // 64)
            words = flat[pos : pos + W].view(_np.uint64)
            bits = _np.unpackbits(words.view(_np.uint8), bitorder="little")
            out.append(bits[:L].astype(bool))
            pos += W
        else:
            seg = flat[pos : pos + L]
            if kind == _KIND_F64:
                out.append(seg.view(_np.float64))
            elif kind == _KIND_U64:
                out.append(seg.view(_np.uint64))
            else:
                out.append(seg)
            pos += L
    return out

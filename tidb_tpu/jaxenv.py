"""JAX environment setup — imported by every device-facing module.

Device aggregation of scaled-int decimals and packed datetimes requires
64-bit lanes; XLA:TPU lowers s64 via 32-bit pairs, which is acceptable for
the reduction tails (the hot loops are f32/i32). Centralizing the config
here keeps `import tidb_tpu` (and the pure-host modules: mysqltypes, codec,
chunk, parser, planner) jax-free.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: cop/MPP programs are keyed by DAG
# digest in-process, but across processes (server restart, bench runs,
# the driver) recompiling identical programs costs seconds each on the
# TPU. The on-disk cache makes warmup a read (ref: the jit-cache story
# of copr/coprocessor_cache.go, taken one level down the stack).
_cache_dir = os.environ.get(
    "TIDB_TPU_XLA_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "tidb_tpu_xla")
)
try:
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
    pass

from jax import numpy as jnp  # noqa: E402  (re-export for device modules)

__all__ = ["jax", "jnp"]

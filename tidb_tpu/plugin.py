"""Plugin framework — audit-style hook points
(ref: plugin/plugin.go:135 Load + plugin/spi.go + plugin/audit.go; the
reference loads .so plugins with audit hooks fired from session/conn.
Here plugins are Python objects registered per Storage, with the same
hook surface)."""

from __future__ import annotations

import importlib
import threading


class Plugin:
    """Base plugin: override any subset of the hooks."""

    name = "plugin"

    def on_connect(self, user: str, host: str) -> None:  # noqa: B027
        pass

    def on_query(self, user: str, db: str, sql: str, ok: bool, duration_s: float) -> None:  # noqa: B027
        pass

    def on_shutdown(self) -> None:  # noqa: B027
        pass


class PluginRegistry:
    def __init__(self):
        self._plugins: list[Plugin] = []
        self._lock = threading.Lock()

    def register(self, plugin: Plugin) -> None:
        with self._lock:
            self._plugins.append(plugin)

    def load(self, module_path: str) -> Plugin:
        """Import a module exposing `plugin` (an instance) or `activate()`
        (a factory) — the dlopen/Load analog."""
        mod = importlib.import_module(module_path)
        p = getattr(mod, "plugin", None)
        if p is None and hasattr(mod, "activate"):
            p = mod.activate()
        if not isinstance(p, Plugin):
            raise TypeError(f"{module_path} does not expose a Plugin")
        self.register(p)
        return p

    def unregister(self, name: str) -> None:
        with self._lock:
            self._plugins = [p for p in self._plugins if p.name != name]

    def fire(self, hook: str, *args) -> None:
        with self._lock:
            plugins = list(self._plugins)
        for p in plugins:
            try:
                getattr(p, hook)(*args)
            except Exception:  # noqa: BLE001 — a broken plugin must not break queries
                import logging

                logging.getLogger("tidb_tpu.plugin").exception("plugin %s hook %s failed", p.name, hook)

from .table import Table

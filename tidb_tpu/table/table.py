"""Table row operations (ref: table/tables/tables.go AddRecord:634,
UpdateRecord:322, tables/index.go — fresh implementation).

Row layout: record key t{tid}_r{handle} → tagged row codec value.
Handles: single-int primary key becomes the handle (clustered,
pk_is_handle); otherwise a hidden `_tidb_rowid` auto id.
Index layout: unique → t{tid}_i{iid}{vals} = handle;
non-unique → t{tid}_i{iid}{vals}{handle} = b''. NULL-containing unique
keys degrade to non-unique form (MySQL semantics: NULLs don't collide).
"""

from __future__ import annotations

from ..codec.key import encode_datum_key
from ..codec.row import encode_row, decode_row
from ..codec import tablecodec
from ..errors import DuplicateEntry
from ..mysqltypes.datum import Datum
from ..mysqltypes.coretime import parse_datetime
from ..catalog.schema import ColumnInfo, TableInfo, IndexInfo


def datum_from_default(col: ColumnInfo) -> Datum:
    """Materialize a column's stored default for rows written before the
    column existed (ref: rowcodec decoder default fill; table/column.go)."""
    if not col.has_default or col.default is None:
        return Datum.null()
    v = col.default
    ft = col.ft
    if ft.is_time():
        p = parse_datetime(str(v))
        return Datum.t(p) if p is not None else Datum.null()
    if ft.is_decimal():
        return Datum.d(Datum.s(str(v)).to_dec().rescale(max(ft.decimal, 0)))
    if ft.is_float():
        return Datum.f(float(v))
    if ft.is_int():
        return Datum.i(int(v))
    return Datum.s(str(v))


class Table:
    def __init__(self, info: TableInfo):
        self.info = info

    # --- key builders ------------------------------------------------------

    def record_key(self, handle: int) -> bytes:
        return tablecodec.record_key(self.info.id, handle)

    def index_value_key(self, idx: IndexInfo, datums: list[Datum], handle: int | None):
        """→ (key, value, needs_handle_suffix) for one index entry."""
        buf = bytearray()
        has_null = False
        for off in idx.col_offsets:
            d = datums[off]
            if d.is_null:
                has_null = True
            encode_datum_key(buf, d)
        distinct = idx.unique and not has_null
        if distinct:
            key = tablecodec.index_key(self.info.id, idx.id, bytes(buf))
            return key, str(handle).encode() if handle is not None else b"", True
        key = tablecodec.index_key(self.info.id, idx.id, bytes(buf), handle=handle)
        return key, b"", False

    # --- row ops ------------------------------------------------------------

    def row_datums_with_hidden(self, datums: list[Datum], handle: int) -> list[Datum]:
        """Full row including the hidden rowid column if present."""
        out = list(datums)
        for c in self.info.columns:
            if c.hidden and c.name == "_tidb_rowid":
                while len(out) <= c.offset:
                    out.append(Datum.null())
                out[c.offset] = Datum.i(handle)
        return out

    def add_record(self, txn, datums: list[Datum], handle: int, check_dup: bool = True) -> int:
        """Write row + all index entries into the txn membuffer."""
        info = self.info
        rk = self.record_key(handle)
        if check_dup and info.pk_is_handle and txn.get(rk) is not None:
            pk_off = next(i for i in info.indexes if i.primary).col_offsets[0]
            raise DuplicateEntry(f"Duplicate entry '{datums[pk_off].to_str()}' for key 'PRIMARY'")
        col_ids = [c.id for c in info.columns]
        full = self.row_datums_with_hidden(datums, handle)
        txn.put(rk, encode_row(col_ids, full))
        for idx in info.indexes:
            if info.pk_is_handle and idx.primary:
                continue  # clustered: the record key IS the pk index
            if idx.state in ("none", "delete_only"):
                continue  # online DDL: index not yet writable
            key, val, distinct = self.index_value_key(idx, full, handle)
            # unique check applies in EVERY writable state: during
            # write_only/write_reorg a silent overwrite would corrupt the
            # entry backfill already wrote (F1 dual-write invariant)
            if distinct and check_dup:
                existing = txn.get(key)
                if existing is not None and existing != val:
                    raise DuplicateEntry(f"Duplicate entry for key '{idx.name}'")
            txn.put(key, val)
        return handle

    def remove_record(self, txn, handle: int, datums: list[Datum]) -> None:
        txn.delete(self.record_key(handle))
        full = self.row_datums_with_hidden(datums, handle)
        for idx in self.info.indexes:
            if self.info.pk_is_handle and idx.primary:
                continue
            if idx.state == "none":
                continue  # no entries can exist yet
            key, _, _ = self.index_value_key(idx, full, handle)
            txn.delete(key)

    def update_record(self, txn, handle: int, old: list[Datum], new: list[Datum]) -> None:
        self.remove_record(txn, handle, old)
        self.add_record(txn, new, handle, check_dup=True)

    def decode_record(self, value: bytes) -> list[Datum]:
        """KV row value → datums in column offset order."""
        by_id = decode_row(value)
        out = []
        for c in self.info.columns:
            d = by_id.get(c.id)
            if d is None:
                d = datum_from_default(c)
            out.append(d)
        return out

    # --- auto id (ref: meta/autoid — simplified batched allocator) ---------

    def alloc_handles(self, session, n: int) -> int:
        """Allocate n consecutive handles; returns first. Batches through
        the table's auto_inc counter persisted at DDL meta."""
        return session.alloc_auto_id(self.info, n)

from .cache import PrivilegeCache, mysql_native_hash

__all__ = ["PrivilegeCache", "mysql_native_hash"]

"""Privilege cache — MySQL-compatible grants loaded from mysql.user /
mysql.db (ref: privilege/privileges/cache.go:94 UserRecord + :120; the
reference caches the mysql.* privilege tables in memory and reloads on
a notify version — here the version is a meta-keyspace counter bumped by
user-admin statements)."""

from __future__ import annotations

import hashlib

import threading

from ..errors import TiDBError

PRIVS = {
    "SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP",
    "ALTER", "INDEX", "PROCESS", "SUPER", "LOCK TABLES", "FILE",
}

# dynamic privileges (ref: privilege/privileges/cache.go:120 dynamic
# privs + privileges.go RequestDynamicVerification: grantable on *.*
# only, SUPER acts as the legacy fallback for each)
DYNAMIC_PRIVS = {
    "BACKUP_ADMIN", "RESTORE_ADMIN", "SYSTEM_VARIABLES_ADMIN",
    "CONNECTION_ADMIN", "ROLE_ADMIN", "BINDING_ADMIN", "DASHBOARD_CLIENT",
}

class PrivilegeError(TiDBError):
    pass


def mysql_native_hash(password: str) -> str:
    """MySQL password hash: '*' + HEX(SHA1(SHA1(pw)))."""
    if not password:
        return ""
    inner = hashlib.sha1(password.encode()).digest()
    return "*" + hashlib.sha1(inner).hexdigest().upper()


def verify_native_password(auth_string: str, salt: bytes, scramble: bytes) -> bool:
    """mysql_native_password: client sends SHA1(pw) XOR SHA1(salt+SHA1(SHA1(pw)))."""
    if not auth_string:
        return len(scramble) == 0
    if not scramble:
        return False
    stored = bytes.fromhex(auth_string.lstrip("*"))
    token = hashlib.sha1(salt + stored).digest()
    candidate = bytes(a ^ b for a, b in zip(token, scramble))
    return hashlib.sha1(candidate).digest() == stored


class PrivilegeCache:
    """Per-storage cache of user records + grants."""

    def __init__(self, storage):
        self.storage = storage
        # in-memory notify version (the etcd-notify analog); the cache
        # object lives on the Storage, so a restart naturally reloads
        self.notify_version = 0
        self._version = -1
        self._lock = threading.Lock()
        self._sys_session = None
        self._users: dict[str, dict] = {}  # user → {auth, global: set}
        self._db_privs: dict[tuple[str, str], set] = {}  # (user, db) → privs
        self._tbl_privs: dict[tuple[str, str, str], set] = {}  # (user, db, tbl) → privs
        self._dyn_privs: dict[str, set] = {}  # user → dynamic privs

    def bump_version(self) -> None:
        with self._lock:
            self.notify_version += 1

    def _sys(self):
        """Dedicated internal session: cache loads must see COMMITTED
        grants, never a calling session's transaction snapshot."""
        if self._sys_session is None:
            from ..session import Session

            self._sys_session = Session(self.storage)
        return self._sys_session

    # --- load --------------------------------------------------------------

    def _ensure(self, session) -> None:
        with self._lock:
            v = self.notify_version
            if v == self._version:
                return
            sess = self._sys()
            users: dict[str, dict] = {}
            db_privs: dict[tuple[str, str], set] = {}
            for host, user, auth, privs in sess._sql_internal(
                "SELECT host, user, auth_string, privs FROM mysql.user"
            ):
                pset = set() if not privs else set(privs.split(","))
                users[(user or "").lower()] = {"auth": auth or "", "global": pset, "host": host}
            for host, user, db, privs in sess._sql_internal(
                "SELECT host, user, db, privs FROM mysql.db"
            ):
                pset = set() if not privs else set(privs.split(","))
                db_privs[((user or "").lower(), (db or "").lower())] = pset
            tbl_privs: dict[tuple[str, str, str], set] = {}
            for host, user, db, tbl, privs in sess._sql_internal(
                "SELECT host, user, db, table_name, privs FROM mysql.tables_priv"
            ):
                pset = set() if not privs else set(privs.split(","))
                tbl_privs[((user or "").lower(), (db or "").lower(), (tbl or "").lower())] = pset
            dyn: dict[str, set] = {}
            for user, priv in sess._sql_internal(
                "SELECT user, priv FROM mysql.global_grants"
            ):
                dyn.setdefault((user or "").lower(), set()).add(priv)
            self._users = users
            self._db_privs = db_privs
            self._tbl_privs = tbl_privs
            self._dyn_privs = dyn
            self._version = v

    # --- checks ------------------------------------------------------------

    def user_exists(self, session, user: str) -> bool:
        self._ensure(session)
        return user.lower() in self._users

    def auth(self, session, user: str, salt: bytes, scramble: bytes) -> bool:
        self._ensure(session)
        rec = self._users.get(user.lower())
        if rec is None:
            return False
        return verify_native_password(rec["auth"], salt, scramble)

    def check(self, session, user: str, db: str, priv: str, table: str | None = None) -> bool:
        """Global → db → table level, most general wins (ref:
        privileges.go RequestVerification)."""
        self._ensure(session)
        rec = self._users.get(user.lower())
        if rec is None:
            return False
        g = rec["global"]
        if "ALL" in g or priv in g:
            return True
        d = self._db_privs.get((user.lower(), db.lower()), set())
        if "ALL" in d or priv in d:
            return True
        if table:
            t = self._tbl_privs.get((user.lower(), db.lower(), table.lower()), set())
            return "ALL" in t or priv in t
        return False

    def require(self, session, user: str, db: str, priv: str, table: str | None = None) -> None:
        if not self.check(session, user, db, priv, table):
            raise PrivilegeError(
                f"{priv} command denied to user '{user}'@'%' for database '{db}'"
            )

    def check_dynamic(self, session, user: str, priv: str) -> bool:
        """Dynamic privilege, with SUPER as the legacy fallback (ref:
        privileges.go RequestDynamicVerification grantableAtGlobalLevel)."""
        self._ensure(session)
        rec = self._users.get(user.lower())
        if rec is None:
            return False
        if priv in self._dyn_privs.get(user.lower(), set()):
            return True
        g = rec["global"]
        return "ALL" in g or "SUPER" in g

    def require_dynamic(self, session, user: str, priv: str) -> None:
        if not self.check_dynamic(session, user, priv):
            raise PrivilegeError(
                f"Access denied; you need (at least one of) the {priv} or SUPER "
                f"privilege(s) for this operation"
            )

    def grants_for(self, session, user: str) -> list[str]:
        self._ensure(session)
        rec = self._users.get(user.lower())
        if rec is None:
            raise PrivilegeError(f"There is no such grant defined for user '{user}'")
        out = []
        g = rec["global"]
        if g:
            privs = "ALL PRIVILEGES" if "ALL" in g else ", ".join(sorted(g))
            out.append(f"GRANT {privs} ON *.* TO '{user}'@'%'")
        else:
            out.append(f"GRANT USAGE ON *.* TO '{user}'@'%'")
        for (u, db), privs in sorted(self._db_privs.items()):
            if u == user.lower() and privs:
                ps = "ALL PRIVILEGES" if "ALL" in privs else ", ".join(sorted(privs))
                out.append(f"GRANT {ps} ON `{db}`.* TO '{user}'@'%'")
        for (u, db, tbl), privs in sorted(self._tbl_privs.items()):
            if u == user.lower() and privs:
                ps = "ALL PRIVILEGES" if "ALL" in privs else ", ".join(sorted(privs))
                out.append(f"GRANT {ps} ON `{db}`.`{tbl}` TO '{user}'@'%'")
        dyn = self._dyn_privs.get(user.lower(), set())
        if dyn:
            out.append(f"GRANT {', '.join(sorted(dyn))} ON *.* TO '{user}'@'%'")
        return out

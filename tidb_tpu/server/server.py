"""MySQL-protocol server — the framework's front door
(ref: server/server.go:322 Run, :452 onConn; server/conn.go:912
clientConn.Run, :1112 dispatch, :1634 handleQuery).

One OS thread per connection over a shared Storage; every connection
owns a Session (catalog/vars/txn state). COM_QUERY results stream as
text resultsets; KILL/graceful shutdown drain via the closing flag.
"""

from __future__ import annotations

import logging
import os
import socket
import threading

from ..errors import TiDBError
from ..session import Session
from ..storage.txn import Storage
from . import protocol as p

log = logging.getLogger("tidb_tpu.server")


def _py_to_constant(v):
    """Decoded wire parameter → typed Constant for the planner."""
    from ..expr.expression import Constant
    from ..mysqltypes.datum import Datum
    from ..mysqltypes.field_type import ft_double, ft_longlong, ft_varchar

    if v is None:
        return Constant(Datum.null(), ft_varchar())
    if isinstance(v, bool):
        return Constant(Datum.i(int(v)), ft_longlong())
    if isinstance(v, int):
        return Constant(Datum.i(v), ft_longlong())
    if isinstance(v, float):
        return Constant(Datum.f(v), ft_double())
    if isinstance(v, (bytes, bytearray)):
        from ..mysqltypes.field_type import FieldType, TypeCode

        return Constant(Datum.b(bytes(v)), FieldType(TypeCode.Blob, flen=1 << 16))
    return Constant(Datum.s(str(v)), ft_varchar())


class ClientConn:
    def __init__(self, server: "Server", sock, conn_id: int):
        self.server = server
        self.pkt = p.PacketIO(sock)
        self.conn_id = conn_id
        # one CopClient per server: connections share the tile cache,
        # worker pool, and jit program caches
        self.session = Session(server.storage, cop_client=server.cop)
        self.user = ""
        self.alive = True
        # wire prepared statements: stmt_id → (parsed ast, n_params, long_data)
        self.stmts: dict[int, list] = {}
        # server-side cursors: stmt_id → (pending rows, fts)
        # (ref: conn_stmt.go useCursor + OnFetchReturned)
        self.cursors: dict[int, list] = {}
        self._next_stmt_id = 1

    def _status(self) -> int:
        st = p.SERVER_STATUS_AUTOCOMMIT
        if self.session.in_explicit_txn:
            st |= p.SERVER_STATUS_IN_TRANS
        return st

    # --- lifecycle (ref: clientConn.Run) -----------------------------------

    def handshake(self) -> None:
        salt = os.urandom(20)
        self.pkt.write_packet(p.handshake_v10(self.conn_id, salt))
        self.pkt.flush()  # the client reads this before responding
        resp = p.parse_handshake_response(self.pkt.read_packet())
        self.user = resp["user"]
        # authenticate against the privilege cache (ref: conn.go:246
        # openSessionAndDoAuth + privilege cache mysql_native_password)
        if not self.session.priv.auth(self.session, self.user, salt[:20], resp["auth"]):
            self.pkt.write_packet(
                p.err_packet(1045, f"Access denied for user '{self.user}'@'%'", "28000")
            )
            self.alive = False
            return
        self.session.user = self.user
        if resp["db"]:
            self.session.current_db = resp["db"]
        self.server.storage.plugins.fire("on_connect", self.user, "%")
        self.pkt.write_packet(p.ok_packet())

    def run(self) -> None:
        try:
            self.handshake()
            self.pkt.flush()  # auth verdict (OK/ERR) must reach the client
            while self.alive and not self.server.closing:
                self.pkt.reset_seq()
                self.pkt.max_allowed_packet = int(
                    self.session.vars.get("max_allowed_packet", str(64 << 20))
                )
                try:
                    payload = self.pkt.read_packet()
                except ConnectionError:
                    return
                # execution token (ref: clientConn.Run getToken): bounds
                # how many connections are RUNNING a command at once.
                # Bounded acquire, then proceed tokenless: token holders
                # can BLOCK on another session's locks (hot-row pile-up)
                # while the lock HOLDER's COMMIT — the only command that
                # frees them — queues here; with a small limit that is a
                # priority inversion the reference sidesteps by sizing
                # its limiter at 1000. The timeout turns the inversion
                # into a bounded latency bump instead of a lock-wait-
                # timeout cascade.
                got_token = self.server._tokens.acquire(timeout=1.0)
                try:
                    self.dispatch(payload)
                finally:
                    if got_token:
                        self.server._tokens.release()
                # one sendall per command: the whole response (column
                # defs, rows, EOF) leaves in a single syscall
                self.pkt.flush()
        except Exception:  # noqa: BLE001 — connection thread must not leak exceptions
            log.exception("connection %d aborted", self.conn_id)
        finally:
            # independent teardown steps: one failing must not skip the rest
            try:
                # implicit rollback on disconnect (MySQL semantics). Load-
                # bearing since the PR 13 liveness shield: an open txn's
                # start_ts stays in the active registry, which makes its
                # pessimistic/prewrite locks UNRESOLVABLE by waiters — a
                # dropped connection must deregister, not squat on rows
                # until the leak horizon
                if self.session.txn is not None:
                    self.session.txn.rollback()
                    self.session.txn = None
                    self.session.in_explicit_txn = False
            except Exception:  # noqa: BLE001 — teardown must not raise
                log.exception("txn rollback failed during teardown")
            try:
                self.session.release_table_locks()
            except Exception:  # noqa: BLE001 — teardown must not raise
                log.exception("lock release failed during teardown")
            try:
                self.session.drop_temp_tables()
            except Exception:  # noqa: BLE001
                log.exception("temp-table cleanup failed during teardown")
            self.server.deregister(self.conn_id)
            try:
                self.pkt.sock.close()
            except OSError:
                pass

    # --- command dispatch (ref: conn.go:1112) ------------------------------

    def dispatch(self, payload: bytes) -> None:
        cmd, data = payload[0], payload[1:]
        if cmd == p.COM_QUIT:
            self.alive = False
            return
        if cmd == p.COM_PING:
            self.pkt.write_packet(p.ok_packet(status=self._status()))
            return
        if cmd == p.COM_INIT_DB:
            name = data.decode("utf8", "replace").replace("`", "``")
            return self.handle_query(f"USE `{name}`")
        if cmd == p.COM_QUERY:
            return self.handle_query(data.decode("utf8", "replace"))
        if cmd == p.COM_FIELD_LIST:
            self.pkt.write_packet(p.eof_packet())
            return
        if cmd == p.COM_STMT_PREPARE:
            return self.handle_stmt_prepare(data.decode("utf8", "replace"))
        if cmd == p.COM_STMT_EXECUTE:
            return self.handle_stmt_execute(data)
        if cmd == p.COM_STMT_SEND_LONG_DATA:
            return self.handle_stmt_long_data(data)
        if cmd == p.COM_STMT_CLOSE:
            sid = int.from_bytes(data[:4], "little")
            self.stmts.pop(sid, None)
            self.cursors.pop(sid, None)
            return  # no response by spec
        if cmd == p.COM_STMT_RESET:
            sid = int.from_bytes(data[:4], "little")
            ent = self.stmts.get(sid)
            if ent is not None:
                ent[2].clear()
                self.cursors.pop(sid, None)
            self.pkt.write_packet(p.ok_packet(status=self._status()))
            return
        if cmd == p.COM_STMT_FETCH:
            return self.handle_stmt_fetch(data)
        self.pkt.write_packet(p.err_packet(1047, f"unsupported command {cmd:#x}"))

    # --- binary prepared statements (ref: server/conn_stmt.go) -------------

    def handle_stmt_prepare(self, sql: str) -> None:
        from ..parser import parse_one

        try:
            parsed = parse_one(sql)
        except TiDBError as e:
            self.pkt.write_packet(p.err_packet(1064, str(e), "42000"))
            return
        n_params = Session._count_params(parsed)
        sid = self._next_stmt_id
        self._next_stmt_id += 1
        # [ast, n_params, long_data, bound types, source sql (logs/digest)]
        self.stmts[sid] = [parsed, n_params, {}, None, sql]
        # column count 0: the execute response carries the real resultset
        # header, which every connector reads anyway
        self.pkt.write_packet(p.stmt_prepare_ok(sid, 0, n_params))
        for i in range(n_params):
            from ..mysqltypes.field_type import ft_varchar

            self.pkt.write_packet(p.column_def(f"?{i}", ft_varchar()))
        if n_params:
            self.pkt.write_packet(p.eof_packet(status=self._status()))

    def handle_stmt_execute(self, data: bytes) -> None:
        sid = int.from_bytes(data[:4], "little")
        ent = self.stmts.get(sid)
        if ent is None:
            self.pkt.write_packet(p.err_packet(1243, f"Unknown prepared statement handler ({sid})"))
            return
        use_cursor = len(data) > 4 and bool(data[4] & p.CURSOR_TYPE_READ_ONLY)
        parsed, n_params, long_data, bound_types, src_sql = ent
        import struct as _struct

        try:
            values, types = p.parse_exec_args(data[4:], n_params, long_data, bound_types)
        except (IndexError, ValueError, _struct.error) as e:
            self.pkt.write_packet(p.err_packet(1210, f"Incorrect arguments to EXECUTE: {e}"))
            return
        ent[3] = types  # C clients send types only on the first execute
        long_data.clear()
        params = [_py_to_constant(v) for v in values]
        try:
            rs = self.session.execute_prepared_ast(parsed, params, sql=src_sql)
        except TiDBError as e:
            # real error codes on the wire: clients (and bench_serve)
            # must be able to tell an INDETERMINATE commit (8150 — the
            # fsync-failure shape) from a determinate failure
            self.pkt.write_packet(p.err_packet(getattr(e, "code", 1105) or 1105, str(e)))
            return
        except Exception as e:  # noqa: BLE001 — surface as SQL error, keep conn
            log.exception("stmt execute failed")
            self.pkt.write_packet(p.err_packet(1105, f"internal error: {e}"))
            return
        # MySQL: re-execute implicitly closes any previous cursor
        self.cursors.pop(sid, None)
        if use_cursor and rs.names:
            # cursor mode: column defs now, rows held for COM_STMT_FETCH
            fts = rs.chunk.field_types() if rs.chunk is not None else []
            self.cursors[sid] = [list(rs.rows()), fts]
            self.pkt.write_packet(p.lenc_int(len(rs.names)))
            for name, ft in zip(rs.names, fts):
                self.pkt.write_packet(p.column_def(name, ft))
            self.pkt.write_packet(
                p.eof_packet(status=self._status() | p.SERVER_STATUS_CURSOR_EXISTS)
            )
            return
        self.write_resultset(rs, binary=True)

    def handle_stmt_fetch(self, data: bytes) -> None:
        """COM_STMT_FETCH: stream the next n cursor rows; the final batch
        carries SERVER_STATUS_LAST_ROW_SENT (ref: conn_stmt.go
        handleStmtFetch)."""
        sid = int.from_bytes(data[:4], "little")
        n = int.from_bytes(data[4:8], "little") or 1
        cur = self.cursors.get(sid)
        if cur is None:
            self.pkt.write_packet(p.err_packet(1243, f"statement {sid} has no open cursor"))
            return
        rows, fts = cur
        batch, cur[0] = rows[:n], rows[n:]
        for row in batch:
            self.pkt.write_packet(p.binary_row(list(row), fts))
        status = self._status() | p.SERVER_STATUS_CURSOR_EXISTS
        if not cur[0]:
            status |= p.SERVER_STATUS_LAST_ROW_SENT
            del self.cursors[sid]
        self.pkt.write_packet(p.eof_packet(status=status))

    def handle_stmt_long_data(self, data: bytes) -> None:
        """COM_STMT_SEND_LONG_DATA: append chunk to a param buffer; no
        response (ref: conn_stmt.go handleStmtSendLongData)."""
        sid = int.from_bytes(data[:4], "little")
        param = int.from_bytes(data[4:6], "little")
        ent = self.stmts.get(sid)
        if ent is not None:
            ent[2].setdefault(param, bytearray()).extend(data[6:])

    def handle_query(self, sql: str) -> None:
        """COM_QUERY → execute → OK or text resultset
        (ref: conn.go:1634 handleQuery, writeChunks)."""
        try:
            rs = self.session.execute(sql)
        except TiDBError as e:
            # carry the statement's real error code (an indeterminate
            # commit must reach the client as 8150, not generic 1105)
            self.pkt.write_packet(p.err_packet(getattr(e, "code", 1105) or 1105, str(e)))
            return
        except Exception as e:  # noqa: BLE001 — surface as SQL error, keep conn
            log.exception("query failed: %s", sql)
            self.pkt.write_packet(p.err_packet(1105, f"internal error: {e}"))
            return
        self.write_resultset(rs)

    def write_resultset(self, rs, binary: bool = False) -> None:
        if not rs.names:
            self.pkt.write_packet(p.ok_packet(rs.affected, rs.last_insert_id, status=self._status()))
            return
        fts = rs.chunk.field_types() if rs.chunk is not None else []
        self.pkt.write_packet(p.lenc_int(len(rs.names)))
        for name, ft in zip(rs.names, fts):
            self.pkt.write_packet(p.column_def(name, ft))
        self.pkt.write_packet(p.eof_packet(status=self._status()))
        for row in rs.rows():
            if binary:
                self.pkt.write_packet(p.binary_row(list(row), fts))
            else:
                self.pkt.write_packet(p.text_row(list(row)))
        self.pkt.write_packet(p.eof_packet(status=self._status()))


class Server:
    """Socket accept loop (ref: server/server.go Run/onConn)."""

    def __init__(self, storage: Storage | None = None, host: str = "127.0.0.1", port: int = 4000,
                 status_port: int | None = None, token_limit: int | None = None):
        self.storage = storage or Storage()
        from ..copr.client import CopClient

        self.cop = CopClient(self.storage)  # shared across connections
        # execution token limiter (ref: server.go getToken/returnToken —
        # the reference caps concurrently EXECUTING sessions so a
        # thousand connections don't become a thousand runnable
        # threads): each command acquires a token for its execution
        # only; parked connections wait on the semaphore, cheap for the
        # scheduler, instead of thrashing the interpreter. Sized to a
        # small multiple of the cores — bench_serve measured 32
        # unthrottled executing threads on 2 cores costing ~35% QPS vs
        # a 4-8 token window.
        if token_limit is None:
            token_limit = max(8, 4 * (os.cpu_count() or 2))
        self.token_limit = token_limit
        self._tokens = threading.Semaphore(token_limit)
        self.host = host
        self.port = port
        self.status_port = status_port
        self._status_httpd = None
        self.closing = False
        self._sock: socket.socket | None = None
        self._conns: dict[int, ClientConn] = {}
        self._next_id = 1
        self._lock = threading.Lock()

    def start(self) -> int:
        """Bind + spawn the accept loop; returns the bound port."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        threading.Thread(target=self._accept_loop, name="mysql-accept", daemon=True).start()
        if self.status_port is not None:
            self._start_status_server()
        log.info("listening on %s:%d", self.host, self.port)
        return self.port

    def _start_status_server(self) -> None:
        """HTTP status/debug API: /status and /metrics
        (ref: server/http_status.go:111-163)."""
        import json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: D102 — quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    from ..utils.metrics import REGISTRY

                    body = REGISTRY.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/debug/trace" or self.path.startswith("/debug/trace?"):
                    # last-N statement traces (utils/tracing TraceRing):
                    # the span trees TRACE <sql> renders, as JSON — the
                    # status-API half of the reference's trace viewer
                    body = json.dumps(server.storage.trace_ring.snapshot()).encode()
                    ctype = "application/json"
                elif self.path == "/debug/timeline" or self.path.startswith("/debug/timeline?"):
                    # device timeline (utils/timeline TimelineRing) in
                    # Chrome trace-event JSON — save and open in Perfetto
                    # (ui.perfetto.dev) or chrome://tracing
                    body = json.dumps(server.storage.timeline.chrome_trace()).encode()
                    ctype = "application/json"
                elif self.path == "/debug/fleet" or self.path.startswith("/debug/fleet?"):
                    # replica-fleet topology: per-link ship state plus the
                    # bounded status fan-out (detail=False — the bulky
                    # metrics/statements payloads stay on the CLUSTER_*
                    # memtables; dead members show as {"name", "error"})
                    sh = getattr(server.storage, "_shipper", None)
                    body = json.dumps({
                        "role": "standby" if server.storage.standby else "primary",
                        "links": sh.link_states() if sh is not None else [],
                        "members": (sh.fleet_statuses(detail=False)
                                    if sh is not None else []),
                    }).encode()
                    ctype = "application/json"
                elif self.path.startswith("/stats/dump/"):
                    # /stats/dump/{db}/{table} (ref: statistics_handler.go)
                    parts = self.path.split("/")
                    if len(parts) != 5:
                        self.send_response(400)
                        self.end_headers()
                        return
                    from ..errors import TiDBError
                    from ..session import Session as _S

                    try:
                        sess = _S(server.storage)
                        info = sess.infoschema().table(parts[3], parts[4])
                    except TiDBError:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(b"unknown table")
                        return
                    try:
                        d = server.storage.stats.dump(sess, info)
                    except Exception:  # noqa: BLE001 — HTTP surface
                        log.exception("stats dump failed")
                        self.send_response(500)
                        self.end_headers()
                        return
                    if d is None:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(b"no statistics; run ANALYZE first")
                        return
                    body = json.dumps(d).encode()
                    ctype = "application/json"
                elif self.path == "/status":
                    with server._lock:
                        conns = len(server._conns)
                    body = json.dumps(
                        {"connections": conns, "version": "8.0.11-tidb-tpu", "git_hash": "tpu-native"}
                    ).encode()
                    ctype = "application/json"
                elif self.path == "/schema" or self.path.startswith("/schema/"):
                    # /schema[/{db}[/{table}]] (ref: http_status.go /schema)
                    from ..session import Session as _S

                    sess = _S(server.storage)
                    is_ = sess.infoschema()
                    parts = [p for p in self.path.split("/") if p][1:]
                    try:
                        if not parts:
                            out = sorted({t.db_name for t in is_.tables.values()})
                        elif len(parts) == 1:
                            out = sorted(
                                t.name for t in is_.tables.values() if t.db_name == parts[0]
                            )
                        else:
                            info = is_.table(parts[0], parts[1])
                            out = info.to_json()
                    except Exception:  # noqa: BLE001 — HTTP surface
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps(out).encode()
                    ctype = "application/json"
                elif self.path == "/regions":
                    regs = [
                        {
                            "region_id": r.id,
                            "start_key": r.start.hex(),
                            "end_key": r.end.hex(),
                            "epoch": r.epoch,
                        }
                        for r in list(server.storage.regions.regions)
                    ]
                    body = json.dumps(regs).encode()
                    ctype = "application/json"
                elif self.path.startswith("/mvcc/key/"):
                    # /mvcc/key/{db}/{table}/{handle} (ref: http_status.go /mvcc)
                    parts = [p for p in self.path.split("/") if p]
                    if len(parts) != 5:
                        self.send_response(400)
                        self.end_headers()
                        return
                    from ..codec import tablecodec
                    from ..session import Session as _S

                    try:
                        sess = _S(server.storage)
                        info = sess.infoschema().table(parts[2], parts[3])
                        key = tablecodec.record_key(info.id, int(parts[4]))
                        vers = server.storage.mvcc_versions(key)
                    except Exception:  # noqa: BLE001 — HTTP surface
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps({
                        "key": key.hex(),
                        "versions": [
                            {"start_ts": s_ts, "commit_ts": c_ts, "short_value_len": ln}
                            for s_ts, c_ts, ln in vers
                        ],
                    }).encode()
                    ctype = "application/json"
                elif self.path == "/settings":
                    from ..session.vars import DEFAULT_VARS

                    body = json.dumps(dict(sorted(DEFAULT_VARS.items()))).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._status_httpd = ThreadingHTTPServer((self.host, self.status_port), Handler)
        self.status_port = self._status_httpd.server_address[1]
        threading.Thread(target=self._status_httpd.serve_forever, name="http-status", daemon=True).start()

    def _accept_loop(self) -> None:
        while not self.closing:
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return  # socket closed during shutdown
            try:
                # interactive point queries: a delayed small response is
                # pure p99 (Nagle vs delayed-ACK); responses already
                # coalesce into one send via the buffered PacketIO
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = ClientConn(self, sock, 0)
            # the wire-visible id IS the session id: KILL <id> from any
            # client resolves against the same process registry
            conn.conn_id = conn.session.conn_id
            with self._lock:
                self._conns[conn.conn_id] = conn
            threading.Thread(target=conn.run, name=f"conn-{conn.conn_id}", daemon=True).start()

    def deregister(self, conn_id: int) -> None:
        with self._lock:
            self._conns.pop(conn_id, None)

    def kill(self, conn_id: int) -> bool:
        """KILL <id> (ref: server.go:609 Kill)."""
        with self._lock:
            conn = self._conns.get(conn_id)
        if conn is None:
            return False
        conn.alive = False
        try:
            conn.pkt.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        return True

    def close(self) -> None:
        """Graceful shutdown: stop accepting, drop connections
        (ref: server.go:409 startShutdown)."""
        self.closing = True
        if self._status_httpd is not None:
            self._status_httpd.shutdown()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.pkt.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

"""MySQL client/server wire protocol — packet codec
(ref: server/packetio.go, server/util.go dumpTextRow, server/column.go;
protocol spec mirrored from the reference's implementation behavior).

Covers the v10 handshake, CLIENT_PROTOCOL_41 status/err packets,
length-encoded integers/strings, column definitions and text resultset
rows — the surface a stock `mysql` CLI or connector needs for COM_QUERY.
"""

from __future__ import annotations

import struct

from ..mysqltypes.field_type import FieldType, TypeCode

PROTOCOL_VERSION = 10
SERVER_VERSION = b"8.0.11-tidb-tpu"

# capability flags (subset)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_FOUND_ROWS = 0x2
CLIENT_LONG_FLAG = 0x4
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_DEPRECATE_EOF = 0x1000000

SERVER_CAPABILITIES = (
    CLIENT_LONG_PASSWORD
    | CLIENT_FOUND_ROWS
    | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB
    | CLIENT_PROTOCOL_41
    | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION
    | CLIENT_PLUGIN_AUTH
)

SERVER_STATUS_IN_TRANS = 0x1
SERVER_STATUS_AUTOCOMMIT = 0x2
SERVER_STATUS_CURSOR_EXISTS = 0x40
SERVER_STATUS_LAST_ROW_SENT = 0x80

CURSOR_TYPE_READ_ONLY = 0x1

# commands (ref: dispatch, server/conn.go:1112)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A
COM_STMT_FETCH = 0x1C

# MySQL column types
MYSQL_TYPE = {
    TypeCode.Tiny: 1,
    TypeCode.Short: 2,
    TypeCode.Long: 3,
    TypeCode.Float: 4,
    TypeCode.Double: 5,
    TypeCode.Null: 6,
    TypeCode.Timestamp: 7,
    TypeCode.Longlong: 8,
    TypeCode.Int24: 9,
    TypeCode.Date: 10,
    TypeCode.Duration: 11,
    TypeCode.Datetime: 12,
    TypeCode.Year: 13,
    TypeCode.NewDecimal: 246,
    TypeCode.Blob: 252,
    TypeCode.Varchar: 253,
    TypeCode.String: 254,
}

CHARSET_UTF8MB4 = 255


def lenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < (1 << 16):
        return b"\xfc" + struct.pack("<H", n)
    if n < (1 << 24):
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenc_str(b: bytes) -> bytes:
    return lenc_int(len(b)) + b


def read_lenc_int(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return struct.unpack("<I", buf[pos + 1 : pos + 4] + b"\x00")[0], pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


class PacketIO:
    """4-byte-header packet framing over a socket (ref: packetio.go).

    Writes are BUFFERED: `write_packet` frames into an in-memory buffer
    and `flush()` ships the whole response in one `sendall`. A point
    select's response is five MySQL packets — five separate `send(2)`
    calls used to mean five syscalls and, with Nagle + delayed ACK, tens
    of milliseconds of tail latency per statement; one writev-sized send
    is the classic front-door fix (the reference buffers through
    bufio.Writer and flushes per command the same way). Flushing happens
    per dispatched command (server.py) and at the handshake; the buffer
    also flushes itself beyond _AUTOFLUSH bytes so huge resultsets don't
    balloon memory."""

    _AUTOFLUSH = 1 << 18  # 256KB: cap buffered resultset bytes

    def __init__(self, sock):
        self.sock = sock
        self.seq = 0
        self.max_allowed_packet = 64 << 20  # max_allowed_packet sysvar
        self._wbuf: list[bytes] = []
        self._wbuf_n = 0

    def read_packet(self) -> bytes:
        out = b""
        while True:
            header = self._read_n(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) % 256
            out += self._read_n(length)
            if len(out) > self.max_allowed_packet:
                # ER_NET_PACKET_TOO_LARGE (ref: packetio.go readPacket
                # enforcing the max_allowed_packet limit)
                raise ConnectionError(
                    f"packet for query is too large ({len(out)} > {self.max_allowed_packet})"
                )
            if length < 0xFFFFFF:
                return out  # a full-size frame implies a continuation

    def _read_n(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("client closed connection")
            out += chunk
        return out

    def write_packet(self, payload: bytes) -> None:
        while True:
            chunk = payload[:0xFFFFFF]
            payload = payload[0xFFFFFF:]
            self._wbuf.append(struct.pack("<I", len(chunk))[:3] + bytes([self.seq]) + chunk)
            self._wbuf_n += 4 + len(chunk)
            self.seq = (self.seq + 1) % 256
            if len(chunk) < 0xFFFFFF:
                break  # a full-size chunk demands a (possibly empty) follow-up
        if self._wbuf_n >= self._AUTOFLUSH:
            self.flush()

    def flush(self) -> None:
        if not self._wbuf:
            return
        out = b"".join(self._wbuf)
        self._wbuf.clear()
        self._wbuf_n = 0
        self.sock.sendall(out)

    def reset_seq(self) -> None:
        self.seq = 0


def handshake_v10(conn_id: int, salt: bytes) -> bytes:
    """Initial handshake packet (ref: conn.go writeInitialHandshake)."""
    out = bytes([PROTOCOL_VERSION]) + SERVER_VERSION + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", SERVER_CAPABILITIES & 0xFFFF)
    out += bytes([CHARSET_UTF8MB4])
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (SERVER_CAPABILITIES >> 16) & 0xFFFF)
    out += bytes([21])  # auth plugin data length
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return out


def parse_handshake_response(payload: bytes) -> dict:
    """Client handshake response 41 → {capabilities, user, db, auth}."""
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    end = payload.index(b"\x00", pos)
    user = payload[pos:end].decode("utf8", "replace")
    pos = end + 1
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1 : pos + 1 + alen]
        pos += 1 + alen
    else:
        end = payload.index(b"\x00", pos)
        auth = payload[pos:end]
        pos = end + 1
    db = ""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        end = payload.find(b"\x00", pos)
        if end != -1:
            db = payload[pos:end].decode("utf8", "replace")
    return {"capabilities": caps, "user": user, "db": db, "auth": auth}


def ok_packet(affected: int = 0, last_insert_id: int = 0, status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\x00" + lenc_int(affected) + lenc_int(last_insert_id) + struct.pack("<HH", status, warnings)


def eof_packet(status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def err_packet(errno: int, message: str, sqlstate: str = "HY000") -> bytes:
    return b"\xff" + struct.pack("<H", errno) + b"#" + sqlstate.encode() + message.encode("utf8", "replace")


def column_def(name: str, ft: FieldType) -> bytes:
    """Column definition 41 (ref: server/column.go Dump)."""
    mtype = MYSQL_TYPE.get(ft.tp, 253)
    charset = CHARSET_UTF8MB4 if ft.is_string() else 63  # 63 = binary
    flen = ft.flen if ft.flen > 0 else 255
    out = lenc_str(b"def")  # catalog
    out += lenc_str(b"")  # schema
    out += lenc_str(b"")  # table
    out += lenc_str(b"")  # org_table
    out += lenc_str(name.encode("utf8", "replace"))
    out += lenc_str(b"")  # org_name
    out += bytes([0x0C])  # fixed fields length
    out += struct.pack("<H", charset)
    out += struct.pack("<I", flen)
    out += bytes([mtype])
    out += struct.pack("<H", 0)  # flags
    out += bytes([max(ft.decimal, 0) if ft.decimal is not None and ft.decimal >= 0 else 0])
    out += b"\x00\x00"
    return out


def text_row(values: list[str | None]) -> bytes:
    out = b""
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            out += lenc_str(v.encode("utf8", "replace"))
    return out


# --- binary protocol (COM_STMT_*; ref: server/conn_stmt.go, util.go
# dumpBinaryRow / parseExecArgs) -------------------------------------------

def stmt_prepare_ok(stmt_id: int, num_cols: int, num_params: int) -> bytes:
    return (
        b"\x00"
        + struct.pack("<I", stmt_id)
        + struct.pack("<H", num_cols)
        + struct.pack("<H", num_params)
        + b"\x00"
        + struct.pack("<H", 0)  # warnings
    )


def _encode_binary_datetime(s: str) -> bytes:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' → binary date/datetime value."""
    date, _, clock = s.partition(" ")
    y, mo, d = (int(x) for x in date.split("-"))
    if not clock:
        return bytes([4]) + struct.pack("<HBB", y, mo, d)
    hms, _, frac = clock.partition(".")
    h, mi, sec = (int(x) for x in hms.split(":"))
    if frac:
        micro = int(frac.ljust(6, "0")[:6])
        return bytes([11]) + struct.pack("<HBBBBBI", y, mo, d, h, mi, sec, micro)
    return bytes([7]) + struct.pack("<HBBBBB", y, mo, d, h, mi, sec)


def _encode_binary_duration(s: str) -> bytes:
    """'[-]HHH:MM:SS[.ffffff]' → binary TIME value."""
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    hms, _, frac = s.partition(".")
    h, mi, sec = (int(x) for x in hms.split(":"))
    days, h = divmod(h, 24)
    if frac:
        micro = int(frac.ljust(6, "0")[:6])
        return bytes([12, 1 if neg else 0]) + struct.pack("<IBBBI", days, h, mi, sec, micro)
    return bytes([8, 1 if neg else 0]) + struct.pack("<IBBB", days, h, mi, sec)


_INT_SIZES = {1: "<b", 2: "<h", 3: "<i", 8: "<q", 9: "<i", 13: "<H"}
_UINT_SIZES = {1: "<B", 2: "<H", 3: "<I", 8: "<Q", 9: "<I", 13: "<H"}


def binary_row(values: list[str | None], fts: list[FieldType]) -> bytes:
    """One binary-protocol resultset row from display values + types
    (ref: util.go dumpBinaryRow). Ints/floats are fixed-width, temporal
    types use the packed binary layouts, the rest are length-encoded."""
    n = len(values)
    null_bitmap = bytearray((n + 7 + 2) // 8)
    body = b""
    for i, (v, ft) in enumerate(zip(values, fts)):
        if v is None:
            pos = i + 2  # binary-row null bitmap has a 2-bit offset
            null_bitmap[pos // 8] |= 1 << (pos % 8)
            continue
        mtype = MYSQL_TYPE.get(ft.tp, 253)
        if mtype in _INT_SIZES:
            fmt = _UINT_SIZES[mtype] if ft.is_unsigned else _INT_SIZES[mtype]
            body += struct.pack(fmt, int(v))
        elif mtype == 4:
            body += struct.pack("<f", float(v))
        elif mtype == 5:
            body += struct.pack("<d", float(v))
        elif mtype in (7, 10, 12):  # timestamp/date/datetime
            body += _encode_binary_datetime(v)
        elif mtype == 11:  # time
            body += _encode_binary_duration(v)
        else:  # decimals, strings, blobs, json → length-encoded
            body += lenc_str(v.encode("utf8", "replace"))
    return b"\x00" + bytes(null_bitmap) + body


def parse_exec_args(data: bytes, n_params: int, long_data: dict | None = None,
                    prev_types: list | None = None):
    """COM_STMT_EXECUTE payload after stmt_id → (values, types).

    Returns python values (None/int/float/str/bytes) for each parameter
    (ref: conn_stmt.go parseExecArgs). `long_data` holds accumulated
    COM_STMT_SEND_LONG_DATA buffers keyed by param index. `prev_types`
    are the types bound by an earlier execute — the C clients send types
    only once (new-params-bound-flag=0 afterwards); the caller persists
    the returned types and passes them back."""
    pos = 0
    flags = data[pos]; pos += 1  # noqa: E702 — cursor flags unused (no cursors)
    pos += 4  # iteration count, always 1
    if n_params == 0:
        return [], None
    nb_len = (n_params + 7) // 8
    null_bitmap = data[pos : pos + nb_len]
    pos += nb_len
    new_params_bound = data[pos]; pos += 1  # noqa: E702
    types = prev_types
    if not new_params_bound and types is None and any(
        not (null_bitmap[i // 8] & (1 << (i % 8))) for i in range(n_params)
    ):
        # MySQL rejects this: value bytes are unparseable without types
        raise ValueError("parameter types were never bound for this statement")
    if new_params_bound:
        types = []
        for _ in range(n_params):
            t, flag = data[pos], data[pos + 1]
            types.append((t, bool(flag & 0x80)))
            pos += 2
    values = []
    long_data = long_data or {}
    for i in range(n_params):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            values.append(None)
            continue
        if i in long_data:
            values.append(bytes(long_data[i]))
            continue
        t, unsigned = types[i] if types else (0xFE, False)
        if t == 6:  # NULL type
            values.append(None)
        elif t in (1, 2, 3, 8, 9):
            size = {1: 1, 2: 2, 3: 4, 8: 8, 9: 4}[t]
            raw = data[pos : pos + size]
            pos += size
            values.append(int.from_bytes(raw, "little", signed=not unsigned))
        elif t == 4:
            values.append(struct.unpack_from("<f", data, pos)[0]); pos += 4  # noqa: E702
        elif t == 5:
            values.append(struct.unpack_from("<d", data, pos)[0]); pos += 8  # noqa: E702
        elif t in (7, 10, 12, 14):  # binary date/datetime/timestamp
            ln = data[pos]; pos += 1  # noqa: E702
            raw = data[pos : pos + ln]; pos += ln  # noqa: E702
            values.append(_decode_binary_datetime(raw))
        elif t == 11:  # binary time
            ln = data[pos]; pos += 1  # noqa: E702
            raw = data[pos : pos + ln]; pos += ln  # noqa: E702
            values.append(_decode_binary_duration(raw))
        else:  # varchar/string/blob/decimal/json → length-encoded bytes
            n, pos = read_lenc_int(data, pos)
            raw = data[pos : pos + n]
            pos += n
            # blob family stays bytes — lossy utf8 decode would corrupt
            # binary payloads (TINY/MEDIUM/LONG_BLOB/BLOB = 0xF9-0xFC)
            values.append(bytes(raw) if 0xF9 <= t <= 0xFC else raw.decode("utf8", "replace"))
    return values, types


def _decode_binary_datetime(raw: bytes) -> str:
    if len(raw) == 0:
        return "0000-00-00 00:00:00"
    y, mo, d = struct.unpack_from("<HBB", raw, 0)
    if len(raw) == 4:
        return f"{y:04d}-{mo:02d}-{d:02d}"
    h, mi, s = struct.unpack_from("<BBB", raw, 4)
    if len(raw) == 7:
        return f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
    micro = struct.unpack_from("<I", raw, 7)[0]
    return f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}.{micro:06d}"


def _decode_binary_duration(raw: bytes) -> str:
    if len(raw) == 0:
        return "00:00:00"
    neg = raw[0] == 1
    days, h, mi, s = struct.unpack_from("<IBBB", raw, 1)
    total_h = days * 24 + h
    out = f"{total_h:02d}:{mi:02d}:{s:02d}"
    if len(raw) == 12:
        micro = struct.unpack_from("<I", raw, 8)[0]
        out += f".{micro:06d}"
    return ("-" if neg else "") + out

"""MySQL client/server wire protocol — packet codec
(ref: server/packetio.go, server/util.go dumpTextRow, server/column.go;
protocol spec mirrored from the reference's implementation behavior).

Covers the v10 handshake, CLIENT_PROTOCOL_41 status/err packets,
length-encoded integers/strings, column definitions and text resultset
rows — the surface a stock `mysql` CLI or connector needs for COM_QUERY.
"""

from __future__ import annotations

import struct

from ..mysqltypes.field_type import FieldType, TypeCode

PROTOCOL_VERSION = 10
SERVER_VERSION = b"8.0.11-tidb-tpu"

# capability flags (subset)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_FOUND_ROWS = 0x2
CLIENT_LONG_FLAG = 0x4
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_DEPRECATE_EOF = 0x1000000

SERVER_CAPABILITIES = (
    CLIENT_LONG_PASSWORD
    | CLIENT_FOUND_ROWS
    | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB
    | CLIENT_PROTOCOL_41
    | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION
    | CLIENT_PLUGIN_AUTH
)

SERVER_STATUS_IN_TRANS = 0x1
SERVER_STATUS_AUTOCOMMIT = 0x2

# commands (ref: dispatch, server/conn.go:1112)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E

# MySQL column types
MYSQL_TYPE = {
    TypeCode.Tiny: 1,
    TypeCode.Short: 2,
    TypeCode.Long: 3,
    TypeCode.Float: 4,
    TypeCode.Double: 5,
    TypeCode.Null: 6,
    TypeCode.Timestamp: 7,
    TypeCode.Longlong: 8,
    TypeCode.Int24: 9,
    TypeCode.Date: 10,
    TypeCode.Duration: 11,
    TypeCode.Datetime: 12,
    TypeCode.Year: 13,
    TypeCode.NewDecimal: 246,
    TypeCode.Blob: 252,
    TypeCode.Varchar: 253,
    TypeCode.String: 254,
}

CHARSET_UTF8MB4 = 255


def lenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < (1 << 16):
        return b"\xfc" + struct.pack("<H", n)
    if n < (1 << 24):
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenc_str(b: bytes) -> bytes:
    return lenc_int(len(b)) + b


def read_lenc_int(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return struct.unpack("<I", buf[pos + 1 : pos + 4] + b"\x00")[0], pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


class PacketIO:
    """4-byte-header packet framing over a socket (ref: packetio.go)."""

    def __init__(self, sock):
        self.sock = sock
        self.seq = 0

    def read_packet(self) -> bytes:
        out = b""
        while True:
            header = self._read_n(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) % 256
            out += self._read_n(length)
            if length < 0xFFFFFF:
                return out  # a full-size frame implies a continuation

    def _read_n(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("client closed connection")
            out += chunk
        return out

    def write_packet(self, payload: bytes) -> None:
        out = b""
        while True:
            chunk = payload[:0xFFFFFF]
            payload = payload[0xFFFFFF:]
            out += struct.pack("<I", len(chunk))[:3] + bytes([self.seq]) + chunk
            self.seq = (self.seq + 1) % 256
            if len(chunk) < 0xFFFFFF:
                break  # a full-size chunk demands a (possibly empty) follow-up
        self.sock.sendall(out)

    def reset_seq(self) -> None:
        self.seq = 0


def handshake_v10(conn_id: int, salt: bytes) -> bytes:
    """Initial handshake packet (ref: conn.go writeInitialHandshake)."""
    out = bytes([PROTOCOL_VERSION]) + SERVER_VERSION + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", SERVER_CAPABILITIES & 0xFFFF)
    out += bytes([CHARSET_UTF8MB4])
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (SERVER_CAPABILITIES >> 16) & 0xFFFF)
    out += bytes([21])  # auth plugin data length
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return out


def parse_handshake_response(payload: bytes) -> dict:
    """Client handshake response 41 → {capabilities, user, db, auth}."""
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    end = payload.index(b"\x00", pos)
    user = payload[pos:end].decode("utf8", "replace")
    pos = end + 1
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1 : pos + 1 + alen]
        pos += 1 + alen
    else:
        end = payload.index(b"\x00", pos)
        auth = payload[pos:end]
        pos = end + 1
    db = ""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        end = payload.find(b"\x00", pos)
        if end != -1:
            db = payload[pos:end].decode("utf8", "replace")
    return {"capabilities": caps, "user": user, "db": db, "auth": auth}


def ok_packet(affected: int = 0, last_insert_id: int = 0, status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\x00" + lenc_int(affected) + lenc_int(last_insert_id) + struct.pack("<HH", status, warnings)


def eof_packet(status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def err_packet(errno: int, message: str, sqlstate: str = "HY000") -> bytes:
    return b"\xff" + struct.pack("<H", errno) + b"#" + sqlstate.encode() + message.encode("utf8", "replace")


def column_def(name: str, ft: FieldType) -> bytes:
    """Column definition 41 (ref: server/column.go Dump)."""
    mtype = MYSQL_TYPE.get(ft.tp, 253)
    charset = CHARSET_UTF8MB4 if ft.is_string() else 63  # 63 = binary
    flen = ft.flen if ft.flen > 0 else 255
    out = lenc_str(b"def")  # catalog
    out += lenc_str(b"")  # schema
    out += lenc_str(b"")  # table
    out += lenc_str(b"")  # org_table
    out += lenc_str(name.encode("utf8", "replace"))
    out += lenc_str(b"")  # org_name
    out += bytes([0x0C])  # fixed fields length
    out += struct.pack("<H", charset)
    out += struct.pack("<I", flen)
    out += bytes([mtype])
    out += struct.pack("<H", 0)  # flags
    out += bytes([max(ft.decimal, 0) if ft.decimal is not None and ft.decimal >= 0 else 0])
    out += b"\x00\x00"
    return out


def text_row(values: list[str | None]) -> bytes:
    out = b""
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            out += lenc_str(v.encode("utf8", "replace"))
    return out

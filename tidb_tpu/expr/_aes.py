"""Pure-Python AES-128 block cipher — fallback for AES_ENCRYPT/DECRYPT
when the optional `cryptography` package is absent (MySQL's default
aes-128-ecb mode only needs the raw block transform; padding and key
folding live in builtins_ext2). Verified against the FIPS-197 appendix C
vector at import time, so a transcription slip can never silently
corrupt user data."""

from __future__ import annotations

_SBOX = bytes.fromhex(
    "637c777bf26b6fc53001672bfed7ab76ca82c97dfa5947f0add4a2af9ca472c0"
    "b7fd9326363ff7cc34a5e5f171d8311504c723c31896059a071280e2eb27b275"
    "09832c1a1b6e5aa0523bd6b329e32f8453d100ed20fcb15b6acbbe394a4c58cf"
    "d0efaafb434d338545f9027f503c9fa851a3408f929d38f5bcb6da2110fff3d2"
    "cd0c13ec5f974417c4a77e3d645d197360814fdc222a908846eeb814de5e0bdb"
    "e0323a0a4906245cc2d3ac629195e479e7c8376d8dd54ea96c56f4ea657aae08"
    "ba78252e1ca6b4c6e8dd741f4bbd8b8a703eb5664803f60e613557b986c11d9e"
    "e1f8981169d98e949b1e87e9ce5528df8ca1890dbfe6426841992d0fb054bb16"
)
_INV_SBOX = bytearray(256)
for _i, _v in enumerate(_SBOX):
    _INV_SBOX[_v] = _i
_INV_SBOX = bytes(_INV_SBOX)

_RCON = (0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36)


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x1B) & 0xFF if a & 0x100 else a


def _mul(a: int, b: int) -> int:
    out = 0
    while b:
        if b & 1:
            out ^= a
        a = _xtime(a)
        b >>= 1
    return out


def expand_key(key: bytes) -> list[bytes]:
    """128-bit key schedule → 11 round keys of 16 bytes."""
    words = [key[i : i + 4] for i in range(0, 16, 4)]
    for r in range(10):
        w = words[-1]
        w = bytes(
            (_SBOX[w[1]] ^ _RCON[r], _SBOX[w[2]], _SBOX[w[3]], _SBOX[w[0]])
        )
        for j in range(4):
            w = bytes(x ^ y for x, y in zip(words[-4], w))
            words.append(w)
            if j < 3:
                w = words[-1]
    return [b"".join(words[i : i + 4]) for i in range(0, 44, 4)]


def _add_round_key(s: bytearray, rk: bytes) -> None:
    for i in range(16):
        s[i] ^= rk[i]


_SHIFT = [0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11]
_INV_SHIFT = [0, 13, 10, 7, 4, 1, 14, 11, 8, 5, 2, 15, 12, 9, 6, 3]


def encrypt_block(block: bytes, round_keys: list[bytes]) -> bytes:
    s = bytearray(block)
    _add_round_key(s, round_keys[0])
    for rnd in range(1, 11):
        s = bytearray(_SBOX[s[_SHIFT[i]]] for i in range(16))  # sub+shift
        if rnd < 10:
            t = bytearray(16)
            for c in range(0, 16, 4):
                a0, a1, a2, a3 = s[c : c + 4]
                t[c] = _xtime(a0) ^ _xtime(a1) ^ a1 ^ a2 ^ a3
                t[c + 1] = a0 ^ _xtime(a1) ^ _xtime(a2) ^ a2 ^ a3
                t[c + 2] = a0 ^ a1 ^ _xtime(a2) ^ _xtime(a3) ^ a3
                t[c + 3] = _xtime(a0) ^ a0 ^ a1 ^ a2 ^ _xtime(a3)
            s = t
        _add_round_key(s, round_keys[rnd])
    return bytes(s)


def decrypt_block(block: bytes, round_keys: list[bytes]) -> bytes:
    s = bytearray(block)
    _add_round_key(s, round_keys[10])
    for rnd in range(9, -1, -1):
        s = bytearray(_INV_SBOX[s[_INV_SHIFT[i]]] for i in range(16))
        _add_round_key(s, round_keys[rnd])
        if rnd > 0:
            t = bytearray(16)
            for c in range(0, 16, 4):
                a0, a1, a2, a3 = s[c : c + 4]
                t[c] = _mul(a0, 14) ^ _mul(a1, 11) ^ _mul(a2, 13) ^ _mul(a3, 9)
                t[c + 1] = _mul(a0, 9) ^ _mul(a1, 14) ^ _mul(a2, 11) ^ _mul(a3, 13)
                t[c + 2] = _mul(a0, 13) ^ _mul(a1, 9) ^ _mul(a2, 14) ^ _mul(a3, 11)
                t[c + 3] = _mul(a0, 11) ^ _mul(a1, 13) ^ _mul(a2, 9) ^ _mul(a3, 14)
            s = t
    return bytes(s)


def ecb_encrypt(data: bytes, key: bytes) -> bytes:
    rks = expand_key(key)
    return b"".join(
        encrypt_block(data[i : i + 16], rks) for i in range(0, len(data), 16)
    )


def ecb_decrypt(data: bytes, key: bytes) -> bytes:
    rks = expand_key(key)
    return b"".join(
        decrypt_block(data[i : i + 16], rks) for i in range(0, len(data), 16)
    )


# FIPS-197 appendix C.1 known-answer self-check
_K = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
_P = bytes.fromhex("00112233445566778899aabbccddeeff")
_C = bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")
assert ecb_encrypt(_P, _K) == _C and ecb_decrypt(_C, _K) == _P, (
    "AES self-check failed"
)
del _K, _P, _C

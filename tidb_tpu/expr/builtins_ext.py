"""Builtin registry extension — date arithmetic, string/math breadth,
JSON functions, duration support (ref: expression/builtin_time.go,
builtin_string.go, builtin_math.go, builtin_json.go; same one-kernel
architecture as builtins.py). Imported by builtins.py at the end."""

from __future__ import annotations

import datetime as _dt
import json as _json
import math

import numpy as np

from ..mysqltypes import coretime as _ct
from ..mysqltypes.field_type import FieldType, TypeCode, ft_double, ft_longlong, ft_varchar
from .builtins import _as_str, _obj_map, infer_first
from .expression import lane_as_float
from .expression import FuncSig, register

_US = 1_000_000


def _ft_json() -> FieldType:
    return FieldType(TypeCode.JSON, flen=-1)


# ---------------------------------------------------------------------------
# date/time breadth
# ---------------------------------------------------------------------------


def _packed_lane(d, v, ft):
    """Datetime lane → (int64 packed, valid), parsing string lanes/consts
    per row (host path; device kernels only ever see typed int lanes)."""
    dd = np.asarray(d).reshape(-1)
    valid = np.asarray(v).reshape(-1)
    if dd.dtype == object or (ft is not None and ft.is_string()):
        out = np.zeros(len(dd), np.int64)
        valid = valid.copy()
        for i in np.nonzero(valid)[0]:
            p = _ct.parse_datetime(_as_str(dd[i]))
            if p is None:
                valid[i] = False
            else:
                out[i] = p
        return out, valid
    return dd.astype(np.int64), valid


def _packed_to_date(p: int) -> _dt.datetime | None:
    y, mo, d, h, mi, s, us = _ct.unpack_time(int(p))
    try:
        return _dt.datetime(y, mo, d, h, mi, s, us)
    except ValueError:
        return None


def _date_to_packed(t: _dt.datetime) -> int:
    return _ct.pack_time(t.year, t.month, t.day, t.hour, t.minute, t.second, t.microsecond)


_INTERVAL_UNITS = {
    "microsecond": lambda n: _dt.timedelta(microseconds=n),
    "second": lambda n: _dt.timedelta(seconds=n),
    "minute": lambda n: _dt.timedelta(minutes=n),
    "hour": lambda n: _dt.timedelta(hours=n),
    "day": lambda n: _dt.timedelta(days=n),
    "week": lambda n: _dt.timedelta(weeks=n),
}


def _add_months(t: _dt.datetime, n: int) -> _dt.datetime:
    m = t.year * 12 + (t.month - 1) + n
    year, month = divmod(m, 12)
    month += 1
    # clamp day to the target month's length (MySQL semantics)
    for day in (t.day, 30, 29, 28):
        try:
            return t.replace(year=year, month=month, day=day)
        except ValueError:
            continue
    raise ValueError("unreachable")


def _date_addsub_kernel(sign: int):
    def kernel(xp, avals, fts, ret_ft):
        (d, v), (nd, nv), (ud, uv) = avals
        dd, dv = _packed_lane(d, v, fts[0])
        n = len(dd)
        out = np.zeros(n, dtype=np.int64)
        valid = (dv & np.asarray(nv).reshape(-1) & np.asarray(uv).reshape(-1)).copy()
        nn = np.asarray(nd).reshape(-1)
        uu = np.asarray(ud).reshape(-1)
        for i in np.nonzero(valid)[0]:
            t = _packed_to_date(dd[i])
            if t is None:
                valid[i] = False
                continue
            unit = _as_str(uu[i if len(uu) > 1 else 0]).lower()
            amount = sign * int(nn[i])
            if unit in _INTERVAL_UNITS:
                t2 = t + _INTERVAL_UNITS[unit](amount)
            elif unit == "month":
                t2 = _add_months(t, amount)
            elif unit in ("quarter",):
                t2 = _add_months(t, amount * 3)
            elif unit == "year":
                t2 = _add_months(t, amount * 12)
            else:
                valid[i] = False
                continue
            out[i] = _date_to_packed(t2)
        return out, valid

    return kernel


def _infer_datetime(fts):
    ft = FieldType(TypeCode.Datetime)
    ft.decimal = max(fts[0].decimal, 0) if fts and fts[0].is_time() else 0
    return ft


register(FuncSig("date_add", _infer_datetime, _date_addsub_kernel(+1), pushable=False, arity=3))
register(FuncSig("date_sub", _infer_datetime, _date_addsub_kernel(-1), pushable=False, arity=3))
register(FuncSig("adddate", _infer_datetime, _date_addsub_kernel(+1), pushable=False, arity=3))
register(FuncSig("subdate", _infer_datetime, _date_addsub_kernel(-1), pushable=False, arity=3))


def _date_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    if xp is np:
        d, v = _packed_lane(d, v, fts[0])
    # truncate time-of-day: packed layout divides evenly at the day radix
    day = _ct.DIV_DAY
    return (d.astype(xp.int64) // day) * day, v


register(FuncSig("date", lambda fts: FieldType(TypeCode.Date), _date_kernel, arity=1))


def _per_row_time(fn, ret="int"):
    def kernel(xp, avals, fts, ret_ft):
        d, v = avals[0]
        dd, valid = _packed_lane(d, v, fts[0] if fts else None)
        n = len(dd)
        out = np.empty(n, dtype=object) if ret == "str" else np.zeros(n, dtype=np.int64)
        valid = valid.copy()
        for i in np.nonzero(valid)[0]:
            t = _packed_to_date(dd[i])
            if t is None:
                valid[i] = False
                continue
            out[i] = fn(t)
        return out, valid

    return kernel


register(FuncSig("dayofweek", lambda fts: ft_longlong(), _per_row_time(lambda t: t.isoweekday() % 7 + 1), pushable=False, arity=1))
register(FuncSig("weekday", lambda fts: ft_longlong(), _per_row_time(lambda t: t.weekday()), pushable=False, arity=1))
register(FuncSig("dayofyear", lambda fts: ft_longlong(), _per_row_time(lambda t: t.timetuple().tm_yday), pushable=False, arity=1))
register(FuncSig("quarter", lambda fts: ft_longlong(), _per_row_time(lambda t: (t.month - 1) // 3 + 1), pushable=False, arity=1))
# week/yearweek: mode-aware _calc_week implementations in builtins_ext2
register(FuncSig("dayname", lambda fts: ft_varchar(16), _per_row_time(lambda t: t.strftime("%A"), "str"), pushable=False, arity=1))
register(FuncSig("monthname", lambda fts: ft_varchar(16), _per_row_time(lambda t: t.strftime("%B"), "str"), pushable=False, arity=1))
register(
    FuncSig(
        "last_day",
        lambda fts: FieldType(TypeCode.Date),
        _per_row_time(
            lambda t: _date_to_packed(
                (_add_months(t.replace(day=1), 1) - _dt.timedelta(days=1)).replace(
                    hour=0, minute=0, second=0, microsecond=0
                )
            )
        ),
        pushable=False,
        arity=1,
    )
)
def _unix_ts_kernel(xp, avals, fts, ret_ft):
    if not avals:  # UNIX_TIMESTAMP() == now
        import time as _time

        return int(_time.time()), True
    return _per_row_time(lambda t: int(t.replace(tzinfo=_dt.timezone.utc).timestamp()))(
        xp, avals, fts, ret_ft
    )


register(FuncSig("unix_timestamp", lambda fts: ft_longlong(), _unix_ts_kernel, pushable=False, arity=(0, 1)))


def _from_unixtime_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    dd = np.asarray(d).reshape(-1)
    fmt_lane = np.asarray(avals[1][0]).reshape(-1) if len(avals) > 1 else None
    out = np.empty(len(dd), dtype=object) if fmt_lane is not None else np.zeros(len(dd), dtype=np.int64)
    valid = np.asarray(v).reshape(-1).copy()
    if fmt_lane is not None:
        valid = valid & np.asarray(avals[1][1]).reshape(-1)
    for i in np.nonzero(valid)[0]:
        t = _dt.datetime.fromtimestamp(float(dd[i]), tz=_dt.timezone.utc).replace(tzinfo=None)
        if fmt_lane is not None:
            fmt = _mysql_fmt_to_py(_as_str(fmt_lane[i if len(fmt_lane) > 1 else 0]))
            out[i] = t.strftime(fmt)
        else:
            out[i] = _date_to_packed(t)
    return out, valid


def _infer_from_unixtime(fts):
    if len(fts) > 1:
        return ft_varchar(64)
    return _infer_datetime(fts)


register(FuncSig("from_unixtime", _infer_from_unixtime, _from_unixtime_kernel, pushable=False, arity=(1, 2)))


def _datediff_kernel(xp, avals, fts, ret_ft):
    # calendar-day difference: the packed radix (32 day slots/month) is
    # NOT a day count, so go through real dates per row
    (a, av), (b, bv) = avals
    a, av = _packed_lane(a, av, fts[0])
    b, bv = _packed_lane(b, bv, fts[1])
    if len(a) != len(b):  # const vs lane broadcast
        if len(a) == 1:
            a, av = np.broadcast_to(a, b.shape), np.broadcast_to(av, bv.shape)
        else:
            b, bv = np.broadcast_to(b, a.shape), np.broadcast_to(bv, av.shape)
    out = np.zeros(len(a), dtype=np.int64)
    valid = np.asarray(av & bv).reshape(-1).copy()
    for i in np.nonzero(valid)[0]:
        ta, tb = _packed_to_date(a[i]), _packed_to_date(b[i])
        if ta is None or tb is None:
            valid[i] = False
            continue
        out[i] = (ta.date() - tb.date()).days
    return out, valid


register(FuncSig("datediff", lambda fts: ft_longlong(), _datediff_kernel, pushable=False, arity=2))

# single-pass specifier translation (sequential replace would collide:
# %i→%M then %M→%B)
_FMT_MAP = {
    "Y": "%Y", "y": "%y", "m": "%m", "d": "%d", "H": "%H", "i": "%M",
    "s": "%S", "S": "%S", "f": "%f", "M": "%B", "b": "%b", "W": "%A",
    "a": "%a", "e": "%-d", "c": "%-m", "T": "%H:%M:%S", "p": "%p",
    "r": "%I:%M:%S %p", "h": "%I", "I": "%I", "j": "%j", "%": "%%",
}
import re as _re

_FMT_RE = _re.compile(r"%(.)")


def _mysql_fmt_to_py(fmt: str) -> str:
    return _FMT_RE.sub(lambda m: _FMT_MAP.get(m.group(1), m.group(1)), fmt)


def _date_format_kernel(xp, avals, fts, ret_ft):
    (d, v), (fd, fv) = avals
    dd, valid = _packed_lane(d, v, fts[0])
    ff = np.asarray(fd).reshape(-1)
    out = np.empty(len(dd), dtype=object)
    valid = (valid & np.asarray(fv).reshape(-1)).copy()
    for i in np.nonzero(valid)[0]:
        t = _packed_to_date(dd[i])
        if t is None:
            valid[i] = False
            continue
        fmt = _mysql_fmt_to_py(_as_str(ff[i if len(ff) > 1 else 0]))
        out[i] = t.strftime(fmt)
    return out, valid


register(FuncSig("date_format", lambda fts: ft_varchar(64), _date_format_kernel, pushable=False, arity=2))


# --- duration helpers (K_DUR lanes are microseconds int64) -----------------


def _time_to_sec_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    src = fts[0]
    if src.tp == TypeCode.Duration:
        return d.astype(xp.int64) // _US, v
    # datetime: seconds within the day
    day_us = (d.astype(xp.int64) % _ct.DIV_DAY)
    return day_us // _US, v


register(FuncSig("time_to_sec", lambda fts: ft_longlong(), _time_to_sec_kernel, arity=1))
register(
    FuncSig(
        "sec_to_time",
        lambda fts: FieldType(TypeCode.Duration),
        lambda xp, avals, fts, ret_ft: (avals[0][0].astype(xp.int64) * _US, avals[0][1]),
        arity=1,
    )
)


# ---------------------------------------------------------------------------
# string breadth (host-only object-lane kernels)
# ---------------------------------------------------------------------------

register(FuncSig("ascii", lambda fts: ft_longlong(), _obj_map(lambda s: ord(_as_str(s)[0]) if _as_str(s) else 0), pushable=False, arity=1))
register(FuncSig("space", lambda fts: ft_varchar(255), _obj_map(lambda n: " " * max(int(n), 0)), pushable=False, arity=1))
register(FuncSig("hex", lambda fts: ft_varchar(255), _obj_map(
    lambda s: (bytes(s).hex().upper() if isinstance(s, (bytes, bytearray))
               else s.encode("utf8").hex().upper() if isinstance(s, str)
               # MySQL: negative ints hex as two's-complement uint64
               else format(int(s) & ((1 << 64) - 1), "X"))), pushable=False, arity=1))
register(FuncSig("unhex", lambda fts: ft_varchar(255), _obj_map(lambda s: bytes.fromhex(_as_str(s))), pushable=False, arity=1))
register(FuncSig("lcase", lambda fts: ft_varchar(255), _obj_map(lambda s: _as_str(s).lower()), pushable=False, arity=1))
register(FuncSig("ucase", lambda fts: ft_varchar(255), _obj_map(lambda s: _as_str(s).upper()), pushable=False, arity=1))


def _multi_str(fn, infer=lambda fts: ft_varchar(255), arity=None, name=None):
    from ..errors import TiDBError

    def kernel(xp, avals, fts, ret_ft):
        if not avals:  # zero-arg form (JSON_OBJECT(), JSON_ARRAY())
            r = fn()
            return r, r is not None
        n = max(len(np.asarray(d).reshape(-1)) for d, _ in avals)
        out = np.empty(n, dtype=object)
        valid = np.ones(n, dtype=bool)
        for _, v in avals:
            valid &= np.asarray(v).reshape(-1)
        cols = [np.asarray(d).reshape(-1) for d, _ in avals]
        for i in np.nonzero(valid)[0]:
            args = [c[i if len(c) > 1 else 0] for c in cols]
            try:
                r = fn(*args)
            except TiDBError:
                raise
            except Exception:  # noqa: BLE001 — malformed input → SQL NULL
                r = None
            if r is None:
                valid[i] = False
            else:
                out[i] = r
        return out, valid

    return FuncSig(name, infer, kernel, pushable=False, arity=arity)


register(_multi_str(lambda *a: _as_str(a[0]).join(_as_str(x) for x in a[1:]), name="concat_ws", arity=(2, None)))
register(_multi_str(lambda s, l, p: _as_str(s)[: int(l)] if len(_as_str(s)) >= int(l) else (_as_str(p) * int(l))[: max(int(l) - len(_as_str(s)), 0)] + _as_str(s), name="lpad", arity=3))
register(_multi_str(lambda s, l, p: _as_str(s)[: int(l)] if len(_as_str(s)) >= int(l) else _as_str(s) + (_as_str(p) * int(l))[: max(int(l) - len(_as_str(s)), 0)], name="rpad", arity=3))
register(_multi_str(lambda s, sub: _as_str(s).find(_as_str(sub)) + 1, infer=lambda fts: ft_longlong(), name="instr", arity=2))
register(_multi_str(lambda sub, s, *pos: _as_str(s).find(_as_str(sub), int(pos[0]) - 1 if pos else 0) + 1, infer=lambda fts: ft_longlong(), name="locate", arity=(2, 3)))
register(_multi_str(lambda sub, s: _as_str(s).find(_as_str(sub)) + 1, infer=lambda fts: ft_longlong(), name="position", arity=2))
register(_multi_str(lambda s, n: _as_str(s) * max(int(n), 0), name="repeat", arity=2))
register(_multi_str(lambda a, b: (_as_str(a) > _as_str(b)) - (_as_str(a) < _as_str(b)), infer=lambda fts: ft_longlong(), name="strcmp", arity=2))


def _substring_index(s, delim, count):
    s, delim, count = _as_str(s), _as_str(delim), int(count)
    if not delim:
        return ""
    parts = s.split(delim)
    if count >= 0:
        return delim.join(parts[:count])
    return delim.join(parts[count:])


register(_multi_str(_substring_index, name="substring_index", arity=3))
register(_multi_str(lambda n, *args: _as_str(args[int(n) - 1]) if 1 <= int(n) <= len(args) else None, name="elt", arity=(2, None)))
register(_multi_str(lambda s, *args: next((i + 1 for i, a in enumerate(args) if _as_str(a) == _as_str(s)), 0), infer=lambda fts: ft_longlong(), name="field", arity=(2, None)))


# ---------------------------------------------------------------------------
# math breadth
# ---------------------------------------------------------------------------


def _f1(fn):
    def kernel(xp, avals, fts, ret_ft):
        d, v = avals[0]
        # decimal lanes are scaled ints: coerce by TYPE, not dtype
        return fn(xp, lane_as_float(xp, d, fts[0])), v

    return kernel


register(FuncSig("asin", lambda fts: ft_double(), _f1(lambda xp, x: xp.arcsin(x)), arity=1))
register(FuncSig("acos", lambda fts: ft_double(), _f1(lambda xp, x: xp.arccos(x)), arity=1))
def _atan_kernel(xp, avals, fts, ret_ft):
    if len(avals) == 2:
        (a, av), (b, bv) = avals
        return xp.arctan2(lane_as_float(xp, a, fts[0]), lane_as_float(xp, b, fts[1])), av & bv
    d, v = avals[0]
    return xp.arctan(lane_as_float(xp, d, fts[0])), v


register(FuncSig("atan", lambda fts: ft_double(), _atan_kernel, arity=(1, 2)))
register(FuncSig("atan2", lambda fts: ft_double(), _atan_kernel, arity=2))
register(FuncSig("cot", lambda fts: ft_double(), _f1(lambda xp, x: 1.0 / xp.tan(x)), arity=1))
register(FuncSig("degrees", lambda fts: ft_double(), _f1(lambda xp, x: x * (180.0 / math.pi)), arity=1))
register(FuncSig("radians", lambda fts: ft_double(), _f1(lambda xp, x: x * (math.pi / 180.0)), arity=1))
register(FuncSig("pi", lambda fts: ft_double(), lambda xp, avals, fts, ret_ft: (xp.asarray(math.pi), xp.asarray(True)), arity=0))
register(
    FuncSig(
        "rand",
        lambda fts: ft_double(),
        # scalar result, broadcast by the projection layer (statement-level
        # randomness; per-row RAND() is a later refinement)
        lambda xp, avals, fts, ret_ft: (float(np.random.random()), True),
        pushable=False,
        arity=(0, 1),
    )
)
register(
    FuncSig(
        "crc32",
        lambda fts: ft_longlong(),
        _obj_map(lambda s: __import__("zlib").crc32(_as_str(s).encode())),
        pushable=False,
        arity=1,
    )
)


def _nullif_kernel(xp, avals, fts, ret_ft):
    (a, av), (b, bv) = avals
    eq = (a == b) & av & bv
    return a, av & ~eq


register(FuncSig("nullif", infer_first, _nullif_kernel, arity=2))


# ---------------------------------------------------------------------------
# JSON (ref: expression/builtin_json.go; documents stored as normalized
# JSON text in object lanes — the binary format is a later optimization)
# ---------------------------------------------------------------------------


def _json_parse(s):
    try:
        return _json.loads(_as_str(s))
    except (ValueError, TypeError):
        return None


def _json_path_tokens(path: str):
    """Tokenize a JSON path: $, .key, ."quoted", [i], [*] →
    [('key', k) | ('idx', i) | ('wild',)] — the ONE path scanner shared by
    the read (json_extract) and modify (json_set/remove/...) families."""
    from ..errors import TiDBError

    if not path.startswith("$"):
        raise TiDBError(f"Invalid JSON path expression {path!r}")
    toks = []
    i, n = 1, len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
            if i < n and path[i] == '"':
                j = path.find('"', i + 1)
                if j < 0:
                    raise TiDBError(f"Invalid JSON path expression {path!r}")
                toks.append(("key", path[i + 1 : j]))
                i = j + 1
            else:
                j = i
                while j < n and (path[j].isalnum() or path[j] == "_"):
                    j += 1
                if j == i:
                    raise TiDBError(f"Invalid JSON path expression {path!r}")
                toks.append(("key", path[i:j]))
                i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                raise TiDBError(f"Invalid JSON path expression {path!r}")
            tok = path[i + 1 : j].strip()
            i = j + 1
            if tok == "*":
                toks.append(("wild",))
            else:
                try:
                    toks.append(("idx", int(tok)))
                except ValueError:
                    raise TiDBError(f"Invalid JSON path expression {path!r}")
        else:
            raise TiDBError(f"Invalid JSON path expression {path!r}")
    return toks


def _json_path_get(doc, path: str):
    """Subset of JSON path: $, .key, ."quoted", [i], [*]. Returns a list of
    matches (for [*]) or a single value wrapped in a list."""
    cur = [doc]
    for t in _json_path_tokens(path):
        if t[0] == "key":
            key = t[1]
            cur = [d[key] for d in cur if isinstance(d, dict) and key in d]
        elif t[0] == "idx":
            idx = t[1]
            cur = [d[idx] for d in cur if isinstance(d, list) and -len(d) <= idx < len(d)]
        else:
            nxt = []
            for d in cur:
                if isinstance(d, list):
                    nxt.extend(d)
            cur = nxt
    return cur


def _json_extract(doc, *paths):
    d = _json_parse(doc)
    if d is None:
        return None
    hits = []
    many = len(paths) > 1 or any("*" in _as_str(p) for p in paths)
    for p in paths:
        hits.extend(_json_path_get(d, _as_str(p)))
    if not hits:
        return None
    out = hits if many else hits[0]
    return _json.dumps(out)


register(_multi_str(_json_extract, infer=lambda fts: _ft_json(), name="json_extract", arity=(2, None)))
register(
    _multi_str(
        lambda s: (_json.loads(_as_str(s)) if _as_str(s).startswith('"') else _as_str(s)),
        name="json_unquote",
        arity=1,
    )
)
register(
    _multi_str(
        lambda s: {type(None): "NULL", bool: "BOOLEAN", int: "INTEGER", float: "DOUBLE",
                   str: "STRING", list: "ARRAY", dict: "OBJECT"}[type(_json_parse(s))]
        if _json_parse(s) is not None or _as_str(s).strip() == "null" else None,
        name="json_type",
        arity=1,
    )
)
register(
    _multi_str(
        lambda s: 1 if _json_parse(s) is not None or _as_str(s).strip() == "null" else 0,
        infer=lambda fts: ft_longlong(),
        name="json_valid",
        arity=1,
    )
)


def _json_length(s, *path):
    d = _json_parse(s)
    if d is None:
        return None
    if path:
        hits = _json_path_get(d, _as_str(path[0]))
        if not hits:
            return None
        d = hits[0]
    return len(d) if isinstance(d, (list, dict)) else 1


register(_multi_str(_json_length, infer=lambda fts: ft_longlong(), name="json_length", arity=(1, 2)))
register(
    _multi_str(
        lambda s: _json.dumps(sorted(_json_parse(s).keys())) if isinstance(_json_parse(s), dict) else None,
        infer=lambda fts: _ft_json(),
        name="json_keys",
        arity=1,
    )
)


def _json_scalar(x):
    if isinstance(x, (bytes, bytearray)):
        return x.decode("utf8", "replace")
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    return x


def _json_object(*args):
    if len(args) % 2:
        return None
    return _json.dumps({_as_str(args[i]): _json_scalar(args[i + 1]) for i in range(0, len(args), 2)})


register(_multi_str(_json_object, infer=lambda fts: _ft_json(), name="json_object", arity=(0, None)))
register(
    _multi_str(
        lambda *a: _json.dumps([_json_scalar(x) for x in a]),
        infer=lambda fts: _ft_json(),
        name="json_array",
        arity=(0, None),
    )
)


def _json_contains(doc, cand, *path):
    d = _json_parse(doc)
    c = _json_parse(cand)
    if d is None or c is None:
        return None
    if path:
        hits = _json_path_get(d, _as_str(path[0]))
        if not hits:
            return 0
        d = hits[0]

    def contains(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            return all(k in a and contains(a[k], v) for k, v in b.items())
        if isinstance(a, list):
            if isinstance(b, list):
                return all(any(contains(x, y) for x in a) for y in b)
            return any(contains(x, b) for x in a)
        return a == b

    return 1 if contains(d, c) else 0


register(_multi_str(_json_contains, infer=lambda fts: ft_longlong(), name="json_contains", arity=(2, 3)))

from . import builtins_ext2  # noqa: E402,F401  (registration side effects)

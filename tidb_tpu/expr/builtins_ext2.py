"""Builtin registry extension II — crypto/encoding, regexp, network,
temporal arithmetic tail (ref: expression/builtin_encryption.go,
builtin_regexp*.go, builtin_miscellaneous.go, builtin_time.go; same
one-kernel architecture as builtins.py). Imported by builtins_ext.py."""

from __future__ import annotations

import base64 as _b64
import datetime as _dt
import hashlib as _hl
import ipaddress as _ip
import os as _os
import re as _re
import time as _time
import uuid as _uuid
import zlib as _zlib

import numpy as np

from ..mysqltypes import coretime as _ct
from ..mysqltypes.field_type import FieldType, TypeCode, ft_double, ft_longlong, ft_varchar
from .builtins import _as_str, _obj_map
from .builtins_ext import _packed_to_date, _multi_str
from .expression import FuncSig, register

_US = 1_000_000


def _null():
    """Sentinel: raise so _obj_map marks the row NULL."""
    raise ValueError("NULL")


# ---------------------------------------------------------------------------
# bitwise operators (ref: builtin_op.go; MySQL bit ops are uint64)
# ---------------------------------------------------------------------------


def _bit_kernel(op):
    def kernel(xp, avals, fts, ret_ft):
        (a, va), (b, vb) = avals
        a = xp.asarray(a).astype(xp.int64)
        b = xp.asarray(b).astype(xp.int64)
        return op(xp, a, b), va & vb

    return kernel


register(FuncSig("bitor", lambda fts: ft_longlong(True), _bit_kernel(lambda xp, a, b: a | b), arity=2))
register(FuncSig("bitand", lambda fts: ft_longlong(True), _bit_kernel(lambda xp, a, b: a & b), arity=2))
register(FuncSig("bitxor", lambda fts: ft_longlong(True), _bit_kernel(lambda xp, a, b: a ^ b), arity=2))
register(FuncSig("lshift", lambda fts: ft_longlong(True), _bit_kernel(lambda xp, a, b: xp.where((b >= 0) & (b < 64), a << (b & 63), 0)), arity=2))
register(FuncSig("rshift", lambda fts: ft_longlong(True), _bit_kernel(
    lambda xp, a, b: xp.where((b >= 0) & (b < 64),
                              (a.view(xp.uint64) if xp is np else a.astype("uint64")) >> (b.astype("uint64") & xp.uint64(63)), 0).astype(xp.int64)), arity=2))
register(FuncSig(
    "bitneg", lambda fts: ft_longlong(True),
    lambda xp, avals, fts, ret_ft: (~xp.asarray(avals[0][0]).astype(xp.int64), avals[0][1]),
    arity=1,
))


# ---------------------------------------------------------------------------
# hashes / encodings (ref: builtin_encryption.go)
# ---------------------------------------------------------------------------

def _as_bytes(v) -> bytes:
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    return _as_str(v).encode("utf8")


register(FuncSig("md5", lambda fts: ft_varchar(32), _obj_map(lambda s: _hl.md5(_as_bytes(s)).hexdigest()), pushable=False, arity=1))
register(FuncSig("sha1", lambda fts: ft_varchar(40), _obj_map(lambda s: _hl.sha1(_as_bytes(s)).hexdigest()), pushable=False, arity=1))
register(FuncSig("sha", lambda fts: ft_varchar(40), _obj_map(lambda s: _hl.sha1(_as_bytes(s)).hexdigest()), pushable=False, arity=1))


def _sha2(s, bits):
    bits = int(bits) or 256
    algo = {224: _hl.sha224, 256: _hl.sha256, 384: _hl.sha384, 512: _hl.sha512}.get(bits)
    if algo is None:
        _null()  # MySQL: invalid hash length → NULL
    return algo(_as_bytes(s)).hexdigest()


register(FuncSig("sha2", lambda fts: ft_varchar(128), _obj_map(_sha2), pushable=False, arity=2))
register(FuncSig("to_base64", lambda fts: ft_varchar(), _obj_map(lambda s: _b64.b64encode(_as_bytes(s)).decode()), pushable=False, arity=1))
register(FuncSig("from_base64", lambda fts: ft_varchar(), _obj_map(lambda s: _b64.b64decode(_as_str(s), validate=True)), pushable=False, arity=1))


def _compress(s):
    b = _as_bytes(s)
    if not b:
        return b""
    return len(b).to_bytes(4, "little") + _zlib.compress(b)


def _uncompress(s):
    b = _as_bytes(s)
    if not b:
        return b""
    return _zlib.decompress(b[4:])


register(FuncSig("compress", lambda fts: ft_varchar(), _obj_map(_compress), pushable=False, arity=1))
register(FuncSig("uncompress", lambda fts: ft_varchar(), _obj_map(_uncompress), pushable=False, arity=1))
register(FuncSig("uncompressed_length", lambda fts: ft_longlong(), _obj_map(lambda s: 0 if not _as_bytes(s) else int.from_bytes(_as_bytes(s)[:4], "little")), pushable=False, arity=1))
register(FuncSig("random_bytes", lambda fts: ft_varchar(), _obj_map(lambda n: _os.urandom(int(n)) if 0 < int(n) <= 1024 else _null()), pushable=False, arity=1))


def _mysql_aes_key(key: bytes, bits: int = 128) -> bytes:
    """MySQL's key folding: XOR key bytes cyclically into the key buffer."""
    n = bits // 8
    out = bytearray(n)
    for i, b in enumerate(key):
        out[i % n] ^= b
    return bytes(out)


try:  # optional acceleration: only AES_ENCRYPT/DECRYPT use it
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
except ImportError:
    Cipher = None  # pure-Python `_aes` fallback takes over


def _ecb_encrypt(raw: bytes, key: bytes) -> bytes:
    if Cipher is not None:
        enc = Cipher(algorithms.AES(key), modes.ECB()).encryptor()
        return enc.update(raw) + enc.finalize()
    from ._aes import ecb_encrypt

    return ecb_encrypt(raw, key)


def _ecb_decrypt(raw: bytes, key: bytes) -> bytes:
    if Cipher is not None:
        dec = Cipher(algorithms.AES(key), modes.ECB()).decryptor()
        return dec.update(raw) + dec.finalize()
    from ._aes import ecb_decrypt

    return ecb_decrypt(raw, key)


def _aes_encrypt(data, key):
    raw = _as_bytes(data)
    pad = 16 - len(raw) % 16
    raw += bytes([pad]) * pad  # PKCS7, always padded (MySQL semantics)
    return _ecb_encrypt(raw, _mysql_aes_key(_as_bytes(key)))


def _aes_decrypt(data, key):
    raw = _as_bytes(data)
    if not raw or len(raw) % 16:
        _null()
    out = _ecb_decrypt(raw, _mysql_aes_key(_as_bytes(key)))
    pad = out[-1]
    if not 1 <= pad <= 16 or out[-pad:] != bytes([pad]) * pad:
        _null()  # wrong key → invalid padding → NULL (MySQL)
    out = out[:-pad]
    try:
        return out.decode("utf8")
    except UnicodeDecodeError:
        return out


register(FuncSig("aes_encrypt", lambda fts: ft_varchar(), _obj_map(_aes_encrypt), pushable=False, arity=2))
register(FuncSig("aes_decrypt", lambda fts: ft_varchar(), _obj_map(_aes_decrypt), pushable=False, arity=2))


def _password(s):
    from ..privilege.cache import mysql_native_hash

    return mysql_native_hash(_as_str(s))


register(FuncSig("password", lambda fts: ft_varchar(41), _obj_map(_password), pushable=False, arity=1))

# ---------------------------------------------------------------------------
# string tail (ref: builtin_string.go)
# ---------------------------------------------------------------------------

register(FuncSig("find_in_set", lambda fts: ft_longlong(), _obj_map(
    lambda s, l: 0 if "," in _as_str(s) else (
        (_as_str(l).split(",").index(_as_str(s)) + 1) if _as_str(s) in _as_str(l).split(",") else 0)),
    pushable=False, arity=2))


def _nullable_args(fn, infer, name, arity):
    """Kernel passing per-row python values with None for NULL args —
    for functions that SKIP null arguments rather than return NULL
    (MAKE_SET, CHAR; ref: builtin_string.go)."""

    def kernel(xp, avals, fts, ret_ft):
        datas = [np.asarray(d).reshape(-1) for d, _ in avals]
        vs = [np.asarray(v).reshape(-1) for _, v in avals]
        n = max((len(d) for d in datas), default=1)
        out = np.empty(n, dtype=object)
        valid = np.ones(n, dtype=bool)
        for i in range(n):
            args = [d[i % len(d)] if len(vv) and vv[i % len(vv)] else None
                    for d, vv in zip(datas, vs)]
            try:
                out[i] = fn(*args)
            except Exception:  # noqa: BLE001 — malformed input → SQL NULL
                valid[i] = False
        return out, valid

    return FuncSig(name, infer, kernel, pushable=False, arity=arity)


def _make_set(bits, *strs):
    if bits is None:
        _null()
    bits = int(bits)
    return ",".join(_as_str(s) for i, s in enumerate(strs)
                    if s is not None and bits & (1 << i))


register(_nullable_args(_make_set, lambda fts: ft_varchar(), "make_set", (2, None)))
register(FuncSig("quote", lambda fts: ft_varchar(), _obj_map(
    lambda s: "'" + _as_str(s).replace("\\", "\\\\").replace("'", "\\'")
    .replace("\x00", "\\0").replace("\x1a", "\\Z") + "'"), pushable=False, arity=1))


def _soundex(s):
    s = _as_str(s).upper()
    s = "".join(c for c in s if c.isalpha())
    if not s:
        return ""
    codes = {**{c: "1" for c in "BFPV"}, **{c: "2" for c in "CGJKQSXZ"},
             **{c: "3" for c in "DT"}, "L": "4", **{c: "5" for c in "MN"}, "R": "6"}
    out = s[0]
    prev = codes.get(s[0], "")
    for c in s[1:]:
        code = codes.get(c, "")
        if code and code != prev:
            out += code
        if c not in "HW":
            prev = code
    return (out + "000")[:4] if len(out) < 4 else out


register(FuncSig("soundex", lambda fts: ft_varchar(8), _obj_map(_soundex), pushable=False, arity=1))


def _export_set(bits, on, off, *rest):
    sep = _as_str(rest[0]) if len(rest) >= 1 else ","
    n = int(rest[1]) if len(rest) >= 2 else 64
    n = min(max(n, 0), 64)
    bits = int(bits)
    return sep.join(_as_str(on) if bits & (1 << i) else _as_str(off) for i in range(n))


register(_multi_str(_export_set, name="export_set", arity=(3, 5)))


def _insert_str(s, pos, ln, new):
    s, pos, ln, new = _as_str(s), int(pos), int(ln), _as_str(new)
    if pos < 1 or pos > len(s):
        return s
    if ln < 0 or pos + ln - 1 >= len(s):
        return s[: pos - 1] + new
    return s[: pos - 1] + new + s[pos - 1 + ln:]


register(FuncSig("insert", lambda fts: ft_varchar(), _obj_map(_insert_str), pushable=False, arity=4))
register(FuncSig("bit_length", lambda fts: ft_longlong(), _obj_map(lambda s: len(_as_bytes(s)) * 8), pushable=False, arity=1))
register(FuncSig("ord", lambda fts: ft_longlong(), _obj_map(lambda s: ord(_as_str(s)[0]) if _as_str(s) else 0), pushable=False, arity=1))
register(_nullable_args(
    lambda *xs: "".join(chr(int(x) & 0xFF) if int(x) < 256 else chr(int(x)) for x in xs if x is not None),
    lambda fts: ft_varchar(), "char", (1, None)))


def _format_kernel(xp, avals, fts, ret_ft):
    from .expression import lane_as_float

    # decimal lanes are scaled ints: coerce via the type-aware helper
    fx = lane_as_float(np, np.asarray(avals[0][0]).reshape(-1), fts[0])
    scaled = [(fx, avals[0][1]), avals[1]]
    return _obj_map(lambda x, d: f"{float(x):,.{max(int(d), 0)}f}")(xp, scaled, fts, ret_ft)


register(FuncSig("format", lambda fts: ft_varchar(), _format_kernel, pushable=False, arity=2))
register(FuncSig("bin", lambda fts: ft_varchar(64), _obj_map(lambda x: format(int(x) & ((1 << 64) - 1) if int(x) < 0 else int(x), "b")), pushable=False, arity=1))
register(FuncSig("oct", lambda fts: ft_varchar(64), _obj_map(lambda x: format(int(x) & ((1 << 64) - 1) if int(x) < 0 else int(x), "o")), pushable=False, arity=1))


def _conv(s, from_b, to_b):
    from_b, to_b = int(from_b), int(to_b)
    if not (2 <= abs(from_b) <= 36 and 2 <= abs(to_b) <= 36):
        _null()
    v = int(_as_str(s).strip() or "0", abs(from_b))
    digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    if v == 0:
        return "0"
    neg = v < 0 and to_b < 0
    v = abs(v)
    out = ""
    while v:
        out = digits[v % abs(to_b)] + out
        v //= abs(to_b)
    return ("-" if neg else "") + out


register(FuncSig("conv", lambda fts: ft_varchar(64), _obj_map(_conv), pushable=False, arity=3))

# ---------------------------------------------------------------------------
# regexp family (ref: builtin_regexp.go; MySQL default is case-insensitive
# for nonbinary strings)
# ---------------------------------------------------------------------------


def _re_compile(pat):
    return _re.compile(_as_str(pat), _re.IGNORECASE)


register(FuncSig("regexp_like", lambda fts: ft_longlong(), _obj_map(
    lambda s, p: 1 if _re_compile(p).search(_as_str(s)) else 0), pushable=False, arity=2))
# the REGEXP/RLIKE operator desugars to the same kernel (ref: builtin.go ast.Regexp)
register(FuncSig("regexp", lambda fts: ft_longlong(), _obj_map(
    lambda s, p: 1 if _re_compile(p).search(_as_str(s)) else 0), pushable=False, arity=2))
register(FuncSig("regexp_replace", lambda fts: ft_varchar(), _obj_map(
    lambda s, p, r: _re_compile(p).sub(_as_str(r), _as_str(s))), pushable=False, arity=3))


def _regexp_substr(s, p):
    m = _re_compile(p).search(_as_str(s))
    if m is None:
        _null()
    return m.group(0)


register(FuncSig("regexp_substr", lambda fts: ft_varchar(), _obj_map(_regexp_substr), pushable=False, arity=2))
register(FuncSig("regexp_instr", lambda fts: ft_longlong(), _obj_map(
    lambda s, p: (m.start() + 1) if (m := _re_compile(p).search(_as_str(s))) else 0), pushable=False, arity=2))

# ---------------------------------------------------------------------------
# network / misc (ref: builtin_miscellaneous.go)
# ---------------------------------------------------------------------------


def _inet_aton(s):
    parts = _as_str(s).split(".")
    if not 1 <= len(parts) <= 4:
        _null()
    try:
        nums = [int(p) for p in parts]
    except ValueError:
        _null()
    if any(not 0 <= x <= 255 for x in nums[:-1]) or nums[-1] < 0:
        _null()
    # MySQL: 'a.b' == a<<24 | b etc (last part fills the remaining bytes)
    v = 0
    for x in nums[:-1]:
        v = (v << 8) | x
    v = (v << (8 * (5 - len(parts)))) | nums[-1]
    return v


register(FuncSig("inet_aton", lambda fts: ft_longlong(), _obj_map(_inet_aton), pushable=False, arity=1))
register(FuncSig("inet_ntoa", lambda fts: ft_varchar(15), _obj_map(
    lambda x: str(_ip.IPv4Address(int(x))) if 0 <= int(x) <= 0xFFFFFFFF else _null()), pushable=False, arity=1))
register(FuncSig("inet6_aton", lambda fts: ft_varchar(16), _obj_map(
    lambda s: _ip.ip_address(_as_str(s)).packed), pushable=False, arity=1))
register(FuncSig("inet6_ntoa", lambda fts: ft_varchar(39), _obj_map(
    lambda b: str(_ip.ip_address(bytes(b) if isinstance(b, (bytes, bytearray)) else _as_str(b).encode("latin1")))), pushable=False, arity=1))


def _is_ipv4(s):
    try:
        _ip.IPv4Address(_as_str(s))
        return 1
    except ValueError:
        return 0


def _is_ipv6(s):
    try:
        _ip.IPv6Address(_as_str(s))
        return 1
    except ValueError:
        return 0


register(FuncSig("is_ipv4", lambda fts: ft_longlong(), _obj_map(_is_ipv4), pushable=False, arity=1))
register(FuncSig("is_ipv6", lambda fts: ft_longlong(), _obj_map(_is_ipv6), pushable=False, arity=1))
register(_multi_str(lambda: str(_uuid.uuid1()), name="uuid", arity=0))
register(FuncSig("any_value", lambda fts: fts[0], lambda xp, avals, fts, ret_ft: avals[0], pushable=False, arity=1))


def _sleep(x):
    _time.sleep(min(max(float(x), 0.0), 10.0))  # capped: protect tests/server
    return 0


register(FuncSig("sleep", lambda fts: ft_longlong(), _obj_map(_sleep), pushable=False, arity=1))

# ---------------------------------------------------------------------------
# temporal arithmetic tail (ref: builtin_time.go)
# ---------------------------------------------------------------------------

_DUR_RE = _re.compile(r"^(-)?(\d+):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,6}))?)?$")


def _parse_duration_us(v) -> int:
    """'[-]HH:MM[:SS[.ffffff]]' or duration-lane int → microseconds."""
    if isinstance(v, (int, np.integer)):
        return int(v)
    m = _DUR_RE.match(_as_str(v).strip())
    if m is None:
        # bare seconds number?
        try:
            return int(float(_as_str(v)) * _US)
        except ValueError:
            _null()
    sign = -1 if m.group(1) else 1
    h, mi = int(m.group(2)), int(m.group(3))
    s = int(m.group(4) or 0)
    frac = int((m.group(5) or "0").ljust(6, "0"))
    return sign * (((h * 60 + mi) * 60 + s) * _US + frac)


def _fmt_duration(us: int) -> str:
    sign = "-" if us < 0 else ""
    us = abs(us)
    s, frac = divmod(us, _US)
    h, rem = divmod(s, 3600)
    mi, sec = divmod(rem, 60)
    out = f"{sign}{h:02d}:{mi:02d}:{sec:02d}"
    if frac:
        out += f".{frac:06d}".rstrip("0")
    return out


_DATE_RE = _re.compile(r"^\s*\d{2,4}-\d{1,2}-\d{1,2}")


def _is_datetime_like(v) -> bool:
    # a leading '-' is a negative duration, not a date
    return isinstance(v, (int, np.integer)) or bool(_DATE_RE.match(_as_str(v)))


def _addtime_like(sign):
    def fn(a, b):
        dus = _parse_duration_us(b)
        if _is_datetime_like(a):  # packed lane int or 'Y-m-d ...' string
            p = int(a) if isinstance(a, (int, np.integer)) else _ct.parse_datetime(_as_str(a))
            if p is None:
                _null()
            t = _packed_to_date(p)
            if t is None:
                _null()
            t2 = t + _dt.timedelta(microseconds=sign * dus)
            return t2.strftime("%Y-%m-%d %H:%M:%S") + (f".{t2.microsecond:06d}" if t2.microsecond else "")
        return _fmt_duration(_parse_duration_us(a) + sign * dus)

    return fn


def _temporal_obj(fn):
    """_obj_map with duration-typed int lanes rendered to 'HH:MM:SS'
    strings first — a TIME column's microsecond lane must not be read as
    a packed datetime."""

    def kernel(xp, avals, fts, ret_ft):
        conv = []
        for (d, v), ft in zip(avals, fts):
            dd = np.asarray(d).reshape(-1)
            if dd.dtype != object and ft is not None and ft.tp == TypeCode.Duration:
                dd = np.array([_fmt_duration(int(x)) for x in dd], dtype=object)
            conv.append((dd, v))
        return _obj_map(fn)(xp, conv, fts, ret_ft)

    return kernel


register(FuncSig("addtime", lambda fts: ft_varchar(32), _temporal_obj(_addtime_like(+1)), pushable=False, arity=2))
register(FuncSig("subtime", lambda fts: ft_varchar(32), _temporal_obj(_addtime_like(-1)), pushable=False, arity=2))


def _timediff(a, b):
    sa, sb = _as_str(a), _as_str(b)
    if _is_datetime_like(a) != _is_datetime_like(b):
        _null()  # mixed datetime/time operands → NULL (MySQL)
    if _is_datetime_like(a):
        pa, pb = _ct.parse_datetime(sa), _ct.parse_datetime(sb)
        if pa is None or pb is None:
            _null()
        ta, tb = _packed_to_date(pa), _packed_to_date(pb)
        return _fmt_duration(int((ta - tb).total_seconds() * _US))
    return _fmt_duration(_parse_duration_us(a) - _parse_duration_us(b))


register(FuncSig("timediff", lambda fts: ft_varchar(32), _temporal_obj(_timediff), pushable=False, arity=2))
register(FuncSig("maketime", lambda fts: ft_varchar(32), _obj_map(
    lambda h, m, s: _fmt_duration(int(((abs(int(h)) * 60 + int(m)) * 60 + float(s)) * _US) * (-1 if int(h) < 0 else 1)) if 0 <= int(m) < 60 and 0 <= float(s) < 60 else _null()),
    pushable=False, arity=3))


def _makedate(y, dy):
    y, dy = int(y), int(dy)
    if dy <= 0:
        _null()
    if y < 70:
        y += 2000
    elif y < 100:
        y += 1900
    try:
        d = _dt.date(y, 1, 1) + _dt.timedelta(days=dy - 1)
    except OverflowError:
        _null()
    return d.strftime("%Y-%m-%d")


register(FuncSig("makedate", lambda fts: ft_varchar(10), _obj_map(_makedate), pushable=False, arity=2))


def _to_date(v):
    if isinstance(v, (int, np.integer)):
        t = _packed_to_date(int(v))
    else:
        p = _ct.parse_datetime(_as_str(v))
        t = _packed_to_date(p) if p is not None else None
    if t is None:
        _null()
    return t


# MySQL day numbers count from year 0 — 365 days before Python's
# proleptic ordinal epoch (0001-01-01): TO_DAYS('1970-01-01') = 719528
_MYSQL_DAY0 = 365

register(FuncSig("to_days", lambda fts: ft_longlong(), _obj_map(lambda v: _to_date(v).toordinal() + _MYSQL_DAY0), pushable=False, arity=1))
register(FuncSig("from_days", lambda fts: ft_varchar(10), _obj_map(
    lambda n: _dt.date.fromordinal(int(n) - _MYSQL_DAY0).strftime("%Y-%m-%d") if int(n) > 730 else _null()), pushable=False, arity=1))
register(FuncSig("to_seconds", lambda fts: ft_longlong(), _obj_map(
    lambda v: (lambda t: (t.toordinal() + _MYSQL_DAY0) * 86400 + t.hour * 3600 + t.minute * 60 + t.second)(_to_date(v))), pushable=False, arity=1))


def _period_to_months(p):
    p = int(p)
    y, m = divmod(p, 100)
    if y < 70:
        y += 2000
    elif y < 100:
        y += 1900
    return y * 12 + m - 1


def _months_to_period(months):
    y, m = divmod(months, 12)
    return y * 100 + m + 1


register(FuncSig("period_add", lambda fts: ft_longlong(), _obj_map(
    lambda p, n: _months_to_period(_period_to_months(p) + int(n))), pushable=False, arity=2))
register(FuncSig("period_diff", lambda fts: ft_longlong(), _obj_map(
    lambda a, b: _period_to_months(a) - _period_to_months(b)), pushable=False, arity=2))
def _days_in_year(y: int) -> int:
    return 366 if (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)) else 365


def _calc_week(d: _dt.date, mode: int):
    """MySQL's calc_week bit semantics (WEEK_MONDAY_FIRST=1, WEEK_YEAR=2,
    WEEK_FIRST_WEEKDAY=4) — the spec behind WEEK()/YEARWEEK() modes 0-7
    (ref: expression/builtin_time.go calcWeek)."""
    monday_first = bool(mode & 1)
    week_year = bool(mode & 2)
    first_weekday = bool(mode & 4)
    daynr = d.toordinal()
    jan1 = _dt.date(d.year, 1, 1)
    first_daynr = jan1.toordinal()
    wd = jan1.weekday()  # Monday=0
    weekday = wd if monday_first else (wd + 1) % 7
    year = d.year
    if d.month == 1 and d.day <= 7 - weekday:
        if not week_year and (
            (first_weekday and weekday != 0) or (not first_weekday and weekday >= 4)
        ):
            return year, 0
        week_year = True
        year -= 1
        diy = _days_in_year(year)
        first_daynr -= diy
        weekday = (weekday + 53 * 7 - diy) % 7
    if (first_weekday and weekday != 0) or (not first_weekday and weekday >= 4):
        days = daynr - (first_daynr + (7 - weekday))
    else:
        days = daynr - (first_daynr - weekday)
    if week_year and days >= 52 * 7:
        weekday = (weekday + _days_in_year(year)) % 7
        if (not first_weekday and weekday < 4) or (first_weekday and weekday == 0):
            return year + 1, 1
    return year, days // 7 + 1


def _week_mode(mode: int) -> int:
    mode &= 7
    if not (mode & 1):
        mode ^= 4
    return mode


def _default_week_mode() -> int:
    from . import sessioninfo

    try:
        return int((sessioninfo.get("vars") or {}).get("default_week_format", "0"))
    except (TypeError, ValueError):
        return 0


def _week(v, *mode):
    t = _to_date(v)
    d = t.date() if isinstance(t, _dt.datetime) else t
    m = int(mode[0]) if mode and mode[0] is not None else _default_week_mode()
    return _calc_week(d, _week_mode(m))[1]


def _yearweek2(v, *mode):
    t = _to_date(v)
    d = t.date() if isinstance(t, _dt.datetime) else t
    m = int(mode[0]) if mode and mode[0] is not None else _default_week_mode()
    y, w = _calc_week(d, _week_mode(m | 2))
    return y * 100 + w


register(FuncSig("week", lambda fts: ft_longlong(), _obj_map(_week), pushable=False, arity=(1, 2)))
register(FuncSig("yearweek", lambda fts: ft_longlong(), _obj_map(_yearweek2), pushable=False, arity=(1, 2)))
register(FuncSig("weekofyear", lambda fts: ft_longlong(), _obj_map(
    lambda v: _to_date(v).isocalendar()[1]), pushable=False, arity=1))
register(_multi_str(lambda: _dt.datetime.utcnow().strftime("%Y-%m-%d"), name="utc_date", arity=0))
register(_multi_str(lambda: _dt.datetime.utcnow().strftime("%Y-%m-%d %H:%M:%S"), name="utc_timestamp", arity=0))


def _time_of(v):
    s = _as_str(v)
    if " " in s:
        return s.split(" ", 1)[1]
    if isinstance(v, (int, np.integer)):
        t = _packed_to_date(int(v))
        if t is None:
            _null()
        return t.strftime("%H:%M:%S")
    return _fmt_duration(_parse_duration_us(v))


register(FuncSig("time", lambda fts: ft_varchar(32), _temporal_obj(_time_of), pushable=False, arity=1))

_STRPTIME = {
    "%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%m", "%d": "%d", "%e": "%d",
    "%H": "%H", "%k": "%H", "%h": "%I", "%I": "%I", "%i": "%M", "%s": "%S",
    "%S": "%S", "%p": "%p", "%f": "%f", "%b": "%b", "%M": "%B", "%a": "%a",
    "%W": "%A", "%j": "%j", "%%": "%%",
}


def _mysql_fmt_to_py(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "%" and i + 1 < len(fmt):
            tok = fmt[i : i + 2]
            out.append(_STRPTIME.get(tok, tok[1]))
            i += 2
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


def _str_to_date(s, fmt):
    try:
        t = _dt.datetime.strptime(_as_str(s), _mysql_fmt_to_py(_as_str(fmt)))
    except ValueError:
        _null()
    if t.hour or t.minute or t.second or t.microsecond:
        return t.strftime("%Y-%m-%d %H:%M:%S")
    return t.strftime("%Y-%m-%d")


register(FuncSig("str_to_date", lambda fts: ft_varchar(26), _obj_map(_str_to_date), pushable=False, arity=2))
register(FuncSig("time_format", lambda fts: ft_varchar(32), _obj_map(
    lambda v, f: (_dt.datetime(2000, 1, 1) + _dt.timedelta(microseconds=abs(_parse_duration_us(v)))).strftime(
        _mysql_fmt_to_py(_as_str(f)).replace("%H", f"{abs(_parse_duration_us(v)) // 3600000000:02d}"))),
    pushable=False, arity=2))

_UNIT_US = {
    "microsecond": 1, "second": _US, "minute": 60 * _US, "hour": 3600 * _US,
    "day": 86400 * _US, "week": 7 * 86400 * _US,
}


def _timestampdiff(unit, a, b):
    unit = _as_str(unit).lower()
    ta, tb = _to_date(a), _to_date(b)
    if unit in ("month", "quarter", "year"):
        months = (tb.year - ta.year) * 12 + tb.month - ta.month
        # partial months don't count
        if months > 0 and (tb.day, tb.time()) < (ta.day, ta.time()):
            months -= 1
        elif months < 0 and (tb.day, tb.time()) > (ta.day, ta.time()):
            months += 1
        return {"month": months, "quarter": int(months / 3), "year": int(months / 12)}[unit]
    us = int((tb - ta).total_seconds() * _US)
    return int(us / _UNIT_US[unit])


def _timestampadd(unit, n, v):
    unit = _as_str(unit).lower()
    t = _to_date(v)
    n = int(n)
    if unit in ("month", "quarter", "year"):
        months = n * {"month": 1, "quarter": 3, "year": 12}[unit]
        total = t.year * 12 + (t.month - 1) + months
        y, m = divmod(total, 12)
        day = min(t.day, [31, 29 if y % 4 == 0 and (y % 100 or y % 400 == 0) else 28,
                          31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m])
        t2 = t.replace(year=y, month=m + 1, day=day)
    else:
        t2 = t + _dt.timedelta(microseconds=n * _UNIT_US[unit])
    if t2.hour or t2.minute or t2.second or t2.microsecond:
        return t2.strftime("%Y-%m-%d %H:%M:%S")
    return t2.strftime("%Y-%m-%d")


register(FuncSig("timestampdiff", lambda fts: ft_longlong(), _obj_map(_timestampdiff), pushable=False, arity=3))
register(FuncSig("timestampadd", lambda fts: ft_varchar(26), _obj_map(_timestampadd), pushable=False, arity=3))


def _extract(unit, v):
    unit = _as_str(unit).lower()
    t = _to_date(v)
    return {
        "year": t.year, "month": t.month, "day": t.day, "hour": t.hour,
        "minute": t.minute, "second": t.second, "microsecond": t.microsecond,
        "quarter": (t.month - 1) // 3 + 1, "week": t.isocalendar()[1],
        "year_month": t.year * 100 + t.month, "day_hour": t.day * 100 + t.hour,
    }.get(unit) if unit in ("year", "month", "day", "hour", "minute", "second",
                            "microsecond", "quarter", "week", "year_month",
                            "day_hour") else _null()


register(FuncSig("extract", lambda fts: ft_longlong(), _obj_map(_extract), pushable=False, arity=2))

from . import builtins_ext3  # noqa: E402,F401  (registration side effects)

"""Aggregate function descriptors (ref: expression/aggregation/descriptor.go).

The partial/final mode split is the heart of distributed aggregation
(SURVEY §2.13.3): cop/TPU side computes partials per shard, root side
merges. On device, partials are exact integer/float segment reductions
and the cross-device merge is a `psum` — which is why SUM over decimals
uses scaled int64 lanes.

    func   | partial state         | final merge
    -------|-----------------------|---------------------
    count  | count:int64           | sum of counts
    sum    | sum (+has flag)       | sum of sums
    avg    | (sum, count)          | sum/ count  (exact decimal div)
    min    | min (+has flag)       | min of mins
    max    | max                   | max of maxs
    first_row | first value        | first of firsts
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..mysqltypes.field_type import FieldType, ft_longlong, ft_double, ft_decimal
from ..mysqltypes.mydecimal import MAX_SCALE, DIV_FRAC_INCR
from .expression import Expression

MODE_COMPLETE = "complete"
MODE_PARTIAL = "partial"
MODE_FINAL = "final"

PUSHABLE_AGGS = (
    "count", "sum", "avg", "min", "max", "first_row",
    # (cnt, sum, sumsq) / bitwise partials merge exactly at the root final
    "stddev_pop", "stddev_samp", "var_pop", "var_samp",
    "bit_and", "bit_or", "bit_xor",
    # FM-sketch partials union exactly at the root final (ref:
    # aggfuncs approxCountDistinctPartial1/Final, statistics/fmsketch.go)
    "approx_count_distinct",
)
AGG_FUNCS = PUSHABLE_AGGS + (
    "group_concat",
    "stddev_pop", "stddev_samp", "std", "stddev",
    "var_pop", "var_samp", "variance",
    "bit_and", "bit_or", "bit_xor",
    # complete-mode only (ref: aggfuncs.go:45-53 percentileOriginal*,
    # jsonArrayagg/jsonObjectagg)
    "approx_percentile", "json_arrayagg", "json_objectagg",
)
# aliases normalize at construction (ref: MySQL STD/STDDEV/VARIANCE)
_AGG_ALIAS = {"std": "stddev_pop", "stddev": "stddev_pop", "variance": "var_pop"}
# aggs that take other than exactly one argument
_AGG_ARITY = {"approx_percentile": 2, "json_objectagg": 2, "count": (0, 1)}
# aggs that keep NULL argument rows (JSON aggregation includes nulls)
NULL_KEEPING_AGGS = ("json_arrayagg", "json_objectagg")
GROUP_CONCAT_MAX_LEN = 1024  # MySQL group_concat_max_len default


def _scale(ft: FieldType) -> int:
    return max(ft.decimal, 0) if ft.is_decimal() else 0


def agg_ret_type(name: str, arg_ft: FieldType | None) -> FieldType:
    if name == "count":
        return ft_longlong()
    if name == "group_concat":
        from ..mysqltypes.field_type import ft_varchar

        return ft_varchar(GROUP_CONCAT_MAX_LEN)
    if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
        return ft_double()
    if name in ("bit_and", "bit_or", "bit_xor"):
        ft = ft_longlong()
        from ..mysqltypes.field_type import UNSIGNED_FLAG

        ft.flag |= UNSIGNED_FLAG
        return ft
    if name == "approx_count_distinct":
        return ft_longlong()
    if name in ("json_arrayagg", "json_objectagg"):
        from ..mysqltypes.field_type import TypeCode

        return FieldType(TypeCode.JSON, flen=-1)
    if name == "approx_percentile":
        return arg_ft.clone()
    if name == "sum":
        if arg_ft.is_float() or arg_ft.is_string():
            return ft_double()
        # SUM of int/decimal is decimal in MySQL
        return ft_decimal(38, _scale(arg_ft))
    if name == "avg":
        if arg_ft.is_float() or arg_ft.is_string():
            return ft_double()
        return ft_decimal(38, min(_scale(arg_ft) + DIV_FRAC_INCR, MAX_SCALE))
    # min/max/first_row keep the arg type
    return arg_ft.clone()


@dataclass
class AggDesc:
    name: str
    args: list[Expression]
    distinct: bool = False
    mode: str = MODE_COMPLETE
    ret_type: FieldType = field(default_factory=ft_longlong)

    sep: str = ","  # GROUP_CONCAT separator
    max_len: int = GROUP_CONCAT_MAX_LEN  # group_concat_max_len sysvar

    @staticmethod
    def make(name: str, args: list[Expression], distinct: bool = False) -> "AggDesc":
        from ..errors import TiDBError

        name = _AGG_ALIAS.get(name.lower(), name.lower())
        if name not in AGG_FUNCS:
            raise ValueError(f"unknown aggregate {name}")
        want = _AGG_ARITY.get(name, 1)
        lo, hi = want if isinstance(want, tuple) else (want, want)
        if not (lo <= len(args) <= hi):
            raise TiDBError(f"aggregate {name.upper()} takes {want} argument(s)")
        if name == "approx_percentile":
            from .expression import Constant

            p = args[1]
            ok = isinstance(p, Constant) and not p.value.is_null
            try:
                f = p.value.to_float()
                ok = ok and f == int(f) and 1 <= int(f) <= 100
            except Exception:
                ok = False
            if not ok:
                raise TiDBError("Percentage value must be a constant integer in [1, 100]")
        arg_ft = args[0].ret_type if args else None
        return AggDesc(name, args, distinct, MODE_COMPLETE, agg_ret_type(name, arg_ft))

    def pushable(self) -> bool:
        """May this aggregate run as a cop/TPU partial? (ref: agg_to_pb.go)"""
        return (
            not self.distinct
            and self.name in PUSHABLE_AGGS
            and all(a.pushable() for a in self.args)
        )

    def partial_final_types(self) -> list[tuple[str, FieldType]]:
        """The partial-state columns this agg ships back from the cop side."""
        if self.name == "count":
            return [("count", ft_longlong())]
        if self.name == "sum":
            return [("sum", self.ret_type)]
        if self.name == "avg":
            arg = self.args[0].ret_type
            return [("sum", agg_ret_type("sum", arg)), ("count", ft_longlong())]
        if self.name == "group_concat":
            return [("concat", self.ret_type)]
        if self.name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            return [("count", ft_longlong()), ("sum", ft_double()), ("sumsq", ft_double())]
        if self.name == "approx_count_distinct":
            from ..mysqltypes.field_type import ft_varchar

            return [("sketch", ft_varchar(-1))]  # serialized FMSketch bytes
        return [(self.name, self.ret_type)]

    def __repr__(self):
        d = "distinct " if self.distinct else ""
        s = f" sep={self.sep!r}" if self.name == "group_concat" and self.sep != "," else ""
        if self.name == "group_concat" and self.max_len != GROUP_CONCAT_MAX_LEN:
            s += f" maxlen={self.max_len}"  # digest/plan-cache key material
        return f"{self.name}({d}{', '.join(map(repr, self.args))}{s})"


# window-only functions (ref: executor/aggfuncs window builders; the agg
# functions above are also valid window functions via OVER)
WINDOW_FUNCS = (
    "row_number",
    "rank",
    "dense_rank",
    "ntile",
    "lead",
    "lag",
    "first_value",
    "last_value",
    "nth_value",
    "cume_dist",
    "percent_rank",
)


@dataclass(frozen=True)
class Frame:
    """Normalized window frame (ref: planner/core WindowFrame). Bound
    kinds: 'up'|'pre'|'cur'|'fol'|'uf'; offsets are validated non-negative
    numbers (ROWS: ints; RANGE: numbers in the ORDER BY key's own space —
    decimal keys carry the offset pre-scaled to the key's scaled-int
    form). `None` frame == MySQL default (RANGE UNBOUNDED PRECEDING ..
    CURRENT ROW with ORDER BY, whole partition without)."""

    unit: str  # 'rows' | 'range'
    start_kind: str
    start_off: object = 0  # int | float
    end_kind: str = "cur"
    end_off: object = 0

    def key(self):
        return (self.unit, self.start_kind, self.start_off, self.end_kind, self.end_off)


@dataclass
class WinDesc:
    """One window function over a (PARTITION BY, ORDER BY) spec
    (ref: planner/core WindowFuncDesc + ast WindowSpec)."""

    name: str
    args: list[Expression]
    part_by: list[Expression]
    order_by: list  # [(Expression, desc: bool)]
    ret_type: FieldType = field(default_factory=ft_longlong)
    frame: Frame | None = None  # None == default frame semantics

    def spec_key(self) -> str:
        return f"part={self.part_by!r}|order={[(repr(e), d) for e, d in self.order_by]!r}"

    def __repr__(self):
        fr = f" frame={self.frame.key()}" if self.frame is not None else ""
        return f"{self.name}({', '.join(map(repr, self.args))}) over({self.spec_key()}{fr})"

from .expression import Expression, Column, Constant, ScalarFunc, make_func, eval_expr_np, FUNCS
from . import builtins  # populate the registry
from .aggregation import AggDesc, AGG_FUNCS

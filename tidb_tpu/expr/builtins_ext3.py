"""Builtin registry extension III — JSON modify/merge/search family,
session info functions, current time family, user locks, and the
miscellaneous tail toward the reference's 279 classes (ref:
expression/builtin_json.go, builtin_info.go, builtin_time.go,
builtin_miscellaneous.go builtin.go:599; same one-kernel architecture
as builtins.py). Imported by builtins_ext2.py."""

from __future__ import annotations

import datetime as _dt
import json as _json
import threading as _th
import time as _time
import uuid as _uuid

import numpy as np

from ..mysqltypes import coretime as _ct
from ..mysqltypes.datum import Datum, K_DUR
from ..mysqltypes.field_type import FieldType, TypeCode, ft_double, ft_longlong, ft_varchar
from . import sessioninfo
from .builtins import _as_str, _obj_map
from .builtins_ext import _ft_json, _json_parse, _json_path_get, _json_path_tokens, _json_scalar, _multi_str, _packed_to_date
from .expression import FuncSig, register

_US = 1_000_000


# ---------------------------------------------------------------------------
# JSON modify family (ref: builtin_json.go jsonSet/Insert/Replace/...)
# ---------------------------------------------------------------------------


def _path_steps(path: str):
    """Wildcard-free JSON path steps for the modify family — the shared
    tokenizer (_json_path_tokens) with [*]/'**' rejected (MySQL rule)."""
    from ..errors import TiDBError

    steps = _json_path_tokens(path)
    if any(t[0] == "wild" for t in steps):
        raise TiDBError("In this situation, path expressions may not contain the * and ** tokens")
    return steps


def _modify(doc, path: str, val, mode: str):
    """One json_set/insert/replace step (mode 'set'|'insert'|'replace')."""
    steps = _path_steps(path)
    if not steps:
        return val if mode != "insert" else doc
    cur = doc
    for kind, k in steps[:-1]:
        if kind == "key":
            if not isinstance(cur, dict) or k not in cur:
                return doc  # missing intermediate: no-op (MySQL)
            cur = cur[k]
        else:
            if not isinstance(cur, list) or not (-len(cur) <= k < len(cur)):
                return doc
            cur = cur[k]
    kind, k = steps[-1]
    if kind == "key":
        if not isinstance(cur, dict):
            return doc
        exists = k in cur
        if (exists and mode != "insert") or (not exists and mode != "replace"):
            cur[k] = val
    else:
        if not isinstance(cur, list):
            # MySQL autowraps scalars: $[0] on a scalar replaces it
            return doc
        if -len(cur) <= k < len(cur):
            if mode != "insert":
                cur[k] = val
        elif mode != "replace":
            cur.append(val)
    return doc


def _json_modify_fn(mode):
    def fn(doc, *pairs):
        d = _json_parse(doc)
        if d is None:
            return None
        if len(pairs) % 2:
            return None
        for i in range(0, len(pairs), 2):
            d = _modify(d, _as_str(pairs[i]), _json_scalar(pairs[i + 1]), mode)
        return _json.dumps(d)

    return fn


for _nm, _md in (("json_set", "set"), ("json_insert", "insert"), ("json_replace", "replace")):
    register(_multi_str(_json_modify_fn(_md), infer=lambda fts: _ft_json(), name=_nm, arity=(3, None)))


def _json_remove(doc, *paths):
    d = _json_parse(doc)
    if d is None:
        return None
    for p in paths:
        steps = _path_steps(_as_str(p))
        if not steps:
            return None  # removing $ is an error → NULL row
        cur = d
        ok = True
        for kind, k in steps[:-1]:
            if kind == "key" and isinstance(cur, dict) and k in cur:
                cur = cur[k]
            elif kind == "idx" and isinstance(cur, list) and -len(cur) <= k < len(cur):
                cur = cur[k]
            else:
                ok = False
                break
        if not ok:
            continue
        kind, k = steps[-1]
        if kind == "key" and isinstance(cur, dict):
            cur.pop(k, None)
        elif kind == "idx" and isinstance(cur, list) and -len(cur) <= k < len(cur):
            del cur[k]
    return _json.dumps(d)


register(_multi_str(_json_remove, infer=lambda fts: _ft_json(), name="json_remove", arity=(2, None)))


def _json_array_append(doc, *pairs):
    d = _json_parse(doc)
    if d is None or len(pairs) % 2:
        return None
    for i in range(0, len(pairs), 2):
        steps = _path_steps(_as_str(pairs[i]))
        val = _json_scalar(pairs[i + 1])
        if not steps:
            d = d + [val] if isinstance(d, list) else [d, val]
            continue
        cur = d
        ok = True
        for kind, k in steps[:-1]:
            if kind == "key" and isinstance(cur, dict) and k in cur:
                cur = cur[k]
            elif kind == "idx" and isinstance(cur, list) and -len(cur) <= k < len(cur):
                cur = cur[k]
            else:
                ok = False
                break
        if not ok:
            continue
        kind, k = steps[-1]
        tgt = None
        if kind == "key" and isinstance(cur, dict) and k in cur:
            tgt = cur[k]
            cur[k] = tgt + [val] if isinstance(tgt, list) else [tgt, val]
        elif kind == "idx" and isinstance(cur, list) and -len(cur) <= k < len(cur):
            tgt = cur[k]
            cur[k] = tgt + [val] if isinstance(tgt, list) else [tgt, val]
    return _json.dumps(d)


register(_multi_str(_json_array_append, infer=lambda fts: _ft_json(), name="json_array_append", arity=(3, None)))


def _json_array_insert(doc, *pairs):
    d = _json_parse(doc)
    if d is None or len(pairs) % 2:
        return None
    for i in range(0, len(pairs), 2):
        steps = _path_steps(_as_str(pairs[i]))
        if not steps or steps[-1][0] != "idx":
            return None  # path must end in an array index (MySQL error)
        val = _json_scalar(pairs[i + 1])
        cur = d
        ok = True
        for kind, k in steps[:-1]:
            if kind == "key" and isinstance(cur, dict) and k in cur:
                cur = cur[k]
            elif kind == "idx" and isinstance(cur, list) and -len(cur) <= k < len(cur):
                cur = cur[k]
            else:
                ok = False
                break
        if ok and isinstance(cur, list):
            k = steps[-1][1]
            cur.insert(max(0, k if k >= 0 else len(cur) + k), val)
    return _json.dumps(d)


register(_multi_str(_json_array_insert, infer=lambda fts: _ft_json(), name="json_array_insert", arity=(3, None)))


def _merge_preserve(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _merge_preserve(out[k], v) if k in out else v
        return out
    la = a if isinstance(a, list) else [a]
    lb = b if isinstance(b, list) else [b]
    return la + lb


def _merge_patch(a, b):
    if not isinstance(b, dict):
        return b
    out = dict(a) if isinstance(a, dict) else {}
    for k, v in b.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


def _json_merge_fn(merge):
    def fn(*docs):
        ds = [_json_parse(x) for x in docs]
        if any(d is None and _as_str(x).strip() != "null" for d, x in zip(ds, docs)):
            return None
        acc = ds[0]
        for d in ds[1:]:
            acc = merge(acc, d)
        return _json.dumps(acc)

    return fn


for _nm in ("json_merge", "json_merge_preserve"):
    register(_multi_str(_json_merge_fn(_merge_preserve), infer=lambda fts: _ft_json(), name=_nm, arity=(2, None)))
register(_multi_str(_json_merge_fn(_merge_patch), infer=lambda fts: _ft_json(), name="json_merge_patch", arity=(2, None)))


def _json_contains_path(doc, one_or_all, *paths):
    d = _json_parse(doc)
    if d is None:
        return None
    mode = _as_str(one_or_all).lower()
    if mode not in ("one", "all"):
        return None
    hits = [bool(_json_path_get(d, _as_str(p))) for p in paths]
    return int(any(hits) if mode == "one" else all(hits))


register(_multi_str(_json_contains_path, infer=lambda fts: ft_longlong(), name="json_contains_path", arity=(3, None)))


def _depth(d):
    if isinstance(d, dict):
        return 1 + max((_depth(v) for v in d.values()), default=0)
    if isinstance(d, list):
        return 1 + max((_depth(v) for v in d), default=0)
    return 1


register(
    _multi_str(
        lambda s: _depth(_json_parse(s)) if _json_parse(s) is not None or _as_str(s).strip() == "null" else None,
        infer=lambda fts: ft_longlong(),
        name="json_depth",
        arity=1,
    )
)
register(
    _multi_str(
        lambda s: _json.dumps(_json_parse(s), indent=2) if _json_parse(s) is not None else None,
        infer=lambda fts: _ft_json(),
        name="json_pretty",
        arity=1,
    )
)
register(_multi_str(lambda s: _json.dumps(_as_str(s)), infer=lambda fts: _ft_json(), name="json_quote", arity=1))
register(
    _multi_str(
        lambda s: len(_json.dumps(_json_parse(s)).encode()) if _json_parse(s) is not None else None,
        infer=lambda fts: ft_longlong(),
        name="json_storage_size",
        arity=1,
    )
)


def _json_subdocs(doc, path: str):
    """[(path string, value)] for every node a (possibly wildcarded)
    path matches — the path-tracking twin of _json_path_get."""
    cur = [("$", doc)]
    for t in _json_path_tokens(path):
        nxt = []
        for p, d in cur:
            if t[0] == "key":
                if isinstance(d, dict) and t[1] in d:
                    k = t[1]
                    nxt.append((f'{p}."{k}"' if not k.isalnum() else f"{p}.{k}", d[k]))
            elif t[0] == "idx":
                if isinstance(d, list) and -len(d) <= t[1] < len(d):
                    nxt.append((f"{p}[{t[1] % len(d)}]", d[t[1]]))
            else:  # wildcard
                if isinstance(d, list):
                    nxt.extend((f"{p}[{i}]", x) for i, x in enumerate(d))
        cur = nxt
    return cur


def _json_search(doc, one_or_all, pat, *rest):
    import fnmatch

    d = _json_parse(doc)
    if d is None or pat is None:
        return None
    mode = _as_str(one_or_all).lower()
    if mode not in ("one", "all"):
        return None
    # rest: [escape_char [, path...]] (MySQL: NULL escape means default \)
    esc = "\\"
    if rest and rest[0] is not None and _as_str(rest[0]) != "":
        esc = _as_str(rest[0])
        if len(esc) != 1:
            return None
    pattern = _as_str(pat)

    def like(s):
        # SQL LIKE: % any run, _ one char, honoring the escape character
        trans = pattern.replace(esc + "%", "\0").replace(esc + "_", "\1")
        trans = trans.replace("%", "*").replace("_", "?")
        trans = trans.replace("\0", "%").replace("\1", "_")
        return fnmatch.fnmatchcase(s, trans)

    roots = [("$", d)]
    if len(rest) > 1:
        roots = []
        for p in rest[1:]:
            roots.extend(_json_subdocs(d, _as_str(p)))

    out = []

    def walk(v, path):
        if isinstance(v, str) and like(v):
            out.append(path)
        elif isinstance(v, dict):
            for k, x in v.items():
                walk(x, f'{path}."{k}"' if not k.isalnum() else f"{path}.{k}")
        elif isinstance(v, list):
            for i, x in enumerate(v):
                walk(x, f"{path}[{i}]")

    for base, sub in roots:
        walk(sub, base)
    seen = set()
    out = [p for p in out if not (p in seen or seen.add(p))]
    if not out:
        return None
    if mode == "one":
        return _json.dumps(out[0])
    return _json.dumps(out if len(out) > 1 else out[0])


def _json_search_kernel(xp, avals, fts, ret_ft):
    """Custom lane kernel: only (doc, one_or_all, pattern) are required
    non-NULL; a NULL escape/path argument reaches _json_search as None
    (MySQL treats a NULL escape as the default backslash)."""
    from ..errors import TiDBError

    cols = [np.asarray(d).reshape(-1) for d, _ in avals]
    vlds = [np.asarray(v).reshape(-1) for _, v in avals]
    n = max(len(c) for c in cols)
    req = np.ones(n, dtype=bool)
    for v in vlds[:3]:
        req &= v
    out = np.empty(n, dtype=object)
    valid = req.copy()
    for i in np.nonzero(req)[0]:
        args = [
            c[i if len(c) > 1 else 0] if bool(v[i if len(v) > 1 else 0]) else None
            for c, v in zip(cols, vlds)
        ]
        try:
            r = _json_search(*args)
        except TiDBError:
            raise
        except Exception:  # noqa: BLE001 — malformed input → SQL NULL
            r = None
        if r is None:
            valid[i] = False
        else:
            out[i] = r
    return out, valid


register(FuncSig("json_search", lambda fts: _ft_json(), _json_search_kernel, pushable=False, arity=(3, None)))


# ---------------------------------------------------------------------------
# session info functions (ref: builtin_info.go; values published by the
# Session through expr.sessioninfo)
# ---------------------------------------------------------------------------


def _scalar0(fn):
    """Zero-arg kernel; numeric results become 0-d arrays so downstream
    kernels can re-coerce them (strings stay python scalars like uuid())."""

    def kernel(xp, avals, fts, ret_ft):
        r = fn()
        if isinstance(r, (int, float)) and not isinstance(r, bool):
            return np.asarray(r), np.asarray(r is not None)
        return r, r is not None

    return kernel


def _info_func(name, fn, ft=None, arity=0):
    register(
        FuncSig(
            name,
            (lambda fts: ft.clone()) if ft is not None else (lambda fts: ft_varchar(64)),
            _obj_map(fn) if arity else _scalar0(fn),
            pushable=False,
            arity=arity,
        )
    )


_info_func("version", lambda: "8.0.11-tidb-tpu")
_info_func("tidb_version", lambda: "8.0.11-tidb-tpu\nEdition: TPU-native (jax/XLA)")
_info_func("database", lambda: sessioninfo.get("db") or None)
_info_func("schema", lambda: sessioninfo.get("db") or None)
_info_func("user", lambda: f"{sessioninfo.get('user', 'root')}@%")
_info_func("current_user", lambda: f"{sessioninfo.get('user', 'root')}@%")
_info_func("session_user", lambda: f"{sessioninfo.get('user', 'root')}@%")
_info_func("system_user", lambda: f"{sessioninfo.get('user', 'root')}@%")
_info_func("current_role", lambda: "NONE")
_info_func("connection_id", lambda: int(sessioninfo.get("conn_id", 0)), ft=ft_longlong())
_info_func("found_rows", lambda: int(sessioninfo.get("found_rows", 0)), ft=ft_longlong())
_info_func("row_count", lambda: int(sessioninfo.get("row_count", -1)), ft=ft_longlong())
_info_func("last_insert_id", lambda: int(sessioninfo.get("last_insert_id", 0)), ft=ft_longlong())
register(
    FuncSig(
        "benchmark",
        lambda fts: ft_longlong(),
        # the lane is already evaluated once per row; MySQL returns 0
        lambda xp, avals, fts, ret_ft: (np.zeros(len(np.asarray(avals[0][0]).reshape(-1)), np.int64), np.ones(len(np.asarray(avals[0][0]).reshape(-1)), bool)),
        pushable=False,
        arity=2,
    )
)


# ---------------------------------------------------------------------------
# current time family (ref: builtin_time.go; the planner also folds these
# at plan time for cacheability — these kernels serve nested/late binding)
# ---------------------------------------------------------------------------


def _now_epoch():
    return sessioninfo.now_epoch()


def _now_packed():
    t = _time.localtime(_now_epoch())
    return _ct.pack_time(t.tm_year, t.tm_mon, t.tm_mday, t.tm_hour, t.tm_min, t.tm_sec)


def _time_func(name, fn, tc):
    register(
        FuncSig(
            name,
            lambda fts, _tc=tc: FieldType(_tc),
            lambda xp, avals, fts, ret_ft, _fn=fn: (_fn(), True),
            pushable=False,
            arity=(0, 1) if name in ("now", "sysdate", "current_timestamp", "localtime", "localtimestamp", "curtime", "current_time", "utc_time") else 0,
        )
    )


for _nm in ("now", "sysdate", "current_timestamp", "localtime", "localtimestamp"):
    _time_func(_nm, _now_packed, TypeCode.Datetime)
for _nm in ("curdate", "current_date"):
    _time_func(
        _nm,
        lambda: (lambda t: _ct.pack_time(t.tm_year, t.tm_mon, t.tm_mday))(_time.localtime(_now_epoch())),
        TypeCode.Date,
    )


def _curtime_us():
    t = _time.localtime(_now_epoch())
    return (t.tm_hour * 3600 + t.tm_min * 60 + t.tm_sec) * _US


for _nm in ("curtime", "current_time"):
    _time_func(_nm, _curtime_us, TypeCode.Duration)


def _utc_time_us():
    t = _time.gmtime(_now_epoch())
    return (t.tm_hour * 3600 + t.tm_min * 60 + t.tm_sec) * _US


_time_func("utc_time", _utc_time_us, TypeCode.Duration)


def _timestamp_fn(expr, *timeadd):
    p = _ct.parse_datetime(_as_str(expr))
    if p is None:
        return None
    if timeadd:
        d = _ct.parse_duration(_as_str(timeadd[0]))
        if d is None:
            return None
        t = _packed_to_date(p)
        if t is None:
            return None
        t = t + _dt.timedelta(microseconds=d)
        return t.strftime("%Y-%m-%d %H:%M:%S")
    t = _packed_to_date(p)
    return t.strftime("%Y-%m-%d %H:%M:%S") if t else None


register(_multi_str(_timestamp_fn, name="timestamp", arity=(1, 2)))


def _tz_offset(tz: str):
    tz = _as_str(tz).strip()
    if tz.upper() in ("SYSTEM", "UTC", "+00:00", "-00:00"):
        if tz.upper() == "SYSTEM":
            off = -_time.timezone if not _time.daylight else -_time.altzone
            return _dt.timedelta(seconds=off)
        return _dt.timedelta(0)
    sign = 1 if tz[0] == "+" else -1 if tz[0] == "-" else None
    if sign is None or ":" not in tz:
        return None  # named zones need a tz database: NULL (documented)
    hh, mm = tz[1:].split(":", 1)
    return sign * _dt.timedelta(hours=int(hh), minutes=int(mm))


def _convert_tz(dtv, frm, to):
    p = _ct.parse_datetime(_as_str(dtv))
    if p is None:
        return None
    o1, o2 = _tz_offset(frm), _tz_offset(to)
    if o1 is None or o2 is None:
        return None
    t = _packed_to_date(p)
    if t is None:
        return None
    return (t - o1 + o2).strftime("%Y-%m-%d %H:%M:%S")


register(_multi_str(_convert_tz, name="convert_tz", arity=3))

_GET_FORMAT = {
    ("date", "usa"): "%m.%d.%Y", ("date", "jis"): "%Y-%m-%d", ("date", "iso"): "%Y-%m-%d",
    ("date", "eur"): "%d.%m.%Y", ("date", "internal"): "%Y%m%d",
    ("datetime", "usa"): "%Y-%m-%d %H.%i.%s", ("datetime", "jis"): "%Y-%m-%d %H:%i:%s",
    ("datetime", "iso"): "%Y-%m-%d %H:%i:%s", ("datetime", "eur"): "%Y-%m-%d %H.%i.%s",
    ("datetime", "internal"): "%Y%m%d%H%i%s",
    ("time", "usa"): "%h:%i:%s %p", ("time", "jis"): "%H:%i:%s", ("time", "iso"): "%H:%i:%s",
    ("time", "eur"): "%H.%i.%s", ("time", "internal"): "%H%i%s",
}
register(
    _multi_str(
        lambda t, loc: _GET_FORMAT.get((_as_str(t).lower(), _as_str(loc).lower())),
        name="get_format",
        arity=2,
    )
)


# ---------------------------------------------------------------------------
# string/misc tail (ref: builtin_string.go, builtin_miscellaneous.go)
# ---------------------------------------------------------------------------

register(
    FuncSig(
        "mid",
        lambda fts: ft_varchar(),
        _obj_map(lambda s, pos, ln: _as_str(s)[int(pos) - 1 : int(pos) - 1 + int(ln)] if int(pos) > 0 else (_as_str(s)[int(pos):][:int(ln)] if int(pos) < 0 else "")),
        pushable=False,
        arity=3,
    )
)
register(
    FuncSig(
        "octet_length",
        lambda fts: ft_longlong(),
        _obj_map(lambda s: len(s) if isinstance(s, (bytes, bytearray)) else len(_as_str(s).encode())),
        pushable=False,
        arity=1,
    )
)
register(
    FuncSig(
        "character_length",
        lambda fts: ft_longlong(),
        _obj_map(lambda s: len(_as_str(s))),
        pushable=False,
        arity=1,
    )
)


def _translate(s, frm, to):
    s, frm, to = _as_str(s), _as_str(frm), _as_str(to)
    table = {}
    for i, ch in enumerate(frm):
        if ch not in table:  # first occurrence wins (MySQL)
            table[ch] = to[i] if i < len(to) else None
    return "".join(t for ch in s for t in [table.get(ch, ch)] if t is not None)


register(_multi_str(_translate, name="translate", arity=3))
register(
    _multi_str(
        # binary collation: the weight string IS the byte sequence
        lambda s: s if isinstance(s, (bytes, bytearray)) else _as_str(s).encode(),
        name="weight_string",
        arity=1,
    )
)
register(
    FuncSig(
        "bit_count",
        lambda fts: ft_longlong(),
        _obj_map(lambda x: bin(int(x) & 0xFFFFFFFFFFFFFFFF).count("1")),
        pushable=False,
        arity=1,
    )
)


def _interval_fn(n, *bounds):
    if n is None:
        return -1
    x = float(n)
    out = 0
    for b in bounds:
        if b is not None and x >= float(b):
            out += 1
        else:
            break
    return out


register(_multi_str(_interval_fn, infer=lambda fts: ft_longlong(), name="interval", arity=(2, None)))
register(
    FuncSig(
        "name_const",
        lambda fts: fts[1].clone() if len(fts) > 1 else ft_varchar(),
        lambda xp, avals, fts, ret_ft: avals[1],
        pushable=False,
        arity=2,
    )
)

_uuid_short_state = {"lock": _th.Lock(), "n": int(_time.time()) << 24}


def _uuid_short():
    with _uuid_short_state["lock"]:
        _uuid_short_state["n"] += 1
        return _uuid_short_state["n"] & 0x7FFFFFFFFFFFFFFF


register(FuncSig("uuid_short", lambda fts: ft_longlong(), _scalar0(_uuid_short), pushable=False, arity=0))


def _uuid_to_bin(s, *swap):
    u = _uuid.UUID(_as_str(s))
    b = u.bytes
    if swap and int(swap[0]):
        b = b[6:8] + b[4:6] + b[0:4] + b[8:]
    return b


def _bin_to_uuid(b, *swap):
    if not isinstance(b, (bytes, bytearray)):
        b = _as_str(b).encode("latin-1")
    if len(b) != 16:
        return None
    b = bytes(b)
    if swap and int(swap[0]):
        b = b[4:8] + b[2:4] + b[0:2] + b[8:]
    return str(_uuid.UUID(bytes=b))


register(_multi_str(_uuid_to_bin, name="uuid_to_bin", arity=(1, 2)))
register(_multi_str(_bin_to_uuid, name="bin_to_uuid", arity=(1, 2)))


def _is_ipv4_compat(b):
    if not isinstance(b, (bytes, bytearray)):
        b = _as_str(b).encode("latin-1")
    return int(len(b) == 16 and b[:12] == b"\x00" * 12)


def _is_ipv4_mapped(b):
    if not isinstance(b, (bytes, bytearray)):
        b = _as_str(b).encode("latin-1")
    return int(len(b) == 16 and b[:12] == b"\x00" * 10 + b"\xff\xff")


register(_multi_str(_is_ipv4_compat, infer=lambda fts: ft_longlong(), name="is_ipv4_compat", arity=1))
register(_multi_str(_is_ipv4_mapped, infer=lambda fts: ft_longlong(), name="is_ipv4_mapped", arity=1))


def _format_bytes(x):
    v = float(x)
    for unit in ("Bytes", "KiB", "MiB", "GiB", "TiB", "PiB", "EiB"):
        if abs(v) < 1024 or unit == "EiB":
            return f"{v:.0f} {unit}" if unit == "Bytes" else f"{v:.2f} {unit}"
        v /= 1024


def _format_nanotime(x):
    v = float(x)
    for unit, div in (("ns", 1), ("µs", 1e3), ("ms", 1e6), ("s", 1e9), ("min", 6e10), ("h", 3.6e12)):
        if abs(v) < div * 1000 or unit == "h":
            return f"{v / div:.2f} {unit}"


register(_multi_str(_format_bytes, name="format_bytes", arity=1))
register(_multi_str(_format_nanotime, name="format_nanotime", arity=1))


# ---------------------------------------------------------------------------
# user-level locks (ref: builtin_miscellaneous.go GET_LOCK; process-global
# table keyed by lock name, reentrant per connection)
# ---------------------------------------------------------------------------

_USER_LOCKS: dict[str, list] = {}  # name -> [conn_id, count]
_USER_LOCKS_MU = _th.Lock()
_USER_LOCKS_CV = _th.Condition(_USER_LOCKS_MU)


def _conn():
    return int(sessioninfo.get("conn_id", 0))


def _get_lock(name, timeout):
    name = _as_str(name)
    me = _conn()
    deadline = _time.monotonic() + max(float(timeout), 0)
    with _USER_LOCKS_CV:
        while True:
            cur = _USER_LOCKS.get(name)
            if cur is None or cur[0] == me:
                if cur is None:
                    _USER_LOCKS[name] = [me, 1]
                else:
                    cur[1] += 1
                return 1
            left = deadline - _time.monotonic()
            if left <= 0:
                return 0
            _USER_LOCKS_CV.wait(min(left, 0.05))


def _release_lock(name):
    name = _as_str(name)
    me = _conn()
    with _USER_LOCKS_CV:
        cur = _USER_LOCKS.get(name)
        if cur is None:
            return None  # lock never existed
        if cur[0] != me:
            return 0
        cur[1] -= 1
        if cur[1] <= 0:
            del _USER_LOCKS[name]
            _USER_LOCKS_CV.notify_all()
        return 1


def _release_all_locks():
    me = _conn()
    with _USER_LOCKS_CV:
        mine = [k for k, v in _USER_LOCKS.items() if v[0] == me]
        n = sum(_USER_LOCKS[k][1] for k in mine)
        for k in mine:
            del _USER_LOCKS[k]
        if mine:
            _USER_LOCKS_CV.notify_all()
        return n


register(_multi_str(_get_lock, infer=lambda fts: ft_longlong(), name="get_lock", arity=2))
register(_multi_str(_release_lock, infer=lambda fts: ft_longlong(), name="release_lock", arity=1))
register(
    _multi_str(
        lambda name: int(_as_str(name) not in _USER_LOCKS),
        infer=lambda fts: ft_longlong(),
        name="is_free_lock",
        arity=1,
    )
)
register(
    _multi_str(
        lambda name: (_USER_LOCKS.get(_as_str(name)) or [None])[0],
        infer=lambda fts: ft_longlong(),
        name="is_used_lock",
        arity=1,
    )
)
register(
    FuncSig(
        "release_all_locks",
        lambda fts: ft_longlong(),
        _scalar0(_release_all_locks),
        pushable=False,
        arity=0,
    )
)


# ---------------------------------------------------------------------------
# encode/decode + password strength + load_file (ref: builtin_encryption.go)
# ---------------------------------------------------------------------------


def _xor_stream(data: bytes, password: str) -> bytes:
    import hashlib

    key = hashlib.sha256(password.encode()).digest()
    out = bytearray(len(data))
    for i, b in enumerate(data):
        out[i] = b ^ key[i % len(key)]
    return bytes(out)


def _encode(s, pw):
    data = s if isinstance(s, (bytes, bytearray)) else _as_str(s).encode()
    return _xor_stream(bytes(data), _as_str(pw))


register(_multi_str(_encode, name="encode", arity=2))
register(_multi_str(_encode, name="decode", arity=2))  # XOR stream is its own inverse


def _password_strength(s):
    s = _as_str(s)
    if len(s) < 4:
        return 0
    if len(s) < 8:
        return 25
    score = 50
    if any(c.isdigit() for c in s):
        score += 12
    if any(c.islower() for c in s) and any(c.isupper() for c in s):
        score += 13
    if any(not c.isalnum() for c in s):
        score += 25
    return min(score, 100)


register(_multi_str(_password_strength, infer=lambda fts: ft_longlong(), name="validate_password_strength", arity=1))


def _load_file(p):
    from ..utils import sem

    sem.check_file_access()
    try:
        with open(_as_str(p), "rb") as f:
            return f.read()
    except OSError:
        return None


register(_multi_str(_load_file, name="load_file", arity=1))


# ---------------------------------------------------------------------------
# TiDB-specific introspection (ref: builtin_info.go tidb* funcs)
# ---------------------------------------------------------------------------


def _tidb_parse_tso(ts):
    ms = int(ts) >> 18
    t = _dt.datetime.fromtimestamp(ms / 1000.0)
    return t.strftime("%Y-%m-%d %H:%M:%S.%f")


register(_multi_str(_tidb_parse_tso, name="tidb_parse_tso", arity=1))
register(
    FuncSig(
        "tidb_is_ddl_owner",
        lambda fts: ft_longlong(),
        # single-process deployment: this node always owns DDL
        _scalar0(lambda: 1),
        pushable=False,
        arity=0,
    )
)


def _tidb_decode_key(s):
    from ..codec import tablecodec as tc

    try:
        key = bytes.fromhex(_as_str(s))
    except ValueError:
        return _as_str(s)
    try:
        tid = tc.decode_table_id(key)
    except Exception:  # noqa: BLE001 — undecodable: echo input (TiDB behavior)
        return _as_str(s)
    try:
        h = tc.decode_record_handle(key)
        return _json.dumps({"table_id": tid, "row_id": h})
    except Exception:  # noqa: BLE001
        try:
            h = tc.decode_index_handle(key)
            return _json.dumps({"table_id": tid, "index_handle": h})
        except Exception:  # noqa: BLE001
            return _json.dumps({"table_id": tid})


register(_multi_str(_tidb_decode_key, name="tidb_decode_key", arity=1))


def _tidb_bounded_staleness(lo, hi):
    # resolved read ts within [lo, hi]: single node resolves to hi
    p = _ct.parse_datetime(_as_str(hi))
    if p is None:
        return None
    t = _packed_to_date(p)
    return t.strftime("%Y-%m-%d %H:%M:%S.%f") if t else None


register(_multi_str(_tidb_bounded_staleness, name="tidb_bounded_staleness", arity=2))

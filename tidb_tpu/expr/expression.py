"""Scalar expression framework (ref: expression/expression.go).

The reference has per-row `Eval*` plus vectorized `VecEval*` twins over
chunk columns (expression.go:62-82) — ~279 builtin classes with generated
vector code. Here each builtin is ONE generic array kernel written against
an array namespace `xp`, instantiated twice:

  * xp=numpy  → the host vectorized evaluator (root-side executors)
  * xp=jax.numpy → the device lowering, composed into fused jit programs
    by the coprocessor engine (the closure_exec.go:167 fusion analog)

Value representation per lane (matches chunk/tile):
  int/time/duration → int64, float → float64, decimal → int64 scaled by
  ret_type.decimal, strings → object (numpy only; device uses dict codes),
  booleans → int64 {0,1} with a validity mask (SQL three-valued logic).

Evaluation returns (data, valid) pairs; `valid` is the NOT-NULL mask.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..mysqltypes.field_type import FieldType, TypeCode, ft_longlong, ft_double
from ..mysqltypes.datum import Datum, K_NULL, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR
from ..mysqltypes.mydecimal import pow10
from ..chunk.chunk import Chunk, col_numpy_dtype, VARLEN


class Expression:
    ret_type: FieldType

    def eval(self, chunk: Chunk):
        """numpy vectorized evaluation → (data ndarray, valid ndarray)."""
        raise NotImplementedError

    def collect_columns(self, out: set):
        pass

    def pushable(self) -> bool:
        """May this expression be encoded into a pushdown DAG?

        (ref: expression/expr_to_pb.go CanExprsPushDown + blocklist)
        """
        return False

    def equal(self, other) -> bool:
        return repr(self) == repr(other)


@dataclass
class Column(Expression):
    """Offset-based reference into the input schema (ref: expression.Column)."""

    idx: int
    ret_type: FieldType = field(default_factory=ft_longlong)
    name: str = ""

    def eval(self, chunk: Chunk):
        col = chunk.columns[self.idx]
        return col.data, col.valid

    def collect_columns(self, out: set):
        out.add(self.idx)

    def pushable(self) -> bool:
        return True

    def __repr__(self):
        return f"col#{self.idx}" + (f"({self.name})" if self.name else "")


@dataclass
class Constant(Expression):
    value: Datum = field(default_factory=Datum.null)
    ret_type: FieldType = field(default_factory=ft_longlong)

    def eval(self, chunk: Chunk):
        n = chunk.num_rows
        if self.value.is_null:
            dt = col_numpy_dtype(self.ret_type)
            data = np.empty(n, dtype=object) if dt is VARLEN else np.zeros(n, dtype=dt)
            return data, np.zeros(n, dtype=bool)
        v = self.scalar_value()
        dt = col_numpy_dtype(self.ret_type)
        if dt is VARLEN:
            data = np.empty(n, dtype=object)
            data[:] = v
        else:
            if dt is np.int64 and isinstance(v, int) and v > np.iinfo(np.int64).max:
                dt = np.uint64  # np.full would silently wrap the literal
            data = np.full(n, v, dtype=dt)
        return data, np.ones(n, dtype=bool)

    def scalar_value(self):
        """The lane-representation scalar (scaled int for decimals, etc.)."""
        d, ft = self.value, self.ret_type
        if d.is_null:
            return None
        if ft.is_decimal():
            return d.to_dec().rescale(max(ft.decimal, 0)).value
        if ft.is_float():
            return d.to_float()
        if d.kind in (K_STR, K_BYTES):
            return d.val
        return d.to_int()

    def pushable(self) -> bool:
        return True

    def __repr__(self):
        return f"const({self.value!r})"


@dataclass
class ScalarFunc(Expression):
    sig: "FuncSig"
    args: list[Expression]
    ret_type: FieldType

    def eval(self, chunk: Chunk):
        avals = [a.eval(chunk) for a in self.args]
        return self.sig.kernel(np, avals, [a.ret_type for a in self.args], self.ret_type)

    def eval_xp(self, xp, avals):
        """Device path: kernel over already-materialized (data, valid) pairs."""
        return self.sig.kernel(xp, avals, [a.ret_type for a in self.args], self.ret_type)

    def collect_columns(self, out: set):
        for a in self.args:
            a.collect_columns(out)

    def pushable(self) -> bool:
        return self.sig.pushable and all(a.pushable() for a in self.args)

    def __repr__(self):
        return f"{self.sig.name}({', '.join(map(repr, self.args))})"


@dataclass
class FuncSig:
    """A builtin function: type inference + one generic array kernel."""

    name: str
    infer: Callable  # (arg_fts) -> ret FieldType
    kernel: Callable  # (xp, [(data,valid)...], arg_fts, ret_ft) -> (data, valid)
    pushable: bool = True
    varargs: bool = False
    arity: int | tuple | None = None  # int exact, (min, max|None) range, None unchecked
    post_infer: Callable | None = None  # (args, ret_ft) -> ret FieldType


# registry filled by builtins.py
FUNCS: dict[str, FuncSig] = {}


def register(sig: FuncSig):
    FUNCS[sig.name] = sig
    return sig


def make_func(name: str, *args: Expression) -> ScalarFunc:
    sig = FUNCS.get(name.lower())
    if sig is None:
        raise ValueError(f"unknown function {name}")
    n = len(args)
    ar = sig.arity
    if ar is not None:
        lo, hi = (ar, ar) if isinstance(ar, int) else ar
        if n < lo or (hi is not None and n > hi):
            raise ValueError(f"wrong number of arguments to {sig.name.upper()}: got {n}")
    ret = sig.infer([a.ret_type for a in args])
    if sig.post_infer is not None:
        ret = sig.post_infer(list(args), ret)
    return ScalarFunc(sig, list(args), ret)


def eval_expr_np(expr: Expression, chunk: Chunk):
    return expr.eval(chunk)


# ---------------------------------------------------------------------------
# shared coercion helpers used by kernels (work for numpy and jax.numpy)
# ---------------------------------------------------------------------------


def collation_key_lane(d, ft: FieldType | None):
    """Sort/group/join KEY form of a lane: weight strings when `ft` is a
    case-insensitive-collated string column, the lane itself otherwise
    (ref: util/collate — every comparison surface keys on weights)."""
    from ..mysqltypes import collate as _c

    if (
        ft is not None
        and ft.is_string()
        and _c.is_ci(getattr(ft, "collate", None))
        and getattr(d, "dtype", None) == object
    ):
        return _c.weight_lane(d, ft.collate)
    return d


def datum_sort_key(dat, ft: FieldType | None):
    """Collation-aware comparable for one string datum: (weight, raw) —
    weight orders, raw breaks ties deterministically (binary-min wins)."""
    from ..mysqltypes import collate as _c

    s = dat.val if isinstance(dat.val, str) else (
        bytes(dat.val).decode("latin-1") if isinstance(dat.val, (bytes, bytearray)) else str(dat.val)
    )
    if ft is not None and ft.is_string() and _c.is_ci(getattr(ft, "collate", None)):
        return (_c.weight(s, ft.collate), s)
    return (s, s)


def lane_as_float(xp, data, ft: FieldType):
    """Coerce a lane to float64 honoring decimal scale."""
    if ft.is_decimal():
        return data.astype(xp.float64) / pow10(max(ft.decimal, 0))
    if ft.is_string() and xp is np:
        out = np.zeros(len(data), dtype=np.float64)
        for i, v in enumerate(data):
            if v is not None:
                out[i] = Datum.s(v if isinstance(v, str) else v.decode("utf8", "replace")).to_float()
        return out
    return data.astype(xp.float64)


def lane_as_decimal(xp, data, ft: FieldType, target_scale: int):
    """Coerce int/decimal lane to a scaled-int lane at target_scale (exact)."""
    s = max(ft.decimal, 0) if ft.is_decimal() else 0
    if target_scale == s:
        return data.astype(xp.int64)
    return data.astype(xp.int64) * pow10(target_scale - s)


def _string_lane_as_time(data, valid):
    """Parse a string lane as packed datetimes (host only). Unparseable → 0."""
    from ..mysqltypes.coretime import parse_datetime

    out = np.zeros(len(data), dtype=np.int64)
    for i in np.nonzero(valid)[0]:
        s = data[i]
        p = parse_datetime(s if isinstance(s, str) else s.decode("utf8", "replace"))
        out[i] = p if p is not None else 0
    return out


def numeric_common(xp, avals, fts):
    """Coerce arg lanes to a common numeric domain for comparison/arith.

    Returns (kind, lanes) where kind is 'int' | 'dec:<scale>' | 'float' | 'str'.
    A time mixed with strings compares chronologically: the string side is
    parsed as a datetime (ref: expression/builtin_compare.go
    GetAccurateCmpType + RefineComparedConstant semantics).
    """
    if all(ft.is_string() for ft in fts):
        return "str", [d for d, _ in avals]
    if any(ft.is_time() for ft in fts) and all(ft.is_time() or ft.is_string() for ft in fts):
        lanes = [
            d.astype(xp.int64) if ft.is_time() else _string_lane_as_time(d, v)
            for (d, v), ft in zip(avals, fts)
        ]
        return "int", lanes
    if any(ft.is_float() or ft.is_string() for ft in fts):
        return "float", [lane_as_float(xp, d, ft) for (d, _), ft in zip(avals, fts)]
    if any(ft.is_decimal() for ft in fts):
        scale = max(max(ft.decimal, 0) for ft in fts if ft.is_decimal())
        return f"dec:{scale}", [lane_as_decimal(xp, d, ft, scale) for (d, _), ft in zip(avals, fts)]
    lanes = [d for d, _ in avals]
    if any(str(getattr(l, "dtype", "")) == "uint64" for l in lanes):
        if all(str(getattr(l, "dtype", "")) == "uint64" for l in lanes):
            return "uint", lanes
        # mixed signed/unsigned BIGINT: value-correct without widening
        # (ref: expression/builtin_compare.go CompareInt's sign-aware
        # branches). Each value maps to a lexicographic (class, lo) pair:
        #   class -1: negative signed            lo = x
        #   class  0: [0, 2^63) from either side lo = value
        #   class +1: unsigned >= 2^63           lo = u - 2^64 (monotone)
        # int64 wrap of the high uint half is order-preserving per class.
        return "int2", [int2_pair(xp, l) for l in lanes]
    return "int", [l.astype(xp.int64) for l in lanes]


def int2_pair(xp, lane):
    """(class, lo) encoding for exact mixed signed/unsigned comparison."""
    if str(lane.dtype) == "uint64":
        hi = (lane > xp.asarray(np.iinfo(np.int64).max, dtype=lane.dtype)).astype(xp.int64)
        return hi, lane.astype(xp.int64)
    lo = lane.astype(xp.int64)
    return -(lo < 0).astype(xp.int64), lo


def int2_as_float(xp, pair):
    """Approximate scalar value of an int2 pair (for arithmetic domains
    where exactness above 2^53 is not contractual)."""
    hi, lo = pair
    return lo.astype(xp.float64) + (hi == 1) * np.float64(2.0**64)


def all_valid(xp, avals):
    v = avals[0][1]
    for _, vv in avals[1:]:
        v = v & vv
    return v

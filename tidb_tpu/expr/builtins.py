"""Builtin scalar functions (ref: expression/builtin_*.go, ~279 classes).

Each builtin is registered once with a type-inference rule and ONE generic
kernel over the array namespace `xp` (numpy host / jax.numpy device) —
replacing the reference's hand-written + generated Eval/VecEval twins
(expression/builtin_arithmetic_vec.go etc.).

TPC-H/SSB-critical functions are implemented first; the registry covers
arithmetic, comparison, 3-valued logic, control flow, rounding/math, date
extraction, string basics, and casts. String kernels are host-only
(pushable=False) except equality/compare, which the device engine handles
via dictionary codes.
"""

from __future__ import annotations

import math
import re

import numpy as np

from ..errors import TiDBError as TiDBErrorBase
from ..mysqltypes.field_type import FieldType, TypeCode, ft_longlong, ft_double, ft_decimal, ft_varchar, UNSIGNED_FLAG
from ..mysqltypes.mydecimal import pow10, MAX_SCALE, DIV_FRAC_INCR
from .expression import (
    FuncSig,
    register,
    lane_as_float,
    lane_as_decimal,
    numeric_common,
    int2_as_float,
    all_valid,
)

_US = 1_000_000


# ---------------------------------------------------------------------------
# type inference helpers
# ---------------------------------------------------------------------------


def _scale(ft: FieldType) -> int:
    return max(ft.decimal, 0) if ft.is_decimal() else 0


# Decimal lanes are scaled int64: ~18 significant digits total. Results
# needing a finer scale cannot be represented exactly in a lane, so
# arithmetic degrades to float64 instead of silently wrapping int64
# (the reference's 65-digit MyDecimal words don't have this cliff; our
# device-representable domain covers real workloads — TPC-H uses scale ≤ 4).
DEC_LANE_MAX_SCALE = 12


def infer_arith(op: str):
    def infer(fts):
        if any(ft.is_float() or ft.is_string() for ft in fts):
            return ft_double()
        if any(ft.is_decimal() for ft in fts):
            if op == "mul":
                s = sum(_scale(ft) for ft in fts)
            else:
                s = max(_scale(ft) for ft in fts)
            if s > DEC_LANE_MAX_SCALE:
                return ft_double()
            return ft_decimal(30, s)
        return ft_longlong()

    return infer


def _div_frac_incr() -> int:
    """Division scale growth — div_precision_increment when a session is
    active (ref: expression/builtin_arithmetic.go deriveDivisionScale)."""
    from . import sessioninfo

    try:
        return int((sessioninfo.get("vars") or {}).get("div_precision_increment", DIV_FRAC_INCR))
    except (TypeError, ValueError):
        return DIV_FRAC_INCR


def infer_div(fts):
    if any(ft.is_float() or ft.is_string() for ft in fts):
        return ft_double()
    s = max((_scale(ft) for ft in fts), default=0) + _div_frac_incr()
    if s > DEC_LANE_MAX_SCALE:
        return ft_double()
    return ft_decimal(30, s)


def infer_bool(fts):
    return ft_longlong()


def infer_first(fts):
    return fts[0].clone()


def merge_types(fts: list[FieldType]) -> FieldType:
    """Result type of CASE/IF/COALESCE branches (ref: types/field_type.go MergeFieldType)."""
    fts = [ft for ft in fts if ft.tp != TypeCode.Null]
    if not fts:
        return ft_varchar()
    if all(ft.is_string() for ft in fts):
        return ft_varchar(max(ft.flen for ft in fts))
    if all(ft.is_time() for ft in fts):
        return fts[0].clone()
    if any(ft.is_string() or ft.is_float() or ft.is_time() for ft in fts):
        return ft_double()
    if any(ft.is_decimal() for ft in fts):
        return ft_decimal(30, max(_scale(ft) for ft in fts))
    # unsignedness survives only when every branch is unsigned (MySQL
    # MergeFieldType flag semantics)
    return ft_longlong(unsigned=all(ft.is_unsigned for ft in fts))


# ---------------------------------------------------------------------------
# arithmetic kernels
# ---------------------------------------------------------------------------


def _arith_kernel(op: str):
    def kernel(xp, avals, fts, ret_ft):
        valid = all_valid(xp, avals)
        if ret_ft.is_float():
            a, b = (lane_as_float(xp, d, ft) for (d, _), ft in zip(avals, fts))
            data = {"plus": lambda: a + b, "minus": lambda: a - b, "mul": lambda: a * b}[op]()
        elif ret_ft.is_decimal():
            rs = _scale(ret_ft)
            if op == "mul":
                a = avals[0][0].astype(xp.int64)
                b = avals[1][0].astype(xp.int64)
                data = a * b  # product scale is s1+s2
                ps = _scale(fts[0]) + _scale(fts[1])
                if ps > rs:  # infer capped at MAX_SCALE: round down to rs
                    data = _round_div(xp, data, xp.full_like(data, pow10(ps - rs)))
            else:
                a, b = (lane_as_decimal(xp, d, ft, rs) for (d, _), ft in zip(avals, fts))
                data = a + b if op == "plus" else a - b
        else:
            a, b = (d.astype(xp.int64) for d, _ in avals)
            data = {"plus": lambda: a + b, "minus": lambda: a - b, "mul": lambda: a * b}[op]()
        return data, valid

    return kernel


def _round_div(xp, num, den):
    """Exact integer division rounding half away from zero (den != 0 lanes)."""
    den_safe = xp.where(den == 0, 1, den)
    q = xp.abs(num) // xp.abs(den_safe)
    r = xp.abs(num) - q * xp.abs(den_safe)
    q = q + (2 * r >= xp.abs(den_safe)).astype(xp.int64)
    sign = xp.where((num < 0) != (den_safe < 0), -1, 1)
    return q * sign


def _div_kernel(xp, avals, fts, ret_ft):
    valid = all_valid(xp, avals)
    if ret_ft.is_float():
        a, b = (lane_as_float(xp, d, ft) for (d, _), ft in zip(avals, fts))
        valid = valid & (b != 0)
        return a / xp.where(b == 0, 1.0, b), valid
    rs = _scale(ret_ft)
    s1, s2 = _scale(fts[0]), _scale(fts[1])
    num = avals[0][0].astype(xp.int64) * pow10(rs - s1 + s2)
    den = avals[1][0].astype(xp.int64)
    valid = valid & (den != 0)
    return _round_div(xp, num, den), valid


def _intdiv_kernel(xp, avals, fts, ret_ft):
    valid = all_valid(xp, avals)
    kind, (a, b) = numeric_common(xp, avals, fts)
    if kind == "int2":  # mixed sign domain: float64 approximation
        a, b = int2_as_float(xp, a), int2_as_float(xp, b)
        kind = "float"
    if kind == "float":
        valid = valid & (b != 0)
        q = a / xp.where(b == 0, 1.0, b)
        return xp.trunc(q).astype(xp.int64), valid
    valid = valid & (b != 0)
    bs = xp.where(b == 0, 1, b)
    q = a // bs
    # python/numpy floor-div → truncate toward zero like MySQL DIV
    q = xp.where((q < 0) & (q * bs != a), q + 1, q)
    return q.astype(xp.int64), valid


def _mod_kernel(xp, avals, fts, ret_ft):
    valid = all_valid(xp, avals)
    if ret_ft.is_float():
        a, b = (lane_as_float(xp, d, ft) for (d, _), ft in zip(avals, fts))
        valid = valid & (b != 0)
        bs = xp.where(b == 0, 1.0, b)
        r = a - xp.trunc(a / bs) * bs
        return r, valid
    rs = _scale(ret_ft)
    a, b = (lane_as_decimal(xp, d, ft, rs) for (d, _), ft in zip(avals, fts))
    valid = valid & (b != 0)
    bs = xp.where(b == 0, 1, b)
    q = a // bs
    q = xp.where((q < 0) & (q * bs != a), q + 1, q)  # trunc toward zero
    return a - q * bs, valid


def _unary_minus_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    if ret_ft.is_float():
        return -lane_as_float(xp, d, fts[0]), v
    return -d.astype(xp.int64), v


register(FuncSig("plus", infer_arith("plus"), _arith_kernel("plus"), arity=2))
register(FuncSig("minus", infer_arith("minus"), _arith_kernel("minus"), arity=2))
register(FuncSig("mul", infer_arith("mul"), _arith_kernel("mul"), arity=2))
register(FuncSig("div", infer_div, _div_kernel, arity=2))
register(FuncSig("intdiv", lambda fts: ft_longlong(), _intdiv_kernel, arity=2))
register(FuncSig("mod", infer_arith("plus"), _mod_kernel, arity=2))
register(FuncSig("unaryminus", infer_arith("plus"), _unary_minus_kernel, arity=1))


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------


def _int2_cmp(op, a, b):
    """Lexicographic compare of (class, lo) pairs — exact across the full
    signed+unsigned BIGINT value range."""
    (ha, la), (hb, lb) = a, b
    eq = (ha == hb) & (la == lb)
    lt = (ha < hb) | ((ha == hb) & (la < lb))
    return {
        "eq": lambda: eq,
        "ne": lambda: ~eq,
        "lt": lambda: lt,
        "le": lambda: lt | eq,
        "gt": lambda: ~(lt | eq),
        "ge": lambda: ~lt,
    }[op]()


def _ci_weight1(a, fts):
    """Collation weights for one string lane when the operands' derived
    collation is case-insensitive (ref: expression/collation.go)."""
    from ..mysqltypes import collate as _coll

    c = _coll.resolve(fts)
    if _coll.is_ci(c):
        return _coll.weight_lane(np.atleast_1d(np.asarray(a, dtype=object)), c)
    return a


def _ci_weights(a, b, fts):
    return _ci_weight1(a, fts), _ci_weight1(b, fts)


def _cmp_kernel(op: str):
    def kernel(xp, avals, fts, ret_ft):
        valid = all_valid(xp, avals)
        kind, lanes = numeric_common(xp, avals, fts)
        a, b = lanes
        if kind == "int2":
            return _int2_cmp(op, a, b).astype(xp.int64), valid
        if kind == "str":
            # numpy-only path; device compares dictionary codes instead
            a = np.where(avals[0][1], a, "")
            b = np.where(avals[1][1], b, "")
            a, b = _ci_weights(a, b, fts)
        data = {
            "eq": lambda: a == b,
            "ne": lambda: a != b,
            "lt": lambda: a < b,
            "le": lambda: a <= b,
            "gt": lambda: a > b,
            "ge": lambda: a >= b,
        }[op]()
        return data.astype(xp.int64), valid

    return kernel


for _op in ("eq", "ne", "lt", "le", "gt", "ge"):
    register(FuncSig(_op, infer_bool, _cmp_kernel(_op), arity=2))


def _nulleq_kernel(xp, avals, fts, ret_ft):
    va, vb = avals[0][1], avals[1][1]
    kind, (a, b) = numeric_common(xp, avals, fts)
    if kind == "int2":
        same = _int2_cmp("eq", a, b)
    else:
        if kind == "str":
            a = np.where(va, a, "")
            b = np.where(vb, b, "")
            a, b = _ci_weights(a, b, fts)
        same = a == b
    eq = same & va & vb | (~va & ~vb)
    return eq.astype(xp.int64), xp.ones_like(va)


register(FuncSig("nulleq", infer_bool, _nulleq_kernel, arity=2))  # <=>


def _in_kernel(xp, avals, fts, ret_ft):
    # IN over a value list: any-equal w/ SQL NULL semantics
    valid0 = avals[0][1]
    kind, lanes = numeric_common(xp, avals, fts)
    a = lanes[0]
    if kind == "str":
        a = np.where(valid0, a, "")
        a = _ci_weight1(a, fts)
    hit = None
    any_null = ~valid0
    for (d, v), lane in zip(avals[1:], lanes[1:]):
        if kind == "int2":
            e = _int2_cmp("eq", a, lane) & v
        else:
            if kind == "str":
                b = np.where(v, lane, "")
                b = _ci_weight1(b, fts)
            else:
                b = lane
            e = (a == b) & v
        hit = e if hit is None else (hit | e)
        any_null = any_null | ~v
    valid = valid0 & (hit | ~any_null)
    return hit.astype(xp.int64), valid


register(FuncSig("in", infer_bool, _in_kernel, varargs=True, arity=(2, None)))


# ---------------------------------------------------------------------------
# 3-valued logic
# ---------------------------------------------------------------------------


def _logic_and(xp, avals, fts, ret_ft):
    (da, va), (db, vb) = avals
    ta, tb = da != 0, db != 0
    false_any = (va & ~ta) | (vb & ~tb)
    valid = (va & vb) | false_any
    return (ta & tb & va & vb).astype(xp.int64), valid


def _logic_or(xp, avals, fts, ret_ft):
    (da, va), (db, vb) = avals
    ta, tb = (da != 0) & va, (db != 0) & vb
    true_any = ta | tb
    valid = (va & vb) | true_any
    return true_any.astype(xp.int64), valid


def _logic_xor(xp, avals, fts, ret_ft):
    (da, va), (db, vb) = avals
    return ((da != 0) != (db != 0)).astype(xp.int64), va & vb


def _logic_not(xp, avals, fts, ret_ft):
    d, v = avals[0]
    return (d == 0).astype(xp.int64), v


register(FuncSig("and", infer_bool, _logic_and, arity=2))
register(FuncSig("or", infer_bool, _logic_or, arity=2))
register(FuncSig("xor", infer_bool, _logic_xor, arity=2))
register(FuncSig("not", infer_bool, _logic_not, arity=1))


def _isnull_kernel(xp, avals, fts, ret_ft):
    _, v = avals[0]
    return (~v).astype(xp.int64), xp.ones_like(v)


def _istrue_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    return ((d != 0) & v).astype(xp.int64), xp.ones_like(v)


def _isfalse_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    return ((d == 0) & v).astype(xp.int64), xp.ones_like(v)


register(FuncSig("isnull", infer_bool, _isnull_kernel, arity=1))
register(FuncSig("istrue", infer_bool, _istrue_kernel, arity=1))
register(FuncSig("isfalse", infer_bool, _isfalse_kernel, arity=1))


# ---------------------------------------------------------------------------
# control flow: IF / IFNULL / COALESCE / CASE
# ---------------------------------------------------------------------------


def _coerce_to(xp, aval, ft: FieldType, ret_ft: FieldType):
    """Coerce a branch lane to the merged result type."""
    d, v = aval
    if ret_ft.is_float():
        return lane_as_float(xp, d, ft), v
    if ret_ft.is_decimal():
        return lane_as_decimal(xp, d, ft, _scale(ret_ft)), v
    if ret_ft.is_string():
        return d, v
    return d.astype(xp.int64), v


def _if_kernel(xp, avals, fts, ret_ft):
    (dc, vc) = avals[0]
    cond = (dc != 0) & vc
    (a, va) = _coerce_to(xp, avals[1], fts[1], ret_ft)
    (b, vb) = _coerce_to(xp, avals[2], fts[2], ret_ft)
    if ret_ft.is_string() and xp is np:
        data = np.where(cond, a, b)
    else:
        data = xp.where(cond, a, b)
    return data, xp.where(cond, va, vb)


def _ifnull_kernel(xp, avals, fts, ret_ft):
    (a, va) = _coerce_to(xp, avals[0], fts[0], ret_ft)
    (b, vb) = _coerce_to(xp, avals[1], fts[1], ret_ft)
    data = xp.where(va, a, b)
    return data, va | vb


def _coalesce_kernel(xp, avals, fts, ret_ft):
    lanes = [_coerce_to(xp, av, ft, ret_ft) for av, ft in zip(avals, fts)]
    data, valid = lanes[-1]
    for a, va in reversed(lanes[:-1]):
        data = xp.where(va, a, data)
        valid = va | valid
    return data, valid


def _case_kernel(xp, avals, fts, ret_ft):
    """case(when1, then1, when2, then2, ..., [else]) — pre-desugared."""
    npairs = len(avals) // 2
    has_else = len(avals) % 2 == 1
    if has_else:
        data, valid = _coerce_to(xp, avals[-1], fts[-1], ret_ft)
    else:
        d0, v0 = _coerce_to(xp, avals[1], fts[1], ret_ft)
        data, valid = xp.zeros_like(d0), xp.zeros_like(v0)
    for i in reversed(range(npairs)):
        dc, vc = avals[2 * i]
        cond = (dc != 0) & vc
        dt, vt = _coerce_to(xp, avals[2 * i + 1], fts[2 * i + 1], ret_ft)
        data = xp.where(cond, dt, data)
        valid = xp.where(cond, vt, valid)
    return data, valid


def _infer_if(fts):
    return merge_types(fts[1:])


def _infer_case(fts):
    np_ = len(fts) // 2
    branches = [fts[2 * i + 1] for i in range(np_)]
    if len(fts) % 2:
        branches.append(fts[-1])
    return merge_types(branches)


register(FuncSig("if", _infer_if, _if_kernel, arity=3))
register(FuncSig("ifnull", lambda fts: merge_types(fts), _ifnull_kernel, arity=2))
register(FuncSig("coalesce", lambda fts: merge_types(fts), _coalesce_kernel, varargs=True, arity=(1, None)))
register(FuncSig("case", _infer_case, _case_kernel, varargs=True, arity=(2, None)))


# ---------------------------------------------------------------------------
# math / rounding
# ---------------------------------------------------------------------------


def _abs_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    return xp.abs(d), v


def _f1(fn, domain=None):
    def kernel(xp, avals, fts, ret_ft):
        d, v = avals[0]
        x = lane_as_float(xp, d, fts[0])
        if domain is not None:
            ok = domain(xp, x)
            v = v & ok
            x = xp.where(ok, x, 1.0)
        return getattr(xp, fn)(x), v

    return kernel


def _ceil_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    ft = fts[0]
    if ft.is_float():
        return xp.ceil(d.astype(xp.float64)), v
    if ft.is_decimal():
        s = pow10(_scale(ft))
        return -((-d.astype(xp.int64)) // s), v
    return d.astype(xp.int64), v


def _floor_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    ft = fts[0]
    if ft.is_float():
        return xp.floor(d.astype(xp.float64)), v
    if ft.is_decimal():
        return d.astype(xp.int64) // pow10(_scale(ft)), v
    return d.astype(xp.int64), v


def _const_frac(avals):
    """Scalar frac from the (guaranteed-constant) second arg lane."""
    fd = avals[1][0]
    return int(fd[0]) if getattr(fd, "ndim", 0) else int(fd)


def _round_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    ft = fts[0]
    if ret_ft.is_float():
        # float path supports per-row (non-constant) frac
        x = lane_as_float(xp, d, ft)
        if len(avals) > 1:
            p = 10.0 ** avals[1][0].astype(xp.float64)
            v = v & avals[1][1]
        else:
            p = 1.0
        scaled = x * p
        r = xp.where(scaled >= 0, xp.floor(scaled + 0.5), xp.ceil(scaled - 0.5))
        return r / p, v
    # int/decimal paths require constant frac (enforced by post_infer)
    frac = _const_frac(avals) if len(avals) > 1 else 0
    if not ft.is_decimal():  # int input
        x = d.astype(xp.int64)
        if frac >= 0:
            return x, v
        p = pow10(-frac)
        return _round_div(xp, x, xp.full_like(x, p)) * p, v
    s = _scale(ft)
    x = d.astype(xp.int64)
    if frac >= s:  # no-op numerically; ret scale == s
        return x, v
    p = pow10(s - frac)  # frac may be negative: rounds past the point
    q = _round_div(xp, x, xp.full_like(x, p))
    if frac < 0:
        q = q * pow10(-frac)  # result has scale 0
    return q, v


def _infer_round(fts):
    ft = fts[0]
    if ft.is_float() or ft.is_string():
        return ft_double()
    if ft.is_decimal():
        return ft_decimal(30, _scale(ft))  # post_infer narrows using const frac
    return ft_longlong()


def _round_post_infer(args, ret_ft):
    """Narrow the decimal result scale once the const frac arg is known.

    Non-constant frac is only supported on the float path (the lane kernel
    needs a static scale for int/decimal inputs).
    """
    from .expression import Constant

    if not ret_ft.is_decimal():
        return ret_ft
    s = _scale(args[0].ret_type)
    frac = 0
    if len(args) > 1:
        if not isinstance(args[1], Constant):
            return ft_double()  # dynamic frac: degrade to the float path
        frac = args[1].value.to_int()
    return ft_decimal(30, min(max(frac, 0), s))


register(FuncSig("abs", infer_first, _abs_kernel, arity=1))
register(FuncSig("round", _infer_round, _round_kernel, arity=(1, 2), post_infer=_round_post_infer))


def _truncate_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    ft = fts[0]
    if ret_ft.is_float():
        x = lane_as_float(xp, d, ft)
        p = 10.0 ** avals[1][0].astype(xp.float64)
        v = v & avals[1][1]
        return xp.trunc(x * p) / p, v
    frac = _const_frac(avals)
    if not ft.is_decimal():
        x = d.astype(xp.int64)
        if frac >= 0:
            return x, v
        p = pow10(-frac)
        return (xp.sign(x) * (xp.abs(x) // p)) * p, v
    s = _scale(ft)
    x = d.astype(xp.int64)
    if frac >= s:
        return x, v
    p = pow10(s - frac)
    q = xp.sign(x) * (xp.abs(x) // p)
    if frac < 0:
        q = q * pow10(-frac)
    return q, v


register(FuncSig("truncate", _infer_round, _truncate_kernel, arity=2, post_infer=_round_post_infer))
register(FuncSig("ceil", lambda fts: ft_longlong() if not fts[0].is_float() else ft_double(), _ceil_kernel, arity=1))
register(FuncSig("ceiling", lambda fts: ft_longlong() if not fts[0].is_float() else ft_double(), _ceil_kernel, arity=1))
register(FuncSig("floor", lambda fts: ft_longlong() if not fts[0].is_float() else ft_double(), _floor_kernel, arity=1))
register(FuncSig("sqrt", lambda fts: ft_double(), _f1("sqrt", domain=lambda xp, x: x >= 0), arity=1))
register(FuncSig("exp", lambda fts: ft_double(), _f1("exp"), arity=1))
register(FuncSig("ln", lambda fts: ft_double(), _f1("log", domain=lambda xp, x: x > 0), arity=1))
register(FuncSig("log", lambda fts: ft_double(), _f1("log", domain=lambda xp, x: x > 0), arity=1))
register(FuncSig("log2", lambda fts: ft_double(), _f1("log2", domain=lambda xp, x: x > 0), arity=1))
register(FuncSig("log10", lambda fts: ft_double(), _f1("log10", domain=lambda xp, x: x > 0), arity=1))
register(FuncSig("sin", lambda fts: ft_double(), _f1("sin"), arity=1))
register(FuncSig("cos", lambda fts: ft_double(), _f1("cos"), arity=1))
register(FuncSig("tan", lambda fts: ft_double(), _f1("tan"), arity=1))
register(FuncSig("sign", lambda fts: ft_longlong(), lambda xp, a, f, r: (xp.sign(lane_as_float(xp, a[0][0], f[0])).astype(xp.int64), a[0][1]), arity=1))


def _pow_kernel(xp, avals, fts, ret_ft):
    a = lane_as_float(xp, avals[0][0], fts[0])
    b = lane_as_float(xp, avals[1][0], fts[1])
    return xp.power(a, b), all_valid(xp, avals)


register(FuncSig("pow", lambda fts: ft_double(), _pow_kernel, arity=2))
register(FuncSig("power", lambda fts: ft_double(), _pow_kernel, arity=2))


def _minmax_lanes(xp, avals, fts):
    kind, lanes = numeric_common(xp, avals, fts)
    if kind == "int2":
        lanes = [int2_as_float(xp, p) for p in lanes]
    if kind == "str":
        # mask NULL slots so object-lane comparison never sees None
        lanes = [np.where(v, l, "") for (_, v), l in zip(avals, lanes)]
    return lanes


def _greatest_kernel(xp, avals, fts, ret_ft):
    valid = all_valid(xp, avals)
    lanes = _minmax_lanes(xp, avals, fts)
    data = lanes[0]
    for l in lanes[1:]:
        data = xp.maximum(data, l)
    return _coerce_greatest(xp, data, ret_ft), valid


def _least_kernel(xp, avals, fts, ret_ft):
    valid = all_valid(xp, avals)
    lanes = _minmax_lanes(xp, avals, fts)
    data = lanes[0]
    for l in lanes[1:]:
        data = xp.minimum(data, l)
    return _coerce_greatest(xp, data, ret_ft), valid


def _coerce_greatest(xp, data, ret_ft):
    if ret_ft.is_float():
        return data.astype(xp.float64)
    return data


register(FuncSig("greatest", lambda fts: merge_types(fts), _greatest_kernel, varargs=True, arity=(2, None)))
register(FuncSig("least", lambda fts: merge_types(fts), _least_kernel, varargs=True, arity=(2, None)))


# ---------------------------------------------------------------------------
# date/time extraction over packed int64 (chronological-order packing)
# ---------------------------------------------------------------------------


def _time_extract(divisor: int, modulus: int | None):
    def kernel(xp, avals, fts, ret_ft):
        d, v = avals[0]
        x = d.astype(xp.int64) // divisor
        if modulus is not None:
            x = x % modulus
        return x, v

    return kernel


from ..mysqltypes import coretime as _ct

register(FuncSig("year", lambda fts: ft_longlong(), _time_extract(_ct.DIV_YEAR, None), arity=1))
register(FuncSig("month", lambda fts: ft_longlong(), _time_extract(_ct.DIV_MONTH, _ct.MOD_MONTH), arity=1))
register(FuncSig("day", lambda fts: ft_longlong(), _time_extract(_ct.DIV_DAY, _ct.MOD_DAY), arity=1))
register(FuncSig("dayofmonth", lambda fts: ft_longlong(), _time_extract(_ct.DIV_DAY, _ct.MOD_DAY), arity=1))
register(FuncSig("hour", lambda fts: ft_longlong(), _time_extract(_ct.DIV_HOUR, _ct.MOD_HOUR), arity=1))
register(FuncSig("minute", lambda fts: ft_longlong(), _time_extract(_ct.DIV_MINUTE, _ct.MOD_MINUTE), arity=1))
register(FuncSig("second", lambda fts: ft_longlong(), _time_extract(_ct.DIV_SECOND, _ct.MOD_SECOND), arity=1))
register(FuncSig("microsecond", lambda fts: ft_longlong(), _time_extract(1, _ct.MOD_MICRO), arity=1))


# ---------------------------------------------------------------------------
# strings (host-only kernels; device handles eq/cmp via dict codes)
# ---------------------------------------------------------------------------


def _obj_map(fn):
    """Lift a python scalar function over object lanes (numpy host only)."""

    def kernel(xp, avals, fts, ret_ft):
        assert xp is np, "string kernel is host-only"
        valid = all_valid(np, avals)
        n = len(avals[0][0])
        out = np.empty(n, dtype=object)
        idx = np.nonzero(valid)[0]
        if valid.ndim == 0:
            valid = np.asarray([bool(valid)])
        else:
            valid = valid.copy()
        args_data = [d for d, _ in avals]
        for i in idx:
            try:
                out[i] = fn(*[d[i] for d in args_data])
            except TiDBErrorBase:
                raise
            except Exception:  # noqa: BLE001 — malformed input → SQL NULL
                valid[i] = False
        return out, valid

    return kernel


def _as_str(v):
    return v if isinstance(v, str) else (v.decode("utf8", "replace") if isinstance(v, (bytes, bytearray)) else str(v))


register(FuncSig("concat", lambda fts: ft_varchar(), _obj_map(lambda *xs: "".join(_as_str(x) for x in xs)), pushable=False, varargs=True))
register(FuncSig("lower", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x).lower()), pushable=False, arity=1))
register(FuncSig("upper", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x).upper()), pushable=False, arity=1))
register(FuncSig("trim", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x).strip()), pushable=False, arity=1))
register(FuncSig("ltrim", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x).lstrip()), pushable=False, arity=1))
register(FuncSig("rtrim", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x).rstrip()), pushable=False, arity=1))
register(FuncSig("reverse", lambda fts: ft_varchar(), _obj_map(lambda x: _as_str(x)[::-1]), pushable=False, arity=1))


def _length_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    out = np.zeros(len(d), dtype=np.int64)
    for i in np.nonzero(v)[0]:
        s = d[i]
        out[i] = len(s.encode("utf8")) if isinstance(s, str) else len(s)
    return out, v


def _char_length_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    out = np.zeros(len(d), dtype=np.int64)
    for i in np.nonzero(v)[0]:
        out[i] = len(_as_str(d[i]))
    return out, v


register(FuncSig("length", lambda fts: ft_longlong(), _length_kernel, pushable=False, arity=1))
register(FuncSig("char_length", lambda fts: ft_longlong(), _char_length_kernel, pushable=False, arity=1))


def _substr(s, pos, ln=None):
    s = _as_str(s)
    pos = int(pos)
    if pos == 0:
        return ""
    start = pos - 1 if pos > 0 else len(s) + pos
    if start < 0:
        return ""
    end = len(s) if ln is None else start + max(int(ln), 0)
    return s[start:end]


register(FuncSig("substr", lambda fts: ft_varchar(), _obj_map(_substr), pushable=False, varargs=True, arity=(2, 3)))
register(FuncSig("substring", lambda fts: ft_varchar(), _obj_map(_substr), pushable=False, varargs=True, arity=(2, 3)))
register(FuncSig("left", lambda fts: ft_varchar(), _obj_map(lambda s, n: _as_str(s)[: max(int(n), 0)]), pushable=False))
register(FuncSig("right", lambda fts: ft_varchar(), _obj_map(lambda s, n: _as_str(s)[-max(int(n), 0) :] if int(n) > 0 else ""), pushable=False))
register(FuncSig("replace", lambda fts: ft_varchar(), _obj_map(lambda s, a, b: _as_str(s).replace(_as_str(a), _as_str(b))), pushable=False, varargs=True))


def like_to_regex(pat: str, escape: str = "\\") -> re.Pattern:
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == escape and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.S | re.I)


def _like_kernel(xp, avals, fts, ret_ft):
    (d, v), (pd, pv) = avals[0], avals[1]
    valid = v & pv
    out = np.zeros(len(d), dtype=np.int64)
    idx = np.nonzero(valid)[0]
    if len(idx):
        # pattern is near-always constant; compile per distinct pattern
        cache: dict = {}
        for i in idx:
            pat = _as_str(pd[i])
            rx = cache.get(pat)
            if rx is None:
                rx = cache[pat] = like_to_regex(pat)
            out[i] = 1 if rx.match(_as_str(d[i])) else 0
    return out, valid


register(FuncSig("like", infer_bool, _like_kernel, pushable=False, arity=2))


# ---------------------------------------------------------------------------
# casts — one sig per target family (ref: expression/builtin_cast.go)
# ---------------------------------------------------------------------------


def _cast_kernel(xp, avals, fts, ret_ft):
    d, v = avals[0]
    src = fts[0]
    if ret_ft.is_float():
        return lane_as_float(xp, d, src), v
    if ret_ft.is_decimal():
        rs = _scale(ret_ft)
        if src.is_float():
            x = d.astype(xp.float64) * pow10(rs)
            r = xp.where(x >= 0, xp.floor(x + 0.5), xp.ceil(x - 0.5))
            return r.astype(xp.int64), v
        if src.is_string():
            out = np.zeros(len(d), dtype=np.int64)
            from ..mysqltypes.datum import Datum

            for i in np.nonzero(v)[0]:
                out[i] = Datum.s(_as_str(d[i])).to_dec().rescale(rs).value
            return out, v
        return lane_as_decimal(xp, d, src, rs), v
    if ret_ft.is_string():
        assert xp is np
        out = np.empty(len(d), dtype=object)
        for i in np.nonzero(v)[0]:
            if src.is_decimal():
                from ..mysqltypes.mydecimal import Dec

                out[i] = str(Dec(int(d[i]), _scale(src)))
            elif src.is_time():
                from ..mysqltypes.coretime import format_time

                out[i] = format_time(int(d[i]), is_date=src.tp == TypeCode.Date, fsp=max(src.decimal, 0))
            else:
                out[i] = _as_str(d[i]) if src.is_string() else str(d[i])
        return out, v
    # int target
    if src.is_float():
        x = d.astype(xp.float64)
        r = xp.where(x >= 0, xp.floor(x + 0.5), xp.ceil(x - 0.5))
        return r.astype(xp.int64), v
    if src.is_decimal():
        return _round_div(xp, d.astype(xp.int64), xp.full_like(d.astype(xp.int64), pow10(_scale(src)))), v
    if src.is_string():
        from ..mysqltypes.datum import Datum

        out = np.zeros(len(d), dtype=np.int64)
        for i in np.nonzero(v)[0]:
            out[i] = Datum.s(_as_str(d[i])).to_int()
        return out, v
    return d.astype(xp.int64), v


CAST_SIG = FuncSig("cast", infer_first, _cast_kernel)
register(CAST_SIG)


# extended registry: date arithmetic, string/math breadth, JSON
from . import builtins_ext  # noqa: E402,F401  (registration side effects)

"""Per-session info visible to builtin kernels (ref: sessionctx.Context
reaching builtin_info.go via the expression EvalContext).

The Session publishes a mutable dict through a contextvar at construction
and keeps it current per statement; info builtins (USER(), FOUND_ROWS(),
GET_LOCK(), ...) read it at eval time. Defaults keep the kernels usable
outside a session (tests, direct expression eval)."""

from __future__ import annotations

import contextvars

CURRENT: contextvars.ContextVar[dict] = contextvars.ContextVar("tidb_session_info")


def get(key: str, default=None):
    try:
        info = CURRENT.get()
    except LookupError:
        return default
    return info.get(key, default)


def now_epoch(vars_dict: dict | None = None) -> float:
    """NOW()'s clock: the `timestamp` sysvar freezes it when set (MySQL
    SET timestamp=N; replication/test determinism), else wall clock.
    Shared by plan-time constant folding and the runtime kernels so the
    two can never disagree on freeze semantics."""
    import time

    if vars_dict is None:
        vars_dict = get("vars") or {}
    frozen = vars_dict.get("timestamp", "")
    if frozen not in ("", "0", None):
        try:
            return float(frozen)
        except ValueError:
            pass
    return time.time()

"""Per-session info visible to builtin kernels (ref: sessionctx.Context
reaching builtin_info.go via the expression EvalContext).

The Session publishes a mutable dict through a contextvar at construction
and keeps it current per statement; info builtins (USER(), FOUND_ROWS(),
GET_LOCK(), ...) read it at eval time. Defaults keep the kernels usable
outside a session (tests, direct expression eval)."""

from __future__ import annotations

import contextvars

CURRENT: contextvars.ContextVar[dict] = contextvars.ContextVar("tidb_session_info")


def get(key: str, default=None):
    try:
        info = CURRENT.get()
    except LookupError:
        return default
    return info.get(key, default)

"""Resource groups — RU token buckets with priority, persisted in the
catalog (ref: the reference's resource control: ddl_api.go
CreateResourceGroup + pkg/resourcegroup; RU model per the Request Unit
accounting of resource_manager, radically simplified to a local bucket —
this store has no cross-keyspace GAC to reconcile with).

A group is a spec dict in the meta KV (`m:rg:<name>`, see catalog/meta.py)
plus live runtime state (the token bucket). The manager caches specs the
way `bindinfo.BindingCache` caches bindings: a notify version bumped on
every DDL, re-scanned lazily on first use after the bump, so every session
over one store observes one consistent group table. Buckets survive cache
reloads (debt must not reset on unrelated DDL) unless the group's rate or
burst changed.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..errors import ResourceGroupExists, ResourceGroupNotExists

# admission order: HIGH beats MEDIUM beats LOW whenever slots are scarce
# (the reference's tri-level priority for resource groups)
PRIORITIES = {"LOW": 1, "MEDIUM": 8, "HIGH": 16}

DEFAULT_GROUP = "default"


class TokenBucket:
    """RU bucket with post-hoc debits: admission charges an estimate, the
    task settles the true cost after running, so tokens may go negative
    (debt). A group is admissible while it holds no debt; refill pays debt
    down at `rate` RU/s. rate <= 0 means unlimited (the default group).

    `burstable` buckets (PR 20) borrow from MEASURED headroom instead of
    being unlimited: while in debt they stay admissible only when the
    caller reports the store has free capacity (`admissible(headroom=...)`
    — AdmissionScheduler passes its slot utilization under BORROW_HEADROOM).
    Debt still accrues on every run and is repaid at the reserved rate, so
    a saturated store throttles a burstable group at its ru_per_sec."""

    def __init__(self, rate: float, burst: float | None = None,
                 burstable: bool = False):
        self.rate = float(rate)
        self.burstable = burstable
        self.capacity = float(burst) if burst else max(self.rate, 1.0)
        self.tokens = self.capacity
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        dt = now - self._t
        self._t = now
        if self.rate > 0 and dt > 0:
            self.tokens = min(self.tokens + dt * self.rate, self.capacity)

    def available(self, now: float | None = None) -> float:
        with self._lock:
            self._refill_locked(time.monotonic() if now is None else now)
            return self.tokens

    def admissible(self, now: float | None = None, headroom: bool = False) -> bool:
        if self.rate <= 0:
            return True
        if self.available(now) > 0.0:
            return True
        return self.burstable and headroom

    def debit(self, n: float) -> None:
        if self.rate <= 0:
            return
        with self._lock:
            self._refill_locked(time.monotonic())
            self.tokens -= n

    def credit(self, n: float) -> None:
        if self.rate <= 0:
            return
        with self._lock:
            self._refill_locked(time.monotonic())
            self.tokens = min(self.tokens + n, self.capacity)


@dataclass
class ResourceGroup:
    name: str
    ru_per_sec: int = 0  # 0 = unlimited
    priority: str = "MEDIUM"
    burstable: bool = False
    # QUERY_LIMIT runaway spec (sched/runaway.py): exec_elapsed_ms / ru /
    # processed_rows thresholds + action + watch_ms; None/{} = no limit
    query_limit: dict | None = None
    bucket: TokenBucket = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self):
        if self.bucket is None:
            # burstable groups borrow beyond their rate only while the
            # admission scheduler measures free device slots (the bucket's
            # burstable flag + the scheduler's headroom report, PR 20);
            # ru_per_sec = 0 stays a genuinely unlimited bucket either way
            self.bucket = TokenBucket(self.ru_per_sec, burstable=self.burstable)
        self._ql_parsed = False
        self._ql = None

    def parsed_limit(self):
        """Parsed QueryLimit (cached — checked once per statement)."""
        if not self._ql_parsed:
            from .runaway import QueryLimit

            self._ql = QueryLimit.from_spec(self.query_limit or {})
            self._ql_parsed = True
        return self._ql

    @property
    def priority_value(self) -> int:
        return PRIORITIES.get(self.priority, PRIORITIES["MEDIUM"])

    def to_spec(self) -> dict:
        return {
            "name": self.name,
            "ru_per_sec": self.ru_per_sec,
            "priority": self.priority,
            "burstable": self.burstable,
            "query_limit": self.query_limit,
        }

    @classmethod
    def from_spec(cls, d: dict) -> "ResourceGroup":
        return cls(
            name=d["name"],
            ru_per_sec=int(d.get("ru_per_sec", 0)),
            priority=d.get("priority", "MEDIUM"),
            burstable=bool(d.get("burstable", False)),
            query_limit=d.get("query_limit") or None,
        )


class ResourceGroupManager:
    """Catalog-backed group table shared by every session over one store."""

    def __init__(self, storage):
        self.storage = storage
        self.notify_version = 0
        self._version = -1
        self._lock = threading.Lock()
        self._groups: dict[str, ResourceGroup] = {}

    # --- read side ---------------------------------------------------------

    def _ensure(self) -> None:
        with self._lock:
            v = self.notify_version
            if v == self._version:
                return
            from ..catalog.meta import Meta

            txn = self.storage.begin()
            try:
                specs = Meta(txn).list_resource_groups()
            finally:
                txn.rollback()
            groups: dict[str, ResourceGroup] = {}
            for spec in specs:
                g = ResourceGroup.from_spec(spec)
                old = self._groups.get(g.name)
                if old is not None and (old.ru_per_sec, old.burstable) == (
                    g.ru_per_sec, g.burstable,
                ):
                    g.bucket = old.bucket  # keep accumulated debt/credit
                groups[g.name] = g
            self._groups = groups
            self._version = v

    def get(self, name: str) -> ResourceGroup:
        """Admission-time lookup: unknown names fall back to the default
        group (a group dropped mid-flight must not fail running queries —
        the reference degrades to `default` the same way)."""
        name = (name or DEFAULT_GROUP).lower()
        if name == DEFAULT_GROUP:
            return self.default
        self._ensure()
        return self._groups.get(name) or self.default

    def exists(self, name: str) -> bool:
        if (name or "").lower() == DEFAULT_GROUP:
            return True
        self._ensure()
        return name.lower() in self._groups

    def list(self) -> list[ResourceGroup]:
        self._ensure()
        out = [self.default]
        out.extend(self._groups[k] for k in sorted(self._groups))
        return out

    @property
    def default(self) -> ResourceGroup:
        if not hasattr(self, "_default"):
            self._default = ResourceGroup(DEFAULT_GROUP, 0, "MEDIUM", True)
        return self._default

    # --- DDL side ----------------------------------------------------------
    # `spec` carries only the options the statement named (None = keep);
    # ALTER merges over the stored spec, CREATE fills defaults.

    def create(self, name: str, spec: dict, if_not_exists: bool = False) -> None:
        self._mutate("create", name, spec, if_not_exists=if_not_exists)

    def alter(self, name: str, spec: dict) -> None:
        self._mutate("alter", name, spec)

    def drop(self, name: str, if_exists: bool = False) -> None:
        self._mutate("drop", name, {}, if_exists=if_exists)

    def _mutate(self, kind: str, name: str, spec: dict,
                if_not_exists: bool = False, if_exists: bool = False) -> None:
        from ..catalog.meta import Meta

        name = name.lower()
        opts = {k: v for k, v in spec.items() if v is not None}
        if name == DEFAULT_GROUP:
            if kind == "alter":
                # the default group is synthetic: retune it in memory.
                # Naming RU_PER_SEC without BURSTABLE turns bursting off —
                # otherwise the headroom borrow would keep the new limit
                # soft whenever the store is idle, which is rarely what
                # an ALTER that names a rate intends
                d = self.default
                d.ru_per_sec = int(opts.get("ru_per_sec", d.ru_per_sec))
                d.priority = opts.get("priority", d.priority)
                if "burstable" in opts:
                    d.burstable = bool(opts["burstable"])
                elif "ru_per_sec" in opts:
                    d.burstable = False
                if "query_limit" in opts:
                    # {} is the parsed QUERY_LIMIT=NULL (clear) sentinel
                    d.query_limit = opts["query_limit"] or None
                    d._ql_parsed = False
                d.bucket = TokenBucket(d.ru_per_sec, burstable=d.burstable)
                self.bump()
                return
            if kind == "create":
                if if_not_exists:
                    return
                raise ResourceGroupExists(f"resource group '{name}' already exists")
            raise ResourceGroupNotExists(f"resource group '{name}' is reserved")
        txn = self.storage.begin()
        try:
            m = Meta(txn)
            cur = m.resource_group(name)
            if kind == "create":
                if cur is not None:
                    if if_not_exists:
                        txn.rollback()
                        return
                    raise ResourceGroupExists(f"resource group '{name}' already exists")
                full = ResourceGroup(name).to_spec()
                full.update(opts)
                m.put_resource_group(full)
            elif kind == "alter":
                if cur is None:
                    raise ResourceGroupNotExists(f"resource group '{name}' does not exist")
                merged = dict(cur)
                merged.update(opts)
                m.put_resource_group(merged)
            else:  # drop
                if cur is None:
                    if if_exists:
                        txn.rollback()
                        return
                    raise ResourceGroupNotExists(f"resource group '{name}' does not exist")
                m.drop_resource_group(name)
            txn.commit()
        except Exception:
            try:
                txn.rollback()
            except Exception:  # noqa: BLE001 — already committed/rolled back
                pass
            raise
        self.bump()

    def bump(self) -> None:
        with self._lock:
            self.notify_version += 1

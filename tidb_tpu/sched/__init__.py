"""Resource control for the cop path (ref: the reference's resource
groups + unified read pool; SURVEY §5.8 names the cop client seam).

Three layers, one facade:

  ResourceGroupManager — RU-style token buckets + priority, DDL-managed
      (`CREATE/ALTER/DROP RESOURCE GROUP`), persisted in the catalog meta
      KV and cached per store like bindinfo.
  AdmissionScheduler — inline admission gate every cop-task execution
      passes through: per-priority wait queues, RU debt checks, deadline/
      KILL-aware waiting, hard backpressure beyond MAX_QUEUE.
  LaunchBatcher — cross-session micro-batching of compatible device
      launches (same DAG digest + tile bucket): dedup of identical
      snapshot reads plus one-fetch grouped dispatch via
      `TPUEngine.execute_many`.

One `ResourceController` hangs off each `Storage` (`Storage.sched`), so
every session over a store shares the same admission state, the same
device-launch batcher AND the same TPU engine (one XLA program cache per
store instead of one per session — compatible launches can only coalesce
when they share compiled programs).
"""

from __future__ import annotations

import threading

from .batcher import LaunchBatcher
from .runaway import QueryLimit, RunawayChecker, RunawayManager
from .resource_group import (
    DEFAULT_GROUP,
    PRIORITIES,
    ResourceGroup,
    ResourceGroupManager,
    TokenBucket,
)
from .scheduler import (
    AdmissionScheduler,
    SchedCtx,
    Ticket,
    raise_if_interrupted,
    ru_cost,
    sleep_interruptible,
)

__all__ = [
    "AdmissionScheduler", "DEFAULT_GROUP", "LaunchBatcher", "PRIORITIES",
    "QueryLimit", "ResourceController", "ResourceGroup",
    "ResourceGroupManager", "RunawayChecker", "RunawayManager", "SchedCtx",
    "Ticket", "TokenBucket", "raise_if_interrupted", "ru_cost",
    "sleep_interruptible",
]


class ResourceController:
    """Per-store facade: groups + scheduler + batcher + runaway watchdog
    + shared TPU engine."""

    def __init__(self, storage):
        self.storage = storage
        self.groups = ResourceGroupManager(storage)
        self.scheduler = AdmissionScheduler(self.groups)
        self.batcher = LaunchBatcher()
        self.runaway = RunawayManager(self)
        self._tpu = None
        self._lock = threading.Lock()

    @property
    def tpu_engine(self):
        if self._tpu is None:
            with self._lock:
                if self._tpu is None:
                    from ..copr.tpu_engine import TPUEngine

                    self._tpu = TPUEngine()
        return self._tpu

"""Cross-session device-launch micro-batcher.

Per-task device dispatch is the cop-path bottleneck (round-5 verdict:
p50 at 0.15x of the host engine): every task pays its own jit-call
dispatch plus a blocking device→host fetch. Tensor-runtime query engines
win by amortizing launch cost over bucketed batches (arXiv:2203.01877
§4.2); this batcher applies the same move across sessions.

Concurrent cop tasks that lower to the SAME compiled program — same DAG
digest, same padded tile count (the static-shape bucket the jit cache is
keyed on) — coalesce into one launch group. The group leader waits a
microscopic window for followers, then

  * tier 1 (dedup): tasks over the identical data snapshot (same digest,
    table version and handle span) execute ONCE and share the chunk — the
    same sharing rule the cop result cache already applies, without its
    min-scan-rows admission gate;
  * tier 2 (launch coalescing): remaining tasks dispatch back-to-back
    through `TPUEngine.execute_many`, which defers every device→host
    fetch to ONE `device_get` over the whole group.

Every task still runs its own per-task compiled program over its own
batch, so results are bit-identical to serial `execute` calls by
construction (no cross-task reduction reordering).

A solo task (nothing else in flight) bypasses the batcher entirely:
zero added latency on the uncontended path.
"""

from __future__ import annotations

import logging
import threading
import time

from ..errors import MemoryQuotaExceeded
from ..utils import memory
from ..utils import metrics as M
from ..utils import timeline as TL
from ..utils import tracing
from ..utils.failpoint import inject as _fp

log = logging.getLogger("tidb_tpu.sched")


class _Job:
    __slots__ = ("dag", "batch", "dedup_key", "result", "exc", "followers", "mode",
                 "trace", "parent_id", "client", "mem")

    def __init__(self, dag, batch, dedup_key, client=None):
        self.dag = dag
        self.batch = batch
        self.dedup_key = dedup_key
        self.result = None
        self.exc = None
        self.followers: list["_Job"] = []
        self.mode = "leader"
        # fan-out attribution: the waiter's statement trace + the span the
        # shared launch span should hang under in THAT trace, captured on
        # the waiter's own thread at enqueue time
        self.trace = tracing.current_trace()
        self.parent_id = self.trace.current_parent() if self.trace is not None else 0
        # the waiter's CopClient: launch-wide device counters fan out
        # into every participating client's store-level `stats` (EXPLAIN
        # ANALYZE's `device:` line), once per client per launch
        self.client = client
        # the waiter's statement MemTracker, captured on its own thread:
        # the per-job serial fallback rebinds it so one statement's
        # quota/server-limit error can never poison co-batched neighbors
        self.mem = memory.current_tracker()


class _Group:
    __slots__ = ("jobs", "n_dedup", "done", "closed")

    def __init__(self):
        self.jobs: list[_Job] = []
        self.n_dedup = 0
        self.done = threading.Event()
        self.closed = False


class LaunchBatcher:
    WINDOW_S = 0.002  # follower collection window; >> jit dispatch, << a launch
    WAIT_TIMEOUT_S = 120.0  # follower safety valve (leader crashed hard)

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict[tuple, _Group] = {}
        self._inflight = 0

    def execute(self, engine, dag, batch, dedup_key=None, stats=None, client=None,
                lane=None):
        """Run one cop DAG over one batch through the engine, coalescing
        with concurrent compatible tasks ON ONE DEVICE RUNNER LANE: the
        placement policy (engine.place — residency affinity, spill to
        idle lanes under load, breaker gating on the client path) picks
        the lane up front, groups key on it, and sibling lanes launch in
        parallel. `lane` is the caller's pre-placed DeviceLane (the cop
        client places so it can record breaker outcomes on the same
        lane); None places here. `stats` is an optional callable
        `(key, n)` for the owning client's per-query counters; `client`
        is the owning CopClient whose store-level stats receive the
        launch's device counters (solo bypasses report through the
        caller's phase collector instead)."""
        placed = None
        if lane is None and hasattr(engine, "place"):
            lane = placed = engine.place(batch, stats=stats)
        with self._lock:
            self._inflight += 1
            concurrent = self._inflight > 1
        try:
            if not concurrent or lane is None:
                return engine.execute(dag, batch, lane=lane) if lane is not None \
                    else engine.execute(dag, batch)
            return self._coalesced(engine, dag, batch, lane, dedup_key, stats, client)
        finally:
            with self._lock:
                self._inflight -= 1
            if placed is not None:
                engine.release_lane(placed)

    # --- grouped path -------------------------------------------------------

    def _coalesced(self, engine, dag, batch, lane, dedup_key, stats, client=None):
        try:
            # the NARROWED (tile count, row bucket) class: two tasks can
            # only stack into one vmapped program when they pad to the
            # same shape, which since the bucketed tile layout is the
            # power-of-two row bucket, not the legacy 64Ki tile count
            bucket_of = getattr(engine, "tile_bucket", engine.tile_count)
            tiles = bucket_of(batch)
        except Exception:  # noqa: BLE001 — engine without tiling: run solo
            return engine.execute(dag, batch, lane=lane)
        # groups are PER LANE: a group's tasks all run one vmapped launch
        # on one device, so only same-device (and same-program) tasks fuse
        ckey = (id(engine), lane.idx, dag.digest(), tiles)
        job = _Job(dag, batch, dedup_key, client=client)
        t_enq = time.perf_counter_ns()
        with self._lock:
            g = self._pending.get(ckey)
            if g is not None and not g.closed:
                if dedup_key is not None:
                    for j in g.jobs:
                        if j.dedup_key == dedup_key:
                            j.followers.append(job)
                            job.mode = "dedup"
                            g.n_dedup += 1
                            break
                if job.mode != "dedup":
                    g.jobs.append(job)
                    job.mode = "member"
                group = g
            else:
                group = _Group()
                group.jobs.append(job)
                self._pending[ckey] = group

        TL.group_event("launch.enqueue", "launch", t_enq, t_enq, mode=job.mode,
                       trace=job.trace.trace_id if job.trace is not None else None)
        if job.mode == "leader":
            time.sleep(self.WINDOW_S)
            with self._lock:
                group.closed = True
                if self._pending.get(ckey) is group:
                    del self._pending[ckey]
            TL.group_event("launch.leader_elected", "launch", t_enq,
                           time.perf_counter_ns(),
                           jobs=len(group.jobs), n_dedup=group.n_dedup,
                           device=lane.name)
            self._launch(engine, group, stats, lane)
        else:
            if not group.done.wait(self.WAIT_TIMEOUT_S):
                # leader died without completing the group (should be
                # impossible — _launch sets done unconditionally): fail
                # loudly rather than return a None chunk downstream
                raise RuntimeError(
                    "launch batcher follower timed out waiting for its group leader"
                )
            if stats is not None:
                stats("dedup_tasks" if job.mode == "dedup" else "batched_tasks", 1)
        if job.exc is not None:
            raise job.exc
        return job.result

    def _launch(self, engine, group: _Group, stats, lane=None) -> None:
        placed = None
        if lane is None and hasattr(engine, "place"):
            # direct callers (tests) without a pre-placed lane
            lane = placed = engine.place(group.jobs[0].batch)
        try:
            if lane is not None:
                # the lane's launch lock serializes device work per device
                # and keeps its timeline tid free of partial overlap; the
                # device_scope binding lands every engine-boundary event
                # recorded below on the REAL device lane
                with lane.lock, TL.device_scope(lane.name):
                    self._launch_on(engine, group, stats, lane)
            else:
                self._launch_on(engine, group, stats, lane)
        finally:
            if placed is not None:
                engine.release_lane(placed)

    def _launch_on(self, engine, group: _Group, stats, lane) -> None:
        jobs = group.jobs
        t0_ns = time.perf_counter_ns()
        # one launch identity shared by the timeline event and the trace
        # span fanned into every waiter (same id space as span ids)
        launch_id = tracing._next_id()
        # the group's shared uploads belong to NO statement (a neighbor's
        # bytes must not draw the leader's quota verdict) but the SERVER
        # arbiter must still see the volume: a detachable, quota-less
        # tracker hung straight off the server root carries it for the
        # launch's duration, then unwinds
        mem0 = next((j.mem for j in jobs if j.mem is not None), None)
        launch_mem = None
        if mem0 is not None and mem0.root is not mem0:
            launch_mem = memory.MemTracker(0, "cop.launch", parent=mem0.root)
        # the leader runs device work for OTHER statements' traces too:
        # collect the device phases (compile/transfer/execute) for the
        # whole launch here and fan them out with the shared launch span
        ph_token = tracing.push_phases()
        try:
            # everything before the engine call sits inside the guard too:
            # an armed failpoint (or metrics error) must still release the
            # followers via done.set(), never strand them on the 120s valve
            _fp("sched/before-launch")
            occupancy = len(jobs) + group.n_dedup
            M.SCHED_BATCH_OCCUPANCY.observe(occupancy)
            if stats is not None and occupancy > 1:
                stats("batched_tasks", 1)
            try:
                with memory.bind(launch_mem):
                    results = engine.execute_many(
                        [(j.dag, j.batch) for j in jobs], lane=lane
                    ) if lane is not None else engine.execute_many(
                        [(j.dag, j.batch) for j in jobs]
                    )
                for j, r in zip(jobs, results):
                    j.result = r
            except Exception:  # noqa: BLE001
                # one poisoned task must not fail its co-batched neighbors:
                # fall back to per-task serial execution with per-task
                # errors, each job under ITS OWN statement's memory
                # tracker — the group ran under the leader's, and a
                # leader-quota breach mid-upload must die with the leader
                # only, not with every waiter
                for j in jobs:
                    try:
                        with memory.bind(j.mem):
                            j.result = self._solo(engine, j.dag, j.batch, lane)
                    except Exception as e:  # noqa: BLE001
                        j.exc = e
        except BaseException as e:  # noqa: BLE001 — e.g. an armed failpoint
            # no job may be left with neither result nor error: a follower
            # would otherwise surface a None chunk downstream
            for j in jobs:
                if j.result is None and j.exc is None:
                    j.exc = e
            raise
        finally:
            phases = tracing.pop_phases(ph_token)
            if launch_mem is not None:
                launch_mem.detach()  # launch volume unwinds with the launch
            for j in jobs:
                for f in j.followers:
                    if j.exc is not None and isinstance(j.exc, MemoryQuotaExceeded):
                        # a statement-scoped quota verdict is the
                        # MEMBER's, not the work's: the dedup follower
                        # re-runs the task under ITS OWN tracker instead
                        # of dying of a neighbor's quota. The re-run runs
                        # AFTER pop_phases restored the leader's phase
                        # frame — collect_phases isolates its device
                        # phases so they can't inflate the leader's
                        # device: line / trace
                        try:
                            with memory.bind(f.mem), tracing.collect_phases():
                                f.result = self._solo(engine, f.dag, f.batch, lane)
                        except Exception as e:  # noqa: BLE001
                            f.exc = e
                    else:
                        f.result, f.exc = j.result, j.exc
            try:
                self._attribute(jobs, group, t0_ns, phases, launch_id=launch_id,
                                lane=lane)
            except Exception:  # noqa: BLE001 — attribution must never strand waiters
                log.warning("launch-span fan-out attribution failed", exc_info=True)
            group.done.set()
            TL.group_event("launch.fanout", "launch",
                           time.perf_counter_ns(), time.perf_counter_ns(),
                           launch_id=launch_id, waiters=len(jobs) + group.n_dedup)

    @staticmethod
    def _solo(engine, dag, batch, lane):
        """Per-job serial fallback / dedup re-run on the group's OWN lane
        — already inside the lane guard, so no solo launch event (the
        enclosing grouped `cop.launch` slice covers it)."""
        if lane is not None:
            return engine.execute(dag, batch, lane=lane, _solo_event=False)
        return engine.execute(dag, batch)

    def _attribute(self, jobs, group: _Group, t0_ns: int, phases: dict,
                   launch_id: int | None = None, lane=None) -> None:
        """Fan the ONE launch out into every co-batched waiter's trace:
        each participant (members, dedup followers, the leader itself)
        gets the SAME launch span — identical launch/span id, occupancy,
        which statement ran it, and the device-phase breakdown — linked
        as a child of its own cop-task span, plus the exec-detail
        counters the slow log / STATEMENTS_SUMMARY columns read."""
        waiters = []
        for j in jobs:
            waiters.append(j)
            waiters.extend(j.followers)
        occupancy = len(waiters)
        dur_ns = time.perf_counter_ns() - t0_ns
        # grouped-launch shared uploads: memory tracking deliberately
        # charges these bytes to NOBODY (a neighbor's data must not draw
        # the leader's quota verdict) — but the volume is real device
        # traffic, so it gets its own series and rides the shared launch
        # span/event as `shared_h2d` instead of vanishing
        shared_h2d = int(phases.get("h2d_bytes", 0)) if occupancy > 1 else 0
        if shared_h2d:
            M.TPU_SHARED_UPLOAD_BYTES.inc(shared_h2d)
        # ONE timeline event per launch on the runner's DEVICE lane —
        # every dispatch shows, 1-job groups included (PR 5 leftover) —
        # referenced by every co-batched waiter's trace id (the chrome
        # export turns the references into flow-event arrows)
        if lane is not None:
            lane.launches += 1
            M.TPU_LANE_LAUNCHES.inc(
                device=lane.name, mode="grouped" if occupancy > 1 else "solo"
            )
        tl = TL.active()
        if tl is not None:
            tl.device_event(
                "cop.launch", "launch", t0_ns, t0_ns + dur_ns,
                launch_id=launch_id, occupancy=occupancy, n_dedup=group.n_dedup,
                shared_h2d_bytes=shared_h2d,
                device=lane.name if lane is not None else "",
                waiters=[w.trace.trace_id for w in waiters if w.trace is not None],
            )
        # store-level stats fan-out (PR 3 debt): a co-batched launch's
        # compile/transfer/execute counters land in EVERY participating
        # client's `cop.stats` — once per client per launch — so EXPLAIN
        # ANALYZE's `device:` line covers grouped launches, not just
        # solos (the statement-level traces get theirs below)
        counters = tracing.phase_counters(phases)
        if shared_h2d:
            counters = counters + [("shared_h2d_bytes", shared_h2d)]
        clients = {}
        for w in waiters:
            if w.client is not None:
                clients[id(w.client)] = w.client
        for cl in clients.values():
            for key, n in counters:
                cl._bump(key, n)
        traces = []
        seen = set()
        for w in waiters:
            t = w.trace
            if t is not None and id(t) not in seen:
                seen.add(id(t))
                traces.append(t)
        if not traces:
            return
        for t in traces:
            t.set_max("batch_occupancy", occupancy)
            for key, cnt in counters:
                t.add(key, cnt)
        if not any(t.recording for t in traces):
            return
        leader = jobs[0].trace
        span = tracing.Span("cop.launch", 0, dur_ns, span_id=launch_id)
        span.tags.update(
            launch_id=span.span_id, occupancy=occupancy, n_dedup=group.n_dedup,
            runner=leader.trace_id if leader is not None else "-",
        )
        if shared_h2d:
            span.tags["shared_h2d"] = shared_h2d
        failed = next((j.exc for j in jobs if j.exc is not None), None)
        if failed is not None:
            span.tags["error"] = type(failed).__name__
        # device phase children: real captured timestamps when the frame
        # carries boundary events (start_ns holds the ABSOLUTE clock
        # reading, rebased per adopting trace); plain-dict frames fall
        # back to back-to-back synthesis relative to the launch start
        events = getattr(phases, "events", None)
        if events:
            children = [
                tracing.Span(name, c_t0, c_t1 - c_t0,
                             parent_id=span.span_id, tags=dict(tags))
                for name, c_t0, c_t1, tags in events
            ]
        else:
            children = tracing.phase_spans(phases, span.span_id, dur_ns)
        adopted = set()
        for w in waiters:
            t = w.trace
            if t is None or not t.recording:
                continue
            if id(t) in adopted:
                # one launch appears ONCE per trace: a statement whose own
                # sibling cop tasks co-batched must not adopt the span (and
                # its children, which key off the shared span id) twice —
                # tree() would render the children cross-product
                continue
            adopted.add(id(t))
            sp = span.copy_with_parent(w.parent_id or t.root_id)
            if events:
                # real timestamps: rebase the one monotonic clock onto
                # this trace's epoch — gaps between phases survive
                sp.start_ns = t0_ns - t._epoch_ns
                kids = tuple(
                    tracing.Span(c.name, c.start_ns - t._epoch_ns, c.dur_ns,
                                 parent_id=c.parent_id, span_id=c.span_id,
                                 tags=c.tags)
                    for c in children
                )
            else:
                # synthesized: start relative to THIS trace's epoch, the
                # launch ends "now"
                sp.start_ns = t._now_ns() - dur_ns
                kids = tuple(
                    tracing.Span(c.name, sp.start_ns + c.start_ns, c.dur_ns,
                                 parent_id=c.parent_id, span_id=c.span_id,
                                 tags=c.tags)
                    for c in children
                )
            t.adopt(sp, sp.parent_id, children=kids)

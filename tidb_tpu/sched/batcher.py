"""Cross-session device-launch micro-batcher.

Per-task device dispatch is the cop-path bottleneck (round-5 verdict:
p50 at 0.15x of the host engine): every task pays its own jit-call
dispatch plus a blocking device→host fetch. Tensor-runtime query engines
win by amortizing launch cost over bucketed batches (arXiv:2203.01877
§4.2); this batcher applies the same move across sessions.

Concurrent cop tasks that lower to the SAME compiled program — same DAG
digest, same padded tile count (the static-shape bucket the jit cache is
keyed on) — coalesce into one launch group. The group leader waits a
microscopic window for followers, then

  * tier 1 (dedup): tasks over the identical data snapshot (same digest,
    table version and handle span) execute ONCE and share the chunk — the
    same sharing rule the cop result cache already applies, without its
    min-scan-rows admission gate;
  * tier 2 (launch coalescing): remaining tasks dispatch back-to-back
    through `TPUEngine.execute_many`, which defers every device→host
    fetch to ONE `device_get` over the whole group.

Every task still runs its own per-task compiled program over its own
batch, so results are bit-identical to serial `execute` calls by
construction (no cross-task reduction reordering).

A solo task (nothing else in flight) bypasses the batcher entirely:
zero added latency on the uncontended path.
"""

from __future__ import annotations

import threading
import time

from ..utils import metrics as M
from ..utils.failpoint import inject as _fp


class _Job:
    __slots__ = ("dag", "batch", "dedup_key", "result", "exc", "followers", "mode")

    def __init__(self, dag, batch, dedup_key):
        self.dag = dag
        self.batch = batch
        self.dedup_key = dedup_key
        self.result = None
        self.exc = None
        self.followers: list["_Job"] = []
        self.mode = "leader"


class _Group:
    __slots__ = ("jobs", "n_dedup", "done", "closed")

    def __init__(self):
        self.jobs: list[_Job] = []
        self.n_dedup = 0
        self.done = threading.Event()
        self.closed = False


class LaunchBatcher:
    WINDOW_S = 0.002  # follower collection window; >> jit dispatch, << a launch
    WAIT_TIMEOUT_S = 120.0  # follower safety valve (leader crashed hard)

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict[tuple, _Group] = {}
        self._inflight = 0

    def execute(self, engine, dag, batch, dedup_key=None, stats=None):
        """Run one cop DAG over one batch through the engine, coalescing
        with concurrent compatible tasks. `stats` is an optional callable
        `(key, n)` for the owning client's per-query counters."""
        with self._lock:
            self._inflight += 1
            concurrent = self._inflight > 1
        try:
            if not concurrent:
                return engine.execute(dag, batch)
            return self._coalesced(engine, dag, batch, dedup_key, stats)
        finally:
            with self._lock:
                self._inflight -= 1

    # --- grouped path -------------------------------------------------------

    def _coalesced(self, engine, dag, batch, dedup_key, stats):
        try:
            tiles = engine.tile_count(batch)
        except Exception:  # noqa: BLE001 — engine without tiling: run solo
            return engine.execute(dag, batch)
        ckey = (id(engine), dag.digest(), tiles)
        job = _Job(dag, batch, dedup_key)
        with self._lock:
            g = self._pending.get(ckey)
            if g is not None and not g.closed:
                if dedup_key is not None:
                    for j in g.jobs:
                        if j.dedup_key == dedup_key:
                            j.followers.append(job)
                            job.mode = "dedup"
                            g.n_dedup += 1
                            break
                if job.mode != "dedup":
                    g.jobs.append(job)
                    job.mode = "member"
                group = g
            else:
                group = _Group()
                group.jobs.append(job)
                self._pending[ckey] = group

        if job.mode == "leader":
            time.sleep(self.WINDOW_S)
            with self._lock:
                group.closed = True
                if self._pending.get(ckey) is group:
                    del self._pending[ckey]
            self._launch(engine, group, stats)
        else:
            if not group.done.wait(self.WAIT_TIMEOUT_S):
                # leader died without completing the group (should be
                # impossible — _launch sets done unconditionally): fail
                # loudly rather than return a None chunk downstream
                raise RuntimeError(
                    "launch batcher follower timed out waiting for its group leader"
                )
            if stats is not None:
                stats("dedup_tasks" if job.mode == "dedup" else "batched_tasks", 1)
        if job.exc is not None:
            raise job.exc
        return job.result

    def _launch(self, engine, group: _Group, stats) -> None:
        jobs = group.jobs
        try:
            # everything before the engine call sits inside the guard too:
            # an armed failpoint (or metrics error) must still release the
            # followers via done.set(), never strand them on the 120s valve
            _fp("sched/before-launch")
            occupancy = len(jobs) + group.n_dedup
            M.SCHED_BATCH_OCCUPANCY.observe(occupancy)
            if stats is not None and occupancy > 1:
                stats("batched_tasks", 1)
            try:
                results = engine.execute_many([(j.dag, j.batch) for j in jobs])
                for j, r in zip(jobs, results):
                    j.result = r
            except Exception:  # noqa: BLE001
                # one poisoned task must not fail its co-batched neighbors:
                # fall back to per-task serial execution with per-task errors
                for j in jobs:
                    try:
                        j.result = engine.execute(j.dag, j.batch)
                    except Exception as e:  # noqa: BLE001
                        j.exc = e
        except BaseException as e:  # noqa: BLE001 — e.g. an armed failpoint
            # no job may be left with neither result nor error: a follower
            # would otherwise surface a None chunk downstream
            for j in jobs:
                if j.result is None and j.exc is None:
                    j.exc = e
            raise
        finally:
            for j in jobs:
                for f in j.followers:
                    f.result, f.exc = j.result, j.exc
            group.done.set()

"""Cop-task admission scheduler — the unified-read-pool analog
(ref: the reference's tikv unified read pool + resource_control admission:
tasks queue per priority, a token-bucket debt check gates each resource
group, and the scheduler grants device slots to the highest-priority
admissible waiter first).

Admission is INLINE: the thread that will execute the cop task (a session
thread or a cop pool worker) blocks in `acquire` until a slot and its
group's RU budget are both available, then runs the task wherever it
already is and calls `release` with the measured RU cost. That keeps the
executor topology untouched (no second thread pool to hand work to) while
still giving global cross-session admission: every session over one store
shares one scheduler via `Storage.sched`.

Waiting is deadline- and kill-aware: a queued task whose statement
deadline (max_execution_time) passes fails with the MySQL timeout error
before it ever touches the device, and KILL marks propagate exactly like
the executor chunk-boundary checks (executor/executors.py:79).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass

from ..errors import MemoryQuotaExceeded, QueryInterrupted, ResourceGroupQueueFull
from ..utils import metrics as M
from ..utils.failpoint import inject as _fp
from .resource_group import PRIORITIES, ResourceGroupManager


@dataclass
class SchedCtx:
    """Per-statement admission context, captured on the session thread
    (contextvars do not cross the cop pool boundary)."""

    group: str = "default"
    deadline: float | None = None  # time.monotonic() deadline, from max_execution_time
    session: object = None  # for KILL checks while queued
    enabled: bool = True
    trace: object = None  # StatementTrace: per-statement spans + exec details
    backoff_budget_ms: float | None = None  # tidb_backoff_budget_ms (None = default)
    runaway: object = None  # RunawayChecker: QUERY_LIMIT watchdog + watch list
    mem: object = None  # statement MemTracker: device transfers consume here
    # workload-history feedback routing (PR 20): the statement's digest
    # keys the store's WorkloadProfile; `feedback` mirrors the live
    # GLOBAL tidb_tpu_feedback_route (OFF = static heuristics, bit-exact)
    digest: str | None = None
    feedback: bool = False


@dataclass
class Ticket:
    group: object  # ResourceGroup
    est: float
    wait_s: float = 0.0


@dataclass
class _Waiter:
    priority: int
    seq: int
    group: object
    granted: bool = False


def ru_cost(rows: int, nbytes: float = 0.0, cpu_ms: float = 0.0) -> float:
    """RU model: one base unit per cop task plus one per KiRow scanned
    plus one per 64KiB of batch data touched (the read-request +
    read-byte split of the reference's RU formula — the byte term makes
    wide-row scans cost what they move, not just what they count; 64KiB
    per RU mirrors the reference's ReadBytesCost) plus one per 3ms of
    MEASURED host-engine CPU wall (the reference's CPUMsCost — the term
    this model was missing until the workload-history plane started
    measuring host walls per task, PR 20; device-path tasks charge 0
    here, their cost lives in the byte term)."""
    return 1.0 + rows / 1024.0 + nbytes / 65536.0 + cpu_ms / 3.0


def raise_if_interrupted(session=None, deadline=None) -> None:
    """The deadline/KILL gate, shared by admission waits, cop-path
    backoff sleeps (copr/retry.py) AND executor chunk boundaries
    (executor/executors.py drain): one definition of "stop now" so a
    KILLed or timed-out statement escapes every wait the same way. The
    raised error carries `.reason` ("killed" | "timeout" | "oom" |
    "runaway") for metric labeling.

    Two protection layers piggyback this poll tick: a session KILLed by
    the server memory arbiter carries reason "oom" and raises the 8175
    quota error instead of a generic interrupt, and the statement's
    runaway checker (session._runaway, sched/runaway.py) ticks its
    QUERY_LIMIT thresholds here — no watchdog thread, the gate IS the
    watchdog's clock."""
    if session is not None:
        if getattr(session, "_killed", False):
            session._killed = False
            reason = getattr(session, "_kill_reason", None)
            if reason is not None:
                session._kill_reason = None
            if reason == "oom":
                from ..errors import ServerMemoryExceeded

                e = ServerMemoryExceeded(
                    "Out Of Memory Quota! statement killed by the server "
                    "memory arbiter (tidb_server_memory_limit exceeded; this "
                    "statement was the top consumer)"
                )
                e.reason = "oom"
                raise e
            e = QueryInterrupted("Query execution was interrupted")
            e.reason = "killed"
            raise e
        rc = getattr(session, "_runaway", None)
        if rc is not None:
            rc.tick()
    if deadline is not None and time.monotonic() >= deadline:
        e = QueryInterrupted(
            "Query execution was interrupted, maximum statement execution time exceeded"
        )
        e.reason = "timeout"
        raise e


def sleep_interruptible(seconds: float, deadline=None, session=None, stop=None) -> None:
    """Deadline/KILL-aware sleep: naps in scheduler-tick slices so a task
    backing off between retries observes KILL / max_execution_time within
    one poll interval instead of finishing its full backoff first. `stop`
    (optional () -> bool) aborts the wait the same way when its stream was
    abandoned — the drain path must not ride out full backoff budgets."""
    end = time.monotonic() + seconds
    while True:
        # abandon check FIRST: raise_if_interrupted consumes the one-shot
        # _killed flag, and an abandoned task's interrupt is swallowed by
        # the stream drain — it must not eat a KILL meant for live work
        if stop is not None and stop():
            e = QueryInterrupted("cop stream abandoned")
            e.reason = "abandoned"
            raise e
        raise_if_interrupted(session, deadline)
        now = time.monotonic()
        if now >= end:
            return
        nap = min(AdmissionScheduler._TICK_S, end - now)
        if deadline is not None:
            nap = min(nap, max(deadline - now, 0.001))
        time.sleep(nap)


class AdmissionScheduler:
    MAX_QUEUE = 256  # waiters beyond this hard-fail (backpressure edge)
    EST_RU = 1.0  # debited at admission, settled at release
    _TICK_S = 0.05  # poll cadence for bucket refills / kill marks
    # BURSTABLE borrow gate (PR 20): a burstable group in RU debt may
    # still admit while the store runs below this fraction of its device
    # slots — measured headroom, not an unlimited bucket. At/above it
    # the group throttles at its reserved ru_per_sec like any other.
    BORROW_HEADROOM = 0.75

    def __init__(self, groups: ResourceGroupManager, max_concurrency: int = 32):
        self.groups = groups
        self.max_concurrency = max_concurrency
        self._cond = threading.Condition()
        self._running = 0
        self._waiting: list[_Waiter] = []
        self._seq = itertools.count()

    # --- introspection (memtables / tests) ---------------------------------

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._waiting)

    def running(self) -> int:
        with self._cond:
            return self._running

    def _headroom_locked(self) -> bool:
        """Measured store headroom for BURSTABLE borrowing: true while
        running work occupies less than BORROW_HEADROOM of the device
        slots (caller holds self._cond)."""
        return self._running < max(1, int(self.max_concurrency * self.BORROW_HEADROOM))

    # --- admission ----------------------------------------------------------

    def acquire(self, ctx: SchedCtx, stop=None) -> Ticket:
        """`stop` (optional () -> bool): abort the wait when the owning
        cop stream was abandoned — a drained task must not sit out the
        admission queue to run work whose result is already discarded."""
        _fp("sched/before-admit")
        g = self.groups.get(ctx.group)
        rc = getattr(ctx, "runaway", None)
        if rc is not None:
            # runaway control gates admission itself: a watch-listed
            # digest is rejected (KILL) or demoted (COOLDOWN) here,
            # before a ticket or RU estimate is consumed
            rc.on_admission()
        t0 = time.monotonic()
        with self._cond:
            if not self._waiting and self._running < self.max_concurrency \
                    and g.bucket.admissible(headroom=self._headroom_locked()):
                self._running += 1
                g.bucket.debit(self.EST_RU)
                M.SCHED_TASKS.inc(group=g.name, outcome="admitted")
                M.SCHED_WAIT.observe(0.0)
                if ctx.trace is not None and ctx.trace.recording:
                    ctx.trace.closed_span("sched.admission", 0.0, group=g.name, queued=False)
                return Ticket(g, self.EST_RU)
            if len(self._waiting) >= self.MAX_QUEUE:
                # backpressure hard edge — typed as ServerBusy so the cop
                # client retries it through the Backoffer's serverBusy
                # class before surfacing (PR 2 taxonomy, exercised here)
                M.SCHED_TASKS.inc(group=g.name, outcome="rejected")
                raise ResourceGroupQueueFull(
                    f"resource group '{g.name}' admission queue is full "
                    f"({self.MAX_QUEUE} waiting); retry later"
                )
            # a COOLDOWN-demoted statement queues at LOW priority no
            # matter what its group grants (the runaway demotion)
            prio = PRIORITIES["LOW"] if (rc is not None and rc.demoted) else g.priority_value
            w = _Waiter(prio, next(self._seq), g)
            self._waiting.append(w)
            M.SCHED_QUEUE_DEPTH.set(len(self._waiting))
            try:
                while True:
                    self._grant_locked()
                    if w.granted:
                        break
                    if stop is not None and stop():
                        M.SCHED_TASKS.inc(group=g.name, outcome="abandoned")
                        e = QueryInterrupted("cop stream abandoned")
                        e.reason = "abandoned"
                        raise e
                    try:
                        raise_if_interrupted(ctx.session, ctx.deadline)
                    except (QueryInterrupted, MemoryQuotaExceeded) as e:
                        # MemoryQuotaExceeded covers the oom-arbiter kill
                        # (ServerMemoryExceeded, reason "oom") — it is a
                        # quota error, not a QueryInterrupted subclass
                        M.SCHED_TASKS.inc(
                            group=g.name, outcome=getattr(e, "reason", "killed")
                        )
                        raise
                    if rc is not None and rc.demoted and w.priority != PRIORITIES["LOW"]:
                        # the COOLDOWN verdict fired while this task was
                        # ALREADY queued (rc.tick above): demote the live
                        # waiter now — the next _grant_locked pass sorts
                        # it behind every normal-priority waiter instead
                        # of honoring the priority it enqueued with
                        w.priority = PRIORITIES["LOW"]
                    now = time.monotonic()
                    timeout = self._TICK_S
                    if ctx.deadline is not None:
                        timeout = min(timeout, max(ctx.deadline - now, 0.001))
                    self._cond.wait(timeout)
            finally:
                if not w.granted and w in self._waiting:
                    self._waiting.remove(w)
                M.SCHED_QUEUE_DEPTH.set(len(self._waiting))
        wait = time.monotonic() - t0
        M.SCHED_WAIT.observe(wait)
        M.SCHED_TASKS.inc(group=g.name, outcome="admitted")
        if ctx.trace is not None and ctx.trace.recording:
            ctx.trace.closed_span("sched.admission", wait, group=g.name, queued=True)
        return Ticket(g, self.EST_RU, wait)

    def _grant_locked(self) -> None:
        """Grant free slots to waiters: strict priority order, FIFO within
        a priority, skipping groups whose bucket is in debt (they neither
        run nor block higher/other groups — no head-of-line starvation)."""
        granted_any = False
        while self._running < self.max_concurrency and self._waiting:
            chosen = None
            hr = self._headroom_locked()  # re-read per grant: each fills a slot
            for w in sorted(self._waiting, key=lambda x: (-x.priority, x.seq)):
                if w.group.bucket.admissible(headroom=hr):
                    chosen = w
                    break
            if chosen is None:
                break  # every waiting group is bucket-starved; refill will re-grant
            self._waiting.remove(chosen)
            chosen.group.bucket.debit(self.EST_RU)
            self._running += 1
            chosen.granted = True
            granted_any = True
        if granted_any:
            M.SCHED_QUEUE_DEPTH.set(len(self._waiting))
            self._cond.notify_all()

    def release(self, ticket: Ticket, ru: float | None = None) -> None:
        ru = ticket.est if ru is None else ru
        extra = ru - ticket.est
        if extra > 0:
            ticket.group.bucket.debit(extra)
        elif extra < 0:
            ticket.group.bucket.credit(-extra)
        M.RU_CONSUMED.inc(ru, group=ticket.group.name)
        with self._cond:
            self._running -= 1
            self._grant_locked()
            self._cond.notify_all()

"""Runaway-query watchdog (ref: the reference's runaway control:
ddl QUERY_LIMIT group option + pkg/resourcegroup/runaway — a per-group
QUERY_LIMIT of EXEC_ELAPSED / RU / PROCESSED_ROWS thresholds with DRYRUN
/ COOLDOWN / KILL actions, plus a TTL watch list that rejects a KILLed
statement's digest at admission before it consumes anything).

The watchdog owns no thread: checks piggyback the scheduler's existing
poll tick. `RunawayChecker.tick()` is called from
`sched.scheduler.raise_if_interrupted` — the one shared "stop now?" gate
that admission waits, backoff sleeps and executor chunk boundaries
already poll — so a runaway observes its verdict within one tick slice
wherever it happens to be stuck. `on_admission()` runs once per
statement at `AdmissionScheduler.acquire`, where the watch list can
reject (KILL watch) or demote (COOLDOWN watch) a repeat offender before
a ticket is granted.

COOLDOWN semantics: the statement survives but its remaining cop tasks
are admitted at LOW priority and its Backoffer budget shrinks to a
quarter (a misbehaving statement gets less patience, not more).
"""

from __future__ import annotations

import logging
import re
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..errors import RunawayKilled, RunawayQuarantined
from ..utils import metrics as M

log = logging.getLogger("tidb_tpu.runaway")

ACTIONS = ("DRYRUN", "COOLDOWN", "KILL")

_BARE_NUM = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*$")


def parse_duration_ms(s: str) -> float:
    """Go duration string → milliseconds: '800ms' / '10s' / '5m' / '1h'
    and compound forms like '1m30s' (delegates to the tidb_gc_* parser,
    storage/gcworker.parse_go_duration_ms); a bare number means seconds."""
    m = _BARE_NUM.match(str(s))
    if m is not None:
        return float(m.group(1)) * 1000.0
    from ..storage.gcworker import parse_go_duration_ms

    ms = parse_go_duration_ms(str(s))
    if ms is None:
        raise ValueError(f"invalid duration value {s!r}")
    return float(ms)


def format_duration(ms: float) -> str:
    if ms and ms % 60000.0 == 0:
        return f"{int(ms // 60000)}m"
    if ms and ms % 1000.0 == 0:
        return f"{int(ms // 1000)}s"
    return f"{ms:g}ms"


@dataclass(frozen=True)
class QueryLimit:
    """Parsed form of a group spec's `query_limit` dict."""

    exec_elapsed_ms: float | None = None
    ru: float | None = None
    processed_rows: int | None = None
    action: str = "DRYRUN"
    watch_ms: float | None = None  # explicit WATCH duration

    DEFAULT_WATCH_MS = 60_000.0  # KILLed digests watch this long when
    # the spec names no WATCH (repeat offenders must not re-enter free)

    @classmethod
    def from_spec(cls, d: dict) -> "QueryLimit | None":
        if not d:
            return None
        return cls(
            exec_elapsed_ms=d.get("exec_elapsed_ms"),
            ru=d.get("ru"),
            processed_rows=d.get("processed_rows"),
            action=str(d.get("action", "DRYRUN")).upper(),
            watch_ms=d.get("watch_ms"),
        )

    def render(self) -> str:
        parts = []
        if self.exec_elapsed_ms is not None:
            parts.append(f"EXEC_ELAPSED='{format_duration(self.exec_elapsed_ms)}'")
        if self.ru is not None:
            parts.append(f"RU={self.ru:g}")
        if self.processed_rows is not None:
            parts.append(f"PROCESSED_ROWS={self.processed_rows}")
        parts.append(f"ACTION={self.action}")
        if self.watch_ms is not None:
            parts.append(f"WATCH='{format_duration(self.watch_ms)}'")
        return ", ".join(parts)


@dataclass
class Watch:
    group: str
    action: str
    reason: str
    start: float  # wall clock, for the memtable
    until: float  # monotonic expiry
    until_wall: float = 0.0  # wall-clock expiry, for persistence


class RunawayChecker:
    """Per-statement watchdog state. `tick()` is on the interrupt-gate
    hot path: when the group has no limit (watch-only checker) or the
    action already fired it is two attribute loads and out."""

    __slots__ = ("manager", "session", "group", "limit", "digest", "trace",
                 "sql", "start", "demoted", "_fired", "_watch", "_lock",
                 "_kill_rule")

    def __init__(self, manager: "RunawayManager", session, group: str,
                 limit: QueryLimit | None, digest: str, trace, sql: str):
        self.manager = manager
        self.session = session
        self.group = group
        self.limit = limit
        self.digest = digest
        self.trace = trace
        self.sql = sql
        self.start = time.monotonic()
        self.demoted = False
        self._fired = False
        self._watch = None  # resolved watch verdict: (group, action, reason)
        self._kill_rule = None  # sticky KILL verdict: every tick re-raises
        self._lock = threading.Lock()

    # --- admission-time (watch list) ---------------------------------------

    def on_admission(self) -> None:
        """Admission gate: resolve the watch-list verdict ONCE per
        statement (a statement's parallel cop tasks share this checker —
        the lock keeps the hit event/metric single) and enforce it for
        EVERY task: a KILL watch rejects before a ticket is consumed, a
        COOLDOWN watch demotes. Then the normal threshold tick."""
        with self._lock:
            if self._watch is None:
                w = self.manager.watch_for(self.digest, self.group)
                if w is None:
                    self._watch = ()
                else:
                    self._watch = (w.group, w.action, w.reason)
                    M.RUNAWAY_WATCH_HITS.inc(group=w.group, action=w.action)
                    self.manager.record_event(w.group, self.digest, "watch",
                                              w.action, self.sql)
                    self._span("runaway.watch_hit", action=w.action)
                    if w.action == "COOLDOWN":
                        self.demoted = True
        if self._watch and self._watch[1] == "KILL":
            wg, _, wr = self._watch
            raise RunawayQuarantined(
                f"Quarantined and interrupted because of being in the "
                f"runaway watch list (digest {self.digest}, group "
                f"'{wg}', reason: {wr})"
            )
        self.tick()

    # --- the poll-tick check -----------------------------------------------

    def tick(self) -> None:
        if self._kill_rule is not None:
            # a parallel sibling task already drew the KILL verdict: the
            # whole statement dies, whichever task polls next
            self._raise_killed(self._kill_rule)
        lim = self.limit
        if lim is None or self._fired:
            return
        rule = None
        if (lim.exec_elapsed_ms is not None
                and (time.monotonic() - self.start) * 1000.0 > lim.exec_elapsed_ms):
            rule = "exec_elapsed"
        elif self.trace is not None and (lim.ru is not None or lim.processed_rows is not None):
            c = self.trace.counters  # read-mostly dict; snapshot-free peek
            if lim.ru is not None and c.get("ru", 0.0) > lim.ru:
                rule = "ru"
            elif lim.processed_rows is not None and c.get("processed_rows", 0.0) > lim.processed_rows:
                rule = "processed_rows"
        if rule is not None:
            self._fire(rule)

    def _span(self, name: str, **tags) -> None:
        if self.trace is not None and self.trace.recording:
            self.trace.closed_span(name, 0.0, group=self.group, **tags)

    def _fire(self, rule: str) -> None:
        with self._lock:
            if self._fired:
                return  # a parallel sibling drew the verdict first
            self._fired = True
        lim = self.limit
        action = lim.action if lim.action in ACTIONS else "DRYRUN"
        M.RUNAWAY_ACTIONS.inc(group=self.group, action=action, rule=rule)
        self.manager.record_event(self.group, self.digest, rule, action, self.sql)
        self._span(f"runaway.{action.lower()}", rule=rule)
        if action == "COOLDOWN":
            self.demoted = True
        if lim.watch_ms is not None and action in ("COOLDOWN", "DRYRUN"):
            # an explicit WATCH clause extends a non-kill verdict to the
            # digest's future statements (demote-on-arrival / dryrun note)
            self.manager.mark(self.digest, self.group, action, rule, lim.watch_ms)
        if action == "KILL":
            ttl = lim.watch_ms if lim.watch_ms is not None else QueryLimit.DEFAULT_WATCH_MS
            self.manager.mark(self.digest, self.group, "KILL", rule, ttl)
            self._kill_rule = rule
            self._raise_killed(rule)

    def _raise_killed(self, rule: str) -> None:
        raise RunawayKilled(
            f"Query execution was interrupted, identified as runaway query "
            f"(rule: {rule}, resource group '{self.group}')"
        )


class RunawayManager:
    """Store-wide watch list + event history (one per ResourceController,
    like the group table itself)."""

    EVENTS_CAP = 512

    def __init__(self, controller=None):
        self.controller = controller
        self._lock = threading.Lock()
        # keyed (digest, group): one digest may carry DIFFERENT verdicts
        # in different groups — rg2's DRYRUN watch must not overwrite
        # rg1's still-live KILL watch for the same digest
        self._watches: dict[tuple[str, str], Watch] = {}
        self.events: deque = deque(maxlen=self.EVENTS_CAP)
        # lazy one-shot load of watches persisted in the catalog meta: a
        # KILLed digest must stay rejected across store restart, not
        # only while the process that drew the verdict lives
        self._loaded = False

    # --- persistence (catalog meta, `m:rw:` keyspace) ----------------------

    @property
    def _storage(self):
        return getattr(self.controller, "storage", None)

    def _load_locked(self) -> None:
        """Rebuild the in-memory watch table from the catalog meta ONCE
        per manager (first touch). Entries whose wall-clock TTL lapsed
        while the store was down are swept from the meta here; survivors
        get a fresh monotonic expiry covering their remaining time."""
        if self._loaded:
            return
        self._loaded = True
        storage = self._storage
        if storage is None:
            return  # bare manager (unit tests): nothing to restore
        from ..catalog.meta import Meta

        try:
            txn = storage.begin()
            try:
                specs = Meta(txn).list_runaway_watches()
            finally:
                txn.rollback()
        except Exception:  # noqa: BLE001 — a cold/closed store: stay empty
            log.warning("runaway watch-list load failed", exc_info=True)
            return
        now_wall = time.time()
        now_mono = time.monotonic()
        expired = []
        for d in specs:
            remaining = float(d.get("until_wall", 0.0)) - now_wall
            if remaining <= 0:
                expired.append((d.get("group", ""), d.get("digest", "")))
                continue
            key = (d["digest"], d["group"])
            self._watches[key] = Watch(
                group=d["group"], action=d.get("action", "KILL"),
                reason=d.get("reason", ""), start=float(d.get("start", now_wall)),
                until=now_mono + remaining, until_wall=float(d["until_wall"]),
            )
        for group, digest in expired:
            self._meta_drop(group, digest)

    def _meta_put(self, digest: str, w: Watch) -> None:
        storage = self._storage
        if storage is None:
            return
        from ..catalog.meta import Meta

        try:
            txn = storage.begin()
            try:
                Meta(txn).put_runaway_watch({
                    "digest": digest, "group": w.group, "action": w.action,
                    "reason": w.reason, "start": w.start,
                    "until_wall": w.until_wall,
                })
                txn.commit()
            except BaseException:
                txn.rollback()
                raise
        except Exception:  # noqa: BLE001 — the verdict must still fire
            log.warning("runaway watch persist failed", exc_info=True)

    def _meta_drop(self, group: str, digest: str) -> None:
        storage = self._storage
        if storage is None:
            return
        from ..catalog.meta import Meta

        try:
            txn = storage.begin()
            try:
                Meta(txn).drop_runaway_watch(group, digest)
                txn.commit()
            except BaseException:
                txn.rollback()
                raise
        except Exception:  # noqa: BLE001 — expiry sweep is best-effort
            pass

    # --- per-statement entry ------------------------------------------------

    def checker_for(self, session, group, sql: str, trace) -> RunawayChecker | None:
        """Called once per statement. Fast-exits with None when the bound
        group carries no QUERY_LIMIT and the watch list is empty — the
        every-statement overhead of an idle watchdog is this check.
        Expired watches are swept here, not only on re-admission of the
        same digest: one long-forgotten KILL must not leave every future
        statement paying digest hashing + checker construction forever."""
        limit = group.parsed_limit()
        if limit is None and not self._any_watch():
            return None
        from ..utils.stmtstats import sql_digest

        return RunawayChecker(self, session, group.name, limit,
                              sql_digest(sql), trace, sql[:256])

    def _any_watch(self) -> bool:
        """True while an UNEXPIRED watch exists; purges expired entries
        so the idle fast path comes back once every TTL has lapsed."""
        if not self._loaded:
            with self._lock:
                self._load_locked()
        if not self._watches:
            return False
        now = time.monotonic()
        with self._lock:
            expired = [k for k, w in self._watches.items() if now >= w.until]
            for k in expired:
                del self._watches[k]
            alive = bool(self._watches)
        for digest, group in expired:
            self._meta_drop(group, digest)
        return alive

    # --- watch list ----------------------------------------------------------

    def watch_for(self, digest: str, group: str) -> Watch | None:
        """The unexpired watch for (digest, group): a KILL watch armed
        under 'rg1' must not quarantine the same digest running under a
        group that never opted into runaway control (the reference
        scopes watches per group; the RUNAWAY_WATCHES memtable column
        implies the same)."""
        now = time.monotonic()
        key = (digest, group)
        with self._lock:
            self._load_locked()
            w = self._watches.get(key)
            if w is None:
                return None
            if now >= w.until:
                del self._watches[key]
                w = None
        if w is None:
            self._meta_drop(group, digest)
        return w

    def mark(self, digest: str, group: str, action: str, reason: str, ttl_ms: float) -> None:
        now_wall = time.time()
        w = Watch(
            group=group, action=action, reason=reason,
            start=now_wall, until=time.monotonic() + ttl_ms / 1000.0,
            until_wall=now_wall + ttl_ms / 1000.0,
        )
        with self._lock:
            self._load_locked()
            self._watches[(digest, group)] = w
        # persist OUTSIDE the lock: the meta write opens its own txn and
        # must not serialize every admission-path watch probe behind it
        self._meta_put(digest, w)

    def watches_snapshot(self) -> list[tuple[str, Watch, float]]:
        """[(digest, watch, remaining_s)] of unexpired entries."""
        now = time.monotonic()
        with self._lock:
            self._load_locked()
            expired = [k for k, w in self._watches.items() if now >= w.until]
            for k in expired:
                del self._watches[k]
            out = [(k[0], w, w.until - now) for k, w in self._watches.items()]
        for digest, group in expired:
            self._meta_drop(group, digest)
        return out

    # --- events --------------------------------------------------------------

    def record_event(self, group: str, digest: str, rule: str, action: str, sql: str) -> None:
        self.events.append({
            "time": time.time(), "group": group, "digest": digest,
            "rule": rule, "action": action, "sql": sql,
        })

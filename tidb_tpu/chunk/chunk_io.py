"""Chunk disk serialization — the ListInDisk analog
(ref: util/chunk/disk.go:34; spilled operators stream chunks through
temp files in a compact self-describing format, no pickle)."""

from __future__ import annotations

import os
import struct
import tempfile

import numpy as np

from .chunk import Chunk, Column, VARLEN, col_numpy_dtype

_MAGIC = b"TPCH"


def write_chunk(f, chunk: Chunk) -> None:
    f.write(_MAGIC)
    f.write(struct.pack("<II", chunk.num_cols, chunk.num_rows))
    for col in chunk.columns:
        vbits = np.packbits(col.valid.astype(np.uint8)).tobytes()
        f.write(struct.pack("<I", len(vbits)))
        f.write(vbits)
        if col.data.dtype == object:
            f.write(b"O")
            blobs = []
            for i in range(chunk.num_rows):
                v = col.data[i]
                if not col.valid[i] or v is None:
                    blobs.append((0, b""))
                elif isinstance(v, bytes):
                    blobs.append((2, v))
                else:
                    blobs.append((1, str(v).encode("utf8")))
            lens = np.fromiter((len(b) for _, b in blobs), np.int64, chunk.num_rows)
            tags = bytes(t for t, _ in blobs)
            f.write(lens.tobytes())
            f.write(tags)
            f.write(b"".join(b for _, b in blobs))
        else:
            f.write(b"F")
            f.write(col.data.dtype.str.encode("ascii").ljust(8, b" "))
            f.write(col.data.tobytes())


def read_chunk(f, fts) -> Chunk | None:
    magic = f.read(4)
    if not magic:
        return None
    if magic != _MAGIC:
        raise ValueError("corrupt spill file")
    ncols, nrows = struct.unpack("<II", f.read(8))
    cols = []
    for ft in fts:
        (vlen,) = struct.unpack("<I", f.read(4))
        valid = np.unpackbits(np.frombuffer(f.read(vlen), np.uint8))[:nrows].astype(bool)
        kind = f.read(1)
        if kind == b"O":
            lens = np.frombuffer(f.read(8 * nrows), np.int64)
            tags = f.read(nrows)
            data = np.empty(nrows, dtype=object)
            for i in range(nrows):
                blob = f.read(int(lens[i]))
                if tags[i] == 1:
                    data[i] = blob.decode("utf8")
                elif tags[i] == 2:
                    data[i] = blob
        else:
            dt = np.dtype(f.read(8).decode("ascii").strip())
            data = np.frombuffer(f.read(dt.itemsize * nrows), dt).copy()
        cols.append(Column(ft, data, valid))
    return Chunk(cols)


class SpillFile:
    """One temp run file of chunks."""

    def __init__(self):
        fd, self.path = tempfile.mkstemp(prefix="tidbtpu-spill-")
        self._f = os.fdopen(fd, "wb")

    def write(self, chunk: Chunk) -> None:
        write_chunk(self._f, chunk)

    def finish(self) -> None:
        self._f.close()

    def chunks(self, fts):
        with open(self.path, "rb") as f:
            while True:
                c = read_chunk(f, fts)
                if c is None:
                    return
                yield c

    def cleanup(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

from .chunk import Chunk, Column, col_numpy_dtype, VARLEN
from .tile import DeviceTile, HostTileSet, TILE_ROWS

"""Columnar batch format (ref: util/chunk/chunk.go, column.go).

The reference's Chunk is Arrow-layout columns (null bitmap + offsets +
contiguous data) pulled through Volcano `Next(chk)` with `requiredRows`
sizing. Here a Column is:
  data  — numpy array: int64 (ints/times/durations/enum codes/scaled
          decimals), uint64, float64, or object (strings/bytes/json)
  valid — numpy bool array, True = non-NULL

Fixed-width columns are exactly the host mirror of a device tile lane; a
Chunk becomes a DeviceTile by padding to tile shape (see tile.py). Strings
dictionary-encode at the tile boundary.

The `sel` concept (chunk.go:37) appears here as filter() returning a
compacted chunk — on device the mask itself is kept instead (validity
semantics, SURVEY §7 hard-parts).
"""

from __future__ import annotations

import numpy as np

from ..mysqltypes.field_type import FieldType, TypeCode
from ..mysqltypes.datum import Datum, K_NULL, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR
from ..mysqltypes.mydecimal import Dec

VARLEN = "varlen"


def col_numpy_dtype(ft: FieldType):
    """numpy dtype for a FieldType; VARLEN sentinel for object columns."""
    if ft.is_int():
        return np.uint64 if ft.is_unsigned and ft.tp == TypeCode.Longlong else np.int64
    if ft.tp in (TypeCode.Date, TypeCode.Datetime, TypeCode.Timestamp, TypeCode.Duration, TypeCode.Year):
        return np.int64
    if ft.is_float():
        return np.float64
    if ft.is_decimal():
        return np.int64  # scaled by ft.decimal
    return VARLEN


class Column:
    __slots__ = ("ft", "data", "valid")

    def __init__(self, ft: FieldType, data: np.ndarray, valid: np.ndarray):
        self.ft = ft
        self.data = data
        self.valid = valid

    @staticmethod
    def empty(ft: FieldType, n: int = 0) -> "Column":
        dt = col_numpy_dtype(ft)
        data = np.empty(n, dtype=object) if dt is VARLEN else np.zeros(n, dtype=dt)
        return Column(ft, data, np.zeros(n, dtype=bool))

    def __len__(self):
        return len(self.data)

    def is_varlen(self) -> bool:
        return col_numpy_dtype(self.ft) is VARLEN

    def get_datum(self, i: int) -> Datum:
        if not self.valid[i]:
            return Datum.null()
        v = self.data[i]
        ft = self.ft
        if ft.is_decimal():
            return Datum.d(Dec(int(v), max(ft.decimal, 0)))
        if ft.is_time():
            return Datum.t(int(v))
        if ft.tp == TypeCode.Duration:
            return Datum(K_DUR, int(v))
        if ft.is_float():
            return Datum.f(float(v))
        if ft.is_int():
            return Datum.u(int(v)) if ft.is_unsigned else Datum.i(int(v))
        if isinstance(v, bytes):
            return Datum.b(v)
        return Datum.s(v)

    def set_datum(self, i: int, d: Datum) -> None:
        if d.is_null:
            self.valid[i] = False
            return
        self.valid[i] = True
        ft = self.ft
        if ft.is_decimal():
            self.data[i] = d.to_dec().rescale(max(ft.decimal, 0)).value
        elif self.is_varlen():
            self.data[i] = d.val
        elif ft.is_float():
            self.data[i] = d.to_float()
        else:
            self.data[i] = d.to_int()

    def take(self, idx: np.ndarray) -> "Column":
        return Column(self.ft, self.data[idx], self.valid[idx])

    def slice(self, lo: int, hi: int) -> "Column":
        return Column(self.ft, self.data[lo:hi], self.valid[lo:hi])

    def concat(self, other: "Column") -> "Column":
        return Column(self.ft, np.concatenate([self.data, other.data]), np.concatenate([self.valid, other.valid]))


class Chunk:
    """A batch of rows in columnar form.

    `_device` is set (True) by the TPU engine on chunks a device program
    produced — the cop client charges such tasks' RU read-byte term at
    the compressed mirror's wire bytes, while host-produced chunks (incl.
    the engine's internal lowering fallback) charge the host lanes they
    actually scanned. Absent on every other construction path."""

    __slots__ = ("columns", "_device")

    def __init__(self, columns: list[Column]):
        self.columns = columns

    @staticmethod
    def empty(fts: list[FieldType], n: int = 0) -> "Chunk":
        return Chunk([Column.empty(ft, n) for ft in fts])

    @staticmethod
    def from_datum_rows(fts: list[FieldType], rows: list[list[Datum]]) -> "Chunk":
        chk = Chunk.empty(fts, len(rows))
        for i, row in enumerate(rows):
            for c, d in enumerate(row):
                chk.columns[c].set_datum(i, d)
        return chk

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def field_types(self) -> list[FieldType]:
        return [c.ft for c in self.columns]

    def get_row(self, i: int) -> list[Datum]:
        return [c.get_datum(i) for c in self.columns]

    def iter_rows(self):
        for i in range(self.num_rows):
            yield self.get_row(i)

    def take(self, idx: np.ndarray) -> "Chunk":
        return Chunk([c.take(idx) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "Chunk":
        idx = np.nonzero(mask)[0]
        return self.take(idx)

    def slice(self, lo: int, hi: int) -> "Chunk":
        return Chunk([c.slice(lo, hi) for c in self.columns])

    def concat(self, other: "Chunk") -> "Chunk":
        if self.num_cols == 0:
            return other
        return Chunk([a.concat(b) for a, b in zip(self.columns, other.columns)])

    @staticmethod
    def concat_all(chunks: list["Chunk"]) -> "Chunk":
        chunks = [c for c in chunks if c is not None and c.num_rows > 0]
        if not chunks:
            return Chunk([])
        if len(chunks) == 1:
            return chunks[0]
        # one np.concatenate per column — pairwise concat is O(k^2) copies
        import numpy as np

        cols = []
        for i, c0 in enumerate(chunks[0].columns):
            cols.append(Column(
                c0.ft,
                np.concatenate([c.columns[i].data for c in chunks]),
                np.concatenate([c.columns[i].valid for c in chunks]),
            ))
        return Chunk(cols)

    def to_pylist(self) -> list[tuple]:
        """Render all rows as python tuples (None for NULL) — test/display helper."""
        out = []
        for i in range(self.num_rows):
            out.append(tuple(d.render(c.ft) for d, c in zip(self.get_row(i), self.columns)))
        return out

"""Device tiles — the fixed-shape device twin of a Chunk.

TPU/XLA wants static shapes; SQL produces data-dependent cardinalities.
The contract (SURVEY §7 "hard parts"):
  * a tile is TILE_ROWS rows of each referenced column, zero-padded
  * `row_valid` marks real rows; per-column `valid` marks non-NULLs
  * selection produces masks, never compaction, until the host boundary

Lane dtypes: int64 (ints/decimals-scaled/times), float64/float32, int32
dictionary codes for strings. Dictionary vocabularies live host-side; only
codes go to device (GPU-compressed-scan papers' pattern; also how TiFlash
ships packed columns).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..mysqltypes.field_type import FieldType
from .chunk import Chunk, Column, col_numpy_dtype, VARLEN

TILE_ROWS = 1 << 16  # 65536 — big enough to amortize dispatch, fits VMEM-tiled pipelines


@dataclass
class DeviceTile:
    """Host-side staging of one tile; arrays are numpy, shipped via jnp.asarray."""

    n_rows: int  # real rows (<= TILE_ROWS)
    data: list[np.ndarray]  # per column, padded to TILE_ROWS
    valid: list[np.ndarray]  # per column bool, padded (False in padding)


@dataclass
class HostTileSet:
    """Columnar snapshot of a table region, pre-split into tiles.

    Built once per (table, data-version) by the cop engine's tile cache
    (the TiFlash-columnar-replica analog) and reused across queries.
    `dicts[i]` is the string vocabulary for dictionary-coded column i
    (None for numeric lanes).
    """

    fts: list[FieldType]
    tiles: list[DeviceTile]
    dicts: list[list | None]
    total_rows: int

    def dict_lookup(self, col: int, code: int):
        return self.dicts[col][code]


def _dict_encode(objs: np.ndarray, valid: np.ndarray):
    """Dictionary-encode an object column → (int32 codes, vocab list).

    Codes are assigned in *sorted* vocab order so that integer code order
    == binary collation order; device-side min/max/sort/group-by on codes
    is then semantically exact for the column (per-tileset vocab).
    """
    vals = objs[valid]
    vocab = sorted(set(vals.tolist()))
    codes = np.zeros(len(objs), dtype=np.int32)
    if vocab:
        vocab_arr = np.array(vocab, dtype=object)
        codes[valid] = np.searchsorted(vocab_arr, vals).astype(np.int32)
    return codes, vocab


def build_tileset(chunk: Chunk, tile_rows: int = TILE_ROWS) -> HostTileSet:
    """Split a (possibly huge) chunk into padded device-ready tiles."""
    n = chunk.num_rows
    fts = chunk.field_types()
    cols_data: list[np.ndarray] = []
    dicts: list[list | None] = []
    for c in chunk.columns:
        if c.is_varlen():
            codes, vocab = _dict_encode(c.data, c.valid)
            cols_data.append(codes)
            dicts.append(vocab)
        else:
            cols_data.append(c.data)
            dicts.append(None)
    tiles = []
    for lo in range(0, max(n, 1), tile_rows):
        hi = min(lo + tile_rows, n)
        cnt = hi - lo
        tdata, tvalid = [], []
        for data, col in zip(cols_data, chunk.columns):
            pad = tile_rows - cnt
            d = data[lo:hi]
            v = col.valid[lo:hi]
            if pad:
                d = np.concatenate([d, np.zeros(pad, dtype=d.dtype)])
                v = np.concatenate([v, np.zeros(pad, dtype=bool)])
            tdata.append(np.ascontiguousarray(d))
            tvalid.append(np.ascontiguousarray(v))
        tiles.append(DeviceTile(n_rows=cnt, data=tdata, valid=tvalid))
    return HostTileSet(fts=fts, tiles=tiles, dicts=dicts, total_rows=n)

"""Online DDL worker — F1-style asynchronous schema change
(ref: ddl/ddl_worker.go:490 handleDDLJobQueue, ddl/index.go onCreateIndex,
ddl/backfilling.go:546 writePhysicalTableRecord, ddl/reorg.go checkpoints).

ADD INDEX walks delete_only → write_only → write_reorg → public, one meta
transaction + schema-version bump per transition, so any concurrent
session (which reloads the schema per statement) is at most one state
behind — the F1 invariant that makes dual-writes + backfill safe:

  delete_only : new index accepts deletes only (no dangling entries when
                a one-state-behind session deletes a row)
  write_only  : DML dual-writes the index, readers don't use it
  write_reorg : backfill copies snapshot rows in batches; the done-handle
                checkpoint persists in the job so an interrupted reorg
                resumes where it stopped
  public      : readable; unique constraints enforced at write time

The single-process owner is a lock on the worker (the etcd election seam,
owner/manager.go:94, collapses to in-process mutual exclusion here).
`hook` is the test seam for interleaving DML between transitions
(ref: ddl/callback.go).
"""

from __future__ import annotations

from threading import RLock

from ..catalog.meta import Meta
from ..codec import tablecodec
from ..planner.ranger import prefix_next
from ..errors import DuplicateEntry, TiDBError
from ..utils import metrics as M
from ..utils.failpoint import inject as _fp
from .jobs import (
    DDLJob,
    JOB_DONE,
    JOB_QUEUED,
    JOB_ROLLBACK,
    JOB_RUNNING,
    ST_DELETE_ONLY,
    ST_NONE,
    ST_PUBLIC,
    ST_WRITE_ONLY,
    ST_WRITE_REORG,
)

BACKFILL_BATCH = 256  # rows per reorg txn (ref: ddl.reorg batch size)

ADD_INDEX_STATES = [ST_DELETE_ONLY, ST_WRITE_ONLY, ST_WRITE_REORG, ST_PUBLIC]
DROP_INDEX_STATES = [ST_WRITE_ONLY, ST_DELETE_ONLY, ST_NONE]


class DDLWorker:
    def __init__(self, storage):
        self.storage = storage
        self._lock = RLock()  # in-process serialization of the run loop
        self.hook = None  # callable(event: str, job: DDLJob) — test seam
        # cross-process serialization: the election over the shared meta
        # keyspace (ref: owner/manager.go CampaignOwner — only the owner
        # may drive the job queue; a second attached process campaigns
        # against the same record)
        from .owner import OwnerManager

        self.owner = OwnerManager(storage)

    def _fire(self, event: str, job: DDLJob) -> None:
        if self.hook is not None:
            self.hook(event, job)

    # --- queue driving -----------------------------------------------------

    def enqueue(self, job_type: str, table_id: int, args: dict) -> int:
        txn = self.storage.begin()
        m = Meta(txn)
        job = DDLJob(m.alloc_id(), job_type, table_id, args)
        m.put_job(job)
        txn.commit()
        return job.id

    def run_until_done(self, job_id: int) -> DDLJob:
        """Drive the queue until `job_id` finishes (the doDDLJob wait loop,
        ddl.go:562). Raises the job's error if it rolled back."""
        with self._lock:
            # block until elected (the etcd campaign WAITS for the seat;
            # a crashed predecessor's lease parks us at most one TTL —
            # ref: owner/manager.go campaignLoop)
            import time as _t

            deadline = _t.time() + self.owner.lease_s + 5
            while not self.owner.campaign():
                if _t.time() > deadline:
                    raise TiDBError(
                        f"not the DDL owner (current: {self.owner.get_owner_id()})"
                    )
                _t.sleep(0.1)
            while True:
                txn = self.storage.begin()
                m = Meta(txn)
                done = m.history_job(job_id)
                job = m.first_job()
                txn.rollback()
                if done is not None:
                    if done.state == JOB_ROLLBACK:
                        err = done.error or "DDL job rolled back"
                        if "Duplicate entry" in err:
                            raise DuplicateEntry(err)
                        raise TiDBError(err)
                    return done
                if job is None:
                    raise TiDBError(f"DDL job {job_id} vanished from the queue")
                self._step(job)
                # lease keepalive between steps (Proclaim): a reorg longer
                # than the TTL must not silently lose the seat mid-job
                self.owner.renew()

    def run_pending(self) -> None:
        """Drain the whole queue (background-owner mode)."""
        with self._lock:
            while True:
                txn = self.storage.begin()
                job = Meta(txn).first_job()
                txn.rollback()
                if job is None:
                    return
                self._step(job)

    # --- job execution -----------------------------------------------------

    INGEST_PARK_S = 30.0  # max wait for a bulk-ingest window before erroring

    def _step(self, job: DDLJob) -> None:
        """Run ONE state transition (or one backfill round) of the job."""
        if self.storage.table_ingesting(job.table_id):
            # bulk-ingest exclusion (PR 15): a live ingest window on the
            # target table parks the job — no schema transition may land
            # under rows encoded against the pre-transition schema. The
            # wait is BOUNDED: the job queue is serial (as in the
            # reference), so an unbounded park would head-of-line-block
            # every other table's DDL behind one leaked window; past the
            # deadline the step fails typed and the job stays queued.
            import time as _t

            deadline = _t.time() + self.INGEST_PARK_S
            while self.storage.table_ingesting(job.table_id):
                if _t.time() > deadline:
                    raise TiDBError(
                        f"DDL job {job.id} parked behind a bulk-ingest window "
                        f"on table {job.table_id} for {self.INGEST_PARK_S:.0f}s "
                        f"— retry after the ingest finishes"
                    )
                _t.sleep(0.02)
        if job.type == "add_index":
            self._step_add_index(job)
        elif job.type == "drop_index":
            self._step_drop_index(job)
        else:
            self._finish(job, JOB_ROLLBACK, error=f"unknown DDL job type {job.type!r}")

    def _set_index_state(self, job: DDLJob, new_state: str) -> None:
        """One meta txn: flip the index state + bump schema version +
        persist job progress (ref: updateSchemaVersion per transition)."""
        txn = self.storage.begin()
        m = Meta(txn)
        t = m.table(job.table_id)
        idx = next((i for i in t.indexes if i.id == job.args["index_id"]), None)
        if idx is None:
            txn.rollback()
            raise TiDBError(f"index {job.args['index_id']} missing during DDL job {job.id}")
        idx.state = new_state
        m.put_table(t)
        job.schema_state = new_state
        job.state = JOB_RUNNING
        m.put_job(job)
        m.bump_schema_version()
        txn.commit()
        self._fire(f"state:{new_state}", job)

    def _finish(self, job: DDLJob, state: str, error: str | None = None) -> None:
        txn = self.storage.begin()
        m = Meta(txn)
        job.state = state
        job.error = error
        m.finish_job(job)
        m.bump_schema_version()
        txn.commit()
        M.DDL_JOBS.inc(type=job.type, state=state)
        self._fire("finish", job)

    # --- ADD INDEX ---------------------------------------------------------

    def _step_add_index(self, job: DDLJob) -> None:
        cur = job.schema_state
        if cur == ST_NONE:
            self._set_index_state(job, ST_DELETE_ONLY)
        elif cur == ST_DELETE_ONLY:
            self._set_index_state(job, ST_WRITE_ONLY)
        elif cur == ST_WRITE_ONLY:
            self._set_index_state(job, ST_WRITE_REORG)
        elif cur == ST_WRITE_REORG:
            finished = self._backfill_batch(job)
            if finished:
                self._set_index_state(job, ST_PUBLIC)
        elif cur == ST_PUBLIC:
            self._finish(job, JOB_DONE)

    def _backfill_batch(self, job: DDLJob) -> bool:
        """Copy one batch of snapshot rows into the index; the done-handle
        checkpoint commits atomically with the entries (ref:
        backfilling.go:546 + BackfillDataInTxn). Returns True when the
        table is exhausted."""
        from ..table.table import Table

        txn = self.storage.begin()
        m = Meta(txn)
        t = m.table(job.table_id)
        idx = next(i for i in t.indexes if i.id == job.args["index_id"])
        tbl = Table(t)
        prefix = tablecodec.record_prefix(t.id)
        start = prefix if job.reorg_handle is None else tablecodec.record_key(t.id, job.reorg_handle + 1)
        batch = int(job.args.get("reorg_batch_size", BACKFILL_BATCH))
        rows = txn.scan(start, prefix_next(prefix), limit=batch)
        last_handle = None
        for k, v in rows:
            handle = tablecodec.decode_record_handle(k)
            datums = tbl.decode_record(v)
            key, val, distinct = tbl.index_value_key(idx, datums, handle)
            if distinct:
                existing = txn.get(key)
                # a dual-written entry for the same handle/value is fine;
                # a different one is a real duplicate → roll the job back
                if existing is not None and existing != val:
                    txn.rollback()
                    self._rollback_add_index(job)
                    return False
            txn.put(key, val)
            last_handle = handle
        if last_handle is not None:
            job.reorg_handle = last_handle
            m.put_job(job)
        from ..errors import RetryableError, WriteConflict

        try:
            _fp("ddl/before-backfill-commit")
            txn.commit()
        except (WriteConflict, RetryableError):
            # concurrent DML dual-wrote a key this batch staged: the batch
            # simply re-runs from the unchanged checkpoint (ref: reorg txn
            # retry in backfilling.go)
            return False
        if last_handle is not None:
            # crashpoint: a backfill batch + its done-handle checkpoint are
            # durable, the index is still write_reorg — recovery must resume
            # from the checkpoint and finish to public (or the index stays
            # invisible to readers), never serve a half-built index
            _fp("ddl/mid-reorg")
            self._fire("backfill_batch", job)
        return len(rows) < batch

    def _rollback_add_index(self, job: DDLJob) -> None:
        """Duplicate data found mid-reorg: retract the index (reverse
        transitions) and finish the job rolled-back (ref: rollingback.go)."""
        for st in (ST_WRITE_ONLY, ST_DELETE_ONLY):
            self._set_index_state(job, st)
        txn = self.storage.begin()
        m = Meta(txn)
        t = m.table(job.table_id)
        t.indexes = [i for i in t.indexes if i.id != job.args["index_id"]]
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        self._destroy_index_ranges(t, job.args["index_id"])
        self._finish(job, JOB_ROLLBACK, error=f"Duplicate entry for key {job.args.get('index_name')!r}")

    def _destroy_index_ranges(self, t, index_id: int) -> None:
        """Deferred index data removal over EVERY physical keyspace —
        partition-local index entries live under the partition ids
        (ref: ddl/delete_range.go insertJobIntoDeleteRangeTable)."""
        for pid in t.physical_ids():
            self.storage.mvcc.unsafe_destroy_range(
                tablecodec.index_prefix(pid, index_id),
                tablecodec.index_prefix(pid, index_id + 1),
            )

    # --- DROP INDEX --------------------------------------------------------

    def _step_drop_index(self, job: DDLJob) -> None:
        cur = job.schema_state
        if cur == ST_NONE:
            # entry point: job starts with the index public
            job.schema_state = ST_PUBLIC
            self._step_drop_index(job)
        elif cur == ST_PUBLIC:
            self._set_index_state(job, ST_WRITE_ONLY)
        elif cur == ST_WRITE_ONLY:
            self._set_index_state(job, ST_DELETE_ONLY)
        elif cur == ST_DELETE_ONLY:
            txn = self.storage.begin()
            m = Meta(txn)
            t = m.table(job.table_id)
            t.indexes = [i for i in t.indexes if i.id != job.args["index_id"]]
            m.put_table(t)
            m.bump_schema_version()
            txn.commit()
            # deferred data removal (ref: ddl/delete_range.go)
            self._destroy_index_ranges(t, job.args["index_id"])
            self._fire("state:none", job)
            self._finish(job, JOB_DONE)

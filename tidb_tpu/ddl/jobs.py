"""DDL jobs — the persisted unit of online schema change
(ref: model Job in the reference's parser/model; queued via ddl.go:535
doDDLJob into meta job queues, executed by ddl_worker.go:490)."""

from __future__ import annotations

from dataclasses import dataclass, field

# F1-style schema states (ref: model.SchemaState; ddl_worker.go runs each
# object through none → delete_only → write_only → write_reorg → public,
# bumping the schema version per transition so concurrent sessions are at
# most one state apart)
ST_NONE = "none"
ST_DELETE_ONLY = "delete_only"
ST_WRITE_ONLY = "write_only"
ST_WRITE_REORG = "write_reorg"
ST_PUBLIC = "public"

# job queue states (ref: model.JobState)
JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_DONE = "done"
JOB_ROLLBACK = "rollback_done"


@dataclass
class DDLJob:
    id: int
    type: str  # add_index | drop_index
    table_id: int
    args: dict = field(default_factory=dict)
    state: str = JOB_QUEUED
    schema_state: str = ST_NONE
    reorg_handle: int | None = None  # backfill checkpoint (ref: ddl/reorg.go)
    error: str | None = None

    def to_json(self) -> dict:
        return {
            "id": self.id,
            "type": self.type,
            "table_id": self.table_id,
            "args": self.args,
            "state": self.state,
            "schema_state": self.schema_state,
            "reorg_handle": self.reorg_handle,
            "error": self.error,
        }

    @staticmethod
    def from_json(d: dict) -> "DDLJob":
        return DDLJob(
            d["id"], d["type"], d["table_id"], d.get("args", {}), d.get("state", JOB_QUEUED),
            d.get("schema_state", ST_NONE), d.get("reorg_handle"), d.get("error"),
        )

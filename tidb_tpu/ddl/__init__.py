from .jobs import DDLJob
from .worker import DDLWorker

__all__ = ["DDLJob", "DDLWorker"]

"""Owner election over the meta keyspace (ref: owner/manager.go:94
CampaignOwner + domain/infosync/info.go — etcd lease/campaign semantics
re-expressed over the store's own transactional KV).

The reference elects one DDL owner per cluster through an etcd session
lease; every tidb-server campaigns and the winner runs the DDL worker.
This framework is single-process today, but the ELECTION RUNS THROUGH
THE SHARED KEYSPACE, not through process-local state: a second process
attached to the same store would campaign against the same key and the
protocol would hold — the seam the reference's multi-node schema change
needs is real, not a stub.

Protocol (the etcd Campaign/Proclaim/Resign triple over MVCC txns):
  campaign():  txn-read the owner record; if absent or its lease expired,
               txn-write (owner_id, lease_deadline) — write conflicts
               mean another campaigner won, retry/observe.
  renew():     owner extends its lease (Proclaim); losing the record
               (another owner) demotes.
  resign():    delete the record iff still owned; others may campaign.
"""

from __future__ import annotations

import time
import uuid

OWNER_KEY = b"m:owner:ddl"  # meta keyspace, shared by every attached node
DEFAULT_LEASE_S = 45.0  # ref: owner.ManagerSessionTTL


def _encode(owner_id: str, deadline: float) -> bytes:
    return f"{owner_id}|{deadline:.6f}".encode()


def _decode(raw: bytes) -> tuple[str, float]:
    s = raw.decode()
    oid, dl = s.rsplit("|", 1)
    return oid, float(dl)


class OwnerManager:
    """One campaigner (ref: owner.NewOwnerManager). Thread-safe at the
    txn layer: all state transitions go through the store's MVCC commits,
    so concurrent campaigners serialize on write conflicts."""

    def __init__(self, storage, key: bytes = OWNER_KEY, lease_s: float = DEFAULT_LEASE_S):
        self.storage = storage
        self.key = key
        self.lease_s = lease_s
        self.id = uuid.uuid4().hex[:12]

    # ------------------------------------------------------------ queries

    def get_owner_id(self) -> str | None:
        """Current owner per the shared record, None if the seat is empty
        or the lease lapsed (ref: manager.go GetOwnerID)."""
        txn = self.storage.begin()
        try:
            raw = txn.get(self.key)
        finally:
            txn.rollback()
        if raw is None:
            return None
        oid, deadline = _decode(raw)
        if deadline < time.time():
            return None
        return oid

    def is_owner(self) -> bool:
        return self.get_owner_id() == self.id

    # -------------------------------------------------------- transitions

    def campaign(self) -> bool:
        """Try to take (or keep) the seat; True iff this manager owns it
        afterwards. A write conflict means a rival won — report their
        victory instead of retrying blindly (the caller's watch loop
        decides cadence, like the etcd campaign watch)."""
        from ..errors import RetryableError, WriteConflict

        txn = self.storage.begin()
        try:
            raw = txn.get(self.key)
            if raw is not None:
                oid, deadline = _decode(raw)
                if deadline >= time.time() and oid != self.id:
                    txn.rollback()
                    return False  # live rival owner
            txn.put(self.key, _encode(self.id, time.time() + self.lease_s))
            txn.commit()
            return True
        except (WriteConflict, RetryableError):
            return self.is_owner()
        except Exception:
            txn.rollback()
            raise

    def renew(self) -> bool:
        """Extend the lease while still owner (Proclaim); False demotes."""
        from ..errors import RetryableError, WriteConflict

        txn = self.storage.begin()
        try:
            raw = txn.get(self.key)
            if raw is None or _decode(raw)[0] != self.id:
                txn.rollback()
                return False
            txn.put(self.key, _encode(self.id, time.time() + self.lease_s))
            txn.commit()
            return True
        except (WriteConflict, RetryableError):
            return False
        except Exception:
            txn.rollback()
            raise

    def resign(self) -> None:
        """Give the seat up iff still holding it (ref: manager Resign)."""
        from ..errors import RetryableError, WriteConflict

        txn = self.storage.begin()
        try:
            raw = txn.get(self.key)
            if raw is None or _decode(raw)[0] != self.id:
                txn.rollback()
                return
            txn.delete(self.key)
            txn.commit()
        except (WriteConflict, RetryableError):
            pass
        except Exception:
            txn.rollback()
            raise

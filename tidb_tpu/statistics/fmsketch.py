"""Flajolet-Martin sketch for NDV estimation (ref: statistics/fmsketch.go —
numpy mask-based redesign)."""

from __future__ import annotations

import numpy as np


class FMSketch:
    __slots__ = ("mask", "hashset", "max_size")

    def __init__(self, max_size: int = 10000):
        self.mask = np.uint64(0)
        self.hashset: set[int] = set()
        self.max_size = max_size

    def insert_hashes(self, hashes: np.ndarray) -> None:
        for h in hashes.tolist():
            h = int(h)
            if h & int(self.mask) != 0:
                continue
            self.hashset.add(h)
            while len(self.hashset) > self.max_size:
                self.mask = np.uint64((int(self.mask) << 1) | 1)
                self.hashset = {x for x in self.hashset if x & int(self.mask) == 0}

    def ndv(self) -> int:
        return (int(self.mask) + 1) * len(self.hashset)

    def merge(self, other: "FMSketch") -> None:
        mask = max(int(self.mask), int(other.mask))
        merged = {x for x in self.hashset | other.hashset if x & mask == 0}
        self.mask = np.uint64(mask)
        self.hashset = merged
        while len(self.hashset) > self.max_size:
            self.mask = np.uint64((int(self.mask) << 1) | 1)
            self.hashset = {x for x in self.hashset if x & int(self.mask) == 0}

    def serialize(self) -> bytes:
        """Wire form for APPROX_COUNT_DISTINCT partial transport: little-
        endian mask then the hash set (ref: aggfuncs approx_count_distinct
        partial encoding)."""
        import struct

        hs = np.array(sorted(self.hashset), dtype=np.uint64)
        return struct.pack("<Q", int(self.mask)) + hs.tobytes()

    @staticmethod
    def deserialize(b: bytes, max_size: int = 10000) -> "FMSketch":
        import struct

        sk = FMSketch(max_size)
        sk.mask = np.uint64(struct.unpack_from("<Q", b)[0])
        sk.hashset = set(np.frombuffer(b[8:], dtype=np.uint64).tolist())
        return sk

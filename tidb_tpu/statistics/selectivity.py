"""Selectivity estimation over pushed-down conjuncts (ref: statistics/
selectivity.go:177 Selectivity — simplified to per-conjunct independence,
which is what the planner needs for access-path and join-side choices)."""

from __future__ import annotations

from dataclasses import dataclass

from ..planner.ranger import _simple_cond, const_to_col_datum
from .tablestats import TableStats, surrogate_datum

SELECTION_FACTOR = 0.8  # default for unmatchable conds (ref: selectionFactor)


def cond_selectivity(ts: TableStats, cond, visible_cols) -> float:
    """Fraction of rows one conjunct keeps."""
    if ts.row_count <= 0:
        return 1.0
    s = _simple_cond(cond)
    if s is None:
        name = getattr(getattr(cond, "sig", None), "name", "")
        if name == "isnull":
            arg = cond.args[0]
            idx = getattr(arg, "idx", None)
            if idx is not None and 0 <= idx < len(visible_cols):
                cs = ts.col(visible_cols[idx].id)
                if cs is not None and cs.total > 0:
                    return cs.null_count / cs.total
        return SELECTION_FACTOR
    off, op, vals = s
    if off >= len(visible_cols):
        return SELECTION_FACTOR
    col = visible_cols[off]
    cs = ts.col(col.id)
    if cs is None or cs.total <= 0:
        return SELECTION_FACTOR
    if op in ("eq", "in"):
        rows = 0.0
        for v in vals:
            d = const_to_col_datum(v, col.ft)
            if d is None:
                continue
            sur = surrogate_datum(d, col.ft)
            if sur is None:
                continue
            rows += cs.eq_rows(sur)
        return min(rows / ts.row_count, 1.0)
    # range ops
    d = const_to_col_datum(vals[0], col.ft)
    sur = surrogate_datum(d, col.ft) if d is not None else None
    if sur is None:
        return 1 / 3.0
    if op in ("gt", "ge"):
        rows = cs.range_rows(sur, None, op == "ge", False)
    else:
        rows = cs.range_rows(None, sur, False, op == "le")
    return min(rows / ts.row_count, 1.0)


def estimate_conds(ts: TableStats | None, conds, visible_cols) -> float:
    """Combined selectivity of a conjunct list (independence assumption)."""
    if ts is None:
        sel = 1.0
        for _ in conds:
            sel *= SELECTION_FACTOR
        return sel
    sel = 1.0
    for c in conds:
        sel *= cond_selectivity(ts, c, visible_cols)
    return sel


@dataclass
class AccessEstimate:
    rows: float  # estimated rows the access path returns
    total: float  # table row count used

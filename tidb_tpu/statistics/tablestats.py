"""Per-table statistics built from columnar batches (ref: statistics/
builder.go + executor/analyze.go — here ANALYZE reads the same ColumnBatch
tiles the cop engines scan, so stats build is itself a columnar pass)."""

from __future__ import annotations

import numpy as np

from ..mysqltypes.field_type import FieldType
from ..mysqltypes.datum import Datum, K_STR, K_BYTES
from ..mysqltypes.mydecimal import pow10
from .cmsketch import CMSketch, TopN, hash_values
from .histogram import Histogram

SAMPLE_CAP = 65536  # histogram build sample cap (reference: maxSampleSize)
TOPN_SIZE = 20


def _str_surrogate(s) -> float:
    """Order-preserving float from the first 8 bytes of a string."""
    b = (s if isinstance(s, bytes) else str(s).encode("utf8"))[:8].ljust(8, b"\x00")
    return float(int.from_bytes(b, "big"))


def surrogate_lane(data: np.ndarray, valid: np.ndarray, ft: FieldType) -> np.ndarray:
    """Non-null values → order-preserving float64 surrogate array."""
    sel = data[valid] if valid is not None else data
    if sel.dtype == object:
        return np.array([_str_surrogate(v) for v in sel], dtype=np.float64)
    if ft is not None and ft.is_decimal():
        return sel.astype(np.float64) / pow10(max(ft.decimal, 0))
    return sel.astype(np.float64)


def surrogate_datum(d: Datum, ft: FieldType) -> float | None:
    if d.is_null:
        return None
    if d.kind in (K_STR, K_BYTES):
        return _str_surrogate(d.val)
    if ft is not None and ft.is_decimal():
        dec = d.to_dec()
        return dec.value / pow10(dec.scale) if dec.scale else float(dec.value)
    try:
        return float(d.to_float())
    except (TypeError, ValueError):
        return None


class ColumnStats:
    __slots__ = ("hist", "cms", "topn", "ndv", "null_count", "total")

    def __init__(self, hist, cms, topn, ndv, null_count, total):
        self.hist = hist
        self.cms = cms
        self.topn = topn
        self.ndv = int(ndv)
        self.null_count = int(null_count)
        self.total = int(total)

    @property
    def non_null(self) -> int:
        return self.total - self.null_count

    def eq_rows(self, surrogate: float) -> float:
        """Estimated rows equal to one value (TopN exact → CMS → hist avg)."""
        h = int(hash_values(np.array([surrogate]))[0])
        if self.topn is not None:
            t = self.topn.get(h)
            if t is not None:
                return float(t)
        if self.cms is not None:
            c = self.cms.query_hash(h)
            # CMS overcounts; trust it only when it's below the hist average
            avg = self.hist.equal_row_count(surrogate) if self.hist else self.non_null / max(self.ndv, 1)
            return float(min(c, avg * 4)) if c > 0 else min(1.0, float(self.non_null))
        if self.hist is not None:
            return self.hist.equal_row_count(surrogate)
        return self.non_null / max(self.ndv, 1)

    def range_rows(self, lo, hi, lo_incl, hi_incl) -> float:
        if self.hist is None:
            return self.non_null / 3.0
        return self.hist.range_row_count(lo, hi, lo_incl, hi_incl)

    def to_json(self):
        return {
            "hist": self.hist.to_json() if self.hist else None,
            "cms": self.cms.to_json() if self.cms else None,
            "topn": self.topn.to_json() if self.topn else None,
            "ndv": self.ndv, "null_count": self.null_count, "total": self.total,
        }

    @staticmethod
    def from_json(d) -> "ColumnStats":
        return ColumnStats(
            Histogram.from_json(d["hist"]) if d["hist"] else None,
            CMSketch.from_json(d["cms"]) if d["cms"] else None,
            TopN.from_json(d["topn"]) if d["topn"] else None,
            d["ndv"], d["null_count"], d["total"],
        )


class TableStats:
    __slots__ = ("table_id", "row_count", "version", "columns", "modify_count")

    def __init__(self, table_id: int, row_count: int, version: int, columns: dict[int, ColumnStats]):
        self.table_id = table_id
        self.row_count = int(row_count)
        self.version = version
        self.columns = columns  # by column id
        self.modify_count = 0

    def col(self, col_id: int) -> ColumnStats | None:
        return self.columns.get(col_id)

    def to_json(self):
        return {
            "table_id": self.table_id,
            "row_count": self.row_count,
            "version": self.version,
            "modify_count": self.modify_count,
            "columns": {str(k): v.to_json() for k, v in self.columns.items()},
        }

    @staticmethod
    def from_json(d) -> "TableStats":
        ts = TableStats(
            d["table_id"], d["row_count"], d["version"],
            {int(k): ColumnStats.from_json(v) for k, v in d["columns"].items()},
        )
        ts.modify_count = d.get("modify_count", 0)
        return ts


def build_column_stats(data: np.ndarray, valid: np.ndarray, ft: FieldType) -> ColumnStats:
    total = len(data)
    null_count = total - int(valid.sum())
    sur = surrogate_lane(data, valid, ft)
    n = len(sur)
    if n == 0:
        return ColumnStats(None, None, None, 0, null_count, total)
    # exact NDV + value counts on the (possibly huge) lane — numpy unique
    # is O(n log n), fine for analyze
    uniq, counts = np.unique(sur, return_counts=True)
    ndv = len(uniq)
    # TopN: heaviest repeated values kept exact; CMS takes the remainder
    uh = hash_values(uniq)
    order = np.argsort(counts)[::-1][:TOPN_SIZE]
    topn_items: dict[int, int] = {}
    topn_idx = []
    for i in order:
        if counts[i] > 1:
            topn_items[int(uh[i])] = int(counts[i])
            topn_idx.append(i)
    topn = TopN(topn_items)
    mask = np.ones(len(uniq), dtype=bool)
    if topn_idx:
        mask[np.array(topn_idx)] = False
    cms = CMSketch()
    cms.insert_many(uh[mask], counts[mask])
    # histogram from a sample of the raw lane (equi-depth wants row-level
    # distribution, not distinct values)
    if n > SAMPLE_CAP:
        step = n // SAMPLE_CAP
        sample = sur[::step]
    else:
        sample = sur
    hist = Histogram.build(sample, n, ndv)
    return ColumnStats(hist, cms, topn, ndv, null_count, total)


def build_table_stats(table, batches, version: int) -> TableStats:
    """batches: iterable of ColumnBatch covering the table's regions."""
    visible = table.visible_columns()
    data_parts: dict[int, list] = {c.offset: [] for c in visible}
    valid_parts: dict[int, list] = {c.offset: [] for c in visible}
    rows = 0
    for b in batches:
        rows += b.n_rows
        for c in visible:
            data_parts[c.offset].append(b.data[c.offset])
            valid_parts[c.offset].append(b.valid[c.offset])
    columns: dict[int, ColumnStats] = {}
    for c in visible:
        if not data_parts[c.offset]:
            continue
        data = np.concatenate(data_parts[c.offset])
        valid = np.concatenate(valid_parts[c.offset])
        columns[c.id] = build_column_stats(data, valid, c.ft)
    return TableStats(table.id, rows, version, columns)

"""Statistics & CBO inputs (ref: statistics/ — histogram.go, cmsketch.go,
fmsketch.go, selectivity.go, handle/)."""

from .histogram import Histogram
from .cmsketch import CMSketch, TopN
from .fmsketch import FMSketch
from .tablestats import ColumnStats, TableStats, build_table_stats, surrogate_lane
from .handle import StatsHandle
from .selectivity import estimate_conds, AccessEstimate

__all__ = [
    "Histogram", "CMSketch", "TopN", "FMSketch",
    "ColumnStats", "TableStats", "build_table_stats", "surrogate_lane",
    "StatsHandle", "estimate_conds", "AccessEstimate",
]

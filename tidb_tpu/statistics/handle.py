"""Stats lifecycle: cache, DML deltas, persistence, auto-analyze policy
(ref: statistics/handle/handle.go:74, update.go:866 NeedAnalyzeTable).

The handle hangs off Storage so every session over the store shares one
stats view (the reference loads from mysql.stats_* tables; here stats
persist as JSON blobs in the meta keyspace `m_stats_{table_id}`)."""

from __future__ import annotations

import json

from ..codec import tablecodec
from ..planner.ranger import prefix_next
from .tablestats import TableStats, build_table_stats

AUTO_ANALYZE_RATIO = 0.5
AUTO_ANALYZE_MIN_COUNT = 1000

_STATS_PREFIX = b"m_stats_"


def _stats_key(table_id: int) -> bytes:
    return _STATS_PREFIX + str(table_id).encode()


class StatsHandle:
    def __init__(self, storage):
        self.storage = storage
        self.cache: dict[int, TableStats] = {}
        self.generation = 0  # bumped on stats writes; plan caches key on it

    # --- access ------------------------------------------------------------

    def get(self, table_id: int) -> TableStats | None:
        ts = self.cache.get(table_id)
        if ts is not None:
            return ts
        raw = self.storage.mvcc.get(_stats_key(table_id), self.storage.tso.current())
        if raw is None:
            return None
        ts = TableStats.from_json(json.loads(raw))
        self.cache[table_id] = ts
        return ts

    # --- analyze -----------------------------------------------------------

    def analyze_table(self, session, info) -> TableStats:
        """Full-table stats build over the cop client's columnar batches
        (ref: executor/analyze.go pushing sample collection to the store)."""
        read_ts = session.store.tso.next()
        cop = session.cop
        batches = []
        for pid in info.physical_ids():
            phys = info.partition_physical(pid) if info.partition else info
            prefix = tablecodec.record_prefix(pid)
            for region, s, e in session.store.regions.split_ranges(prefix, prefix_next(prefix)):
                batches.append(cop.tiles.get_batch(phys, s, e, read_ts))
        ts = build_table_stats(info, batches, read_ts)
        self.save(ts, session)
        return ts

    def save(self, ts: TableStats, session) -> None:
        self.generation += 1
        self.cache[ts.table_id] = ts
        txn = session.store.begin()
        txn.put(_stats_key(ts.table_id), json.dumps(ts.to_json()).encode())
        txn.commit()

    def dump(self, session, info, build_if_missing: bool = False) -> dict | None:
        """JSON stats dump for one table (ref: statistics/handle/dump.go
        DumpStatsToJSON; column ids are carried with their names so a
        load can remap onto a re-created table). Returns None when no
        stats exist unless build_if_missing — HTTP GETs must not trigger
        a full ANALYZE as a side effect."""
        ts = self.get(info.id)
        if ts is None:
            if not build_if_missing:
                return None
            ts = self.analyze_table(session, info)
        return {
            "database_name": info.db_name,
            "table_name": info.name,
            "stats": ts.to_json(),
            "col_names": {str(c.id): c.name for c in info.columns},
        }

    def load_dump(self, session, d: dict) -> None:
        """Install a dumped stats JSON onto the current schema's table of
        the same name, remapping column ids by column NAME (ref:
        handle/dump.go LoadStatsFromJSON)."""
        info = session.infoschema().table(d["database_name"], d["table_name"])
        ts = TableStats.from_json(d["stats"])
        name_by_old = {int(k): v for k, v in d.get("col_names", {}).items()}
        cur_by_name = {c.name.lower(): c.id for c in info.columns}
        cols = {}
        for old_id, cs in ts.columns.items():
            new_id = cur_by_name.get((name_by_old.get(old_id) or "").lower())
            if new_id is not None:  # dropped/renamed columns are skipped,
                cols[new_id] = cs   # never attached to an unrelated id
        ts.columns = cols
        ts.table_id = info.id
        self.save(ts, session)

    def drop_table(self, table_id: int, session) -> None:
        self.cache.pop(table_id, None)
        txn = session.store.begin()
        txn.delete(_stats_key(table_id))
        txn.commit()

    # --- DML delta + auto-analyze (ref: handle/update.go) -------------------

    def report_delta(self, table_id: int, changed: int, delta_rows: int = 0) -> None:
        self.generation += 1  # DML re-costs: plan caches must not go stale
        ts = self.cache.get(table_id)
        if ts is not None:
            ts.modify_count += changed
            ts.row_count = max(0, ts.row_count + delta_rows)

    def needs_analyze(self, table_id: int) -> bool:
        ts = self.cache.get(table_id)
        if ts is None:
            return False
        if ts.modify_count < AUTO_ANALYZE_MIN_COUNT:
            return False
        return ts.modify_count > ts.row_count * AUTO_ANALYZE_RATIO

    def auto_analyze(self, session) -> list[int]:
        """Re-analyze any table whose modify ratio crossed the trigger
        (ref: domain.go:1337 autoAnalyzeWorker — called at statement
        boundaries instead of from a background loop)."""
        done = []
        for tid in list(self.cache):
            if self.needs_analyze(tid):
                info = session.infoschema().table_by_id(tid)
                if info is not None:
                    self.analyze_table(session, info)
                    done.append(tid)
        return done

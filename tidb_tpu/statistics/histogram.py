"""Equi-depth histogram (ref: statistics/histogram.go:48 — redesigned as
numpy bucket arrays over a numeric surrogate domain).

Values of every SQL type map to an order-preserving float64 surrogate
(ints/times as-is, decimals descaled, strings via an 8-byte big-endian
prefix of the key encoding), so one array-based histogram implementation
covers all types; estimates only need order, not exact values.
"""

from __future__ import annotations

import numpy as np


class Histogram:
    """`uppers[i]` is the inclusive upper bound of bucket i; `cum[i]` is the
    cumulative row count through bucket i. Built equi-depth from a sorted
    (possibly sampled) value array, scaled to the true non-null count."""

    __slots__ = ("uppers", "lowers", "cum", "total", "ndv")

    def __init__(self, uppers: np.ndarray, lowers: np.ndarray, cum: np.ndarray, total: float, ndv: int):
        self.uppers = uppers
        self.lowers = lowers
        self.cum = cum
        self.total = float(total)
        self.ndv = int(ndv)

    @staticmethod
    def build(values: np.ndarray, total_rows: int, ndv: int, n_buckets: int = 64) -> "Histogram | None":
        """values: non-null surrogate array (unsorted ok)."""
        n = len(values)
        if n == 0:
            return None
        v = np.sort(values.astype(np.float64))
        n_buckets = max(1, min(n_buckets, n))
        # equi-depth split points
        idx = np.linspace(0, n, n_buckets + 1).astype(np.int64)
        idx = np.unique(idx)
        uppers = v[np.clip(idx[1:] - 1, 0, n - 1)]
        lowers = v[np.clip(idx[:-1], 0, n - 1)]
        counts = np.diff(idx).astype(np.float64)
        scale = total_rows / n
        cum = np.cumsum(counts) * scale
        return Histogram(uppers, lowers, cum, total_rows, ndv)

    def less_row_count(self, x: float) -> float:
        """Rows with value < x (linear interpolation inside a bucket,
        ref: histogram.go lessRowCountWithBktIdx)."""
        if self.total <= 0:
            return 0.0
        b = int(np.searchsorted(self.uppers, x, side="left"))
        if b >= len(self.uppers):
            return self.total
        prev = self.cum[b - 1] if b > 0 else 0.0
        in_bucket = self.cum[b] - prev
        lo, hi = self.lowers[b], self.uppers[b]
        if x <= lo:
            frac = 0.0
        elif hi > lo:
            frac = min(max((x - lo) / (hi - lo), 0.0), 1.0)
        else:
            frac = 0.0
        return prev + in_bucket * frac

    def range_row_count(self, lo: float | None, hi: float | None, lo_incl: bool, hi_incl: bool) -> float:
        lo_cnt = 0.0 if lo is None else self.less_row_count(lo) + (0.0 if lo_incl else self.equal_row_count(lo))
        hi_cnt = self.total if hi is None else self.less_row_count(hi) + (self.equal_row_count(hi) if hi_incl else 0.0)
        return max(hi_cnt - lo_cnt, 0.0)

    def equal_row_count(self, x: float) -> float:
        """Average rows per distinct value (TopN handles heavy hitters)."""
        if self.ndv <= 0:
            return 0.0
        return self.total / self.ndv

    def to_json(self):
        return {
            "uppers": self.uppers.tolist(),
            "lowers": self.lowers.tolist(),
            "cum": self.cum.tolist(),
            "total": self.total,
            "ndv": self.ndv,
        }

    @staticmethod
    def from_json(d) -> "Histogram":
        return Histogram(
            np.asarray(d["uppers"], dtype=np.float64),
            np.asarray(d["lowers"], dtype=np.float64),
            np.asarray(d["cum"], dtype=np.float64),
            d["total"], d["ndv"],
        )

"""Count-Min sketch + TopN (ref: statistics/cmsketch.go:46,503 — vectorized
numpy build instead of per-row insertion)."""

from __future__ import annotations

import numpy as np

_PRIMES = np.array([2654435761, 2246822519, 3266489917, 668265263], dtype=np.uint64)
_DEPTH = 4


class CMSketch:
    __slots__ = ("width", "table")

    def __init__(self, width: int = 2048, table: np.ndarray | None = None):
        self.width = width
        self.table = table if table is not None else np.zeros((_DEPTH, width), dtype=np.int64)

    @staticmethod
    def _rows(hashes: np.ndarray, width: int) -> np.ndarray:
        """(depth, n) bucket indices from one 64-bit hash per value."""
        h = hashes.astype(np.uint64)
        return np.stack([((h * p) >> np.uint64(17)) % np.uint64(width) for p in _PRIMES])

    def insert_many(self, hashes: np.ndarray, counts: np.ndarray) -> None:
        rows = self._rows(hashes, self.width)
        for d in range(_DEPTH):
            np.add.at(self.table[d], rows[d], counts)

    def query_hash(self, h: int) -> int:
        rows = self._rows(np.array([h], dtype=np.uint64), self.width)
        return int(min(self.table[d][rows[d][0]] for d in range(_DEPTH)))

    def merge(self, other: "CMSketch") -> None:
        self.table += other.table

    def to_json(self):
        return {"width": self.width, "table": self.table.tolist()}

    @staticmethod
    def from_json(d) -> "CMSketch":
        return CMSketch(d["width"], np.asarray(d["table"], dtype=np.int64))


class TopN:
    """Heavy hitters kept exactly, excluded from the histogram/CMS domain
    (ref: cmsketch.go TopN)."""

    __slots__ = ("items",)

    def __init__(self, items: dict[int, int] | None = None):
        self.items = items or {}  # value hash → exact count

    def get(self, h: int) -> int | None:
        return self.items.get(h)

    @property
    def total(self) -> int:
        return sum(self.items.values())

    def to_json(self):
        return {str(k): v for k, v in self.items.items()}

    @staticmethod
    def from_json(d) -> "TopN":
        return TopN({int(k): v for k, v in d.items()})


def hash_values(values: np.ndarray) -> np.ndarray:
    """Order-free 64-bit hashes for a surrogate/object lane."""
    if values.dtype == object:
        return np.array([hash(v) & 0xFFFFFFFFFFFFFFFF for v in values], dtype=np.uint64)
    v = values.astype(np.float64).view(np.uint64)
    v = (v ^ (v >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
    return v ^ (v >> np.uint64(33))

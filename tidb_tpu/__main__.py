"""Process entry — `python -m tidb_tpu` starts the MySQL-protocol server
(ref: tidb-server/main.go:157 main, :505 setGlobalVars, :621 createServer;
flags subset + graceful signal shutdown)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def load_config(path: str) -> dict:
    """TOML config file (ref: config/config.go + config.toml.example —
    the file layer below CLI flags). Recognized keys mirror the flag
    names; [log]/[security]/[gc] tables flatten into them."""
    try:
        import tomllib  # 3.11+
    except ImportError:
        # tomllib IS tomli vendored into the stdlib; on 3.10 pip's
        # vendored copy is the only API-compatible parser in the image
        from pip._vendor import tomli as tomllib

    with open(path, "rb") as f:
        raw = tomllib.load(f)
    flat: dict = {}
    for k, v in raw.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = v2
        else:
            flat[k] = v
    out = {}
    # (dest, coerce, validator) — the same constraints the CLI flags carry
    mapping = {
        "host": ("host", str, None),
        "port": ("port", int, None),
        "log.level": ("log_level", str, ("debug", "info", "warn", "error")),
        "gc.life-minutes": ("gc_life_minutes", int, None),
        "security.enable-sem": ("enable_sem", bool, None),
    }
    for src, (dst, coerce, choices) in mapping.items():
        if src not in flat:
            continue
        try:
            v = coerce(flat[src])
        except (TypeError, ValueError):
            raise SystemExit(f"config: {src} must be {coerce.__name__}, got {flat[src]!r}")
        if choices is not None and v not in choices:
            raise SystemExit(f"config: {src} must be one of {choices}, got {v!r}")
        out[dst] = v
    unknown = sorted(set(flat) - set(mapping))
    if unknown:
        logging.getLogger(__name__).warning("config: ignoring unknown keys %s", unknown)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tidb-tpu-server", description="TPU-native TiDB-compatible SQL server")
    ap.add_argument("--config", default=None, help="TOML config file (flags override it)")
    ap.add_argument("--host", default=None, help="listen address")
    ap.add_argument("-P", "--port", type=int, default=None, help="listen port (0 = ephemeral)")
    ap.add_argument("--log-level", default=None, choices=["debug", "info", "warn", "error"])
    ap.add_argument("--gc-life-minutes", type=int, default=None, help="MVCC GC retention window")
    ap.add_argument(
        "--enable-sem", action="store_true", default=None,
        help="security enhanced mode: hide restricted vars/tables, deny FILE (ref: util/sem)",
    )
    ap.add_argument("--data-dir", default=None,
                    help="durable store directory (omit for in-memory)")
    ap.add_argument(
        "--wal-spare-dirs", default=None,
        help="comma-separated spare WAL dirs for online media failover "
             "(tidb_wal_spare_dirs; requires --data-dir)",
    )
    args = ap.parse_args(argv)
    # precedence: defaults < config file < CLI flags (tidb-server rule)
    defaults = {"host": "127.0.0.1", "port": 4000, "log_level": "info",
                "gc_life_minutes": 10, "enable_sem": False}
    conf = dict(defaults)
    if args.config:
        conf.update(load_config(args.config))
    for k in defaults:
        v = getattr(args, k)
        if v is not None:
            conf[k] = v
        setattr(args, k, conf[k])
    if args.enable_sem:
        from .utils import sem

        sem.enable()

    logging.basicConfig(
        level={"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING, "error": logging.ERROR}[args.log_level],
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from .server import Server

    storage = None
    if args.data_dir:
        from .storage.txn import Storage

        spares = [p.strip() for p in (args.wal_spare_dirs or "").split(",") if p.strip()]
        storage = Storage(data_dir=args.data_dir, spare_dirs=spares or None)
        if spares:
            storage.global_vars["tidb_wal_spare_dirs"] = ",".join(spares)
    srv = Server(storage=storage, host=args.host, port=args.port)
    srv.storage.gc_worker.life_ms = args.gc_life_minutes * 60 * 1000
    port = srv.start()
    print(f"tidb-tpu server listening on {args.host}:{port}", flush=True)

    stop = threading.Event()

    def on_signal(signum, frame):  # noqa: ARG001
        print("shutting down...", flush=True)
        srv.close()
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    import time as _t

    last_gc = _t.time()
    while not stop.is_set():
        stop.wait(30)
        # background GC loop honoring the LIVE tidb_gc_run_interval
        # (leaderTick; a SET GLOBAL takes effect on the next wakeup)
        if _t.time() - last_gc >= srv.storage.gc_worker.interval_ms / 1000.0:
            srv.storage.gc_worker.tick()
            last_gc = _t.time()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Process entry — `python -m tidb_tpu` starts the MySQL-protocol server
(ref: tidb-server/main.go:157 main, :505 setGlobalVars, :621 createServer;
flags subset + graceful signal shutdown)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tidb-tpu-server", description="TPU-native TiDB-compatible SQL server")
    ap.add_argument("--host", default="127.0.0.1", help="listen address")
    ap.add_argument("-P", "--port", type=int, default=4000, help="listen port (0 = ephemeral)")
    ap.add_argument("--log-level", default="info", choices=["debug", "info", "warn", "error"])
    ap.add_argument("--gc-life-minutes", type=int, default=10, help="MVCC GC retention window")
    ap.add_argument(
        "--enable-sem", action="store_true",
        help="security enhanced mode: hide restricted vars/tables, deny FILE (ref: util/sem)",
    )
    args = ap.parse_args(argv)
    if args.enable_sem:
        from .utils import sem

        sem.enable()

    logging.basicConfig(
        level={"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING, "error": logging.ERROR}[args.log_level],
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from .server import Server

    srv = Server(host=args.host, port=args.port)
    srv.storage.gc_worker.life_ms = args.gc_life_minutes * 60 * 1000
    port = srv.start()
    print(f"tidb-tpu server listening on {args.host}:{port}", flush=True)

    stop = threading.Event()

    def on_signal(signum, frame):  # noqa: ARG001
        print("shutting down...", flush=True)
        srv.close()
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    import time as _t

    last_gc = _t.time()
    while not stop.is_set():
        stop.wait(30)
        # background GC loop honoring the LIVE tidb_gc_run_interval
        # (leaderTick; a SET GLOBAL takes effect on the next wakeup)
        if _t.time() - last_gc >= srv.storage.gc_worker.interval_ms / 1000.0:
            srv.storage.gc_worker.tick()
            last_gc = _t.time()
    return 0


if __name__ == "__main__":
    sys.exit(main())

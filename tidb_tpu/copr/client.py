"""Coprocessor client (ref: store/copr/coprocessor.go CopClient.Send:71,
buildCopTasks:151 — the kv.Client seam SURVEY §5.8 names as the boundary
where the TPU backend registers).

Splits key ranges along region boundaries into cop tasks, dispatches each
to an engine (TPU-fused program or host-vectorized fallback), and merges
result chunks. Engine selection is per-session (`tidb_cop_engine` sysvar:
'tpu' | 'host' | 'auto').
"""

from __future__ import annotations

from dataclasses import dataclass

from ..chunk.chunk import Chunk
from ..catalog.schema import TableInfo
from ..codec import tablecodec
from .dag import DAGRequest
from .host_engine import execute_dag_host
from .tilecache import TileCache


@dataclass
class CopTask:
    region_id: int
    start: bytes
    end: bytes


class CopClient:
    def __init__(self, storage):
        self.storage = storage
        self.tiles = TileCache(storage)
        self._tpu = None
        self.stats = {"tasks": 0, "tpu_tasks": 0, "host_tasks": 0}

    @property
    def tpu(self):
        if self._tpu is None:
            from .tpu_engine import TPUEngine

            self._tpu = TPUEngine()
        return self._tpu

    @staticmethod
    def _txn_dirty(txn, table_id: int) -> bool:
        prefix = tablecodec.record_prefix(table_id)
        return any(k.startswith(prefix) for k in txn.membuf)

    def build_tasks(self, table_id: int, ranges: list[tuple[bytes, bytes]]) -> list[CopTask]:
        """Region-align ranges (ref: buildCopTasks)."""
        tasks = []
        for start, end in ranges:
            for region, s, e in self.storage.regions.split_ranges(start, end):
                tasks.append(CopTask(region.id, s, e))
        return tasks

    def send(
        self,
        table: TableInfo,
        dag: DAGRequest,
        ranges: list[tuple[bytes, bytes]] | None,
        read_ts: int,
        engine: str = "auto",
        txn=None,
    ) -> list[Chunk]:
        """Execute the DAG over all tasks; returns per-task partial chunks
        (the selectResult stream analog — caller merges/finalizes).

        If `txn` carries uncommitted writes for this table, the task batch
        is built from the txn's merged view instead of the tile cache
        (the UnionScan semantic, ref: executor/union_scan.go) — engines
        run over it uncached."""
        if ranges is None:
            prefix = tablecodec.record_prefix(table.id)
            ranges = [(prefix, prefix + b"\xff")]
        tasks = self.build_tasks(table.id, ranges)
        dirty = txn is not None and self._txn_dirty(txn, table.id)
        out = []
        for t in tasks:
            self.stats["tasks"] += 1
            if dirty:
                from .tilecache import decode_rows_to_batch

                kvs = [
                    (k, v)
                    for k, v in txn.scan(t.start, t.end)
                    if tablecodec.is_record_key(k)
                ]
                batch = decode_rows_to_batch(table, kvs, (-1, 0))
            else:
                batch = self.tiles.get_batch(table, t.start, t.end, read_ts)
            if batch.n_rows == 0:
                continue
            chunk = None
            if engine in ("tpu", "auto"):
                try:
                    chunk = self.tpu.execute(dag, batch)
                    self.stats["tpu_tasks"] += 1
                except Exception:
                    if engine == "tpu":
                        raise
                    chunk = None
            if chunk is None:
                chunk = execute_dag_host(dag, batch)
                self.stats["host_tasks"] += 1
            out.append(chunk)
        return out

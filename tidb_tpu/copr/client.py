"""Coprocessor client (ref: store/copr/coprocessor.go CopClient.Send:71,
buildCopTasks:151 — the kv.Client seam SURVEY §5.8 names as the boundary
where the TPU backend registers).

Splits key ranges along region boundaries into cop tasks, dispatches each
to an engine (TPU-fused program or host-vectorized fallback), and merges
result chunks. Engine selection is per-session (`tidb_cop_engine` sysvar:
'tpu' | 'host' | 'auto').
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..chunk.chunk import Chunk
from ..catalog.schema import IndexInfo, TableInfo
from ..codec import tablecodec
from ..codec.key import decode_datum_key
from ..mysqltypes.datum import Datum, K_BYTES
from .dag import DAGRequest
from .host_engine import execute_dag_host
from .tilecache import ColumnBatch, TileCache, decode_rows_to_batch


@dataclass
class CopTask:
    region_id: int
    start: bytes
    end: bytes


class CopClient:
    def __init__(self, storage):
        self.storage = storage
        self.tiles = TileCache(storage)
        self._tpu = None
        self.stats = {"tasks": 0, "tpu_tasks": 0, "host_tasks": 0}

    @property
    def tpu(self):
        if self._tpu is None:
            from .tpu_engine import TPUEngine

            self._tpu = TPUEngine()
        return self._tpu

    @property
    def mpp(self):
        if getattr(self, "_mpp", None) is None:
            from ..parallel.mpp import MPPEngine

            self._mpp = MPPEngine()
        return self._mpp

    @staticmethod
    def _txn_dirty(txn, table_id: int) -> bool:
        prefix = tablecodec.record_prefix(table_id)
        return any(k.startswith(prefix) for k in txn.membuf)

    @staticmethod
    def _txn_dirty_index(txn, table_id: int, index_id: int) -> bool:
        prefix = tablecodec.index_prefix(table_id, index_id)
        return any(k.startswith(prefix) for k in txn.membuf)

    def build_tasks(self, table_id: int, ranges: list[tuple[bytes, bytes]]) -> list[CopTask]:
        """Region-align ranges (ref: buildCopTasks)."""
        tasks = []
        for start, end in ranges:
            for region, s, e in self.storage.regions.split_ranges(start, end):
                tasks.append(CopTask(region.id, s, e))
        return tasks

    def send(
        self,
        table: TableInfo,
        dag: DAGRequest,
        ranges: list[tuple[bytes, bytes]] | None,
        read_ts: int,
        engine: str = "auto",
        txn=None,
    ) -> list[Chunk]:
        """Execute the DAG over all tasks; returns per-task partial chunks
        (the selectResult stream analog — caller merges/finalizes).

        If `txn` carries uncommitted writes for this table, the task batch
        is built from the txn's merged view instead of the tile cache
        (the UnionScan semantic, ref: executor/union_scan.go) — engines
        run over it uncached."""
        if ranges is None:
            prefix = tablecodec.record_prefix(table.id)
            ranges = [(prefix, prefix + b"\xff")]
        tasks = self.build_tasks(table.id, ranges)
        dirty = txn is not None and self._txn_dirty(txn, table.id)
        out = []
        for t in tasks:
            if dirty:
                kvs = [
                    (k, v)
                    for k, v in txn.scan(t.start, t.end)
                    if tablecodec.is_record_key(k)
                ]
                batch = decode_rows_to_batch(table, kvs, (-1, 0))
            else:
                batch = self.tiles.get_batch(table, t.start, t.end, read_ts)
            if batch.n_rows == 0:
                continue
            out.append(self._run_engines(dag, batch, engine))
        return out

    # --- engine dispatch over an arbitrary batch --------------------------

    def _run_engines(self, dag: DAGRequest, batch: ColumnBatch, engine: str) -> Chunk:
        self.stats["tasks"] += 1
        if engine in ("tpu", "auto"):
            try:
                chunk = self.tpu.execute(dag, batch)
                self.stats["tpu_tasks"] += 1
                return chunk
            except Exception:
                if engine == "tpu":
                    raise
        chunk = execute_dag_host(dag, batch)
        self.stats["host_tasks"] += 1
        return chunk

    # --- index scans (ref: executor/distsql.go IndexReader/IndexLookUp) ---

    def _scan_kvs(self, start: bytes, end: bytes, read_ts: int, txn, dirty: bool):
        if dirty:
            return list(txn.scan(start, end))
        return self.storage.snapshot(read_ts).scan(start, end)

    def index_entries(
        self, table: TableInfo, idx: IndexInfo, ranges: list[tuple[bytes, bytes]], read_ts: int, txn=None
    ) -> list[tuple[list[Datum], int]]:
        """Scan index key ranges → [(index column datums, row handle)] in
        index key order (the stage-1 half of a double read)."""
        dirty = txn is not None and self._txn_dirty_index(txn, table.id, idx.id)
        prefix_len = len(tablecodec.index_prefix(table.id, idx.id))
        ncols = len(idx.col_offsets)
        out = []
        for start, end in ranges:
            for k, v in self._scan_kvs(start, end, read_ts, txn, dirty):
                mv = memoryview(k)
                pos = prefix_len
                datums = []
                for _ in range(ncols):
                    d, pos = decode_datum_key(mv, pos)
                    if d.kind == K_BYTES:
                        d = Datum.s(d.val.decode("utf8", "replace"))
                    datums.append(d)
                if pos < len(k):
                    handle = tablecodec.decode_index_handle(k)
                else:
                    handle = int(v)
                out.append((datums, handle))
        return out

    def index_batch(
        self, table: TableInfo, idx: IndexInfo, ranges, read_ts: int, txn=None
    ) -> ColumnBatch:
        """Index entries materialized as a full-visible-layout columnar
        batch (covering reads): index-supplied lanes are filled, all other
        lanes stay invalid — the planner guarantees they are unreferenced."""
        entries = self.index_entries(table, idx, ranges, read_ts, txn)
        n = len(entries)
        handles = np.zeros(n, dtype=np.int64)
        chk = Chunk.empty([c.ft for c in table.columns], n)
        cols = chk.columns
        hc = table.handle_col()
        pk_off = hc.offset if (hc is not None and not hc.hidden) else None
        for i, (datums, handle) in enumerate(entries):
            handles[i] = handle
            for off, d in zip(idx.col_offsets, datums):
                cols[off].set_datum(i, d)
            if pk_off is not None:
                cols[pk_off].set_datum(i, Datum.i(handle))
        ver, _ = self.storage.data_version(tablecodec.table_prefix(table.id))
        return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], ver)

    def send_index(
        self, table: TableInfo, idx: IndexInfo, dag: DAGRequest, ranges, read_ts: int,
        engine: str = "auto", txn=None,
    ) -> list[Chunk]:
        """Covering index read: one cop task per range batch."""
        batch = self.index_batch(table, idx, ranges, read_ts, txn)
        if batch.n_rows == 0:
            return []
        return [self._run_engines(dag, batch, engine)]

    def send_handles(
        self, table: TableInfo, dag: DAGRequest, handles: list[int], read_ts: int,
        engine: str = "auto", txn=None,
    ) -> list[Chunk]:
        """Stage-2 of a double read: fetch rows by handle, run the DAG
        (ref: IndexLookUp table-worker)."""
        if not handles:
            return []
        keys = [tablecodec.record_key(table.id, h) for h in handles]
        if txn is not None and self._txn_dirty(txn, table.id):
            got = txn.batch_get(keys)
        else:
            got = self.storage.snapshot(read_ts).batch_get(keys)
        kvs = [(k, got[k]) for k in keys if k in got]
        batch = decode_rows_to_batch(table, kvs, (-1, 0))
        if batch.n_rows == 0:
            return []
        return [self._run_engines(dag, batch, engine)]

"""Coprocessor client (ref: store/copr/coprocessor.go CopClient.Send:71,
buildCopTasks:151 — the kv.Client seam SURVEY §5.8 names as the boundary
where the TPU backend registers).

Splits key ranges along region boundaries into cop tasks, dispatches them
through a bounded worker pool (copIterator's run:363 analog) with
ordered/unordered streaming merge (:461,533), retries tasks whose region
epoch changed by re-splitting the remaining range (:1025
buildCopTasksFromRemain), and streams result chunks back lazily so the
root operators overlap with in-flight cop work. Engine selection is
per-session (`tidb_cop_engine` sysvar: 'tpu' | 'host' | 'auto').
"""

from __future__ import annotations

import logging
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass
from threading import Lock

import numpy as np

log = logging.getLogger("tidb_tpu.copr")

from ..chunk.chunk import Chunk
from ..catalog.schema import IndexInfo, TableInfo
from ..codec import tablecodec
from ..codec.key import decode_datum_key
from ..planner.ranger import prefix_next
from ..errors import (
    BackoffExhausted,
    DeviceTransientError,
    EpochNotMatch,
    NotLeader,
    QueryInterrupted,
    ServerBusy,
)
from ..mysqltypes.datum import Datum, K_BYTES
from ..sched import SchedCtx, ru_cost
from ..utils import memory
from ..utils import metrics as M
from ..utils import timeline as TL
from ..utils import tracing
from ..utils.failpoint import inject as _fp
from .dag import DAGRequest
from .host_engine import execute_dag_host
from .retry import (
    BO_DEVICE,
    BO_REGION_MISS,
    BO_SERVER_BUSY,
    BO_UPDATE_LEADER,
    Backoffer,
    classify_device_error,
)
from .tilecache import (
    ColumnBatch,
    TileCache,
    batch_nbytes,
    decode_rows_to_batch,
    device_nbytes,
)


@dataclass
class CopTask:
    region_id: int
    start: bytes
    end: bytes
    epoch: int = 1
    leader: int = 1  # leader store the task was built against


class CopResultCache:
    """Per-task result cache (ref: store/copr/coprocessor_cache.go:31,60
    — ristretto LRU with admission rules, redesigned over this store's
    version counters). Keyed (DAG digest, table, range); an entry is
    valid while the table's data version is unchanged and the read
    timestamp is at/after the version's commit (the tile-cache snapshot
    rule), so `bump_version` on any committed write invalidates it.
    Admission mirrors the reference's min-process-time / max-result-size
    gates with row counts: only tasks that scanned enough rows AND
    produced a small result are worth pinning."""

    CAPACITY = 256
    ADMIT_MIN_SCAN_ROWS = 4096  # the admission-min-process-time analog
    ADMIT_MAX_RESULT_ROWS = 20480  # the admission-max-result-bytes analog

    def __init__(self):
        from collections import OrderedDict

        self._od: "OrderedDict" = OrderedDict()
        self._lock = Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key, ver, read_ts):
        with self._lock:
            e = self._od.get(key)
            if e is None or e[1] != ver or read_ts < e[2]:
                self.misses += 1
                return None
            self._od.move_to_end(key)
            self.hits += 1
            return e[0]

    def put(self, key, chunk, ver, min_valid_ts, scan_rows: int):
        if scan_rows < self.ADMIT_MIN_SCAN_ROWS or chunk.num_rows > self.ADMIT_MAX_RESULT_ROWS:
            return
        with self._lock:
            self._od[key] = (chunk, ver, min_valid_ts)
            self._od.move_to_end(key)
            while len(self._od) > self.CAPACITY:
                self._od.popitem(last=False)


class CopClient:
    def __init__(self, storage):
        self.storage = storage
        self.tiles = TileCache(storage)
        # the server memory arbiter's soft-limit action evicts this
        # client's tile cache (and its device mirrors) with every other
        # registered one when the store crosses the alarm ratio
        storage.mem.register_cache(self.tiles)
        self.results = CopResultCache()
        self._tpu = None
        self._pool = None
        self._lock = Lock()  # guards lazy singletons + stats counters
        self._ndv_cache: dict = {}  # (dag digest, batch version) → (est,)
        # cross-node trace propagation (PR 18): when the session routed
        # a statement to this replica-side cop, its cop.task spans carry
        # the serving replica's name so they adopt into the PRIMARY
        # statement trace attributed (set per statement by the router
        # gate, None on the primary's own cop)
        self.replica_name: str | None = None
        self.stats = {
            "tasks": 0,
            "tpu_tasks": 0,
            "host_tasks": 0,
            "region_errors": 0,
            "fallback_errors": 0,
            # resource-control counters (EXPLAIN ANALYZE sched line)
            "sched_wait_ms": 0,
            "ru": 0,
            "batched_tasks": 0,
            "dedup_tasks": 0,
            # fault-tolerance counters (EXPLAIN ANALYZE retry line)
            "retries": 0,
            "backoff_ms": 0,
            "breaker_skips": 0,
            "cancelled_tasks": 0,
            "drained_tasks": 0,
            # device-path counters (EXPLAIN ANALYZE device line / tracing)
            "compile_ms": 0,
            "transfer_bytes": 0,
            "device_ms": 0,
            "host_ms": 0,
            # upload-attribution counters (PR 5): bytes served from a
            # prior launch's cached device lanes, and grouped-launch
            # shared uploads performed on behalf of the whole group
            "cache_ref_bytes": 0,
            "shared_h2d_bytes": 0,
            # tile-codec counters (PR 7): the dense uncompressed bytes a
            # statement's uploads REPRESENT vs the narrowed/compressed
            # bytes that actually crossed the wire (EXPLAIN ANALYZE
            # device: line `logical_bytes`/`wire_bytes`)
            "logical_bytes": 0,
            "wire_bytes": 0,
            # mesh-placement counters (PR 6): tasks moved OFF their
            # resident device lane — by an open breaker (reroute to a
            # sibling, not host) or by load (spill to an idle lane)
            "lane_reroutes": 0,
            "lane_spills": 0,
            # memory-arbitration + runaway counters (PR 4)
            "mem_degraded_tasks": 0,
            "processed_rows": 0,
            # unified fault domain (PR 8): MPP dispatches/declines and
            # device-window runs/declines, per statement (EXPLAIN ANALYZE
            # `mpp:` / `window:` lines ride the before/after delta)
            "mpp_tasks": 0,
            "mpp_fallbacks": 0,
            "window_device_tasks": 0,
            "window_fallbacks": 0,
            # workload-history feedback routing (PR 20): `auto` decisions
            # answered (and whether history or the static explore arm
            # answered them), typed lowering declines the device path
            # returned per statement, and the measured wall each
            # device-path task spent place-to-result (the fair
            # counterpart of host_ms — the profile compares the two)
            "route_decisions": 0,
            "route_explore": 0,
            "route_history": 0,
            "lowering_declines": 0,
            "device_task_ms": 0,
        }
        # last feedback-routing decision (EXPLAIN ANALYZE `route:` line
        # cites its evidence); benign last-writer-wins like mpp's
        # last_fallback_reason
        self.last_route: dict | None = None

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] += n

    def _stats_fn(self, sctx):
        """The per-call stats sink: the store-wide counters, mirrored into
        the statement's trace when one is attached (per-statement exec
        details for the slow log / STATEMENTS_SUMMARY / TRACE)."""
        trace = getattr(sctx, "trace", None) if sctx is not None else None
        if trace is None:
            return self._bump
        bump = self._bump

        def both(key: str, n: float = 1) -> None:
            bump(key, n)
            trace.add(key, n)

        return both

    @property
    def pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            with self._lock:
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(max_workers=16, thread_name_prefix="cop")
        return self._pool

    @property
    def ctl(self):
        """The store-wide resource controller (admission + batcher). None
        only for exotic storages without the `sched` seam."""
        return getattr(self.storage, "sched", None)

    @property
    def tpu(self):
        if self._tpu is None:
            with self._lock:
                if self._tpu is None:
                    ctl = self.ctl
                    if ctl is not None:
                        # ONE engine (and XLA program cache) per store:
                        # cross-session launches can only coalesce when
                        # they share compiled programs
                        self._tpu = ctl.tpu_engine
                    else:
                        from .tpu_engine import TPUEngine

                        self._tpu = TPUEngine()
        return self._tpu

    def _sched_ctx(self) -> SchedCtx:
        """Capture admission context ON the session thread (send/send_index/
        send_handles run there; _run_task may not — contextvars don't cross
        the cop pool)."""
        from ..executor.executors import _ACTIVE_SESSION, _ACTIVE_TRACKER

        sess = _ACTIVE_SESSION.get(None)
        if sess is None:
            return SchedCtx()
        # GLOBAL-only toggle: read the live store value so SET GLOBAL takes
        # effect for every session immediately, not just newly-seeded ones
        enabled = sess.store.global_vars.get("tidb_enable_resource_control", "ON")
        # backoff budget: statement scope (SET_VAR hint) wins over session
        budget = None
        raw = (getattr(sess, "_stmt_vars", None) or {}).get("tidb_backoff_budget_ms") \
            or sess.vars.get("tidb_backoff_budget_ms")
        if raw:
            try:
                budget = float(raw)
            except ValueError:
                budget = None
        return SchedCtx(
            group=sess.vars.get("tidb_resource_group", "default") or "default",
            deadline=getattr(sess, "_deadline", None),
            session=sess,
            enabled=enabled == "ON",
            trace=getattr(sess, "_tracer", None),
            backoff_budget_ms=budget,
            runaway=getattr(sess, "_runaway", None),
            mem=_ACTIVE_TRACKER.get(None),
            # feedback routing (PR 20): GLOBAL-only like resource control —
            # SET GLOBAL tidb_tpu_feedback_route=OFF must recover the
            # static heuristics live for every session
            digest=getattr(sess, "_stmt_digest", None),
            feedback=sess.store.global_vars.get(
                "tidb_tpu_feedback_route", "ON") == "ON",
        )

    @property
    def mpp(self):
        if getattr(self, "_mpp", None) is None:
            from ..parallel.mpp import MPPEngine

            self._mpp = MPPEngine()
        return self._mpp

    @staticmethod
    def _txn_dirty(txn, table_id: int) -> bool:
        prefix = tablecodec.record_prefix(table_id)
        return any(k.startswith(prefix) for k in txn.membuf)

    @staticmethod
    def _txn_dirty_index(txn, table_id: int, index_id: int) -> bool:
        prefix = tablecodec.index_prefix(table_id, index_id)
        return any(k.startswith(prefix) for k in txn.membuf)

    def build_ranged_tasks(self, ranges: list[tuple[bytes, bytes]]) -> list[CopTask]:
        """Region-align raw key ranges (ref: buildCopTasksFromRemain) —
        the re-split path's helper: the ranges are already absolute keys,
        no table identity involved."""
        tasks = []
        for start, end in ranges:
            for region, s, e in self.storage.regions.split_ranges(start, end):
                tasks.append(CopTask(region.id, s, e, region.epoch, region.leader_store))
        return tasks

    def build_tasks(self, table_id: int, ranges: list[tuple[bytes, bytes]]) -> list[CopTask]:
        """Region-align a table's ranges (ref: buildCopTasks)."""
        return self.build_ranged_tasks(ranges)

    def send(
        self,
        table: TableInfo,
        dag: DAGRequest,
        ranges: list[tuple[bytes, bytes]] | None,
        read_ts: int,
        engine: str = "auto",
        txn=None,
        concurrency: int = 1,
        keep_order: bool = True,
        result_cache: bool = True,
    ):
        """Execute the DAG over all tasks; yields per-task partial chunks
        lazily (the selectResult/copIterator stream analog — caller
        merges/finalizes). With concurrency > 1 tasks run through the
        worker pool: host decode of task N+1 overlaps device execution of
        task N; `keep_order` picks the ordered vs completion-order merge
        (ref copr/coprocessor.go:461,533).

        If `txn` carries uncommitted writes for this table, the task batch
        is built from the txn's merged view instead of the tile cache
        (the UnionScan semantic, ref: executor/union_scan.go) — engines
        run over it uncached and serially (the membuffer is not shared
        across workers)."""
        if ranges is None:
            prefix = tablecodec.record_prefix(table.id)
            ranges = [(prefix, prefix_next(prefix))]
        tasks = self.build_tasks(table.id, ranges)
        sctx = self._sched_ctx()
        dirty = txn is not None and self._txn_dirty(txn, table.id)
        if dirty:
            out = []
            for t in tasks:
                kvs = [
                    (k, v)
                    for k, v in txn.scan(t.start, t.end)
                    if tablecodec.is_record_key(k)
                ]
                batch = decode_rows_to_batch(table, kvs, (-1, 0))
                if batch.n_rows == 0:
                    continue
                out.append(self._run_engines(dag, batch, engine, sctx=sctx))
            return out
        if concurrency <= 1 or len(tasks) <= 1:
            return self._send_serial(table, dag, tasks, read_ts, engine, result_cache, sctx)
        return self._send_parallel(table, dag, tasks, read_ts, engine, concurrency, keep_order, result_cache, sctx)

    def _send_serial(self, table, dag, tasks, read_ts, engine, result_cache=True, sctx=None):
        for t in tasks:
            yield from self._run_task(table, dag, t, read_ts, engine, cache=result_cache, sctx=sctx)

    def _send_parallel(self, table, dag, tasks, read_ts, engine, concurrency, keep_order, result_cache=True, sctx=None):
        """Bounded in-flight window (the copIterator concurrency semantic):
        at most `concurrency` tasks run/buffer ahead of the consumer, new
        tasks are submitted as results drain, and abandoning the stream
        cancels everything not yet started."""
        from threading import Event

        it = iter(tasks)
        futs: deque = deque()
        abandon = Event()  # set at stream close: in-flight tasks bail at
        # their next retry-loop/backoff checkpoint instead of riding out
        # full backoff budgets while the drain below waits on them

        def submit_next():
            t = next(it, None)
            if t is not None:
                futs.append(
                    self.pool.submit(self._run_task, table, dag, t, read_ts, engine,
                                     cache=result_cache, sctx=sctx, abort=abandon)
                )

        for _ in range(min(concurrency, len(tasks))):
            submit_next()
        try:
            while futs:
                if keep_order:
                    f = futs.popleft()
                    f.result()  # wait first so a refill overlaps the yield
                else:
                    f = next(as_completed(futs))
                    futs.remove(f)
                submit_next()
                yield from f.result()
        finally:
            # a failing or abandoned stream must not poison its siblings:
            # cancel what hasn't started, then DRAIN what has — f.cancel()
            # is a no-op on a running future, and a worker left running
            # would outlive the stream. The abandon flag makes the drain
            # short: a task sleeping in backoff or about to re-acquire a
            # ticket bails at its next checkpoint (≤ one poll tick), so
            # the wait below is bounded by one engine run, not by backoff
            # budgets. Outcomes (results and errors alike) die with the
            # stream.
            abandon.set()
            cancelled = drained = 0
            for f in futs:
                if f.cancel():
                    cancelled += 1
            for f in futs:
                if not f.cancelled():
                    drained += 1
                    try:
                        f.result()
                    except BaseException:  # noqa: BLE001 — stream already failing
                        pass
            if cancelled:
                self._bump("cancelled_tasks", cancelled)
            if drained:
                self._bump("drained_tasks", drained)

    def _run_task(self, table, dag, t: CopTask, read_ts, engine, bo: Backoffer | None = None,
                  cache: bool = True, sctx=None, abort=None) -> list[Chunk]:
        """Execute one cop task, chasing region errors through the typed
        backoff machinery (ref: handleCopResponse region-error path,
        coprocessor.go:1025): EpochNotMatch re-splits the remaining range,
        NotLeader retries the SAME task against the new leader, every
        retry drawing from ONE per-task Backoffer budget (sub-tasks of a
        re-split share their parent's). Repeated identical (DAG, range)
        reads serve from the result cache while the table version holds
        (ref: coprocessor_cache.go)."""
        _fp("cop/before-task")
        st = self._stats_fn(sctx)
        if bo is None:
            bo = Backoffer.for_ctx(sctx, stats=st)
            bo.abort = abort
        trace = getattr(sctx, "trace", None) if sctx is not None else None
        mem = getattr(sctx, "mem", None) if sctx is not None else None
        # replica-tagged span: a follower-routed statement's cop tasks
        # (and their device-phase children) adopt into the primary trace
        # attributed to the serving node
        tags = {"region": t.region_id}
        if self.replica_name:
            tags["replica"] = self.replica_name
        with tracing.activate(trace), memory.bind(mem), (
            trace.span("cop.task", **tags) if trace is not None else tracing._NOOP
        ):
            return self._run_task_traced(table, dag, t, read_ts, engine, bo, cache, sctx, st)

    def _run_task_traced(self, table, dag, t: CopTask, read_ts, engine,
                         bo: Backoffer, cache: bool, sctx, st) -> list[Chunk]:
        while True:
            if bo.abort is not None and bo.abort.is_set():
                return []  # stream abandoned: result would be discarded
            region = self.storage.regions.locate(t.start)
            if region.id == t.region_id and region.epoch == t.epoch and region.leader_store != t.leader:
                # NotLeader: same region and epoch, leadership moved —
                # no re-split, just chase the new leader after a short wait
                st("region_errors")
                bo.backoff(BO_UPDATE_LEADER, NotLeader(
                    f"region {region.id} leader moved store {t.leader} -> {region.leader_store}",
                    region_id=region.id,
                ))
                t.leader = region.leader_store
                continue
            stale = (
                region.id != t.region_id
                or region.epoch != t.epoch
                or (region.end != b"" and (t.end == b"" or t.end > region.end))
            )
            if stale:
                st("region_errors")
                bo.backoff(BO_REGION_MISS, EpochNotMatch(
                    f"region {t.region_id}@{t.epoch} is stale for "
                    f"[{t.start!r}, {t.end!r}) (now {region.id}@{region.epoch})",
                    region_id=t.region_id,
                ))
                out = []
                for sub in self.build_ranged_tasks([(t.start, t.end)]):
                    out.extend(self._run_task(table, dag, sub, read_ts, engine, bo=bo, cache=cache, sctx=sctx))
                return out
            break
        ckey = ver = last_commit = None
        if cache:
            ver, last_commit = self.storage.data_version(tablecodec.table_prefix(table.id))
            ckey = (dag.digest(), table.id, t.start, t.end, engine != "host")
            hit = self.results.get(ckey, ver, read_ts)
            if hit is not None:
                return [hit]
        batch = self.tiles.get_batch(table, t.start, t.end, read_ts)
        if batch.n_rows == 0:
            return []
        # cross-session dedup identity: valid only under the result-cache
        # snapshot rule (read at/after the last commit of an unchanged
        # version) — exactly when two tasks with this key see one content
        dedup = (ckey, ver) if (cache and read_ts >= last_commit) else None
        chunk = self._run_engines(dag, batch, engine, sctx=sctx, dedup=dedup, bo=bo)
        if cache and read_ts >= last_commit:
            self.results.put(ckey, chunk, ver, last_commit, batch.n_rows)
        return [chunk]

    # --- engine dispatch over an arbitrary batch --------------------------

    AUTO_MIN_ROWS = 2048  # below this, device jit cost can't amortize
    AUTO_GROUP_MAX = 1 << 16  # est. NDV beyond direct addressing → host

    def _estimate_groups(self, dag, batch) -> int | None:
        """Sampled NDV estimate for the GROUP BY key tuple; None when the
        keys aren't plain columns. A routing-cost heuristic only (the
        sample is pre-filter, so a selective WHERE can over-estimate —
        worst case the query runs on the well-vectorized host path).
        Cached per (dag digest, batch version) so repeat dispatches and
        sibling cop tasks don't re-sample."""
        from ..expr.expression import Column as ECol

        ck = (dag.digest(), getattr(batch, "version", -1))
        hit = self._ndv_cache.get(ck)
        if hit is not None:
            return hit[0]
        cols = []
        for g in dag.agg.group_by:
            if not isinstance(g, ECol):
                return None
            pos = g.idx
            if not (0 <= pos < len(dag.scan.col_offsets)):
                return None
            cols.append(dag.scan.col_offsets[pos])
        n = batch.n_rows
        if n == 0:
            return 0
        m = min(n, 8192)
        step = max(1, n // m)
        import numpy as np

        sel = slice(None, None, step)
        valid = np.ones(len(batch.data[cols[0]][sel][:m]), dtype=bool)
        sample = []
        for off in cols:
            sample.append(np.asarray(batch.data[off][sel][:m]))
            valid &= np.asarray(batch.valid[off][sel][: len(valid)])
        sample = [s[valid] for s in sample]
        k = max(len(sample[0]), 1)
        try:
            if len(sample) == 1:
                d = len(np.unique(sample[0]))
            else:
                d = len(np.unique(np.rec.fromarrays(sample)))
        except (TypeError, ValueError):  # mixed/object lanes
            d = len({tuple(row) for row in zip(*sample)})
        if d >= k * 0.95:
            est = n  # nearly all-distinct sample: assume NDV ~ rows
        else:
            # birthday-style scale-up, clamped to the population
            est = min(n, int(d * (n / k)))
        if len(self._ndv_cache) > 512:
            self._ndv_cache.clear()
        self._ndv_cache[ck] = (est,)
        return est

    def _route_static(self, dag, batch, st, trace) -> str:
        """The pre-feedback static heuristics, verbatim — the whole policy
        while tidb_tpu_feedback_route=OFF (bit-exact legacy behavior) and
        the EXPLORE arm when the workload profile has no verdict. Returns
        "host" or "auto" ("auto" = try the device path, allowed to fall)."""
        if batch.n_rows < self.AUTO_MIN_ROWS:
            return "host"
        if self.storage.mem.degraded:
            # server soft memory limit crossed: auto traffic degrades to
            # the host engine — a device round-trip means fresh h2d
            # uploads exactly when the store is trying to shed memory.
            # Forced 'tpu' stays forced (the explicit-engine contract)
            st("mem_degraded_tasks")
            M.TPU_FALLBACK.inc(path="cop", reason="mem_degrade")
            if trace is not None and trace.recording:
                trace.closed_span("mem.degrade", 0.0,
                                  consumed=self.storage.mem.consumed,
                                  limit=self.storage.mem.limit)
            return "host"
        if (dag.agg is None and dag.topn is None
                and dag.limit is None and dag.selection is None):
            # bare scan: the lanes already live host-side in the tile
            # cache — a device round-trip (upload + full-row fetch over a
            # possibly remote link) computes nothing and costs everything.
            # 'tpu' stays forced (tests/EXPLAIN rely on that contract).
            return "host"
        if dag.agg is not None and dag.agg.group_by:
            # NDV routing: beyond the direct-addressing domain the device
            # takes the sort-based path whose XLA compile scales badly
            # with group capacity, while the vectorized host final-merge
            # handles high-NDV partials well — send it there (the
            # reference's engine cost choice, tidb_isolation_read_engines)
            est = self._estimate_groups(dag, batch)
            if est is not None and est > self.AUTO_GROUP_MAX:
                return "host"
        return "auto"

    def _route_auto(self, dag, batch, sctx, st, trace) -> str:
        """Engine choice for one `auto` cop task (PR 20): consult the
        store's workload-history profile per (statement digest, row
        bucket); no verdict → explore via the static heuristics. The
        overrides — mem degrade, runaway watch quarantine — win over any
        history (open breakers stay structural: the placement loop below
        already drains to host when every lane refuses, history or not).
        With tidb_tpu_feedback_route=OFF this is the static path alone:
        no profile reads, no route accounting, bit-exact legacy routing."""
        if (sctx is None or not getattr(sctx, "feedback", False)
                or not getattr(sctx, "digest", None)):
            return self._route_static(dag, batch, st, trace)

        def note(engine, reason, evidence, exploited):
            decision = "host" if engine == "host" else "device"
            M.TPU_ROUTE.inc(decision=decision, reason=reason)
            st("route_decisions")
            st("route_history" if exploited else "route_explore")
            self.last_route = {"decision": decision, "reason": reason,
                               "evidence": evidence}
            if trace is not None and trace.recording:
                trace.closed_span("route.decide", 0.0, decision=decision,
                                  reason=reason, evidence=evidence)
            return engine

        if self.storage.mem.degraded:
            st("mem_degraded_tasks")
            M.TPU_FALLBACK.inc(path="cop", reason="mem_degrade")
            if trace is not None and trace.recording:
                trace.closed_span("mem.degrade", 0.0,
                                  consumed=self.storage.mem.consumed,
                                  limit=self.storage.mem.limit)
            return note("host", "mem_degrade", "server over soft memory limit",
                        False)
        rc = getattr(sctx, "runaway", None)
        if rc is not None and getattr(rc, "demoted", False):
            # a COOLDOWN-quarantined digest must not ride its (possibly
            # excellent) device history back onto the mesh
            return note("host", "quarantine", "runaway watch demotion", False)
        verdict = self.storage.workload.decide(sctx.digest, batch.n_rows)
        if verdict is None:
            eng = self._route_static(dag, batch, st, trace)
            return note(eng, "explore",
                        "no (digest,bucket) history - static heuristic", False)
        side, reason, evidence = verdict
        return note("host" if side == "host" else "auto", reason, evidence,
                    True)

    def _run_engines(self, dag: DAGRequest, batch: ColumnBatch, engine: str,
                     sctx: SchedCtx | None = None, dedup=None,
                     bo: Backoffer | None = None) -> Chunk:
        st = self._stats_fn(sctx)
        trace = getattr(sctx, "trace", None) if sctx is not None else None
        st("tasks")
        st("processed_rows", batch.n_rows)
        if trace is not None:
            tid = getattr(getattr(batch, "table", None), "id", None)
            if tid is not None:
                trace.tables.add(tid)  # workload-profile invalidation index
        if engine == "auto":
            engine = self._route_auto(dag, batch, sctx, st, trace)
        # resource control: every engine run passes the store-wide
        # admission gate (the unified-read-pool seam); the ticket holds a
        # device slot + the group's RU estimate until release settles the
        # measured cost
        ctl = self.ctl if (sctx is None or sctx.enabled) else None
        if bo is None:
            bo = Backoffer.for_ctx(sctx, stats=st)
        # feedback plane armed: weighted lane placement + per-task wall
        # observation ride the same GLOBAL switch as the router
        fb = sctx is not None and getattr(sctx, "feedback", False)
        host_cpu_ms = 0.0  # measured host-engine wall → the RU CPU term
        # device timeline: bind the store ring + this statement's resource
        # group to the engine-call thread — the engine boundary hooks and
        # the launch batcher's lifecycle events read it from TLS
        with tracing.activate(trace), memory.bind(
            getattr(sctx, "mem", None) if sctx is not None else None
        ), TL.bind(
            getattr(self.storage, "timeline", None),
            getattr(sctx, "group", "default") if sctx is not None else "default",
        ):
            while True:
                if bo.abort is not None and bo.abort.is_set():
                    raise QueryInterrupted("cop stream abandoned")
                ticket = None
                wire = None  # set on device success: mirror's REAL bytes
                if ctl is not None:
                    try:
                        ticket = ctl.scheduler.acquire(
                            sctx or SchedCtx(),
                            stop=bo.abort.is_set if bo.abort is not None else None,
                        )
                    except ServerBusy as sb:
                        # queue-full backpressure is the in-process
                        # ServerBusy: retry through its own backoff class
                        # (holding no slot) until the budget runs out
                        bo.backoff(BO_SERVER_BUSY, sb)
                        continue
                    if ticket.wait_s:
                        st("sched_wait_ms", ticket.wait_s * 1000.0)
                try:
                    _fp("sched/engine-stall")
                    if engine in ("tpu", "auto"):
                        # per-device placement (PR 6): pick the runner lane
                        # by residency/occupancy, skipping lanes whose
                        # breaker rejects — an open breaker drains only its
                        # own lane, `auto` traffic reroutes to siblings and
                        # only falls to host when EVERY lane refuses.
                        # Breaker outcomes are recorded on the lane that
                        # actually ran the task.
                        t_dev = time.perf_counter()
                        lane = self.tpu.place(
                            batch, sched=ctl, gate_breakers=True, stats=st,
                            weighted=fb,
                        )
                        if lane is None:
                            # every device lane's breaker is open: 'auto'
                            # routes host at zero exception cost; forced
                            # 'tpu' fails fast with the states
                            if engine == "tpu":
                                self.tpu.raise_breakers_open()
                            st("breaker_skips")
                            M.TPU_FALLBACK.inc(path="cop", reason="breaker_open")
                            if trace is not None and trace.recording:
                                trace.closed_span(
                                    "breaker.skip", 0.0,
                                    state=self.tpu.breakers_describe(),
                                )
                        else:
                            breaker = lane.breaker
                            try:
                                _fp("cop/device-error")
                                _fp(f"cop/lane{lane.idx}/device-error")
                                with tracing.collect_phases() as ph:
                                    if ctl is not None:
                                        chunk = ctl.batcher.execute(
                                            self.tpu, dag, batch, dedup_key=dedup,
                                            stats=st, client=self, lane=lane,
                                        )
                                    else:
                                        chunk = self.tpu.execute(dag, batch, lane=lane)
                            except Exception as exc:
                                err = classify_device_error(exc)
                                if err is None:
                                    # not a device fault (kill/quota/SQL error):
                                    # propagate untouched, no fault counted —
                                    # but release a held half-open probe slot
                                    breaker.record_aborted()
                                    raise
                                tripped = breaker.record_failure(exc)
                                # lane-health observation (PR 20): the
                                # fault penalizes the lane's believed cost
                                # so weighted placement prefers a healthy
                                # sibling while the breaker makes up its
                                # mind
                                self.tpu.note_lane(
                                    lane, (time.perf_counter() - t_dev) * 1000.0,
                                    ok=False,
                                )
                                if isinstance(err, DeviceTransientError) and not tripped:
                                    # release the device slot while sleeping so
                                    # backoff never holds admission capacity,
                                    # then retry the device path (the retry
                                    # re-places: a lane tripped meanwhile is
                                    # skipped, its tasks land on siblings)
                                    if ticket is not None:
                                        ctl.scheduler.release(ticket)
                                        ticket = None
                                    self.tpu.release_lane(lane)
                                    lane = None
                                    try:
                                        bo.backoff(BO_DEVICE, err)
                                    except BackoffExhausted as bex:
                                        if engine == "tpu":
                                            raise
                                        err = bex
                                    else:
                                        continue
                                if engine == "tpu":
                                    raise err from exc
                                # a device-path failure must never be silent: it
                                # is a correctness bug masked by the host answer
                                # (VERDICT Weak#5)
                                st("fallback_errors")
                                M.TPU_FALLBACK.inc(path="cop", reason="device_error")
                                # keep the stack: a fatal classification may be
                                # a masked lowering bug (VERDICT Weak#5)
                                log.warning(
                                    "TPU engine fault (%s); falling back to host engine",
                                    err, exc_info=exc,
                                )
                            else:
                                breaker.record_success()
                                st("tpu_tasks")
                                M.COP_TASKS.inc(engine="tpu")
                                # per-task device wall, place → result: the
                                # apples-to-apples counterpart of host_ms
                                # the workload profile compares (device_ms
                                # alone is kernel time and hides dispatch)
                                dev_ms = (time.perf_counter() - t_dev) * 1000.0
                                st("device_task_ms", dev_ms)
                                self.tpu.note_lane(lane, dev_ms, ok=True)
                                if not getattr(chunk, "_device", False):
                                    # the engine's typed not_lowerable
                                    # decline: it scanned host lanes
                                    # internally — per-statement evidence
                                    # for the learned-decline route
                                    st("lowering_declines")
                                self._note_device_phases(ph, st, trace)
                                # only chunks a device program PRODUCED
                                # charge the compressed mirror; the
                                # engine's internal lowering fallback
                                # scanned host lanes and pays host bytes
                                if getattr(chunk, "_device", False):
                                    wire = device_nbytes(
                                        batch,
                                        lane.idx if lane is not None else None,
                                    )
                                return chunk
                            finally:
                                if lane is not None:
                                    self.tpu.release_lane(lane)
                    t0 = time.perf_counter()
                    chunk = execute_dag_host(dag, batch)
                    host_s = time.perf_counter() - t0
                    host_cpu_ms = host_s * 1000.0
                    st("host_tasks")
                    M.COP_TASKS.inc(engine="host")
                    st("host_ms", host_s * 1000.0)
                    if trace is not None and trace.recording:
                        trace.closed_span("cop.host_execute", host_s, rows=batch.n_rows)
                    return chunk
                finally:
                    if ticket is not None:
                        # RU read-byte term: a device-path task charges the
                        # bytes its narrowed/compressed mirror actually
                        # holds (and moved), not the 64Ki-padded or host
                        # lane fiction; host-path tasks keep charging the
                        # host lanes they scanned
                        nb = wire if wire is not None else batch_nbytes(batch)
                        # RU CPU term (PR 20): a host-path task charges the
                        # host-engine wall it actually measured; device
                        # tasks charge 0 here (their cost is the byte term)
                        ru = ru_cost(batch.n_rows, nb, cpu_ms=host_cpu_ms)
                        ctl.scheduler.release(ticket, ru)
                        st("ru", ru)

    @staticmethod
    def _note_device_phases(ph: dict, st, trace) -> None:
        """Solo-launch device phases (the batcher attributes grouped
        launches itself): exec-detail counters + trace spans."""
        if not ph:
            return
        for key, n in tracing.phase_counters(ph):
            st(key, n)
        if trace is not None:
            trace.add_phase_spans(ph)

    # --- index scans (ref: executor/distsql.go IndexReader/IndexLookUp) ---

    def _scan_kvs(self, start: bytes, end: bytes, read_ts: int, txn, dirty: bool):
        if dirty:
            return list(txn.scan(start, end))
        return self.storage.snapshot(read_ts).scan(start, end)

    def index_entries(
        self, table: TableInfo, idx: IndexInfo, ranges: list[tuple[bytes, bytes]], read_ts: int, txn=None
    ) -> list[tuple[list[Datum], int]]:
        """Scan index key ranges → [(index column datums, row handle)] in
        index key order (the stage-1 half of a double read)."""
        dirty = txn is not None and self._txn_dirty_index(txn, table.id, idx.id)
        prefix_len = len(tablecodec.index_prefix(table.id, idx.id))
        ncols = len(idx.col_offsets)
        out = []
        for start, end in ranges:
            for k, v in self._scan_kvs(start, end, read_ts, txn, dirty):
                mv = memoryview(k)
                pos = prefix_len
                datums = []
                for _ in range(ncols):
                    d, pos = decode_datum_key(mv, pos)
                    if d.kind == K_BYTES:
                        d = Datum.s(d.val.decode("utf8", "replace"))
                    datums.append(d)
                if pos < len(k):
                    handle = tablecodec.decode_index_handle(k)
                else:
                    handle = int(v)
                out.append((datums, handle))
        return out

    def index_batch(
        self, table: TableInfo, idx: IndexInfo, ranges, read_ts: int, txn=None
    ) -> ColumnBatch:
        """Index entries materialized as a full-visible-layout columnar
        batch (covering reads): index-supplied lanes are filled, all other
        lanes stay invalid — the planner guarantees they are unreferenced."""
        entries = self.index_entries(table, idx, ranges, read_ts, txn)
        n = len(entries)
        handles = np.zeros(n, dtype=np.int64)
        chk = Chunk.empty([c.ft for c in table.columns], n)
        cols = chk.columns
        hc = table.handle_col()
        pk_off = hc.offset if (hc is not None and not hc.hidden) else None
        for i, (datums, handle) in enumerate(entries):
            handles[i] = handle
            for off, d in zip(idx.col_offsets, datums):
                cols[off].set_datum(i, d)
            if pk_off is not None:
                cols[pk_off].set_datum(i, Datum.i(handle))
        ver, _ = self.storage.data_version(tablecodec.table_prefix(table.id))
        return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], ver)

    def send_index(
        self, table: TableInfo, idx: IndexInfo, dag: DAGRequest, ranges, read_ts: int,
        engine: str = "auto", txn=None,
    ) -> list[Chunk]:
        """Covering index read: one cop task per range batch."""
        batch = self.index_batch(table, idx, ranges, read_ts, txn)
        if batch.n_rows == 0:
            return []
        return [self._run_engines(dag, batch, engine, sctx=self._sched_ctx())]

    def send_handles(
        self, table: TableInfo, dag: DAGRequest, handles: list[int], read_ts: int,
        engine: str = "auto", txn=None,
    ) -> list[Chunk]:
        """Stage-2 of a double read: fetch rows by handle, run the DAG
        (ref: IndexLookUp table-worker)."""
        if not handles:
            return []
        keys = [tablecodec.record_key(table.id, h) for h in handles]
        if txn is not None and self._txn_dirty(txn, table.id):
            got = txn.batch_get(keys)
        else:
            got = self.storage.snapshot(read_ts).batch_get(keys)
        kvs = [(k, got[k]) for k in keys if k in got]
        batch = decode_rows_to_batch(table, kvs, (-1, 0))
        if batch.n_rows == 0:
            return []
        return [self._run_engines(dag, batch, engine, sctx=self._sched_ctx())]

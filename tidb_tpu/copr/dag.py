"""The pushdown IR — tipb-DAGRequest analog (ref: pingcap/tipb DAGRequest,
planner/core/plan_to_pb.go producer, unistore cophandler consumer).

A DAGRequest is a linear pipeline rooted at a scan:

    ScanNode → [SelectionNode] → [AggNode | TopNNode] → [LimitNode]

Expressions inside nodes are `expr.Expression` trees whose Column indices
refer to the scan's output column order. The digest (stable structural
hash) keys the TPU engine's jit-program cache — the analog of the cop
cache keyed on request bytes (store/copr/coprocessor_cache.go), except
what's cached here is a compiled XLA program, not a result.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..expr.expression import Expression
from ..expr.aggregation import AggDesc
from ..mysqltypes.field_type import FieldType


@dataclass
class ScanNode:
    table_id: int
    col_offsets: list[int]  # offsets into the table's full column list
    col_fts: list[FieldType]
    col_ids: list[int]
    desc: bool = False


@dataclass
class SelectionNode:
    conds: list[Expression]


@dataclass
class AggNode:
    group_by: list[Expression]
    aggs: list[AggDesc]


@dataclass
class TopNNode:
    by: list[tuple[Expression, bool]]  # (expr, desc)
    n: int


@dataclass
class LimitNode:
    n: int


@dataclass
class DAGRequest:
    scan: ScanNode
    selection: SelectionNode | None = None
    agg: AggNode | None = None
    topn: TopNNode | None = None
    limit: LimitNode | None = None

    def output_types(self) -> list[FieldType]:
        """Field types of the chunks this DAG produces (partial-agg layout:
        group-by columns first, then per-agg partial states)."""
        if self.agg is not None:
            fts = [g.ret_type for g in self.agg.group_by]
            for a in self.agg.aggs:
                fts.extend(ft for _, ft in a.partial_final_types())
            return fts
        return list(self.scan.col_fts)

    def digest(self) -> str:
        """Stable structural key for program caching."""
        parts = [
            "scan", str(self.scan.table_id), repr(self.scan.col_offsets),
            repr([int(ft.tp) for ft in self.scan.col_fts]),
            repr([(ft.flag, ft.decimal) for ft in self.scan.col_fts]),
        ]
        if self.selection:
            parts += ["sel"] + [repr(c) for c in self.selection.conds]
        if self.agg:
            parts += ["agg"] + [repr(g) for g in self.agg.group_by] + [repr(a) for a in self.agg.aggs]
        if self.topn:
            parts += ["topn", str(self.topn.n)] + [f"{e!r}:{d}" for e, d in self.topn.by]
        if self.limit:
            parts += ["limit", str(self.limit.n)]
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]

"""Typed retry/backoff + TPU-engine circuit breaker for the cop path
(ref: store/tikv/retry/backoff.go Backoffer/Config; kv/error.go).

The reference survives a hostile distributed substrate by classifying
every fault into a named backoff class (regionMiss, updateLeader,
serverBusy, ...) with its own exponential-with-jitter sleep curve, all
drawing from one per-request sleep budget. This module is that machinery
rebuilt for a heterogeneous substrate: region errors AND accelerator
faults share one Backoffer, and the TPU engine additionally sits behind a
circuit breaker so a *persistently* failing device path stops costing
every query an exception before the host fallback answers.

Waits are deadline/KILL-aware through the admission scheduler's shared
gate (`sched.scheduler.raise_if_interrupted`): a task sleeping in backoff
observes KILL or max_execution_time within one poll interval.
"""

from __future__ import annotations

import itertools
import random
import time
import weakref
from dataclasses import dataclass
from threading import Lock

from ..errors import (
    BackoffExhausted,
    CircuitBreakerOpen,
    DeviceFatalError,
    DeviceTransientError,
    RegionError,
    TiDBError,
)
from ..sched.scheduler import sleep_interruptible
from ..utils import metrics as M


@dataclass(frozen=True)
class BackoffConfig:
    """One retriable-error class: its sleep curve (ref: retry.Config —
    base/cap exponential, jitter flavor) keyed by the name metrics and
    error messages use."""

    name: str
    base_ms: float
    cap_ms: float
    jitter: str = "full"  # "full" | "equal" | "none"

    def sleep_ms(self, attempt: int, rng: random.Random) -> float:
        raw = min(self.base_ms * (2.0 ** attempt), self.cap_ms)
        if self.jitter == "full":
            return rng.uniform(0.0, raw)
        if self.jitter == "equal":
            return raw / 2.0 + rng.uniform(0.0, raw / 2.0)
        return raw


# the typed classes (ref: retry.BoRegionMiss, BoUpdateLeader, BoTiKVServerBusy)
BO_REGION_MISS = BackoffConfig("regionMiss", 2.0, 500.0)
BO_UPDATE_LEADER = BackoffConfig("updateLeader", 1.0, 200.0)
BO_SERVER_BUSY = BackoffConfig("serverBusy", 5.0, 1000.0, "equal")
BO_DEVICE = BackoffConfig("deviceTransient", 1.0, 200.0)

# per-task sleep budget (ref: CopNextMaxBackoff = 20s, scaled to this
# store's in-process latencies)
COP_BACKOFF_BUDGET_MS = 2000.0

# default jitter source for every Backoffer (GIL-serialized; interleaved
# draws are fine for jitter)
_SHARED_RNG = random.Random()


class Backoffer:
    """Per-cop-task retry budget: every retriable fault calls
    `backoff(cfg, err)`, which sleeps per the class curve and accounts the
    sleep against one shared budget. Exhausting the budget raises
    `BackoffExhausted` naming the region, per-class attempt counts and the
    last error — the caller fails the stream with that, siblings retry on
    their own Backoffers (per-task isolation)."""

    def __init__(self, budget_ms: float = COP_BACKOFF_BUDGET_MS, deadline=None,
                 session=None, rng: random.Random | None = None, stats=None,
                 trace=None):
        self.budget_ms = budget_ms
        self.deadline = deadline
        self.session = session
        self.abort = None  # optional Event: owning stream was abandoned
        self.trace = trace  # StatementTrace: backoff sleeps become spans
        self.slept_ms = 0.0
        self.attempts: dict[str, int] = {}
        self.errors: list[BaseException] = []
        # shared module RNG by default: seeding a fresh Random() per
        # statement costs ~80µs of os.urandom — pure hot-path churn for
        # backoff jitter nobody needs to be independent (tests that want
        # determinism still pass their own rng)
        self._rng = rng or _SHARED_RNG
        self._stats = stats  # optional callable(key, n) — client counters
        self._runaway = None  # RunawayChecker, for in-flight COOLDOWN
        self._demote_applied = False

    @classmethod
    def for_ctx(cls, sctx, budget_ms: float | None = None, stats=None):
        """Build from a SchedCtx (or None) so backoff waits observe the
        same deadline/KILL state admission waits do. The budget comes from
        the context's `backoff_budget_ms` (the tidb_backoff_budget_ms
        sysvar / SET_VAR hint) unless overridden, falling back to the
        compiled-in default."""
        if budget_ms is None:
            budget_ms = getattr(sctx, "backoff_budget_ms", None)
        if budget_ms is None:
            budget_ms = COP_BACKOFF_BUDGET_MS
        rc = getattr(sctx, "runaway", None)
        if rc is not None and rc.demoted:
            # runaway COOLDOWN: a demoted statement gets a quarter of the
            # sleep budget — less patience for a known misbehaver
            budget_ms *= 0.25
        bo = cls(
            budget_ms,
            deadline=getattr(sctx, "deadline", None),
            session=getattr(sctx, "session", None),
            stats=stats,
            trace=getattr(sctx, "trace", None),
        )
        # keep the checker: a COOLDOWN verdict landing MID-statement must
        # demote the budget still unspent, not wait for the next statement
        bo._runaway = rc
        bo._demote_applied = rc is not None and rc.demoted
        return bo

    @property
    def total_attempts(self) -> int:
        return sum(self.attempts.values())

    def backoff(self, cfg: BackoffConfig, err: BaseException) -> None:
        """Record `err` under `cfg`'s class and sleep its next interval;
        raises BackoffExhausted when the budget can't cover the sleep, and
        QueryInterrupted the moment a KILL/deadline lands mid-sleep."""
        rc = self._runaway
        if rc is not None and rc.demoted and not self._demote_applied:
            # the COOLDOWN verdict fired while this statement was already
            # retrying: quarter the budget it has NOT yet slept, effective
            # from this very backoff — not from its next statement
            self._demote_applied = True
            self.budget_ms = self.slept_ms + (self.budget_ms - self.slept_ms) * 0.25
        n = self.attempts.get(cfg.name, 0)
        self.attempts[cfg.name] = n + 1
        self.errors.append(err)
        M.COP_RETRIES.inc(reason=cfg.name)
        if self._stats is not None:
            self._stats("retries", 1)
        sleep = cfg.sleep_ms(n, self._rng)
        if self.slept_ms + sleep > self.budget_ms:
            raise BackoffExhausted(self._exhausted_msg(err)) from err
        self.slept_ms += sleep
        if self._stats is not None:
            self._stats("backoff_ms", sleep)
        M.COP_BACKOFF.observe(sleep / 1000.0)
        sleep_interruptible(
            sleep / 1000.0, self.deadline, self.session,
            stop=self.abort.is_set if self.abort is not None else None,
        )
        if self.trace is not None and self.trace.recording:
            # after the sleep so the span is closed (back-dated) — a
            # KILL/deadline escape mid-sleep skips it with the exception
            self.trace.closed_span(
                f"backoff.{cfg.name}", sleep / 1000.0,
                attempt=n + 1, error=type(err).__name__,
            )

    def _exhausted_msg(self, last_err: BaseException) -> str:
        region = next(
            (e.region_id for e in reversed(self.errors)
             if isinstance(e, RegionError) and e.region_id is not None),
            None,
        )
        per_class = ", ".join(f"{k}:{v}" for k, v in sorted(self.attempts.items()))
        where = f"region {region}" if region is not None else "task"
        return (
            f"cop task backoff budget exhausted ({self.budget_ms:.0f}ms slept "
            f"{self.slept_ms:.0f}ms) for {where} after {self.total_attempts} "
            f"attempts ({per_class}); last error: {last_err}"
        )


# --- engine-boundary fault classification ---------------------------------

# substrings marking a device fault worth retrying on-device (XLA runtime
# status codes + tunnel/transport hiccups); everything else device-side is
# fatal and feeds the breaker
_TRANSIENT_MARKERS = (
    "resource_exhausted", "unavailable", "deadline_exceeded", "aborted",
    "cancelled", "preempt", "connection", "socket", "tunnel", "timed out",
    "timeout", "temporarily",
)


def classify_device_error(exc: BaseException):
    """Triage an exception escaping the TPU engine (replaces the blanket
    `except Exception` fallback): returns a DeviceTransientError /
    DeviceFatalError, or None when the exception is NOT a device fault at
    all (interrupts, quota, SQL runtime errors) and must propagate to the
    caller untouched — neither retried, breaker-counted, nor absorbed by
    the host fallback."""
    if isinstance(exc, (DeviceTransientError, DeviceFatalError)):
        return exc
    if isinstance(exc, TiDBError):
        return None
    msg = f"{type(exc).__name__}: {exc}"
    low = msg.lower()
    if any(m in low for m in _TRANSIENT_MARKERS):
        return DeviceTransientError(msg)
    return DeviceFatalError(msg)


# --- the one shared device-boundary guard ----------------------------------


def guarded_device_call(fn, bo: "Backoffer", breakers=(), forced: bool = False,
                        failpoint: str | None = None):
    """Run a device-path callable under the unified fault domain — the
    MPP gather and the device window route share this with the cop
    path's inline boundary (client._run_engines), so every device entry
    point fails the same way:

      * escaping exceptions are CLASSIFIED (classify_device_error) —
        interrupts / quota / SQL errors propagate untouched (any claimed
        half-open probe slot is released, no fault counted);
      * every device fault feeds every breaker in `breakers` (one event
        per exception instance per breaker);
      * transients retry through `bo` (per-task budget, KILL/deadline-
        aware sleeps) while no breaker has tripped;
      * with `forced` (engine='tpu' / enforce), the typed error raises;
        otherwise the terminal fault is RETURNED so the caller degrades
        to host with a typed reason and zero further exception cost.

    Returns (result, None) on success — breakers hear record_success
    only when `result is not None`, because a None result means the
    callable declined before touching the device (a half-open probe must
    not close on no evidence) — or (None, err) when the device path
    lost. tools/lint_boundaries.py pins this as the ONE sanctioned
    blanket-except site for the MPP/window boundaries."""
    from ..utils.failpoint import inject as _fp

    while True:
        try:
            if failpoint is not None:
                _fp(failpoint)
            res = fn()
        except Exception as exc:  # noqa: BLE001 — classified, never absorbed
            err = classify_device_error(exc)
            if err is None:
                for b in breakers:
                    b.record_aborted()
                raise
            tripped = False
            for b in breakers:
                tripped = b.record_failure(exc) or tripped
            if isinstance(err, DeviceTransientError) and not tripped:
                try:
                    bo.backoff(BO_DEVICE, err)
                except BackoffExhausted as bex:
                    err = bex
                else:
                    continue
            if forced:
                raise err from exc
            return None, err
        if res is not None:
            for b in breakers:
                b.record_success()
        return res, None


# --- circuit breaker --------------------------------------------------------


class CircuitBreaker:
    """TPU-engine circuit breaker: closed → open after `threshold`
    CONSECUTIVE device faults (each success resets the run), open →
    half-open after `cooldown_s`, half-open admits exactly ONE probe —
    success closes the breaker, failure re-opens it for another cooldown.

    While open, `auto` traffic routes straight to the host engine at zero
    exception cost and `engine='tpu'` raises CircuitBreakerOpen carrying
    `describe()`. State/trips surface in /metrics (tidb_tpu_breaker_*)
    and EXPLAIN ANALYZE's tpu line."""

    FAIL_THRESHOLD = 5
    COOLDOWN_S = 30.0

    _STATE_GAUGE = {"closed": 0.0, "half-open": 1.0, "open": 2.0}
    _seq = itertools.count()

    def __init__(self, threshold: int | None = None, cooldown_s: float | None = None,
                 clock=time.monotonic, label: str | None = None):
        self.threshold = self.FAIL_THRESHOLD if threshold is None else threshold
        self.cooldown_s = self.COOLDOWN_S if cooldown_s is None else cooldown_s
        self._clock = clock
        self._lock = Lock()
        # breakers are per-engine: the published series is labeled so two
        # stores in one process can't clobber each other's state
        self.label = label if label is not None else f"e{next(self._seq)}"
        self.state = "closed"
        self.trips = 0
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False  # a half-open probe is in flight
        self._probe_at = 0.0
        # identity ring of already-counted fault events: WEAK refs — a
        # strong ring would pin up to 8 tracebacks (and the batch locals
        # in their frames) to this process-lifetime engine singleton
        self._counted: list = []
        # no eager publish: a series appears only on the first transition,
        # so idle breakers (one per short-lived embedded store) don't leak
        # dead label values into the process-global registry

    def allow(self) -> bool:
        """May the next task try the device path? Flips open → half-open
        once the cooldown has passed, and admits one probe at a time. A
        probe that never reported back (its thread died outside the
        record_* paths) goes stale after another cooldown and the probe
        slot is re-granted — the breaker can't wedge in half-open."""
        with self._lock:
            if self.state == "closed":
                return True
            now = self._clock()
            if self.state == "open" and now - self._opened_at >= self.cooldown_s:
                self.state = "half-open"
                self._probing = False
                self._publish_locked()
            if self.state == "half-open":
                if self._probing and now - self._probe_at >= self.cooldown_s:
                    self._probing = False  # lost probe: reclaim the slot
                if not self._probing:
                    self._probing = True
                    self._probe_at = now
                    return True
            return False

    def record_success(self) -> None:
        """A successful device run: resets the consecutive-fault count;
        closes the breaker only from half-open (the probe's success). A
        straggler admitted before a trip must NOT close an OPEN breaker —
        that would bypass the cooldown + single-probe protocol whenever a
        device faults for only some program keys."""
        with self._lock:
            self._consecutive = 0
            self._probing = False
            if self.state == "half-open":
                self.state = "closed"
                self._publish_locked()

    def record_aborted(self) -> None:
        """The device attempt ended for a NON-device reason (KILL, quota,
        queue-full): releases a held probe slot without counting a fault
        either way."""
        with self._lock:
            self._probing = False

    def record_failure(self, err: BaseException | None = None) -> bool:
        """Count one device fault; returns True when the breaker is (now)
        open. One fault EVENT counts once: co-batched/dedup'd cop tasks
        that all failed from a single launch share one exception instance
        (sched/batcher.py fans `j.exc` out to every follower), and N
        waiters of one blip must not masquerade as N consecutive faults.
        Real faults arrive as fresh instances and always count."""
        with self._lock:
            if err is not None:
                if any(r() is err for r in self._counted):
                    self._probing = False
                    return self.state == "open"
                try:
                    self._counted.append(weakref.ref(err))
                    del self._counted[:-8]
                except TypeError:
                    pass  # exception type without weakref support: count always
            self._consecutive += 1
            tripped = (
                self.state == "half-open"
                or (self.state == "closed" and self._consecutive >= self.threshold)
            )
            self._probing = False
            if tripped:
                self.state = "open"
                self._opened_at = self._clock()
                self.trips += 1
                M.BREAKER_TRIPS.inc(engine=self.label)
                self._publish_locked()
            return self.state == "open"

    def is_open(self) -> bool:
        with self._lock:
            return self.state == "open"

    def describe(self) -> str:
        with self._lock:
            return (
                f"state={self.state} consecutive_faults={self._consecutive} "
                f"trips={self.trips} cooldown_s={self.cooldown_s}"
            )

    def raise_open(self) -> None:
        raise CircuitBreakerOpen(
            f"TPU engine circuit breaker rejected the request ({self.describe()}); "
            f"use engine='host'/'auto' or wait out the cooldown"
        )

    def _publish_locked(self) -> None:
        M.BREAKER_STATE.set(self._STATE_GAUGE[self.state], engine=self.label)

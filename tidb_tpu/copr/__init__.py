from .dag import DAGRequest, ScanNode, SelectionNode, AggNode, TopNNode, LimitNode
from .client import CopClient

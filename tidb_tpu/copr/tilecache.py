"""Columnar tile cache — the TiFlash-replica analog (SURVEY §2.12 TiFlash
row: "columnar replica + MPP engine"; here the columnar replica is a
lazily-built, version-tagged cache of decoded column batches per
(table, region), reused across queries so the scan hot path never touches
row decode).

Invalidation: `Storage.bump_version` increments a per-table counter on
every committed write; a batch built at an older version is rebuilt on
next access. Uncommitted reads (txn membuffer) bypass the cache: the cop client
builds the task batch from the txn's merged view (client.py send).
"""

from __future__ import annotations

from dataclasses import dataclass
from threading import RLock

import numpy as np

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..codec import tablecodec
from ..codec.row import decode_row
from ..catalog.schema import TableInfo
from ..mysqltypes.datum import Datum


@dataclass
class ColumnBatch:
    """All rows of one (table, region) decoded into dense numpy columns."""

    table: TableInfo
    handles: np.ndarray  # int64 row handles
    data: list[np.ndarray]  # per table column (offset order)
    valid: list[np.ndarray]
    version: tuple | int
    start: bytes = b""
    end: bytes = b""
    min_valid_ts: int = 0  # last table-commit ts at build time

    @property
    def n_rows(self) -> int:
        return len(self.handles)

    def to_chunk(self, col_offsets: list[int]) -> Chunk:
        cols = []
        for off in col_offsets:
            ft = self.table.columns[off].ft
            cols.append(Column(ft, self.data[off], self.valid[off]))
        return Chunk(cols)


# --- device tile codecs (host-side encode half; decode is fused into the
# --- jitted device program in tpu_engine._decode_lane) ----------------------
#
# Per-column encodings chosen at batch build so the WIRE/h2d form is the
# compressed form ("GPU Acceleration of SQL Analytics on Compressed Data",
# arXiv:2506.10092 — decompress-in-kernel beats transfer-then-process):
#
#   pack   frame-of-reference downcast for narrow-range int lanes: upload
#          (d - lo) as uint8/16/32 plus a 0-d base scalar in the ORIGINAL
#          dtype; decode is one add (bit-exact, ints only)
#   dict   sorted-unique values + narrow codes for low-NDV lanes (ints AND
#          floats — skipped when the lane holds NaN, which breaks
#          searchsorted, or a negative zero, which np.unique would
#          bit-merge with +0.0); decode is one gather
#   rle    run-length (vals, lens) for sorted/clustered/constant lanes and
#          few-run validity masks; decode is jnp.repeat with a static
#          total_repeat_length (pad tail rows are don't-care: every
#          kernel masks with row_valid / the per-lane valid bit first)
#   rv     zero-byte alias for the all-valid mask — it is bit-identical
#          to row_valid, which the kernel already holds
#   dense  the plain padded [T, R] lane — chosen whenever no codec beats
#          it (wide-range high-NDV ints, high-entropy floats)
#
# Invalid rows are normalized to 0 before encoding (kernels never read
# data under a false valid bit), and aux arrays (dict vocab, rle runs) pad
# to power-of-two lengths so compile-cache keys — which carry the codec
# signature — stay bounded.

MIN_TILE_ROWS = 256  # smallest row bucket a DeviceBatch pads to
DICT_MAX_NDV = 4096  # beyond this a dict vocab stops paying for itself
_AUX_MIN = 8  # smallest padded aux-array length (vocab / run buffers)


def pow2_rows(n: int, lo: int = MIN_TILE_ROWS) -> int:
    """Row-bucket for n rows: next power of two, floored at `lo`."""
    return max(lo, 1 << max(0, int(n - 1).bit_length()))


def _pow2_len(n: int, lo: int = _AUX_MIN) -> int:
    return pow2_rows(n, lo)


def _pad2d(a: np.ndarray, shape: tuple[int, int]) -> np.ndarray:
    t, r = shape
    out = np.zeros(t * r, dtype=a.dtype)
    out[: len(a)] = a
    return out.reshape(t, r)


def _pad1d(a: np.ndarray, length: int) -> np.ndarray:
    out = np.zeros(length, dtype=a.dtype)
    out[: len(a)] = a
    return out


def _rle_encode(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(run values, run lengths) of x. NaN != NaN splits runs — harmless:
    each NaN becomes its own run and decodes back bit-exact."""
    n = len(x)
    if n == 0:
        return x[:0], np.zeros(0, np.int32)
    change = np.empty(n, dtype=bool)
    change[0] = True
    np.not_equal(x[1:], x[:-1], out=change[1:])
    idx = np.flatnonzero(change)
    return x[idx], np.diff(np.append(idx, n)).astype(np.int32)


def _code_dtype(span: int):
    """Smallest unsigned dtype holding values in [0, span]."""
    if span < (1 << 8):
        return np.uint8
    if span < (1 << 16):
        return np.uint16
    if span < (1 << 32):
        return np.uint32
    return None


def encode_valid_lane(v: np.ndarray, shape: tuple[int, int]):
    """Validity mask codec. The overwhelmingly common all-valid mask is
    EXACTLY row_valid (true for real rows, false for the pad tail), so it
    ships as a zero-byte alias — the kernel reuses the row_valid array it
    already holds, paying neither wire bytes nor a decode expand. Masks
    with few runs take RLE; ragged ones stay dense. Returns
    (payload | None for dense, sig)."""
    if v.all():
        return {}, ("rv",)
    padded = shape[0] * shape[1]
    vals, lens = _rle_encode(v)
    # +1 guarantees a trailing zero-value zero-length pad run: jnp.repeat
    # with total_repeat_length clamps the tail gather to the LAST run,
    # so without the pad an exactly-pow2 run count ending in True would
    # decode pad rows as valid
    np_len = _pow2_len(len(vals) + 1)
    rle_bytes = np_len * (vals.dtype.itemsize + 4)
    if rle_bytes < padded // 2:
        return (
            {"rv": _pad1d(vals, np_len), "rl": _pad1d(lens, np_len)},
            ("rle", np_len),
        )
    return None, ("dense",)


def encode_data_lane(d: np.ndarray, v: np.ndarray, shape: tuple[int, int]):
    """Pick + apply the cheapest codec for one numeric data lane.
    Returns (payload | None for dense, sig). `sig` is the static codec
    descriptor that joins the device program's compile-cache key (decode
    is traced into the program, so programs are codec-specific) AND the
    launch-group fuse key (stacked lanes must agree on aux shapes)."""
    padded = shape[0] * shape[1]
    item = d.dtype.itemsize
    dense_bytes = padded * item
    dz = np.where(v, d, np.zeros((), d.dtype)) if not v.all() else d
    any_valid = bool(v.any())
    is_int = np.issubdtype(d.dtype, np.integer)

    # a float lane holding negative zero stays dense/pack-free of value
    # merging: -0.0 == 0.0 under np.unique AND run detection, so dict and
    # rle would canonicalize the sign bit the dense lane preserves
    has_negzero = (not is_int) and bool(np.any((dz == 0.0) & np.signbit(dz)))

    best = (dense_bytes, "dense", None)

    # rle — runs over the normalized lane (+1: always keep a zero pad
    # run so the decode's tail-clamp gathers 0, see encode_valid_lane)
    if not has_negzero:
        rvals, rlens = _rle_encode(dz)
        np_len = _pow2_len(len(rvals) + 1)
        rle_bytes = np_len * (item + 4)
        if rle_bytes < best[0]:
            best = (rle_bytes, "rle", (rvals, rlens, np_len))

    lo = hi = None
    if any_valid and is_int:
        lo, hi = dz[v].min(), dz[v].max()
        cdt = _code_dtype(int(hi) - int(lo))
        if cdt is not None and cdt().itemsize < item:
            pack_bytes = padded * cdt().itemsize + item
            if pack_bytes < best[0]:
                best = (pack_bytes, "pack", (lo, cdt))

    if any_valid and not has_negzero:
        # dict — sample NDV first so np.unique never runs on a lane that
        # obviously won't dictionary-compress; the stride comes from the
        # VALID subset being sampled (a sparse-valid lane would otherwise
        # be under-sampled into a spuriously high NDV estimate)
        pres = dz[v]
        sample = pres[:: max(1, len(pres) // 4096)][:4096]
        if len(np.unique(sample)) <= min(DICT_MAX_NDV, max(len(sample) // 2, 1)):
            if is_int or not np.isnan(pres).any():
                uniq = np.unique(pres)
                ndv = len(uniq)
                cdt = _code_dtype(ndv - 1) if ndv else None
                if ndv and ndv <= DICT_MAX_NDV and cdt is not None \
                        and cdt().itemsize < item:
                    vp = _pow2_len(ndv)
                    dict_bytes = padded * cdt().itemsize + vp * item
                    if dict_bytes < best[0]:
                        best = (dict_bytes, "dict", (uniq, vp, cdt))

    kind = best[1]
    if kind == "dense":
        return None, ("dense",)
    if kind == "rle":
        rvals, rlens, np_len = best[2]
        return (
            {"rv": _pad1d(rvals, np_len), "rl": _pad1d(rlens, np_len)},
            ("rle", np_len, d.dtype.str),
        )
    if kind == "pack":
        lo, cdt = best[2]
        packed = (dz.astype(np.int64) - int(lo)).astype(cdt) if d.dtype.kind == "i" \
            else (dz - lo).astype(cdt)
        return (
            {"p": _pad2d(packed, shape), "b": np.asarray(lo, dtype=d.dtype)},
            ("pack", np.dtype(cdt).str, d.dtype.str),
        )
    uniq, vp, cdt = best[2]
    codes = np.searchsorted(uniq, dz).astype(cdt)
    codes[~v] = 0
    vocab = _pad1d(uniq, vp)
    if vp > len(uniq):
        vocab[len(uniq):] = uniq[-1]  # pad codes stay in-domain
    return (
        {"c": _pad2d(codes, shape), "v": vocab},
        ("dict", np.dtype(cdt).str, vp, d.dtype.str),
    )


def batch_nbytes(batch: ColumnBatch) -> float:
    """Approximate host bytes of a batch — the RU read-byte term and the
    arbiter's footprint proxy. numpy lanes answer exactly; object lanes
    count their pointer array (a cheap, stable underestimate — the RU
    model needs monotonic, not forensic). Cached: sibling tasks and
    retries re-ask for the same immutable batch."""
    cached = getattr(batch, "_nbytes", None)
    if cached is None:
        n = float(getattr(batch.handles, "nbytes", 0))
        for a in batch.data:
            n += getattr(a, "nbytes", 0)
        for v in batch.valid:
            n += getattr(v, "nbytes", 0)
        batch._nbytes = cached = n
    return cached


def device_nbytes(batch: ColumnBatch, lane_idx: int | None = None) -> float | None:
    """Actual device wire footprint of a batch's mirrors: the bytes the
    narrowed/compressed tiles REALLY moved (and hold resident), not the
    64Ki-padded fiction the RU/memory layers used to see. None when no
    mirror exists (host-path task). With `lane_idx` the SERVING lane's
    mirror answers — stale sibling mirrors (built under another layout
    flag, or spill copies with fewer lanes uploaded) must not set another
    lane's RU charge; without it, the smallest mirror stands in."""
    mirrors = getattr(batch, "_mirrors", None)
    if not mirrors:
        return None
    if lane_idx is not None:
        m = mirrors.get(lane_idx)
        if m is not None and getattr(m, "wire_nbytes", 0):
            return float(m.wire_nbytes)
    vals = [
        float(m.wire_nbytes)
        for m in mirrors.values()
        if getattr(m, "wire_nbytes", 0)
    ]
    return min(vals) if vals else None


def _decode_handles(keybuf: np.ndarray, n: int) -> np.ndarray:
    """(n, 19) record-key byte matrix → int64 handles (vectorized BE+sign)."""
    enc = np.ascontiguousarray(keybuf[:, 11:19]).view(">u8").reshape(n)
    return (enc.astype(np.uint64) ^ np.uint64(1 << 63)).view(np.int64)


def _decode_values_into(table, cols, big: np.ndarray, offs: np.ndarray, lens: np.ndarray, rows_idx: np.ndarray, handles: np.ndarray) -> None:
    """Decode row values (at byte offsets `offs`, byte lengths `lens`, in
    buffer `big`) into chunk columns at target positions `rows_idx`; v2
    rows vectorized, v1 rows per-row."""
    from ..codec import rowfast

    n = len(offs)
    if n == 0:
        return
    first = big[offs]
    v2 = first == rowfast.V2_FLAG
    v2_pos = np.nonzero(v2)[0]
    if len(v2_pos):
        # batch-decode header-identical rows; fall back on the rest
        bad = rowfast.decode_v2_batch(big, offs[v2_pos], table, cols, rows_idx[v2_pos])
        for b in bad:  # rare: schema drifted mid-table
            p = v2_pos[int(b)]
            end = int(offs[p]) + int(lens[p])
            _decode_one(table, cols, int(rows_idx[p]), big[offs[p] : end].tobytes(), int(handles[p]))
    for p in np.nonzero(~v2)[0]:
        end = int(offs[p]) + int(lens[p])
        _decode_one(table, cols, int(rows_idx[p]), big[offs[p] : end].tobytes(), int(handles[p]))


def decode_rows_to_batch(table: TableInfo, kvs: list[tuple[bytes, bytes]], version: int) -> ColumnBatch:
    """Row-format KV pairs → dense columnar batch (the once-per-version
    decode; ref: rowcodec ChunkDecoder decoding straight into chunks).

    v2 rows (bulk-loaded, identical headers) decode with vectorized numpy
    gathers; v1 rows (DML path) fall back to per-row decode. A mixed batch
    routes each row down the right path by its version flag.
    """
    n = len(kvs)
    chk = Chunk.empty([c.ft for c in table.columns], n)
    cols = chk.columns

    # handles: record keys are fixed 19 bytes → one vectorized BE decode
    keybuf = np.frombuffer(b"".join(k for k, _ in kvs), dtype=np.uint8)
    if n and len(keybuf) == 19 * n:
        handles = _decode_handles(keybuf.reshape(n, 19), n)
    else:  # ragged keys (shouldn't happen for record scans) — per-row
        handles = np.fromiter((tablecodec.decode_record_handle(k) for k, _ in kvs), np.int64, n)

    vals = [v for _, v in kvs]
    lens = np.fromiter((len(v) for v in vals), np.int64, n)
    big = np.frombuffer(b"".join(vals), dtype=np.uint8)
    offs = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    _decode_values_into(table, cols, big, offs, lens, np.arange(n, dtype=np.int64), handles)

    # hidden rowid column mirrors handles
    for c in table.columns:
        if c.hidden and c.name == "_tidb_rowid":
            cols[c.offset].data[:] = handles
            cols[c.offset].valid[:] = True
    return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], version)


def _gather_columnar(table: TableInfo, cols, run, keep: np.ndarray,
                     rows_idx: np.ndarray) -> None:
    """ColumnarRun fast path: copy the run's column arrays straight into
    the chunk columns — no v2 row decode, no byte-matrix gather. Mirrors
    decode_v2_batch's routing exactly (decimal rescale to the table's
    scale, float/uint bit views, ascii/utf8 strings, defaults for table
    columns the run doesn't carry)."""
    from ..mysqltypes.datum import K_DEC, K_STR
    from ..table.table import datum_from_default

    by_id = {c.id: c for c in table.columns}
    contiguous = len(keep) == run.n  # whole-run scans skip the gather copy
    present: set[int] = set()
    for spec in run.cols:
        c = by_id.get(spec.cid)
        if c is None:
            continue
        present.add(spec.cid)
        col = cols[c.offset]
        data = spec.data if contiguous else spec.data[keep]
        if data.dtype.kind == "O":
            # still-object str lane: already the chunk form — no decode
            col.data[rows_idx] = data
        elif data.dtype.kind == "S":
            w = data.dtype.itemsize
            if spec.kind != K_STR:  # K_BYTES lanes keep bytes payloads
                strs = np.array([bytes(x) for x in data], dtype=object)
            elif w == 0:
                strs = np.full(len(rows_idx), "", dtype=object)
            elif (data.view(np.uint8) >= 0x80).any():  # non-ascii → utf8 per row
                strs = np.array([bytes(x).decode("utf8") for x in data], dtype=object)
            else:
                strs = data.astype("U").astype(object)
            col.data[rows_idx] = strs
        else:
            vals = data
            if spec.kind == K_DEC:
                want = max(c.ft.decimal, 0)
                sc = spec.scale
                if want != sc:
                    vals = vals * 10 ** (want - sc) if want > sc else vals // 10 ** (sc - want)
            col.data[rows_idx] = vals.astype(col.data.dtype, copy=False)
        if spec.valid is None:
            col.valid[rows_idx] = True
        else:
            col.valid[rows_idx] = spec.valid if contiguous else spec.valid[keep]
    for c in table.columns:
        if c.id in present:
            continue
        if c.hidden and c.name == "_tidb_rowid":
            continue  # caller fills from handles
        d = datum_from_default(c)
        col = cols[c.offset]
        if d.is_null:
            col.valid[rows_idx] = False
        else:
            for i in rows_idx:
                col.set_datum(int(i), d)


def build_batch_from_segments(table: TableInfo, segs, loose, version) -> ColumnBatch:
    """Segment scan results → columnar batch, gathering key/value bytes
    straight out of run buffers (zero per-row materialization for the
    bulk-loaded fast path; ColumnarRun segments copy their column arrays
    directly — no row decode at all)."""
    from ..storage.segment import ColumnarRun

    keeps = [s.keep_idx() for s in segs]
    n = sum(len(k) for k in keeps) + len(loose)
    chk = Chunk.empty([c.ft for c in table.columns], n)
    cols = chk.columns
    handles = np.zeros(n, dtype=np.int64)
    row0 = 0
    for s, keep in zip(segs, keeps):
        m = len(keep)
        if m == 0:
            continue
        run = s.run
        rows_idx = np.arange(row0, row0 + m, dtype=np.int64)
        if isinstance(run, ColumnarRun):
            seg_handles = run.handles_arr if m == run.n else run.handles_arr[keep]
            handles[row0 : row0 + m] = seg_handles
            _gather_columnar(table, cols, run, keep, rows_idx)
            row0 += m
            continue
        key_mat = run.key_mat[keep]
        if key_mat.shape[1] == 19:
            seg_handles = _decode_handles(key_mat, m)
        else:
            seg_handles = np.fromiter(
                (tablecodec.decode_record_handle(run.key_at(int(i))) for i in keep), np.int64, m
            )
        handles[row0 : row0 + m] = seg_handles
        big = run.value_buffer()
        _decode_values_into(table, cols, big, run.starts[keep], run.lens[keep], rows_idx, seg_handles)
        row0 += m
    for k, v in loose:
        h = tablecodec.decode_record_handle(k)
        handles[row0] = h
        _decode_one(table, cols, row0, v, h)
        row0 += 1
    for c in table.columns:
        if c.hidden and c.name == "_tidb_rowid":
            cols[c.offset].data[:] = handles
            cols[c.offset].valid[:] = True
    return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], version)


def _decode_one(table: TableInfo, cols, i: int, val: bytes, handle: int) -> None:
    from ..table.table import datum_from_default

    by_id = decode_row(val)
    for off, c in enumerate(table.columns):
        d = by_id.get(c.id)
        if d is None:
            if c.hidden and c.name == "_tidb_rowid":
                d = Datum.i(handle)
            else:
                d = datum_from_default(c)
        cols[off].set_datum(i, d)


class BuildSideCache:
    """Device-resident build-side join structures, shared store-wide
    (ISSUE 11; "Fine-Tuning Data Structures for Analytical Query
    Processing", arXiv:2112.13099 — specialize the join structure per
    build-side shape and keep it resident).

    TPC-H dimension tables rarely change between statements, so the MPP
    engine's specialized build sides (today: the direct-address LUT
    mapping packed join key → build row position, probed as a pure
    device gather) stay uploaded across statements instead of being
    re-sorted inside every fused program.

    Keying: `(table_id, span, schema_version, codec_sig)` where
    `codec_sig` carries the structure tag, the table DATA version and
    every layout parameter (key offsets, packing lo/strides, domain,
    lane codec form). A get() under a NEW schema/data version purges the
    stale entries of the same (table, span, tag) — a stale build side
    must never serve — and counts them as invalidations. Entries LRU
    under a byte budget, and `evict_all()` joins the server memory
    arbiter's soft-limit degrade sweep exactly like the tile cache (the
    arbiter snapshots its cache list OUTSIDE the registry lock, so this
    lock nests under nothing of lower rank)."""

    CAP_BYTES = 1 << 30

    def __init__(self):
        from collections import OrderedDict

        self._od: "OrderedDict[tuple, tuple]" = OrderedDict()  # key → (value, nbytes)
        self._lock = RLock()
        self.nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evicts = 0
        self.invalidates = 0

    @staticmethod
    def _nbytes(value) -> int:
        n = 0
        for x in value if isinstance(value, (tuple, list)) else (value,):
            n += int(getattr(x, "nbytes", 64))
        return n

    def get(self, table_id: int, span: tuple, schema_ver: int, sig: tuple, build):
        """Cached device structure for the key, building (and uploading)
        via `build()` on miss. `sig[0]` is the structure tag: stale
        same-(table, span, tag) entries under any OTHER (schema_ver,
        sig) are purged here — version bumps invalidate, they don't
        linger until LRU pressure."""
        from ..utils import metrics as M

        key = (table_id, span, schema_ver, sig)
        with self._lock:
            ent = self._od.get(key)
            if ent is not None:
                self._od.move_to_end(key)
                self.hits += 1
                M.TPU_BUILD_CACHE.inc(outcome="hit")
                return ent[0]
            stale = [k for k in self._od
                     if k[0] == table_id and k[1] == span and k[3][0] == sig[0]
                     and (k[2] != schema_ver or k[3] != sig)]
            for k in stale:
                self.nbytes -= self._od.pop(k)[1]
                self.invalidates += 1
                M.TPU_BUILD_CACHE.inc(outcome="invalidate")
            self.misses += 1
            M.TPU_BUILD_CACHE.inc(outcome="miss")
        # build + upload OUTSIDE the lock: a slow h2d must not stall
        # every other statement's probe (a racing duplicate build is
        # benign — last writer wins, same content)
        value = build()
        nb = self._nbytes(value)
        with self._lock:
            old = self._od.pop(key, None)
            if old is not None:
                # a concurrent statement built the same key while we
                # were outside the lock — return the bytes its entry
                # held, or the ledger drifts up on every such race
                self.nbytes -= old[1]
            self._od[key] = (value, nb)
            self.nbytes += nb
            while self.nbytes > self.CAP_BYTES and len(self._od) > 1:
                _, (_, old_nb) = self._od.popitem(last=False)
                self.nbytes -= old_nb
                self.evicts += 1
                M.TPU_BUILD_CACHE.inc(outcome="evict")
        return value

    def invalidate_table(self, table_id: int) -> None:
        from ..utils import metrics as M

        with self._lock:
            for k in [k for k in self._od if k[0] == table_id]:
                self.nbytes -= self._od.pop(k)[1]
                self.invalidates += 1
                M.TPU_BUILD_CACHE.inc(outcome="invalidate")

    def evict_all(self) -> float:
        """Server soft-memory-limit degrade action (utils/memory
        ServerMemTracker sweep): drop every resident structure. Returns
        the device bytes released for collection."""
        from ..utils import metrics as M

        with self._lock:
            freed = float(self.nbytes)
            n = len(self._od)
            self._od.clear()
            self.nbytes = 0
            self.evicts += n
            for _ in range(n):
                M.TPU_BUILD_CACHE.inc(outcome="evict")
        return freed


class TileCache:
    def __init__(self, storage):
        self.storage = storage
        self._cache: dict[tuple[int, bytes], ColumnBatch] = {}
        self._lock = RLock()  # cop worker pool shares this cache
        self.hits = 0
        self.misses = 0

    def get_batch(self, table: TableInfo, start: bytes, end: bytes, read_ts: int) -> ColumnBatch:
        """Snapshot-correct cache: a batch built when the table's last
        commit was at `last_commit_ts` is valid for any read_ts ≥ that
        commit while the version counter is unchanged. Reads BELOW the
        last commit (historic snapshots) always rebuild, uncached."""
        ver, last_commit_ts = self.storage.data_version(tablecodec.table_prefix(table.id))
        key = (table.id, start)
        with self._lock:
            cached = self._cache.get(key)
            if (
                cached is not None
                and cached.version == ver
                and cached.end == end
                and read_ts >= cached.min_valid_ts
            ):
                self.hits += 1
                return cached
            self.misses += 1
        snap = self.storage.snapshot(read_ts)
        segs, loose = snap.scan_segments(start, end)
        batch = build_batch_from_segments(table, segs, loose, ver)
        batch.start, batch.end = start, end
        batch.min_valid_ts = last_commit_ts
        if read_ts >= last_commit_ts:
            with self._lock:
                self._cache[key] = batch
        return batch

    def invalidate_table(self, table_id: int) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == table_id]:
                del self._cache[key]
        # build sides are DERIVED from these lanes: whoever invalidates
        # the tiles (DDL, TRUNCATE, RESTORE) invalidates the resident
        # join structures too — without instantiating the cache just to
        # empty it
        bc = getattr(self.storage, "_build_cache", None)
        if bc is not None:
            bc.invalidate_table(table_id)
        # the workload-history plane learned its walls against the OLD
        # tiles: schema-level invalidation drops its routing entries the
        # same lazy way (PR 20)
        wl = getattr(self.storage, "_workload", None)
        if wl is not None:
            wl.invalidate_table(table_id)

    def evict_all(self) -> float:
        """Server soft-memory-limit action (utils/memory ServerMemTracker):
        drop every cached column batch AND its device mirrors — the tile
        cache and the per-device DeviceBatch uploads hanging off it (the
        residency index placement routes by) are the store's biggest
        reclaimable pools. Batches still referenced by in-flight tasks
        keep working; only the cache lets go. Returns the bytes whose
        OWNERSHIP the cache dropped — host lane bytes plus each mirror's
        real (compressed) wire footprint, not a padded-tile estimate.
        Batches still referenced by in-flight tasks free only when those
        tasks finish, so the figure is what was released for collection,
        not an instantaneous RSS delta."""
        freed = 0.0
        with self._lock:
            for b in self._cache.values():
                freed += batch_nbytes(b)
                mirrors = getattr(b, "_mirrors", None)
                if mirrors is not None:
                    freed += sum(
                        float(getattr(m, "wire_nbytes", 0))
                        for m in mirrors.values()
                    )
                    b._mirrors = None
                if getattr(b, "_enc_cache", None) is not None:
                    b._enc_cache = None  # host-side encode cache goes too
            self._cache.clear()
        return freed

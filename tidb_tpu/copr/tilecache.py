"""Columnar tile cache — the TiFlash-replica analog (SURVEY §2.12 TiFlash
row: "columnar replica + MPP engine"; here the columnar replica is a
lazily-built, version-tagged cache of decoded column batches per
(table, region), reused across queries so the scan hot path never touches
row decode).

Invalidation: `Storage.bump_version` increments a per-table counter on
every committed write; a batch built at an older version is rebuilt on
next access. Uncommitted reads (txn membuffer) bypass the cache: the cop client
builds the task batch from the txn's merged view (client.py send).
"""

from __future__ import annotations

from dataclasses import dataclass
from threading import RLock

import numpy as np

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..codec import tablecodec
from ..codec.row import decode_row
from ..catalog.schema import TableInfo
from ..mysqltypes.datum import Datum


@dataclass
class ColumnBatch:
    """All rows of one (table, region) decoded into dense numpy columns."""

    table: TableInfo
    handles: np.ndarray  # int64 row handles
    data: list[np.ndarray]  # per table column (offset order)
    valid: list[np.ndarray]
    version: tuple | int
    start: bytes = b""
    end: bytes = b""
    min_valid_ts: int = 0  # last table-commit ts at build time

    @property
    def n_rows(self) -> int:
        return len(self.handles)

    def to_chunk(self, col_offsets: list[int]) -> Chunk:
        cols = []
        for off in col_offsets:
            ft = self.table.columns[off].ft
            cols.append(Column(ft, self.data[off], self.valid[off]))
        return Chunk(cols)


def batch_nbytes(batch: ColumnBatch) -> float:
    """Approximate host bytes of a batch — the RU read-byte term and the
    arbiter's footprint proxy. numpy lanes answer exactly; object lanes
    count their pointer array (a cheap, stable underestimate — the RU
    model needs monotonic, not forensic). Cached: sibling tasks and
    retries re-ask for the same immutable batch."""
    cached = getattr(batch, "_nbytes", None)
    if cached is None:
        n = float(getattr(batch.handles, "nbytes", 0))
        for a in batch.data:
            n += getattr(a, "nbytes", 0)
        for v in batch.valid:
            n += getattr(v, "nbytes", 0)
        batch._nbytes = cached = n
    return cached


def _decode_handles(keybuf: np.ndarray, n: int) -> np.ndarray:
    """(n, 19) record-key byte matrix → int64 handles (vectorized BE+sign)."""
    enc = np.ascontiguousarray(keybuf[:, 11:19]).view(">u8").reshape(n)
    return (enc.astype(np.uint64) ^ np.uint64(1 << 63)).view(np.int64)


def _decode_values_into(table, cols, big: np.ndarray, offs: np.ndarray, lens: np.ndarray, rows_idx: np.ndarray, handles: np.ndarray) -> None:
    """Decode row values (at byte offsets `offs`, byte lengths `lens`, in
    buffer `big`) into chunk columns at target positions `rows_idx`; v2
    rows vectorized, v1 rows per-row."""
    from ..codec import rowfast

    n = len(offs)
    if n == 0:
        return
    first = big[offs]
    v2 = first == rowfast.V2_FLAG
    v2_pos = np.nonzero(v2)[0]
    if len(v2_pos):
        # batch-decode header-identical rows; fall back on the rest
        bad = rowfast.decode_v2_batch(big, offs[v2_pos], table, cols, rows_idx[v2_pos])
        for b in bad:  # rare: schema drifted mid-table
            p = v2_pos[int(b)]
            end = int(offs[p]) + int(lens[p])
            _decode_one(table, cols, int(rows_idx[p]), big[offs[p] : end].tobytes(), int(handles[p]))
    for p in np.nonzero(~v2)[0]:
        end = int(offs[p]) + int(lens[p])
        _decode_one(table, cols, int(rows_idx[p]), big[offs[p] : end].tobytes(), int(handles[p]))


def decode_rows_to_batch(table: TableInfo, kvs: list[tuple[bytes, bytes]], version: int) -> ColumnBatch:
    """Row-format KV pairs → dense columnar batch (the once-per-version
    decode; ref: rowcodec ChunkDecoder decoding straight into chunks).

    v2 rows (bulk-loaded, identical headers) decode with vectorized numpy
    gathers; v1 rows (DML path) fall back to per-row decode. A mixed batch
    routes each row down the right path by its version flag.
    """
    n = len(kvs)
    chk = Chunk.empty([c.ft for c in table.columns], n)
    cols = chk.columns

    # handles: record keys are fixed 19 bytes → one vectorized BE decode
    keybuf = np.frombuffer(b"".join(k for k, _ in kvs), dtype=np.uint8)
    if n and len(keybuf) == 19 * n:
        handles = _decode_handles(keybuf.reshape(n, 19), n)
    else:  # ragged keys (shouldn't happen for record scans) — per-row
        handles = np.fromiter((tablecodec.decode_record_handle(k) for k, _ in kvs), np.int64, n)

    vals = [v for _, v in kvs]
    lens = np.fromiter((len(v) for v in vals), np.int64, n)
    big = np.frombuffer(b"".join(vals), dtype=np.uint8)
    offs = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    _decode_values_into(table, cols, big, offs, lens, np.arange(n, dtype=np.int64), handles)

    # hidden rowid column mirrors handles
    for c in table.columns:
        if c.hidden and c.name == "_tidb_rowid":
            cols[c.offset].data[:] = handles
            cols[c.offset].valid[:] = True
    return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], version)


def build_batch_from_segments(table: TableInfo, segs, loose, version) -> ColumnBatch:
    """Segment scan results → columnar batch, gathering key/value bytes
    straight out of run buffers (zero per-row materialization for the
    bulk-loaded fast path)."""
    keeps = [s.keep_idx() for s in segs]
    n = sum(len(k) for k in keeps) + len(loose)
    chk = Chunk.empty([c.ft for c in table.columns], n)
    cols = chk.columns
    handles = np.zeros(n, dtype=np.int64)
    row0 = 0
    for s, keep in zip(segs, keeps):
        m = len(keep)
        if m == 0:
            continue
        run = s.run
        key_mat = run.key_mat[keep]
        if key_mat.shape[1] == 19:
            seg_handles = _decode_handles(key_mat, m)
        else:
            seg_handles = np.fromiter(
                (tablecodec.decode_record_handle(run.key_at(int(i))) for i in keep), np.int64, m
            )
        handles[row0 : row0 + m] = seg_handles
        big = run.value_buffer()
        rows_idx = np.arange(row0, row0 + m, dtype=np.int64)
        _decode_values_into(table, cols, big, run.starts[keep], run.lens[keep], rows_idx, seg_handles)
        row0 += m
    for k, v in loose:
        h = tablecodec.decode_record_handle(k)
        handles[row0] = h
        _decode_one(table, cols, row0, v, h)
        row0 += 1
    for c in table.columns:
        if c.hidden and c.name == "_tidb_rowid":
            cols[c.offset].data[:] = handles
            cols[c.offset].valid[:] = True
    return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], version)


def _decode_one(table: TableInfo, cols, i: int, val: bytes, handle: int) -> None:
    from ..table.table import datum_from_default

    by_id = decode_row(val)
    for off, c in enumerate(table.columns):
        d = by_id.get(c.id)
        if d is None:
            if c.hidden and c.name == "_tidb_rowid":
                d = Datum.i(handle)
            else:
                d = datum_from_default(c)
        cols[off].set_datum(i, d)


class TileCache:
    def __init__(self, storage):
        self.storage = storage
        self._cache: dict[tuple[int, bytes], ColumnBatch] = {}
        self._lock = RLock()  # cop worker pool shares this cache
        self.hits = 0
        self.misses = 0

    def get_batch(self, table: TableInfo, start: bytes, end: bytes, read_ts: int) -> ColumnBatch:
        """Snapshot-correct cache: a batch built when the table's last
        commit was at `last_commit_ts` is valid for any read_ts ≥ that
        commit while the version counter is unchanged. Reads BELOW the
        last commit (historic snapshots) always rebuild, uncached."""
        ver, last_commit_ts = self.storage.data_version(tablecodec.table_prefix(table.id))
        key = (table.id, start)
        with self._lock:
            cached = self._cache.get(key)
            if (
                cached is not None
                and cached.version == ver
                and cached.end == end
                and read_ts >= cached.min_valid_ts
            ):
                self.hits += 1
                return cached
            self.misses += 1
        snap = self.storage.snapshot(read_ts)
        segs, loose = snap.scan_segments(start, end)
        batch = build_batch_from_segments(table, segs, loose, ver)
        batch.start, batch.end = start, end
        batch.min_valid_ts = last_commit_ts
        if read_ts >= last_commit_ts:
            with self._lock:
                self._cache[key] = batch
        return batch

    def invalidate_table(self, table_id: int) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == table_id]:
                del self._cache[key]

    def evict_all(self) -> None:
        """Server soft-memory-limit action (utils/memory ServerMemTracker):
        drop every cached column batch AND its device mirrors — the tile
        cache and the per-device DeviceBatch uploads hanging off it (the
        residency index placement routes by) are the store's biggest
        reclaimable pools. Batches still referenced by in-flight tasks
        keep working; only the cache lets go."""
        with self._lock:
            for b in self._cache.values():
                if getattr(b, "_mirrors", None) is not None:
                    b._mirrors = None
            self._cache.clear()

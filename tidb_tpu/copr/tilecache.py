"""Columnar tile cache — the TiFlash-replica analog (SURVEY §2.12 TiFlash
row: "columnar replica + MPP engine"; here the columnar replica is a
lazily-built, version-tagged cache of decoded column batches per
(table, region), reused across queries so the scan hot path never touches
row decode).

Invalidation: `Storage.bump_version` increments a per-table counter on
every committed write; a batch built at an older version is rebuilt on
next access. Uncommitted reads (txn membuffer) bypass the cache: the cop client
builds the task batch from the txn's merged view (client.py send).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..codec import tablecodec
from ..codec.row import decode_row
from ..catalog.schema import TableInfo
from ..mysqltypes.datum import Datum


@dataclass
class ColumnBatch:
    """All rows of one (table, region) decoded into dense numpy columns."""

    table: TableInfo
    handles: np.ndarray  # int64 row handles
    data: list[np.ndarray]  # per table column (offset order)
    valid: list[np.ndarray]
    version: tuple | int
    start: bytes = b""
    end: bytes = b""
    min_valid_ts: int = 0  # last table-commit ts at build time

    @property
    def n_rows(self) -> int:
        return len(self.handles)

    def to_chunk(self, col_offsets: list[int]) -> Chunk:
        cols = []
        for off in col_offsets:
            ft = self.table.columns[off].ft
            cols.append(Column(ft, self.data[off], self.valid[off]))
        return Chunk(cols)


def decode_rows_to_batch(table: TableInfo, kvs: list[tuple[bytes, bytes]], version: int) -> ColumnBatch:
    """Row-format KV pairs → dense columnar batch (the once-per-version
    decode; ref: rowcodec ChunkDecoder decoding straight into chunks)."""
    n = len(kvs)
    handles = np.zeros(n, dtype=np.int64)
    chk = Chunk.empty([c.ft for c in table.columns], n)
    cols = chk.columns
    defaults = [c.default for c in table.columns]
    from ..table.table import datum_from_default

    for i, (k, v) in enumerate(kvs):
        handles[i] = tablecodec.decode_record_handle(k)
        by_id = decode_row(v)
        for off, c in enumerate(table.columns):
            d = by_id.get(c.id)
            if d is None:
                if c.hidden and c.name == "_tidb_rowid":
                    d = Datum.i(handles[i])
                else:
                    d = datum_from_default(c)
            cols[off].set_datum(i, d)
    return ColumnBatch(table, handles, [c.data for c in cols], [c.valid for c in cols], version)


class TileCache:
    def __init__(self, storage):
        self.storage = storage
        self._cache: dict[tuple[int, bytes], ColumnBatch] = {}
        self.hits = 0
        self.misses = 0

    def get_batch(self, table: TableInfo, start: bytes, end: bytes, read_ts: int) -> ColumnBatch:
        """Snapshot-correct cache: a batch built when the table's last
        commit was at `last_commit_ts` is valid for any read_ts ≥ that
        commit while the version counter is unchanged. Reads BELOW the
        last commit (historic snapshots) always rebuild, uncached."""
        ver, last_commit_ts = self.storage.data_version(tablecodec.table_prefix(table.id))
        key = (table.id, start)
        cached = self._cache.get(key)
        if (
            cached is not None
            and cached.version == ver
            and cached.end == end
            and read_ts >= cached.min_valid_ts
        ):
            self.hits += 1
            return cached
        self.misses += 1
        snap = self.storage.snapshot(read_ts)
        kvs = snap.scan(start, end)
        batch = decode_rows_to_batch(table, kvs, ver)
        batch.start, batch.end = start, end
        batch.min_valid_ts = last_commit_ts
        if read_ts >= last_commit_ts:
            self._cache[key] = batch
        return batch

    def invalidate_table(self, table_id: int) -> None:
        for key in [k for k in self._cache if k[0] == table_id]:
            del self._cache[key]

"""Host (numpy-vectorized) coprocessor engine — the correctness oracle and
CPU fallback (ref behavior: unistore cophandler/closure_exec.go's fused
scan→sel→agg/topN/limit single pass, here over cached columnar batches).

Also serves as the bench baseline the TPU engine is compared against.
"""

from __future__ import annotations

import numpy as np

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..expr.aggregation import AggDesc, MODE_PARTIAL
from ..expr.expression import Expression
from ..mysqltypes.field_type import FieldType
from ..mysqltypes.mydecimal import pow10
from .dag import DAGRequest
from .tilecache import ColumnBatch


_2_64 = 18446744073709551616
_2_32 = 4294967296


def _exact_sum64_ints(wrap: np.ndarray, est: np.ndarray) -> list:
    """Exact Python-int sums of int64 terms, from the order-independent
    int64 wrap-sum (exact mod 2^64) plus any float64 estimate with
    |error| < 2^63. Estimate error is ~n·(running sum)·2^-53, so the
    precondition holds for any per-task segment under ~10^7 rows."""
    out = []
    for i in range(len(wrap)):
        w = int(wrap[i])
        k = round((float(est[i]) - float(w)) / _2_64)
        out.append(w + k * _2_64)
    return out


def exact_sum64(wrap: np.ndarray, est: np.ndarray) -> np.ndarray:
    """float64 of _exact_sum64_ints, with a vectorized fast path for the
    common case (no wrap, |sum| < 2^53). Makes decimal variance partials
    identical across cop engines regardless of summation order."""
    wf = wrap.astype(np.float64)
    if len(wrap) and not np.rint((est - wf) / _2_64).any() and np.all(np.abs(wf) < 2**53):
        return wf
    return np.array([float(v) for v in _exact_sum64_ints(wrap, est)], dtype=np.float64)


def exact_sumsq64(wA, eA, wB, eB, wC, eC) -> np.ndarray:
    """Exact Σx² from 32-bit limb sums: with x = a·2^32 + b (arithmetic
    shift; b in [0,2^32)), Σx² = ΣA·2^64 + 2·ΣB·2^32 + ΣC for A=a², B=a·b,
    C=b². Each limb product fits the wrap+estimate reconstruction envelope
    (per-term float error ≤ 2^10), so the result is exact — and therefore
    engine-order-independent — far beyond where float64(x²) loses 2^63."""
    A = _exact_sum64_ints(wA, eA)
    B = _exact_sum64_ints(wB, eB)
    C = _exact_sum64_ints(wC, eC)
    return np.array(
        [float(a * _2_64 + 2 * b * _2_32 + c) for a, b, c in zip(A, B, C)],
        dtype=np.float64,
    )


def _eval_mask(conds: list[Expression], chunk: Chunk) -> np.ndarray:
    mask = np.ones(chunk.num_rows, dtype=bool)
    for c in conds:
        d, v = c.eval(chunk)
        mask &= v & (d != 0)
    return mask


def _lane_codes(d: np.ndarray, v: np.ndarray) -> np.ndarray:
    """One key lane → small-range non-negative int64 codes (NULL = extra
    code 0; valid codes start at 1)."""
    if d.dtype == object:
        filled = np.where(v, d, "")
        try:
            s = filled.astype("S")  # ascii fast path
        except UnicodeEncodeError:
            s = filled.astype("U")  # non-ascii: factorize unicode directly
        w = s.dtype.itemsize
        if s.dtype.kind == "S" and 0 < w <= 8:
            # ≤8-byte strings: big-endian byte code preserves ordering and
            # identity — factorize with ONE 1-D sort instead of string sorts
            mat = np.zeros((len(s), 8), dtype=np.uint8)
            mat[:, :w] = s.view(np.uint8).reshape(len(s), w)
            raw = mat.view(">u8").reshape(len(s))
        else:
            raw = s
        _, inv = np.unique(raw, return_inverse=True)
        codes = inv.astype(np.int64) + 1
    elif d.dtype == np.float64:
        _, inv = np.unique(np.where(v, d, 0.0), return_inverse=True)
        codes = inv.astype(np.int64) + 1
    else:
        x = np.where(v, d.astype(np.int64), 0)
        lo = int(x.min()) if len(x) else 0
        hi = int(x.max()) if len(x) else 0
        if hi - lo >= (1 << 62):  # huge span: factorize instead of shifting
            _, inv = np.unique(x, return_inverse=True)
            codes = inv.astype(np.int64) + 1
        else:
            codes = (x - lo) + 1
    return np.where(v, codes, 0)


def _group_codes_masked(keys: list[tuple[np.ndarray, np.ndarray]], mask: np.ndarray):
    """Selected rows → dense group ids.

    → (inv: group id per selected row, first_row: absolute row index of
    each group's first occurrence, G). Lanes factorize to small ranges,
    pack into one int64 (single final sort); falls back to a stacked
    column unique if the range product overflows.
    """
    sel_idx = np.nonzero(mask)[0]
    if len(sel_idx) == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64), 0
    lanes = [_lane_codes(d[sel_idx], v[sel_idx]) for d, v in keys]
    packed = None
    total = 1
    for lane in lanes:
        rng = int(lane.max()) + 1
        if total > (1 << 62) // max(rng, 1):
            packed = None
            break
        packed = lane if packed is None else packed * rng + lane
        total *= rng
    if packed is None:  # overflow — stacked lexicographic unique
        stacked = np.stack(lanes, axis=0)
        _, first_sel, inv = np.unique(stacked, axis=1, return_index=True, return_inverse=True)
    else:
        _, first_sel, inv = np.unique(packed, return_index=True, return_inverse=True)
    return inv.astype(np.int64), sel_idx[first_sel], len(first_sel)


def execute_dag_host(dag: DAGRequest, batch: ColumnBatch) -> Chunk:
    chunk = batch.to_chunk(dag.scan.col_offsets)
    mask = None
    if dag.selection is not None:
        mask = _eval_mask(dag.selection.conds, chunk)
        if dag.agg is None:
            chunk = chunk.filter(mask)
            mask = None

    if dag.agg is not None:
        return _exec_agg(dag, chunk, mask)

    if dag.topn is not None:
        from ..expr.expression import collation_key_lane

        keys = []
        for e, desc in dag.topn.by:
            d, v = e.eval(chunk)
            keys.append((collation_key_lane(d, e.ret_type), v, desc))
        order = _lex_argsort(keys, chunk.num_rows)
        order = order[: dag.topn.n]
        chunk = chunk.take(order)
    if dag.limit is not None:
        chunk = chunk.slice(0, min(dag.limit.n, chunk.num_rows))
    return chunk


def _lex_argsort(keys, n: int) -> np.ndarray:
    """Stable lexicographic argsort; NULLs first asc / last desc (MySQL).

    DESC keys sort by NEGATED rank under a stable sort — reversing an
    ascending stable sort would also reverse the tie order established by
    later (less significant) keys."""
    order = np.arange(n)
    for d, v, desc in reversed(keys):
        if d.dtype == object:
            strs = np.where(v, d, "").astype("U")
            x = np.unique(strs, return_inverse=True)[1].astype(np.int64)
        else:
            x = d
        # DESC int lanes flip via ~x (monotone decreasing, exact for the
        # full int64 range — a float64 negate would lose >2^53 keys)
        if desc:
            x = -x if x.dtype == np.float64 else ~x
        idx = np.argsort(x[order], kind="stable")
        order = order[idx]
        # NULLs first asc / last desc (boolean selection is stable)
        nulls = ~v[order]
        if desc:
            order = np.concatenate([order[~nulls], order[nulls]])
        else:
            order = np.concatenate([order[nulls], order[~nulls]])
    return order


def _exec_agg(dag: DAGRequest, chunk: Chunk, mask: np.ndarray | None) -> Chunk:
    n = chunk.num_rows
    if mask is None:
        mask = np.ones(n, dtype=bool)
    out_fts = dag.output_types()
    gb = dag.agg.group_by
    if gb:
        from ..expr.expression import collation_key_lane

        keyvals = []
        for e in gb:
            d, v = e.eval(chunk)
            keyvals.append((collation_key_lane(d, e.ret_type), v))
        inv, first_row, G = _group_codes_masked(keyvals, mask)
    else:
        G = 1
        inv = np.zeros(int(mask.sum()), dtype=np.int64)
        first_row = np.zeros(1, dtype=np.int64)

    cols: list[Column] = []
    oi = 0
    for e in gb:
        d, v = e.eval(chunk)
        cols.append(Column(out_fts[oi], d[first_row], v[first_row]))
        oi += 1
    for a in dag.agg.aggs:
        for col in _agg_partial_columns(a, chunk, mask, inv, G, out_fts, oi):
            cols.append(col)
            oi += 1
    return Chunk(cols)


def _agg_partial_columns(a: AggDesc, chunk: Chunk, mask: np.ndarray, inv: np.ndarray, G: int, out_fts, oi: int):
    """Partial-state columns for one aggregate over grouped rows."""
    name = a.name
    sel = np.nonzero(mask)[0]
    if a.args:
        d, v = a.args[0].eval(chunk)
        dv, vv = d[sel], v[sel]
    else:
        dv = np.ones(len(sel), dtype=np.int64)
        vv = np.ones(len(sel), dtype=bool)

    def seg_sum(vals):
        return np.bincount(inv, weights=vals, minlength=G)

    if name == "count":
        cnt = seg_sum(vv.astype(np.float64)).astype(np.int64)
        yield Column(out_fts[oi], cnt, np.ones(G, dtype=bool))
        return
    if name in ("sum", "avg"):
        ft = out_fts[oi]
        if ft.is_float():
            vals = np.where(vv, dv.astype(np.float64), 0.0)
            s = seg_sum(vals)
        else:
            # exact: integer bincount may lose precision in float64 weights
            # beyond 2^53 — use object-accumulate only when needed
            vals = np.where(vv, dv.astype(np.int64), 0)
            s = np.zeros(G, dtype=np.int64)
            np.add.at(s, inv, vals)
        cnt = seg_sum(vv.astype(np.float64)).astype(np.int64)
        has = cnt > 0
        yield Column(ft, s if not ft.is_float() else s, has)
        if name == "avg":
            yield Column(out_fts[oi + 1], cnt, np.ones(G, dtype=bool))
        return
    if name in ("min", "max"):
        ft = out_fts[oi]
        out_valid = np.zeros(G, dtype=bool)
        if dv.dtype == object:
            from ..expr.expression import collation_key_lane

            kv = collation_key_lane(dv, a.args[0].ret_type if a.args else None)
            out = np.empty(G, dtype=object)
            outk = np.empty(G, dtype=object)
            for i, g in enumerate(inv):
                if not vv[i]:
                    continue
                # ci collation orders by WEIGHT; equal-weight ties keep
                # the FIRST-encountered value, the same representative the
                # device dict-code path decodes to
                w = kv[i]
                if not out_valid[g]:
                    better = True
                elif w == outk[g]:
                    better = False
                else:
                    better = (w < outk[g]) if name == "min" else (w > outk[g])
                if better:
                    out[g] = dv[i]
                    outk[g] = w
                    out_valid[g] = True
        else:
            if dv.dtype == np.float64:
                init = np.inf if name == "min" else -np.inf
            else:  # the lane's own int dtype (uint64 must not wrap)
                init = np.iinfo(dv.dtype).max if name == "min" else np.iinfo(dv.dtype).min
            out = np.full(G, init, dtype=dv.dtype)
            fn = np.minimum if name == "min" else np.maximum
            fn.at(out, inv, np.where(vv, dv, init))
            np.bitwise_or.at(out_valid, inv, vv)
        yield Column(ft, out, out_valid)
        return
    if name == "group_concat":
        from ..chunk.chunk import Column as _C

        argc = _C(a.args[0].ret_type, dv, vv)
        parts: list[list[str]] = [[] for _ in range(G)]
        for i, g in enumerate(inv):
            if vv[i]:
                parts[g].append(argc.get_datum(i).render(a.args[0].ret_type))
        out = np.empty(G, dtype=object)
        out_valid = np.zeros(G, dtype=bool)
        for g in range(G):
            if parts[g]:
                out[g] = a.sep.join(parts[g])[: a.max_len]
                out_valid[g] = True
        yield Column(out_fts[oi], out, out_valid)
        return
    if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
        from ..expr.expression import lane_as_float

        cnt = seg_sum(vv.astype(np.float64)).astype(np.int64)
        arg_ft = a.args[0].ret_type
        if arg_ft.is_decimal():
            # exact sums of the SCALED ints, reconstructed from order-
            # independent int64 wrap-sums + float estimates (sumsq via
            # 32-bit limbs) — both cop engines land on the identical exact
            # integer whatever their summation order
            # (tpu_engine._agg_partials_device is the device twin)
            xi = np.where(vv, dv.astype(np.int64), 0)
            ai = xi >> 32
            bi = xi - (ai << 32)
            af, bf = ai.astype(np.float64), bi.astype(np.float64)

            def wrap_at(vals):
                w = np.zeros(G, dtype=np.int64)
                np.add.at(w, inv, vals)
                return w

            scale = float(pow10(max(arg_ft.decimal, 0)))
            s = exact_sum64(wrap_at(xi), seg_sum(xi.astype(np.float64))) / scale
            sq = exact_sumsq64(
                wrap_at(ai * ai), seg_sum(af * af),
                wrap_at(ai * bi), seg_sum(af * bf),
                wrap_at(bi * bi), seg_sum(bf * bf),
            ) / (scale * scale)
        else:
            x = np.where(vv, lane_as_float(np, dv, arg_ft), 0.0)
            s = seg_sum(x)
            sq = seg_sum(x * x)
        ones = np.ones(G, dtype=bool)
        yield Column(out_fts[oi], cnt, ones)
        yield Column(out_fts[oi + 1], s, ones)
        yield Column(out_fts[oi + 2], sq, ones)
        return
    if name == "approx_count_distinct":
        # per-group FM sketch, shipped serialized; the root final unions
        # them (ref: aggfuncs approxCountDistinctPartial1, fmsketch.go)
        from ..statistics.cmsketch import hash_values
        from ..statistics.fmsketch import FMSketch

        hashes = hash_values(dv)
        out = np.empty(G, dtype=object)
        for g in range(G):
            sel_g = (inv == g) & vv
            sk = FMSketch()
            sk.insert_hashes(np.asarray(hashes[sel_g], dtype=np.uint64))
            out[g] = sk.serialize()
        yield Column(out_fts[oi], out, np.ones(G, dtype=bool))
        return
    if name in ("bit_and", "bit_or", "bit_xor"):
        if dv.dtype == object:
            from ..errors import TiDBError

            raise TiDBError(f"{name.upper()} over string operands is not supported")
        from ..expr.expression import lane_as_float

        # MySQL rounds non-integers to the nearest integer before bit ops
        ints = np.rint(lane_as_float(np, dv, a.args[0].ret_type)).astype(np.int64)
        init = -1 if name == "bit_and" else 0  # all-ones / zero identities
        out = np.full(G, init, dtype=np.int64)
        fn = {"bit_and": np.bitwise_and, "bit_or": np.bitwise_or, "bit_xor": np.bitwise_xor}[name]
        fn.at(out, inv, np.where(vv, ints, init if name == "bit_and" else 0))
        # MySQL: bit aggregates over no rows return the identity, not NULL
        yield Column(out_fts[oi], out, np.ones(G, dtype=bool))
        return
    if name == "first_row":
        ft = out_fts[oi]
        out_valid = np.zeros(G, dtype=bool)
        dt = col_numpy_dtype(ft)
        out = np.empty(G, dtype=object) if dt is VARLEN else np.zeros(G, dtype=dt)
        seen = np.zeros(G, dtype=bool)
        for i, g in enumerate(inv):
            if not seen[g]:
                seen[g] = True
                out[g] = dv[i]
                out_valid[g] = vv[i]
        yield Column(ft, out, out_valid)
        return
    raise NotImplementedError(f"aggregate {name} in cop")

"""TPU coprocessor engine — pushed-down DAGs as fused XLA programs.

The reference's unistore compiles a cop DAG into a fused per-KV closure
(cophandler/closure_exec.go:167 buildClosureExecutor, :557 execute); here
the same fusion is reborn as ONE jit-compiled XLA program per DAG digest:

    column tiles [T, R] ──► selection mask ──► partial aggregation
    (device-resident,        (vmapped expr      (masked reductions /
     dict-coded strings)      kernels)           segment_sum by group code)

Design rules (SURVEY §7 hard parts):
  * static shapes: batches pad to tile multiples; recompiles keyed on
    (digest, T) only
  * no compaction on device — masks all the way; host compacts at the
    boundary
  * strings never reach the device: sorted-dict codes + constant
    rewriting make eq/range predicates exact in code space
  * group-by uses direct addressing over the product of key domains
    (≤ DIRECT_GROUP_MAX segments); larger cardinalities fall back to the
    host engine (device hash-repartition lands with the MPP layer)
  * decimals are scaled int64 lanes: partial SUMs are exact; the final
    merge at root is exact big-int

The jit cache is the compile-once analog of the coprocessor cache
(store/copr/coprocessor_cache.go) — keyed on program shape, not results.
"""

from __future__ import annotations

import bisect
import time
from threading import Lock, RLock

import numpy as np

from ..jaxenv import jax, jnp
from ..utils import memory as _mem
from ..utils import metrics as M
from ..utils import timeline as TL
from ..utils import tracing
from ..chunk.chunk import Chunk, Column
from ..expr.expression import Column as ExprCol, Constant, Expression, ScalarFunc
from ..mysqltypes.datum import Datum, K_STR, K_BYTES
from ..mysqltypes.field_type import ft_longlong
from ..mysqltypes.mydecimal import pow10
from .dag import DAGRequest
from .host_engine import exact_sum64, exact_sumsq64, execute_dag_host
from .tilecache import (
    MIN_TILE_ROWS,
    ColumnBatch,
    encode_data_lane,
    encode_valid_lane,
    pow2_rows,
)

class _Timed:
    """A jitted program with its first dispatch timed: JAX traces+compiles
    synchronously inside the first call (later calls dispatch async in
    sub-ms), so the first-call wall IS the compile cost — the
    tidb_tpu_compile_seconds series and the trace's device.compile phase.
    A benign race (two threads both timing the first call) at worst
    records one extra sample."""

    __slots__ = ("fn", "_compiled")

    def __init__(self, fn):
        self.fn = fn
        self._compiled = False

    def __call__(self, *args):
        if self._compiled:
            tl = TL.active()
            if tl is None:
                return self.fn(*args)
            # warmed path: the jit call IS the async dispatch — its wall
            # is queueing cost, not compute (device_get observes that)
            t0 = time.perf_counter_ns()
            out = self.fn(*args)
            tl.device_event("device.dispatch", "dispatch", t0, time.perf_counter_ns())
            return out
        t0 = time.perf_counter_ns()
        out = self.fn(*args)
        t1 = time.perf_counter_ns()
        dt = (t1 - t0) / 1e9
        self._compiled = True
        M.TPU_COMPILE_SECONDS.observe(dt)
        tracing.add_phase("compile_ms", dt * 1e3)
        tracing.add_phase_event("device.compile", t0, t1)
        tl = TL.active()
        if tl is not None:
            tl.device_event("device.compile", "compile", t0, t1)
        return out


def _to_device(a: np.ndarray, device=None):
    """Host→device upload with transfer accounting (the h2d half of
    tidb_tpu_transfer_bytes_total and the trace's device.transfer phase).
    With `device` the array is COMMITTED to that mesh device — jit
    follows committed inputs, so pinning the uploads is what pins the
    whole launch to its runner lane (PR 6 per-device dispatch).
    The bytes also consume into the bound statement MemTracker — device
    allocations were invisible to memory quotas before PR 4 — so the
    consume can raise the quota/server-limit error right at the
    allocation site (a real allocation failure, never a device fault)."""
    _mem.consume_current(a.nbytes)
    t0 = time.perf_counter_ns()
    out = jnp.asarray(a) if device is None else jax.device_put(a, device)
    t1 = time.perf_counter_ns()
    M.TPU_TRANSFER_BYTES.inc(a.nbytes, dir="h2d")
    tracing.add_phase("h2d_bytes", a.nbytes)
    tracing.add_phase("h2d_ms", (t1 - t0) / 1e6)
    tracing.add_phase_event("device.transfer", t0, t1, dir="h2d", bytes=int(a.nbytes))
    tl = TL.active()
    if tl is not None:
        tl.device_event("device.h2d", "transfer", t0, t1, bytes=int(a.nbytes))
    return out


def _fetch(x):
    """Device→host fetch: `jax.device_get` blocks until the async dispatch
    finishes computing, so its wall is the observable device execute+fetch
    time (tidb_tpu_device_execute_seconds); result bytes are the d2h half
    of the transfer series."""
    t0 = time.perf_counter_ns()
    out = jax.device_get(x)
    t1 = time.perf_counter_ns()
    dt = (t1 - t0) / 1e9
    nbytes = sum(getattr(l, "nbytes", 0) for l in jax.tree_util.tree_leaves(out))
    M.TPU_EXECUTE_SECONDS.observe(dt, resource_group=TL.current_group())
    M.TPU_TRANSFER_BYTES.inc(nbytes, dir="d2h")
    tracing.add_phase("execute_ms", dt * 1e3)
    tracing.add_phase("d2h_bytes", nbytes)
    tracing.add_phase_event("device.execute", t0, t1, d2h_bytes=int(nbytes))
    tl = TL.active()
    if tl is not None:
        tl.device_event("device.execute", "execute", t0, t1, d2h_bytes=int(nbytes))
    # NOT consumed into the memory tracker: the fetched result becomes a
    # chunk that drain() charges at materialization — charging the d2h
    # here too would double-count the same data on the device path only
    return out


def _tree_to_device(tree, device=None):
    """Upload every leaf of a codec payload pytree (dict of numpy arrays)
    through `_to_device`, so transfer accounting/quota charges cover the
    compressed form — the only form that crosses the wire."""
    return jax.tree_util.tree_map(lambda a: _to_device(a, device), tree)


def _mark_device(chunk):
    """Stamp a chunk as device-produced (Chunk._device): the cop client
    charges its RU read-byte term at the mirror's compressed wire bytes.
    Chunks from the engine's internal host fallback stay unstamped and
    charge the host lanes the fallback actually scanned."""
    try:
        chunk._device = True
    except AttributeError:  # exotic chunk-like result without the slot
        pass
    return chunk


TILE_ROWS = 1 << 16
DIRECT_GROUP_MAX = 1 << 16
# group domains up to this size reduce via dense masked reductions
# (VPU-friendly compare+reduce, fuses across agg lanes) instead of
# segment_sum: TPU scatter-adds serialize and cost ~100ms per lane at 2M
# rows while the dense form is bandwidth-bound (~µs at Q1 scale)
SEG_DENSE_MAX = 64


def _seg_ids(seg, nseg):
    return jnp.arange(nseg, dtype=seg.dtype)[:, None] == seg[None, :]


def _seg_sum(vals, seg, nseg):
    """Sum `vals` per segment; rows with seg >= nseg are dropped (the
    masked-row overflow slot)."""
    if nseg <= SEG_DENSE_MAX:
        zero = jnp.zeros((), dtype=vals.dtype)
        return jnp.sum(jnp.where(_seg_ids(seg, nseg), vals[None, :], zero), axis=1)
    return jax.ops.segment_sum(vals, seg, num_segments=nseg + 1)[:nseg]


def _seg_min(vals, seg, nseg, fill):
    if nseg <= SEG_DENSE_MAX:
        return jnp.min(jnp.where(_seg_ids(seg, nseg), vals[None, :], fill), axis=1)
    return jax.ops.segment_min(vals, seg, num_segments=nseg + 1)[:nseg]


def _seg_max(vals, seg, nseg, fill):
    if nseg <= SEG_DENSE_MAX:
        return jnp.max(jnp.where(_seg_ids(seg, nseg), vals[None, :], fill), axis=1)
    return jax.ops.segment_max(vals, seg, num_segments=nseg + 1)[:nseg]

def lex_sort_perm(ops, iota_dtype=jnp.int32):
    """Lexicographic sort permutation over significance-ordered key
    operands (most significant FIRST); ties break by row id.

    Emulates one multi-key `lax.sort` with successive single-key STABLE
    sorts (np.lexsort's recipe): the TPU backend's x64 comparator rewrite
    makes >=3-key sorts with int64 operands explode — measured on axon:
    76s compile at 3 keys, compiler SIGSEGV at 4 — while single-key
    sorts compile in well under a second each."""
    P = ops[0].shape[0]
    perm = jnp.arange(P, dtype=iota_dtype)
    for k in reversed(ops):
        _, perm = jax.lax.sort((k[perm], perm), num_keys=1)
    return perm


_CMP_SWAP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


class Vocab(list):
    """Sorted dict-encode vocabulary: ORIGINAL values in code order, plus
    the lookup keys codes were assigned by (weight strings under a ci
    collation, the values themselves under binary)."""

    def __init__(self, originals, keys=None, coll="utf8mb4_bin"):
        super().__init__(originals)
        self.keys = list(self) if keys is None else keys
        self.coll = coll

    def lookup(self, s: str):
        """(insertion position, exact-present) for a constant under this
        vocab's collation — the bisect behind code-space compare/IN."""
        from ..mysqltypes import collate as _c

        k = _c.weight(s, self.coll) if _c.is_ci(self.coll) else s
        i = bisect.bisect_left(self.keys, k)
        return i, i < len(self.keys) and self.keys[i] == k


def _dict_encode_lane(d: np.ndarray, v: np.ndarray, coll: str = "utf8mb4_bin"):
    """Vectorized sorted-dict encoding of an object lane → (int32 codes,
    Vocab). Handles str lanes (numpy 'U' fast path) and bytes lanes
    (latin-1 view: byte order == code-point order, so code order stays
    binary-collation order); mixed lanes take the generic python path.
    Under a ci collation codes follow WEIGHT order — equal-weight values
    share one code whose vocab entry is the binary-min original (the same
    representative the host paths resolve ties to)."""
    from ..mysqltypes import collate as _coll

    if not v.any():
        return np.zeros(len(d), np.int32), Vocab([], coll=coll)
    present = d[v]
    kinds = {type(x) for x in present.tolist()}
    if _coll.is_ci(coll) and kinds <= {str}:
        raw = np.where(v, d, "")
        wa = _coll.weight_lane(raw, coll).astype("U")
        sel = np.nonzero(v)[0]
        # representative per weight class = FIRST occurrence in row order,
        # matching the host engines' first-row group output and the
        # first-wins tie rule of min/max
        uniqw, first = np.unique(wa[sel], return_index=True)
        reps = [d[i] for i in sel[first]]
        codes = np.searchsorted(uniqw, wa).astype(np.int32)
        codes[~v] = 0
        return codes, Vocab(reps, keys=uniqw.tolist(), coll=coll)
    if kinds <= {str}:
        vals = np.where(v, d, "").astype("U")
        vocab_arr = np.unique(vals[v])
        codes = np.searchsorted(vocab_arr, vals).astype(np.int32)
        codes[~v] = 0
        return codes, Vocab(vocab_arr.tolist())
    if kinds <= {bytes}:
        as_str = np.array([x.decode("latin-1") for x in present.tolist()], dtype="U")
        vocab_arr = np.unique(as_str)
        codes = np.zeros(len(d), np.int32)
        codes[v] = np.searchsorted(vocab_arr, as_str).astype(np.int32)
        orig = [s.encode("latin-1") for s in vocab_arr.tolist()]
        return codes, Vocab(orig, keys=vocab_arr.tolist())
    # mixed str/bytes/other: generic exact path
    vocab = sorted({x if isinstance(x, str) else x.decode("latin-1") for x in present.tolist()})
    code_of = {s: i for i, s in enumerate(vocab)}
    codes = np.zeros(len(d), np.int32)
    for i in np.nonzero(v)[0]:
        x = d[i]
        codes[i] = code_of[x if isinstance(x, str) else x.decode("latin-1")]
    return codes, Vocab(vocab)


class DeviceBatch:
    """Device-resident mirror of a ColumnBatch: [T, R] lanes per column,
    committed to ONE mesh device (`device`) — the residency unit the
    placement policy routes by (a cached upload stays hot on the device
    that owns it; a spill builds a second mirror on a sibling).

    With `compress` (the `tidb_tpu_tile_compression` default) the layout
    is bucketed and codec-encoded: batches up to TILE_ROWS pad to a
    power-of-two row bucket (min MIN_TILE_ROWS) instead of a full 64Ki
    tile, larger batches keep TILE_ROWS tiles, and every lane ships in the
    cheapest of dense/pack/dict/rle form with decode fused into the
    jitted program (tilecache codec half). `compress=False` reproduces
    the legacy layout exactly: 64Ki tiles, dense lanes."""

    def __init__(self, batch: ColumnBatch, device=None, compress: bool = True):
        self.batch = batch
        self.device = device
        self.compress = compress
        n = batch.n_rows
        if compress and n <= TILE_ROWS:
            self.t, self.r = 1, pow2_rows(n)
        else:
            self.t, self.r = max((n + TILE_ROWS - 1) // TILE_ROWS, 1), TILE_ROWS
        self.padded = self.t * self.r
        M.TPU_TILE_ROWS_PADDED.inc(self.padded - n)
        self.vocabs: dict[int, list] = {}
        self._data: dict[int, object] = {}
        self._valid: dict[int, object] = {}
        # static per-lane codec descriptors — they join the compile-cache
        # key (programs trace the decode) and the launch-group fuse key
        self.lane_sigs: dict[int, tuple] = {}
        # per-lane upload identity: (upload_id, bytes) recorded by the
        # launch that actually paid the h2d — later statements hitting
        # the cached lane reference it instead of inheriting the cost
        self.upload_ids: dict[int, tuple[int, int]] = {}
        # actual transferred (= device-resident) bytes vs the dense
        # uncompressed equivalent — what MemTracker/RU/EXPLAIN now read
        self.wire_nbytes = 0
        self.logical_nbytes = 0
        rv = np.zeros(self.padded, dtype=bool)
        rv[:n] = True
        self.row_valid = _to_device(rv.reshape(self.t, self.r), device)
        self.wire_nbytes += self.padded
        self.logical_nbytes += self.padded

    def _pad2d(self, a: np.ndarray):
        from .tilecache import _pad2d

        return _pad2d(a, (self.t, self.r))

    @staticmethod
    def _wire(x) -> int:
        return sum(int(l.nbytes) for l in jax.tree_util.tree_leaves(x))

    def lanes(self, off: int):
        """(data, valid) device lanes for a table column offset — each a
        plain [T,R] array or a codec payload pytree the program decodes
        in-kernel (engine._decode_lane). Object lanes dict-encode to
        sorted-vocab int32 codes first (the codes lane then compresses
        like any int lane). The h2d upload span and bytes belong to the
        launch that performs it; a cache hit records a zero-duration
        `cache_ref` annotation carrying the original upload id —
        attribution follows the work, not first-touch."""
        if off not in self._data:
            # encode-once: the codec pass (NDV probe, np.unique, run
            # detection) is cached ON the ColumnBatch keyed by lane +
            # shape, so a second mirror (spill to a sibling lane, rebuild
            # after eviction) pays only the h2d, never a re-encode — the
            # compressed payload is small enough to keep, which the dense
            # padded form never was. Writes race benignly: the encode is
            # deterministic and dict assignment is atomic.
            ecache = getattr(self.batch, "_enc_cache", None)
            if ecache is None:
                ecache = self.batch._enc_cache = {}
            ekey = (off, self.t, self.r)
            hit = ecache.get(ekey) if self.compress else None
            if hit is not None:
                d, vocab, pay_d, sig_d, pay_v, sig_v = hit
                if vocab is not None:
                    self.vocabs[off] = vocab
                v = self.batch.valid[off]
            else:
                d = self.batch.data[off]
                v = self.batch.valid[off]
                vocab = None
                if d.dtype == object:
                    coll = getattr(self.batch.table.columns[off].ft, "collate", "utf8mb4_bin")
                    codes, vocab = _dict_encode_lane(d, v, coll)
                    self.vocabs[off] = vocab
                    d = codes
                if self.compress:
                    pay_d, sig_d = encode_data_lane(d, v, (self.t, self.r))
                    pay_v, sig_v = encode_valid_lane(v, (self.t, self.r))
                    # cache the verdict even when both sides stayed dense:
                    # the entry is a tuple of references (d IS the batch's
                    # own lane) and skipping it would re-pay the O(n)
                    # codec probes on every mirror rebuild — which cluster
                    # exactly on the memory-pressure evict/spill paths
                    ecache[ekey] = (d, vocab, pay_d, sig_d, pay_v, sig_v)
                else:
                    pay_d = pay_v = None
                    sig_d, sig_v = ("dense",), ("dense",)
            logical = self.padded * (d.dtype.itemsize + 1)  # dense data+valid
            self._data[off] = (
                _to_device(self._pad2d(d), self.device) if pay_d is None
                else _tree_to_device(pay_d, self.device)
            )
            self._valid[off] = (
                _to_device(self._pad2d(v), self.device) if pay_v is None
                else _tree_to_device(pay_v, self.device)
            )
            self.lane_sigs[off] = (sig_d, sig_v)
            wire = self._wire(self._data[off]) + self._wire(self._valid[off])
            self.wire_nbytes += wire
            self.logical_nbytes += logical
            M.TPU_TILE_COMPRESSED_BYTES.inc(
                self._wire(self._data[off]), codec=sig_d[0]
            )
            M.TPU_TILE_COMPRESSED_BYTES.inc(
                self._wire(self._valid[off]), codec=sig_v[0]
            )
            tracing.add_phase("wire_bytes", wire)
            tracing.add_phase("logical_bytes", logical)
            self.upload_ids[off] = (tracing._next_id(), wire)
        else:
            rec = self.upload_ids.get(off)
            if rec is not None:
                now = time.perf_counter_ns()
                tracing.add_phase("cache_ref_bytes", rec[1])
                tracing.add_phase_event("device.cache_ref", now, now,
                                        upload_id=rec[0], bytes=rec[1])
                tl = TL.active()
                if tl is not None:
                    tl.device_event("device.cache_ref", "transfer", now, now,
                                    upload_id=rec[0], bytes=rec[1])
        return self._data[off], self._valid[off]


class DevicePlan:
    """A lowered DAG split at the device→host boundary: `launch()`
    dispatches the compiled program and returns UN-fetched device arrays
    (XLA dispatch is async — compute proceeds in the background);
    `finalize(fetched)` turns the host copies into the result Chunk.

    The split is what makes cross-task launch batching possible: a group
    of plans can all launch first, then pay ONE `jax.device_get` for the
    whole group (sched/batcher.py) instead of one blocking fetch each.

    Plans that also carry (`key`, `args`) are FUSABLE: tasks sharing a
    program key (same rewritten DAG + tile bucket ⇒ identical shapes)
    stack their input lanes and run ONE vmapped program launch for the
    whole group (`execute_many`), the arXiv:2203.01877 §4.2 move applied
    across sessions. Each task's lanes stay a separate batch row of the
    vmap, so results are bit-identical to solo `launch`+`finalize`.
    """

    __slots__ = ("launch", "finalize", "key", "args", "rows")

    def __init__(self, launch, finalize, key=None, args=None, rows=0):
        self.launch = launch
        self.finalize = finalize
        self.key = key  # program-cache key, shared ⇒ vmap-compatible
        self.args = args  # (flat_lanes, row_valid) device inputs
        self.rows = rows  # real (unpadded) row count of the batch


class DeviceLane:
    """One cop runner lane per mesh device: the device handle, its OWN
    circuit breaker (an open breaker drains only this lane), a launch
    lock serializing device work (and keeping the lane's timeline tid
    free of partial overlap), and an in-flight occupancy counter the
    placement policy balances on. Occupancy is guarded by the engine's
    placement lock, not per-lane — choose-and-bump must be atomic across
    lanes or a concurrent burst all picks the same idle lane."""

    __slots__ = ("idx", "device", "name", "breaker", "lock", "occupancy",
                 "launches", "ewma_ms", "faults")

    def __init__(self, idx: int, device, breaker):
        self.idx = idx
        self.device = device
        plat = getattr(device, "platform", None) or "dev"
        self.name = f"{plat}:{getattr(device, 'id', idx)}"
        self.breaker = breaker
        self.lock = RLock()
        self.occupancy = 0  # placed-but-unfinished tasks (queued + running)
        self.launches = 0
        # observed per-task health (PR 20, guarded by the engine's
        # placement lock like occupancy): EWMA of the wall each placed
        # task spent on this lane, fault-penalized — the weighted
        # placement order reads these instead of treating lanes as
        # equal-cost
        self.ewma_ms = 0.0  # 0 = no observation yet
        self.faults = 0


class _lane_guard:
    """Exclusive use of one device lane for a launch: the lane's launch
    lock plus the timeline device-lane binding. Re-entrant — the batcher
    guards around `execute_many`, which guards again internally."""

    __slots__ = ("lane", "_scope")

    def __init__(self, lane: DeviceLane):
        self.lane = lane

    def __enter__(self):
        self.lane.lock.acquire()
        self._scope = TL.device_scope(self.lane.name)
        self._scope.__enter__()
        return self.lane

    def __exit__(self, *exc):
        self._scope.__exit__(*exc)
        self.lane.lock.release()
        return False


class TPUEngine:
    MAX_FUSE = 64  # largest vmapped launch group (and largest size bucket)
    # resident-lane queue depth beyond the fair mesh share before a task
    # spills off its resident device: slack matters because same-program
    # tasks piling on one lane COALESCE into one launch (free), while a
    # spill pays a fresh h2d mirror — only a genuinely deep queue of
    # other work justifies that
    SPILL_SLACK = 3

    def __init__(self):
        from .retry import CircuitBreaker

        self._programs: dict = {}  # (digest, T, domains) -> compiled fn
        self._raw: dict = {}  # program key -> raw traceable kernel
        self._vprograms: dict = {}  # (key, group_cap) -> jit(vmap(raw))
        self._gcap: dict = {}  # sorted-agg digest -> last sufficient capacity
        self.gcap0 = 1 << 16  # initial sorted-agg group capacity
        self._lock = Lock()  # cop pool workers share this engine
        self.compile_count = 0
        self.fallbacks = 0
        # bucketed/compressed device tiles (SET GLOBAL
        # tidb_tpu_tile_compression, default ON): power-of-two row buckets
        # + per-column codecs with in-program decode. OFF forces the
        # legacy dense 64Ki-tile layout — the A/B + incident-fallback path
        self.tile_compression = True
        # per-DEVICE runner lanes (PR 6): every mesh device gets its own
        # queue position, circuit breaker and timeline lane; the cop
        # client records successes/faults on the lane that ran the task,
        # and an open breaker drains only that lane (`auto` reroutes its
        # tasks to sibling devices before ever falling back to host)
        try:
            devices = list(jax.devices())
        except Exception:  # noqa: BLE001 — broken backend: one host-side lane
            devices = [None]
        # one unique prefix per engine instance: two stores in one
        # process must not clobber each other's breaker series (the
        # retry.py label invariant), so lane labels are engine-scoped
        eid = f"e{next(CircuitBreaker._seq)}"
        self._all_lanes = [
            DeviceLane(i, d, CircuitBreaker(
                label=f"{eid}/{getattr(d, 'platform', None) or 'dev'}"
                      f":{getattr(d, 'id', i)}"
            ))
            for i, d in enumerate(devices)
        ]
        self.lanes = list(self._all_lanes)
        self._place_lock = Lock()  # atomic choose-and-bump across lanes
        # device-aware residency index, keyed by batch CONTENT (table,
        # span, version) rather than object identity: CopClients are
        # per-session, so the same region's batch is a different object
        # in every session — content routing is what lands cross-session
        # same-snapshot tasks on one lane where they can coalesce. A
        # stale entry (mirror evicted) merely routes to a lane that
        # re-uploads; correctness never depends on this index.
        self._residency: dict[tuple, set] = {}

    @staticmethod
    def _residency_key(batch) -> tuple:
        t = getattr(batch, "table", None)
        return (
            getattr(t, "id", None),
            getattr(batch, "start", b""),
            getattr(batch, "end", b""),
            getattr(batch, "version", None),
            batch.n_rows,
        )

    # --- per-device placement ----------------------------------------------

    @property
    def breaker(self):
        """Lane 0's breaker — the single-device view. Chaos/bench code
        that wants the old one-breaker-per-engine economics pins the mesh
        first with `limit_lanes(1)`; multi-lane callers use `lanes`."""
        return self.lanes[0].breaker

    def set_active_lanes(self, n: int) -> None:
        """Dispatch width (`SET GLOBAL tidb_tpu_cop_lanes`): route cop
        tasks over only the first `n` mesh devices; 0 = every device.
        The serving knob for hosts whose backend SERIALIZES executions
        across in-process devices (the CPU test box — see the mesh
        bench's `overlap_x` probe): there, fanning a burst out pays
        per-launch overhead with no parallel silicon behind it, and
        width 1 recovers full cross-session coalescing. Real multi-chip
        meshes want the full width."""
        n = int(n)
        if n <= 0 or n > len(self._all_lanes):
            n = len(self._all_lanes)
        self.lanes = self._all_lanes[:n]

    def limit_lanes(self, n: int) -> None:
        """Test/bench hook: SHRINK the dispatch width to at most `n`
        lanes (n=1 reproduces the pre-mesh single-lane engine exactly).
        Unlike set_active_lanes, never widens."""
        self.set_active_lanes(min(max(1, n), len(self.lanes)))

    def place(self, batch: ColumnBatch, sched=None, gate_breakers: bool = False,
              stats=None, weighted: bool = False) -> DeviceLane | None:
        """Choose the runner lane for one cop task and bump its occupancy
        (caller MUST `release_lane` when the task leaves the lane).

        Policy, in order:
          * residency affinity — a batch with a DeviceBatch mirror stays
            on the device that owns the upload (no fresh h2d);
          * spill — when the resident lane is oversubscribed relative to
            the admission load (`Storage.sched`'s running+queued tasks
            spread fairly over the mesh) AND an idle sibling exists, the
            task spills to the least-occupied lane and pays a second
            mirror there — latency under load beats upload thrift;
          * breaker gating (`gate_breakers`, the cop-client path) — lanes
            whose breaker rejects are skipped, so an open breaker drains
            only its own lane and `auto` traffic reroutes to siblings;
            None only when EVERY lane refuses (then: host / raise).

        `weighted` (PR 20, the feedback-routing path): lanes order by
        (occupancy+1) x their observed per-task EWMA wall instead of
        occupancy alone — a lane that has been running slow (or was
        fault-penalized by `note_lane`) yields to a healthy sibling even
        at equal queue depth. Lanes without observations cost the mesh
        median, so a cold mesh reproduces the unweighted order exactly.
        """
        lanes = self.lanes
        mirrors = getattr(batch, "_mirrors", None) or {}
        rkey = self._residency_key(batch)
        with self._place_lock:
            if weighted:
                seen = sorted(l.ewma_ms for l in lanes if l.ewma_ms > 0.0)
                base = seen[len(seen) // 2] if seen else 1.0
                cost = lambda l: (  # noqa: E731 — placement-local key
                    (l.occupancy + 1) * (l.ewma_ms if l.ewma_ms > 0.0 else base),
                    l.occupancy, l.idx,
                )
            else:
                cost = lambda l: (l.occupancy, l.idx)  # noqa: E731
            res_idx = set(mirrors) | (self._residency.get(rkey) or set())
            order: list[DeviceLane] = []
            resident = [l for l in lanes if l.idx in res_idx]
            spilled = False
            if resident:
                r = min(resident, key=cost)
                load = 0
                if sched is not None:
                    sc = getattr(sched, "scheduler", None)
                    if sc is not None:
                        load = sc.running() + sc.queue_depth()
                fair = max(1.0, load / len(lanes))
                if r.occupancy > fair + self.SPILL_SLACK and any(
                    l.occupancy == 0 for l in lanes if l is not r
                ):
                    spilled = True  # deeply oversubscribed + an idle sibling
                else:
                    order.append(r)
            chosen_first = order[0] if order else None
            order += sorted(
                (l for l in lanes if l is not chosen_first),
                key=cost,
            )
            rerouted = False
            for lane in order:
                if gate_breakers and not lane.breaker.allow():
                    rerouted = True
                    continue
                if resident and lane.idx not in res_idx:
                    reason = "breaker" if rerouted else "spill"
                    M.TPU_LANE_REROUTES.inc(device=lane.name, reason=reason)
                    if stats is not None:
                        stats("lane_reroutes" if rerouted else "lane_spills", 1)
                lane.occupancy += 1
                M.TPU_LANE_OCCUPANCY.set(lane.occupancy, device=lane.name)
                return lane
        return None

    def release_lane(self, lane: DeviceLane) -> None:
        with self._place_lock:
            lane.occupancy -= 1
            M.TPU_LANE_OCCUPANCY.set(lane.occupancy, device=lane.name)

    def note_lane(self, lane: DeviceLane, wall_ms: float, ok: bool = True) -> None:
        """Observed per-task lane health (PR 20): the cop client reports
        each placed task's wall (place → result) here. Success folds into
        the lane's EWMA; a device fault doubles the believed cost instead
        — the next weighted placement prefers a healthy sibling while the
        breaker decides whether to open."""
        with self._place_lock:
            if ok:
                if lane.ewma_ms <= 0.0:
                    lane.ewma_ms = wall_ms
                else:
                    lane.ewma_ms = 0.7 * lane.ewma_ms + 0.3 * wall_ms
            else:
                lane.faults += 1
                lane.ewma_ms = max(lane.ewma_ms, wall_ms, 0.001) * 2.0

    def breakers_describe(self) -> str:
        return ", ".join(f"{l.name}:{l.breaker.state}" for l in self.lanes)

    def raise_breakers_open(self) -> None:
        """Forced `engine='tpu'` with EVERY lane's breaker rejecting."""
        if len(self.lanes) == 1:
            self.lanes[0].breaker.raise_open()
        from ..errors import CircuitBreakerOpen

        raise CircuitBreakerOpen(
            f"every device lane's circuit breaker rejected the request "
            f"(state=open on all {len(self.lanes)} lanes: "
            f"{self.breakers_describe()}); use engine='host'/'auto' or "
            f"wait out the cooldown"
        )

    # --- public ------------------------------------------------------------

    @staticmethod
    def tile_count(batch: ColumnBatch) -> int:
        """Padded tile count at the legacy full-tile width (kept for
        callers that only need a coarse size class; the batcher groups on
        `tile_bucket`, which sees the narrowed row bucket)."""
        return max((batch.n_rows + TILE_ROWS - 1) // TILE_ROWS, 1)

    def tile_bucket(self, batch: ColumnBatch) -> tuple[int, int]:
        """(tile count, row bucket) a batch pads to under the current
        layout — the static-shape class the batcher's launch groups key
        on: only same-bucket tasks can stack into one vmapped program."""
        n = batch.n_rows
        if self.tile_compression and n <= TILE_ROWS:
            return (1, pow2_rows(n))
        return (max((n + TILE_ROWS - 1) // TILE_ROWS, 1), TILE_ROWS)

    def _plan_for(self, dag: DAGRequest, batch: ColumnBatch, lane: DeviceLane | None = None):
        if lane is None:
            lane = self.lanes[0]
        mirrors = getattr(batch, "_mirrors", None)
        if mirrors is None:
            mirrors = {}
            batch._mirrors = mirrors
        dev = mirrors.get(lane.idx)
        if dev is not None and dev.compress != self.tile_compression:
            dev = None  # layout flag flipped: rebuild under the new layout
        if dev is None:
            dev = DeviceBatch(batch, device=lane.device,
                              compress=self.tile_compression)
            mirrors[lane.idx] = dev
            with self._place_lock:
                if len(self._residency) > 4096:
                    self._residency.clear()
                self._residency.setdefault(
                    self._residency_key(batch), set()
                ).add(lane.idx)
        return self._lower(dag, dev)

    def execute(self, dag: DAGRequest, batch: ColumnBatch,
                lane: DeviceLane | None = None, _solo_event: bool = True) -> Chunk:
        placed = None
        if lane is None:
            lane = placed = self.place(batch)
        try:
            with _lane_guard(lane):
                t0 = time.perf_counter_ns()
                plan = self._plan_for(dag, batch, lane)
                if plan is None:
                    with self._lock:
                        self.fallbacks += 1
                    M.TPU_FALLBACK.inc(path="cop", reason="not_lowerable")
                    return execute_dag_host(dag, batch)
                if isinstance(plan, DevicePlan):
                    chunk = _mark_device(plan.finalize(_fetch(plan.launch())))
                else:
                    chunk = _mark_device(plan())
                if _solo_event:
                    # every device dispatch shows on the timeline, solo
                    # launches included (grouped ones are the batcher's)
                    lane.launches += 1
                    M.TPU_LANE_LAUNCHES.inc(device=lane.name, mode="solo")
                    tl = TL.active()
                    if tl is not None:
                        tl.device_event(
                            "cop.launch", "launch", t0, time.perf_counter_ns(),
                            launch_id=tracing._next_id(), occupancy=1,
                            device=lane.name,
                        )
                return chunk
        finally:
            if placed is not None:
                self.release_lane(placed)

    def execute_many(self, items: list[tuple[DAGRequest, ColumnBatch]],
                     lane: DeviceLane | None = None) -> list[Chunk]:
        placed = None
        if lane is None:
            if items:
                lane = placed = self.place(items[0][1])
            else:
                lane = self.lanes[0]  # nothing to place (or release)
        try:
            with _lane_guard(lane):
                return self._execute_many_on(items, lane)
        finally:
            if placed is not None:
                self.release_lane(placed)

    def _execute_many_on(self, items: list[tuple[DAGRequest, ColumnBatch]],
                         lane: DeviceLane) -> list[Chunk]:
        """Run a batch of cop tasks with launch amortization, two tiers:

        1. tasks sharing a program key (identical rewritten DAG + tile
           bucket ⇒ identical lane shapes) STACK into one vmapped device
           program launch — per-task dispatch cost paid once per group;
        2. everything launched (fused groups and singles) is pulled back
           by a single `jax.device_get` — one host sync (on a tunneled
           device one round-trip) instead of len(items).

        Group programs are compiled per power-of-two size bucket (group
        padded by repeating its last task, padding discarded), so steady
        state pays at most log2(MAX_FUSE) extra compiles per key — per
        device lane (jit caches executables per committed device)."""
        plans = [self._plan_for(dag, batch, lane) for dag, batch in items]
        results: list = [None] * len(items)
        fusable: dict = {}  # program key -> [task index]
        launched = []  # (kind, payload) in launch order
        for i, (plan, (dag, batch)) in enumerate(zip(plans, items)):
            if plan is None:
                with self._lock:
                    self.fallbacks += 1
                M.TPU_FALLBACK.inc(path="cop", reason="not_lowerable")
                results[i] = execute_dag_host(dag, batch)
            elif isinstance(plan, DevicePlan):
                if plan.key is not None and plan.args is not None:
                    fusable.setdefault(plan.key, []).append(i)
                else:
                    launched.append(("one", (i, plan.launch())))
            else:
                results[i] = _mark_device(plan())  # exotic eager plan (none today)

        for key, idx_list in fusable.items():
            for lo in range(0, len(idx_list), self.MAX_FUSE):
                grp = idx_list[lo : lo + self.MAX_FUSE]
                if len(grp) == 1:
                    i = grp[0]
                    launched.append(("one", (i, plans[i].launch())))
                    continue
                gcap = 1 << (len(grp) - 1).bit_length()
                # run the group at the real row-count bucket instead of
                # the full padded shape — multi-tile groups included (the
                # old single-tile-only gate was the standing sched/ gap):
                # a single-tile group narrows to the power-of-two bucket
                # of its largest task, a multi-tile group narrows its
                # LAST tile's padding to a power-of-two remainder bucket
                # (full tiles hold real rows; pure pow2 of the total would
                # never undercut tile-multiple padding). `width` counts
                # FLATTENED rows, always a multiple of MIN_TILE_ROWS, and
                # the slice happens inside the jitted group program
                # (codec-aware, see _narrow_args). row_valid already
                # zeroes the tail, so narrowing only drops rows that
                # contribute exact zeros — at most log2 width buckets per
                # (key, size bucket) keep recompiles bounded
                width = None
                rv = plans[grp[0]].args[1]
                t_, r_ = rv.shape
                padded = t_ * r_
                need = max(plans[i].rows for i in grp)
                if t_ == 1:
                    w = pow2_rows(need)
                else:
                    w = (t_ - 1) * r_ + pow2_rows(need - (t_ - 1) * r_)
                if w < padded:
                    width = w
                vfn = self._vmapped_program(key, gcap, width)
                if vfn is None:  # no raw kernel on record: launch solo
                    for i in grp:
                        launched.append(("one", (i, plans[i].launch())))
                    continue
                padded = grp + [grp[-1]] * (gcap - len(grp))
                out = vfn(*[plans[i].args for i in padded])
                launched.append(("grp", (grp, out)))

        if launched:
            fetched = _fetch([payload[1] for _, payload in launched])
            for (kind, payload), host in zip(launched, fetched):
                if kind == "one":
                    i = payload[0]
                    results[i] = _mark_device(plans[i].finalize(host))
                else:
                    for j, i in enumerate(payload[0]):
                        results[i] = _mark_device(plans[i].finalize(
                            jax.tree_util.tree_map(lambda a: a[j], host)
                        ))
        return results

    # --- lowering ----------------------------------------------------------

    def _lower(self, dag: DAGRequest, dev: DeviceBatch):
        """→ zero-arg callable producing the result Chunk, or None if this
        DAG can't run on device (host fallback)."""
        scan_offs = dag.scan.col_offsets

        # columns used anywhere in the dag (scan-relative indices)
        used: set[int] = set()
        conds = dag.selection.conds if dag.selection else []
        for c in conds:
            c.collect_columns(used)
        if dag.agg:
            for g in dag.agg.group_by:
                g.collect_columns(used)
            for a in dag.agg.aggs:
                for e in a.args:
                    e.collect_columns(used)
        elif dag.topn:
            for e, _ in dag.topn.by:
                e.collect_columns(used)
            used |= set(range(len(scan_offs)))
        else:
            used |= set(range(len(scan_offs)))

        # materialize device lanes for used columns; build the vocab map
        lanes = {}
        vocabs = {}
        for i in sorted(used):
            off = scan_offs[i]
            d, v = dev.lanes(off)
            lanes[i] = (d, v)
            if off in dev.vocabs:
                vocabs[i] = dev.vocabs[off]

        r_conds = [self._rewrite(c, vocabs) for c in conds]
        if any(c is None for c in r_conds):
            return None

        # the static shape half of every program key: (tile count, row
        # bucket) plus each used lane's codec signature — the decode is
        # traced INTO the program, so two batches whose lanes encoded
        # differently must never share a compiled fn, and launch groups
        # (which stack these args) must agree on every aux shape. Codec
        # choices are content-stable, so steady state still compiles once
        # per (digest, size bucket, width bucket, codec shape).
        sig = (dev.t, dev.r) + tuple(
            (i, dev.lane_sigs.get(scan_offs[i], ((), ()))) for i in sorted(used)
        )

        if dag.agg is not None:
            return self._lower_agg(dag, dev, lanes, vocabs, r_conds, sig)
        if dag.topn is not None:
            return self._lower_topn(dag, dev, lanes, vocabs, r_conds, sig)
        return self._lower_filter(dag, dev, lanes, r_conds, sig)

    # --- string/dict rewriting --------------------------------------------

    def _rewrite(self, e: Expression, vocabs: dict[int, list]):
        """Rewrite an expression into device (code-space) form; None if not
        lowerable. String columns become int32 code lanes; comparisons with
        string constants map through the sorted vocab so code order ==
        collation order."""
        if isinstance(e, ExprCol):
            return e  # codes lane supplied by caller keyed on idx
        if isinstance(e, Constant):
            if e.value.kind in (K_STR, K_BYTES):
                return None  # bare string const outside rewritten cmp
            return e
        if not isinstance(e, ScalarFunc):
            return None
        name = e.sig.name
        # comparison with a string column vs string constant
        if name in _CMP_SWAP and len(e.args) == 2:
            a, b = e.args
            if isinstance(b, ExprCol) and isinstance(a, Constant):
                a, b = b, a
                name = _CMP_SWAP[name]
            if isinstance(a, ExprCol) and a.idx in vocabs and isinstance(b, Constant):
                if b.value.kind not in (K_STR, K_BYTES):
                    return None
                return self._code_cmp(name, a, b, vocabs[a.idx])
            if isinstance(a, ExprCol) and a.idx in vocabs:
                return None  # string col vs non-const: host
        if name == "in" and isinstance(e.args[0], ExprCol) and e.args[0].idx in vocabs:
            vocab = vocabs[e.args[0].idx]
            codes = []
            for c in e.args[1:]:
                if not isinstance(c, Constant) or c.value.kind not in (K_STR, K_BYTES):
                    return None
                i, present = vocab.lookup(c.value.to_str())
                codes.append(i if present else -1)
            col = ExprCol(e.args[0].idx, ft_longlong(), e.args[0].name)
            from ..expr.expression import make_func

            return make_func("in", col, *[Constant(Datum.i(c), ft_longlong()) for c in codes])
        # strings in any other position: not lowerable
        for a in e.args:
            if isinstance(a, ExprCol) and a.idx in vocabs:
                return None
        new_args = [self._rewrite(a, vocabs) for a in e.args]
        if any(a is None for a in new_args):
            return None
        return ScalarFunc(e.sig, new_args, e.ret_type)

    def _code_cmp(self, op: str, col: ExprCol, const: Constant, vocab: "Vocab"):
        """col <op> 'str' → code-space comparison via sorted-vocab bisect
        (weight-space under a ci collation)."""
        from ..expr.expression import make_func

        pos, present = vocab.lookup(const.value.to_str())
        icol = ExprCol(col.idx, ft_longlong(), col.name)

        def c(v):
            return Constant(Datum.i(v), ft_longlong())

        if op == "eq":
            return make_func("eq", icol, c(pos if present else -1))
        if op == "ne":
            return make_func("ne", icol, c(pos if present else -1))
        if op == "lt":
            return make_func("lt", icol, c(pos))
        if op == "ge":
            return make_func("ge", icol, c(pos))
        if op == "le":
            return make_func("lt" if not present else "le", icol, c(pos))
        if op == "gt":
            return make_func("ge" if not present else "gt", icol, c(pos))
        return None

    # --- kernels ------------------------------------------------------------

    @staticmethod
    def _eval_device(e: Expression, lanes: dict):
        """Recursive device eval over [T, R] lanes."""

        def rec(x):
            if isinstance(x, ExprCol):
                return lanes[x.idx]
            if isinstance(x, Constant):
                v = x.scalar_value()
                if v is None:
                    z = jnp.zeros((), dtype=jnp.int64)
                    return z, jnp.zeros((), dtype=bool)
                if x.ret_type.is_float():
                    dt = jnp.float64
                elif isinstance(v, int) and v > np.iinfo(np.int64).max:
                    dt = jnp.uint64  # literals above 2^63-1 (BIGINT UNSIGNED)
                else:
                    dt = jnp.int64
                return jnp.asarray(v, dtype=dt), jnp.asarray(True)
            avals = [rec(a) for a in x.args]
            return x.eval_xp(jnp, avals)

        return rec(e)

    def _mask(self, r_conds, lanes, row_valid):
        mask = row_valid
        for c in r_conds:
            d, v = self._eval_device(c, lanes)
            mask = mask & v & (d != 0)
        return mask

    def _program(self, key, builder):
        with self._lock:
            self._raw.setdefault(key, builder)  # for vmapped group launches
            fn = self._programs.get(key)
            if fn is None:
                M.TPU_COMPILE_CACHE.inc(result="miss")
                fn = _Timed(jax.jit(builder))
                self._programs[key] = fn
                self.compile_count += 1
            else:
                M.TPU_COMPILE_CACHE.inc(result="hit")
        return fn

    @staticmethod
    def _narrow_args(args, width):
        """Codec-aware in-program slice of one task's (lanes, row_valid)
        to `width` FLATTENED rows: positional lanes (dense data/valid,
        pack sub-words, dict codes, row_valid) slice row-major — real rows
        are a prefix of the flattened order, so only padding drops — while
        rle payloads pass through untouched (their decode reads the
        narrowed row_valid shape and truncates to it). Aux leaves (pack
        base, dict vocab) are positionless and keep their shape."""
        flat, rv = args

        def cut2d(a):
            t, r = a.shape
            if t * r <= width:
                return a
            # [1, width] when the cut fits one tile row; otherwise re-tile
            # at MIN_TILE_ROWS so the multi-tile last-tile cut stays
            # rectangular (width is always a multiple of MIN_TILE_ROWS)
            r2 = r if width % r == 0 else (width if width < r else MIN_TILE_ROWS)
            return a.reshape(-1)[:width].reshape(width // r2, r2)

        def cut(enc):
            if not isinstance(enc, dict):
                return cut2d(enc)
            if "p" in enc:
                return {**enc, "p": cut2d(enc["p"])}
            if "c" in enc:
                return {**enc, "c": cut2d(enc["c"])}
            return enc  # rle

        return ([cut(e) for e in flat], cut2d(rv))

    def _vmapped_program(self, key, gcap, width):
        """One device program for a whole compatible launch group: takes
        `gcap` tasks' (lanes, row_valid) pytrees, narrows every task to
        `width` flattened rows (None = keep the full padded shape —
        multi-tile groups reshape to a narrower [T', R'] the same way),
        stacks them on a new leading axis, and vmaps the raw per-task
        kernel over it — all INSIDE one jit so XLA fuses
        slice+stack+decode+compute into one dispatch (an eager stack of
        TILE_ROWS-padded point tasks copies ~16x more bytes than the
        group actually holds).

        Narrowing is exact, not approximate: every kernel masks with
        row_valid before reducing, so rows beyond `width` contribute
        literal zeros — dropping them cannot change any output bit
        (IEEE x+0.0 == x). Compiled per (key, size bucket, width bucket)
        — `key` already carries the codec signature; None if the raw
        kernel for `key` isn't on record."""
        with self._lock:
            vfn = self._vprograms.get((key, gcap, width))
            if vfn is None:
                raw = self._raw.get(key)
                if raw is None:
                    return None

                def group(*argss):
                    if width is not None:
                        argss = [self._narrow_args(args, width) for args in argss]
                    stacked = jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs), *argss
                    )
                    return jax.vmap(raw)(*stacked)

                M.TPU_COMPILE_CACHE.inc(result="miss")
                vfn = _Timed(jax.jit(group))
                self._vprograms[(key, gcap, width)] = vfn
                self.compile_count += 1
            else:
                M.TPU_COMPILE_CACHE.inc(result="hit")
        return vfn

    # --- filter-only --------------------------------------------------------

    def _lower_filter(self, dag: DAGRequest, dev: DeviceBatch, lanes, r_conds, sig):
        # cache key includes the REWRITTEN conds: dict-code constants are
        # vocab-specific, so the same SQL against a different region/batch
        # may compile to a different program
        key = ("filter", repr(r_conds), sig)
        arrs, order = self._flatten_lanes(lanes)
        fn = self._program(key, lambda flat, rv: self._mask(
            r_conds, self._unflatten(flat, order, rv), rv))

        def finalize(mask):
            mask = np.asarray(mask).reshape(-1)[: dev.batch.n_rows]
            chunk = dev.batch.to_chunk(dag.scan.col_offsets)
            chunk = chunk.filter(mask)
            if dag.limit is not None:
                chunk = chunk.slice(0, min(dag.limit.n, chunk.num_rows))
            return chunk

        return DevicePlan(
            lambda: fn(arrs, dev.row_valid), finalize,
            key=key, args=(arrs, dev.row_valid), rows=dev.batch.n_rows,
        )

    def _flatten_lanes(self, lanes):
        order = sorted(lanes)
        flat = []
        for i in order:
            flat.append(lanes[i][0])
            flat.append(lanes[i][1])
        return flat, order

    @staticmethod
    def _decode_lane(enc, row_valid):
        """Fused in-program decode of one uploaded lane: a plain array
        passes through; a codec payload (tilecache encode half) expands to
        the dense [T, R] lane INSIDE the jitted program, so XLA fuses
        decode+compute and the wire/h2d form stays the compressed form
        (arXiv:2506.10092's decompress-in-kernel). `row_valid` supplies
        the target static shape — the (possibly group-narrowed) one — and
        doubles as the value of zero-byte all-valid aliases."""
        if not isinstance(enc, dict):
            return enc
        if not enc:  # all-valid alias: the mask IS row_valid, for free
            return row_valid
        if "p" in enc:  # pack: frame-of-reference sub-word + base scalar
            return enc["p"].astype(enc["b"].dtype) + enc["b"]
        if "c" in enc:  # dict: sorted vocab gather
            return enc["v"][enc["c"]]
        # rle: static-length expand; total_repeat_length truncates to the
        # narrowed shape (only pad rows drop). The tail BEYOND the last
        # run gathers from the trailing zero-value pad run the encoder
        # always keeps (jnp.repeat clamps to the last run, not zero), so
        # pad rows decode to 0/False — and every kernel additionally
        # masks with row_valid before reducing
        shape = row_valid.shape
        flat = jnp.repeat(
            enc["rv"], enc["rl"], total_repeat_length=shape[0] * shape[1]
        )
        return flat.reshape(shape)

    @classmethod
    def _unflatten(cls, flat, order, row_valid):
        return {
            i: (
                cls._decode_lane(flat[2 * k], row_valid),
                cls._decode_lane(flat[2 * k + 1], row_valid),
            )
            for k, i in enumerate(order)
        }

    # --- aggregation --------------------------------------------------------

    def _lower_agg(self, dag: DAGRequest, dev: DeviceBatch, lanes, vocabs, r_conds, sig):
        agg = dag.agg
        gb = agg.group_by
        # group keys must be plain columns; float/uint64 keys group by
        # canonicalized bit pattern in the sorted path (never direct)
        wide_keys = False
        for g in gb:
            if not isinstance(g, ExprCol):
                return None
            if g.idx not in vocabs:
                d = dev.batch.data[dag.scan.col_offsets[g.idx]]
                if d.dtype == np.float64 or d.dtype == np.uint64:
                    wide_keys = True
        from ..mysqltypes import collate as _coll

        for a in agg.aggs:
            if a.name not in (
                "count", "sum", "avg", "min", "max", "first_row",
                "stddev_pop", "stddev_samp", "var_pop", "var_samp",
                "bit_and", "bit_or", "bit_xor",
            ):
                return None
            if (
                a.name in ("min", "max")
                and a.args
                and a.args[0].ret_type.is_string()
                and _coll.is_ci(getattr(a.args[0].ret_type, "collate", None))
            ):
                # dict codes collapse a ci weight class to ONE vocab
                # representative chosen batch-wide (pre-filter), which can
                # surface a value outside the qualifying rows — host path
                return None
            r_args = [self._rewrite(x, vocabs) if not (isinstance(x, ExprCol) and x.idx in vocabs) else (x if a.name in ("min", "max", "first_row", "count") else None) for x in a.args]
            if any(x is None for x in r_args):
                return None
            a._device_args = r_args

        # direct addressing needs NULL-free keys with small finite domains;
        # anything else routes to the sort-based segment path
        domains = []
        key_cols = []
        direct = not wide_keys
        for g in gb:
            if not direct:
                break
            if g.idx in vocabs:
                domains.append(max(len(vocabs[g.idx]), 1))
            else:
                d = dev.batch.data[dag.scan.col_offsets[g.idx]]
                v = dev.batch.valid[dag.scan.col_offsets[g.idx]]
                if not v.all() or len(d) == 0:
                    direct = False
                    break
                lo, hi = int(d.min()), int(d.max())
                if hi - lo + 1 > DIRECT_GROUP_MAX:
                    direct = False
                    break
                domains.append(hi - lo + 1)
                key_cols.append((g.idx, lo))
                continue
            key_cols.append((g.idx, 0))
        nseg = 1
        for s in domains:
            nseg *= s + 1  # +1 lane for NULL keys
        if not direct or nseg > DIRECT_GROUP_MAX:
            return self._lower_agg_sorted(dag, dev, lanes, vocabs, r_conds, sig)

        arrs, order = self._flatten_lanes(lanes)
        key = (
            "agg",
            repr(r_conds),
            repr([(a.name, repr(a._device_args)) for a in agg.aggs]),
            repr(key_cols),
            repr(domains),
            sig,
            nseg,
        )

        def kernel(flat, row_valid):
            l = self._unflatten(flat, order, row_valid)
            mask = self._mask(r_conds, l, row_valid)
            flat_mask = mask.reshape(-1)
            # combined group code, mixed radix; NULL key → extra slot
            if gb:
                code = jnp.zeros(flat_mask.shape, dtype=jnp.int32)
                for (idx, lo), dom in zip(key_cols, domains):
                    d, v = l[idx]
                    kd = (d.reshape(-1).astype(jnp.int32) - lo + 1) * v.reshape(-1)
                    code = code * (dom + 1) + kd
            else:
                code = jnp.zeros(flat_mask.shape, dtype=jnp.int32)
            seg = jnp.where(flat_mask, code, nseg)  # masked rows → overflow slot
            outs = [_seg_sum(flat_mask.astype(jnp.int64), seg, nseg)]
            for a in agg.aggs:
                outs.extend(self._agg_partials_device(a, l, flat_mask, seg, nseg))
            return outs

        fn, aux = self._packed_program(key, kernel, nseg)

        def finalize(fetched):
            # The whole partial state comes back as (at most) TWO stacked
            # arrays — each device->host fetch over the tunnel pays a full
            # round-trip, so per-array fetches dominated query time before
            # (32 × ~15-75ms); one packed fetch is one round-trip, and the
            # batcher further shares one fetch across a whole launch group.
            outs = self._unpack(fetched, aux)
            return self._agg_outputs_to_chunk(dag, dev, outs, domains, key_cols, vocabs, nseg)

        return DevicePlan(
            lambda: fn(arrs, dev.row_valid), finalize,
            key=key, args=(arrs, dev.row_valid), rows=dev.batch.n_rows,
        )

    # --- sort-based aggregation (high-cardinality GROUP BY) -----------------

    def _lower_agg_sorted(self, dag: DAGRequest, dev: DeviceBatch, lanes, vocabs, r_conds, sig):
        """GROUP BY with unbounded/NULLable key domains, fully on device.

        The reference's high-NDV path is a murmur3 hash shuffle into
        partial/final worker maps (executor/aggregate.go:544); hash tables
        don't map onto the MXU/VPU, so the TPU redesign is sort-based: one
        multi-operand `lax.sort` over (mask, null-flags, key lanes) makes
        groups contiguous, a cumsum over boundary flags assigns dense
        segment ids, and the same masked segment reductions as the direct
        path produce partial states. Output capacity must be static under
        jit, so programs are compiled at a group capacity that escalates
        (and is remembered per DAG digest) when a batch overflows it."""
        agg = dag.agg
        gb = agg.group_by
        key_idx = [g.idx for g in gb]
        if not key_idx:
            return None
        arrs, order = self._flatten_lanes(lanes)
        base_key = (
            "aggsort",
            repr(r_conds),
            repr([(a.name, repr(a._device_args)) for a in agg.aggs]),
            repr(key_idx),
            sig,
        )
        I64_MIN = np.iinfo(np.int64).min

        def make_kernel(gcap):
            def kernel(flat, row_valid):
                l = self._unflatten(flat, order, row_valid)
                mask = self._mask(r_conds, l, row_valid).reshape(-1)
                n = mask.shape[0]
                # lexicographic sort: masked rows last, then NULL flag +
                # value per key; the trailing iota operand is the row perm
                ops = [(~mask).astype(jnp.int32)]
                for ki in key_idx:
                    d, v = l[ki]
                    vf = v.reshape(-1)
                    ops.append((~vf).astype(jnp.int32))
                    # zero data under NULL so residual bytes can't split
                    # the NULL group (direct path normalizes the same way).
                    # float/uint64 keys group by canonical bit pattern:
                    # equality (all GROUP BY needs) survives the bitcast,
                    # with -0.0 folded into +0.0 first
                    dr = d.reshape(-1)
                    if jnp.issubdtype(dr.dtype, jnp.floating):
                        dr = jnp.where(dr == 0.0, 0.0, dr.astype(jnp.float64))
                        dr = jax.lax.bitcast_convert_type(dr, jnp.int64)
                    elif dr.dtype == jnp.uint64:
                        dr = jax.lax.bitcast_convert_type(dr, jnp.int64)
                    else:
                        dr = dr.astype(jnp.int64)
                    ops.append(jnp.where(vf, dr, 0))
                perm = lex_sort_perm(ops)
                res = [o[perm] for o in ops]
                s_mask = res[0] == 0
                s_keys = res[1:]
                diff = jnp.zeros(n, dtype=bool).at[0].set(True)
                one = jnp.ones(1, dtype=bool)
                for k in s_keys:
                    diff = diff | jnp.concatenate([one, k[1:] != k[:-1]])
                new = diff & s_mask
                seg0 = jnp.cumsum(new.astype(jnp.int32)) - 1
                n_groups = jnp.maximum(seg0[-1] + 1, 0)
                # groups beyond capacity fold into the overflow slot; the
                # exact n_groups triggers a host-side retry at higher cap
                seg = jnp.where(s_mask, jnp.minimum(seg0, gcap), gcap)
                outs = []
                for j in range(len(key_idx)):
                    knull = s_keys[2 * j]
                    kval = s_keys[2 * j + 1]
                    outs.append(_seg_max(jnp.where(s_mask, kval, I64_MIN), seg, gcap, I64_MIN))
                    outs.append(_seg_max(jnp.where(s_mask, 1 - knull.astype(jnp.int64), -1), seg, gcap, -1))
                l_perm = {i: (dd.reshape(-1)[perm], vv.reshape(-1)[perm]) for i, (dd, vv) in l.items()}
                for a in agg.aggs:
                    outs.extend(self._agg_partials_device(a, l_perm, s_mask, seg, gcap, index_lane=perm))
                return n_groups, outs

            return kernel

        # DevicePlan (not an eager loop, the standing PR 1 gap): the plan
        # launches at the remembered group capacity and carries (key,
        # args), so concurrent same-digest sorted-agg tasks FUSE into one
        # vmapped launch through the batcher like every other cop task.
        # Capacity overflow is detected in finalize from the fetched
        # n_groups scalar and re-runs THIS task solo at an escalated
        # capacity (exact at the higher cap, so results stay bit-identical
        # to the old loop); the remembered capacity means steady state
        # never overflows again.
        gcap = self._gcap.get(base_key, self.gcap0)
        fn, aux = self._packed_program(
            base_key + (gcap,), make_kernel(gcap), gcap, has_scalar=True
        )

        def rerun_escalated(ng: int):
            cap = gcap
            while True:
                while cap < ng:
                    cap <<= 2
                self._gcap[base_key] = cap
                fn2, aux2 = self._packed_program(
                    base_key + (cap,), make_kernel(cap), cap, has_scalar=True
                )
                ng_a, i_arr, f_arr = _fetch(fn2(arrs, dev.row_valid))
                ng = int(ng_a)
                if ng <= cap:
                    outs = self._unpack((i_arr, f_arr), aux2)
                    return self._agg_sorted_to_chunk(dag, dev, outs, key_idx, vocabs, ng)

        def finalize(fetched):
            ng_a, i_arr, f_arr = fetched
            ng = int(ng_a)
            if ng > gcap:
                return rerun_escalated(ng)
            outs = self._unpack((i_arr, f_arr), aux)
            return self._agg_sorted_to_chunk(dag, dev, outs, key_idx, vocabs, ng)

        return DevicePlan(
            lambda: fn(arrs, dev.row_valid), finalize,
            key=base_key + (gcap,), args=(arrs, dev.row_valid),
            rows=dev.batch.n_rows,
        )

    def _agg_sorted_to_chunk(self, dag, dev, outs, key_idx, vocabs, ng):
        agg = dag.agg
        out_fts = dag.output_types()
        present = np.arange(ng)
        cols: list[Column] = []
        pos = 0
        oi = 0
        for ki in key_idx:
            kval = np.asarray(outs[pos])[:ng]
            valid = np.asarray(outs[pos + 1])[:ng] == 1
            ft = out_fts[oi]
            if ki in vocabs:
                vocab = vocabs[ki]
                data = np.empty(ng, dtype=object)
                for j in range(ng):
                    c = int(kval[j])
                    data[j] = vocab[c] if valid[j] and 0 <= c < len(vocab) else None
            else:
                # undo the kernel's bit-pattern canonicalization
                src_dt = dev.batch.data[dag.scan.col_offsets[ki]].dtype
                data = kval.astype(np.int64)
                if src_dt == np.float64:
                    data = data.view(np.float64).copy()
                    data[~valid] = 0.0
                elif src_dt == np.uint64:
                    data = data.view(np.uint64).copy()
                    data[~valid] = 0
                else:
                    data[~valid] = 0
            cols.append(Column(ft, data, valid))
            pos += 2
            oi += 1
        cols.extend(self._agg_value_cols(dag, dev, outs, pos, oi, present, vocabs))
        return Chunk(cols)

    def _packed_program(self, key, kernel, nseg, has_scalar=False):
        """jit `kernel` (→ list of [nseg] arrays of mixed int/float dtype;
        with has_scalar, a (scalar, outs) pair) wrapped so the compiled
        program returns one stacked int64 array + one stacked float64 array
        (+ the scalar). The unpack layout is discovered at trace time and
        cached next to the compiled fn."""
        with self._lock:
            return self._packed_program_locked(key, kernel, nseg, has_scalar)

    def _packed_program_locked(self, key, kernel, nseg, has_scalar):
        cached = self._programs.get(key)
        if cached is not None:
            M.TPU_COMPILE_CACHE.inc(result="hit")
            return cached

        aux: dict = {}

        def packed(flat, row_valid):
            res = kernel(flat, row_valid)
            scalar, outs = res if has_scalar else (None, res)
            ints, flts, lay = [], [], []
            for o in outs:
                if jnp.issubdtype(o.dtype, jnp.floating):
                    lay.append(("f", len(flts)))
                    flts.append(o.astype(jnp.float64))
                else:
                    lay.append(("i", len(ints)))
                    ints.append(o.astype(jnp.int64))
            aux["layout"] = lay
            i_arr = jnp.stack(ints) if ints else jnp.zeros((0, nseg), jnp.int64)
            f_arr = jnp.stack(flts) if flts else jnp.zeros((0, nseg), jnp.float64)
            return (scalar, i_arr, f_arr) if has_scalar else (i_arr, f_arr)

        self._raw.setdefault(key, packed)
        M.TPU_COMPILE_CACHE.inc(result="miss")
        cached = (_Timed(jax.jit(packed)), aux)
        self._programs[key] = cached
        self.compile_count += 1
        return cached

    @staticmethod
    def _unpack(packed, aux):
        i_arr, f_arr = packed
        return [i_arr[k] if t == "i" else f_arr[k] for t, k in aux["layout"]]

    def _agg_partials_device(self, a, lanes, flat_mask, seg, nseg, index_lane=None):
        name = a.name
        if a._device_args:
            d, v = self._eval_device(a._device_args[0], lanes)
            d = jnp.full(seg.shape, d) if d.ndim == 0 else d.reshape(-1)
            v = jnp.full(seg.shape, v) if v.ndim == 0 else v.reshape(-1)
        else:
            d = jnp.ones(seg.shape, dtype=jnp.int64)
            v = jnp.ones(seg.shape, dtype=bool)
        ok = flat_mask & v
        if name == "count":
            return [_seg_sum(ok.astype(jnp.int64), seg, nseg)]
        if name in ("sum", "avg"):
            if d.dtype == jnp.float64 or d.dtype == jnp.float32:
                s = _seg_sum(jnp.where(ok, d, 0.0), seg, nseg)
            else:
                s = _seg_sum(jnp.where(ok, d.astype(jnp.int64), 0), seg, nseg)
            cnt = _seg_sum(ok.astype(jnp.int64), seg, nseg)
            return [s, cnt]
        if name in ("min", "max"):
            # sentinels in the lane's OWN dtype: an int64 sentinel written
            # into a uint64 lane both mis-orders values >= 2^63 and
            # overflows the decode (BIGINT UNSIGNED)
            if jnp.issubdtype(d.dtype, jnp.floating):
                big, small = jnp.asarray(jnp.inf, d.dtype), jnp.asarray(-jnp.inf, d.dtype)
            else:
                # sentinels in the lane's OWN dtype: jnp.where silently
                # TRUNCATES a wider sentinel into the lane dtype (int64
                # max → int32 -1), poisoning MIN over dict-code lanes
                info = np.iinfo(np.dtype(str(d.dtype)))
                big = jnp.asarray(info.max, d.dtype)
                small = jnp.asarray(info.min, d.dtype)
            if name == "min":
                s = _seg_min(jnp.where(ok, d, big), seg, nseg, big)
            else:
                s = _seg_max(jnp.where(ok, d, small), seg, nseg, small)
            if s.dtype == jnp.uint64:
                # packed transport is int64; undone by view(uint64) at decode
                s = jax.lax.bitcast_convert_type(s, jnp.int64)
            cnt = _seg_sum(ok.astype(jnp.int64), seg, nseg)
            return [s, cnt]
        if name == "first_row":
            idx = jnp.arange(seg.shape[0]) if index_lane is None else index_lane
            first = _seg_min(jnp.where(ok, idx, seg.shape[0]), seg, nseg, jnp.asarray(seg.shape[0]))
            return [first]
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            # (cnt, sum, sumsq) partials, mirroring the host cop form.
            # Decimals ship (int64 wrap-sum, float estimate) pairs of the
            # SCALED ints; decode reconstructs the exact integer sums
            # (order-independent) and does the single float division —
            # bit-identical to host_engine whatever the summation order.
            arg_ft = a.args[0].ret_type
            cnt = _seg_sum(ok.astype(jnp.int64), seg, nseg)
            if arg_ft.is_decimal():
                xi = jnp.where(ok, d.astype(jnp.int64), 0)
                ai = xi >> 32  # arithmetic shift: hi limb keeps the sign
                bi = xi - (ai << 32)  # lo limb in [0, 2^32)
                af, bf = ai.astype(jnp.float64), bi.astype(jnp.float64)
                return [cnt,
                        _seg_sum(xi, seg, nseg), _seg_sum(xi.astype(jnp.float64), seg, nseg),
                        _seg_sum(ai * ai, seg, nseg), _seg_sum(af * af, seg, nseg),
                        _seg_sum(ai * bi, seg, nseg), _seg_sum(af * bf, seg, nseg),
                        _seg_sum(bi * bi, seg, nseg), _seg_sum(bf * bf, seg, nseg)]
            x = jnp.where(ok, d.astype(jnp.float64), 0.0)
            return [cnt, _seg_sum(x, seg, nseg), _seg_sum(x * x, seg, nseg)]
        if name in ("bit_and", "bit_or", "bit_xor"):
            # bitwise reductions decompose per bit: segment min/max/sum-mod-2
            # over a [n, 64] bit matrix, recombined by shifts (two's
            # complement places bit 63 via the int64 wrap)
            arg_ft = a.args[0].ret_type
            if arg_ft.is_decimal():
                xf = d.astype(jnp.float64) / float(pow10(max(arg_ft.decimal, 0)))
                x = jnp.rint(xf).astype(jnp.int64)
            elif jnp.issubdtype(d.dtype, jnp.floating):
                x = jnp.rint(d).astype(jnp.int64)
            else:
                x = d.astype(jnp.int64)
            shifts = jnp.arange(64, dtype=jnp.int64)
            bits = ((x[:, None] >> shifts[None, :]) & 1).astype(jnp.int32)
            if name == "bit_and":
                bits = jnp.where(ok[:, None], bits, 1)
                red = jax.ops.segment_min(bits, seg, num_segments=nseg + 1)[:nseg]
            elif name == "bit_or":
                bits = jnp.where(ok[:, None], bits, 0)
                red = jax.ops.segment_max(bits, seg, num_segments=nseg + 1)[:nseg]
            else:
                bits = jnp.where(ok[:, None], bits, 0)
                red = jax.ops.segment_sum(bits, seg, num_segments=nseg + 1)[:nseg] % 2
            out = ((red & 1).astype(jnp.int64) << shifts[None, :]).sum(axis=1)
            return [out]
        raise NotImplementedError(name)

    def _agg_outputs_to_chunk(self, dag, dev, outs, domains, key_cols, vocabs, nseg):
        agg = dag.agg
        out_fts = dag.output_types()
        group_count = np.asarray(outs[0])
        present = np.nonzero(group_count > 0)[0]
        G = len(present)
        cols: list[Column] = []
        # decode group keys from segment index (mixed radix)
        radix = [d + 1 for d in domains]
        codes = present.copy()
        key_vals = []
        for r in reversed(radix):
            key_vals.append(codes % r)
            codes = codes // r
        key_vals.reverse()
        oi = 0
        for (idx, lo), kv in zip(key_cols, key_vals):
            ft = out_fts[oi]
            valid = kv > 0
            if idx in vocabs:
                vocab = vocabs[idx]
                data = np.empty(G, dtype=object)
                for j, code in enumerate(kv):
                    data[j] = vocab[code - 1] if code > 0 else None
            else:
                data = (kv.astype(np.int64) - 1) + lo
                data[~valid] = 0
            cols.append(Column(ft, data, valid))
            oi += 1
        cols.extend(self._agg_value_cols(dag, dev, outs, 1, oi, present, vocabs))
        return Chunk(cols)

    def _agg_value_cols(self, dag, dev, outs, pos, oi, present, vocabs):
        """Shared partial-state → Column decode for both agg paths.
        `present` selects live group slots; `pos`/`oi` index the first
        agg partial in `outs` / the first agg field in output_types()."""
        agg = dag.agg
        out_fts = dag.output_types()
        G = len(present)
        cols: list[Column] = []
        for a in agg.aggs:
            pf = a.partial_final_types()
            if a.name == "count":
                cnt = np.asarray(outs[pos])[present]
                cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, dtype=bool)))
                pos += 1
                oi += 1
            elif a.name in ("sum", "avg"):
                s = np.asarray(outs[pos])[present]
                cnt = np.asarray(outs[pos + 1])[present]
                has = cnt > 0
                sd = s if out_fts[oi].is_float() else s.astype(np.int64)
                cols.append(Column(out_fts[oi], sd, has))
                oi += 1
                if a.name == "avg":
                    cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, dtype=bool)))
                    oi += 1
                pos += 2
            elif a.name in ("min", "max"):
                s = np.asarray(outs[pos])[present]
                cnt = np.asarray(outs[pos + 1])[present]
                has = cnt > 0
                ft = out_fts[oi]
                arg = a.args[0]
                if isinstance(arg, ExprCol) and arg.idx in vocabs:
                    vocab = vocabs[arg.idx]
                    data = np.empty(G, dtype=object)
                    for j in range(G):
                        data[j] = vocab[int(s[j])] if has[j] and 0 <= int(s[j]) < len(vocab) else None
                elif ft.is_float():
                    data = s
                elif ft.is_int() and ft.is_unsigned:
                    # undo the kernel's uint64→int64 transport bitcast
                    data = s.astype(np.int64).view(np.uint64).copy()
                    data[~has] = 0
                else:
                    data = np.where(has, s.astype(np.int64), 0)
                cols.append(Column(ft, data, has))
                pos += 2
                oi += 1
            elif a.name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
                ones = np.ones(G, dtype=bool)
                cnt = np.asarray(outs[pos])[present].astype(np.int64)
                arg_ft = a.args[0].ret_type
                if arg_ft.is_decimal():
                    # (wrap, estimate) pairs → exact scaled-int sums
                    # (sumsq via 32-bit limbs), then the single float
                    # division happens here on host
                    o = [np.asarray(outs[pos + j])[present] for j in range(1, 9)]
                    scale = float(pow10(max(arg_ft.decimal, 0)))
                    s = exact_sum64(o[0], o[1]) / scale
                    sq = exact_sumsq64(o[2], o[3], o[4], o[5], o[6], o[7]) / (scale * scale)
                    pos += 9
                else:
                    s = np.asarray(outs[pos + 1])[present]
                    sq = np.asarray(outs[pos + 2])[present]
                    pos += 3
                cols.append(Column(out_fts[oi], cnt, ones))
                cols.append(Column(out_fts[oi + 1], s, ones))
                cols.append(Column(out_fts[oi + 2], sq, ones))
                oi += 3
            elif a.name in ("bit_and", "bit_or", "bit_xor"):
                val = np.asarray(outs[pos])[present].astype(np.int64)
                cols.append(Column(out_fts[oi], val, np.ones(G, dtype=bool)))
                pos += 1
                oi += 1
            elif a.name == "first_row":
                firsts = np.asarray(outs[pos])[present]
                ft = out_fts[oi]
                n = dev.batch.n_rows
                src_off = dag.scan.col_offsets[a.args[0].idx] if isinstance(a.args[0], ExprCol) else None
                from ..chunk.chunk import col_numpy_dtype, VARLEN

                dt = col_numpy_dtype(ft)
                data = np.empty(G, dtype=object) if dt is VARLEN else np.zeros(G, dtype=dt)
                valid = np.zeros(G, dtype=bool)
                for j, fi in enumerate(firsts):
                    fi = int(fi)
                    if fi < n and src_off is not None:
                        data[j] = dev.batch.data[src_off][fi]
                        valid[j] = dev.batch.valid[src_off][fi]
                cols.append(Column(ft, data, valid))
                pos += 1
                oi += 1
        return cols

    # --- topn ----------------------------------------------------------------

    def _lower_topn(self, dag: DAGRequest, dev: DeviceBatch, lanes, vocabs, r_conds, sig):
        by = dag.topn.by
        if len(by) != 1:
            return self._lower_topn_multi(dag, dev, lanes, vocabs, r_conds, sig)
        e, desc = by[0]
        r_e = self._rewrite(e, vocabs)
        if r_e is None:
            return None
        n = dag.topn.n
        key = ("topn", repr(r_conds), repr(r_e), desc, n, sig)
        arrs, order = self._flatten_lanes(lanes)

        def kernel(flat, row_valid):
            l = self._unflatten(flat, order, row_valid)
            mask = self._mask(r_conds, l, row_valid)
            d, v = self._eval_device(r_e, l)
            d = jnp.full(mask.shape, d) if d.ndim == 0 else d
            v = jnp.full(mask.shape, v) if v.ndim == 0 else v
            d, v, m = d.reshape(-1), v.reshape(-1), mask.reshape(-1)
            # integer keys stay integer (exact for packed datetimes/decimals)
            if jnp.issubdtype(d.dtype, jnp.floating):
                lo, hi = -jnp.inf, jnp.inf
            else:
                d = d.astype(jnp.int64)
                info = np.iinfo(np.int64)
                lo, hi = info.min, info.max - 1
            if desc:
                # NULLs last desc; masked rows last
                sortkey = jnp.where(m & v, d, lo)
            else:
                # top_k takes largest → negate for asc; NULLs first asc
                sortkey = jnp.where(m, jnp.where(v, -d, hi), lo)
            _, idx = jax.lax.top_k(sortkey, min(n, sortkey.shape[0]))
            # ship only k validity bits, not the full row mask
            return idx, m[idx]

        fn = self._program(key, kernel)

        def finalize(fetched):
            idx, ok = fetched
            idx = idx[ok]  # drop indices pointing at masked rows
            chunk = dev.batch.to_chunk(dag.scan.col_offsets)
            return chunk.take(idx[: dag.topn.n])

        return DevicePlan(
            lambda: fn(arrs, dev.row_valid), finalize,
            key=key, args=(arrs, dev.row_valid), rows=dev.batch.n_rows,
        )

    def _lower_topn_multi(self, dag: DAGRequest, dev: DeviceBatch, lanes, vocabs, r_conds, sig):
        """Multi-key TopN: one multi-operand lax.sort over (mask, per-key
        NULL-flag + data, row-id), take the first n sorted row-ids (the
        window-kernel sort recipe; ref closure_exec.go topN heap — the TPU
        form is a full sort, exact and still one fused program)."""
        by = dag.topn.by
        r_by = []
        for e, desc in by:
            r_e = self._rewrite(e, vocabs)
            if r_e is None:
                return None
            r_by.append((r_e, desc))
        n = dag.topn.n
        key = ("topn_multi", repr(r_conds), repr(r_by), n, sig)
        arrs, order = self._flatten_lanes(lanes)

        def kernel(flat, row_valid):
            l = self._unflatten(flat, order, row_valid)
            mask = self._mask(r_conds, l, row_valid).reshape(-1)
            rows = mask.shape[0]
            ops = [(~mask).astype(jnp.int32)]  # masked rows last
            for r_e, desc in r_by:
                d, v = self._eval_device(r_e, l)
                d = jnp.full((rows,), d) if d.ndim == 0 else d.reshape(-1)
                v = jnp.full((rows,), v) if v.ndim == 0 else v.reshape(-1)
                # NULLs first asc / last desc (host _lex_argsort contract)
                nullkey = jnp.where(v, 0, 1) if desc else jnp.where(v, 1, 0)
                dd = jnp.where(v, d, jnp.zeros((), d.dtype))
                if desc:
                    dd = -dd if jnp.issubdtype(d.dtype, jnp.floating) else ~dd
                ops += [nullkey.astype(jnp.int32), dd]
            perm = lex_sort_perm(ops)
            return perm[: min(n, rows)], ops[0][perm][: min(n, rows)] == 0

        fn = self._program(key, kernel)

        def finalize(fetched):
            idx, ok = fetched
            chunk = dev.batch.to_chunk(dag.scan.col_offsets)
            return chunk.take(idx[ok][: dag.topn.n])

        return DevicePlan(
            lambda: fn(arrs, dev.row_valid), finalize,
            key=key, args=(arrs, dev.row_valid), rows=dev.batch.n_rows,
        )

"""Recursive-descent SQL parser (ref: pingcap/parser parser.y — the grammar
coverage is modeled on the reference's MySQL dialect; the implementation is
a fresh Pratt/recursive-descent design, not yacc).

Covers the SQL surface the framework executes: SELECT (joins, subqueries,
group/having/order/limit, set-ops), DML, DDL, transactions, SET/SHOW/
EXPLAIN/ANALYZE/ADMIN, prepared statements.
"""

from __future__ import annotations

from ..errors import ParseError
from ..mysqltypes.mydecimal import dec_from_string
from . import ast
from .lexer import Token, tokenize

# binary operator precedence (higher binds tighter); name → builtin func name
BINOPS = {
    "||": (1, "or"),
    "OR": (1, "or"),
    "XOR": (2, "xor"),
    "&&": (3, "and"),
    "AND": (3, "and"),
    "=": (5, "eq"),
    "<=>": (5, "nulleq"),
    "<": (5, "lt"),
    ">": (5, "gt"),
    "<=": (5, "le"),
    ">=": (5, "ge"),
    "!=": (5, "ne"),
    "<>": (5, "ne"),
    "|": (6, "bitor"),
    "&": (7, "bitand"),
    "<<": (8, "lshift"),
    ">>": (8, "rshift"),
    "+": (9, "plus"),
    "-": (9, "minus"),
    "*": (10, "mul"),
    "/": (10, "div"),
    "%": (10, "mod"),
    "DIV": (10, "intdiv"),
    "MOD": (10, "mod"),
    "^": (11, "bitxor"),
}

CMP_PREC = 5

RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION", "EXCEPT", "INTERSECT",
    "ON", "USING", "JOIN", "INNER", "LEFT", "RIGHT", "CROSS", "STRAIGHT_JOIN", "AS", "SET",
    "VALUES", "INTO", "AND", "OR", "NOT", "XOR", "IS", "IN", "LIKE", "BETWEEN", "REGEXP",
    "RLIKE", "ASC", "DESC", "FOR", "LOCK", "THEN", "ELSE", "WHEN", "END", "CASE", "DIV",
    "MOD", "COLLATE", "INTERVAL", "EXISTS", "SELECT", "DUPLICATE", "KEY", "UPDATE", "BY", "WITH",
}


def _walk_tables(node):
    """Yield every TableName under a FROM tree (Join/list), not descending
    into derived-table subqueries — those carry their own AS OF."""
    if node is None:
        return
    if isinstance(node, ast.TableName):
        yield node
    elif isinstance(node, ast.Join):
        yield from _walk_tables(node.left)
        yield from _walk_tables(node.right)
    elif isinstance(node, list):
        for n in node:
            yield from _walk_tables(n)


def parse(sql: str) -> list:
    """Parse a semicolon-separated script into a list of statements."""
    p = Parser(tokenize(sql), sql)
    stmts = []
    while not p.at("eof"):
        if p.try_op(";"):
            continue
        stmts.append(p.statement())
        if not p.at("eof"):
            p.expect_op(";")
    return stmts


def parse_one(sql: str):
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected a single statement, got {len(stmts)}")
    return stmts[0]


import re as _re

_HINT_RE = _re.compile(r"(\w+)\s*(?:\(([^()]*)\))?")


def parse_hint_text(text: str) -> list:
    """'/*+ NAME(a, b) NAME2 */' → [(NAME, [a, b]), (NAME2, [])]."""
    body = text[3:-2]
    out = []
    for m in _HINT_RE.finditer(body):
        name = m.group(1).upper()
        args = [a.strip().strip("'\"`").lower() for a in (m.group(2) or "").split(",") if a.strip()]
        out.append((name, args))
    return out


class Parser:
    def __init__(self, toks: list[Token], sql: str = ""):
        # optimizer hints apply statement-wide (query-block scoping is a
        # later refinement): collect and strip them from the stream
        self.hints = []
        for t in toks:
            if t.kind == "hint":
                self.hints.extend(parse_hint_text(t.text))
        self.toks = [t for t in toks if t.kind != "hint"]
        self.i = 0
        self.sql = sql
        self.param_count = 0

    # --- token helpers -----------------------------------------------------

    @property
    def tok(self) -> Token:
        return self.toks[self.i]

    def peek(self, off=1) -> Token:
        j = min(self.i + off, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at(self, kind: str) -> bool:
        return self.tok.kind == kind

    def at_kw(self, *kws: str) -> bool:
        return self.tok.kind == "ident" and self.tok.upper in kws

    def at_op(self, *ops: str) -> bool:
        return self.tok.kind == "op" and self.tok.text in ops

    def try_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def try_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            self.fail(f"expected {kw}")
        return self.next()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            self.fail(f"expected {op!r}")
        return self.next()

    def ident(self) -> str:
        t = self.tok
        if t.kind in ("ident", "qident"):
            self.next()
            return t.text
        self.fail("expected identifier")

    def fail(self, msg: str):
        t = self.tok
        near = self.sql[max(t.pos - 20, 0) : t.pos + 20]
        raise ParseError(f"{msg} near offset {t.pos}: ...{near!r}... (got {t.text!r})")

    # --- statements --------------------------------------------------------

    def statement(self):
        t = self.tok
        if t.kind != "ident":
            if t.kind == "op" and t.text == "(":
                return self.select_stmt()
            self.fail("expected statement")
        kw = t.upper
        fn = {
            "SELECT": self.select_stmt,
            "WITH": self.select_stmt,
            "INSERT": self.insert_stmt,
            "REPLACE": self.insert_stmt,
            "UPDATE": self.update_stmt,
            "DELETE": self.delete_stmt,
            "CREATE": self.create_stmt,
            "DROP": self.drop_stmt,
            "ALTER": self.alter_stmt,
            "TRUNCATE": self.truncate_stmt,
            "RENAME": self.rename_stmt,
            "BEGIN": self.begin_stmt,
            "START": self.begin_stmt,
            "COMMIT": lambda: (self.next(), ast.Commit())[1],
            "ROLLBACK": lambda: (self.next(), ast.Rollback())[1],
            "SET": self.set_stmt,
            "SHOW": self.show_stmt,
            "EXPLAIN": self.explain_stmt,
            "DESC": self.desc_stmt,
            "DESCRIBE": self.desc_stmt,
            "USE": self.use_stmt,
            "ANALYZE": self.analyze_stmt,
            "PREPARE": self.prepare_stmt,
            "EXECUTE": self.execute_stmt,
            "DEALLOCATE": self.deallocate_stmt,
            "ADMIN": self.admin_stmt,
            "KILL": self.kill_stmt,
            "FLUSH": self.flush_stmt,
            "LOAD": self.load_stmt,
            "SPLIT": self.split_stmt,
            "BACKUP": self.brie_stmt,
            "RESTORE": self.brie_stmt,
            "GRANT": self.grant_stmt,
            "REVOKE": self.grant_stmt,
            "LOCK": self.lock_stmt,
            "UNLOCK": self.unlock_stmt,
            "TRACE": self.trace_stmt,
        }.get(kw)
        if fn is None:
            self.fail(f"unsupported statement {kw}")
        return fn()

    # --- SELECT ------------------------------------------------------------

    def select_stmt(self):
        with_ = None
        if self.at_kw("WITH"):
            self.next()
            recursive = self.try_kw("RECURSIVE")
            ctes = []
            while True:
                name = self.ident()
                cols = []
                if self.try_op("("):
                    cols = self.name_list()
                    self.expect_op(")")
                self.expect_kw("AS")
                self.expect_op("(")
                sub = self.select_stmt()
                self.expect_op(")")
                ctes.append(ast.CTEDef(name, cols, sub))
                if not self.try_op(","):
                    break
            with_ = ast.WithClause(recursive, ctes)
        stmt = self._select_body()
        if with_ is not None:
            stmt.with_ = with_
        if self.hints:
            stmt.hints = list(self.hints)
        return stmt

    def _select_body(self):
        first = self.select_core()
        selects = [first]
        ops = []
        while True:
            if self.at_kw("UNION"):
                self.next()
                ops.append("union_all" if self.try_kw("ALL") else ("union" if not self.try_kw("DISTINCT") else "union"))
            elif self.at_kw("EXCEPT"):
                self.next()
                ops.append("except")
            elif self.at_kw("INTERSECT"):
                self.next()
                ops.append("intersect")
            else:
                break
            selects.append(self.select_core())
        if len(selects) == 1:
            return first
        setop = ast.SetOpSelect(selects, ops)
        # MySQL: a trailing ORDER BY/LIMIT on the (unparenthesized) last
        # branch applies to the whole set operation — hoist it.
        last = selects[-1]
        if isinstance(last, ast.Select):
            setop.order_by, last.order_by = last.order_by, []
            setop.limit, setop.offset, last.limit, last.offset = last.limit, last.offset, None, None
            if last.into_outfile is not None:  # INTO OUTFILE hoists too
                setop.into_outfile, last.into_outfile = last.into_outfile, None
                setop.outfile_fsep = last.outfile_fsep
                setop.outfile_lsep = last.outfile_lsep
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            setop.order_by = self.by_items()
        if self.try_kw("LIMIT"):
            setop.limit, setop.offset = self.limit_clause()
        return setop

    def select_core(self) -> ast.Select:
        if self.try_op("("):
            s = self.select_stmt()
            self.expect_op(")")
            return s
        self.expect_kw("SELECT")
        sel = ast.Select(fields=[])
        while self.at_kw("DISTINCT", "ALL", "DISTINCTROW", "SQL_CALC_FOUND_ROWS"):
            if self.tok.upper in ("DISTINCT", "DISTINCTROW"):
                sel.distinct = True
            self.next()
        # select list
        while True:
            sel.fields.append(self.select_field())
            if not self.try_op(","):
                break
        if self.try_kw("FROM"):
            sel.from_ = self.table_refs()
            # hoist `AS OF TIMESTAMP` to the statement: the read-ts is a
            # per-statement property (one snapshot), not per-table here
            for t in _walk_tables(sel.from_):
                if getattr(t, "as_of", None) is not None:
                    sel.as_of = t.as_of
        if self.try_kw("WHERE"):
            sel.where = self.expr()
        if self.try_kw("GROUP"):
            self.expect_kw("BY")
            sel.group_by = [b.expr for b in self.by_items()]
        if self.try_kw("HAVING"):
            sel.having = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            sel.order_by = self.by_items()
        if self.try_kw("LIMIT"):
            sel.limit, sel.offset = self.limit_clause()
        if self.try_kw("INTO"):
            # SELECT ... INTO OUTFILE 'path' (ref: executor/select_into.go)
            self.expect_kw("OUTFILE")
            t = self.next()
            if t.kind != "str":
                self.fail("expected OUTFILE path string")
            sel.into_outfile = t.text
            if self.try_kw("FIELDS") or self.try_kw("COLUMNS"):
                self.expect_kw("TERMINATED")
                self.expect_kw("BY")
                sel.outfile_fsep = self._str_lit("field separator")
            if self.try_kw("LINES"):
                self.expect_kw("TERMINATED")
                self.expect_kw("BY")
                sel.outfile_lsep = self._str_lit("line separator")
        if self.try_kw("FOR"):
            self.expect_kw("UPDATE")
            sel.for_update = True
        elif self.try_kw("LOCK"):
            self.expect_kw("IN")
            self.expect_kw("SHARE")
            self.expect_kw("MODE")
            sel.lock_in_share = True
        return sel

    def select_field(self):
        if self.at_op("*"):
            self.next()
            return ast.Star()
        # t.* / db.t.*
        if self.tok.kind in ("ident", "qident") and self.tok.upper not in RESERVED_STOP:
            j = self.i
            try:
                name = self.ident()
                if self.try_op("."):
                    if self.try_op("*"):
                        return ast.Star(table=name)
                self.i = j
            except ParseError:
                self.i = j
        e = self.expr()
        alias = None
        if self.try_kw("AS"):
            alias = self.ident_or_string()
        elif self.tok.kind in ("ident", "qident") and self.tok.upper not in RESERVED_STOP:
            alias = self.ident()
        return ast.SelectField(e, alias)

    def ident_or_string(self) -> str:
        if self.tok.kind == "str":
            return self.next().text
        return self.ident()

    def by_items(self) -> list:
        items = []
        while True:
            e = self.expr()
            desc = False
            if self.try_kw("DESC"):
                desc = True
            else:
                self.try_kw("ASC")
            items.append(ast.ByItem(e, desc))
            if not self.try_op(","):
                break
        return items

    def limit_clause(self):
        a = self.expr()
        if self.try_op(","):
            b = self.expr()
            return b, a  # LIMIT offset, count
        if self.try_kw("OFFSET"):
            return a, self.expr()
        return a, None

    # --- table references ---------------------------------------------------

    def table_refs(self):
        left = self.table_factor()
        while True:
            natural = False
            if self.at_kw("NATURAL"):
                self.next()
                natural = True
            if self.try_op(","):
                right = self.table_factor()
                left = ast.Join(left, right, "cross")
                continue
            if self.at_kw("JOIN", "INNER", "CROSS", "STRAIGHT_JOIN"):
                kind = "inner"
                straight = self.tok.upper == "STRAIGHT_JOIN"
                if self.tok.upper == "CROSS":
                    kind = "cross"
                if self.tok.upper in ("INNER", "CROSS"):
                    self.next()
                self.expect_kw("JOIN") if self.at_kw("JOIN") else self.next()
                right = self.table_factor()
                j = ast.Join(left, right, kind)
                j.straight = straight
                self._join_cond(j, natural)
                left = j
                continue
            if self.at_kw("LEFT", "RIGHT"):
                kind = self.next().upper.lower()
                self.try_kw("OUTER")
                self.expect_kw("JOIN")
                right = self.table_factor()
                j = ast.Join(left, right, kind)
                self._join_cond(j, natural)
                left = j
                continue
            break
        return left

    def _join_cond(self, j: ast.Join, natural: bool):
        if natural:
            j.kind = "natural_" + j.kind
            return
        if self.try_kw("ON"):
            j.on = self.expr()
        elif self.try_kw("USING"):
            self.expect_op("(")
            j.using = self.name_list()
            self.expect_op(")")

    def table_factor(self):
        if self.try_op("("):
            if self.at_kw("SELECT", "WITH") or self.at_op("("):
                s = self.select_stmt()
                self.expect_op(")")
                alias = None
                self.try_kw("AS")
                if self.tok.kind in ("ident", "qident"):
                    alias = self.ident()
                if alias is None:
                    self.fail("derived table requires an alias")
                return ast.SubqueryTable(s, alias)
            refs = self.table_refs()
            self.expect_op(")")
            return refs
        db = None
        name = self.ident()
        if self.try_op("."):
            db, name = name, self.ident()
        as_of = None
        # `t AS OF TIMESTAMP expr` must be checked before the `AS alias`
        # branch — a bare try_kw("AS") would eat the AS and read OF as the
        # alias (ref: planner stale-read, executor/stale_txn_test.go)
        if self.at_kw("AS") and self.peek().kind == "ident" and self.peek().upper == "OF":
            self.next()  # AS
            self.next()  # OF
            self.expect_kw("TIMESTAMP")
            as_of = self.expr()
        alias = None
        if self.try_kw("AS"):
            alias = self.ident()
        elif self.tok.kind in ("ident", "qident") and self.tok.upper not in RESERVED_STOP:
            alias = self.ident()
        return ast.TableName(db, name, alias, as_of=as_of)

    def name_list(self) -> list:
        names = [self.ident()]
        while self.try_op(","):
            names.append(self.ident())
        return names

    # --- expressions (Pratt) ------------------------------------------------

    def expr(self, min_prec: int = 0):
        left = self.unary()
        while True:
            t = self.tok
            # IS [NOT] NULL / TRUE / FALSE
            if self.at_kw("IS"):
                if CMP_PREC < min_prec:
                    break
                self.next()
                neg = self.try_kw("NOT")
                if self.try_kw("NULL"):
                    left = ast.Call("isnull", [left])
                elif self.try_kw("TRUE"):
                    left = ast.Call("istrue", [left])
                elif self.try_kw("FALSE"):
                    left = ast.Call("isfalse", [left])
                else:
                    self.fail("expected NULL/TRUE/FALSE after IS")
                if neg:
                    left = ast.Call("not", [left])
                continue
            neg = False
            j = self.i
            if self.at_kw("NOT") and self.peek().kind == "ident" and self.peek().upper in ("IN", "LIKE", "BETWEEN", "REGEXP", "RLIKE"):
                if CMP_PREC < min_prec:
                    break
                self.next()
                neg = True
            if self.at_kw("IN"):
                if CMP_PREC < min_prec:
                    self.i = j
                    break
                self.next()
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    sub = self.select_stmt()
                    self.expect_op(")")
                    left = ast.Call("in_subquery", [left, ast.SubqueryExpr(sub, "in")])
                else:
                    args = [self.expr()]
                    while self.try_op(","):
                        args.append(self.expr())
                    self.expect_op(")")
                    left = ast.Call("in", [left] + args)
                if neg:
                    left = ast.Call("not", [left])
                continue
            if self.at_kw("LIKE"):
                if CMP_PREC < min_prec:
                    self.i = j
                    break
                self.next()
                pat = self.expr(CMP_PREC + 1)
                esc = None
                if self.try_kw("ESCAPE"):
                    esc = self.expr(CMP_PREC + 1)
                left = ast.Call("like", [left, pat] + ([esc] if esc is not None else []))
                if neg:
                    left = ast.Call("not", [left])
                continue
            if self.at_kw("REGEXP", "RLIKE"):
                if CMP_PREC < min_prec:
                    self.i = j
                    break
                self.next()
                pat = self.expr(CMP_PREC + 1)
                left = ast.Call("regexp", [left, pat])
                if neg:
                    left = ast.Call("not", [left])
                continue
            if self.at_kw("BETWEEN"):
                if CMP_PREC < min_prec:
                    self.i = j
                    break
                self.next()
                lo = self.expr(CMP_PREC + 1)
                self.expect_kw("AND")
                hi = self.expr(CMP_PREC + 1)
                left = ast.Call("and", [ast.Call("ge", [left, lo]), ast.Call("le", [left, hi])])
                if neg:
                    left = ast.Call("not", [left])
                continue
            if neg:
                self.i = j
                break
            # plain binary operators
            key = None
            if t.kind == "op" and t.text in BINOPS:
                key = t.text
            elif t.kind == "ident" and t.upper in BINOPS:
                key = t.upper
            if key is None:
                break
            prec, fname = BINOPS[key]
            if prec < min_prec:
                break
            self.next()
            # comparison against subquery / ANY / ALL
            if prec == CMP_PREC and self.at_op("(") and self.peek().kind == "ident" and self.peek().upper in ("SELECT", "WITH"):
                self.next()
                sub = self.select_stmt()
                self.expect_op(")")
                right = ast.SubqueryExpr(sub, "scalar")
            elif prec == CMP_PREC and self.at_kw("ANY", "SOME", "ALL"):
                mod = "any" if self.tok.upper in ("ANY", "SOME") else "all"
                self.next()
                self.expect_op("(")
                sub = self.select_stmt()
                self.expect_op(")")
                right = ast.SubqueryExpr(sub, mod)
            else:
                right = self.expr(prec + 1)
            left = ast.Call(fname, [left, right])
        return left

    def unary(self):
        if self.at_kw("NOT"):
            self.next()
            return ast.Call("not", [self.expr(4)])
        if self.at_op("!"):
            self.next()
            return ast.Call("not", [self.unary()])
        if self.at_op("-"):
            self.next()
            return ast.Call("unaryminus", [self.unary()])
        if self.at_op("+"):
            self.next()
            return self.unary()
        if self.at_op("~"):
            self.next()
            return ast.Call("bitneg", [self.unary()])
        return self.primary()

    def primary(self):
        t = self.tok
        if t.kind == "num":
            self.next()
            txt = t.text
            if "e" in txt.lower():
                return ast.Lit(float(txt), "float")
            if "." in txt:
                return ast.Lit(dec_from_string(txt), "dec")
            return ast.Lit(int(txt), "int")
        if t.kind == "str":
            self.next()
            return ast.Lit(t.text, "str")
        if t.kind == "hex":
            self.next()
            h = t.text
            if h[0] in "xX":
                h = h[2:-1]
            else:
                h = h[2:]
            return ast.Lit(bytes.fromhex(h if len(h) % 2 == 0 else "0" + h), "hex")
        if t.kind == "op":
            if self.try_op("("):
                if self.at_kw("SELECT", "WITH"):
                    sub = self.select_stmt()
                    self.expect_op(")")
                    return ast.SubqueryExpr(sub, "scalar")
                e = self.expr()
                if self.at_op(","):
                    items = [e]
                    while self.try_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    return ast.Call("row", items)
                self.expect_op(")")
                return e
            if self.try_op("?"):
                p = ast.Param(self.param_count)
                self.param_count += 1
                return p
        if t.kind == "sysvar":
            self.next()
            return ast.Name(parts=("@@" + t.text[2:].lower(),))
        if t.kind == "uservar":
            self.next()
            return ast.Name(parts=(t.text.lower(),))
        if t.kind in ("ident", "qident"):
            up = t.upper
            if up == "NULL":
                self.next()
                return ast.Lit(None, "null")
            if up == "TRUE":
                self.next()
                return ast.Lit(True, "bool")
            if up == "FALSE":
                self.next()
                return ast.Lit(False, "bool")
            if up in ("CURRENT_TIMESTAMP", "CURRENT_DATE", "CURRENT_TIME", "CURRENT_USER",
                      "LOCALTIME", "LOCALTIMESTAMP") and self.peek().text != "(":
                self.next()
                return ast.Call(up.lower(), [])
            if up == "CASE":
                return self.case_expr()
            if up == "CAST" or up == "CONVERT":
                return self.cast_expr()
            if up == "EXISTS":
                self.next()
                self.expect_op("(")
                sub = self.select_stmt()
                self.expect_op(")")
                return ast.SubqueryExpr(sub, "exists")
            if up == "INTERVAL":
                # INTERVAL(n, n1, n2, ...) the comparison function vs
                # INTERVAL <expr> <unit> date arithmetic — disambiguated
                # by a top-level comma inside the parens (MySQL grammar)
                if self.peek().kind == "op" and self.peek().text == "(":
                    depth, j = 0, self.i + 1
                    is_call = False
                    while j < len(self.toks):
                        t = self.toks[j]
                        if t.kind == "op" and t.text == "(":
                            depth += 1
                        elif t.kind == "op" and t.text == ")":
                            depth -= 1
                            if depth == 0:
                                break
                        elif t.kind == "op" and t.text == "," and depth == 1:
                            is_call = True
                            break
                        j += 1
                    if is_call:
                        return self.func_call()
                self.next()
                e = self.expr()
                unit = self.ident().lower()
                return ast.Interval(e, unit)
            if up == "BINARY":
                self.next()
                return ast.Call("binary", [self.unary()])
            if up == "DEFAULT" and self.peek().kind == "op" and self.peek().text != "(":
                self.next()
                return ast.Default()
            if up == "DATE" and self.peek().kind == "str":
                self.next()
                return ast.Lit(self.next().text, "str")
            # function call?
            if self.peek().kind == "op" and self.peek().text == "(":
                return self.func_call()
            # plain column ref (possibly qualified)
            name = self.ident()
            parts = [name]
            while self.at_op(".") and self.peek().kind in ("ident", "qident"):
                self.next()
                parts.append(self.ident())
            return ast.Name(parts=tuple(parts))
        self.fail("expected expression")

    def func_call(self):
        fname = self.ident().lower()
        self.expect_op("(")
        # unit-keyword first arguments (ref: parser.y TimestampDiff/Extract)
        if fname in ("timestampdiff", "timestampadd"):
            unit = self.ident().lower()
            self.expect_op(",")
            args = [ast.Lit(unit, "str"), self.expr()]
            self.expect_op(",")
            args.append(self.expr())
            self.expect_op(")")
            return ast.Call(fname, args)
        if fname == "extract":
            unit = self.ident().lower()
            self.expect_kw("FROM")
            args = [ast.Lit(unit, "str"), self.expr()]
            self.expect_op(")")
            return ast.Call(fname, args)
        distinct = False
        if self.try_kw("DISTINCT"):
            distinct = True
        args = []
        if self.at_op("*") and fname == "count":
            self.next()
            self.expect_op(")")
            return self._maybe_over(ast.Call("count", [ast.Star()], distinct=False))
        sep = None
        if not self.at_op(")"):
            args.append(self.expr())
            while self.try_op(","):
                args.append(self.expr())
            if fname == "group_concat" and self.try_kw("SEPARATOR"):
                sep = self.next().text
        self.expect_op(")")
        call = ast.Call(fname, args, distinct=distinct)
        if sep is not None:
            call.sep = sep
        return self._maybe_over(call)

    def _maybe_over(self, call: ast.Call) -> ast.Call:
        """OVER ([PARTITION BY ...] [ORDER BY ...] [frame]) with full
        ROWS/RANGE BETWEEN frame clauses (ref: parser.y WindowFrameClause,
        executor/pipelined_window.go:37)."""
        if not self.at_kw("OVER"):
            return call
        self.next()
        self.expect_op("(")
        part, order = [], []
        if self.try_kw("PARTITION"):
            self.expect_kw("BY")
            part.append(self.expr())
            while self.try_op(","):
                part.append(self.expr())
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            order = self.by_items()
        frame = None
        if self.at_kw("ROWS", "RANGE"):
            unit = self.next().upper.lower()
            if self.try_kw("BETWEEN"):
                start = self._frame_bound()
                self.expect_kw("AND")
                end = self._frame_bound()
            else:
                # single-bound form: <bound> .. CURRENT ROW
                start = self._frame_bound()
                end = ast.FrameBound("cur")
            frame = ast.FrameSpec(unit, start, end)
        self.expect_op(")")
        call.over = ast.WindowSpec(part, order, frame)
        return call

    def _frame_bound(self) -> ast.FrameBound:
        if self.try_kw("UNBOUNDED"):
            if self.try_kw("PRECEDING"):
                return ast.FrameBound("up")
            self.expect_kw("FOLLOWING")
            return ast.FrameBound("uf")
        if self.try_kw("CURRENT"):
            self.expect_kw("ROW")
            return ast.FrameBound("cur")
        e = self.expr()
        if self.try_kw("PRECEDING"):
            return ast.FrameBound("pre", e)
        self.expect_kw("FOLLOWING")
        return ast.FrameBound("fol", e)

    def case_expr(self):
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expr()
        whens = []
        while self.try_kw("WHEN"):
            c = self.expr()
            self.expect_kw("THEN")
            r = self.expr()
            whens.append((c, r))
        else_ = None
        if self.try_kw("ELSE"):
            else_ = self.expr()
        self.expect_kw("END")
        return ast.CaseWhen(operand, whens, else_)

    def cast_expr(self):
        kw = self.next().upper  # CAST or CONVERT
        self.expect_op("(")
        e = self.expr()
        if kw == "CAST":
            self.expect_kw("AS")
        else:
            self.expect_op(",")
        tname, targs, unsigned, _, _ = self.type_spec(cast_ctx=True)
        self.expect_op(")")
        return ast.Cast(e, tname, targs, unsigned)

    def type_spec(self, cast_ctx=False):
        name = self.ident().lower()
        if cast_ctx:
            name = {"signed": "bigint", "unsigned": "bigint", "integer": "bigint", "char": "varchar", "binary": "varbinary"}.get(name, name)
            unsigned_by_name = name == "bigint" and False
        args = ()
        elems = ()
        if self.try_op("("):
            if name in ("enum", "set"):
                vals = [self.tok.text]
                self.next()
                while self.try_op(","):
                    vals.append(self.tok.text)
                    self.next()
                elems = tuple(vals)
            else:
                nums = [int(self.next().text)]
                while self.try_op(","):
                    nums.append(int(self.next().text))
                args = tuple(nums)
            self.expect_op(")")
        unsigned = False
        while self.at_kw("UNSIGNED", "SIGNED", "ZEROFILL"):
            if self.tok.upper == "UNSIGNED":
                unsigned = True
            self.next()
        collate = ""
        if self.try_kw("CHARACTER"):
            self.expect_kw("SET")
            self.ident()
        if self.try_kw("COLLATE"):
            collate = self.ident().lower()
        return name, args, unsigned, elems, collate

    # --- DML ---------------------------------------------------------------

    def insert_stmt(self):
        replace = self.tok.upper == "REPLACE"
        self.next()
        ignore = self.try_kw("IGNORE")
        self.try_kw("INTO")
        tbl = self._table_name()
        cols = []
        if self.at_op("(") :
            self.next()
            cols = self.name_list()
            self.expect_op(")")
        node = ast.Insert(tbl, cols, [], replace=replace, ignore=ignore)
        if self.at_kw("VALUES", "VALUE"):
            self.next()
            while True:
                self.expect_op("(")
                row = []
                if not self.at_op(")"):
                    row.append(self.expr())
                    while self.try_op(","):
                        row.append(self.expr())
                self.expect_op(")")
                node.values.append(row)
                if not self.try_op(","):
                    break
        elif self.at_kw("SELECT", "WITH") or self.at_op("("):
            node.select = self.select_stmt()
        elif self.try_kw("SET"):
            exprs = []
            while True:
                col = self.ident()
                self.expect_op("=")
                node.columns.append(col)
                exprs.append(self.expr())
                if not self.try_op(","):
                    break
            node.values = [exprs]
        if self.try_kw("ON"):
            self.expect_kw("DUPLICATE")
            self.expect_kw("KEY")
            self.expect_kw("UPDATE")
            while True:
                col = self.ident()
                self.expect_op("=")
                node.on_dup.append((col, self.expr()))
                if not self.try_op(","):
                    break
        return node

    def _table_name(self) -> ast.TableName:
        db = None
        name = self.ident()
        if self.try_op("."):
            db, name = name, self.ident()
        return ast.TableName(db, name)

    def update_stmt(self):
        self.expect_kw("UPDATE")
        tbl = self.table_refs()
        self.expect_kw("SET")
        sets = []
        while True:
            parts = [self.ident()]
            while self.try_op("."):
                parts.append(self.ident())
            self.expect_op("=")
            sets.append((ast.Name(tuple(parts)), self.expr()))
            if not self.try_op(","):
                break
        node = ast.Update(tbl, sets)
        if self.try_kw("WHERE"):
            node.where = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            node.order_by = self.by_items()
        if self.try_kw("LIMIT"):
            node.limit, _ = self.limit_clause()
        return node

    def delete_stmt(self):
        self.expect_kw("DELETE")
        targets = None
        if not self.at_kw("FROM"):
            # multi-table form 1: DELETE t1[.*], t2[.*] FROM <table_refs>
            targets = [self._delete_target()]
            while self.try_op(","):
                targets.append(self._delete_target())
        self.expect_kw("FROM")
        tbl = self.table_refs()
        if self.at_kw("USING"):
            # multi-table form 2: DELETE FROM t1[, t2] USING <table_refs>
            if targets is not None:
                self.fail("USING not allowed after DELETE <tables> FROM")
            targets = []
            def leaves(n):
                if isinstance(n, ast.Join):
                    leaves(n.left)
                    leaves(n.right)
                elif isinstance(n, ast.TableName):
                    targets.append(n.name)
                else:
                    self.fail("expected table names before USING")
            leaves(tbl)
            self.next()
            tbl = self.table_refs()
        node = ast.Delete(tbl, targets=targets)
        if self.try_kw("WHERE"):
            node.where = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            node.order_by = self.by_items()
        if self.try_kw("LIMIT"):
            node.limit, _ = self.limit_clause()
        return node

    def lock_stmt(self):
        """LOCK TABLES t [AS alias] READ|WRITE [, ...] (ref: lock/lock.go)."""
        self.expect_kw("LOCK")
        self.expect_kw("TABLES") if self.at_kw("TABLES") else self.expect_kw("TABLE")
        items = []
        while True:
            tn = self._table_name()
            if self.try_kw("AS"):
                tn.alias = self.ident()
            if self.try_kw("READ"):
                mode = "READ"
            elif self.try_kw("WRITE"):
                mode = "WRITE"
            else:
                self.fail("expected READ or WRITE")
            items.append((tn, mode))
            if not self.try_op(","):
                break
        return ast.LockTables(items)

    def unlock_stmt(self):
        self.expect_kw("UNLOCK")
        self.expect_kw("TABLES") if self.at_kw("TABLES") else self.expect_kw("TABLE")
        return ast.UnlockTables()

    def _delete_target(self) -> str:
        """One DELETE target: name or name.* (qualifier form)."""
        name = self.ident()
        if self.try_op("."):
            self.expect_op("*")
        return name

    # --- DDL ---------------------------------------------------------------

    def user_spec(self) -> "ast.UserSpec":
        """'user'[@'host'] [IDENTIFIED BY 'pw'] (ref: parser user identity)."""
        t = self.next()
        if t.kind not in ("str", "ident", "qident"):
            self.fail("expected user name")
        host = "%"
        if self.tok.kind == "uservar":  # unquoted u@host lexes the host as @ident
            host = self.next().text[1:]
        elif self.try_op("@"):
            h = self.next()
            if h.kind not in ("str", "ident", "qident"):
                self.fail("expected host")
            host = h.text
        spec = ast.UserSpec(t.text, host)
        if self.try_kw("IDENTIFIED"):
            self.expect_kw("BY")
            pw = self.next()
            spec.password = pw.text
        return spec

    def _user_spec_list(self):
        specs = [self.user_spec()]
        while self.try_op(","):
            specs.append(self.user_spec())
        return specs

    def grant_stmt(self):
        kind = self.next().upper  # GRANT | REVOKE
        privs = []
        if self.try_kw("ALL"):
            self.try_kw("PRIVILEGES")
            privs = ["ALL"]
        else:
            while True:
                p = self.ident().upper()
                if p == "LOCK" and self.try_kw("TABLES"):
                    p = "LOCK TABLES"
                privs.append(p)
                if not self.try_op(","):
                    break
        self.expect_kw("ON")
        db = self.ident() if not self.at_op("*") else (self.next().text and "*")
        self.expect_op(".")
        tbl = self.ident() if not self.at_op("*") else (self.next().text and "*")
        self.expect_kw("TO") if kind == "GRANT" else self.expect_kw("FROM")
        users = self._user_spec_list()
        if kind == "GRANT":
            return ast.Grant(privs, db, tbl, users)
        return ast.Revoke(privs, db, tbl, users)

    def _binding_stmt(self, kind: str, global_: bool):
        """CREATE/DROP [GLOBAL] BINDING FOR <stmt> [USING <stmt>]
        (ref: bindinfo; the FOR/USING statements are captured as raw SQL
        spans so digests normalize identically to live queries)."""
        self.expect_kw("FOR")
        start = self.tok.pos
        self.statement()  # validate + advance
        if kind == "drop":
            end = self.tok.pos if not self.at("eof") else len(self.sql)
            return ast.DropBinding(self.sql[start:end].strip(), global_)
        using_tok = self.tok
        self.expect_kw("USING")
        for_sql = self.sql[start : using_tok.pos].strip()
        ustart = self.tok.pos
        self.statement()
        uend = self.tok.pos if not self.at("eof") else len(self.sql)
        return ast.CreateBinding(for_sql, self.sql[ustart:uend].strip(), global_)

    def create_stmt(self):
        self.expect_kw("CREATE")
        if self.at_kw("OR") and self.peek().upper == "REPLACE":
            self.next(); self.next()
            self.expect_kw("VIEW")
            return self._create_view(or_replace=True)
        if self.try_kw("VIEW"):
            return self._create_view(or_replace=False)
        g = self.try_kw("GLOBAL")
        if not g:
            self.try_kw("SESSION")
        if self.try_kw("BINDING"):
            return self._binding_stmt("create", g)
        if self.try_kw("USER"):
            ine = self._if_not_exists()
            return ast.CreateUser(self._user_spec_list(), ine)
        if self.try_kw("SEQUENCE"):
            ine = self._if_not_exists()
            tn = self._table_name()
            node = ast.CreateSequence(tn, if_not_exists=ine)
            while self.tok.kind == "ident":
                up = self.tok.upper
                if up == "START":
                    self.next()
                    self.try_kw("WITH")
                    node.start = self._int_bound()
                elif up == "INCREMENT":
                    self.next()
                    self.try_kw("BY")
                    node.increment = self._int_bound()
                elif up == "CACHE":
                    self.next()
                    node.cache = self._int_bound()
                elif up == "MAXVALUE":
                    self.next()
                    node.maxvalue = self._int_bound()
                elif up == "MINVALUE":
                    self.next()
                    node.minvalue = self._int_bound()
                elif up == "NOCACHE":
                    self.next()
                    node.cache = 1
                elif up == "CYCLE":
                    self.next()
                    node.cycle = True
                elif up in ("NOCYCLE", "NOMAXVALUE", "NOMINVALUE"):
                    self.next()
                else:
                    break
            return node
        if self.try_kw("RESOURCE"):
            self.expect_kw("GROUP")
            ine = self._if_not_exists()
            return ast.ResourceGroupDDL(
                "create", self.ident(), self._rg_options(), if_not_exists=ine
            )
        if self.at_kw("DATABASE", "SCHEMA"):
            self.next()
            ine = self._if_not_exists()
            name = self.ident()
            while not self.at("eof") and not self.at_op(";"):
                self.next()  # skip charset options
            return ast.CreateDatabase(name, ine)
        unique = self.try_kw("UNIQUE")
        if self.try_kw("INDEX"):
            iname = self.ident()
            self.expect_kw("ON")
            tbl = self._table_name()
            self.expect_op("(")
            cols = self.name_list()
            self.expect_op(")")
            return ast.CreateIndex(ast.IndexDef(iname, cols, unique=unique), tbl)
        temporary = self.try_kw("TEMPORARY")
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        tbl = self._table_name()
        node = ast.CreateTable(tbl, [], [], if_not_exists=ine, temporary=temporary)
        if self.try_kw("LIKE"):
            node.options["like"] = self._table_name()
            return node
        self.expect_op("(")
        while True:
            if self.at_kw("PRIMARY"):
                self.next()
                self.expect_kw("KEY")
                self.expect_op("(")
                cols = self._key_part_list()
                self.expect_op(")")
                node.indexes.append(ast.IndexDef("PRIMARY", cols, unique=True, primary=True))
            elif self.at_kw("UNIQUE"):
                self.next()
                self.try_kw("KEY") or self.try_kw("INDEX")
                iname = self.ident() if self.tok.kind in ("ident", "qident") and not self.at_op("(") else ""
                self.expect_op("(")
                cols = self._key_part_list()
                self.expect_op(")")
                node.indexes.append(ast.IndexDef(iname or f"uk_{len(node.indexes)}", cols, unique=True))
            elif self.at_kw("KEY", "INDEX"):
                self.next()
                iname = self.ident() if self.tok.kind in ("ident", "qident") and not self.at_op("(") else ""
                self.expect_op("(")
                cols = self._key_part_list()
                self.expect_op(")")
                node.indexes.append(ast.IndexDef(iname or f"idx_{len(node.indexes)}", cols))
            elif self.at_kw("CONSTRAINT", "FOREIGN", "CHECK"):
                # consume and ignore FK/CHECK constraints (parsed, not enforced)
                depth = 0
                while not self.at("eof"):
                    if self.at_op("(") :
                        depth += 1
                    elif self.at_op(")"):
                        if depth == 0:
                            break
                        depth -= 1
                    elif self.at_op(",") and depth == 0:
                        break
                    self.next()
            else:
                node.columns.append(self.column_def())
            if not self.try_op(","):
                break
        self.expect_op(")")
        # table options (the loop refuses PARTITION, parsed after it)
        while self.tok.kind == "ident" and not self.at_op(";") and not self.at_kw("PARTITION"):
            opt = self.ident().lower()
            if self.try_op("="):
                pass
            if self.tok.kind in ("ident", "qident", "num", "str"):
                node.options[opt] = self.next().text
            else:
                break
        if self.at_kw("PARTITION"):
            node.partition = self._partition_spec()
        return node

    def _list_in_values(self) -> tuple:
        """VALUES IN (n | NULL, ...) value tuple for LIST partitions."""
        self.expect_op("(")
        vals = []
        while True:
            if self.try_kw("NULL"):
                vals.append(None)
            else:
                vals.append(self._int_bound())
            if not self.try_op(","):
                break
        self.expect_op(")")
        return tuple(vals)

    def _partition_spec(self):
        """PARTITION BY HASH(col) PARTITIONS n
        | PARTITION BY RANGE (col) (PARTITION p VALUES LESS THAN (n|MAXVALUE), ...)
        | PARTITION BY LIST (col) (PARTITION p VALUES IN (n, ...), ...)"""
        self.expect_kw("PARTITION")
        self.expect_kw("BY")
        if self.try_kw("HASH"):
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            self.expect_kw("PARTITIONS")
            n = int(self.next().text)
            return ast.PartitionSpec("hash", col, count=n)
        if self.try_kw("LIST"):
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            self.expect_op("(")
            defs = []
            while True:
                self.expect_kw("PARTITION")
                name = self.ident()
                self.expect_kw("VALUES")
                self.expect_kw("IN")
                defs.append((name, self._list_in_values()))
                if not self.try_op(","):
                    break
            self.expect_op(")")
            return ast.PartitionSpec("list", col, defs=defs)
        self.expect_kw("RANGE")
        self.expect_op("(")
        col = self.ident()
        self.expect_op(")")
        self.expect_op("(")
        defs = []
        while True:
            self.expect_kw("PARTITION")
            name = self.ident()
            self.expect_kw("VALUES")
            self.expect_kw("LESS")
            self.expect_kw("THAN")
            if self.try_kw("MAXVALUE"):
                defs.append((name, None))
            else:
                self.expect_op("(")
                defs.append((name, self._int_bound()))
                self.expect_op(")")
            if not self.try_op(","):
                break
        self.expect_op(")")
        return ast.PartitionSpec("range", col, defs=defs)

    def _key_part_list(self):
        cols = []
        while True:
            c = self.ident()
            if self.try_op("("):  # prefix length — ignored
                self.next()
                self.expect_op(")")
            self.try_kw("ASC") or self.try_kw("DESC")
            cols.append(c)
            if not self.try_op(","):
                break
        return cols

    def column_def(self) -> ast.ColumnDef:
        name = self.ident()
        tname, targs, unsigned, elems, collate = self.type_spec()
        col = ast.ColumnDef(name, tname, targs, unsigned, elems=elems, collate=collate)
        while True:
            if self.try_kw("NOT"):
                self.expect_kw("NULL")
                col.not_null = True
            elif self.try_kw("NULL"):
                pass
            elif self.try_kw("DEFAULT"):
                if self.at_kw("CURRENT_TIMESTAMP", "NOW"):
                    self.next()
                    if self.try_op("("):
                        self.try_op(")") or (self.next(), self.expect_op(")"))
                    col.default = ast.Call("now", [])
                else:
                    col.default = self.unary()
            elif self.try_kw("AUTO_INCREMENT"):
                col.auto_increment = True
            elif self.try_kw("PRIMARY"):
                self.expect_kw("KEY")
                col.primary_key = True
            elif self.try_kw("UNIQUE"):
                self.try_kw("KEY")
                col.unique = True
            elif self.try_kw("KEY"):
                pass
            elif self.try_kw("COMMENT"):
                col.comment = self.next().text
            elif self.at_kw("COLLATE", "CHARACTER"):
                if self.next().upper == "CHARACTER":
                    self.expect_kw("SET")
                    self.ident()
                else:
                    col.collate = self.ident().lower()
            elif self.try_kw("ON"):
                self.expect_kw("UPDATE")
                self.unary()
                if self.try_op("("):
                    self.expect_op(")")
            else:
                break
        return col

    def _if_not_exists(self) -> bool:
        if self.try_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def drop_stmt(self):
        self.expect_kw("DROP")
        g = self.try_kw("GLOBAL")
        if not g:
            self.try_kw("SESSION")
        if self.try_kw("BINDING"):
            return self._binding_stmt("drop", g)
        if self.try_kw("USER"):
            ie = self._if_exists()
            return ast.DropUser(self._user_spec_list(), ie)
        if self.try_kw("SEQUENCE"):
            ie = self._if_exists()
            names = [self._table_name()]
            while self.try_op(","):
                names.append(self._table_name())
            return ast.DropSequence(names, ie)
        if self.try_kw("VIEW"):
            ie = self._if_exists()
            names = [self._table_name()]
            while self.try_op(","):
                names.append(self._table_name())
            return ast.DropView(names, ie)
        if self.try_kw("RESOURCE"):
            self.expect_kw("GROUP")
            ie = self._if_exists()
            return ast.ResourceGroupDDL("drop", self.ident(), {}, if_exists=ie)
        if self.at_kw("DATABASE", "SCHEMA"):
            self.next()
            ie = self._if_exists()
            return ast.DropDatabase(self.ident(), ie)
        if self.try_kw("INDEX"):
            iname = self.ident()
            self.expect_kw("ON")
            return ast.DropIndex(iname, self._table_name())
        self.expect_kw("TABLE")
        ie = self._if_exists()
        tbls = [self._table_name()]
        while self.try_op(","):
            tbls.append(self._table_name())
        return ast.DropTable(tbls, ie)

    def _if_exists(self) -> bool:
        if self.try_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def _rg_options(self) -> dict:
        """RU_PER_SEC = n | PRIORITY = LOW/MEDIUM/HIGH | BURSTABLE [= bool]
        | QUERY_LIMIT = (rules..., ACTION = ..., WATCH = '...') | QUERY_LIMIT = NULL
        (ref: parser.y ResourceGroupOptionList — the RU form plus the
        runaway QUERY_LIMIT option; the RAW mode's per-resource knobs
        have no meaning on one device mesh)."""
        spec: dict = {}
        while self.tok.kind == "ident":
            up = self.tok.upper
            if up == "RU_PER_SEC":
                self.next()
                self.try_op("=")
                spec["ru_per_sec"] = self._int_bound()
            elif up == "QUERY_LIMIT":
                self.next()
                self.try_op("=")
                spec["query_limit"] = self._rg_query_limit()
            elif up == "PRIORITY":
                self.next()
                self.try_op("=")
                p = self.ident().upper()
                if p not in ("LOW", "MEDIUM", "HIGH"):
                    self.fail(f"invalid resource group priority {p!r}")
                spec["priority"] = p
            elif up == "BURSTABLE":
                self.next()
                if self.try_op("="):
                    b = self.next().upper
                    if b in ("TRUE", "1", "ON"):
                        spec["burstable"] = True
                    elif b in ("FALSE", "0", "OFF"):
                        spec["burstable"] = False
                    else:
                        self.fail(f"invalid BURSTABLE value {b!r}")
                else:
                    spec["burstable"] = True
            else:
                break
            self.try_op(",")
        return spec

    def _rg_query_limit(self) -> dict:
        """QUERY_LIMIT = ( EXEC_ELAPSED='10s', RU=n, PROCESSED_ROWS=n,
        ACTION=DRYRUN|COOLDOWN|KILL, WATCH='60s' ) | NULL — the runaway
        watchdog spec (ref: parser.y ResourceGroupRunawayOptionList,
        WATCH collapsed to the EXACT-match digest form this store keys
        its watch list on). NULL (ALTER) clears; the parsed {} sentinel
        survives the DDL merge where None could not. Durations become
        milliseconds at parse time."""
        from ..sched.runaway import ACTIONS, parse_duration_ms

        if self.try_kw("NULL"):
            return {}
        self.expect_op("(")
        ql: dict = {}

        def dur() -> float:
            t = self.next()
            try:
                return parse_duration_ms(t.text)
            except ValueError as e:
                self.fail(str(e))

        while self.tok.kind == "ident":
            u = self.tok.upper
            if u == "EXEC_ELAPSED":
                self.next()
                self.try_op("=")
                ql["exec_elapsed_ms"] = dur()
            elif u == "RU":
                self.next()
                self.try_op("=")
                ql["ru"] = float(self._int_bound())
            elif u == "PROCESSED_ROWS":
                self.next()
                self.try_op("=")
                ql["processed_rows"] = self._int_bound()
            elif u == "ACTION":
                self.next()
                self.try_op("=")
                a = self.ident().upper()
                if a not in ACTIONS:
                    self.fail(f"invalid QUERY_LIMIT action {a!r}")
                ql["action"] = a
            elif u == "WATCH":
                self.next()
                self.try_op("=")
                ql["watch_ms"] = dur()
            else:
                self.fail(f"unknown QUERY_LIMIT option {self.tok.text!r}")
            self.try_op(",")
        self.expect_op(")")
        if not any(k in ql for k in ("exec_elapsed_ms", "ru", "processed_rows")):
            self.fail("QUERY_LIMIT needs at least one rule "
                      "(EXEC_ELAPSED / RU / PROCESSED_ROWS)")
        return ql

    def alter_stmt(self):
        self.expect_kw("ALTER")
        if self.try_kw("RESOURCE"):
            self.expect_kw("GROUP")
            return ast.ResourceGroupDDL("alter", self.ident(), self._rg_options())
        self.expect_kw("TABLE")
        tbl = self._table_name()
        actions = []
        while True:
            if self.try_kw("ADD"):
                if self.at_kw("PARTITION"):
                    self.next()
                    self.expect_op("(")
                    defs = []
                    while True:
                        self.expect_kw("PARTITION")
                        pname = self.ident()
                        self.expect_kw("VALUES")
                        if self.try_kw("IN"):  # LIST partition
                            defs.append((pname, ("in", self._list_in_values())))
                        else:
                            self.expect_kw("LESS")
                            self.expect_kw("THAN")
                            if self.try_kw("MAXVALUE"):
                                defs.append((pname, None))
                            else:
                                self.expect_op("(")
                                defs.append((pname, self._int_bound()))
                                self.expect_op(")")
                        if not self.try_op(","):
                            break
                    self.expect_op(")")
                    actions.append(("add_partition", defs))
                elif self.try_kw("INDEX") or self.try_kw("KEY"):
                    iname = self.ident() if not self.at_op("(") else ""
                    self.expect_op("(")
                    cols = self._key_part_list()
                    self.expect_op(")")
                    actions.append(("add_index", ast.IndexDef(iname or "idx", cols)))
                elif self.try_kw("UNIQUE"):
                    self.try_kw("INDEX") or self.try_kw("KEY")
                    iname = self.ident() if not self.at_op("(") else ""
                    self.expect_op("(")
                    cols = self._key_part_list()
                    self.expect_op(")")
                    actions.append(("add_index", ast.IndexDef(iname or "uk", cols, unique=True)))
                elif self.try_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    self.expect_op("(")
                    cols = self._key_part_list()
                    self.expect_op(")")
                    actions.append(("add_index", ast.IndexDef("PRIMARY", cols, unique=True, primary=True)))
                else:
                    self.try_kw("COLUMN")
                    actions.append(("add_column", self.column_def()))
            elif self.try_kw("DROP"):
                if self.at_kw("PARTITION"):
                    self.next()
                    actions.append(("drop_partition", self._partition_name_list()))
                elif self.try_kw("INDEX") or self.try_kw("KEY"):
                    actions.append(("drop_index", self.ident()))
                elif self.try_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    actions.append(("drop_index", "PRIMARY"))
                else:
                    self.try_kw("COLUMN")
                    actions.append(("drop_column", self.ident()))
            elif self.at_kw("TRUNCATE"):
                self.next()
                self.expect_kw("PARTITION")
                actions.append(("truncate_partition", self._partition_name_list()))
            elif self.try_kw("MODIFY"):
                self.try_kw("COLUMN")
                actions.append(("modify_column", self.column_def()))
            elif self.try_kw("RENAME"):
                self.try_kw("TO") or self.try_kw("AS")
                actions.append(("rename", self._table_name()))
            else:
                self.fail("unsupported ALTER action")
            if not self.try_op(","):
                break
        return ast.AlterTable(tbl, actions)

    def _create_view(self, or_replace: bool):
        """CREATE [OR REPLACE] VIEW v [(cols)] AS <select> — the SELECT is
        stored as SQL text and re-planned at reference time (ref:
        ddl_api.go CreateView; plans always see the current schema)."""
        tn = self._table_name()
        cols = []
        if self.try_op("("):
            cols = self.name_list()
            self.expect_op(")")
        self.expect_kw("AS")
        start = self.tok.pos
        self.select_stmt()  # validate + advance
        end = self.tok.pos if not self.at("eof") else len(self.sql)
        return ast.CreateView(tn, cols, self.sql[start:end].strip(), or_replace)

    def _str_lit(self, what: str) -> str:
        t = self.tok
        if t.kind != "str":
            self.fail(f"expected {what} string literal")
        self.next()
        return t.text

    def _int_bound(self) -> int:
        """Integer partition bound; non-integer bounds are a parse error,
        not a Python exception."""
        neg = bool(self.try_op("-"))
        t = self.tok
        if t.kind != "num" or not t.text.lstrip("-").isdigit():
            self.fail("expected integer partition bound")
        self.next()
        return -int(t.text) if neg else int(t.text)

    _ALTER_ACTION_KWS = {"ADD", "DROP", "MODIFY", "RENAME", "TRUNCATE", "CHANGE"}

    def _partition_name_list(self) -> list[str]:
        """Partition idents; a comma followed by an action keyword ends
        the list (the actions loop owns that comma)."""
        names = [self.ident()]
        while self.at_op(",") and self.peek().kind == "ident" and self.peek().upper not in self._ALTER_ACTION_KWS:
            self.next()
            names.append(self.ident())
        return names

    def truncate_stmt(self):
        self.expect_kw("TRUNCATE")
        self.try_kw("TABLE")
        return ast.TruncateTable(self._table_name())

    def rename_stmt(self):
        self.expect_kw("RENAME")
        self.expect_kw("TABLE")
        old = self._table_name()
        self.expect_kw("TO")
        new = self._table_name()
        return ast.AlterTable(old, [("rename", new)])

    # --- session / admin ----------------------------------------------------

    def begin_stmt(self):
        if self.tok.upper == "START":
            self.next()
            self.expect_kw("TRANSACTION")
        else:
            self.next()
        mode = ""
        if self.try_kw("PESSIMISTIC"):
            mode = "pessimistic"
        elif self.try_kw("OPTIMISTIC"):
            mode = "optimistic"
        return ast.Begin(mode)

    def set_stmt(self):
        self.expect_kw("SET")
        if self.try_kw("NAMES"):
            self.next()
            return ast.SetStmt([])
        if self.at_kw("RESOURCE") and self.peek().upper == "GROUP":
            self.next()
            self.next()
            return ast.SetResourceGroup(self.ident())
        assignments = []
        while True:
            scope = "session"
            if self.try_kw("GLOBAL"):
                scope = "global"
            elif self.try_kw("SESSION") or self.try_kw("LOCAL"):
                scope = "session"
            t = self.tok
            if t.kind == "sysvar":
                self.next()
                name = t.text[2:].lower()
                if name.startswith("global."):
                    scope, name = "global", name[7:]
                elif name.startswith("session."):
                    name = name[8:]
            elif t.kind == "uservar":
                self.next()
                name = t.text
            else:
                name = self.ident().lower()
            self.try_op("=") or self.try_op(":=") or self.fail("expected =")
            if self.at_kw("ON", "OFF") and self.peek().kind in ("op", "eof") and (self.peek().text in (",", ";", "")):
                val = ast.Lit(self.next().text, "str")
            else:
                val = self.expr()
            assignments.append((scope, name, val))
            if not self.try_op(","):
                break
        return ast.SetStmt(assignments)

    def show_stmt(self):
        self.expect_kw("SHOW")
        full = self.try_kw("FULL")
        glob = self.try_kw("GLOBAL")
        self.try_kw("SESSION")
        node = ast.Show("", full=full, global_scope=glob)
        if self.try_kw("TABLES"):
            node.kind = "tables"
            if self.try_kw("FROM") or self.try_kw("IN"):
                node.target = self.ident()
        elif self.try_kw("DATABASES") or self.try_kw("SCHEMAS"):
            node.kind = "databases"
        elif self.try_kw("BINDINGS"):
            node.kind = "bindings"
        elif self.try_kw("RESOURCE"):
            self.expect_kw("GROUPS")
            node.kind = "resource_groups"
        elif self.try_kw("GRANTS"):
            node.kind = "grants"
            if self.try_kw("FOR"):
                node.target = self.user_spec()
        elif self.try_kw("CREATE"):
            self.expect_kw("TABLE")
            node.kind = "create_table"
            node.target = self._table_name()
        elif self.try_kw("STATS_META"):
            node.kind = "stats_meta"
        elif self.try_kw("STATS_HISTOGRAMS"):
            node.kind = "stats_histograms"
        elif self.try_kw("VARIABLES"):
            node.kind = "variables"
        elif self.try_kw("COLUMNS") or self.try_kw("FIELDS"):
            node.kind = "columns"
            self.try_kw("FROM") or self.try_kw("IN")
            node.target = self._table_name()
        elif self.try_kw("INDEX") or self.try_kw("INDEXES") or self.try_kw("KEYS"):
            node.kind = "index"
            self.try_kw("FROM") or self.try_kw("IN")
            node.target = self._table_name()
        elif self.try_kw("STATUS"):
            node.kind = "status"
        elif self.try_kw("WARNINGS"):
            node.kind = "warnings"
        elif self.try_kw("PROCESSLIST"):
            node.kind = "processlist"
        elif self.try_kw("ENGINES"):
            node.kind = "engines"
        elif self.try_kw("COLLATION"):
            node.kind = "collation"
        elif self.try_kw("CHARSET") or (self.try_kw("CHARACTER") and self.expect_kw("SET")):
            node.kind = "charset"
        elif self.try_kw("BINDINGS"):
            node.kind = "bindings"
        elif self.try_kw("GRANTS"):
            node.kind = "grants"
            while not self.at("eof") and not self.at_op(";"):
                self.next()
        elif self.try_kw("STATS_META"):
            node.kind = "stats_meta"
        elif self.try_kw("STATS_HISTOGRAMS"):
            node.kind = "stats_histograms"
        elif self.try_kw("TABLE"):
            self.expect_kw("STATUS")
            node.kind = "table_status"
        else:
            self.fail("unsupported SHOW")
        if self.try_kw("LIKE"):
            node.like = self.expr()
        elif self.try_kw("WHERE"):
            node.where = self.expr()
        return node

    def trace_stmt(self):
        """TRACE [FORMAT = 'row'] <stmt> (ref: executor/trace.go TraceExec:
        renders the statement's span tree as rows)."""
        self.expect_kw("TRACE")
        if self.try_kw("FORMAT"):
            self.expect_op("=")
            fmt = self._str_lit("trace format")
            if fmt.lower() != "row":
                self.fail(f"unsupported TRACE format {fmt!r} (only 'row')")
        return ast.TraceStmt(self.statement())

    def explain_stmt(self):
        self.next()
        analyze = self.try_kw("ANALYZE")
        fmt = "row"
        if self.try_kw("FORMAT"):
            self.expect_op("=")
            fmt = self.next().text.lower()
        if self.at_kw("SELECT", "INSERT", "UPDATE", "DELETE", "REPLACE", "WITH") or self.at_op("("):
            start = self.tok.pos
            inner = self.statement()
            end = self.tok.pos if not self.at("eof") else len(self.sql)
            node = ast.Explain(inner, analyze=analyze, format=fmt)
            node.inner_sql = self.sql[start:end].strip()
            return node
        # EXPLAIN <table> == DESC <table>
        return ast.Show("columns", target=self._table_name())

    def desc_stmt(self):
        self.next()
        if self.at_kw("SELECT", "INSERT", "UPDATE", "DELETE", "WITH"):
            return ast.Explain(self.statement())
        return ast.Show("columns", target=self._table_name())

    def use_stmt(self):
        self.expect_kw("USE")
        return ast.UseDB(self.ident())

    def analyze_stmt(self):
        self.expect_kw("ANALYZE")
        self.expect_kw("TABLE")
        tbls = [self._table_name()]
        while self.try_op(","):
            tbls.append(self._table_name())
        return ast.AnalyzeTable(tbls)

    def prepare_stmt(self):
        self.expect_kw("PREPARE")
        name = self.ident()
        self.expect_kw("FROM")
        t = self.next()
        if t.kind == "uservar":
            return ast.Prepare(name, None, from_var=t.text.lower())
        if t.kind != "str":
            self.fail("PREPARE ... FROM expects a string literal or @user_var")
        return ast.Prepare(name, t.text)  # str tokens are already unquoted

    def execute_stmt(self):
        self.expect_kw("EXECUTE")
        name = self.ident()
        using = []
        if self.try_kw("USING"):
            while True:
                t = self.next()
                if t.kind != "uservar":
                    self.fail("EXECUTE ... USING expects @user_var arguments")
                using.append(t.text)
                if not self.try_op(","):
                    break
        return ast.Execute(name, using)

    def deallocate_stmt(self):
        self.expect_kw("DEALLOCATE")
        self.expect_kw("PREPARE")
        return ast.Deallocate(self.ident())

    def admin_stmt(self):
        self.expect_kw("ADMIN")
        if self.try_kw("CHECK"):
            self.expect_kw("TABLE")
            return ast.AdminStmt("check_table", self._table_name())
        if self.try_kw("CHECKSUM"):
            self.expect_kw("TABLE")
            return ast.AdminStmt("checksum_table", self._table_name())
        if self.try_kw("SHOW"):
            if self.try_kw("DDL"):
                if self.try_kw("JOBS"):
                    return ast.AdminStmt("show_ddl_jobs")
                return ast.AdminStmt("show_ddl")
        if self.try_kw("CANCEL"):
            self.expect_kw("DDL")
            self.expect_kw("JOBS")
            ids = [int(self.next().text)]
            while self.try_op(","):
                ids.append(int(self.next().text))
            return ast.AdminStmt("cancel_ddl_jobs", ids)
        if self.try_kw("RECOVER"):
            self.expect_kw("INDEX")
            tbl = self._table_name()
            idx = self.ident()
            return ast.AdminStmt("recover_index", (tbl, idx))
        if self.try_kw("CLEANUP"):
            self.expect_kw("INDEX")
            tbl = self._table_name()
            idx = self.ident()
            return ast.AdminStmt("cleanup_index", (tbl, idx))
        if self.try_kw("PROMOTE"):
            # ADMIN PROMOTE: flip a warm standby read-write (PR 14)
            return ast.AdminStmt("promote")
        if self.try_kw("REJOIN"):
            # ADMIN REJOIN: rebuild a fenced old primary as a standby of the
            # promoted new primary (PR 17)
            return ast.AdminStmt("rejoin")
        self.fail("unsupported ADMIN")

    def kill_stmt(self):
        self.expect_kw("KILL")
        self.try_kw("TIDB") or self.try_kw("CONNECTION")
        qo = self.try_kw("QUERY")
        return ast.KillStmt(int(self.next().text), query_only=qo)

    def flush_stmt(self):
        self.expect_kw("FLUSH")
        what = []
        while not self.at("eof") and not self.at_op(";"):
            what.append(self.next().text)
        return ast.FlushStmt(" ".join(what))

    def load_stmt(self):
        self.expect_kw("LOAD")
        if self.try_kw("STATS"):
            # LOAD STATS 'dump.json' (ref: executor/load_stats.go)
            t = self.next()
            if t.kind != "str":
                self.fail("expected stats dump path string")
            return ast.LoadStats(t.text)
        self.expect_kw("DATA")
        self.try_kw("LOCAL")
        self.expect_kw("INFILE")
        path = self.next().text
        self.try_kw("IGNORE") or self.try_kw("REPLACE")
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        tbl = self._table_name()
        node = ast.LoadData(path, tbl)
        if self.try_kw("FIELDS") or self.try_kw("COLUMNS"):
            if self.try_kw("TERMINATED"):
                self.expect_kw("BY")
                node.fields_terminated = self.next().text
            if self.try_kw("ENCLOSED") or (self.try_kw("OPTIONALLY") and self.expect_kw("ENCLOSED")):
                self.expect_kw("BY")
                node.enclosed = self.next().text
        if self.try_kw("LINES"):
            self.expect_kw("TERMINATED")
            self.expect_kw("BY")
            node.lines_terminated = self.next().text
        if self.try_kw("IGNORE"):
            node.ignore_lines = int(self.next().text)
            self.try_kw("LINES") or self.try_kw("ROWS")
        if self.try_op("("):
            node.columns = self.name_list()
            self.expect_op(")")
        if self.try_kw("WITH"):
            # TiDB LOAD DATA options: WITH bulk_ingest=1, batch_size=4096
            while True:
                name = self.next().text.lower()
                self.expect_op("=")
                node.options[name] = self.next().text
                if not self.try_op(","):
                    break
        return node

    def split_stmt(self):
        self.expect_kw("SPLIT")
        self.expect_kw("TABLE")
        tbl = self._table_name()
        node = ast.SplitRegion(tbl)
        if self.try_kw("BETWEEN"):
            self.expect_op("(")
            lo = [self.expr()]
            while self.try_op(","):
                lo.append(self.expr())
            self.expect_op(")")
            self.expect_kw("AND")
            self.expect_op("(")
            hi = [self.expr()]
            while self.try_op(","):
                hi.append(self.expr())
            self.expect_op(")")
            self.expect_kw("REGIONS")
            node.between = (lo, hi, int(self.next().text))
        elif self.try_kw("BY"):
            while self.try_op("("):
                vals = [self.expr()]
                while self.try_op(","):
                    vals.append(self.expr())
                self.expect_op(")")
                node.by.append(vals)
                if not self.try_op(","):
                    break
        return node

    def brie_stmt(self):
        kind = self.next().upper.lower()
        node = ast.BRIEStmt(kind)
        if self.try_kw("DATABASE"):
            if self.try_op("*"):
                pass
            else:
                node.databases.append(self.ident())
                while self.try_op(","):
                    node.databases.append(self.ident())
        self.expect_kw("TO") if kind == "backup" else self.expect_kw("FROM")
        node.storage = self.next().text
        return node

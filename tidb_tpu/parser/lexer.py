"""SQL lexer — regex scanner (ref: pingcap/parser lexer.go, fresh design)."""

from __future__ import annotations

import re
from dataclasses import dataclass

from ..errors import ParseError

TOKEN_RE = re.compile(
    r"""
    (?P<hint>/\*\+.*?\*/)
  | (?P<ws>\s+|\#[^\n]*|--\s[^\n]*|/\*.*?\*/)
  | (?P<hex>0[xX][0-9a-fA-F]+|[xX]'[0-9a-fA-F]*')
  | (?P<num>(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?)
  | (?P<str>'(?:[^'\\]|\\.|'')*'|"(?:[^"\\]|\\.|"")*")
  | (?P<qident>`(?:[^`]|``)*`)
  | (?P<ident>[A-Za-z_\$][A-Za-z0-9_\$]*)
  | (?P<sysvar>@@(?:global\.|session\.)?[A-Za-z_][A-Za-z0-9_]*)
  | (?P<uservar>@[A-Za-z0-9_\.\$]+)
  | (?P<op><=>|<<|>>|!=|<>|<=|>=|:=|\|\||&&|[-+*/%=<>(),.;!~&|^?{}\[\]:@])
    """,
    re.X | re.S,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "0": "\0", "b": "\b", "Z": "\x1a", "\\": "\\", "'": "'", '"': '"', "%": "\\%", "_": "\\_"}


@dataclass
class Token:
    kind: str  # ident | qident | num | hex | str | op | sysvar | uservar | eof
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def _unquote_string(s: str) -> str:
    q = s[0]
    body = s[1:-1].replace(q + q, q)
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            out.append(_ESCAPES.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = TOKEN_RE.match(sql, pos)
        if not m:
            raise ParseError(f"unexpected character {sql[pos]!r} at offset {pos}")
        kind = m.lastgroup
        text = m.group()
        if kind != "ws":
            if kind == "str":
                text = _unquote_string(text)
            elif kind == "qident":
                text = text[1:-1].replace("``", "`")
            toks.append(Token(kind, text, pos))
        pos = m.end()
    toks.append(Token("eof", "", n))
    return toks

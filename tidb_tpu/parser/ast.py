"""SQL AST nodes (ref: pingcap/parser ast package — fresh design).

Nodes are plain dataclasses; the planner walks them. Every expression node
carries no type — typing happens at plan-build (name resolution) time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


# --- expressions -----------------------------------------------------------


@dataclass
class Lit:
    """Literal: int, Dec, float, str, bytes, None (NULL), bool."""

    value: Any
    kind: str  # 'int' | 'dec' | 'float' | 'str' | 'hex' | 'null' | 'bool'


@dataclass
class Name:
    """Column reference: [db.][table.]column; '*' handled by Star."""

    parts: tuple  # (col,) or (tbl, col) or (db, tbl, col)

    @property
    def column(self) -> str:
        return self.parts[-1]

    @property
    def table(self) -> str | None:
        return self.parts[-2] if len(self.parts) >= 2 else None


@dataclass
class Star:
    table: str | None = None  # t.* keeps the qualifier


@dataclass
class FrameBound:
    """One window frame edge (ref: parser ast FrameBound).
    kind: 'up' UNBOUNDED PRECEDING | 'pre' n PRECEDING | 'cur' CURRENT ROW
        | 'fol' n FOLLOWING | 'uf' UNBOUNDED FOLLOWING."""

    kind: str
    offset: Any = None  # expr for 'pre'/'fol'


@dataclass
class FrameSpec:
    """ROWS/RANGE frame clause (ref: parser ast FrameClause)."""

    unit: str  # 'rows' | 'range'
    start: FrameBound
    end: FrameBound


@dataclass
class WindowSpec:
    """OVER (...) clause (ref: parser ast WindowSpec)."""

    partition_by: list
    order_by: list  # ByItem
    frame: FrameSpec | None = None


@dataclass
class Call:
    """Function call, incl. operators desugared to calls (plus, eq, ...)."""

    name: str
    args: list
    distinct: bool = False  # COUNT(DISTINCT x)
    over: Any = None  # WindowSpec for window function calls


@dataclass
class CaseWhen:
    operand: Any  # CASE <operand> WHEN ... or None for searched CASE
    whens: list  # [(cond, result), ...]
    else_: Any = None


@dataclass
class Cast:
    expr: Any
    type_name: str
    type_args: tuple = ()
    unsigned: bool = False


@dataclass
class SubqueryExpr:
    select: "Select"
    modifier: str = "scalar"  # 'scalar' | 'exists' | 'in' | 'any' | 'all'


@dataclass
class Param:
    """Prepared-statement placeholder '?' (ordinal)."""

    index: int


@dataclass
class Default:
    """DEFAULT keyword in INSERT/UPDATE value position."""


@dataclass
class Interval:
    expr: Any
    unit: str  # 'day' | 'month' | 'year' | ...


# --- table references ------------------------------------------------------


@dataclass
class TableName:
    db: str | None
    name: str
    alias: str | None = None
    index_hints: list = field(default_factory=list)
    as_of: Any = None  # AS OF TIMESTAMP expr (ref: stale read)


@dataclass
class SubqueryTable:
    select: "Select"
    alias: str


@dataclass
class Join:
    left: Any
    right: Any
    kind: str  # 'inner' | 'left' | 'right' | 'cross'
    on: Any = None
    using: list = field(default_factory=list)
    straight: bool = False  # STRAIGHT_JOIN: written order is pinned


# --- statements ------------------------------------------------------------


@dataclass
class CTEDef:
    """One WITH-clause table (ref: parser ast CommonTableExpression)."""

    name: str
    cols: list  # optional explicit column names
    select: Any  # Select | SetOpSelect


@dataclass
class WithClause:
    recursive: bool
    ctes: list  # [CTEDef]


@dataclass
class SelectField:
    expr: Any
    alias: str | None = None


@dataclass
class ByItem:
    expr: Any
    desc: bool = False


@dataclass
class Select:
    fields: list  # [SelectField | Star]
    from_: Any = None  # TableName | Join | SubqueryTable | None
    where: Any = None
    group_by: list = field(default_factory=list)
    having: Any = None
    order_by: list = field(default_factory=list)  # [ByItem]
    limit: Any = None  # int expr or None
    offset: Any = None
    distinct: bool = False
    for_update: bool = False
    lock_in_share: bool = False
    windows: list = field(default_factory=list)
    setop: Any = None  # ('union'|'union all'|..., Select) chained
    with_: Any = None  # WithClause
    hints: list = field(default_factory=list)  # [(NAME, [args])]
    into_outfile: str | None = None  # SELECT ... INTO OUTFILE
    outfile_fsep: str = "\t"
    outfile_lsep: str = "\n"
    as_of: Any = None  # AS OF TIMESTAMP expr (stale read), hoisted from FROM


@dataclass
class SetOpSelect:
    """UNION / UNION ALL / EXCEPT / INTERSECT chain."""

    selects: list  # [Select]
    ops: list  # between selects: 'union' | 'union_all' | ...
    order_by: list = field(default_factory=list)
    limit: Any = None
    offset: Any = None
    with_: Any = None  # WithClause
    into_outfile: str | None = None  # hoisted from the last branch
    outfile_fsep: str = "\t"
    outfile_lsep: str = "\n"


@dataclass
class Insert:
    table: TableName
    columns: list  # [str] or []
    values: list  # [[expr,...], ...]
    select: Any = None  # INSERT ... SELECT
    on_dup: list = field(default_factory=list)  # [(col, expr)]
    replace: bool = False
    ignore: bool = False


@dataclass
class Update:
    table: Any  # TableName or Join
    sets: list  # [(Name, expr)]
    where: Any = None
    order_by: list = field(default_factory=list)
    limit: Any = None


@dataclass
class Delete:
    table: Any
    where: Any = None
    order_by: list = field(default_factory=list)
    limit: Any = None
    targets: list | None = None  # multi-table: names/aliases to delete from


@dataclass
class ColumnDef:
    name: str
    type_name: str
    type_args: tuple = ()
    unsigned: bool = False
    not_null: bool = False
    default: Any = None
    auto_increment: bool = False
    primary_key: bool = False
    unique: bool = False
    comment: str = ""
    elems: tuple = ()
    collate: str = ""


@dataclass
class IndexDef:
    name: str
    columns: list  # [str]
    unique: bool = False
    primary: bool = False


@dataclass
class PartitionSpec:
    type: str  # 'hash' | 'range'
    col: str
    count: int = 0  # hash partition count
    defs: list = field(default_factory=list)  # [(name, bound_int | None)]


@dataclass
class CreateTable:
    table: TableName
    columns: list  # [ColumnDef]
    indexes: list  # [IndexDef]
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)
    partition: PartitionSpec | None = None
    temporary: bool = False  # session-local, shadows permanent names


@dataclass
class DropTable:
    tables: list
    if_exists: bool = False


@dataclass
class TruncateTable:
    table: TableName


@dataclass
class CreateIndex:
    index: IndexDef
    table: TableName


@dataclass
class DropIndex:
    name: str
    table: TableName


@dataclass
class AlterTable:
    table: TableName
    actions: list  # [('add_column', ColumnDef) | ('drop_column', str) | ('add_index', IndexDef) | ('drop_index', str) | ('rename', TableName) | ('modify_column', ColumnDef)]


@dataclass
class CreateDatabase:
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabase:
    name: str
    if_exists: bool = False


@dataclass
class UseDB:
    name: str


@dataclass
class Begin:
    mode: str = ""  # '' (session default) | 'pessimistic' | 'optimistic'


@dataclass
class Commit:
    pass


@dataclass
class Rollback:
    pass


@dataclass
class SetStmt:
    assignments: list  # [(scope, name, expr)] scope in {'session','global'}


@dataclass
class Show:
    kind: str  # 'tables' | 'databases' | 'create_table' | 'variables' | 'columns' | 'index' | 'status' | 'warnings' | 'processlist'
    target: Any = None
    like: Any = None
    where: Any = None
    full: bool = False
    global_scope: bool = False


@dataclass
class Explain:
    stmt: Any
    analyze: bool = False
    format: str = "row"


@dataclass
class AnalyzeTable:
    tables: list


@dataclass
class Prepare:
    name: str
    sql: str | None
    from_var: str | None = None  # PREPARE name FROM @var


@dataclass
class Execute:
    name: str
    using: list = field(default_factory=list)


@dataclass
class Deallocate:
    name: str


@dataclass
class AdminStmt:
    kind: str  # 'check_table' | 'show_ddl' | 'show_ddl_jobs' | 'checksum_table' | 'cancel_ddl_jobs' | 'recover_index'
    target: Any = None


@dataclass
class CreateView:
    table: Any  # TableName
    cols: list  # optional explicit column names
    select_sql: str  # stored definition text
    or_replace: bool = False


@dataclass
class DropView:
    names: list  # [TableName]
    if_exists: bool = False


@dataclass
class CreateSequence:
    table: Any  # TableName (sequences share the table namespace)
    start: int = 1
    increment: int = 1
    cache: int = 1000
    maxvalue: int | None = None
    minvalue: int | None = None
    cycle: bool = False
    if_not_exists: bool = False


@dataclass
class DropSequence:
    names: list  # [TableName]
    if_exists: bool = False


@dataclass
class ResourceGroupDDL:
    """CREATE/ALTER/DROP RESOURCE GROUP (ref: ast ResourceGroupStmt;
    `spec` holds only the options the statement named — ALTER merges)."""

    kind: str  # 'create' | 'alter' | 'drop'
    name: str
    spec: dict = field(default_factory=dict)  # ru_per_sec / priority / burstable
    if_not_exists: bool = False
    if_exists: bool = False


@dataclass
class SetResourceGroup:
    """SET RESOURCE GROUP name — rebind the session mid-flight
    (ref: ast.SetResourceGroupStmt)."""

    name: str


@dataclass
class LoadStats:
    path: str


@dataclass
class LockTables:
    tables: list  # [(TableName, 'READ'|'WRITE')]


@dataclass
class UnlockTables:
    pass


@dataclass
class TraceStmt:
    stmt: Any  # traced inner statement


@dataclass
class KillStmt:
    conn_id: int
    query_only: bool = False


@dataclass
class FlushStmt:
    what: str = ""


@dataclass
class LoadData:
    path: str
    table: TableName
    fields_terminated: str = "\t"
    lines_terminated: str = "\n"
    enclosed: str = ""
    ignore_lines: int = 0
    columns: list = field(default_factory=list)
    # WITH key=value options (TiDB LOAD DATA ... WITH syntax):
    # bulk_ingest=0|1 overrides the tidb_bulk_ingest sysvar per
    # statement; batch_size=N sizes the legacy path's txn batches
    options: dict = field(default_factory=dict)


@dataclass
class SplitRegion:
    table: TableName
    between: tuple | None = None  # (lower expr list, upper expr list, regions int)
    by: list = field(default_factory=list)


@dataclass
class CreateBinding:
    for_sql: str
    using_sql: str
    global_: bool = True


@dataclass
class DropBinding:
    for_sql: str
    global_: bool = True


@dataclass
class UserSpec:
    user: str
    host: str = "%"
    password: str | None = None

    @property
    def key(self) -> str:
        return f"{self.user}@{self.host}"


@dataclass
class CreateUser:
    users: list  # [UserSpec]
    if_not_exists: bool = False


@dataclass
class DropUser:
    users: list
    if_exists: bool = False


@dataclass
class Grant:
    privs: list  # ['ALL'] or ['SELECT', ...]
    db: str  # '*' for global
    table: str  # '*' (table granularity folds into db level)
    users: list  # [UserSpec]


@dataclass
class Revoke:
    privs: list
    db: str
    table: str
    users: list


@dataclass
class BRIEStmt:
    kind: str  # 'backup' | 'restore'
    storage: str = ""
    databases: list = field(default_factory=list)

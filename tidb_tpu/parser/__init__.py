from .parser import parse, parse_one, ParseError
from . import ast

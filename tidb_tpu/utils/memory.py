"""Memory quota tracker (ref: util/memory/tracker.go:54 tracker tree +
action.go:29 action chain). One tracker per statement, consuming at
chunk-materialization points; exceeding tidb_mem_quota_query fires the
cancel action (MemoryQuotaExceeded, MySQL's OOM-kill analog)."""

from __future__ import annotations

import threading

from ..errors import MemoryQuotaExceeded


class MemTracker:
    def __init__(self, quota: int = 0, label: str = "query"):
        self.quota = quota  # 0 = unlimited
        self.label = label
        self.consumed = 0
        self.max_consumed = 0
        self._lock = threading.Lock()

    def consume(self, nbytes: int) -> None:
        with self._lock:
            self.consumed += nbytes
            if self.consumed > self.max_consumed:
                self.max_consumed = self.consumed
            if self.quota and self.consumed > self.quota:
                raise MemoryQuotaExceeded(
                    f"Out Of Memory Quota! [{self.label}] consumed {self.consumed} > quota {self.quota}"
                )

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.consumed = max(0, self.consumed - nbytes)


def chunk_bytes(chunk) -> int:
    n = 0
    for col in chunk.columns:
        data = col.data
        if getattr(data, "dtype", None) is not None and data.dtype == object:
            m = len(data)
            if m > 4096:
                # big object lanes: estimate from a stride sample — a full
                # per-element pass costs more than the query it guards
                sample = data[:: max(1, m // 4096)]
                sb = sum(
                    len(x) if isinstance(x, (str, bytes)) else 8
                    for x in sample
                    if x is not None
                )
                n += int(sb * (m / max(len(sample), 1))) + m
            else:
                n += sum(len(x) if isinstance(x, (str, bytes)) else 8 for x in data if x is not None)
                n += m
        else:
            n += getattr(data, "nbytes", 0)
        n += getattr(col.valid, "nbytes", 0)
    return n

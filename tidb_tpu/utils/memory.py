"""Memory quota tracker tree + server-level arbitration (ref:
util/memory/tracker.go:54 tracker tree + action.go:29 action chain +
util/servermemorylimit — the three-layer protection the reference runs:
per-statement quota cancel, server soft-limit actions, and a server hard
limit that kills the TOP consumer instead of whoever allocates next).

Layout: one `MemTracker` per statement, attached under its session's
tracker, attached under the store's `ServerMemTracker` (`Storage.mem`).
`consume` at chunk-materialization points propagates up the chain; each
layer owns its action:

  * statement — exceeding tidb_mem_quota_query raises
    MemoryQuotaExceeded (the classic OOM-kill analog, unchanged);
  * server soft limit (tidb_server_memory_limit ×
    tidb_memory_usage_alarm_ratio) — DEGRADE, not cancel: `engine='auto'`
    cop tasks reroute to the host engine (device h2d would only deepen
    the pressure) and the tile caches drop their column batches AND
    device mirrors (the biggest reclaimable pools);
  * server hard limit (tidb_server_memory_limit) — the arbiter kills the
    TOP-consuming statement through the scheduler's shared interrupt
    gate (sched.scheduler.raise_if_interrupted): the victim's session is
    flagged with reason "oom" and escapes at its next checkpoint, while
    innocent allocators proceed.

Device transfers (tpu_engine h2d/d2h) consume into the statement tracker
through a thread-local binding (`bind`/`consume_current`): the cop pool
and the launch batcher run engine work on threads where contextvars are
wrong by construction, the same reason utils/tracing carries its own TLS.
Transfer bytes are a VOLUME proxy, not a resident-set measure — they
unwind with the statement at `detach()`, which releases everything the
statement still holds from every ancestor (tree accounting can never
leak into the global tracker).
"""

from __future__ import annotations

import threading
import time
import weakref

from ..errors import MemoryQuotaExceeded, ServerMemoryExceeded


class MemTracker:
    """One node of the tracker tree. `quota` 0 = unlimited (still
    tracked: the parent chain needs the bytes either way)."""

    def __init__(self, quota: int = 0, label: str = "query", parent: "MemTracker | None" = None,
                 session=None):
        self.quota = quota
        self.label = label
        self.parent = parent
        self.session = session  # statement trackers: the owning session
        self.sql = ""  # statement trackers: sample text for OOM events
        self.consumed = 0
        self.max_consumed = 0
        self._dead = False  # detached: late consumes become no-ops
        self._lock = threading.Lock()
        root = self
        while root.parent is not None:
            root = root.parent
        self.root = root

    def _add(self, nbytes: int) -> bool | None:
        """Charge this node; returns True when the node is now over its
        own quota, or None when the node is DEAD (detached concurrently
        — the TOCTOU between consume's entry check and detach: the node
        absorbed nothing, so the caller must stop before charging
        ancestors bytes that can never unwind). Never raises: every
        ancestor must receive the bytes before any quota verdict, or
        detach() would later subtract bytes an ancestor never saw and
        erase OTHER statements' accounting."""
        with self._lock:
            if self._dead:
                return None
            self.consumed += nbytes
            if self.consumed > self.max_consumed:
                self.max_consumed = self.consumed
            return bool(nbytes > 0 and self.quota and self.consumed > self.quota)

    def consume(self, nbytes: int) -> None:
        """Charge this tracker and every ancestor, THEN act: the
        innermost breached quota fires first (statement cancel beats
        server arbitration, like the reference's action-chain ordering);
        otherwise the root arbitrates with the allocating leaf
        identified, so a hard-limit breach can kill the top consumer
        instead of this allocator.

        The whole up-chain walk runs under the LEAF's lock (every walk —
        consume/release/detach — starts by taking it, and lock order is
        strictly child→parent), so a concurrent detach can never snapshot
        a leaf charge that hasn't reached the ancestors yet: a straggler
        either completes its walk before detach unwinds it, or sees
        `_dead` and drops its bytes entirely — the 'tree accounting never
        leaks into the global tracker' invariant."""
        exceeded = None
        with self._lock:
            if self._dead:
                # a cop-pool worker outliving its abandoned stream: the
                # statement already detached — charging now would inflate
                # the session/server trackers forever (nothing unwinds
                # after detach)
                return
            self.consumed += nbytes
            if self.consumed > self.max_consumed:
                self.max_consumed = self.consumed
            if nbytes > 0 and self.quota and self.consumed > self.quota:
                exceeded = self
            t = self.parent
            while t is not None:
                if t._add(nbytes) and exceeded is None:
                    exceeded = t
                t = t.parent
        if exceeded is not None:
            raise MemoryQuotaExceeded(
                f"Out Of Memory Quota! [{exceeded.label}] consumed "
                f"{exceeded.consumed} > quota {exceeded.quota}"
            )
        root = self.root
        if root is not self and isinstance(root, ServerMemTracker):
            root.arbitrate(self)

    def release(self, nbytes: int) -> None:
        with self._lock:
            if self._dead:
                return
            self.consumed = max(0, self.consumed - nbytes)
            t = self.parent
            while t is not None:
                with t._lock:
                    t.consumed = max(0, t.consumed - nbytes)
                t = t.parent
        root = self.root
        if root is not self and isinstance(root, ServerMemTracker):
            root.settle()

    def detach(self) -> None:
        """Statement teardown: return everything still held to every
        ancestor and drop out of the arbiter's registry. After this the
        statement's footprint is zero at every layer — success, KILL and
        BackoffExhausted unwind identically through the one finally.
        Runs under the leaf lock like every walk (see consume): in-flight
        stragglers have either fully propagated (we unwind their bytes
        here) or will see `_dead` and drop."""
        with self._lock:
            self._dead = True
            left = self.consumed
            self.consumed = 0
            t = self.parent
            while t is not None:
                with t._lock:
                    t.consumed = max(0, t.consumed - left)
                t = t.parent
        root = self.root
        if root is not self and isinstance(root, ServerMemTracker):
            root.forget(self)


class ServerMemTracker(MemTracker):
    """The per-store root: `Storage.mem`. Holds the server limit, the
    alarm ratio, the registry of LIVE statement trackers (the kill
    candidates), the degradation flag the cop client routes on, and the
    ops history the MEMORY_USAGE_OPS_HISTORY memtable reads."""

    EVENTS_CAP = 256

    def __init__(self):
        super().__init__(0, "server")
        self.limit = 0  # tidb_server_memory_limit; 0 = unlimited
        self.alarm_ratio = 0.8  # tidb_memory_usage_alarm_ratio
        self.degraded = False
        self._stmts: list = []  # weakrefs to live statement trackers
        self._caches: list = []  # weakrefs to evictable tile caches
        self._killing = None  # weakref to the victim currently unwinding
        self._reg_lock = threading.Lock()
        from collections import deque

        self.events: "deque" = deque(maxlen=self.EVENTS_CAP)

    # --- configuration (SET GLOBAL side effects) ---------------------------

    def set_limit(self, limit: int) -> None:
        from . import metrics as M

        self.limit = max(0, int(limit))
        M.SERVER_MEM_LIMIT.set(float(self.limit))
        self.settle()

    def set_alarm_ratio(self, ratio: float) -> None:
        self.alarm_ratio = min(max(float(ratio), 0.0), 1.0)
        self.settle()

    # --- registries --------------------------------------------------------

    def attach_statement(self, t: MemTracker) -> None:
        with self._reg_lock:
            self._stmts.append(weakref.ref(t))

    def forget(self, t: MemTracker) -> None:
        with self._reg_lock:
            self._stmts = [r for r in self._stmts if r() is not None and r() is not t]
            k = self._killing
            if k is not None and k() is t:
                self._killing = None
                # the victim statement ended before observing its kill:
                # cancel the flag, or it would spuriously kill the
                # session's NEXT statement. Flagging happens under this
                # same lock (arbitrate), so there is no window where an
                # unobserved oom flag survives its target. A user KILL
                # (no "oom" reason) is left alone.
                sess = t.session
                if sess is not None and getattr(sess, "_kill_reason", None) == "oom":
                    sess._killed = False
                    sess._kill_reason = None
        self.settle()

    def statements(self) -> list[MemTracker]:
        with self._reg_lock:
            return [t for t in (r() for r in self._stmts) if t is not None]

    def register_cache(self, cache) -> None:
        """Register an evictable cache (needs an `evict_all()`); held by
        weakref so short-lived embedded clients don't accumulate."""
        with self._reg_lock:
            self._caches = [r for r in self._caches if r() is not None]
            self._caches.append(weakref.ref(cache))

    # --- arbitration -------------------------------------------------------

    def _event(self, op: str, **kv) -> None:
        self.events.append({"time": time.time(), "op": op,
                            "consumed": self.consumed, "limit": self.limit, **kv})

    def arbitrate(self, origin: MemTracker) -> None:
        """Called after `origin`'s consume reached this root. Soft limit:
        flip degraded + evict caches once per excursion. Hard limit: kill
        the top consumer — at most one victim in flight (its unwind must
        land before a second kill, or pressure spikes would massacre the
        whole process), and when the top consumer IS the allocator it
        fails right here instead of waiting for its own checkpoint."""
        from . import metrics as M

        L = self.limit
        c = self.consumed
        if not L:
            return  # feature off: not even a gauge touch on the hot path
        M.SERVER_MEM_CONSUMED.set(float(c))
        soft = L * self.alarm_ratio
        if c > soft:
            # transition under the lock: concurrent allocators crossing
            # the ratio together must produce ONE degrade action, not a
            # double event/metric and two eviction sweeps
            fire = False
            with self._reg_lock:
                if not self.degraded:
                    self.degraded = True
                    fire = True
                caches = [r() for r in self._caches] if fire else []
            if fire:
                freed = 0.0
                for cache in caches:
                    if cache is not None:
                        # evict_all reports real bytes RELEASED FOR
                        # COLLECTION (host lanes + compressed mirror wire
                        # bytes, no padded-tile estimates); batches still
                        # pinned by in-flight tasks free when they finish
                        freed += float(cache.evict_all() or 0)
                self._event("degrade",
                            detail=f"soft limit {int(soft)} exceeded",
                            dropped=int(freed))
                M.SERVER_MEM_ACTIONS.inc(action="degrade")
        if c <= L:
            return
        with self._reg_lock:
            k = self._killing
            kt = k() if k is not None else None
            if kt is not None:
                if kt is origin:
                    # the victim itself is allocating AGAIN mid-unwind
                    # (e.g. the batcher's serial fallback re-running the
                    # killed leader): it must stay dead, or a recorded
                    # kill quietly completes — same verdict, no second
                    # event
                    raise ServerMemoryExceeded(
                        f"Out Of Memory Quota! server memory limit {L} "
                        f"exceeded; statement [{origin.label}] was already "
                        f"killed and may not allocate further "
                        f"(tidb_server_memory_limit)"
                    )
                if origin.consumed > L:
                    # the allocator ALONE breaches the limit: it needs no
                    # arbitration (failing it reclaims its own bytes, no
                    # innocent involved) — a second memory bomb must not
                    # slip through another victim's unwind window
                    self._event("kill", victim=origin.label,
                                victim_sql=origin.sql,
                                victim_bytes=origin.consumed)
                    M.SERVER_MEM_ACTIONS.inc(action="kill")
                    raise ServerMemoryExceeded(
                        f"Out Of Memory Quota! server memory limit {L} "
                        f"exceeded; statement [{origin.label}] alone holds "
                        f"{origin.consumed} bytes and was killed "
                        f"(tidb_server_memory_limit)"
                    )
                return  # another victim is unwinding; ride it out
            # re-read under the lock: a victim may have unwound between
            # the breach snapshot and here — killing on the stale total
            # would execute an innocent while real consumption is fine
            c = self.consumed
            if c <= L:
                return
            victims = [t for t in (r() for r in self._stmts) if t is not None]
            if not victims:
                return
            if sum(t.consumed for t in victims) <= L:
                # the overage lives in UNREGISTERED transient volume (a
                # grouped launch's shared uploads): the statements
                # collectively fit under the limit, so executing one
                # reclaims nothing — ride the transient out (degrade
                # already fired above)
                return
            top = max(victims, key=lambda t: t.consumed)
            # one victim at a time on BOTH paths: the in-place raise
            # below is also a kill in flight, and without the marker a
            # concurrent small allocator would re-kill the dying
            # statement (duplicate events + a re-flagged session)
            self._killing = weakref.ref(top)
            if top is not origin:
                # flag the victim UNDER the registry lock: forget() (the
                # victim's teardown) takes the same lock, so a kill can
                # never land after its target statement already ended
                sess = top.session
                if sess is not None:
                    sess._kill_reason = "oom"
                    sess._killed = True
        if top is origin:
            self._event("kill", victim=origin.label, victim_sql=origin.sql,
                        victim_bytes=origin.consumed)
            M.SERVER_MEM_ACTIONS.inc(action="kill")
            raise ServerMemoryExceeded(
                f"Out Of Memory Quota! server memory limit {L} exceeded "
                f"(consumed {c}); statement [{origin.label}] is the top consumer "
                f"({origin.consumed} bytes) and was killed (tidb_server_memory_limit)"
            )
        # the victim escapes at its next shared-interrupt-gate checkpoint
        # (chunk boundary, admission wait, backoff sleep) with the oom
        # reason, not a generic KILL; event/metric recorded off-lock
        self._event("kill", victim=top.label, victim_sql=top.sql,
                    victim_bytes=top.consumed)
        M.SERVER_MEM_ACTIONS.inc(action="kill")

    def settle(self) -> None:
        """Release-side check: leave degraded mode once consumption falls
        back under the soft limit (with a small hysteresis so one chunk
        released at the boundary doesn't flap the flag)."""
        from . import metrics as M

        if not self.limit and not self.degraded:
            return  # feature off: keep release() gauge-free too
        M.SERVER_MEM_CONSUMED.set(float(self.consumed))
        if not self.degraded:
            return
        soft = self.limit * self.alarm_ratio
        if self.limit == 0 or self.consumed < soft * 0.9:
            with self._reg_lock:
                if not self.degraded:
                    return  # a releasing sibling already recovered
                self.degraded = False
            self._event("recover", detail="consumption back under the soft limit")
            M.SERVER_MEM_ACTIONS.inc(action="recover")


# --- per-thread statement-tracker binding (the cop/engine seam) -------------

_TLS = threading.local()


class bind:
    """Bind `tracker` (may be None) to this thread for a task's duration;
    the TPU engine's transfer accounting consumes through it."""

    __slots__ = ("tracker", "prev")

    def __init__(self, tracker: MemTracker | None):
        self.tracker = tracker

    def __enter__(self):
        self.prev = getattr(_TLS, "tracker", None)
        _TLS.tracker = self.tracker
        return self.tracker

    def __exit__(self, *exc):
        _TLS.tracker = self.prev
        return False


def current_tracker() -> MemTracker | None:
    return getattr(_TLS, "tracker", None)


def consume_current(nbytes: int) -> None:
    """Charge the thread's bound statement tracker (no-op unbound). May
    raise: a quota/server-limit breach at a device transfer is a real
    allocation failure, not a device fault — classify_device_error passes
    TiDBError through untouched."""
    t = getattr(_TLS, "tracker", None)
    if t is not None and nbytes:
        t.consume(int(nbytes))


def chunk_bytes(chunk) -> int:
    n = 0
    for col in chunk.columns:
        data = col.data
        if getattr(data, "dtype", None) is not None and data.dtype == object:
            m = len(data)
            if m > 4096:
                # big object lanes: estimate from a stride sample — a full
                # per-element pass costs more than the query it guards
                sample = data[:: max(1, m // 4096)]
                sb = sum(
                    len(x) if isinstance(x, (str, bytes)) else 8
                    for x in sample
                    if x is not None
                )
                n += int(sb * (m / max(len(sample), 1))) + m
            else:
                n += sum(len(x) if isinstance(x, (str, bytes)) else 8 for x in data if x is not None)
                n += m
        else:
            n += getattr(data, "nbytes", 0)
        n += getattr(col.valid, "nbytes", 0)
    return n

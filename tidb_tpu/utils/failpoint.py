"""Failpoint framework — conditional fault-injection sites
(ref: pingcap/failpoint; the reference compiles `failpoint.Inject` sites
into 94 files and enables them per test via Makefile failpoint-enable.
Here sites are always present and zero-cost when disarmed)."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class Failpoints:
    def __init__(self):
        self._active: dict[str, object] = {}
        self._hits: dict[str, int] = {}
        self._lock = threading.Lock()

    def enable(self, name: str, action) -> None:
        """action: an Exception instance (raised at the site), a callable
        (invoked at the site), or ("sleep", seconds)."""
        with self._lock:
            self._active[name] = action
            self._hits[name] = 0  # fresh count per arm cycle

    def disable(self, name: str) -> None:
        with self._lock:
            self._active.pop(name, None)

    def disable_all(self) -> None:
        with self._lock:
            self._active.clear()
            self._hits.clear()

    def hits(self, name: str) -> int:
        return self._hits.get(name, 0)

    def inject(self, name: str) -> None:
        """The site call: no-op unless armed."""
        action = self._active.get(name)
        if action is None:
            return
        with self._lock:
            self._hits[name] = self._hits.get(name, 0) + 1
        if isinstance(action, BaseException):
            raise action
        if isinstance(action, type) and issubclass(action, BaseException):
            raise action()
        if isinstance(action, tuple) and action and action[0] == "sleep":
            time.sleep(action[1])
            return
        if callable(action):
            action()

    @contextmanager
    def enabled(self, name: str, action):
        self.enable(name, action)
        try:
            yield self
        finally:
            self.disable(name)


FP = Failpoints()


def inject(name: str) -> None:
    FP.inject(name)

"""Failpoint framework — conditional fault-injection sites
(ref: pingcap/failpoint; the reference compiles `failpoint.Inject` sites
into 94 files and enables them per test via Makefile failpoint-enable.
Here sites are always present and zero-cost when disarmed).

Actions an armed site can carry:
  * an Exception instance or class — raised at the site
  * a callable — invoked at the site
  * ("sleep", seconds) — blocks the site
  * ("crash", [exit_code]) — hard-kills the process via os._exit (no
    atexit, no flush — the closest in-process stand-in for SIGKILL;
    the crashpoint harness tools/crashpoint.py arms this at named
    sites inside a CHILD process and the parent checks recovery)
  * ("prob", p, action) — fires `action` with probability p per hit
    (the chaos-harness marker: 30%-probability device faults, random
    region churn)
  * ("nth", n, action) — fires `action` on every n-th hit (hit counts
    reset when the site is re-armed), for "fail exactly between step A
    and step B" regression tests
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager


class Failpoints:
    def __init__(self):
        self._active: dict[str, object] = {}
        self._hits: dict[str, int] = {}
        self._lock = threading.Lock()
        self._rng = random.Random()

    def enable(self, name: str, action) -> None:
        """action: see the module docstring for the accepted shapes."""
        with self._lock:
            self._active[name] = action
            self._hits[name] = 0  # fresh count per arm cycle

    def disable(self, name: str) -> None:
        with self._lock:
            self._active.pop(name, None)

    def disable_all(self) -> None:
        with self._lock:
            self._active.clear()
            self._hits.clear()

    def seed(self, n: int) -> None:
        """Deterministic ("prob", ...) firing for reproducible chaos runs."""
        with self._lock:
            self._rng.seed(n)

    def hits(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)

    def armed(self, name: str) -> bool:
        """Is the site armed at all? The cheap state gate for rules that
        model a continuous condition (a black-holed link is black-holed
        for every byte while armed) rather than a per-hit decision."""
        with self._lock:
            return name in self._active

    def decide(self, name: str):
        """Resolve an armed site WITHOUT firing: returns the resolved
        action value, or None when the site is disarmed (or this hit's
        prob/nth decision says no). Hit counting and the conditional
        decision happen under the same lock as inject(). A bare
        ("prob", p) / ("nth", n) tuple resolves to True — the
        decision-rule shape netchaos arms (`should this frame drop?`);
        a carried action resolves to the action itself so the caller
        can _fire() it (crashpoint composing a ("crash",) at a chaos
        site)."""
        with self._lock:
            action = self._active.get(name)
            if action is None:
                return None
            hits = self._hits.get(name, 0) + 1
            self._hits[name] = hits
            if isinstance(action, tuple) and action:
                if action[0] == "prob":
                    if self._rng.random() >= action[1]:
                        return None
                    return action[2] if len(action) > 2 else True
                if action[0] == "nth":
                    if hits % action[1] != 0:
                        return None
                    return action[2] if len(action) > 2 else True
            return action

    def rand(self) -> float:
        """One draw from the seeded chaos RNG (jittered delays stay
        reproducible under FP.seed)."""
        with self._lock:
            return self._rng.random()

    def inject(self, name: str) -> None:
        """The site call: no-op unless armed. The action lookup, hit-count
        bump and conditional-firing decision happen under ONE lock hold —
        a concurrent disable_all between the read and the count can no
        longer resurrect the hit entry, and the nth counter can't race."""
        with self._lock:
            action = self._active.get(name)
            if action is None:
                return
            hits = self._hits.get(name, 0) + 1
            self._hits[name] = hits
            if isinstance(action, tuple) and action:
                if action[0] == "prob":
                    if self._rng.random() >= action[1]:
                        return
                    action = action[2]
                elif action[0] == "nth":
                    if hits % action[1] != 0:
                        return
                    action = action[2]
        # fire OUTSIDE the lock: sleeps and callables may block or re-enter
        self._fire(action)

    @staticmethod
    def _fire(action) -> None:
        if isinstance(action, BaseException):
            raise action
        if isinstance(action, type) and issubclass(action, BaseException):
            raise action()
        if isinstance(action, tuple) and action and action[0] == "sleep":
            time.sleep(action[1])
            return
        if isinstance(action, tuple) and action and action[0] == "crash":
            os._exit(action[1] if len(action) > 1 else 137)
        if callable(action):
            action()

    @contextmanager
    def enabled(self, name: str, action):
        self.enable(name, action)
        try:
            yield self
        finally:
            self.disable(name)


FP = Failpoints()


def inject(name: str) -> None:
    FP.inject(name)

"""Metrics registry — Prometheus-style counters/histograms
(ref: metrics/metrics.go registry + per-subsystem files; exposed at
/metrics by server/http_status.go:115)."""

from __future__ import annotations

import threading
from collections import defaultdict

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)


def _esc(v) -> str:
    """Prometheus text-format label-value escaping (exposition format
    §label values: backslash, double-quote and newline must be escaped)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key) -> str:
    return ",".join(f'{k}="{_esc(val)}"' for k, val in key)


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._v = defaultdict(float)  # label tuple → value
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._v[key] += n

    def value(self, **labels) -> float:
        # .get, not [..]: a defaultdict read INSERTS the missing key, so
        # an unlocked probe could grow the dict mid-render (and the
        # registry's lock-free iteration would see a changed dict); the
        # lock makes the read coherent with concurrent inc()
        with self._lock:
            return self._v.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum over every label set — the 'how many, regardless of why'
        read consumers like the inspection memtable want."""
        with self._lock:
            return sum(self._v.values())

    def value_matching(self, **labels) -> float:
        """Sum over every label set CONTAINING the given pairs — the
        partial-match read for counters that carry extra dimensions
        (e.g. value_matching(outcome="follower") sums across reasons)."""
        want = set(labels.items())
        with self._lock:
            return sum(v for key, v in self._v.items() if want.issubset(key))

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # a concurrent inc() may insert a new label set
            items = sorted(self._v.items())
        for key, v in items:
            lbl = _fmt_labels(key)
            out.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return out


class Gauge:
    """Settable point-in-time value (queue depths, in-flight counts)."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._v = defaultdict(float)  # label tuple → value
        self._lock = threading.Lock()

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._v[tuple(sorted(labels.items()))] = v

    def add(self, n: float = 1.0, **labels) -> None:
        with self._lock:
            self._v[tuple(sorted(labels.items()))] += n

    def value(self, **labels) -> float:
        # .get under the lock, like Counter.value: the defaultdict read
        # would otherwise insert the key and race a concurrent render
        with self._lock:
            return self._v.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._v.items())
        for key, v in items:
            lbl = _fmt_labels(key)
            out.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return out


class Histogram:
    """Histogram with optional labels: `observe(v)` feeds the base
    (unlabeled) series; `observe(v, resource_group="g")` feeds that label
    set's shard INSTEAD — label sets partition the observations exactly
    like Counter labels do, so consumers that sum a metric across its
    label instances (metrics_summary, MetricsHistory.base_rates) stay
    correct. The base series renders only while it has samples or no
    shards exist (a labeled histogram exposes labeled children only)."""

    def __init__(self, name: str, help_: str, buckets: tuple = _BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = buckets
        self._lock = threading.Lock()
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._n = 0
        # label tuple → [counts, sum, n]
        self._shards: dict[tuple, list] = {}

    def _observe_into(self, counts: list, v: float) -> None:
        for i, b in enumerate(self.buckets):
            if v <= b:
                counts[i] += 1
                return
        counts[-1] += 1

    def observe(self, v: float, **labels) -> None:
        with self._lock:
            if labels:
                key = tuple(sorted(labels.items()))
                shard = self._shards.get(key)
                if shard is None:
                    shard = self._shards[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
                shard[1] += v
                shard[2] += 1
                self._observe_into(shard[0], v)
            else:
                self._sum += v
                self._n += 1
                self._observe_into(self._counts, v)

    def _render_series(self, out: list[str], counts: list, total_sum: float,
                       n: int, lbl: str) -> None:
        sep = "," if lbl else ""
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            out.append(f'{self.name}_bucket{{le="{b}"{sep}{lbl}}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"{sep}{lbl}}} {n}')
        suffix = f"{{{lbl}}}" if lbl else ""
        out.append(f"{self.name}_sum{suffix} {total_sum}")
        out.append(f"{self.name}_count{suffix} {n}")

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            if self._n or not self._shards:
                self._render_series(out, self._counts, self._sum, self._n, "")
            for key in sorted(self._shards):
                counts, s, n = self._shards[key]
                self._render_series(out, counts, s, n, _fmt_labels(key))
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_)
                self._metrics[name] = m
            return m

    def histogram(self, name: str, help_: str = "", buckets: tuple = _BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            return m

    def _snapshot(self) -> list:
        """Metrics in name order, snapshotted under the registry lock —
        a reader must not iterate `_metrics` while a first-use
        counter()/gauge() call inserts into it."""
        with self._lock:
            return sorted(self._metrics.items())

    def render(self) -> str:
        lines: list[str] = []
        for _name, m in self._snapshot():
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def rows(self) -> list[tuple[str, str, float]]:
        """Flat (metric, labels, value) rows for the METRICS memtable."""
        out = []
        for name, m in self._snapshot():
            if isinstance(m, (Counter, Gauge)):
                # under the metric's lock: inc() can insert a label set
                # while this reader iterates
                with m._lock:
                    items = sorted(m._v.items())
                for key, v in items:
                    out.append((name, ",".join(f"{k}={val}" for k, val in key), v))
            else:
                # under the histogram's lock: observe() can insert a new
                # label shard while a metrics reader iterates
                with m._lock:
                    if m._n or not m._shards:
                        out.append((name + "_count", "", float(m._n)))
                        out.append((name + "_sum", "", m._sum))
                    for key in sorted(m._shards):
                        _, s, n = m._shards[key]
                        lbl = ",".join(f"{k}={val}" for k, val in key)
                        out.append((name + "_count", lbl, float(n)))
                        out.append((name + "_sum", lbl, s))
        return out


REGISTRY = Registry()


class MetricsHistory:
    """Time-windowed metric samples — the METRICS_SCHEMA stand-in for the
    reference's PromQL range queries (ref: infoschema/metric_table_def.go,
    metrics_schema.go). A ring of (wall ts, {series: value}) snapshots;
    `metrics_summary` aggregates avg/min/max and counter RATES over the
    retained window. Sampling is on-demand with a min interval (no
    background thread to leak): every reader tick records at most one
    snapshot per SAMPLE_EVERY seconds."""

    SAMPLE_EVERY = 5.0
    CAPACITY = 720  # ~1h at the 5s cadence

    def __init__(self, registry: Registry):
        self.registry = registry
        self._ring: list[tuple[float, dict]] = []
        self._lock = threading.Lock()

    def tick(self, now: float | None = None) -> None:
        import time as _t

        now = _t.time() if now is None else now
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self.SAMPLE_EVERY:
                return
            snap = {f"{n}{{{l}}}" if l else n: v for n, l, v in self.registry.rows()}
            self._ring.append((now, snap))
            if len(self._ring) > self.CAPACITY:
                del self._ring[: len(self._ring) - self.CAPACITY]

    def base_rates(self) -> dict[str, float]:
        """Per-second rate of each BASE metric (labels summed) over the
        retained window — first→last delta / span."""
        self.tick()
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return {}

        def base_sums(snap: dict) -> dict[str, float]:
            out: dict[str, float] = {}
            for k, v in snap.items():
                base = k.split("{", 1)[0]
                out[base] = out.get(base, 0.0) + v
            return out

        first_ts, first = ring[0][0], base_sums(ring[0][1])
        last_ts, last = ring[-1][0], base_sums(ring[-1][1])
        span = last_ts - first_ts
        if span <= 0:
            return {}
        return {k: (last.get(k, 0.0) - first.get(k, 0.0)) / span for k in last}

    def summary(self) -> list[tuple[str, float, float, float, float, float]]:
        """[(series, now_value, avg, min, max, rate_per_sec)] over the
        retained window; rate derives from first→last counter delta."""
        self.tick()
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return []
        series: dict[str, list[tuple[float, float]]] = {}
        for ts, snap in ring:
            for k, v in snap.items():
                series.setdefault(k, []).append((ts, v))
        out = []
        for k in sorted(series):
            pts = series[k]
            vals = [v for _, v in pts]
            span = pts[-1][0] - pts[0][0]
            rate = (vals[-1] - vals[0]) / span if span > 0 else 0.0
            out.append((k, vals[-1], sum(vals) / len(vals), min(vals), max(vals), rate))
        return out


HISTORY = MetricsHistory(REGISTRY)

# core series (ref: metrics/{session,executor,distsql,ddl}.go)
QUERY_TOTAL = REGISTRY.counter("tidb_query_total", "queries by statement type and result")
# also sharded per resource_group label (PR 5): per-group latency SLOs
QUERY_DURATION = REGISTRY.histogram("tidb_query_duration_seconds", "statement wall time")
COP_TASKS = REGISTRY.counter("tidb_cop_tasks_total", "coprocessor tasks by engine")
TXN_TOTAL = REGISTRY.counter("tidb_txn_total", "transaction outcomes")
DDL_JOBS = REGISTRY.counter("tidb_ddl_jobs_total", "DDL jobs by type and state")

# resource-control series (ref: metrics/resourcemanager.go + the
# resource-group RU counters of the reference's resource_control)
SCHED_TASKS = REGISTRY.counter(
    "tidb_sched_tasks_total", "cop tasks through the admission scheduler by outcome"
)
SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "tidb_sched_queue_depth", "cop tasks currently waiting for admission"
)
SCHED_WAIT = REGISTRY.histogram(
    "tidb_sched_wait_seconds", "admission wait time per cop task"
)
SCHED_BATCH_OCCUPANCY = REGISTRY.histogram(
    "tidb_sched_batch_occupancy", "cop tasks coalesced per device launch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)
RU_CONSUMED = REGISTRY.counter(
    "tidb_resource_group_ru_total", "request units consumed per resource group"
)

# fault-tolerance series (ref: metrics/tikvclient.go backoff counters; the
# breaker is this reproduction's addition for the accelerator path)
COP_RETRIES = REGISTRY.counter(
    "tidb_cop_retries_total", "cop-task backoff retries by error class"
)
COP_BACKOFF = REGISTRY.histogram(
    "tidb_cop_backoff_seconds", "per-retry backoff sleep on the cop path"
)
BREAKER_STATE = REGISTRY.gauge(
    "tidb_tpu_breaker_state", "TPU engine circuit breaker state (0 closed, 1 half-open, 2 open)"
)
BREAKER_TRIPS = REGISTRY.counter(
    "tidb_tpu_breaker_trips_total", "TPU engine circuit breaker trips to open"
)
# both breaker series carry an engine="e<n>" label (one per breaker
# instance); a breaker publishes only on its first state transition, so
# idle breakers never add series

# runaway-control series (ref: the reference's runaway metrics; PR 4)
RUNAWAY_ACTIONS = REGISTRY.counter(
    "tidb_runaway_actions_total",
    "runaway QUERY_LIMIT actions fired, by group, action and breached rule",
)
RUNAWAY_WATCH_HITS = REGISTRY.counter(
    "tidb_runaway_watch_hits_total",
    "statements matched against the runaway watch list at admission",
)

# server memory arbitration series (utils/memory ServerMemTracker; PR 4)
SERVER_MEM_CONSUMED = REGISTRY.gauge(
    "tidb_server_mem_consumed_bytes", "tracked statement memory across the store"
)
SERVER_MEM_LIMIT = REGISTRY.gauge(
    "tidb_server_mem_limit_bytes", "tidb_server_memory_limit (0 = unlimited)"
)
SERVER_MEM_ACTIONS = REGISTRY.counter(
    "tidb_server_mem_actions_total",
    "server memory arbiter actions (degrade / recover / kill)",
)

# device-path series (ref: "Query Processing on Tensor Computation
# Runtimes" names compile-cache behavior and host↔device transfer as the
# dominant hidden costs — these make them first-class)
TPU_COMPILE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_compile_seconds",
    "XLA program trace+compile wall time (first dispatch of a new program key)",
)
TPU_COMPILE_CACHE = REGISTRY.counter(
    "tidb_tpu_compile_cache_total", "device program-cache lookups by result"
)
TPU_TRANSFER_BYTES = REGISTRY.counter(
    "tidb_tpu_transfer_bytes_total", "host<->device transfer bytes by direction"
)
# also sharded per resource_group label (PR 5)
TPU_EXECUTE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_device_execute_seconds",
    "device execute+fetch wall time (dispatch to device_get completion)",
)
# grouped-launch h2d volume that statement memory tracking deliberately
# charges to nobody (a neighbor's bytes must not draw the leader's quota
# verdict) — surfaced here and as `shared_h2d` on the launch span (PR 5)
TPU_SHARED_UPLOAD_BYTES = REGISTRY.counter(
    "tidb_tpu_shared_upload_bytes_total",
    "h2d bytes uploaded by grouped launches on behalf of the whole group",
)

# unified fault domain (PR 8): every device path (cop | mpp | window)
# that declines or degrades to the host engine counts here with a TYPED
# reason — breaker_open, device_error, mem_degrade, not_lowerable,
# string_join_key, capacity_overflow, ... — so "how often and why does
# the accelerator path lose" is one query instead of three ad-hoc
# attributes (the Tailwind observable-fallback policy, arXiv:2604.28079)
TPU_FALLBACK = REGISTRY.counter(
    "tidb_tpu_fallback_total",
    "device-path declines/degrades to the host engine by path (cop|mpp|window) and typed reason",
)

# fused MPP fragment chains (PR 11): how each MPP dispatch ran —
# `fused` (every join level probed a resident LUT structure, agg folded
# to build-row positions), `partial` (some levels fused, the rest took
# the sort-join path), `unfused` (fusion on but no level qualified) or
# `off` (tidb_tpu_mpp_fused=OFF) — and the device-resident build-side
# cache's lifecycle (hit | miss | evict | invalidate)
TPU_MPP_FUSED = REGISTRY.counter(
    "tidb_tpu_mpp_fused_total",
    "MPP dispatches by fusion outcome (fused | partial | unfused | off)",
)
TPU_BUILD_CACHE = REGISTRY.counter(
    "tidb_tpu_build_cache_total",
    "device-resident build-side cache lifecycle (hit | miss | evict | invalidate)",
)

# compressed, width-narrowed device tiles (PR 7): per-lane wire bytes by
# the codec that produced them (dense | pack | dict | rle), and the rows
# of padding every DeviceBatch still adds beyond its real row count —
# together they tell how much of the h2d stream is signal
TPU_TILE_COMPRESSED_BYTES = REGISTRY.counter(
    "tidb_tpu_tile_compressed_bytes_total",
    "device tile lane wire bytes after codec encode, by codec",
)
TPU_TILE_ROWS_PADDED = REGISTRY.counter(
    "tidb_tpu_tile_rows_padded_total",
    "padding rows added to device tiles beyond the real batch rows",
)

# --- per-device runner lanes (PR 6: mesh-wide cop dispatch) ----------------
# every mesh device is a cop runner lane with its own queue position,
# breaker and timeline lane; `device` labels carry the lane name (cpu:3)
TPU_LANE_OCCUPANCY = REGISTRY.gauge(
    "tidb_tpu_lane_occupancy",
    "in-flight cop tasks placed on each device runner lane",
)
TPU_LANE_LAUNCHES = REGISTRY.counter(
    "tidb_tpu_lane_launch_total",
    "device launches per runner lane, solo vs grouped",
)
TPU_LANE_REROUTES = REGISTRY.counter(
    "tidb_tpu_lane_reroutes_total",
    "placements diverted off the resident lane (reason: breaker | spill)",
)

# --- durability fault domain (PR 10: storage/wal.py WAL IO discipline) -----
# a failed append/fsync poisons the WAL and flips the store read-only
# (fsyncgate: one failed fsync means the page cache can no longer be
# trusted, so no later commit may ever ack); recovery counts the bytes it
# deliberately gave up (torn tail truncation / drop-corrupt salvage gaps)
WAL_IO_ERRORS = REGISTRY.counter(
    "tidb_wal_io_errors_total",
    "WAL IO failures by op (append | sync); any hit poisons the log",
)
WAL_DEGRADED = REGISTRY.gauge(
    "tidb_wal_degraded",
    "a store in this process hit a WAL IO failure and degraded read-only "
    "(0 ok, 1 degraded; sticky until a successful spare-dir rotation — "
    "tidb_wal_rotations_total records the heals; without a spare the "
    "store never heals in-place and recovery means reopening on healthy "
    "media in a fresh process)",
)
WAL_RECOVERY_DROPPED = REGISTRY.counter(
    "tidb_wal_recovery_dropped_bytes_total",
    "log bytes recovery discarded, by kind (torn tail | corrupt frames under drop-corrupt)",
)

# --- group-commit WAL (PR 13: Wal.sync_group serving-scale OLTP) -----------
# each commit's durability point counts once: `leader` ran the group's
# fsync, `follower` rode a leader's fsync (including already-covered
# fast-path returns), `off` took the per-commit fallback
# (tidb_wal_group_commit=OFF), `error` marks a failed group sync (the
# whole group's acks withheld, log poisoned)
WAL_GROUP_COMMIT = REGISTRY.counter(
    "tidb_wal_group_commit_total",
    "commit durability points by group-commit outcome (leader | follower | off | error)",
)
WAL_GROUP_SIZE = REGISTRY.histogram(
    "tidb_wal_group_commit_size",
    "committers covered by one group fsync (observed by the leader)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)

# --- warm-standby WAL shipping + online media failover (PR 14) --------------
# the shipper streams DURABLE (fsynced) WAL frames to a standby data dir
# (storage/ship.py); the lag gauge is the age of the oldest frame still
# waiting to ship (0 when fully caught up), the applied-ts gauge is the
# newest commit_ts the standby has replayed into its MVCC state
WAL_SHIP_LAG = REGISTRY.gauge(
    "tidb_wal_ship_lag_seconds",
    "age of the oldest primary WAL frame not yet durably shipped to the "
    "standby (0 = caught up)",
)
STANDBY_APPLIED_TS = REGISTRY.gauge(
    "tidb_standby_applied_ts",
    "newest commit_ts the standby store has replayed from shipped frames",
)
# replica fleet (PR 17): per-link horizons, quorum commit outcomes,
# lag-bounded follower-read routing, socket resync, and rejoin healing
REPLICA_DURABLE_FRAMES = REGISTRY.gauge(
    "tidb_replica_durable_frames",
    "shipped frames acked durable by one replica link (label replica)",
)
REPLICA_APPLIED_TS = REGISTRY.gauge(
    "tidb_replica_applied_ts",
    "newest commit_ts one replica link has applied (label replica)",
)
# outcome=acked: the median per-replica durable horizon covered the
# commit (a majority of links acked); outcome=unreachable: too many
# links broken for the quorum to ever form — the wait raised the typed
# indeterminate shape (8150) instead of blocking forever;
# outcome=timeout (PR 19): enough links were nominally alive but the
# quorum did not form within tidb_replica_quorum_timeout_ms — a stalled
# majority (black-holed / partitioned peers) raised the same 8150 shape
# within the bound instead of pinning the commit
REPLICA_QUORUM = REGISTRY.counter(
    "tidb_replica_quorum_commits_total",
    "semi-sync QUORUM commit waits by outcome (acked | unreachable | timeout)",
)
# outcome=follower: a lag-eligible replica served the read;
# fallback_stale: replicas exist but none could serve THIS statement;
# fallback_none: no replica links at all — both fallbacks route the
# statement to the primary. The reason dimension (PR 18, mirroring the
# PR 8 fallback taxonomy) says WHY: over_lag (every candidate past
# tidb_replica_read_max_lag_ms), beyond_watermark (AS OF ts above every
# applied watermark), in_txn (follower read requested inside an open
# txn — routing would miss its uncommitted writes), no_replica (no
# eligible link); served reads carry reason="-"
REPLICA_READS = REGISTRY.counter(
    "tidb_replica_read_total",
    "read-only statement routing by outcome (follower | fallback_stale | "
    "fallback_none) and reason (- | over_lag | beyond_watermark | in_txn "
    "| no_replica)",
)
# fleet SLO profiling (PR 18): the ReplicaSet lag monitor samples each
# live link's staleness vs the primary's commit high-water every tick;
# ack seconds measure enqueue→durable-ack latency per shipped batch —
# together the inputs for the lagging-replica / quorum-at-risk
# inspection rules and feedback-driven routing
REPLICA_LAG_SECONDS = REGISTRY.histogram(
    "tidb_replica_lag_seconds",
    "sampled per-replica apply staleness vs the primary (label replica)",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0),
)
REPLICA_ACK_SECONDS = REGISTRY.histogram(
    "tidb_replica_ack_seconds",
    "per-link WAL batch enqueue-to-durable-ack latency (label replica)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
REPLICA_REJOINS = REGISTRY.counter(
    "tidb_replica_rejoin_total",
    "ADMIN REJOIN attempts rebuilding a fenced old primary as a standby "
    "(ok | failed)",
)
# a socket ship link reconnecting after a dropped connection (the
# standby refuses wire-corrupted frames by dropping the connection, so
# reason=peer_closed covers CRC refusals; reason=io_error is a local
# socket fault) — bounded retries, then the link breaks for good.
# PR 19 adds the terminal typed breaks: reason=timeout (a frame/ack
# round trip blew the tidb_replica_heartbeat_timeout_ms deadline — a
# black-holed peer; no reconnect ladder) and reason=partitioned (the
# reconnect budget ran dry against an unreachable peer)
SHIP_RECONNECTS = REGISTRY.counter(
    "tidb_ship_reconnects_total",
    "ship-link reconnect-with-resync attempts by reason (peer_closed | "
    "io_error | timeout | partitioned)",
)
# online WAL media failover: on an IO failure a store with
# tidb_wal_spare_dirs checkpoints onto a spare and resumes writes
# (outcome=ok); a spare that fails the attempt counts outcome=failed and
# joins the re-probe list; outcome=no_spare marks a degrade episode that
# found no eligible spare and stayed read-only (the pre-PR-14 behavior)
WAL_ROTATIONS = REGISTRY.counter(
    "tidb_wal_rotations_total",
    "WAL media-failover rotation attempts by outcome (ok | failed | no_spare)",
)
# bulk ingest (PR 15): rows published through the Lightning-style bulk
# path (br/ingest.BulkIngest — LOAD DATA bulk mode + models bulk_load),
# and the bytes each pipeline stage handled: parse (raw input bytes the
# CSV reader consumed), encode (canonical columnar artifact bytes),
# wal (artifact bytes journaled into the single ingest record; absent
# for in-memory stores), publish (artifact bytes made visible)
INGEST_ROWS = REGISTRY.counter(
    "tidb_ingest_rows_total", "rows published by bulk-ingest commits"
)
INGEST_BYTES = REGISTRY.counter(
    "tidb_ingest_bytes_total",
    "bulk-ingest bytes by pipeline stage (parse | encode | wal | publish)",
)
# delta-main compaction (PR 16): the background worker that folds txn
# writes + MVCC versions at/below the gc safepoint into columnar
# segments (storage/compact.py). rounds count every attempt by outcome:
# fold (delta folded into fresh runs), merge (run count bounded by a
# leveled merge), raced (a commit slipped under the fold ts — retried),
# deferred (foreground statements queued at the admission scheduler),
# paused (OOM degrade active). rows/versions/bytes count fold output.
COMPACT_ROUNDS = REGISTRY.counter(
    "tidb_compact_rounds_total",
    "compaction attempts by outcome (fold | merge | raced | deferred | paused)",
)
COMPACT_ROWS = REGISTRY.counter(
    "tidb_compact_rows_total", "live rows folded into columnar segments"
)
COMPACT_VERSIONS = REGISTRY.counter(
    "tidb_compact_versions_total",
    "mutable MVCC version entries reclaimed by compaction folds",
)
COMPACT_BYTES = REGISTRY.counter(
    "tidb_compact_bytes_total",
    "bytes of compaction WAL records (Z frames) published",
)
# workload-history routing (PR 20): every `auto` engine decision the
# feedback router made, labeled by where the task went (device | host)
# and why — explore (no history: static heuristic answered),
# history_device / history_host (exploited measured per-task walls),
# learned_decline (digest's device attempts were ALL typed lowering
# declines — straight to host), mem_degrade / quarantine (overrides
# that win over any history). Absent entirely while
# tidb_tpu_feedback_route=OFF (the incident fallback is bit-silent).
TPU_ROUTE = REGISTRY.counter(
    "tidb_tpu_route_total",
    "auto-engine feedback routing decisions (decision=device|host, "
    "reason=explore|history_device|history_host|learned_decline|"
    "mem_degrade|quarantine)",
)
# resident-set observability (PR 20): bytes currently pinned by the three
# device-path residency pools — host-side cached column tiles
# (kind=tile, TileCache), device-resident MPP join structures
# (kind=build, BuildSideCache.nbytes) and per-device compressed batch
# mirrors (kind=batch, DeviceBatch wire bytes). Sampled on read
# (information_schema.tidb_workload_profile residency rows / /metrics).
TPU_RESIDENT_BYTES = REGISTRY.gauge(
    "tidb_tpu_resident_bytes",
    "bytes resident in device-path caches (kind=tile|build|batch)",
)

"""Slow-query log + statement summary (ref: executor/adapter.go:922
LogSlowQuery + util/stmtsummary/statement_summary.go — kept in memory and
read back as INFORMATION_SCHEMA.SLOW_QUERY / STATEMENTS_SUMMARY)."""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque


import functools


def _mask_literals(sql: str, lower: bool) -> str | None:
    """Tokenize and replace literal tokens with '?' — the single place
    that decides what counts as user data (digests + redaction agree)."""
    from ..parser.lexer import tokenize

    try:
        toks = tokenize(sql)
    except Exception:  # noqa: BLE001 — masking must never fail the statement
        return None
    parts = []
    for t in toks:
        if t.kind in ("num", "str", "hex"):
            parts.append("?")
        elif t.kind == "eof":
            break
        else:
            parts.append(t.text.lower() if lower else t.text)
    return " ".join(parts)


@functools.lru_cache(maxsize=2048)
def sql_digest(sql: str) -> str:
    """Normalized statement digest: literals → '?', idents lowercased
    (ref: parser digests used by stmtsummary/topsql)."""
    norm = _mask_literals(sql, lower=True)
    if norm is None:
        return hashlib.sha256(sql.encode()).hexdigest()[:16]
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


@functools.lru_cache(maxsize=2048)
def normalize_sql(sql: str) -> str:
    """Literal-free statement text (tidb_redact_log: logs must carry no
    user data; ref: errors.RedactLogEnabled + parser.Normalize)."""
    out = _mask_literals(sql, lower=False)
    return out if out is not None else "<redacted>"


class StmtStats:
    """Shared per-store statement telemetry."""

    def __init__(self, slow_capacity: int = 512, summary_capacity: int = 512):
        self.slow: deque = deque(maxlen=slow_capacity)
        self.summary: dict[str, dict] = {}
        self.summary_capacity = summary_capacity
        self._lock = threading.Lock()

    # cop-path exec details carried per statement (utils/tracing
    # StatementTrace.details()); summed per digest in the summary,
    # verbatim on each slow-log entry (ref: util/execdetails fields of
    # LogSlowQuery / stmtsummary)
    DETAIL_KEYS = ("sched_wait_ms", "retries", "backoff_ms", "compile_ms",
                   "transfer_bytes", "mem_degraded_tasks", "quorum_wait_ms")

    def record(
        self, sql: str, dur_s: float, user: str, db: str, ok: bool,
        slow_threshold_s: float, cpu_s: float = 0.0, *,
        summary_on: bool = True, slow_log_on: bool = True,
        max_sql_len: int = 256, redact: bool = False,
        details: dict | None = None,
    ) -> None:
        """Record one statement. The keyword gates map the reference's
        knobs: tidb_enable_stmt_summary, tidb_enable_slow_log,
        tidb_stmt_summary_max_sql_length, tidb_redact_log (literals →
        '?' in every stored sample). summary_capacity is store-level,
        applied by SET GLOBAL tidb_stmt_summary_max_stmt_count.
        `details` carries the statement's cop-path exec details
        (sched_wait_ms, batch_occupancy, retries, backoff_ms, compile_ms,
        transfer_bytes)."""
        digest = sql_digest(sql)
        if redact:
            sql = normalize_sql(sql)
        now = time.time()
        d = details or {}
        with self._lock:
            if summary_on:
                st = self.summary.get(digest)
                if st is None:
                    if len(self.summary) >= self.summary_capacity:
                        # evict the least-executed entry (summary eviction)
                        victim = min(self.summary, key=lambda k: self.summary[k]["exec_count"])
                        del self.summary[victim]
                    st = {
                        "digest": digest,
                        "sample_sql": sql[:max_sql_len],
                        "exec_count": 0,
                        "sum_latency_s": 0.0,
                        "max_latency_s": 0.0,
                        "sum_cpu_s": 0.0,
                        "errors": 0,
                    }
                    self.summary[digest] = st
                st["exec_count"] += 1
                st["sum_latency_s"] += dur_s
                st["max_latency_s"] = max(st["max_latency_s"], dur_s)
                st["sum_cpu_s"] = st.get("sum_cpu_s", 0.0) + cpu_s
                if not ok:
                    st["errors"] += 1
                for k in self.DETAIL_KEYS:
                    st["sum_" + k] = st.get("sum_" + k, 0.0) + d.get(k, 0.0)
                st["max_batch_occupancy"] = max(
                    st.get("max_batch_occupancy", 0), int(d.get("batch_occupancy", 0))
                )
                # peak tracked memory is a high-water mark, not a sum
                st["max_mem_bytes"] = max(
                    st.get("max_mem_bytes", 0), int(d.get("mem_bytes", 0))
                )
                # how many executions of this digest a follower actually
                # served (the replica name itself is per-execution: slow
                # log carries it verbatim)
                st["replica_reads"] = st.get("replica_reads", 0) + (
                    1 if d.get("replica") else 0
                )
            if slow_log_on and dur_s >= slow_threshold_s:
                entry = {
                    "time": now,
                    "user": user,
                    "db": db,
                    "query_time_s": dur_s,
                    "digest": digest,
                    "query": sql[:512],
                    "succ": ok,
                    "batch_occupancy": int(d.get("batch_occupancy", 0)),
                    "mem_bytes": int(d.get("mem_bytes", 0)),
                    "replica": str(d.get("replica", "") or ""),
                }
                for k in self.DETAIL_KEYS:
                    entry[k] = d.get(k, 0.0)
                self.slow.append(entry)

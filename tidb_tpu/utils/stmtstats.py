"""Slow-query log + statement summary (ref: executor/adapter.go:922
LogSlowQuery + util/stmtsummary/statement_summary.go — kept in memory and
read back as INFORMATION_SCHEMA.SLOW_QUERY / STATEMENTS_SUMMARY)."""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque


import functools


@functools.lru_cache(maxsize=2048)
def sql_digest(sql: str) -> str:
    """Normalized statement digest: literals → '?', idents lowercased
    (ref: parser digests used by stmtsummary/topsql)."""
    from ..parser.lexer import tokenize

    try:
        toks = tokenize(sql)
    except Exception:  # noqa: BLE001 — digest must never fail the statement
        return hashlib.sha256(sql.encode()).hexdigest()[:16]
    parts = []
    for t in toks:
        if t.kind in ("num", "str", "hex"):
            parts.append("?")
        elif t.kind == "eof":
            break
        else:
            parts.append(t.text.lower())
    norm = " ".join(parts)
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


class StmtStats:
    """Shared per-store statement telemetry."""

    def __init__(self, slow_capacity: int = 512, summary_capacity: int = 512):
        self.slow: deque = deque(maxlen=slow_capacity)
        self.summary: dict[str, dict] = {}
        self.summary_capacity = summary_capacity
        self._lock = threading.Lock()

    def record(self, sql: str, dur_s: float, user: str, db: str, ok: bool, slow_threshold_s: float, cpu_s: float = 0.0) -> None:
        digest = sql_digest(sql)
        now = time.time()
        with self._lock:
            st = self.summary.get(digest)
            if st is None:
                if len(self.summary) >= self.summary_capacity:
                    # evict the least-executed entry (summary eviction)
                    victim = min(self.summary, key=lambda k: self.summary[k]["exec_count"])
                    del self.summary[victim]
                st = {
                    "digest": digest,
                    "sample_sql": sql[:256],
                    "exec_count": 0,
                    "sum_latency_s": 0.0,
                    "max_latency_s": 0.0,
                    "sum_cpu_s": 0.0,
                    "errors": 0,
                }
                self.summary[digest] = st
            st["exec_count"] += 1
            st["sum_latency_s"] += dur_s
            st["max_latency_s"] = max(st["max_latency_s"], dur_s)
            st["sum_cpu_s"] = st.get("sum_cpu_s", 0.0) + cpu_s
            if not ok:
                st["errors"] += 1
            if dur_s >= slow_threshold_s:
                self.slow.append(
                    {
                        "time": now,
                        "user": user,
                        "db": db,
                        "query_time_s": dur_s,
                        "digest": digest,
                        "query": sql[:512],
                        "succ": ok,
                    }
                )

"""Structured statement tracing (ref: util/tracing + executor/trace.go,
rebuilt for the heterogeneous cop path of SURVEY §5.8).

One `StatementTrace` per statement carries two layers:

  * counters — always on, near-zero cost: per-statement exec details
    (sched_wait_ms, retries, backoff_ms, compile_ms, transfer_bytes,
    batch_occupancy, ...) that feed the slow log and STATEMENTS_SUMMARY
    even when span recording is off;
  * spans — recorded only under `TRACE <sql>` or tidb_enable_trace=ON:
    a thread-safe span tree (trace_id / span_id / parent links) covering
    every layer a cop task crosses — admission wait, launch batching,
    backoff sleeps by error class, breaker events, and the device phases
    (compile / host↔device transfer / execute).

Cross-thread plumbing is explicit, not contextvar-based: the cop pool
and the launch batcher run work on threads (and for co-batched launches,
on a DIFFERENT statement's thread) where ambient context is wrong by
construction. `activate()` binds a trace to the current thread for the
duration of a task; the batcher captures each waiter's (trace, parent)
at enqueue time and FANS OUT the one shared launch span into every
co-batched waiter's tree with identical span/launch ids — device time
spent on a shared launch is attributable from every participant's trace.

Device phases use a separate thread-local collector (`push_phases` /
`pop_phases`): the engine reports compile/transfer/execute measurements
into whichever scope is active — the cop client's for solo launches, the
batcher leader's for grouped ones — without signature changes on the
engine seam (tests and benches monkeypatch those signatures).
"""

from __future__ import annotations

import itertools
import threading
import time

_IDS = itertools.count(1)
_TLS = threading.local()


def _next_id() -> int:
    return next(_IDS)


_TXN_IDS = itertools.count(1)


def new_txn_trace_id() -> str:
    """Transaction-level trace id, minted at BEGIN and stamped on every
    statement trace until COMMIT/ROLLBACK (the txn-linkage key of
    TIDB_TRACE and the TRACE txn tree)."""
    return f"txn-{next(_TXN_IDS):06x}"


class Span:
    """One timed operation. `start_ns` is relative to the owning trace's
    epoch; ids are process-unique so a span fanned out into several traces
    keeps ONE identity (the launch-id contract)."""

    __slots__ = ("span_id", "parent_id", "name", "start_ns", "dur_ns", "tags")

    def __init__(self, name: str, start_ns: int, dur_ns: int = 0,
                 parent_id: int = 0, span_id: int | None = None, tags: dict | None = None):
        self.span_id = _next_id() if span_id is None else span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.tags = tags if tags is not None else {}

    def copy_with_parent(self, parent_id: int) -> "Span":
        """Same span (same id/name/timing/tags) re-parented for another
        trace — the fan-out primitive."""
        return Span(self.name, self.start_ns, self.dur_ns,
                    parent_id=parent_id, span_id=self.span_id, tags=self.tags)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id, "parent_id": self.parent_id,
            "operation": self.name,
            "start_ms": round(self.start_ns / 1e6, 3),
            "duration_ms": round(self.dur_ns / 1e6, 3),
            "tags": {k: v for k, v in self.tags.items()},
        }


class _SpanCtx:
    """Context manager for an open span; closes + appends on exit."""

    __slots__ = ("trace", "span")

    def __init__(self, trace: "StatementTrace", span: Span):
        self.trace = trace
        self.span = span

    def tag(self, **kv) -> None:
        self.span.tags.update(kv)

    def __enter__(self):
        self.trace._push(self.span)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.span.dur_ns = self.trace._now_ns() - self.span.start_ns
        if exc is not None:
            self.span.tags.setdefault("error", type(exc).__name__)
        self.trace._pop(self.span)
        return False


class _NoopSpan:
    __slots__ = ()

    def tag(self, **kv) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class StatementTrace:
    """Per-statement trace: counters always, spans when `recording`.

    Thread-safe by design: counters and the span list append under one
    lock; the open-span STACK is per (trace, thread) so concurrently
    running cop tasks each nest their own children correctly."""

    _seq = itertools.count(1)

    def __init__(self, sql: str = "", session_id: int = 0, recording: bool = False):
        self.trace_id = f"tr-{next(self._seq):06x}"
        # statements inside one BEGIN…COMMIT share a txn_trace_id (the
        # session threads it); None outside explicit transactions
        self.txn_trace_id: str | None = None
        self.sql = sql
        self.session_id = session_id
        self.recording = recording
        self.start_ts = time.time()
        self._epoch_ns = time.perf_counter_ns()
        self.end_ns: int | None = None
        self.ok = True
        self.root_id = _next_id()
        self.counters: dict[str, float] = {}
        # table ids this statement's cop tasks scanned (set adds are
        # GIL-atomic) — the workload profile's invalidation index
        self.tables: set = set()
        self.spans: list[Span] = []
        self._lock = threading.Lock()
        self._local = threading.local() if recording else None

    # --- counters (always on) ----------------------------------------------

    def add(self, key: str, n: float = 1.0) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) + n

    def set_max(self, key: str, v: float) -> None:
        with self._lock:
            if v > self.counters.get(key, 0.0):
                self.counters[key] = v

    def details(self) -> dict:
        """The slow-log / STATEMENTS_SUMMARY exec-detail columns."""
        c = self.counters
        return {
            "sched_wait_ms": c.get("sched_wait_ms", 0.0),
            "batch_occupancy": int(c.get("batch_occupancy", 0)),
            "retries": int(c.get("retries", 0)),
            "backoff_ms": c.get("backoff_ms", 0.0),
            "compile_ms": c.get("compile_ms", 0.0),
            "transfer_bytes": int(c.get("transfer_bytes", 0)),
            "mem_bytes": int(c.get("mem_bytes", 0)),
            "mem_degraded_tasks": int(c.get("mem_degraded_tasks", 0)),
            "quorum_wait_ms": c.get("quorum_wait_ms", 0.0),
        }

    # --- spans (recording only) --------------------------------------------

    def enable_recording(self) -> None:
        """Flip span recording on mid-statement (the TRACE path: the
        statement trace exists before TRACE decides to record spans)."""
        if self._local is None:
            self._local = threading.local()
        self.recording = True

    def _now_ns(self) -> int:
        return time.perf_counter_ns() - self._epoch_ns

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        with self._lock:
            self.spans.append(span)

    def current_parent(self) -> int:
        """Innermost open span on THIS thread (else the root) — the parent
        a cross-thread child (e.g. a fanned-out launch span) links under."""
        if not self.recording:
            return self.root_id
        st = getattr(self._local, "stack", None)
        return st[-1].span_id if st else self.root_id

    def span(self, name: str, **tags):
        """Open a child span on this thread; no-op when not recording."""
        if not self.recording:
            return _NOOP
        st = getattr(self._local, "stack", None)
        parent = st[-1].span_id if st else self.root_id
        return _SpanCtx(self, Span(name, self._now_ns(), parent_id=parent, tags=tags))

    def closed_span(self, name: str, dur_s: float, **tags) -> None:
        """Record an already-elapsed operation ending now (admission
        waits, backoff sleeps — measured by their owners)."""
        if not self.recording:
            return
        dur_ns = int(dur_s * 1e9)
        st = getattr(self._local, "stack", None)
        parent = st[-1].span_id if st else self.root_id
        with self._lock:
            self.spans.append(Span(name, self._now_ns() - dur_ns, dur_ns,
                                   parent_id=parent, tags=tags))

    def adopt(self, span: Span, parent_id: int, children: tuple = ()) -> None:
        """Fan-out: link a SHARED span (one launch, many waiters) into this
        trace under `parent_id`, keeping its identity; `children` (device
        phase spans already parented to it) come along unchanged."""
        if not self.recording:
            return
        with self._lock:
            self.spans.append(span.copy_with_parent(parent_id))
            self.spans.extend(children)

    def add_phase_spans(self, phases: dict) -> None:
        """Record a solo launch's device phases (compile / h2d transfer /
        execute+d2h) as spans under the calling thread's current span.
        Frames carrying real boundary events (PhaseFrame.events) keep
        their captured timestamps; a bare counters dict falls back to
        back-to-back synthesis ending now."""
        if not self.recording:
            return
        events = getattr(phases, "events", None)
        if events:
            spans = real_phase_spans(events, self.current_parent(), self._epoch_ns)
        elif phases:
            spans = phase_spans(phases, self.current_parent(), self._now_ns())
        else:
            return
        with self._lock:
            self.spans.extend(spans)

    # --- lifecycle -----------------------------------------------------------

    def finish(self, ok: bool = True) -> None:
        self.end_ns = self._now_ns()
        self.ok = ok

    def duration_ns(self) -> int:
        return self.end_ns if self.end_ns is not None else self._now_ns()

    def tree(self, extra: list[Span] | None = None) -> list[tuple[int, Span]]:
        """Depth-first (depth, span) rows, root first. Spans whose parent
        is missing (recording flipped on mid-flight) attach to the root —
        a late joiner must never corrupt the tree."""
        with self._lock:
            spans = list(self.spans)
        if extra:
            spans = spans + list(extra)
        root = Span("session.execute", 0, self.duration_ns(),
                    parent_id=0, span_id=self.root_id)
        if self.txn_trace_id:
            root.tags["txn_trace_id"] = self.txn_trace_id
        by_parent: dict[int, list[Span]] = {}
        ids = {root.span_id} | {s.span_id for s in spans}
        for s in spans:
            pid = s.parent_id if s.parent_id in ids else root.span_id
            by_parent.setdefault(pid, []).append(s)
        out: list[tuple[int, Span]] = []

        def rec(span: Span, depth: int) -> None:
            out.append((depth, span))
            for ch in sorted(by_parent.get(span.span_id, ()), key=lambda x: x.start_ns):
                rec(ch, depth + 1)

        rec(root, 0)
        return out

    def to_dict(self) -> dict:
        rows = [s.to_dict() for _, s in self.tree()]
        with self._lock:  # a straggler task may still be adding counters
            counters = dict(self.counters)
        return {
            "trace_id": self.trace_id,
            "txn_trace_id": self.txn_trace_id,
            "session_id": self.session_id,
            "sql": self.sql[:512],
            "start_ts": self.start_ts,
            "duration_ms": round(self.duration_ns() / 1e6, 3),
            "ok": self.ok,
            "counters": counters,
            "spans": rows,
        }


# --- per-thread active trace (set by the cop client around task work) -------


class activate:
    """Bind `trace` (may be None) to the current thread for a task's
    duration; the batcher and backoff machinery read it from here."""

    __slots__ = ("trace", "prev")

    def __init__(self, trace: StatementTrace | None):
        self.trace = trace

    def __enter__(self):
        self.prev = getattr(_TLS, "trace", None)
        _TLS.trace = self.trace
        return self.trace

    def __exit__(self, *exc):
        _TLS.trace = self.prev
        return False


def current_trace() -> StatementTrace | None:
    return getattr(_TLS, "trace", None)


# --- device-phase collector (engine → whoever wrapped the launch) -----------


class PhaseFrame(dict):
    """One launch's device-phase measurements. The dict half is the PR 3
    counters contract (compile_ms, h2d_bytes/ms, execute_ms, d2h_bytes —
    what `phase_counters` folds into exec details); `events` carries the
    PR 5 upgrade: individually-timestamped `(name, t_start_ns, t_end_ns,
    tags)` boundary events from ONE monotonic clock
    (`time.perf_counter_ns`), so trace spans show the REAL device
    timeline instead of walls synthesized back-to-back. Code that hands
    `_attribute`/`add_phase_spans` a plain dict (tests, external shims)
    still works — it just falls back to synthesis."""

    __slots__ = ("events",)

    def __init__(self):
        super().__init__()
        self.events: list[tuple[str, int, int, dict]] = []


def push_phases() -> tuple:
    prev = getattr(_TLS, "phases", None)
    d = PhaseFrame()
    _TLS.phases = d
    return prev, d


def pop_phases(token: tuple) -> PhaseFrame:
    _TLS.phases = token[0]
    return token[1]


class collect_phases:
    """`with collect_phases() as ph:` — ph accumulates the device-phase
    measurements (compile_ms, h2d_bytes/ms, execute_ms, d2h_bytes) the
    engine emits while the block runs on this thread."""

    __slots__ = ("_token",)

    def __enter__(self) -> dict:
        self._token = push_phases()
        return self._token[1]

    def __exit__(self, *exc):
        pop_phases(self._token)
        return False


def add_phase(key: str, n: float) -> None:
    d = getattr(_TLS, "phases", None)
    if d is not None:
        d[key] = d.get(key, 0.0) + n


def add_phase_event(name: str, t_start_ns: int, t_end_ns: int, **tags) -> None:
    """Record one individually-timestamped device boundary event
    (compile / h2d upload / execute+fetch / cache ref) into the active
    phase frame. Timestamps are absolute `time.perf_counter_ns` readings;
    consumers rebase against their own epoch (trace or timeline ring) —
    the clocks agree because there is only one."""
    d = getattr(_TLS, "phases", None)
    if d is not None:
        ev = getattr(d, "events", None)
        if ev is not None:
            ev.append((name, t_start_ns, t_end_ns, tags))


def real_phase_spans(events, parent_id: int, epoch_ns: int) -> list[Span]:
    """Device-phase child spans from REAL captured timestamps: each
    event's start rebases from the shared monotonic clock onto the
    consuming trace's epoch — gaps between phases survive, nothing is
    laid back-to-back."""
    return [
        Span(name, t0 - epoch_ns, t1 - t0, parent_id=parent_id, tags=dict(tags))
        for name, t0, t1, tags in events
    ]


def phase_counters(phases: dict) -> list[tuple[str, float]]:
    """(exec-detail key, value) pairs for a launch's device phases — the
    ONE phase→counter mapping, shared by solo attribution
    (copr/client._note_device_phases) and grouped fan-out
    (sched/batcher._attribute) so both EXPLAIN ANALYZE `device:` paths
    can never drift apart."""
    out = []
    if phases.get("compile_ms"):
        out.append(("compile_ms", phases["compile_ms"]))
    tb = phases.get("h2d_bytes", 0.0) + phases.get("d2h_bytes", 0.0)
    if tb:
        out.append(("transfer_bytes", tb))
    dm = phases.get("execute_ms", 0.0) + phases.get("h2d_ms", 0.0)
    if dm:
        out.append(("device_ms", dm))
    if phases.get("cache_ref_bytes"):
        # device-cache hits: bytes SERVED from a prior statement's upload
        # (zero-duration cache_ref annotation), never charged as transfer
        out.append(("cache_ref_bytes", phases["cache_ref_bytes"]))
    # tile-codec split of the h2d uploads: what the lanes represent
    # uncompressed vs what the narrowed/compressed form actually moved
    if phases.get("logical_bytes"):
        out.append(("logical_bytes", phases["logical_bytes"]))
    if phases.get("wire_bytes"):
        out.append(("wire_bytes", phases["wire_bytes"]))
    return out


def phase_spans(phases: dict, parent_id: int, end_ns: int) -> list[Span]:
    """Synthesize the device-phase child spans (compile → h2d transfer →
    execute+d2h) under `parent_id`, laid out back-to-back ending at
    `end_ns` (phase walls are measured, their gaps are not)."""
    segs = []
    if phases.get("compile_ms"):
        segs.append(("device.compile", phases["compile_ms"], {}))
    if phases.get("h2d_bytes") or phases.get("h2d_ms"):
        segs.append(("device.transfer", phases.get("h2d_ms", 0.0),
                     {"dir": "h2d", "bytes": int(phases.get("h2d_bytes", 0))}))
    if phases.get("execute_ms") or phases.get("d2h_bytes"):
        segs.append(("device.execute", phases.get("execute_ms", 0.0),
                     {"d2h_bytes": int(phases.get("d2h_bytes", 0))}))
    out = []
    start = end_ns - int(sum(d for _, d, _ in segs) * 1e6)
    for name, dur_ms, tags in segs:
        dur_ns = int(dur_ms * 1e6)
        out.append(Span(name, start, dur_ns, parent_id=parent_id, tags=tags))
        start += dur_ns
    return out


class TraceRing:
    """Ring buffer of the last N finished statement traces — the
    TIDB_TRACE memtable / `/debug/trace` backing store. Stores the live
    (finished, no longer written) trace objects and renders them to dicts
    only when a reader asks: pushing is O(1) on the statement hot path."""

    CAPACITY = 64

    def __init__(self, capacity: int | None = None):
        from collections import deque

        self._ring = deque(maxlen=capacity or self.CAPACITY)
        self._lock = threading.Lock()

    def push(self, trace) -> None:
        with self._lock:
            self._ring.append(trace)

    def resize(self, capacity: int) -> None:
        """Live resize (SET GLOBAL tidb_trace_ring_capacity): keeps the
        newest traces that fit — a shrink drops from the old end, like
        the ring itself would have."""
        from collections import deque

        capacity = max(1, int(capacity))
        with self._lock:
            if self._ring.maxlen == capacity:
                return
            self._ring = deque(self._ring, maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def snapshot(self) -> list[dict]:
        with self._lock:
            traces = list(self._ring)
        return [t if isinstance(t, dict) else t.to_dict() for t in traces]

    def items(self) -> list:
        """The raw ring entries (live StatementTrace objects, unrendered)
        — the TRACE txn-tree renderer walks these for same-txn siblings."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

"""Device timeline profiler — individually-timestamped phase events.

PR 3's statement traces carried device phases as walls accumulated in a
dict and synthesized back-to-back; tensor-runtime query engines need the
real device timeline (arXiv:2203.01877 attributes latency to
compile/transfer/kernel phases on it; arXiv:2604.28079 argues for
per-launch, per-lane profiling). This module is that timeline: a bounded
per-store ring (`Storage.timeline`, next to `trace_ring`) of events with
`t_start_ns`/`t_end_ns` captured from ONE monotonic clock
(`time.perf_counter_ns`) at the actual engine boundaries —
first-dispatch compile, each h2d upload, each jitted dispatch, each d2h
fetch (`copr/tpu_engine.py`) — and at the batcher's launch lifecycle
(enqueue → leader-elected → flush → fan-out, `sched/batcher.py`).

Lanes map to Chrome trace-event (pid, tid) pairs, loadable in Perfetto
via `/debug/timeline` (or `chrome://tracing`):

  * pid DEVICE — one tid per REAL device lane (`cpu:3`, `tpu:0`) when
    the per-device dispatch path bound one via `device_scope` (PR 6:
    runner lanes are the mesh devices, serialized by each lane's launch
    lock), falling back to the runner thread's name for unpinned
    engine work. Events within a lane are PROPERLY NESTED by
    construction (one lock / one thread, one clock): phase events are
    pairwise disjoint, and a `cop.launch` — one per launch, solo or
    grouped, args carrying launch id, occupancy, shared-upload bytes
    and every co-batched waiter's trace id — fully encloses the phase
    events recorded during the launch (rendered as a nested slice).
    Partial overlap, which the Chrome format cannot represent on one
    tid, never occurs.
  * pid GROUPS — one tid per (resource group, thread): statement walls
    and launch lifecycle events, clustered by the leading group name in
    the UI. The thread split keeps concurrent same-group statements off
    one tid (complete events on a tid must not partially overlap).

Cross-thread plumbing mirrors `utils/tracing`: `bind()` attaches the
store's ring (plus the statement's resource group) to the current thread
for the duration of an engine call; the engine hooks read it from TLS,
so the uninstrumented path costs one TLS miss. `SET GLOBAL
tidb_enable_timeline` flips recording store-wide.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

_TLS = threading.local()

# lane kinds → Chrome trace pids (process_name metadata at export)
PID_DEVICE = 1
PID_GROUPS = 2
_PID_NAMES = {PID_DEVICE: "device", PID_GROUPS: "resource-groups"}


class TimelineEvent:
    """One timed operation on the device timeline. Timestamps are
    absolute `time.perf_counter_ns()` readings — the ring's epoch (taken
    from the same clock) rebases them for export."""

    __slots__ = ("name", "cat", "t_start_ns", "t_end_ns", "pid", "lane", "args")

    def __init__(self, name: str, cat: str, t_start_ns: int, t_end_ns: int,
                 pid: int, lane: str, args: dict):
        self.name = name
        self.cat = cat
        self.t_start_ns = t_start_ns
        self.t_end_ns = t_end_ns
        self.pid = pid  # PID_DEVICE | PID_GROUPS
        self.lane = lane  # tid label: runner thread / resource group
        self.args = args


class TimelineRing:
    """Bounded per-store timeline (the TIDB_TIMELINE memtable /
    `/debug/timeline` backing store). Recording is O(1) append under one
    lock; Chrome-trace rendering happens only when a reader asks."""

    CAPACITY = 8192

    def __init__(self, capacity: int | None = None):
        self.epoch_ns = time.perf_counter_ns()  # the ONE monotonic clock
        self.epoch_wall = time.time()
        self.enabled = True  # SET GLOBAL tidb_enable_timeline
        self._ring: deque[TimelineEvent] = deque(maxlen=capacity or self.CAPACITY)
        self._lock = threading.Lock()

    def resize(self, capacity: int) -> None:
        """Live resize (SET GLOBAL tidb_timeline_ring_capacity): keeps
        the newest events — deque(iterable, maxlen) retains the tail."""
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    # --- recording ---------------------------------------------------------

    def record(self, name: str, cat: str, t_start_ns: int, t_end_ns: int,
               pid: int = PID_DEVICE, lane: str = "", **args) -> None:
        if not self.enabled:
            return
        ev = TimelineEvent(name, cat, t_start_ns, t_end_ns, pid, lane, args)
        with self._lock:
            self._ring.append(ev)

    def device_event(self, name: str, cat: str, t_start_ns: int, t_end_ns: int,
                     **args) -> None:
        """Record on the bound REAL device lane (`device_scope`, held with
        that lane's launch lock ⇒ events on one device tid never partially
        overlap), falling back to the calling thread's name for unpinned
        engine work (one thread ⇒ events close before the next opens)."""
        self.record(name, cat, t_start_ns, t_end_ns,
                    pid=PID_DEVICE, lane=current_device_lane(), **args)

    # --- reading -----------------------------------------------------------

    def snapshot(self) -> list[TimelineEvent]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the Perfetto/about:tracing loadable
        form): complete events (`ph: "X"`) with `ts`/`dur` in µs relative
        to the ring epoch, plus process/thread name metadata so lanes
        carry their labels in the UI."""
        events = self.snapshot()
        out: list[dict] = []
        tids: dict[tuple[int, str], int] = {}
        for pid, pname in _PID_NAMES.items():
            out.append({"ph": "M", "pid": pid, "tid": 0,
                        "name": "process_name", "args": {"name": pname}})
        for ev in events:
            key = (ev.pid, ev.lane)
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len([k for k in tids if k[0] == ev.pid]) + 1
                out.append({"ph": "M", "pid": ev.pid, "tid": tid,
                            "name": "thread_name", "args": {"name": ev.lane}})
            out.append({
                "ph": "X",
                "pid": ev.pid,
                "tid": tid,
                "name": ev.name,
                "cat": ev.cat,
                "ts": (ev.t_start_ns - self.epoch_ns) / 1e3,
                "dur": max(ev.t_end_ns - ev.t_start_ns, 0) / 1e3,
                "args": dict(ev.args),
            })
        # flow-event arrows: each `cop.launch` slice points at the
        # statement slice of every co-batched waiter (waiter linkage was
        # args-only before PR 6). Second pass: every lane has its tid by
        # now. One s/f pair per (launch, waiter) edge — Chrome flow ids
        # chain events sharing an id, so per-edge ids keep N waiters from
        # rendering as one zig-zag chain.
        stmts = {}
        for ev in events:
            t = ev.args.get("trace_id")
            if ev.name == "statement" and t is not None:
                stmts[t] = ev
        for ev in events:
            waiters = ev.args.get("waiters") if ev.name == "cop.launch" else None
            if not waiters:
                continue
            l_tid = tids[(ev.pid, ev.lane)]
            l_end = (max(ev.t_end_ns, ev.t_start_ns) - self.epoch_ns) / 1e3
            for w in waiters:
                st = stmts.get(w)
                if st is None:
                    continue  # waiter's statement fell off the ring
                fid = f"{ev.args.get('launch_id', 0)}/{w}"
                out.append({
                    "ph": "s", "id": fid, "pid": ev.pid, "tid": l_tid,
                    "name": "cop.launch→stmt", "cat": "launch",
                    "ts": (ev.t_start_ns - self.epoch_ns) / 1e3,
                })
                # bind inside the statement slice: clamp the arrow head
                # to the waiter's own wall (a waiter may adopt a launch
                # that started before its statement did)
                s0 = (st.t_start_ns - self.epoch_ns) / 1e3
                s1 = (max(st.t_end_ns, st.t_start_ns) - self.epoch_ns) / 1e3
                out.append({
                    "ph": "f", "bp": "e", "id": fid,
                    "pid": st.pid, "tid": tids[(st.pid, st.lane)],
                    "name": "cop.launch→stmt", "cat": "launch",
                    "ts": min(max(l_end, s0), s1),
                })
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def to_json(self) -> str:
        return json.dumps(self.chrome_trace())


# --- per-thread binding (set by the cop client around engine work) ---------


class bind:
    """Attach `ring` (may be None) and the statement's resource group to
    the current thread for the duration of an engine call; the engine's
    boundary hooks and the launch batcher read them from here."""

    __slots__ = ("ring", "group", "prev")

    def __init__(self, ring: TimelineRing | None, group: str = "default"):
        self.ring = ring
        self.group = group or "default"

    def __enter__(self):
        self.prev = getattr(_TLS, "tl", None)
        _TLS.tl = (self.ring, self.group)
        return self.ring

    def __exit__(self, *exc):
        _TLS.tl = self.prev
        return False


def active() -> TimelineRing | None:
    """The bound ring, or None when unbound/disabled — the one check on
    the uninstrumented fast path."""
    t = getattr(_TLS, "tl", None)
    if t is None or t[0] is None or not t[0].enabled:
        return None
    return t[0]


def current_group() -> str:
    t = getattr(_TLS, "tl", None)
    return t[1] if t is not None else "default"


class device_scope:
    """Bind a REAL device lane label (`cpu:3`) to the current thread for
    the duration of a launch: engine-boundary events recorded inside land
    on that device's timeline lane instead of the thread's. The caller
    must hold the lane's launch lock — exclusivity is what keeps one
    device tid free of partial overlap. Re-entrant (nested launches on
    one lane re-bind the same label harmlessly)."""

    __slots__ = ("name", "prev")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.prev = getattr(_TLS, "device_lane", None)
        _TLS.device_lane = self.name
        return self

    def __exit__(self, *exc):
        _TLS.device_lane = self.prev
        return False


def current_device_lane() -> str:
    """The bound device-lane label, or the calling thread's name for
    engine work outside any lane guard."""
    name = getattr(_TLS, "device_lane", None)
    return name if name is not None else threading.current_thread().name


def group_lane(group: str) -> str:
    """Track label for resource-group events: one track per (group,
    thread). Chrome complete events on one tid must never partially
    overlap; one thread's events are sequential, so splitting the group's
    lane by recording thread keeps every track well-formed while the
    leading group name still clusters them in the Perfetto UI."""
    return f"{group} ({threading.current_thread().name})"


def group_event(name: str, cat: str, t_start_ns: int, t_end_ns: int, **args) -> None:
    """Record on the bound statement's resource-group lane."""
    t = getattr(_TLS, "tl", None)
    if t is None or t[0] is None:
        return
    t[0].record(name, cat, t_start_ns, t_end_ns,
                pid=PID_GROUPS, lane=group_lane(t[1]), **args)

"""Workload-history plane: per-digest observed execution profiles.

The trace/stats seams (PRs 3/5/8) already measure everything a learned
router needs — device vs host task walls, compile hits, wire/logical
bytes, sched wait, the typed fallback taxonomy. This module closes the
loop: `WorkloadProfile` aggregates those counters at statement
completion, keyed (statement digest, power-of-two row bucket), and
`decide()` turns the history into an engine verdict the cop client's
`auto` routing consults before falling back to the static heuristics
("Tailwind: A Practical Framework for Query Accelerators",
arXiv 2604.28079 — route by observed cost, explore when blind; the cost
asymmetries are those of "Query Processing on Tensor Computation
Runtimes", arXiv 2203.01877).

Policy, in order:

  * a digest whose device attempts are ALL typed lowering declines goes
    straight to host — zero further plan-for/decline round-trips;
  * an exact (digest, bucket) entry with measured per-task walls on both
    engines routes to the cheaper one;
  * a one-sided entry borrows the missing engine's cost from the nearest
    sibling bucket of the SAME digest, at most ``SIBLING_MAX_OCTAVES``
    away — task cost at these sizes is fixed-overhead dominated, so the
    nearest bucket's RAW per-task wall beats a per-row extrapolation
    (which would scale a fixed dispatch cost linearly and misroute);
    farther siblings are treated as no evidence;
  * anything else returns None: the caller explores via the static
    heuristic, and every ``REEXPLORE_EVERY``-th repeat of a learned key
    also returns None so drift (schema growth, lane health) re-measures
    the static arm instead of exploiting a stale verdict forever.

Entries are a bounded LRU; per-table invalidation rides the existing
version seams (TileCache.invalidate_table for DDL/TRUNCATE/RESTORE,
Storage.bump_version for data-version bumps) — a table whose content
changed invalidates every entry that touched it, so stale walls never
steer routing. The profile lock is a leaf (rank `workload` in
tools/analyze/lock_order.toml): nothing else is ever acquired under it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

BUCKET_MIN = 256  # smallest row bucket (matches the device tile floor)
CAPACITY = 512  # (digest, bucket) entries before LRU eviction
EWMA_ALPHA = 0.3  # per-task wall smoothing (recent executions dominate)
FAULT_PENALTY = 2.0  # a device fault doubles the lane's believed cost
REEXPLORE_EVERY = 16  # every Nth decision re-runs the static arm
SIBLING_MAX_OCTAVES = 2  # how far a borrowed sibling cost may reach


def bucket_rows(n: int) -> int:
    """Power-of-two row bucket, floored at BUCKET_MIN — the same bucketing
    the device tile layout pads to, so one bucket sees one compiled
    program shape."""
    return max(BUCKET_MIN, 1 << max(0, int(max(n, 1) - 1).bit_length()))


class _Entry:
    """Observed history for one (digest, row bucket)."""

    __slots__ = (
        "digest", "bucket", "execs", "device_attempts", "device_runs",
        "host_runs", "device_task_ms", "host_task_ms", "compile_ms",
        "wire_bytes", "logical_bytes", "sched_wait_ms", "declines",
        "fallback_errors", "breaker_skips", "tables", "decisions",
    )

    def __init__(self, digest: str, bucket: int):
        self.digest = digest
        self.bucket = bucket
        self.execs = 0  # statements observed
        self.device_attempts = 0  # tasks sent down the device path
        self.device_runs = 0  # ... that a device program actually produced
        self.host_runs = 0  # tasks the host engine ran
        self.device_task_ms = 0.0  # EWMA wall per device-path task
        self.host_task_ms = 0.0  # EWMA wall per host-path task
        self.compile_ms = 0.0  # total XLA compile wall attributed
        self.wire_bytes = 0.0
        self.logical_bytes = 0.0
        self.sched_wait_ms = 0.0
        self.declines = 0  # typed not_lowerable declines
        self.fallback_errors = 0  # device faults that fell to host
        self.breaker_skips = 0
        self.tables: set[int] = set()
        self.decisions = 0  # decide() consultations answered from here


def _ewma(old: float, sample: float) -> float:
    if old <= 0.0:
        return sample
    return (1.0 - EWMA_ALPHA) * old + EWMA_ALPHA * sample


class WorkloadProfile:
    """Bounded per-store history of observed statement execution profiles,
    fed at statement completion from the per-statement trace counters and
    consulted per cop task by the `auto` engine router."""

    def __init__(self, capacity: int = CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()  # leaf lock (lock_order: workload)
        self._entries: OrderedDict[tuple[str, int], _Entry] = OrderedDict()
        self._digests: dict[str, dict[int, _Entry]] = {}
        self._by_table: dict[int, set[tuple[str, int]]] = {}
        self.invalidations = 0  # entries dropped by version bumps

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._digests.clear()
            self._by_table.clear()

    # --- feed (statement completion) ---------------------------------------

    def observe(self, digest: str, counters: dict, tables=()) -> None:
        """Fold one finished statement's trace counters into the history.

        Called from the session's statement-completion seam with the same
        counter dict the slow log / STATEMENTS_SUMMARY read — tasks,
        processed_rows, tpu/host task counts, the measured per-path walls
        and the typed decline/fault counters all arrive through the one
        `st()` both-sink the cop client already feeds."""
        tasks = int(counters.get("tasks", 0))
        if not digest or tasks <= 0:
            return
        rows = counters.get("processed_rows", 0.0)
        bucket = bucket_rows(int(rows / tasks))
        dev_attempts = int(counters.get("tpu_tasks", 0))
        declines = int(counters.get("lowering_declines", 0))
        host_runs = int(counters.get("host_tasks", 0))
        dev_ms = counters.get("device_task_ms", 0.0)
        host_ms = counters.get("host_ms", 0.0)
        key = (digest, bucket)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = _Entry(digest, bucket)
                self._entries[key] = e
                self._digests.setdefault(digest, {})[bucket] = e
                while len(self._entries) > self.capacity:
                    _, old = self._entries.popitem(last=False)
                    self._unlink_locked(old)
            else:
                self._entries.move_to_end(key)
            e.execs += 1
            e.device_attempts += dev_attempts
            e.device_runs += max(dev_attempts - declines, 0)
            e.host_runs += host_runs
            e.declines += declines
            e.fallback_errors += int(counters.get("fallback_errors", 0))
            e.breaker_skips += int(counters.get("breaker_skips", 0))
            e.compile_ms += counters.get("compile_ms", 0.0)
            e.wire_bytes += counters.get("wire_bytes", 0.0)
            e.logical_bytes += counters.get("logical_bytes", 0.0)
            e.sched_wait_ms += counters.get("sched_wait_ms", 0.0)
            if dev_attempts > 0 and dev_ms > 0.0:
                e.device_task_ms = _ewma(e.device_task_ms, dev_ms / dev_attempts)
            if host_runs > 0 and host_ms > 0.0:
                e.host_task_ms = _ewma(e.host_task_ms, host_ms / host_runs)
            for t in tables:
                if t not in e.tables:
                    e.tables.add(t)
                    self._by_table.setdefault(t, set()).add(key)

    def _unlink_locked(self, e: _Entry) -> None:
        buckets = self._digests.get(e.digest)
        if buckets is not None:
            buckets.pop(e.bucket, None)
            if not buckets:
                self._digests.pop(e.digest, None)
        for t in e.tables:
            keys = self._by_table.get(t)
            if keys is not None:
                keys.discard((e.digest, e.bucket))
                if not keys:
                    self._by_table.pop(t, None)

    # --- consult (per cop task) ---------------------------------------------

    def decide(self, digest: str, n_rows: int):
        """→ ("device"|"host", reason, evidence) or None (= explore via the
        static heuristic). Overrides (open breakers, mem degrade, watch
        quarantine) are the CALLER's job — they must win even over fresh
        history, so they sit above this call, not inside it."""
        if not digest:
            return None
        bucket = bucket_rows(n_rows)
        with self._lock:
            buckets = self._digests.get(digest)
            if not buckets:
                return None
            attempts = sum(e.device_attempts for e in buckets.values())
            runs = sum(e.device_runs for e in buckets.values())
            declines = sum(e.declines for e in buckets.values())
            if declines > 0 and attempts > 0 and runs == 0:
                # every device attempt this digest ever made was a typed
                # lowering decline: the engine would scan host lanes anyway
                # — skip the plan-for round-trip entirely
                return ("host", "learned_decline",
                        f"declines:{declines}/attempts:{attempts}")
            e = buckets.get(bucket)
            if e is None:
                return None  # first sight of this bucket: explore
            e.decisions += 1
            if e.decisions % REEXPLORE_EVERY == 0:
                return None  # periodic re-measure of the static arm
            dcost, dsrc = self._cost_locked(buckets, bucket, device=True)
            hcost, hsrc = self._cost_locked(buckets, bucket, device=False)
            if dcost is None or hcost is None:
                return None  # one-sided with no usable sibling: explore
            ev = (f"device {dcost:.3f}ms/task ({dsrc}) vs "
                  f"host {hcost:.3f}ms/task ({hsrc}), execs:{e.execs}")
            if dcost <= hcost:
                return ("device", "history_device", ev)
            return ("host", "history_host", ev)

    @staticmethod
    def _cost_locked(buckets: dict, bucket: int, device: bool):
        """Per-task cost for one engine at `bucket`: the exact entry when
        it has evidence, else the nearest sibling bucket within
        SIBLING_MAX_OCTAVES (raw, not per-row-scaled — see module doc)."""
        e = buckets.get(bucket)
        if e is not None:
            c = e.device_task_ms if device else e.host_task_ms
            if c > 0.0:
                return c, f"b{bucket}"
        target = bucket.bit_length()
        best = None
        for b, s in buckets.items():
            if b == bucket:
                continue
            c = s.device_task_ms if device else s.host_task_ms
            if c <= 0.0:
                continue
            dist = abs(b.bit_length() - target)
            if dist > SIBLING_MAX_OCTAVES:
                continue
            if best is None or dist < best[0]:
                best = (dist, c, b)
        if best is None:
            return None, ""
        return best[1], f"sibling b{best[2]}"

    # --- invalidation (schema / data version bumps) --------------------------

    def invalidate_table(self, table_id: int) -> None:
        """Drop every entry whose statement touched `table_id` — chained
        from TileCache.invalidate_table (DDL, TRUNCATE, RESTORE, ingest)."""
        with self._lock:
            keys = self._by_table.pop(table_id, None)
            if not keys:
                return
            for key in keys:
                e = self._entries.pop(key, None)
                if e is None:
                    continue
                self.invalidations += 1
                e.tables.discard(table_id)
                self._unlink_locked(e)

    def invalidate_prefixes(self, prefixes) -> None:
        """Data-version seam (Storage.bump_version): every committed write
        bumps its table prefixes; measured walls for a changed table are
        stale (row counts moved) and must not steer routing."""
        from ..codec.tablecodec import decode_table_id

        for p in prefixes:
            if len(p) >= 9 and p[:1] == b"t":
                try:
                    tid = decode_table_id(p)
                except Exception:  # noqa: BLE001 — foreign keyspace prefix
                    continue
                self.invalidate_table(tid)

    # --- introspection (memtable / EXPLAIN evidence) --------------------------

    def snapshot(self) -> list[dict]:
        """Point-in-time rows for information_schema.tidb_workload_profile,
        most-recently-used first."""
        with self._lock:
            out = []
            for e in reversed(self._entries.values()):
                out.append({
                    "digest": e.digest,
                    "bucket": e.bucket,
                    "execs": e.execs,
                    "device_attempts": e.device_attempts,
                    "device_runs": e.device_runs,
                    "host_runs": e.host_runs,
                    "device_task_ms": e.device_task_ms,
                    "host_task_ms": e.host_task_ms,
                    "compile_ms": e.compile_ms,
                    "wire_bytes": e.wire_bytes,
                    "logical_bytes": e.logical_bytes,
                    "sched_wait_ms": e.sched_wait_ms,
                    "declines": e.declines,
                    "fallback_errors": e.fallback_errors,
                    "breaker_skips": e.breaker_skips,
                    "decisions": e.decisions,
                    "tables": sorted(e.tables),
                })
            return out

"""Security-Enhanced Mode (ref: util/sem/sem.go): a process-level switch
(config/CLI, NOT settable via SQL) that hides high-risk surfaces even
from SUPER users — restricted system variables reject SET and read as
empty, restricted introspection tables disappear, and the FILE surface
(SELECT INTO OUTFILE, LOAD_FILE, LOAD DATA from server paths) is denied.
"""

from __future__ import annotations

_ENABLED = False

# sysvars invisible/unsettable under SEM (ref: sem.go restrictedVariables)
RESTRICTED_VARIABLES = frozenset((
    "tidb_general_log",
    "tidb_snapshot",
    "tidb_enable_telemetry",
    "tidb_force_priority",
    "tidb_row_format_version",
))

# information_schema tables hidden under SEM (ref: sem.go restrictedTables)
RESTRICTED_TABLES = frozenset((
    "slow_query",
    "metrics",
    "metrics_summary",
    "deadlocks",
    "top_sql",
))


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:  # tests only — the reference has no runtime off-switch
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def check_variable(name: str) -> None:
    if _ENABLED and name in RESTRICTED_VARIABLES:
        raise ValueError(
            f"Variable '{name}' is unsupported when security enhanced mode is enabled"
        )


def check_table(name: str) -> bool:
    """True when the memtable is visible under the current mode."""
    return not (_ENABLED and name.lower() in RESTRICTED_TABLES)


def check_file_access() -> None:
    if _ENABLED:
        from ..errors import TiDBError

        raise TiDBError(
            "FILE operations are not permitted when security enhanced mode is enabled"
        )

"""tidb_tpu — a TPU-native distributed SQL framework.

A brand-new framework with the capabilities of the reference (TiDB, a
MySQL-compatible distributed HTAP database — see SURVEY.md): SQL parser,
cost-based planner, chunk-vectorized volcano executor, MVCC/2PC storage,
and a coprocessor-pushdown boundary — where pushed-down query fragments
(scan/filter/aggregate/TopN/limit and MPP exchange) execute as fused
JAX/XLA programs on TPU meshes instead of a Go/Rust coprocessor.

Layering (top to bottom), mirroring reference SURVEY.md §1:
  session/   — session lifecycle, SQL driver        (ref: session/)
  parser/    — SQL text → AST                       (ref: pingcap/parser)
  planner/   — logical rules + physical cop/root    (ref: planner/core)
  executor/  — chunk volcano executors              (ref: executor/)
  copr/      — coprocessor client + TPU/host engine (ref: store/copr + unistore/cophandler)
  storage/   — MVCC KV, TSO, 2PC                    (ref: kv/ + unistore/tikv)
  chunk/     — columnar batches + device tiles      (ref: util/chunk)
  expr/      — vectorized expressions, JAX lowering (ref: expression/)
  mysqltypes — value domain                         (ref: types/)
  codec/     — key/row encodings                    (ref: util/codec, tablecodec)
  parallel/  — mesh sharding, collectives, MPP      (ref: store/copr/mpp.go, TiFlash)

Importing the top-level package is cheap and jax-free; device-facing
modules (copr.tpu_engine, parallel, expr lowering) import
`tidb_tpu.jaxenv` which configures JAX on first use.
"""

__version__ = "0.1.0"

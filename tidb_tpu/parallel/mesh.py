"""Mesh-parallel cop execution (SURVEY §2.13, §5.8 — the TPU-native
replacement for region-parallel cop fan-out and TiFlash MPP exchange).

Mapping (reference mechanism → mesh construct):
  region-parallel scan (copr/coprocessor.go:151)   → rows sharded over the
      "dp" mesh axis; each device runs the fused scan/filter/partial-agg
      kernel on its shard
  partial/final agg split (aggregation descriptors) → local segment_sum
      partials + `psum` over "dp" — exact for scaled-int decimals
  MPP hash exchange (cophandler/mpp_exec.go:109)    → `all_to_all` over the
      mesh axis after bucketing rows by key hash (hash_repartition)

Everything is jit-compiled once per (shape, mesh) and runs identically on
one real TPU, a v4-8 slice, or the virtual CPU mesh used in tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

from ..jaxenv import jax, jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 JAX keeps it in the experimental namespace
    # check_rep's rep-rule table is incomplete there (a nested-pjit rule
    # returns None and _check_rep crashes) — it is a validation pass only,
    # so disable it rather than lose the whole mesh path
    import functools

    from jax.experimental.shard_map import shard_map as _esm

    shard_map = functools.partial(_esm, check_rep=False)

_US_DAY = 24 * 60 * 60 * 1_000_000


def make_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


@dataclass(frozen=True)
class Q1Spec:
    """Static spec of the fused Q1 cop program (the flagship kernel)."""

    nseg: int = 8  # |returnflag dict| x |linestatus dict| padded (3*2 → 8)
    cutoff: int = 0  # packed shipdate cutoff (constant folded into program)


def q1_local_kernel(spec: Q1Spec, qty, price, disc, tax, rf, ls, ship, row_valid):
    """One shard's fused Q1: filter → group codes → partial segment sums.

    All decimal lanes are scaled int64 (scale 2); products carry scale 4/6.
    Output: tuple of [nseg] partial states (count, sums...), exact ints.
    """
    mask = row_valid & (ship <= spec.cutoff)
    code = rf * 2 + ls  # dict codes: rf in {0,1,2}, ls in {0,1}
    seg = jnp.where(mask, code, spec.nseg)  # masked rows → overflow slot

    def ssum(x):
        return jax.ops.segment_sum(x, seg, num_segments=spec.nseg + 1)[: spec.nseg]

    m64 = mask.astype(jnp.int64)
    disc_price = price * (100 - disc)  # scale 4
    charge = disc_price * (100 + tax)  # scale 6
    return (
        ssum(m64),  # count
        ssum(jnp.where(mask, qty, 0)),  # sum_qty (s2)
        ssum(jnp.where(mask, price, 0)),  # sum_base_price (s2)
        ssum(jnp.where(mask, disc_price, 0)),  # sum_disc_price (s4)
        ssum(jnp.where(mask, charge, 0)),  # sum_charge (s6)
        ssum(jnp.where(mask, disc, 0)),  # sum_disc (s2, for avg)
    )


def distributed_q1_step(mesh: Mesh, spec: Q1Spec, axis: str = "dp"):
    """The full distributed step: shard rows over `axis`, run the fused
    local kernel, merge partials with an exact int64 `psum` over ICI.
    Returns a jitted fn over [n_dev * rows] arrays."""

    def step(qty, price, disc, tax, rf, ls, ship, row_valid):
        def local(qty, price, disc, tax, rf, ls, ship, rv):
            parts = q1_local_kernel(spec, qty, price, disc, tax, rf, ls, ship, rv)
            return tuple(jax.lax.psum(p, axis) for p in parts)

        sharded = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis),) * 8,
            out_specs=(P(),) * 6,
        )
        return sharded(qty, price, disc, tax, rf, ls, ship, row_valid)

    return jax.jit(step)


def hash_repartition(mesh: Mesh, cap: int | None = None, axis: str = "dp"):
    """The MPP exchange primitive: redistribute rows so that rows with
    equal key land on the same device (key % n_devices ownership), via
    `all_to_all` over the mesh axis (ref: ExchangeSender hash mode,
    cophandler/mpp_exec.go:109-206; TiFlash exchange → ICI collective).

    Takes [n_dev*rows] key + payload lanes; returns per-device buckets
    [n_dev*cap]. `cap` is the per-peer send-buffer size: default (None)
    = local rows, which can never drop; a smaller cap trades memory for a
    nonzero `dropped` count (skew overflow — spill path is host-side).
    Returns a jitted fn → (keys_out, payload_out, valid_out, dropped)."""
    n_dev = mesh.shape[axis]
    fixed_cap = cap

    def step(keys, payload, valid):
        def local(keys, payload, valid):
            keys = keys.reshape(-1)
            payload = payload.reshape(-1)
            valid = valid.reshape(-1)
            rows = keys.shape[0]
            cap = fixed_cap if fixed_cap is not None else rows
            owner = (keys % n_dev).astype(jnp.int32)
            # stable-sort rows by owner so each peer's rows are contiguous
            order = jnp.argsort(jnp.where(valid, owner, n_dev))
            keys_s = keys[order]
            pay_s = payload[order]
            val_s = valid[order]
            own_s = jnp.where(val_s, owner[order], n_dev)
            # per-owner counts and in-bucket offsets
            counts = jax.ops.segment_sum(val_s.astype(jnp.int32), own_s, num_segments=n_dev + 1)[:n_dev]
            starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
            idx = jnp.arange(rows)
            within = idx - starts[jnp.clip(own_s, 0, n_dev - 1)]
            # scatter into [n_dev, cap] send buffers
            buf_k = jnp.zeros((n_dev, cap), dtype=keys.dtype)
            buf_p = jnp.zeros((n_dev, cap), dtype=payload.dtype)
            buf_v = jnp.zeros((n_dev, cap), dtype=bool)
            ok = val_s & (within < cap)
            tgt = (jnp.clip(own_s, 0, n_dev - 1), jnp.clip(within, 0, cap - 1))
            buf_k = buf_k.at[tgt].set(jnp.where(ok, keys_s, 0))
            buf_p = buf_p.at[tgt].set(jnp.where(ok, pay_s, 0))
            buf_v = buf_v.at[tgt].set(ok)
            dropped = jnp.sum(val_s) - jnp.sum(ok)
            # the exchange: axis-wise all_to_all of the per-peer buffers
            rk = jax.lax.all_to_all(buf_k, axis, 0, 0, tiled=True)
            rp = jax.lax.all_to_all(buf_p, axis, 0, 0, tiled=True)
            rv = jax.lax.all_to_all(buf_v, axis, 0, 0, tiled=True)
            return rk.reshape(-1), rp.reshape(-1), rv.reshape(-1), jax.lax.psum(dropped, axis)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis), P()),
        )(keys, payload, valid)

    return jax.jit(step)


def build_q1_arrays(n_rows: int, n_shards: int = 1, seed: int = 7):
    """Tiny-shape Q1 inputs: [n_shards * rows_per_shard] padded lanes."""
    from ..models.tpch import gen_lineitem
    from ..mysqltypes.coretime import parse_datetime

    cols = gen_lineitem(n_rows, seed)
    per = -(-n_rows // n_shards)
    total = per * n_shards

    def pad(a, dtype):
        out = np.zeros(total, dtype=dtype)
        out[:n_rows] = a
        return out

    rf_codes = np.searchsorted(np.array(["A", "N", "R"]), cols["l_returnflag"].astype("U"))
    ls_codes = np.searchsorted(np.array(["F", "O"]), cols["l_linestatus"].astype("U"))
    rv = np.zeros(total, dtype=bool)
    rv[:n_rows] = True
    args = (
        pad(cols["l_quantity"], np.int64),
        pad(cols["l_extendedprice"], np.int64),
        pad(cols["l_discount"], np.int64),
        pad(cols["l_tax"], np.int64),
        pad(rf_codes, np.int64),
        pad(ls_codes, np.int64),
        pad(cols["l_shipdate"], np.int64),
        rv,
    )
    spec = Q1Spec(nseg=6, cutoff=int(parse_datetime("1998-09-02")))
    return spec, args

from .mesh import make_mesh, Q1Spec, build_q1_arrays, q1_local_kernel, distributed_q1_step, hash_repartition

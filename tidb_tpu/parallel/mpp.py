"""Mesh MPP engine — the TiFlash-MPP replacement (SURVEY §3.4, §2.13.4).

The reference dispatches plan fragments to stores and streams hash-
partitioned chunks between them over gRPC tunnels (copr/mpp.go:461
DispatchMPPTasks, cophandler/mpp_exec.go exchange/join/agg executors).
Here the whole fragment tree compiles into ONE jit-compiled SPMD program
over a `jax.sharding.Mesh`:

    scan shards (P("dp"))            TableScan + Selection, fused
      │  [optional all_to_all]       ExchangeSender(hash) → ICI collective
      ▼
    local equi-join                  sort build keys + searchsorted probe
      │                              (unique build side: FK/PK joins)
      ▼
    partial agg + psum               Aggregation partial/final split
      ▼
    host finalize                    FinalHashAggExec (exact decimals)

Design notes:
  * broadcast join: build lanes enter the shard_map replicated (P()) —
    the all_gather is free at dispatch; probe stays sharded.
  * shuffle join: both sides bucketed by key%n_dev and exchanged with
    `all_to_all` (send caps sized so nothing can drop: cap == local rows).
  * the build side must have unique join keys (checked host-side on the
    unfiltered lane — a superset, hence safe). Non-unique build → host
    hash join fallback.
  * static shapes everywhere; programs cached per (plan digest, shapes,
    mesh) exactly like the TPU cop engine's jit cache.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..jaxenv import jax, jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 JAX keeps it in the experimental namespace
    # check_rep's rep-rule table is incomplete there (a nested-pjit rule
    # returns None and _check_rep crashes) — it is a validation pass only,
    # so disable it rather than lose the whole mesh path
    import functools

    from jax.experimental.shard_map import shard_map as _esm

    shard_map = functools.partial(_esm, check_rep=False)

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..expr.expression import Column as ExprCol, Constant, Expression
from ..mysqltypes.datum import Datum
from ..planner.fragment import BROADCAST, HASH, JoinFrag, MPPPlan, ScanFrag
from ..utils import metrics as M
from ..utils.memory import consume_current

I64_MAX = np.iinfo(np.int64).max
DIRECT_GROUP_MAX = 1 << 16


class ScanData:
    """Host-side lanes for one scan: full numpy columns (for output
    gather) plus dict-encoded device lanes for the columns the program
    reads. Built by the gather executor from tile-cache batches."""

    def __init__(self, frag: ScanFrag, data: list[np.ndarray], valid: list[np.ndarray],
                 version: int = -1, shared=None, orig_offs: list[int] | None = None):
        self.frag = frag
        self.data = data  # per ds.out_cols position
        self.valid = valid
        self.n_rows = len(data[0]) if data else 0
        self.vocabs: dict[int, list] = {}
        self._dev: dict[int, np.ndarray] = {}
        # (table_id, data_version) identity for the engine's device-lane
        # cache; -1 disables caching (unknown provenance)
        self.version = version
        self.shared = shared  # MPPEngine, for cross-dispatch stat caches
        self.orig_offs = orig_offs  # table-level offsets per local position

    def lane(self, off: int) -> tuple[np.ndarray, np.ndarray]:
        """Device-shaped lane for a scan-local column offset (dict-encodes
        object lanes on first use; encodings cache per table version)."""
        if off not in self._dev:
            d, v = self.data[off], self.valid[off]
            if d.dtype == object:
                from ..copr.tpu_engine import _dict_encode_lane

                def enc(_d=d, _v=v):
                    codes, vocab = _dict_encode_lane(_d, _v)
                    return codes.astype(np.int64), vocab

                if self.shared is not None and self.version >= 0 and self.orig_offs:
                    d, vocab = self.shared._cached_stat(
                        self, ("enc", self.orig_offs[off]), enc
                    )
                else:
                    d, vocab = enc()
                self.vocabs[off] = vocab
            elif d.dtype == bool:
                d = d.astype(np.int64)
            self._dev[off] = d
        return self._dev[off], self.valid[off]


def _pad(a: np.ndarray, total: int):
    out = np.zeros(total, dtype=a.dtype)
    out[: len(a)] = a
    return out


class _Level:
    """Static per-join-level metadata resolved on host before compile."""

    def __init__(self, frag: JoinFrag, key_lo: list[int], key_stride: list[int]):
        self.frag = frag
        self.key_lo = key_lo
        self.key_stride = key_stride
        self.r_post: list[Expression] = []
        self.mult = 1  # 1 = unique build keys, 2 = compact dup path
        self.expected_out: int | None = None  # exact pre-filter join card
        self.key_i32 = False  # packed key domain fits int32 sort lanes


class MPPEngine:
    DEV_CACHE_BYTES = 4 << 30  # device-lane cache budget

    def __init__(self):
        self._programs: dict = {}
        self.compile_count = 0
        # per-reason fallback accounting (PR 8): every decline/degrade is
        # counted under its TYPED reason key and fed to the labeled
        # tidb_tpu_fallback_total{path="mpp"} series — the bare counter
        # the DB inspection row used to read is now the sum (`fallbacks`)
        self.fallback_counts: dict[str, int] = {}
        self.last_fallback_reason = ""  # EXPLAIN ANALYZE / bench surface
        self._decline_key = "not_supported"  # typed key behind the text
        # device-resident input lanes keyed by (table_id, version, tag,
        # total, sharded): re-dispatching the same fragment plan must NOT
        # re-upload unchanged table lanes — over a remote device link the
        # upload dwarfs the compute (the MPP analog of the cop tile cache)
        self._dev_cache: dict = {}
        self._dev_cache_nbytes = 0
        # host-side analysis results (lane min/max/gcd, build multiplicity,
        # dict encodings, concatenated lanes) keyed by (table, version, tag);
        # byte-budgeted LRU like the device cache — a long-lived server
        # must not pin every column of every table it ever joined
        self._stat_cache: dict = {}
        self._stat_cache_nbytes = 0
        self._host_lane_cache: dict = {}
        self._host_lane_nbytes = 0

    HOST_CACHE_BYTES = 4 << 30
    STAT_CACHE_BYTES = 1 << 30

    # --- typed fallback accounting ---------------------------------------

    @property
    def fallbacks(self) -> int:
        """Total declined/failed mesh dispatches (back-compat read; the
        per-reason split lives in `fallback_counts`)."""
        return sum(self.fallback_counts.values())

    def _decline(self, key: str, detail: str) -> None:
        """Record WHY prepare refused the mesh: a typed reason key for the
        labeled metric plus the human detail the enforce_mpp warning and
        EXPLAIN ANALYZE carry. execute() turns it into ONE counted
        fallback when prepare comes back empty."""
        self._decline_key = key
        self.last_fallback_reason = detail

    def _fallback(self, key: str, detail: str | None = None) -> None:
        """Count one fallback under its typed reason and feed the labeled
        series (`tidb_tpu_fallback_total{path="mpp", reason=key}`)."""
        self.fallback_counts[key] = self.fallback_counts.get(key, 0) + 1
        self._decline_key = key  # the trace-span reason must match too
        if detail is not None:
            self.last_fallback_reason = detail
        M.TPU_FALLBACK.inc(path="mpp", reason=key)

    @staticmethod
    def _entry_nbytes(ent) -> int:
        n = 0
        for x in ent if isinstance(ent, (tuple, list)) else (ent,):
            nb = getattr(x, "nbytes", None)
            if nb is not None:
                n += nb
            elif isinstance(x, (list, str, bytes)):
                n += 64 * len(x)  # vocab lists etc., rough
            else:
                n += 64
        return n

    def _host_lane_put(self, key, ent) -> None:
        for k in [k for k in self._host_lane_cache
                  if k[0] == key[0] and k[2] == key[2] and k[1] != key[1]]:
            self._host_lane_nbytes -= self._entry_nbytes(self._host_lane_cache.pop(k))
        self._host_lane_cache[key] = ent
        self._host_lane_nbytes += self._entry_nbytes(ent)
        while self._host_lane_nbytes > self.HOST_CACHE_BYTES and self._host_lane_cache:
            k = next(iter(self._host_lane_cache))
            self._host_lane_nbytes -= self._entry_nbytes(self._host_lane_cache.pop(k))

    def _stat_key(self, sd, tag):
        """Cache key for host analyses over a scan lane set; None when the
        scan has no (table, version) identity."""
        if sd.version < 0:
            return None
        return (sd.frag.ds.table.id, sd.version, tag)

    def _cached_stat(self, sd, tag, compute):
        key = self._stat_key(sd, tag)
        if key is None:
            return compute()
        ent = self._stat_cache.get(key)
        if ent is None:  # entries are 1-tuples so a None RESULT still caches
            ent = (compute(),)
            # evict stale versions of the same (table, tag)
            for k in [k for k in self._stat_cache
                      if k[0] == key[0] and k[2] == key[2] and k[1] != key[1]]:
                self._stat_cache_nbytes -= self._entry_nbytes(self._stat_cache.pop(k))
            self._stat_cache[key] = ent
            self._stat_cache_nbytes += self._entry_nbytes(ent)
            while self._stat_cache_nbytes > self.STAT_CACHE_BYTES and self._stat_cache:
                k = next(iter(self._stat_cache))
                self._stat_cache_nbytes -= self._entry_nbytes(self._stat_cache.pop(k))
        return ent[0]

    def _lane_minmax(self, sd, off):
        """(lo, hi) of a lane's present values, or None when empty/float —
        cached per (table, version, offset): prepare() runs per dispatch
        but the answer only changes when the table does."""
        def compute():
            d, v = sd.lane(off)
            if d.dtype.kind == "f":
                return "float"
            if not v.any():
                return None
            return (int(d[v].min()), int(d[v].max()))

        return self._cached_stat(sd, ("minmax", off), compute)

    def _dev_put(self, key, build):
        """Device array for `key`, uploading via build() on miss. Stale
        versions of the same (table, tag) are evicted eagerly; the rest
        LRU under DEV_CACHE_BYTES."""
        if key is None:
            arr = jnp.asarray(build())
            # uncacheable mesh upload: still this statement's volume —
            # the MPP path charges the same TLS tracker seam the cop
            # engine's h2d does, so memory arbitration sees MPP too
            consume_current(arr.nbytes)
            return arr
        hit = self._dev_cache.get(key)
        if hit is not None:
            self._dev_cache[key] = self._dev_cache.pop(key)  # LRU touch
            return hit
        tid, ver, tag = key[0], key[1], key[2]
        for k in [k for k in self._dev_cache if k[0] == tid and k[2] == tag and k[1] != ver]:
            self._dev_cache_nbytes -= self._dev_cache.pop(k).nbytes
        arr = jnp.asarray(build())
        consume_current(arr.nbytes)  # uploader pays (volume proxy, PR 4 rule)
        self._dev_cache[key] = arr
        self._dev_cache_nbytes += arr.nbytes
        while self._dev_cache_nbytes > self.DEV_CACHE_BYTES and self._dev_cache:
            _, old = next(iter(self._dev_cache.items()))
            self._dev_cache_nbytes -= old.nbytes
            del self._dev_cache[next(iter(self._dev_cache))]
        return arr

    # ------------------------------------------------------------ planning

    @staticmethod
    def _restream_largest(mplan: MPPPlan, by_frag: dict) -> None:
        """Rotate an all-inner left-deep fragment chain so the LARGEST
        scan is the sharded probe stream (ref: TiFlash picks the fact
        side as the MPP stream; exhaust_physical_plans.go build-side
        choice). Dimension tables then sit on the build side where their
        keys are usually unique — the 1:1 searchsorted probe instead of
        the compact duplicate-key path. Pure fragment-tree rewrite: the
        joined-schema side_offsets (lanemap keys, agg/post-cond indices)
        are per-scan and unchanged; the host plan is untouched."""
        levels = []
        f = mplan.root
        while isinstance(f, JoinFrag):
            if f.kind != "inner":
                return
            levels.append(f)
            f = f.probe
        if not isinstance(f, ScanFrag) or len(levels) < 2:
            return
        chain_scans = [f] + [lv.build for lv in reversed(levels)]

        def owner(j):
            for s in chain_scans:
                if s.side_offset <= j < s.side_offset + s.n_cols:
                    return s
            return None

        pairs = []
        for lv in levels:
            for pk, bk in zip(lv.probe_keys, lv.build_keys):
                if owner(pk) is None or owner(bk) is None:
                    return
                pairs.append((pk, bk))
        all_post = [c for lv in levels for c in lv.post_conds]
        stream = max(chain_scans, key=lambda s: by_frag[id(s)].n_rows)
        if stream is f:
            return  # already streaming the largest
        remaining_pairs = list(pairs)
        used = {id(stream)}
        node = stream
        remaining = [s for s in chain_scans if s is not stream]
        pending_post = list(all_post)

        def attachable(cond):
            refs: set = set()
            cond.collect_columns(refs)
            return all(id(owner(j)) in used for j in refs if owner(j) is not None)

        while remaining:
            attached = None
            for s in remaining:
                link = []
                for a, b in remaining_pairs:
                    oa, ob = owner(a), owner(b)
                    if oa is s and id(ob) in used:
                        link.append((b, a))  # (probe side, build side)
                    elif ob is s and id(oa) in used:
                        link.append((a, b))
                if link:
                    attached = s
                    for pkk, bkk in link:
                        for p in list(remaining_pairs):
                            if p in ((pkk, bkk), (bkk, pkk)):
                                remaining_pairs.remove(p)
                                break
                    node = JoinFrag(
                        node, s, "inner",
                        [p for p, _ in link], [b for _, b in link],
                    )
                    used.add(id(s))
                    remaining.remove(s)
                    # inner-join filters commute: attach each residual
                    # cond at the EARLIEST level with all its columns, so
                    # selective filters still prune before later
                    # exchanges (review: hoisting everything to the root
                    # fed unfiltered rows through exchange buckets)
                    here = [c for c in pending_post if attachable(c)]
                    if here:
                        node.post_conds = here
                        pending_post = [c for c in pending_post if c not in here]
                    break
            if attached is None:
                return  # not a connected chain under this rotation: keep
        if remaining_pairs or pending_post:
            return  # something didn't map onto the rotated tree: keep
        mplan.root = node

    def prepare(self, mplan: MPPPlan, scans: list[ScanData], variables: dict,
                gate=None):
        """Resolve all data-dependent static choices; None → fallback.
        `gate` (optional () -> None) is the scheduler's shared interrupt
        gate: the per-scan rewrites and per-level key analyses below walk
        O(table bytes) of host lanes, and a KILL/deadline/runaway verdict
        must land between levels, not after the whole analysis."""
        from ..copr.tpu_engine import TPUEngine

        tick = gate if gate is not None else (lambda: None)
        by_frag = {id(s.frag): s for s in scans}
        self._restream_largest(mplan, by_frag)
        scan_of_joined = {}  # joined idx -> (ScanData, local off)
        for s in scans:
            for off in range(len(s.frag.ds.out_cols)):
                scan_of_joined[s.frag.side_offset + off] = (s, off)

        # rewrite pushed conds per scan (string → dict-code space)
        r_pushed: dict[int, list] = {}
        eng = TPUEngine()
        for s in scans:
            tick()
            conds = s.frag.ds.pushed_conds
            used: set[int] = set()
            for c in conds:
                c.collect_columns(used)
            vocabs = {}
            for off in used:
                s.lane(off)
                if off in s.vocabs:
                    vocabs[off] = s.vocabs[off]
            rc = [eng._rewrite(c, vocabs) for c in conds]
            if any(c is None for c in rc):
                self._decline("non_lowerable_cond", "non-lowerable pushed condition")
                return None
            r_pushed[id(s)] = rc

        # per join level: key packing + uniqueness + exchange mode
        threshold = int(variables.get("tidb_broadcast_join_threshold_count", 10240))
        size_threshold = int(
            variables.get("tidb_broadcast_join_threshold_size", 100 * 1024 * 1024)
        )
        levels: list[_Level] = []

        def visit(frag):
            if isinstance(frag, ScanFrag):
                return True
            if not visit(frag.probe):
                return False
            tick()  # one interrupt poll per join level's key analysis
            bscan = by_frag[id(frag.build)]
            # key domains from both sides (host lanes)
            los, sizes = [], []
            for pk, bk in zip(frag.probe_keys, frag.build_keys):
                ps, poff = scan_of_joined[pk]
                bs, boff = scan_of_joined[bk]
                if poff in ps.vocabs or boff in bs.vocabs:
                    self._decline("string_join_key", "string join key")
                    return False  # dict codes differ per table
                vals = []
                for sd, off in ((ps, poff), (bs, boff)):
                    mm = self._lane_minmax(sd, off)
                    if mm == "float":
                        self._decline("float_join_key", "float join key")
                        return False
                    if mm is not None:
                        vals.append(mm)
                if not vals:
                    los.append(0)
                    sizes.append(1)
                    continue
                lo = min(a for a, _ in vals)
                hi = max(b for _, b in vals)
                los.append(lo)
                sizes.append(hi - lo + 1)
            strides = [1] * len(sizes)
            acc = 1
            for i in range(len(sizes) - 1, -1, -1):
                strides[i] = acc
                acc *= sizes[i]
                if acc > 1 << 62:
                    self._decline("domain_overflow", "join key domain overflow")
                    return False
            lvl = _Level(frag, los, strides)
            # packed keys < acc: int32 sort operands when they fit (TPU
            # sorts/gathers run ~2x faster on 32-bit lanes)
            lvl.key_i32 = acc < (1 << 31) - 2
            # build-side key multiplicity, measured on the UNFILTERED lane
            # (a safe upper bound: pushed filters only shrink groups).
            # Unique keys (FK/PK joins) probe 1:1; duplicated build keys
            # take the compact cumsum-offset path (mult=2 is a path
            # selector, not a fan-out factor — output capacity is bounded
            # by the drop-guarded join output, so no multiplicity cap).
            def key_mult(sd, key_idxs):
                """Max multiplicity (1 or 2) of a key tuple on scan `sd`,
                packed with domains derived from the KEY LANES THEMSELVES
                (never an enclosing level's tables) — cached per (table,
                version, offsets)."""
                offs2 = tuple(scan_of_joined[k][1] for k in key_idxs)

                def compute():
                    los2, sizes2 = [], []
                    for k in key_idxs:
                        mm = self._lane_minmax(*scan_of_joined[k])
                        if mm == "float" or mm is None:
                            # empty lanes have no duplicates; floats can't pack
                            if mm is None:
                                los2.append(0)
                                sizes2.append(1)
                                continue
                            return None
                        los2.append(mm[0])
                        sizes2.append(mm[1] - mm[0] + 1)
                    strides2 = [1] * len(sizes2)
                    acc = 1
                    for i in range(len(sizes2) - 1, -1, -1):
                        strides2[i] = acc
                        acc *= sizes2[i] + 1
                        if acc > 1 << 62:
                            return None
                    packed = self._pack_host(key_idxs, scan_of_joined, los2, strides2)
                    if packed is None:
                        return None
                    kv2, km2 = packed
                    present = kv2[km2]
                    if len(present):
                        _, counts = np.unique(present, return_counts=True)
                        return 1 if int(counts.max()) <= 1 else 2
                    return 1

                return self._cached_stat(sd, ("uniq", offs2), compute)

            # uniqueness is a property of the build key lanes alone
            mult = key_mult(bscan, frag.build_keys)
            if mult is None:
                self._decline("unpackable_build_keys", "unpackable build keys")
                return False
            lvl.mult = mult

            # exact pre-filter join cardinality (Σ over matched keys of
            # probe-count × build-count) — sizes the compact join's output
            # capacity tightly instead of a blanket 2×max(sides). Filters
            # only shrink the true output, so this is a hard upper bound.
            psds = {id(scan_of_joined[pk][0]) for pk in frag.probe_keys}

            def rows_preserved(f, sd):
                """True iff scan `sd`'s rows appear at most once in f's
                output — jcard measured on raw scan lanes stays a hard
                upper bound exactly then. A row survives unmultiplied
                through a join when (a) it rides the probe side and the
                build keys are unique, or (b) it IS the build side and the
                probe keys are unique (each build row matches <=1 probe
                row), recursively."""
                if isinstance(f, ScanFrag):
                    return by_frag[id(f)] is sd
                lv = next((x for x in levels if x.frag is f), None)
                if lv is None:
                    return False
                if by_frag[id(f.build)] is sd:
                    pks = {id(scan_of_joined[pk][0]) for pk in f.probe_keys}
                    if len(pks) != 1:
                        return False
                    ps2 = scan_of_joined[f.probe_keys[0]][0]
                    return rows_preserved(f.probe, ps2) and key_mult(ps2, f.probe_keys) == 1
                return lv.mult == 1 and rows_preserved(f.probe, sd)

            expected = None
            if len(psds) == 1 and mult > 1 and rows_preserved(
                frag.probe, scan_of_joined[frag.probe_keys[0]][0]
            ):
                psd = scan_of_joined[frag.probe_keys[0]][0]
                poffs = tuple(scan_of_joined[pk][1] for pk in frag.probe_keys)

                def jcard():
                    pk = self._pack_host(frag.probe_keys, scan_of_joined, los, strides)
                    bk = self._pack_host(frag.build_keys, scan_of_joined, los, strides)
                    if pk is None or bk is None:
                        return None
                    pu, pc = np.unique(pk[0][pk[1]], return_counts=True)
                    bu, bc = np.unique(bk[0][bk[1]], return_counts=True)
                    ii = np.searchsorted(pu, bu)
                    iic = np.clip(ii, 0, max(len(pu) - 1, 0))
                    m = (ii < len(pu)) & (pu[iic] == bu) if len(pu) else np.zeros(len(bu), bool)
                    return int(np.sum(pc[iic[m]] * bc[m])) if len(bu) else 0

                boffs2 = tuple(scan_of_joined[bk][1] for bk in frag.build_keys)
                tag = ("jcard", boffs2, poffs, psd.frag.ds.table.id, psd.version)
                expected = self._cached_stat(bscan, tag, jcard)
            lvl.expected_out = expected
            # broadcast only when the build side is small by BOTH row count
            # and estimated bytes (ref: tidb_broadcast_join_threshold_count
            # / _size in planner/core exhaust_physical_plans.go)
            build_bytes = bscan.n_rows * 8 * max(1, len(bscan.frag.ds.out_cols))
            frag.exchange = (
                BROADCAST
                if bscan.n_rows <= threshold and build_bytes <= size_threshold
                else HASH
            )
            # left join with extra ON conditions filters *matches*, which
            # the mask model below can't express yet → host fallback
            if frag.post_conds:
                if frag.kind != "inner":
                    self._decline("outer_join_residual",
                                  "outer join with residual ON conditions")
                    return False
                vocabs = {}
                used = set()
                for c in frag.post_conds:
                    c.collect_columns(used)
                for j in used:
                    sd, off = scan_of_joined[j]
                    sd.lane(off)
                    if off in sd.vocabs:
                        vocabs[j] = sd.vocabs[off]
                lvl.r_post = [eng._rewrite(c, vocabs) for c in frag.post_conds]
                if any(c is None for c in lvl.r_post):
                    self._decline("non_lowerable_cond", "non-lowerable ON condition")
                    return False
            levels.append(lvl)
            return True

        if not visit(mplan.root):
            return None

        agg_meta = None
        if mplan.agg is not None:
            agg_meta = self._prepare_agg(mplan, scans, scan_of_joined, eng)
            if agg_meta is None:
                # the JOIN still rides the mesh; the aggregation finishes
                # on host over the joined rows (group-key domains too wide
                # for direct addressing, e.g. raw date/orderkey keys)
                self.last_fallback_reason = "agg on host: group-key domain too wide"
        return {
            "scan_of_joined": scan_of_joined,
            "r_pushed": r_pushed,
            "levels": {id(l.frag): l for l in levels},
            "agg": agg_meta,
        }

    @staticmethod
    def _pack_host(key_idxs, scan_of_joined, los, strides):
        acc = None
        mask = None
        for j, lo, st in zip(key_idxs, los, strides):
            sd, off = scan_of_joined[j]
            d, v = sd.lane(off)
            term = (d.astype(np.int64) - lo) * st
            acc = term if acc is None else acc + term
            mask = v if mask is None else (mask & v)
        if acc is None:
            return None
        return acc, mask

    def _prepare_agg(self, mplan: MPPPlan, scans, scan_of_joined, eng):
        """Device aggregation metadata. Two modes (mirrors TPUEngine's
        dense-vs-segment split):
        - dense: direct-addressed buckets + psum when the packed key
          domain is small (ref: cophandler closure exec hash agg);
        - sorted: wide int key domains, only when a TopN over an agg
          output is fused (mplan.topn) — per-device lexsort + segment
          reduce, hash exchange by group key, final reduce, device top-k.
          The mesh then returns k groups per device instead of shipping
          the joined rows back over the (slow) host link."""
        agg = mplan.agg
        domains, key_meta = [], []
        sorted_domains = []  # step-compressed (gcd) domains for wide mode
        for g in agg.group_by:
            if not isinstance(g, ExprCol):
                return None
            sd, off = scan_of_joined[g.idx]
            d, v = sd.lane(off)
            if off in sd.vocabs:
                dom = max(len(sd.vocabs[off]), 1)
                domains.append(dom)
                sorted_domains.append(dom)
                key_meta.append(("dict", sd.vocabs[off], 1))
            else:
                if d.dtype.kind == "f" or not len(d):
                    return None

                def key_stats(_sd=sd, _off=off):
                    dd, vv = _sd.lane(_off)
                    pres = dd[vv]
                    if not len(pres):
                        return (0, 0, 1)
                    lo_, hi_ = int(pres.min()), int(pres.max())
                    # sparse int keys (e.g. microsecond-packed DATEs step
                    # by 86400e6) compress by their common stride so the
                    # packed code fits int64
                    st = int(np.gcd.reduce((pres - lo_).astype(np.int64))) or 1
                    return (lo_, hi_, st)

                lo, hi, step = self._cached_stat(sd, ("keystats", off), key_stats)
                domains.append(hi - lo + 1)
                sorted_domains.append((hi - lo) // step + 1)
                key_meta.append(("int", lo, step))
        nseg = 1
        dense_ok = True
        for s in domains:
            nseg *= s + 1
            if nseg > DIRECT_GROUP_MAX:
                dense_ok = False
                break
        mode = "dense"
        if not dense_ok:
            if mplan.topn is None:
                return None
            wide = 1
            for s in sorted_domains:
                wide *= s + 1
                if wide > 1 << 62:
                    return None  # even compressed keys overflow the code
            agg_idx = mplan.topn[0]
            if agg.aggs[agg_idx].name not in ("sum", "count"):
                return None
            mode = "sorted"
        r_args = []
        for a in agg.aggs:
            ra = []
            for x in a.args:
                if isinstance(x, ExprCol):
                    sd, off = scan_of_joined[x.idx]
                    sd.lane(off)
                    if off in sd.vocabs:
                        if a.name in ("min", "max"):
                            ra.append(x)  # code order == collation order
                            continue
                        return None
                    ra.append(x)
                    continue
                used = set()
                x.collect_columns(used)
                if any(scan_of_joined[j][1] in scan_of_joined[j][0].vocabs for j in used):
                    return None
                ra.append(x)
            r_args.append(ra)
        meta = {"domains": domains, "key_meta": key_meta, "nseg": nseg,
                "r_args": r_args, "mode": mode}
        if mode == "sorted":
            # lexicographic stride packing (NULL slot per key, radix dom+1)
            radixes = [d + 1 for d in sorted_domains]
            strides = [1] * len(radixes)
            acc = 1
            for i in range(len(radixes) - 1, -1, -1):
                strides[i] = acc
                acc *= radixes[i]
            meta["strides"] = strides
            meta["radixes"] = radixes
            meta["topn"] = mplan.topn
        return meta

    # ------------------------------------------------------------- compile

    def execute(self, mplan: MPPPlan, scans: list[ScanData], mesh: Mesh,
                variables: dict, axis: str = "dp", gate=None):
        """Run the fragment plan; returns a Chunk in partial-agg layout
        (agg case) or joined-schema layout (rows case), or None → caller
        falls back to the host join path. `gate` is the scheduler's
        shared interrupt gate, polled between fragment-level analyses and
        per-scan device uploads so KILL / deadline / runaway / OOM
        verdicts land within one level instead of after the dispatch."""
        # reset per dispatch: a stale reason from a PREVIOUS statement
        # must never leak into this one's enforce_mpp warning / EXPLAIN
        self.last_fallback_reason = ""
        self._decline_key = "not_supported"
        tick = gate if gate is not None else (lambda: None)
        meta = self.prepare(mplan, scans, variables, gate=gate)
        if meta is None:
            self._fallback(self._decline_key)
            return None
        tick()
        n_dev = mesh.shape[axis]
        # which scans are sharded: the stream source + hash-side builds
        sharded = {id(self._stream_source(mplan.root))}
        for lvl in meta["levels"].values():
            if lvl.frag.exchange == HASH:
                sharded.add(id(lvl.frag.build))

        # collect device lanes needed per scan
        need: dict[int, set] = {id(s): set() for s in scans}
        soj = meta["scan_of_joined"]
        def note(j):
            sd, off = soj[j]
            need[id(sd)].add(off)
        for lvl in meta["levels"].values():
            for j in lvl.frag.probe_keys + lvl.frag.build_keys:
                note(j)
            for c in lvl.r_post:
                used = set(); c.collect_columns(used)
                for j in used:
                    note(j)
        for s in scans:
            for c in meta["r_pushed"][id(s)]:
                used = set(); c.collect_columns(used)
                for off in used:
                    need[id(s)].add(off)
        if meta["agg"] is not None:
            for g in mplan.agg.group_by:
                note(g.idx)
            for ra in meta["agg"]["r_args"]:
                for x in ra:
                    used = set(); x.collect_columns(used)
                    for j in used:
                        note(j)

        # flatten args: per scan (in mplan.scans order): rowid, row_valid,
        # then (data, valid) per needed offset (sorted)
        args, in_specs, scan_arg_meta = [], [], []
        shapes = []
        for s in scans:
            tick()  # each scan's lane build/upload is O(table bytes)
            offs = sorted(need[id(s)])
            is_sharded = id(s.frag) in sharded
            n = s.n_rows
            total = max(-(-n // n_dev), 1) * n_dev if is_sharded else max(n, 1)
            tid = s.frag.ds.table.id
            ver = s.version

            def ck(tag, _tid=tid, _ver=ver, _tot=total, _sh=is_sharded):
                return None if _ver < 0 else (_tid, _ver, tag, _tot, _sh)

            spec = P(axis) if is_sharded else P()
            args.append(self._dev_put(ck("rowid"),
                                      lambda: _pad(np.arange(n, dtype=np.int64), total)))
            def _rv():
                rv = np.zeros(total, dtype=bool)
                rv[:n] = True
                return rv
            args.append(self._dev_put(ck("rv"), _rv))
            in_specs += [spec, spec]
            for off in offs:
                args.append(self._dev_put(
                    ck(("d", off)), lambda _o=off: _pad(s.lane(_o)[0], total)))
                args.append(self._dev_put(
                    ck(("v", off)), lambda _o=off: _pad(s.lane(_o)[1], total)))
                in_specs += [spec, spec]
            scan_arg_meta.append((id(s.frag), offs, is_sharded))
            shapes.append((total, is_sharded, offs))

        tick()
        key = self._program_key(mplan, meta, scans, shapes, n_dev)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build_program(mplan, meta, scan_arg_meta, mesh, axis, n_dev, tuple(in_specs))
            self._programs[key] = prog
            self.compile_count += 1
        from ..jaxenv import unpack_rows

        packed = np.asarray(prog(*[jnp.asarray(a) for a in args]))
        tick()
        outs = unpack_rows(packed)
        dropped = int(outs[-1][0])
        outs = outs[:-1]
        if dropped:
            # skewed keys overflowed an exchange bucket: the run is
            # incomplete — never surface it; host path takes over
            self._fallback("capacity_overflow",
                           f"exchange bucket overflow ({dropped} rows)")
            return None
        if meta["agg"] is not None:
            if meta["agg"]["mode"] == "sorted":
                return self._finalize_topk(mplan, meta, outs), True
            return self._finalize_agg(mplan, meta, outs), True
        return self._finalize_rows(mplan, meta, scans, outs), meta["agg"] is not None

    @staticmethod
    def _stream_source(frag):
        while isinstance(frag, JoinFrag):
            frag = frag.probe
        return frag

    def _program_key(self, mplan, meta, scans, shapes, n_dev):
        parts = [repr(shapes), str(n_dev)]
        for s in scans:
            parts.append(repr(meta["r_pushed"][id(s)]))
        for fid, lvl in meta["levels"].items():
            parts += [
                lvl.frag.kind, lvl.frag.exchange,
                repr(lvl.frag.probe_keys), repr(lvl.frag.build_keys),
                repr(lvl.key_lo), repr(lvl.key_stride), repr(lvl.r_post),
                str(lvl.mult), str(lvl.expected_out), str(lvl.key_i32),
            ]
        if meta["agg"]:
            a = meta["agg"]
            # int keys bake `lo` (km[1]) into the compiled kernel, so the
            # cache key must carry it; dict keys are covered by kind+domain
            # (vocab only affects host decode + already-keyed r_pushed).
            parts += [repr(a["domains"]),
                      repr([(m[0], m[1], m[2]) if m[0] == "int" else (m[0],) for m in a["key_meta"]]),
                      repr(a["r_args"]), repr([x.name for x in mplan.agg.aggs]),
                      repr(mplan.agg.group_by),
                      a["mode"], repr(a.get("strides")), repr(a.get("topn"))]
        return hashlib.sha256("|".join(parts).encode()).hexdigest()

    # ------------------------------------------------------------- kernel

    def _build_program(self, mplan, meta, scan_arg_meta, mesh, axis, n_dev, in_specs):
        from ..copr.tpu_engine import TPUEngine

        eval_dev = TPUEngine._eval_device
        soj = meta["scan_of_joined"]
        r_pushed = meta["r_pushed"]
        levels = meta["levels"]
        agg_meta = meta["agg"]
        # rows mode when the agg could not lower: the kernel returns the
        # joined rows and the gather finishes the aggregation on host
        agg = mplan.agg if agg_meta is not None else None
        scans = mplan.scans

        # arg unpacking plan: index into flat args per scan
        arg_plan = []
        pos = 0
        for fid, offs, is_sharded in scan_arg_meta:
            arg_plan.append((fid, pos, offs))
            pos += 2 + 2 * len(offs)

        # r_pushed is keyed by id(ScanData); scan_arg_meta carries frag ids.
        # Re-key via scan_of_joined (every ScanData maps to its frag).
        sd_by_fid = {}
        for j, (sd, off) in soj.items():
            sd_by_fid[id(sd.frag)] = sd

        def scan_stage(frag_id, flat):
            fid, base, offs = next(a for a in arg_plan if a[0] == frag_id)
            rowid = flat[base]
            rv = flat[base + 1]
            lanes = {}
            for k, off in enumerate(offs):
                lanes[off] = (flat[base + 2 + 2 * k], flat[base + 3 + 2 * k])
            sd = sd_by_fid[frag_id]
            mask = rv
            for c in r_pushed[id(sd)]:
                d, v = eval_dev(c, lanes)
                d = jnp.broadcast_to(d, mask.shape) if getattr(d, "ndim", 0) == 0 else d
                v = jnp.broadcast_to(v, mask.shape) if getattr(v, "ndim", 0) == 0 else v
                mask = mask & v & (d != 0)
            # re-key lanes into joined-schema space
            joined = {sd.frag.side_offset + off: lv for off, lv in lanes.items()}
            return joined, mask, {frag_id: rowid}

        def pack_keys(lanemap, key_idxs, lvl):
            acc = None
            kv = None
            for j, lo, st in zip(key_idxs, lvl.key_lo, lvl.key_stride):
                d, v = lanemap[j]
                term = (d.astype(jnp.int64) - lo) * st
                acc = term if acc is None else acc + term
                kv = v if kv is None else (kv & v)
            if lvl.key_i32:
                acc = acc.astype(jnp.int32)  # domain-checked on host
            return acc, kv

        drop_acc: list = []  # per-exchange local drop counts (psum'd at end)

        def exchange_all(lanemap, mask, rowids, okey):
            """all_to_all every lane, bucketed by owner = okey % n_dev.

            Bucket capacity is bounded at ~slack×cap/n_dev (+margin), NOT
            cap per destination: an unbounded layout would grow every
            post-exchange array by n_dev× and the whole downstream program
            with it — the opposite of scaling. Hash-uniform keys overflow
            a 2× slack with negligible probability; when data is skewed
            enough to overflow, the dropped counter (psum'd, returned as
            the program's last output) makes execute() discard the run and
            fall back to the host path, so results are never silently
            wrong (the spill/fallback discipline of the reference's
            exchange, mpp_exec.go, in static-shape form)."""
            if n_dev == 1:
                # single-device mesh (one real chip): every row already
                # lives on its owner — the exchange is the identity
                return lanemap, mask, rowids
            rows = mask.shape[0]
            bcap = -(-rows * 2 // n_dev) + 64  # slack 2 + small-size margin
            bcap = min(bcap, rows)
            owner = (okey % n_dev).astype(jnp.int32)
            order = jnp.argsort(jnp.where(mask, owner, n_dev))
            own_s = jnp.where(mask, owner, n_dev)[order]
            counts = jax.ops.segment_sum(
                (own_s < n_dev).astype(jnp.int32), own_s, num_segments=n_dev + 1
            )[:n_dev]
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
            )
            drop_acc.append(
                jnp.sum(counts - jnp.minimum(counts, bcap)).astype(jnp.int64)
            )
            # owner-sorted rows make the (n_dev, bcap) bucket layout a pure
            # GATHER (src = starts[dev] + slot) — never a scatter, which
            # the TPU serializes
            src = jnp.clip(
                starts[:, None] + jnp.arange(bcap, dtype=jnp.int32)[None, :], 0, rows - 1
            )
            okg = jnp.arange(bcap, dtype=jnp.int32)[None, :] < jnp.minimum(counts, bcap)[:, None]

            def xc(lane):
                lane_s = lane[order]
                buf = jnp.where(okg, lane_s[src], jnp.zeros((), lane.dtype))
                out = jax.lax.all_to_all(buf, axis, 0, 0, tiled=True)
                return out.reshape(-1)

            new_map = {j: (xc(d), xc(v)) for j, (d, v) in lanemap.items()}
            new_rowids = {fid: xc(r) for fid, r in rowids.items()}
            mask_out = xc(mask)
            return new_map, mask_out, new_rowids

        def join_stage(frag, flat):
            if isinstance(frag, ScanFrag):
                return scan_stage(id(frag), flat)
            pmap_, pmask, prow = join_stage(frag.probe, flat)
            bmap, bmask, brow = scan_stage(id(frag.build), flat)
            lvl = levels[id(frag)]
            pkey, pkv = pack_keys(pmap_, frag.probe_keys, lvl)
            bkey, bkv = pack_keys(bmap, frag.build_keys, lvl)
            if frag.exchange == HASH:
                pmap_, pmask, prow = exchange_all(
                    pmap_, pmask, prow, jnp.where(pkv, pkey, jnp.arange(pkey.shape[0]))
                )
                bmap, bmask, brow = exchange_all(bmap, bmask, brow, bkey)
                pkey, pkv = pack_keys(pmap_, frag.probe_keys, lvl)
                bkey, bkv = pack_keys(bmap, frag.build_keys, lvl)
            bvalid = bmask & bkv
            B = bkey.shape[0]
            key_max = (
                jnp.asarray((1 << 31) - 1, jnp.int32) if lvl.key_i32 else I64_MAX
            )
            order = jnp.argsort(jnp.where(bvalid, bkey, key_max))
            sk = jnp.where(bvalid, bkey, key_max)[order]
            sv = bvalid[order]
            M = lvl.mult
            if M == 1:
                pos = jnp.clip(jnp.searchsorted(sk, pkey, method="sort"), 0, B - 1)
                match = pmask & pkv & sv[pos] & (sk[pos] == pkey)
                bsel = order[pos]
                merged = dict(pmap_)
                for j, (d, v) in bmap.items():
                    merged[j] = (d[bsel], v[bsel] & match)
                rowids = dict(prow)
                rowids[id(frag.build)] = jnp.where(match, brow[id(frag.build)][bsel], -1)
                mask = match if frag.kind == "inner" else pmask
            else:
                # duplicate build keys: compact cumsum-offset join. Each
                # probe row claims exactly its match-count output slots
                # (exclusive cumsum → positions), instead of max-mult
                # static fan-out — output capacity stays O(join output),
                # not O(probe × max multiplicity), which is what lets a
                # fact-table build side scale. Capacity overflow bumps the
                # dropped counter → host fallback (never wrong results).
                rows = pkey.shape[0]
                exp = lvl.expected_out
                if exp is None:
                    C = 2 * max(int(rows), int(B)) + 64
                elif n_dev == 1:
                    C = exp + 64  # exact global bound
                else:
                    # per-device share with 2x skew slack, drop-guarded
                    C = min(2 * (exp // n_dev) + 64 + int(rows), 2 * max(int(rows), int(B)) + 64)
                if frag.kind != "inner":
                    C = C + int(rows)  # unmatched probe rows also emit
                left = jnp.searchsorted(sk, pkey, side="left", method="sort")
                # match count per probe = run length at `left` (cummax/
                # cummin run boundaries) — avoids the second sort-based
                # searchsorted for side="right"
                bidx = jnp.arange(B, dtype=jnp.int32)
                bfirst = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
                blast = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones(1, bool)])
                rstart = jax.lax.cummax(jnp.where(bfirst, bidx, 0))
                rend = -jax.lax.cummax(jnp.where(blast, -bidx, -(B - 1))[::-1])[::-1]
                run_len = rend - rstart + 1
                leftc = jnp.clip(left, 0, B - 1)
                hit = (left < B) & (sk[leftc] == pkey)
                pvalid = pmask & pkv
                cnt = jnp.where(pvalid & hit, run_len[leftc], 0).astype(jnp.int32)
                if frag.kind != "inner":
                    # left join: unmatched probe rows still emit one row
                    cnt = jnp.maximum(cnt, (pmask).astype(cnt.dtype))
                opos = (jnp.cumsum(cnt) - cnt).astype(jnp.int32)  # exclusive
                total = jnp.sum(cnt)
                drop_acc.append(jnp.maximum(total - C, 0).astype(jnp.int64))
                j = jnp.arange(C, dtype=jnp.int32)
                src = jnp.clip(jnp.searchsorted(opos, j, side="right", method="sort") - 1, 0, rows - 1)
                slot = j - opos[src]
                emitted = (j < total) & (slot < cnt[src])
                matched_probe = cnt[src] > 0 if frag.kind == "inner" else (pvalid & hit)[src]
                bpos = jnp.clip(left[src] + slot, 0, B - 1)
                match = emitted & matched_probe & pvalid[src] & sv[bpos] & (sk[bpos] == pkey[src])
                bsel = order[bpos]
                merged = {}
                for jj, (d, v) in pmap_.items():
                    merged[jj] = (d[src], v[src] & emitted)
                for jj, (d, v) in bmap.items():
                    merged[jj] = (d[bsel], v[bsel] & match)
                rowids = {fid: jnp.where(emitted, r[src], -1) for fid, r in prow.items()}
                rowids[id(frag.build)] = jnp.where(match, brow[id(frag.build)][bsel], -1)
                if frag.kind == "inner":
                    mask = match
                else:
                    mask = emitted & pmask[src]
            for c in lvl.r_post:
                d, v = eval_dev(c, merged)
                d = jnp.broadcast_to(d, mask.shape) if getattr(d, "ndim", 0) == 0 else d
                v = jnp.broadcast_to(v, mask.shape) if getattr(v, "ndim", 0) == 0 else v
                mask = mask & v & (d != 0)
            return merged, mask, rowids

        def sorted_agg_stage(lanemap, mask):
            """Wide-key device aggregation: lexsort+segment reduce locally,
            hash-exchange complete groups to their owner device, final
            reduce, then top-k by the fused ORDER BY aggregate. Output is
            k exact group results per device — the host only merges
            n_dev*k candidates (ref: the TiFlash partial/final agg +
            TopN pipeline, mpp_exec.go, collapsed into one program)."""
            strides = agg_meta["strides"]
            code = jnp.zeros(mask.shape, jnp.int64)
            for g, km, st in zip(agg.group_by, agg_meta["key_meta"], strides):
                d, v = lanemap[g.idx]
                if km[0] == "int":
                    # gcd-compressed: (d - lo) // step + 1, NULL → 0
                    kd = ((d.astype(jnp.int64) - km[1]) // km[2] + 1) * v
                else:
                    kd = (d.astype(jnp.int64) + 1) * v
                code = code + kd * st
            code = jnp.where(mask, code, I64_MAX)

            # per-agg raw value lanes (+ count lane), zeroed off-mask
            lanes = []  # (array, merge_op)
            for a, ra in zip(agg.aggs, agg_meta["r_args"]):
                if ra:
                    d, v = eval_dev(ra[0], lanemap)
                    d = jnp.broadcast_to(d, code.shape) if getattr(d, "ndim", 0) == 0 else d
                    v = jnp.broadcast_to(v, code.shape) if getattr(v, "ndim", 0) == 0 else v
                else:
                    d = jnp.ones(code.shape, jnp.int64)
                    v = jnp.ones(code.shape, bool)
                ok = mask & v
                if a.name == "count":
                    lanes.append((ok.astype(jnp.int64), "sum"))
                elif a.name in ("sum", "avg"):
                    z = 0.0 if d.dtype in (jnp.float64, jnp.float32) else 0
                    lanes.append((jnp.where(ok, d, z), "sum"))
                    lanes.append((ok.astype(jnp.int64), "sum"))
                elif a.name == "min":
                    big = jnp.inf if d.dtype in (jnp.float64, jnp.float32) else I64_MAX
                    lanes.append((jnp.where(ok, d, big), "min"))
                    lanes.append((ok.astype(jnp.int64), "sum"))
                else:  # max
                    small = -jnp.inf if d.dtype in (jnp.float64, jnp.float32) else -I64_MAX - 1
                    lanes.append((jnp.where(ok, d, small), "max"))
                    lanes.append((ok.astype(jnp.int64), "sum"))

            def _neutral(dtype, op):
                if op == "min":
                    return jnp.inf if dtype in (jnp.float64, jnp.float32) else I64_MAX
                if op == "max":
                    return -jnp.inf if dtype in (jnp.float64, jnp.float32) else -I64_MAX - 1
                return jnp.zeros((), dtype)

            def seg_reduce(key, vals, max_run: int):
                """Scatter-free segmented reduce: sort by key, run totals
                land on each run's FIRST slot. Sum/count lanes use one
                cumsum + run-boundary gathers (3 vector passes); min/max
                lanes use distance-doubling combines (log2(max_run)
                passes). No segment_* scatters anywhere — XLA:CPU
                serializes them and TPU pays scatter overhead."""
                order = jnp.argsort(key)
                sk = key[order]
                n = int(sk.shape[0])
                idx = jnp.arange(n, dtype=jnp.int32)
                first = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
                last = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones(1, bool)])
                rend = -jax.lax.cummax(jnp.where(last, -idx, -(n - 1))[::-1])[::-1]
                arrs = []
                need_doubling = [i for i, (_, op) in enumerate(vals) if op != "sum"]
                for i, (arr, op) in enumerate(vals):
                    a = arr[order]
                    if op == "sum":
                        c = jnp.cumsum(a)
                        prev = jnp.concatenate([jnp.zeros(1, a.dtype), c[:-1]])
                        # total of the run starting here = c[end] - c[start-1]
                        a = jnp.where(first, c[rend] - prev, jnp.zeros((), a.dtype))
                    arrs.append(a)
                if need_doubling:
                    d = 1
                    while d < max_run:
                        same = jnp.concatenate(
                            [sk[d:] == sk[:-d], jnp.zeros((d,), bool)]
                        )
                        for i in need_doubling:
                            a = arrs[i]
                            op = vals[i][1]
                            neut = _neutral(a.dtype, op)
                            sh = jnp.concatenate([a[d:], jnp.full((d,), neut, a.dtype)])
                            contrib = jnp.where(same, sh, neut)
                            if op == "min":
                                arrs[i] = jnp.minimum(a, contrib)
                            else:
                                arrs[i] = jnp.maximum(a, contrib)
                        d *= 2
                valid = first & (sk != I64_MAX)
                ukey = jnp.where(valid, sk, I64_MAX)
                return ukey, arrs, valid

            def finish_topk(fkey, fvals, fvalid):
                # device top-k on the fused ORDER BY aggregate
                agg_idx, desc, k = agg_meta["topn"]
                lane_pos = 0
                for i, a in enumerate(agg.aggs):
                    if i == agg_idx:
                        break
                    lane_pos += 1 if a.name == "count" else 2
                val = fvals[lane_pos]
                valid = fvalid
                if val.dtype in (jnp.float64, jnp.float32):
                    score = jnp.where(valid, val, -jnp.inf)
                    score = score if desc else -score
                else:
                    score = jnp.where(valid, val, -I64_MAX)
                    score = score if desc else jnp.where(valid, -val, -I64_MAX)
                kk = min(k, int(score.shape[0]))
                _, idx = jax.lax.top_k(score, kk)
                outs = [fkey[idx], valid[idx]]
                outs.extend(v[idx] for v in fvals)
                return tuple(outs)

            rows_local = int(code.shape[0])
            if n_dev == 1:
                # one device: a single reduce IS the final state
                fkey, fvals, fvalid = seg_reduce(code, lanes, rows_local)
                return finish_topk(fkey, fvals, fvalid)
            # 1. local pre-reduce (shrinks exchange volume to |local groups|)
            ukey, uvals, uvalid = seg_reduce(code, lanes, rows_local)
            # 2. exchange whole groups to their owner device
            pseudo = {i: (arr, uvalid) for i, arr in enumerate(uvals)}
            pseudo[len(uvals)] = (ukey, uvalid)
            new_map, ex_mask, _ = exchange_all(
                pseudo, uvalid, {}, jnp.where(uvalid, ukey, 0)
            )
            ukey2 = jnp.where(ex_mask, new_map[len(uvals)][0], I64_MAX)
            vals2 = []
            for i, (_, op) in enumerate(lanes):
                arr = new_map[i][0]
                arr = jnp.where(ex_mask, arr, _neutral(arr.dtype, op))
                vals2.append((arr, op))
            # 3. final reduce: each key has at most one fragment per source
            # device, so n_dev bounds the run length
            fkey, fvals, fvalid = seg_reduce(ukey2, vals2, n_dev)
            return finish_topk(fkey, fvals, fvalid)

        def kernel(*flat):
            drop_acc.clear()

            def with_drops(outs):
                """Pack EVERY output + the dropped counter into one int64
                matrix (jaxenv.pack_rows, dtype tags in-band): each
                device→host array read over a remote link costs a full
                round-trip, so the program ships exactly ONE buffer."""
                from ..jaxenv import pack_rows

                d = sum(drop_acc) if drop_acc else jnp.zeros((), jnp.int64)
                d = jax.lax.psum(d, axis)
                outs = list(outs)
                L = outs[0].shape[0]
                outs.append(jnp.broadcast_to(d, (L,)))
                return pack_rows(outs)

            lanemap, mask, rowids = join_stage(mplan.root, flat)
            if agg is None:
                outs = [mask]
                for s in scans:
                    outs.append(rowids.get(id(s), jnp.full(mask.shape, -1, jnp.int64)))
                return with_drops(outs)
            if agg_meta["mode"] == "sorted":
                return with_drops(sorted_agg_stage(lanemap, mask))
            # fused partial aggregation + psum (exact int/scaled-decimal)
            nseg = agg_meta["nseg"]
            code = jnp.zeros(mask.shape, dtype=jnp.int32)
            for g, dom, km in zip(agg.group_by, agg_meta["domains"], agg_meta["key_meta"]):
                d, v = lanemap[g.idx]
                lo = km[1] if km[0] == "int" else 0
                kd = (d.astype(jnp.int32) - lo + 1) * v
                code = code * (dom + 1) + kd
            seg = jnp.where(mask, code, nseg)
            outs = [(jax.ops.segment_sum(mask.astype(jnp.int64), seg, num_segments=nseg + 1)[:nseg], "sum")]
            for a, ra in zip(agg.aggs, agg_meta["r_args"]):
                outs.extend(self._agg_partials(a, ra, lanemap, mask, seg, nseg, eval_dev))
            red = {"sum": jax.lax.psum, "min": jax.lax.pmin, "max": jax.lax.pmax}
            return with_drops([red[op](o, axis) for o, op in outs])

        if agg is not None and agg_meta["mode"] == "dense":
            out_specs = P()  # psum'd: replicated (nout, nseg)
        else:
            out_specs = P(None, axis)  # per-device slices concat on dim 1

        sm = shard_map(kernel, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs)
        return jax.jit(sm)

    @staticmethod
    def _agg_partials(a, r_args, lanemap, mask, seg, nseg, eval_dev):
        if r_args:
            d, v = eval_dev(r_args[0], lanemap)
            d = jnp.broadcast_to(d, seg.shape) if getattr(d, "ndim", 0) == 0 else d
            v = jnp.broadcast_to(v, seg.shape) if getattr(v, "ndim", 0) == 0 else v
        else:
            d = jnp.ones(seg.shape, dtype=jnp.int64)
            v = jnp.ones(seg.shape, dtype=bool)
        ok = mask & v
        if a.name == "count":
            return [(jax.ops.segment_sum(ok.astype(jnp.int64), seg, num_segments=nseg + 1)[:nseg], "sum")]
        if a.name in ("sum", "avg"):
            if d.dtype in (jnp.float64, jnp.float32):
                s = jax.ops.segment_sum(jnp.where(ok, d, 0.0), seg, num_segments=nseg + 1)[:nseg]
            else:
                s = jax.ops.segment_sum(jnp.where(ok, d.astype(jnp.int64), 0), seg, num_segments=nseg + 1)[:nseg]
            cnt = jax.ops.segment_sum(ok.astype(jnp.int64), seg, num_segments=nseg + 1)[:nseg]
            return [(s, "sum"), (cnt, "sum")]
        if a.name in ("min", "max"):
            if a.name == "min":
                big = jnp.inf if d.dtype in (jnp.float64, jnp.float32) else I64_MAX
                s = jax.ops.segment_min(jnp.where(ok, d, big), seg, num_segments=nseg + 1)[:nseg]
                op = "min"
            else:
                small = -jnp.inf if d.dtype in (jnp.float64, jnp.float32) else -I64_MAX - 1
                s = jax.ops.segment_max(jnp.where(ok, d, small), seg, num_segments=nseg + 1)[:nseg]
                op = "max"
            cnt = jax.ops.segment_sum(ok.astype(jnp.int64), seg, num_segments=nseg + 1)[:nseg]
            return [(s, op), (cnt, "sum")]
        raise NotImplementedError(a.name)

    # ------------------------------------------------------------ finalize

    def _finalize_agg(self, mplan, meta, outs) -> Chunk:
        """psum'd partial arrays → partial-layout chunk (group keys then
        per-agg partial states) for FinalHashAggExec."""
        agg = mplan.agg
        agg_meta = meta["agg"]
        soj = meta["scan_of_joined"]
        nseg = agg_meta["nseg"]
        group_count = np.asarray(outs[0])
        present = np.nonzero(group_count > 0)[0]
        G = len(present)
        out_fts = [g.ret_type for g in agg.group_by]
        for a in agg.aggs:
            out_fts.extend(ft for _, ft in a.partial_final_types())
        cols: list[Column] = []
        radix = [d + 1 for d in agg_meta["domains"]]
        codes = present.copy()
        key_vals = []
        for r in reversed(radix):
            key_vals.append(codes % r)
            codes = codes // r
        key_vals.reverse()
        oi = 0
        for km, kv in zip(agg_meta["key_meta"], key_vals):
            ft = out_fts[oi]
            valid = kv > 0
            if km[0] == "dict":
                vocab = km[1]
                data = np.empty(G, dtype=object)
                for j, c in enumerate(kv):
                    data[j] = vocab[c - 1] if c > 0 else None
            else:
                data = (kv.astype(np.int64) - 1) + km[1]
                data[~valid] = 0
            cols.append(Column(ft, data, valid))
            oi += 1
        pos = 1
        for a, ra in zip(agg.aggs, agg_meta["r_args"]):
            if a.name == "count":
                cnt = np.asarray(outs[pos])[present]
                cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, bool)))
                pos += 1
                oi += 1
            elif a.name in ("sum", "avg"):
                s = np.asarray(outs[pos])[present]
                cnt = np.asarray(outs[pos + 1])[present]
                has = cnt > 0
                sd = s if out_fts[oi].is_float() else s.astype(np.int64)
                cols.append(Column(out_fts[oi], sd, has))
                oi += 1
                if a.name == "avg":
                    cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, bool)))
                    oi += 1
                pos += 2
            elif a.name in ("min", "max"):
                s = np.asarray(outs[pos])[present]
                cnt = np.asarray(outs[pos + 1])[present]
                has = cnt > 0
                ft = out_fts[oi]
                arg = a.args[0] if a.args else None
                if isinstance(arg, ExprCol):
                    sd, off = soj[arg.idx]
                    if off in sd.vocabs:
                        vocab = sd.vocabs[off]
                        data = np.empty(G, dtype=object)
                        for j in range(G):
                            data[j] = vocab[int(s[j])] if has[j] and 0 <= int(s[j]) < len(vocab) else None
                        cols.append(Column(ft, data, has))
                        pos += 2
                        oi += 1
                        continue
                data = s if ft.is_float() else np.where(has, s.astype(np.int64), 0)
                cols.append(Column(ft, data, has))
                pos += 2
                oi += 1
        return Chunk(cols)

    def _finalize_topk(self, mplan, meta, outs) -> Chunk:
        """Per-device top-k group results → partial-layout chunk (same
        shape _finalize_agg emits) for the host FinalHashAggExec + exact
        TopN. n_dev*k rows total — the transfer is tiny by construction."""
        agg = mplan.agg
        agg_meta = meta["agg"]
        soj = meta["scan_of_joined"]
        codes = np.asarray(outs[0])
        valid = np.asarray(outs[1])
        keep = np.nonzero(valid & (codes != np.iinfo(np.int64).max))[0]
        G = len(keep)
        codes = codes[keep]
        out_fts = [g.ret_type for g in agg.group_by]
        for a in agg.aggs:
            out_fts.extend(ft for _, ft in a.partial_final_types())
        cols: list[Column] = []
        oi = 0
        for km, st, radix in zip(agg_meta["key_meta"], agg_meta["strides"], agg_meta["radixes"]):
            comp = (codes // st) % radix
            kvalid = comp > 0
            ft = out_fts[oi]
            if km[0] == "dict":
                vocab = km[1]
                data = np.empty(G, dtype=object)
                for j, c in enumerate(comp):
                    data[j] = vocab[c - 1] if c > 0 else None
            else:
                data = np.where(kvalid, (comp - 1) * km[2] + km[1], 0).astype(np.int64)
            cols.append(Column(ft, data, kvalid))
            oi += 1
        pos = 2
        for a, ra in zip(agg.aggs, agg_meta["r_args"]):
            if a.name == "count":
                cnt = np.asarray(outs[pos])[keep]
                cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, bool)))
                pos += 1
                oi += 1
            elif a.name in ("sum", "avg"):
                s = np.asarray(outs[pos])[keep]
                cnt = np.asarray(outs[pos + 1])[keep]
                has = cnt > 0
                sd = s if out_fts[oi].is_float() else s.astype(np.int64)
                cols.append(Column(out_fts[oi], sd, has))
                oi += 1
                if a.name == "avg":
                    cols.append(Column(out_fts[oi], cnt.astype(np.int64), np.ones(G, bool)))
                    oi += 1
                pos += 2
            elif a.name in ("min", "max"):
                s = np.asarray(outs[pos])[keep]
                cnt = np.asarray(outs[pos + 1])[keep]
                has = cnt > 0
                ft = out_fts[oi]
                arg = a.args[0] if a.args else None
                if isinstance(arg, ExprCol):
                    sd, off = soj[arg.idx]
                    if off in sd.vocabs:
                        vocab = sd.vocabs[off]
                        data = np.empty(G, dtype=object)
                        for j in range(G):
                            data[j] = vocab[int(s[j])] if has[j] and 0 <= int(s[j]) < len(vocab) else None
                        cols.append(Column(ft, data, has))
                        pos += 2
                        oi += 1
                        continue
                data = s if ft.is_float() else np.where(has, s.astype(np.int64), 0)
                cols.append(Column(ft, data, has))
                pos += 2
                oi += 1
        return Chunk(cols)

    def _finalize_rows(self, mplan, meta, scans, outs) -> Chunk:
        """(mask, per-scan rowids) → joined-schema chunk via host gather
        from the original (string-preserving) numpy lanes."""
        mask = np.asarray(outs[0])
        rowids = [np.asarray(o) for o in outs[1:]]
        sel = np.nonzero(mask)[0]
        by_frag = {id(s.frag): (s, i) for i, s in enumerate(scans)}
        cols: list[Column] = []
        for j, pc in enumerate(mplan.out_cols):
            sd, off = meta["scan_of_joined"][j]
            _, si = by_frag[id(sd.frag)]
            rid = rowids[si][sel]
            ok = rid >= 0
            safe = np.clip(rid, 0, max(sd.n_rows - 1, 0))
            src = sd.data[off]
            srcv = sd.valid[off]
            if sd.n_rows == 0:
                dt = col_numpy_dtype(pc.ft)
                data = np.empty(len(sel), dtype=object) if dt is VARLEN else np.zeros(len(sel), dtype=dt)
                valid = np.zeros(len(sel), bool)
            else:
                data = src[safe]
                valid = srcv[safe] & ok
                if data.dtype == object:
                    data = data.copy()
                    data[~valid] = None
            cols.append(Column(pc.ft, data, valid))
        return Chunk(cols)
